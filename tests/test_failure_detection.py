"""Failure-detection tests: real sockets, crash = close the messenger."""

import time

import numpy as np

from gigapaxos_tpu.net import Messenger, NodeMap
from gigapaxos_tpu.net.failure_detection import FailureDetection


def cluster(ids, ping=0.05, timeout=0.4):
    nm = NodeMap()
    ms = {nid: Messenger(nid, ("127.0.0.1", 0), nm) for nid in ids}
    for nid, m in ms.items():
        nm.add(nid, "127.0.0.1", m.port)
    fds = {
        nid: FailureDetection(
            m, [x for x in ids if x != nid], ping_interval_s=ping, timeout_s=timeout
        )
        for nid, m in ms.items()
    }
    return nm, ms, fds


def test_all_up_then_crash_then_recover():
    ids = ["A", "B", "C"]
    nm, ms, fds = cluster(ids)
    try:
        # poll-with-deadline, not a fixed sleep: pinger threads can starve
        # for hundreds of ms when the whole suite shares one core
        deadline = time.monotonic() + 20
        while (not all(fds["A"].is_node_up(n) for n in ids)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert all(fds["A"].is_node_up(n) for n in ids)
        assert list(fds["A"].alive_mask(ids)) == [True, True, True]

        # crash B: close its messenger (no more pongs)
        port_b = ms["B"].port
        fds["B"].close()
        ms["B"].close()
        deadline = time.monotonic() + 20
        while fds["A"].is_node_up("B") and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not fds["A"].is_node_up("B")
        assert not fds["C"].is_node_up("B")
        assert fds["A"].is_node_up("C") and fds["C"].is_node_up("A")
        mask = fds["A"].alive_mask(ids)
        assert list(mask) == [True, False, True] and mask.dtype == np.bool_

        # recover B on the same port
        ms["B"] = Messenger("B", ("127.0.0.1", port_b), nm)
        fds["B"] = FailureDetection(
            ms["B"], ["A", "C"], ping_interval_s=0.05, timeout_s=0.4
        )
        deadline = time.monotonic() + 20
        while not fds["A"].is_node_up("B") and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fds["A"].is_node_up("B")
    finally:
        for f in fds.values():
            f.close()
        for m in ms.values():
            m.close()


def test_on_change_edges():
    events = []
    nm = NodeMap()
    a = Messenger("A", ("127.0.0.1", 0), nm)
    nm.add("A", "127.0.0.1", a.port)
    # monitor a node that never existed: one down edge after the grace window
    fd = FailureDetection(
        a,
        ["GHOST"],
        ping_interval_s=0.05,
        timeout_s=0.3,
        on_change=lambda n, up: events.append((n, up)),
    )
    try:
        deadline = time.monotonic() + 5
        while not events and time.monotonic() < deadline:
            time.sleep(0.05)
        assert events and events[0] == ("GHOST", False)
        n_down = len(events)
        time.sleep(0.3)
        assert len(events) == n_down  # edge-triggered, not repeated
    finally:
        fd.close()
        a.close()


def test_self_always_up_and_unmonitor():
    nm = NodeMap()
    a = Messenger("A", ("127.0.0.1", 0), nm)
    nm.add("A", "127.0.0.1", a.port)
    fd = FailureDetection(a, [], ping_interval_s=0.05, timeout_s=0.3)
    try:
        assert fd.is_node_up("A")
        fd.monitor("A")  # no-op
        fd.monitor("X")
        fd.unmonitor("X")
        assert "X" not in fd._monitored
    finally:
        fd.close()
        a.close()

"""Digest-keyed shared payload store (Mode A bulk dissemination).

The ordering/dissemination split's Mode A half (HT-Paxos, arxiv
1407.1237): accepts and commits in the compact outbox already reference
requests by rid — what still multiplied payload bytes was every copy of
the same body being carried separately through admission, the WAL inbox
journal, and the client batch frames.  Interning by content digest makes
"the payload's bytes" a single shared object per unique body, which the
other layers key off:

* ``paxos/manager.py`` interns at admission, so N outstanding requests
  with one body hold one ``bytes``;
* ``wal/logger.py`` journals a body once per checkpoint epoch and an
  8-byte digest reference afterwards (replay resolves references from
  the snapshot + earlier records, bit-identically);
* ``net/binbatch.py`` ships a unique-payload table per batch frame, so a
  body crosses each peer link once (GBR2).

blake2b-64 keys (the same digest the Mode B wire uses for group ids);
an equality check guards the store against digest collisions — a
colliding body is simply never shared.
"""

from __future__ import annotations

import collections
import hashlib
from typing import Optional

from ..obs.metrics import registry as _obs_registry

#: bodies below this aren't worth a digest reference (the reference
#: record itself costs ~20 journal bytes)
DEDUP_MIN_BYTES = 32


def payload_digest(payload: bytes) -> bytes:
    return hashlib.blake2b(payload, digest_size=8).digest()


class PayloadStore:
    """Bounded content-addressed interning of request bodies.

    LRU-bounded like the Mode B payload table: eviction only loses
    sharing (the next intern re-inserts), never correctness — every
    consumer keeps its own reference to the returned object.
    """

    def __init__(self, cap: int = 1 << 16):
        self._by_digest: "collections.OrderedDict[bytes, bytes]" = (
            collections.OrderedDict()
        )
        self.cap = cap
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # registry counters alongside the plain ints: register-mode bodies
        # lean on intern sharing across millions of groups, so hit/miss/
        # eviction rates are a first-class dashboard signal
        reg = _obs_registry()
        self._hits_c = reg.counter(
            "paystore_hits_total", help="payload intern digest hits")
        self._misses_c = reg.counter(
            "paystore_misses_total", help="payload intern digest misses")
        self._evict_c = reg.counter(
            "paystore_evictions_total", help="payload intern LRU evictions")

    def __len__(self) -> int:
        return len(self._by_digest)

    def intern(self, payload: bytes) -> bytes:
        """Return the canonical object for these bytes (may be ``payload``
        itself on first sight).  Tiny bodies pass through untouched."""
        if len(payload) < DEDUP_MIN_BYTES:
            return payload
        d = payload_digest(payload)
        got = self._by_digest.get(d)
        if got is not None and got == payload:
            self.hits += 1
            self._hits_c.inc()
            self._by_digest.move_to_end(d)
            return got
        self.misses += 1
        self._misses_c.inc()
        self._by_digest[d] = payload
        while len(self._by_digest) > self.cap:
            self._by_digest.popitem(last=False)
            self.evictions += 1
            self._evict_c.inc()
        return payload

    def get(self, digest: bytes) -> Optional[bytes]:
        return self._by_digest.get(digest)

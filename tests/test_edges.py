"""HTTP and DNS front-end tests (reference: docs/HTTP-API.md dialect,
reconfiguration/dns/DnsReconfigurator.java)."""

import json
import socket
import struct
import urllib.request

import pytest

from gigapaxos_tpu.client import ReconfigurableAppClient
from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.node import InProcessCluster
from gigapaxos_tpu.reconfiguration.dns_edge import DnsReconfigurator
from gigapaxos_tpu.reconfiguration.http_edge import (
    HttpActiveReplica,
    HttpReconfigurator,
)


@pytest.fixture(scope="module")
def stack():
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 64
    for i in range(3):
        cfg.nodes.actives[f"AR{i}"] = ("127.0.0.1", 0)
    for i in range(3):
        cfg.nodes.reconfigurators[f"RC{i}"] = ("127.0.0.1", 0)
    cl = InProcessCluster(cfg, KVApp)
    client = ReconfigurableAppClient(cfg.nodes)
    rc_http = HttpReconfigurator(client, ("127.0.0.1", 0))
    ar_http = HttpActiveReplica(client, ("127.0.0.1", 0))
    dns = DnsReconfigurator(client, ("127.0.0.1", 0))
    yield cl, client, rc_http, ar_http, dns
    dns.close()
    rc_http.close()
    ar_http.close()
    client.close()
    cl.close()


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_http_create_request_delete(stack):
    _, _, rc_http, ar_http, _ = stack
    code, resp = _get(rc_http.port, "/?type=CREATE&name=Alice")
    assert code == 200 and not resp["FAILED"]
    code, resp = _get(ar_http.port, "/?name=Alice&qval=PUT%20k%20v1")
    assert code == 200 and resp["RVAL"] == "OK"
    code, resp = _get(ar_http.port, "/?name=Alice&qval=GET%20k")
    assert resp["RVAL"] == "v1" and resp["NAME"] == "Alice"
    code, resp = _get(rc_http.port, "/?type=REQ_ACTIVES&name=Alice")
    assert code == 200 and len(resp["ACTIVES"]) == 3
    code, resp = _get(rc_http.port, "/?type=DELETE&name=Alice")
    assert code == 200 and not resp["FAILED"]
    code, resp = _get(rc_http.port, "/?type=REQ_ACTIVES&name=Alice")
    assert code == 404


def test_http_bad_request(stack):
    _, _, rc_http, ar_http, _ = stack
    try:
        code, _ = _get(rc_http.port, "/?type=CREATE")  # missing name
    except urllib.error.HTTPError as e:
        code = e.code
    assert code == 400


def _dns_query(port, qname):
    q = struct.pack(">HHHHHH", 0x1234, 0x0100, 1, 0, 0, 0)
    for label in qname.split("."):
        q += bytes([len(label)]) + label.encode()
    q += b"\x00" + struct.pack(">HH", 1, 1)  # A, IN
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(30)
    s.sendto(q, ("127.0.0.1", port))
    data, _ = s.recvfrom(512)
    s.close()
    tid, flags, qd, an, ns, ar = struct.unpack(">HHHHHH", data[:12])
    ips = []
    # skip question
    off = 12
    while data[off]:
        off += 1 + data[off]
    off += 5
    for _ in range(an):
        off += 2  # name pointer
        rtype, rclass, ttl, rdlen = struct.unpack(">HHIH", data[off: off + 10])
        off += 10
        ips.append(socket.inet_ntoa(data[off: off + rdlen]))
        off += rdlen
    return flags, ips


def test_dns_resolves_actives(stack):
    _, client, _, _, dns = stack
    assert client.create("web")["ok"]
    flags, ips = _dns_query(dns.port, "web.gp")
    assert flags & 0x8000  # response bit
    assert (flags & 0x000F) == 0  # NOERROR
    assert len(ips) == 3 and all(ip == "127.0.0.1" for ip in ips)


def test_dns_nxdomain(stack):
    _, _, _, _, dns = stack
    flags, ips = _dns_query(dns.port, "nosuch.gp")
    assert (flags & 0x000F) == 3  # NXDOMAIN
    assert ips == []

"""PaxosManager: the host control loop that owns the device data plane.

The reference's ``PaxosManager`` (gigapaxos/PaxosManager.java:104-119) is the
per-node multiplexer: instance map, request demultiplexing, the propose API,
recovery driver and pause logic.  Here it owns:

* the dense device state (one :class:`PaxosState`) and the jitted tick;
* the name<->row table (RowAllocator = IntegerMap/MultiArrayMap analog,
  paxosutil/IntegerMap.java:40 / utils/MultiArrayMap.java:41);
* the request store: request-id -> payload/callback (the ``outstanding`` map,
  PaxosManager.java:189-259), with execution-side dedup so a request that
  commits in two slots (possible across coordinator turnover, the
  "preempted request" hazard of PaxosManager.java:1298-1352) executes once;
* per-replica-slot app instances (``Replicable``), executed on the host from
  the device's ordered decision stream;
* the per-tick batcher (RequestBatcher analog, gigapaxos/RequestBatcher.java:25):
  queued proposals are packed into the inbox tensor, rejected intake is
  re-queued.

This manager drives the whole replica set of a mesh (Mode A).  In a
multi-host deployment each host runs one manager per node and the replica
axis exchange goes over the transport instead (net/, Mode B).
"""

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..config import GigapaxosTpuConfig
from .. import overload as _overload
from ..models.replicable import Replicable
from ..types import GroupStatus, NO_REQUEST
from ..utils.intmap import RowAllocator
from ..obs.phase import BLOCKING_PHASE as _BLOCKING_PHASE
from ..obs.phase import phase_clock as _phase_clock
from ..utils.locking import ContendedLock, locked as _locked
from ..utils.reqtrace import tracer as _reqtrace

#: process-wide manager counter for trace namespaces (never reused)
import itertools as _itertools

_MGR_SEQ = _itertools.count()
from . import state as st
from .bulkstore import BulkOverrun, BulkStore
from .paystore import PayloadStore
from ..ops.tick import (LP_ASN, LP_EPOCH, LP_HOLDER, LP_UNTIL, LP_WAIT,
                        CompactHostOutbox, HostOutbox, TickInbox,
                        frontier_rows, health_clear_rows, init_health,
                        lease_clear_rows, merge_compact_outbox, merge_health,
                        merge_outbox, paxos_tick_compact,
                        paxos_tick_compact_demand, paxos_tick_compact_lease,
                        paxos_tick_health, paxos_tick_mixed_compact,
                        paxos_tick_mixed_compact_lease,
                        paxos_tick_mixed_packed,
                        paxos_tick_mixed_packed_lease, paxos_tick_packed,
                        paxos_tick_packed_lease, sweep_frontier,
                        unpack_compact, unpack_health, unpack_outbox)


@dataclass
class RequestRecord:
    rid: int
    name: str
    row: int
    payload: bytes
    stop: bool
    callback: Optional[Callable[[int, bytes], None]]
    entry: int  # entry replica slot
    slot: int = -1  # filled at first execution
    executed_by: set = field(default_factory=set)
    responded: bool = False


def _pad_rows(rows: np.ndarray, oob: int) -> np.ndarray:
    """Pad a row batch to the next power of two with an out-of-range index
    (``oob`` = plane size; jnp ``mode="drop"`` ignores it) so jitted
    point-clears compile once per size class instead of once per batch."""
    n = max(1, 1 << int(len(rows) - 1).bit_length())
    out = np.full(n, oob, np.int32)
    out[:len(rows)] = rows
    return out


class PaxosManager:
    def __init__(
        self,
        cfg: GigapaxosTpuConfig,
        n_replicas: int,
        apps: List[Replicable],
        wal=None,
        spill_ns: str = "default",
    ):
        """``spill_ns`` namespaces this manager's disk spill store — several
        managers (data plane + RC plane) share one cfg, and their DiskMaps
        must never adopt or clear each other's cold files."""
        assert len(apps) == n_replicas
        self.cfg = cfg
        self.R = n_replicas
        self.G = cfg.paxos.max_groups
        self.W = cfg.paxos.window
        self.P = cfg.paxos.proposals_per_tick
        # Register plane (RMWPaxos): a second dense state block at W=1 for
        # in-place consensus registers.  Composite row space: [0, G) log
        # rows, [G, G_total) register rows — the row index IS the mode bit,
        # so every row-keyed host structure below is sized G_total and the
        # two device planes stay separate jit inputs (mixed tick splits the
        # composite inbox at the static boundary).  G_reg == 0 keeps every
        # structure and code path bit-identical to pre-register builds.
        self.G_reg = cfg.paxos.register_groups
        self.G_total = self.G + self.G_reg
        self.state = st.init_state(self.R, self.G, self.W)
        self.rstate = (st.init_state(self.R, self.G_reg, 1)
                       if self.G_reg else None)
        self.rows = RowAllocator(self.G_total, split=self.G)
        self.apps = apps
        self.wal = wal
        self.alive = np.ones(self.R, bool)
        self.tick_num = 0
        self.outstanding: Dict[int, RequestRecord] = {}
        self._next_rid = 1
        # content-addressed payload interning (ordering/dissemination split,
        # Mode A half): N admitted requests sharing one body hold one bytes
        # object, and every digest-keyed consumer (WAL dedup, GBR2 batch
        # frames) sees identity-stable payloads
        self._paystore = PayloadStore()
        self._queues: Dict[int, collections.deque] = collections.defaultdict(
            collections.deque
        )  # row -> rids waiting for intake
        # callbacks held until the WAL record covering their tick is fsynced
        # (log-before-respond, the analog of logAndMessage's log-before-send,
        # AbstractPaxosLogger.java:157-178)
        self._held_callbacks: list = []
        # egress coalescing scopes bracketing each callback flush: hooks
        # return a close-callable; the response edge (ActiveReplica's
        # ClientEgress) uses this to hand the transport per-(client, tick)
        # frame lists instead of frame-at-a-time sends
        self._flush_scope_hooks: list = []
        # per (replica, row) dedup of executed request ids (bounded)
        self._seen: Dict[tuple, collections.OrderedDict] = collections.defaultdict(
            collections.OrderedDict
        )
        self._seen_cap = 8 * self.W
        self.stats = collections.Counter()
        # overload plane (ISSUE 14): watermark-with-hysteresis admission of
        # CLIENT-class work at the node intake.  Control-class proposes
        # (epoch stops, RC plane) are never governed — liveness traffic
        # rides through an overload.  None when disabled.
        self.overload = (
            _overload.IntakeGovernor(cfg.overload.intake_hi,
                                     cfg.overload.intake_lo,
                                     node=spill_ns or "-")
            if cfg.overload.enabled else None
        )
        self._ov_node = spill_ns or "-"
        self._stopped_rows: set[int] = set()
        # ---- pause/spill (deactivation, PaxosManager.java:2284-2412) ----
        # name -> HotRestoreInfo dict (+ "stopped" flag); device row freed.
        # With spill_dir set, cold paused records demand-page to disk
        # (DiskMap analog) so the paused population can exceed host RAM.
        import os as _os

        from ..utils.diskmap import DiskMap

        self._paused = DiskMap(
            _os.path.join(cfg.paxos.spill_dir, spill_ns)
            if cfg.paxos.spill_dir else None,
            cfg.paxos.spill_cache,
        )
        self._last_active = np.zeros(self.G_total, np.int64)
        self._row_outstanding = collections.Counter()
        # Host mirrors of config state (member mask / group size).  The tick
        # never writes these; they change only in create/remove/pause/unpause
        # — so the hot path (propose placement, execution bookkeeping) reads
        # numpy instead of paying a jitted scalar-index dispatch per request
        # (round-2 profile: ~230us per state.n_members[row] lookup).
        self._member_np = np.zeros((self.R, self.G_total), bool)
        self._n_members_np = np.zeros(self.G_total, np.int32)
        # further host mirrors for the vectorized (bulk/compact) path:
        # stopped flags, row->name, member bitmask, member-ordinal table
        self._stopped_np = np.zeros(self.G_total, bool)
        self._row_name_np = np.empty(self.G_total, object)
        self._member_bits = np.zeros(self.G_total, np.int64)
        self._member_ord = None  # lazy [R, G_total] cumulative member ordinal
        #: per-row window / laggard threshold: W for log rows, 1 for
        #: register rows (a register replica one version behind already
        #: needs the register shipped — there is no ring to catch up from)
        self._w_np = np.full(self.G_total, self.W, np.int32)
        self._w_np[self.G:] = 1
        # ---- compacted-outbox / bulk-propose machinery ----
        self._use_compact = bool(cfg.paxos.compact_outbox)
        self._exec_budget = cfg.paxos.exec_budget or max(4096, 2 * self.G_total)
        self._lag_budget = max(64, cfg.paxos.lag_budget)
        from ..ops.tick import CompactLayout

        self._compact_layout = CompactLayout(
            self.R, self.G, self._exec_budget, self._lag_budget
        )
        self._compact_layout_reg = (CompactLayout(
            self.R, self.G_reg, self._exec_budget, self._lag_budget
        ) if self.G_reg else None)
        bc = cfg.paxos.bulk_capacity or max(1 << 16, 4 * self.G)
        self._bulk_cap = 1 << (bc - 1).bit_length()
        self.bulk: Optional[BulkStore] = None  # lazy (most managers: unused)
        self._bulk_cbs: Dict[int, Callable] = {}  # optional per-rid cbs
        #: columnar completion sinks: one per admitted contiguous rid
        #: block — [rid0, rid0+n) -> sink(offsets, responses) called in
        #: per-tick batches instead of one Python callback per request
        #: (the completion-side twin of propose_bulk's columnar admission).
        #: Kept sorted by rid0; vectorized lookup via searchsorted.
        self._sink_blocks: list = []  # [rid0, n, remaining, sink]
        self._bulk_chunks: list = []  # FIFO of staged rid arrays
        self._bulk_leftover = np.zeros(0, np.int64)  # queued, not yet placed
        self._bulk_placed = None  # (rids, entries, ps, rows) of last tick
        #: the last completed tick's compacted laggard table — the l_*
        #: columns (rep, row, donor, donor exec, donor status, laggard
        #: exec): everything a checkpoint transfer needs, device-selected
        z0 = np.zeros(0, np.int64)
        self._lag_pending = (z0, z0, z0, z0, z0, z0)
        #: (replica, row) transfers noticed during tick completion, run at
        #: the next tick() top after a pipeline drain (watermark/blob skew)
        self._lag_sync_due: list = []
        #: pairs repaired at the previous tick() top: the pipelined outbox
        #: completed during that same drain re-flags them from pre-repair
        #: state, and without this filter the next tick would pay a
        #: pipeline drain just to find every entry already healed
        self._repaired_last: set = set()
        #: device sweep frontier (urows + amin/base/live [rows] gathers,
        #: _frontier_gather) stashed at the dispatch whose completion will
        #: sweep — see _complete_tick
        self._sweep_every = 64
        #: HOST-APPLIED execution watermark [R, G]: how far each replica's
        #: app has actually executed (device exec_slot runs one pipelined
        #: tick ahead of it).  The payload sweep must judge "everyone
        #: passed this slot" against THIS, not device state: a payload
        #: swept in the gap makes the very delivery that advanced the
        #: device watermark skip host-side — a silent lost write
        self._host_exec = np.zeros((self.R, self.G_total), np.int32)
        # ---- device-resident application (models/device_kv.py) ----
        self._device_app = bool(cfg.paxos.device_app)
        self.kv = None
        if self._device_app:
            if not self._use_compact:
                raise ValueError("device_app requires compact_outbox")
            if self.G_reg:
                raise ValueError(
                    "register_groups + device_app is not supported yet: the "
                    "fused KV program has no mixed-plane formulation"
                )
            if cfg.paxos.emulate_unreplicated or cfg.paxos.lazy_propagation:
                raise ValueError(
                    "baseline modes are host-app measurement tools; the "
                    "device app executes on-device only"
                )
            from ..models.device_kv import DeviceKVApp, init_kv

            table = cfg.paxos.kv_table or (
                1 << max(16, (4 * self.G - 1).bit_length())
            )
            # live-descriptor evictions must be impossible: rids are
            # sequential and the admit window caps live spread at
            # bulk_capacity, so a table >= 2x that can only ever evict
            # descriptors of already-freed requests
            table = max(table, 2 * self._bulk_cap)
            self.kv = init_kv(self.R, self.G, cfg.paxos.kv_slots, table)
            # the manager owns the device state; the Replicable faces the
            # control plane sees are row-granular views of it
            self.apps = [DeviceKVApp(self, r, row_of=self.rows.row)
                         for r in range(self.R)]
            apps = self.apps
            self._kv_reg_budget = cfg.paxos.kv_reg_budget or 2 * self.G
            self._kv_chunks: list = []  # staged descriptor uploads
            self._kv_watermark = 0  # highest rid with descriptor on device
            self._kv_uploaded = None  # this tick's upload (journaled)
        # ---- sharded data plane (parallel/shard_tick) ----
        # mesh_devices > 0 (or -1 = all): state lives partitioned over a
        # (replica, groups) device mesh and the tick runs as a shard_map
        # program — pallas gathers stay enabled per-shard, quorum exchange
        # is an explicit replica-axis all_gather.  Bit-identical to the
        # single-device path (tests/test_sharding_stack.py), so everything
        # downstream (WAL, replay, laggard repair, compaction layout) is
        # unchanged.
        self.mesh = None
        self._mesh_tick = None
        self._mesh_tick_compact = None
        if cfg.paxos.mesh_devices:
            import jax

            from ..parallel import shard_tick as _stk
            from ..parallel.mesh import make_mesh, state_shardings

            if self.G_reg:
                raise ValueError(
                    "register_groups + mesh_devices is not supported yet: "
                    "the shard_map tick has no mixed-plane formulation"
                )
            if self._device_app:
                raise ValueError(
                    "device_app + mesh_devices is not supported yet: the "
                    "fused KV program has no shard_map formulation"
                )
            devs = jax.devices()
            n = len(devs) if cfg.paxos.mesh_devices < 0 else cfg.paxos.mesh_devices
            if n > len(devs):
                raise ValueError(
                    f"mesh_devices={n} but only {len(devs)} devices visible"
                )
            self.mesh = make_mesh(
                devs[:n], replica_shards=cfg.paxos.mesh_replica_shards
            )
            _stk.validate_mesh_for(self.mesh, self.R, self.G)
            if self._use_compact:
                self._mesh_tick_compact = _stk.make_shardmap_tick_compact(
                    self.mesh, -1, self._exec_budget, self._lag_budget,
                    demand_decay=(cfg.placement.ewma_decay
                                  if cfg.placement.enabled else None),
                )
            else:
                self._mesh_tick = _stk.make_shardmap_tick(self.mesh, -1)
            # recreate the state distributed (each device materializes only
            # its shard; no single-device peak)
            self.state = st.init_state(
                self.R, self.G, self.W,
                shardings=state_shardings(self.mesh),
            )
        # ---- placement plane (placement/): advisory demand counters ----
        # Excluded from WAL/snapshot on purpose: a recovered node restarts
        # with cold counters and waits out the rebalancer's min-interval
        # guard; the migrations themselves ARE journaled (OP_CREATE_AT).
        self._placement = None
        self._demand_dev = None
        if cfg.placement.enabled:
            from ..parallel.mesh import GROUPS_AXIS as _GAX
            from ..placement.counters import PlacementCounters

            gs = self.mesh.shape[_GAX] if self.mesh is not None else 1
            self._placement = PlacementCounters(
                self.G, gs,
                decay=cfg.placement.ewma_decay,
                sample_every_ticks=cfg.placement.sample_every_ticks,
            )
            if self._mesh_tick_compact is not None:
                # device fold active: the tick threads this array through
                # the compact dispatch (see make_shardmap_tick_compact)
                from ..parallel import shard_tick as _stk2

                self._demand_dev = _stk2.init_demand(self.mesh, self.G)
            elif self._use_compact and not self._device_app \
                    and not self.G_reg and not cfg.paxos.read_leases \
                    and not cfg.paxos.group_health:
                # single-device compact path: the intake-popcount fold runs
                # fused inside paxos_tick_compact_demand (no mesh, so the
                # GSPMD same-jit hazard doesn't apply) instead of the old
                # O(G*P) host popcount per tick in _process_compact.
                # Mixed planes keep the host fold: placement demand covers
                # the LOG plane only (register rows never migrate shards).
                # Lease builds keep the host fold too — the lease tick
                # variants carry lease state instead of the demand array —
                # as do health builds (the generic health twin has no
                # demand formulation).
                self._demand_dev = jnp.zeros(self.G, jnp.float32)
        # ---- leader-lease plane (ISSUE 17) ----
        # Dense [G]/[G_reg] lease columns folded inside the fused tick:
        # holder/epoch/until live on device (authoritative for the write
        # fence); the host keeps a per-tick [5, G_total] mirror
        # (_lease_np) + its own lockstep clock for the local-read validity
        # check.  None when off — lease-off builds run the literal
        # pre-lease tick programs, bit for bit.
        self._lease = None
        self._rlease = None
        self._lease_np = None         # [5, G_total] lease_pack mirror
        self._lease_clock = 0         # host lockstep clock (+1/completed tick)
        self._lease_skew_ticks = 0    # test hook: injected holder clock skew
        self._lease_horizon = int(cfg.paxos.lease_ticks)
        self._lease_margin = int(cfg.paxos.lease_margin_ticks)
        if cfg.paxos.read_leases:
            if self._device_app:
                raise ValueError(
                    "read_leases + device_app is not supported yet: the "
                    "fused KV program has no lease formulation"
                )
            if cfg.paxos.mesh_devices:
                raise ValueError(
                    "read_leases + mesh_devices is not supported yet: the "
                    "shard_map tick has no lease formulation"
                )
            from ..ops.tick import init_lease as _init_lease

            self._lease = _init_lease(self.G, self._lease_margin)
            self._rlease = (_init_lease(self.G_reg, self._lease_margin)
                            if self.G_reg else None)
            self._lease_np = np.zeros((5, self.G_total), np.int32)
            self._lease_np[0, :] = -1  # holder column: -1 = none
        # ---- group-health plane (ISSUE 18) ----
        # Dense per-group stall/churn/heat columns folded inside the fused
        # tick; the host consumes only an O(K) health pack per tick (scalar
        # gauges + log2 histograms + top-K anomaly rows).  Observation-only:
        # nothing here feeds back into consensus, and with the flag off the
        # tick programs are the literal pre-health functions, bit for bit.
        self._health = None
        self._rhealth = None
        self._health_view = None      # HealthView as of last completed tick
        self._health_clock = 0        # host lockstep clock (+1/completed tick)
        self._health_topk = int(cfg.paxos.health_topk)
        self._health_wedge = int(cfg.paxos.health_wedge_ticks)
        self._health_shift = int(cfg.paxos.health_decay_shift)
        self._wedged_rows: set = set()   # last tick's wedged top-K rows
        self._topk_stuck: tuple = ()     # last tick's stuck top-K rows
        #: optional FlightRecorder set by the serving layer; health-state
        #: transitions (newly wedged/recovered, top-K churn) land in its
        #: ring so a SIGKILL'd cell's dump names its last-known sick groups
        self.flight = None
        if cfg.paxos.group_health:
            if self._device_app:
                raise ValueError(
                    "group_health + device_app is not supported yet: the "
                    "fused KV program has no health formulation"
                )
            if cfg.paxos.mesh_devices:
                raise ValueError(
                    "group_health + mesh_devices is not supported yet: the "
                    "shard_map tick has no health formulation"
                )
            self._health = init_health(self.G)
            self._rhealth = init_health(self.G_reg) if self.G_reg else None
        # first-occurrence scratch (generation-tagged so no per-tick clear)
        self._scr_pos = np.zeros(self.R * self.G_total, np.int64)
        self._scr_gen = np.zeros(self.R * self.G_total, np.int64)
        self._scr2_pos = None  # store-capacity scratch, allocated w/ store
        self._scr2_gen = None
        self._gen = 0
        # preallocated inbox staging buffers; entries placed last tick are
        # zeroed lazily at the next build instead of reallocating R*P*G
        self._in_req = np.zeros((self.R, self.P, self.G_total), np.int32)
        self._in_stp = np.zeros((self.R, self.P, self.G_total), bool)
        self._placed: list = []
        #: pipelined mode: (outbox, placed) of the last dispatched tick,
        #: consumed at the start of the next (SURVEY §2.2 item 3)
        self._pending_out = None
        #: completed outbox stashed by drain_pipeline() for the next tick()
        #: to return (sync-due ticks must not swallow an outbox)
        self._drained_out = None
        #: lock-free propose staging (drained at each tick; deque append/
        #: popleft are thread-safe) + a tiny rid-assignment lock that never
        #: contends with the tick
        self._staged: collections.deque = collections.deque()
        self._rid_lock = threading.Lock()
        self._draining = False
        #: per-request flow tracing (RequestInstrumenter analog; no-op
        #: unless GPTPU_REQTRACE is set — see utils/reqtrace.py).  Each
        #: manager has its own rid namespace (all start at rid 1), drawn
        #: from a monotonic counter (id() would be reused after GC).
        self.reqtrace = _reqtrace(f"pxm:{next(_MGR_SEQ)}")
        #: always-on tick phase clock (obs/phase.py): host timestamps only —
        #: "dispatch" is enqueue cost, the device wait lands in "tally" at
        #: the unpack sync point, so no device synchronization is added.
        #: cfg.obs.blocking_phases adds an exact "device_step" phase by
        #: blocking on the dispatch result (bench-style measurement).
        self._pc = _phase_clock("modea", plane=spill_ns)
        self._obs_block = bool(getattr(getattr(cfg, "obs", None),
                                       "blocking_phases", False))
        # Control-plane threads (messenger readers, protocol tasks) call the
        # admin/propose API while a tick driver loops on tick(); one reentrant
        # lock serializes them (the reference synchronizes on the instance map
        # the same way, PaxosManager.java:2284-2412).
        # register-plane capacity gauge (tests/test_obs_coverage.py WIRING)
        from ..obs.metrics import registry as _obsreg

        _obsreg().gauge(
            "register_groups",
            help="register-mode (RMW) row capacity of this manager",
        ).set(self.G_reg)
        # lease/read metric families (ISSUE 17; WIRING-gated)
        self._lease_gauge = _obsreg().gauge(
            "lease_holder_groups",
            help="groups with a currently granted read lease",
            node=self._ov_node)
        self._reads_local_c = _obsreg().counter(
            "reads_local_total",
            help="reads answered locally under a valid lease (no consensus "
                 "round)", node=self._ov_node)
        self._reads_fallback_c = _obsreg().counter(
            "reads_fallback_total",
            help="reads that fell back to a consensus round (no/invalid "
                 "lease or non-quiescent group)", node=self._ov_node)
        self._lease_waits_c = _obsreg().counter(
            "lease_waits_total",
            help="per-tick count of groups whose coordinator is write-"
                 "fenced waiting out a prior holder's lease",
            node=self._ov_node)
        # group-health gauge families (ISSUE 18; WIRING-gated).  Scalars
        # only: the histograms and top-K columns travel on the JSON
        # /health route, not the Prometheus scrape.
        self._hg_backlog = _obsreg().gauge(
            "health_backlogged_groups",
            help="groups with pending intake, an unexecuted assignment "
                 "frontier, or an unresolved election (health fold)",
            node=self._ov_node)
        self._hg_wedged = _obsreg().gauge(
            "health_wedged_groups",
            help="backlogged groups with no commit/exec progress for at "
                 "least health_wedge_ticks ticks", node=self._ov_node)
        self._hg_max_stall = _obsreg().gauge(
            "health_max_stall_ticks",
            help="largest per-group stall age (ticks since last progress "
                 "among backlogged groups)", node=self._ov_node)
        self._hg_max_churn = _obsreg().gauge(
            "health_max_churn",
            help="largest per-group coordinator-churn EWMA (handoffs over "
                 "a decaying window)", node=self._ov_node)
        self._hg_lease_wait = _obsreg().gauge(
            "health_lease_wait_groups",
            help="groups write-fenced behind a prior holder's lease this "
                 "tick (0 when leases are off)", node=self._ov_node)
        self.lock = ContendedLock()
        if self.wal is not None:
            self.wal.attach(self)

    # -------------------------------------------------- plane dispatch helpers
    # The composite row space is [0, G) log + [G, G_total) register; these
    # helpers are the ONLY places host code maps a composite row onto one
    # of the two device planes.  All are trivially log-plane passthroughs
    # when G_reg == 0 (rstate is None).

    def is_register_row(self, row: int) -> bool:
        return row >= self.G

    def _plane_state(self, row: int):
        """(plane_state, plane_row) for a composite row."""
        if row >= self.G:
            return self.rstate, row - self.G
        return self.state, row

    def _set_plane_state(self, row: int, new_state) -> None:
        if row >= self.G:
            self.rstate = new_state
        else:
            self.state = new_state

    def _dev_exec_np(self) -> np.ndarray:
        """Composite [R, G_total] device exec watermark (one fetch per
        plane)."""
        ex = np.asarray(self.state.exec_slot)
        if self.rstate is None:
            return ex
        return np.hstack([ex, np.asarray(self.rstate.exec_slot)])

    def _dev_exec_col(self, row: int) -> np.ndarray:
        """Device exec watermark column [R] for one composite row."""
        pst, prow = self._plane_state(row)
        return np.array(pst.exec_slot[:, prow])

    def _set_exec_status(self, r: int, row: int, exec_slot: int,
                         status: int) -> None:
        """Point-write a replica's exec watermark + status on the owning
        plane (checkpoint-transfer apply)."""
        pst, prow = self._plane_state(row)
        self._set_plane_state(row, pst._replace(
            exec_slot=pst.exec_slot.at[r, prow].set(exec_slot),
            status=pst.status.at[r, prow].set(status),
        ))

    # ------------------------------------------------------------ lease plane
    # (ISSUE 17) Host side of the read-lease columns.  The device fold in
    # ops/tick.py owns grant/renew/expiry and the write fence; the host
    # mirrors each tick's [5, G] lease_pack and answers reads against it.

    def _adopt_lease_pack(self, lease_pack) -> None:
        """Consume one tick's lease pack(s) at completion (the device sync
        point, so the pack describes the tick that just finished).  Mixed
        planes hand a (log, register) pair that lands side by side in the
        composite [5, G_total] mirror."""
        if isinstance(lease_pack, tuple):
            lp = np.concatenate([np.asarray(lease_pack[0]),
                                 np.asarray(lease_pack[1])], axis=1)
        else:
            lp = np.asarray(lease_pack)
        self._lease_np = lp
        self._lease_clock += 1  # lockstep with the device fold's clock+1
        self._lease_gauge.set(int((lp[LP_HOLDER] >= 0).sum()))
        waits = int(lp[LP_WAIT].sum())
        if waits:
            self._lease_waits_c.inc(waits)

    def _lease_drop_rows(self, rows) -> None:
        """Reset lease columns for freed rows (remove/pause/migration): a
        recycled row must not inherit the previous occupant's lease.  Row
        batches are padded to the next power of two with an out-of-range
        index (``mode="drop"`` ignores it) so the jitted clear compiles
        once per size class, not once per batch."""
        if self._lease is None or not len(rows):
            return
        if self._pending_out is not None:
            # a pending tick's lease_pack predates this drop; complete it
            # first so adoption cannot resurrect the dropped holder
            self.drain_pipeline()
        rows = np.asarray(rows, np.int32)
        lrows = rows[rows < self.G]
        rrows = rows[rows >= self.G] - np.int32(self.G)
        if len(lrows):
            self._lease = lease_clear_rows(
                self._lease, _pad_rows(lrows, self.G))
        if len(rrows) and self._rlease is not None:
            self._rlease = lease_clear_rows(
                self._rlease, _pad_rows(rrows, self.G_reg))
        if self._lease_np is not None:
            # the mirror may wrap a read-only device buffer zero-copy
            self._lease_np = np.array(self._lease_np)
            self._lease_np[LP_HOLDER, rows] = -1
            self._lease_np[LP_UNTIL, rows] = 0

    @_locked
    def lease_info(self, name: str) -> Optional[dict]:
        """Host view of one group's lease columns as of the last completed
        tick (tests/observability; None when leases are off or the group
        is not resident)."""
        if self._lease_np is None:
            return None
        row = self.rows.row(name)
        if row is None:
            return None
        lp = self._lease_np
        return {
            "holder": int(lp[LP_HOLDER, row]),
            "epoch": int(lp[LP_EPOCH, row]),
            "until": int(lp[LP_UNTIL, row]),
            "asn": int(lp[LP_ASN, row]),
            "clock": self._lease_clock,
        }

    # ----------------------------------------------------------- health plane
    # (ISSUE 18) Host side of the group-health fold.  The device owns the
    # dense stall/churn/heat columns; the host consumes one O(K) pack per
    # completed tick — scalar gauges, log2 histograms, and the top-K
    # stuckest/churniest/hottest rows — so finding the sick needles among
    # a million rows never costs an O(G) transfer.

    def _adopt_health_pack(self, health_pack) -> None:
        """Consume one tick's health pack(s) at completion (the device
        sync point, so the pack describes the tick that just finished).
        Mixed planes hand a (log, register) pair merged with register
        rows re-offset into the composite row space."""
        K = self._health_topk
        if isinstance(health_pack, tuple):
            hv = merge_health(
                unpack_health(np.asarray(health_pack[0]), min(K, self.G)),
                unpack_health(np.asarray(health_pack[1]),
                              min(K, self.G_reg)),
                self.G, K)
        else:
            hv = unpack_health(np.asarray(health_pack), min(K, self.G))
        self._health_view = hv
        self._health_clock += 1  # lockstep with the device fold's clock+1
        self._hg_backlog.set(int(hv.backlog))
        self._hg_wedged.set(int(hv.wedged))
        self._hg_max_stall.set(int(hv.max_stall))
        self._hg_max_churn.set(int(hv.max_churn) / 16.0)  # Q4 -> handoffs
        self._hg_lease_wait.set(int(hv.lease_wait))
        # transition detection -> flight ring: a SIGKILL'd cell's dump
        # should name its last-known sick groups, so newly wedged rows,
        # recoveries, and top-K membership churn are recorded as events
        stall_by_row = {int(r): int(v)
                        for v, r in zip(hv.stuck_val, hv.stuck_row)
                        if int(v) > 0}
        wedged_now = {r for r, v in stall_by_row.items()
                      if v >= self._health_wedge}
        stuck_now = tuple(sorted(stall_by_row))
        if self.flight is not None:
            for r in sorted(wedged_now - self._wedged_rows):
                self.flight.record("group_wedged", {
                    "row": r, "name": self.rows.name(r),
                    "stall_ticks": stall_by_row[r],
                    "tick": self.tick_num})
            for r in sorted(self._wedged_rows - wedged_now):
                self.flight.record("group_recovered", {
                    "row": r, "name": self.rows.name(r),
                    "tick": self.tick_num})
            if stuck_now != self._topk_stuck:
                self.flight.record("health_topk", {
                    "stuck_rows": list(stuck_now), "tick": self.tick_num})
        self._wedged_rows = wedged_now
        self._topk_stuck = stuck_now

    def _health_drop_rows(self, rows) -> None:
        """Reset health columns for freed rows (remove/pause/migration): a
        recycled row must not inherit the previous occupant's stall age or
        churn window.  Same padded-batch clear as _lease_drop_rows."""
        if self._health is None or not len(rows):
            return
        if self._pending_out is not None:
            # a pending tick's health_pack predates this drop; complete it
            # first so adoption cannot resurrect the dropped row
            self.drain_pipeline()
        rows = np.asarray(rows, np.int32)
        lrows = rows[rows < self.G]
        rrows = rows[rows >= self.G] - np.int32(self.G)
        if len(lrows):
            self._health = health_clear_rows(
                self._health, _pad_rows(lrows, self.G))
        if len(rrows) and self._rhealth is not None:
            self._rhealth = health_clear_rows(
                self._rhealth, _pad_rows(rrows, self.G_reg))

    @_locked
    def health_snapshot(self) -> Optional[dict]:
        """JSON-friendly view of the last completed tick's health pack
        (the ``/health`` route body; None when the fold is off or no tick
        has completed).  Top-K rows are resolved back to group names."""
        hv = self._health_view
        if hv is None:
            return None

        def _top(vals, rs, scale=1):
            return [{"row": int(r), "name": self.rows.name(int(r)),
                     "value": int(v) / scale}
                    for v, r in zip(vals, rs) if int(v) > 0]

        return {
            "clock": self._health_clock,
            "allocated": int(hv.alloc),
            "backlogged": int(hv.backlog),
            "wedged": int(hv.wedged),
            "max_stall_ticks": int(hv.max_stall),
            "max_churn": int(hv.max_churn) / 16.0,
            "lease_wait": int(hv.lease_wait),
            "wedge_ticks": self._health_wedge,
            "hist_stall": [int(x) for x in hv.hist_stall],
            "hist_churn": [int(x) for x in hv.hist_churn],
            "top_stuck": _top(hv.stuck_val, hv.stuck_row),
            "top_churny": _top(hv.churn_val, hv.churn_row, scale=16),
            "top_hot": _top(hv.heat_val, hv.heat_row, scale=16),
        }

    @_locked
    def group_info(self, name: str) -> Optional[dict]:
        """Upstream-style single-group drill-down (the dense analog of
        printing one PaxosInstanceStateMachine): ballot, frontiers, member
        liveness, lease columns, register version, pending intake, health
        columns, and a bounded WAL tail — all from row-gathers, no O(G)
        host work.  None when the group is not resident here.

        Accepts either the epoch-qualified paxos name (``svc#3``) or the
        bare service name — the latter resolves to the highest resident
        epoch, the same answer the reconfigurator's live-epoch map gives."""
        row = self.rows.row(name)
        if row is None and "#" not in name:
            prefix, best = name + "#", None
            for pname in self.rows.names():
                base, sep, etxt = pname.rpartition("#")
                if base == name and sep and etxt.isdigit():
                    if best is None or int(etxt) > best:
                        best = int(etxt)
            if best is not None:
                name = prefix + str(best)
                row = self.rows.row(name)
        if row is None:
            return None
        pst, prow = self._plane_state(row)
        register = row >= self.G
        member = np.asarray(pst.member[:, prow])
        bal_n = np.asarray(pst.bal_num[:, prow])
        bal_c = np.asarray(pst.bal_coord[:, prow])
        exec_s = np.asarray(pst.exec_slot[:, prow])
        next_s = np.asarray(pst.next_slot[:, prow])
        status = np.asarray(pst.status[:, prow])
        coord_a = np.asarray(pst.coord_active[:, prow])
        coord_p = np.asarray(pst.coord_preparing[:, prow])
        members = [int(r) for r in np.nonzero(member)[0]]
        replicas = {
            int(r): {
                "alive": bool(self.alive[r]),
                "ballot": [int(bal_n[r]), int(bal_c[r])],
                "exec_slot": int(exec_s[r]),
                "next_slot": int(next_s[r]),
                "status": int(status[r]),
                "coordinator": bool(coord_a[r]),
                "preparing": bool(coord_p[r]),
            }
            for r in members
        }
        info = {
            "name": name,
            "row": int(row),
            "mode": "register" if register else "log",
            "epoch": int(np.asarray(pst.epoch[prow])),
            "members": members,
            "replicas": replicas,
            "stopped": row in self._stopped_rows,
            "pending_intake": len(self._queues.get(row) or ())
            + int(self._row_outstanding[row]),
            "tick": self.tick_num,
        }
        if register and members:
            # register-plane rows carry one in-place value; the executed
            # slot IS its monotone version counter (RMWPaxos)
            info["version"] = max(int(exec_s[r]) for r in members)
        if self._lease_np is not None:
            info["lease"] = self.lease_info(name)
        if self._health is not None:
            h = self._rhealth if register else self._health
            info["health"] = {
                "stall_ticks": int(h.clock) - int(h.last_active[prow]),
                "coordinator": int(h.last_coord[prow]),
                "churn": int(h.churn[prow]) / 16.0,
                "heat": int(h.heat[prow]) / 16.0,
            }
        if self.wal is not None:
            try:
                info["wal_tail"] = self.wal.tail_for_row(row, name)
            except Exception:
                info["wal_tail"] = None
        return info

    def read(
        self,
        name: str,
        payload: bytes = b"",
        callback: Optional[Callable[[int, bytes], None]] = None,
        deadline: Optional[int] = None,
    ) -> Optional[int]:
        """Linearizable read (ISSUE 17).

        Answered LOCALLY — no consensus round, no journal entry — iff the
        last completed tick's lease mirror shows a live holder whose lease
        has not expired (minus any injected skew) AND the group is
        quiescent: the holder's executed frontier equals the accepted
        frontier as of that same tick, so every acked write is already
        applied at the holder.  Otherwise the read falls back to a
        CLS_READ propose through the ordered stream (a classic consensus
        read), which also renews liveness for the next attempt.

        ``payload`` must be side-effect-free under the app's ``execute``
        (the same payload may execute once locally or R times via the
        fallback).  The callback fires ``(rid, response)`` like propose's;
        local reads use rid 0 and fire synchronously.
        """
        if deadline is not None and _overload.expired(deadline):
            _overload.count_expired("intake", self._ov_node)
            if callback is not None:
                callback(_overload.RID_EXPIRED, None)
            return None
        row = self.rows.row(name)  # racy read: benign (propose's argument)
        lp = self._lease_np
        if (lp is not None and row is not None
                and row not in self._stopped_rows):
            holder = int(lp[LP_HOLDER, row])
            if (holder >= 0 and self.alive[holder]
                    and (self._lease_clock - self._lease_skew_ticks)
                    < int(lp[LP_UNTIL, row])
                    and int(self._host_exec[holder, row])
                    == int(lp[LP_ASN, row])):
                resp = self.apps[holder].execute(name, payload, 0)
                self._reads_local_c.inc()
                self.stats["local_reads"] += 1
                if callback is not None:
                    callback(0, resp)
                return 0
        self._reads_fallback_c.inc()
        return self.propose(name, payload, callback, deadline=deadline,
                            cls=_overload.CLS_READ)

    # ------------------------------------------------------------------ admin
    @_locked
    def create_paxos_instance(
        self, name: str, members: List[int], epoch: int = 0,
        register: bool = False,
    ) -> bool:
        """createPaxosInstance analog (PaxosManager.java:611).

        ``register=True`` births the group on the register plane (in-place
        RMW consensus; requires cfg.paxos.register_groups > 0) — the mode
        is permanent for the group's lifetime and journaled with the
        create."""
        if name in self.rows or name in self._paused:
            return False
        if register and not self.G_reg:
            raise ValueError(
                "register-mode create requires paxos.register_groups > 0")
        if register:
            if self.rows.full(hi=True):
                return False
            row = self.rows.alloc(name, hi=True)
        else:
            row = self._alloc_row(name)
        if row is None:
            return False
        mask = np.zeros((1, self.R), bool)
        for m in members:
            mask[0, m] = True
        pst, prow = self._plane_state(row)
        self._set_plane_state(row, st.create_groups(
            pst,
            np.array([prow], np.int32),
            mask,
            np.array([epoch], np.int32),
        ))
        self._set_member_row(row, mask[0], name)
        self._stopped_rows.discard(row)
        self._stopped_np[row] = False
        self._last_active[row] = self.tick_num
        if self.wal is not None:
            self.wal.log_create(name, members, epoch, register=register)
        return True

    @_locked
    def create_paxos_instance_at(
        self, name: str, members: List[int], epoch: int, row: int,
        app_seed: Optional[bytes] = None,
    ) -> bool:
        """Targeted create at a SPECIFIC free row (placement migration:
        the destination row selects the destination mesh shard).

        Unlike :meth:`create_paxos_instance` this never evicts — a full
        destination shard is a planning failure, not an excuse to spill
        someone else's group.  ``app_seed`` (the migrated epoch's final
        checkpoint) is restored into every member's app UNDER THE SAME
        LOCK as the birth and journaled WITH the create (OP_CREATE_AT):
        the plain create path's seed is applied by the caller and never
        journaled, which is fine for empty births but would lose a
        migrated group's state on replay."""
        if name in self.rows or name in self._paused:
            return False
        try:
            self.rows.alloc_at(name, row)
        except KeyError:
            return False  # row occupied / out of range
        mask = np.zeros((1, self.R), bool)
        for m in members:
            mask[0, m] = True
        # the row index encodes the mode: a targeted create at a register
        # row lands on the register plane with no extra record field
        pst, prow = self._plane_state(row)
        self._set_plane_state(row, st.create_groups(
            pst,
            np.array([prow], np.int32),
            mask,
            np.array([epoch], np.int32),
        ))
        self._set_member_row(row, mask[0], name)
        self._stopped_rows.discard(row)
        self._stopped_np[row] = False
        self._last_active[row] = self.tick_num
        if app_seed is not None:
            for s in members:
                self.apps[s].restore(name, app_seed)
        if self.wal is not None:
            self.wal.log_create_at(name, list(members), epoch, row, app_seed)
        return True

    def create_paxos_instances(
        self, names: List[str], members: List[int], epoch: int = 0
    ) -> int:
        """Batched createPaxosInstance: one device call + one WAL
        group-commit for the whole batch (the BatchedCreateServiceName
        shape, gigapaxos/PaxosManager.java:611 + batched creates).  Returns
        how many were created; names already present are skipped and
        capacity overflow spills to the single-create path (which can
        evict cold rows)."""
        if not all(0 <= m < self.R for m in members):
            raise ValueError(f"member slots out of range [0, {self.R}): "
                             f"{members}")
        with self.lock:
            fresh = list(dict.fromkeys(  # order-preserving dedup
                n for n in names
                if n not in self.rows and n not in self._paused
            ))
            take = fresh[:self.rows.free_count()]
            rest = fresh[len(take):]
            if take:
                rows = np.array([self.rows.alloc(n) for n in take], np.int32)
                mask = np.zeros((len(take), self.R), bool)
                mask[:, members] = True
                self.state = st.create_groups(
                    self.state, rows, mask,
                    np.full(len(take), epoch, np.int32),
                )
                # vectorized host-mirror refresh (the batched analog of
                # _set_member_row)
                self._member_np[:, rows] = mask.T
                self._n_members_np[rows] = mask.sum(axis=1)
                bits = int(np.bitwise_or.reduce(
                    (1 << np.array(members, np.int64))
                )) if members else 0
                self._member_bits[rows] = bits
                self._row_name_np[rows] = take
                self._member_ord = None
                self._stopped_np[rows] = False
                self._stopped_rows.difference_update(int(r) for r in rows)
                self._last_active[rows] = self.tick_num
                if self.wal is not None:
                    # one fsync for the whole batch, not one per name
                    self.wal.log_creates(take, list(members), epoch)
            made = len(take)
        for n in rest:  # overflow: single-create path (may evict)
            if self.create_paxos_instance(n, list(members), epoch):
                made += 1
        return made

    def _set_member_row(self, row, mask, name) -> None:
        """Refresh every host mirror of one row's config (mask: [R] bool)."""
        self._member_np[:, row] = mask
        self._n_members_np[row] = mask.sum()
        self._member_bits[row] = int(
            np.bitwise_or.reduce((1 << np.where(mask)[0]).astype(np.int64))
        ) if mask.any() else 0
        self._row_name_np[row] = name
        self._member_ord = None

    def _clear_member_rows(self, rows) -> None:
        self._host_exec[:, rows] = 0  # recycled rows restart at slot 0
        self._member_np[:, rows] = False
        self._n_members_np[rows] = 0
        self._member_bits[rows] = 0
        self._row_name_np[rows] = None
        self._member_ord = None

    @_locked
    def remove_paxos_instance(self, name: str) -> bool:
        """kill/cremation analog (PaxosManager.java:2162-2205)."""
        if name in self._paused:
            del self._paused[name]
            if self.wal is not None:
                self.wal.log_remove(name)
            return True
        row = self.rows.row(name)
        if row is None:
            return False
        # a pipelined pending outbox may still reference this row under its
        # OLD name<->row mapping; complete it before the row is freed (and
        # possibly recycled) so stale placements/decisions cannot resolve
        # against a future occupant
        self.drain_pipeline()
        pst, prow = self._plane_state(row)
        self._set_plane_state(
            row, st.free_groups(pst, np.array([prow], np.int32)))
        self._kv_clear_rows([row])
        self._clear_member_rows([row])
        self._lease_drop_rows([row])
        self._health_drop_rows([row])
        self.rows.free(name)
        self._fail_queued(row)
        self._purge_row_outstanding(row)
        if self.bulk is not None:
            gone = np.nonzero(self.bulk.valid & (self.bulk.row == row))[0]
            if len(gone):
                if self._bulk_cbs or self._sink_blocks:
                    self._bulk_fire(
                        self.bulk.rid[gone[~self.bulk.responded[gone]]]
                    )
                self.stats["failed_requests"] += self.bulk.fail(gone)
        self._stopped_rows.discard(row)
        self._stopped_np[row] = False
        if self.wal is not None:
            self.wal.log_remove(name)
        return True

    @_locked
    def group_members(self, name: str) -> Optional[List[int]]:
        if name in self._paused:
            hri = self._paused[name]
            return [int(r) for r in np.where(hri["member"])[0]]
        row = self.rows.row(name)
        if row is None:
            return None
        return [int(r) for r in np.where(self._member_np[:, row])[0]]

    @_locked
    def is_stopped(self, name: str) -> bool:
        if name in self._paused:
            return bool(self._paused[name].get("stopped"))
        row = self.rows.row(name)
        return row is not None and row in self._stopped_rows

    @_locked
    def exec_watermarks(self, name: str) -> Optional[np.ndarray]:
        """Per-replica-slot execution watermark for the group ([R] int), the
        donor-selection signal for checkpoint transfer: only a replica at
        the group maximum holds the complete (e.g. epoch-final) state."""
        if name in self._paused:
            return np.array(self._paused[name]["exec_slot"])
        row = self.rows.row(name)
        if row is None:
            return None
        return self._dev_exec_col(row)

    # ---------------------------------------------------------- placement
    def shard_geometry(self) -> tuple:
        """(groups_shards, rows_per_shard): mesh shard k owns the
        contiguous row range [k*per, (k+1)*per)."""
        gs = 1
        if self.mesh is not None:
            from ..parallel.mesh import GROUPS_AXIS as _GAX

            gs = self.mesh.shape[_GAX]
        return gs, self.G // gs

    @_locked
    def free_rows_in_shard(self, shard: int) -> int:
        """Free-row capacity of one mesh shard (rebalancer's budget)."""
        gs, _per = self.shard_geometry()
        lo, hi = st.shard_row_range(self.G, gs, shard)
        return sum(1 for r in self.rows._free if lo <= r < hi)

    @_locked
    def blob_bytes_of_row(self, row: int) -> int:
        """Checkpoint-blob size a migration of ``row`` would transfer (the
        rebalancer's move-cost estimator; MigrationStats.bytes_transferred
        records the same quantity after the fact).  0 for free rows.

        Serializes one member's checkpoint, so call it only at plan time
        (the rebalancer probes a handful of near-tie candidates per plan,
        and plans are min-interval paced) — never per tick."""
        name = self.rows.name(int(row))
        if name is None:
            return 0
        for r in range(self.R):
            if self.alive[r] and self._member_np[r, int(row)]:
                blob = self.apps[r].checkpoint(name)
                return len(blob) if blob is not None else 0
        return 0

    def demand_snapshot(self):
        """Host view of the per-group demand EWMA [G] (None when the
        placement plane is disabled).  Device-folded demand is pulled at
        most every ``placement.sample_every_ticks`` ticks."""
        p = self._placement
        if p is None:
            return None
        if self._demand_dev is not None and p.should_sample():
            p.sample_device()  # one device->host pull per sample window
        return p.demand_snapshot()

    # ------------------------------------------------------------ pause/spill
    def _resident_row(self, name: str) -> Optional[int]:
        """Row of ``name``, transparently unpausing a spilled group
        (getInstance -> unpause, PaxosManager.java:2370-2412)."""
        row = self.rows.row(name)
        if row is not None:
            return row
        if name in self._paused:
            return self._unpause(name)
        return None

    def _alloc_row(self, name: str) -> Optional[int]:
        """Row allocation with eviction under pressure: a full table
        force-pauses the coldest quiescent group to make room."""
        if self.rows.full():
            evicted = self._pause_eligible(limit=1, ignore_idle=True)
            if not evicted:
                return None  # every row is hot — table genuinely full
        return self.rows.alloc(name)

    @_locked
    def pause_idle(self, limit: int = 64) -> int:
        """Deactivator analog (PaxosManager.java:2951, period
        PC.DEACTIVATION_PERIOD): spill groups idle for
        ``deactivation_ticks``.  Returns the number paused."""
        return len(self._pause_eligible(limit=limit, ignore_idle=False))

    def _pause_eligible(self, limit: int, ignore_idle: bool) -> List[str]:
        # quiescence is judged against host bookkeeping — admit staged
        # proposals and complete any pipelined pending outbox first so the
        # judgment is current (and no stale placement can target a row this
        # call is about to free)
        self._drain_staged()
        self.drain_pipeline()
        idle_after = 0 if ignore_idle else self.cfg.paxos.deactivation_ticks
        exec_slot = np.array(self.state.exec_slot)
        next_slot = np.array(self.state.next_slot)
        member = self._member_np
        # rows referenced by live/queued bulk requests are not pausable
        # (bulk requests are invisible to _row_outstanding)
        bulk_ref = None
        if self.bulk is not None and (
            self.bulk.n_live or self._bulk_leftover.size or self._bulk_chunks
        ):
            bulk_ref = np.zeros(self.G_total, bool)
            bulk_ref[self.bulk.row[self.bulk.valid]] = True
            parts = ([self._bulk_leftover] if self._bulk_leftover.size
                     else []) + self._bulk_chunks
            if parts:
                q = np.concatenate(parts)
                qi, qlive = self.bulk.lookup(q)
                bulk_ref[self.bulk.row[qi[qlive]]] = True
        # coldest first so eviction keeps the working set hot
        cands = sorted(
            self.rows.items(), key=lambda kv: self._last_active[kv[1]]
        )
        paused: List[str] = []
        for name, row in cands:
            if len(paused) >= limit:
                break
            if row >= self.G:
                # register rows never pause: their whole footprint is the
                # register cell (no ring to reclaim), and hot_restore/HRI
                # extraction are log-plane shaped
                continue
            if self.tick_num - self._last_active[row] < idle_after:
                if not ignore_idle:
                    break  # sorted: everything later is hotter
                continue
            if self._queues.get(row) or self._row_outstanding[row] > 0:
                continue
            if bulk_ref is not None and bulk_ref[row]:
                continue
            ms = np.where(member[:, row])[0]
            if len(ms) == 0:
                continue
            ex = exec_slot[ms, row]
            # quiescent = every member executed everything anyone assigned
            if ex.min() != ex.max() or next_slot[ms, row].max() > ex.min():
                continue
            paused.append(name)
        if paused:
            self._do_pause(paused)
            if self.wal is not None:
                self.wal.log_pause(paused)
        return paused

    def _kv_clear_rows(self, rows) -> None:
        """Scrub device-app KV rows on free: a recycled row must not leak
        the previous occupant's keys to the next group."""
        if self.kv is not None and len(rows):
            r = np.asarray(rows, np.int32)
            self.kv = self.kv._replace(
                key=self.kv.key.at[:, r].set(0),
                val=self.kv.val.at[:, r].set(0),
            )

    def _do_pause(self, names: List[str]) -> None:
        """Spill exactly ``names`` (selection already done — also the WAL
        replay entry point, which must mirror the original run's choice so
        row allocation stays in lockstep)."""
        rows_to_free = []
        for name in names:
            row = self.rows.row(name)
            hri = st.extract_hri(self.state, row)
            hri["stopped"] = row in self._stopped_rows
            if self.kv is not None:
                # device-app state is keyed by ROW — it must ride the
                # spilled record or pause would silently drop it
                hri["dkv_key"] = np.asarray(self.kv.key[:, row])
                hri["dkv_val"] = np.asarray(self.kv.val[:, row])
            self._paused[name] = hri
            rows_to_free.append(row)
        self.state = st.free_groups(self.state, np.array(rows_to_free, np.int32))
        self._kv_clear_rows(rows_to_free)
        self._clear_member_rows(rows_to_free)
        self._lease_drop_rows(rows_to_free)
        self._health_drop_rows(rows_to_free)
        for name in names:
            row = self.rows.free(name)
            self._stopped_rows.discard(row)
            self._stopped_np[row] = False
            self._queues.pop(row, None)
        self.stats["paused"] += len(names)

    def _unpause(self, name: str) -> Optional[int]:
        hri = self._paused.get(name)
        if hri is None:
            return None
        row = self._alloc_row(name)
        if row is None:
            return None
        del self._paused[name]
        # reset the row to a clean slate, then restore the scalar columns
        mask = hri["member"].reshape(1, -1)
        self.state = st.create_groups(
            self.state, np.array([row], np.int32), mask,
            np.array([hri["epoch"]], np.int32),
        )
        self._set_member_row(row, mask[0], name)
        self.state = st.hot_restore(self.state, row, hri)
        # pause spills drained state (host == device), so the restored
        # device watermark is also the host-applied one for this row
        self._host_exec[:, row] = np.asarray(
            self.state.exec_slot[:, row]).astype(np.int32)
        if self.kv is not None and "dkv_key" in hri:
            self.kv = self.kv._replace(
                key=self.kv.key.at[:, row].set(jnp.asarray(hri["dkv_key"])),
                val=self.kv.val.at[:, row].set(jnp.asarray(hri["dkv_val"])),
            )
        if hri.get("stopped"):
            self._stopped_rows.add(row)
            self._stopped_np[row] = True
        self._last_active[row] = self.tick_num
        self.stats["unpaused"] += 1
        if self.wal is not None:
            self.wal.log_unpause(name)
        return row

    def paused_count(self) -> int:
        return len(self._paused)

    # ---------------------------------------------------------------- propose
    def propose(
        self,
        name: str,
        payload: bytes,
        callback: Optional[Callable[[int, bytes], None]] = None,
        stop: bool = False,
        entry: Optional[int] = None,
        deadline: Optional[int] = None,
        cls: int = _overload.CLS_CONTROL,
    ) -> Optional[int]:
        """propose/proposeStop analog (PaxosManager.java:1214-1288).

        ``deadline``: absolute wire deadline (unix ms); a request still
        staged when it passes is dropped at intake with callback
        ``(RID_EXPIRED, None)`` — dead work never reaches the device.
        ``cls``: traffic class; CLS_CLIENT proposes are refused with a
        retriable busy NACK ``(RID_BUSY, None)`` while the intake
        governor sheds, CLS_CONTROL (default) is never governed.

        Returns the request id, or None if the group is unknown (or fenced
        by a stop).  The common case takes NO manager lock: the request is
        staged into a thread-safe deque the next tick drains (the
        RequestBatcher.enqueue decoupling, gigapaxos/RequestBatcher.java:
        25-60) — so a client thread's propose latency is O(1) instead of
        up to a full tick of lock wait.  On the single-core artifact box
        end-to-end throughput is unchanged (within the run-to-run band);
        the decoupling targets multi-core hosts, where client threads no
        longer serialize behind the tick.  The existence/fenced pre-checks
        are racy reads; the authoritative outcome always rides the
        callback (a request staged for a group that is removed or stops
        before the drain fails with response None, as before).
        """
        if self.wal is not None and not self.wal.accepting_writes():
            return self._shed_propose(callback)
        if (cls != _overload.CLS_CONTROL and self.overload is not None
                and not self.overload.admit(cls)):
            return self._shed_busy(callback, cls)
        row = self.rows.row(name)  # racy read: benign (see docstring)
        if row is None:
            if name in self._paused:
                # cold group: unpause needs the lock anyway (rare path)
                return self._propose_locked(name, payload, callback, stop,
                                            entry)
            return None
        if row in self._stopped_rows:
            return self._propose_locked(name, payload, callback, stop, entry)
        with self._rid_lock:
            rid = self._next_rid
            self._next_rid += 1
        self._staged.append((rid, name, payload, callback, stop, entry,
                             deadline))
        if self.reqtrace.enabled:
            self.reqtrace.event(rid, "staged", name=name)
        return rid

    @_locked
    def _shed_propose(self, callback):
        """Storage low-watermark / failed WAL: refuse new writes with the
        retriable failure convention (response None) while reads and the
        already-admitted pipeline keep serving.  The disk-full case clears
        itself once the GC or an operator frees space; the failed case
        fail-stops the node at the next tick anyway."""
        self.wal.note_shed()
        if callback is not None:
            self._held_callbacks.append((callback, -1, None))
        self.stats["shed_requests"] += 1
        self.stats["failed_requests"] += 1
        return None

    @_locked
    def _shed_busy(self, callback, cls: int = _overload.CLS_CLIENT):
        """Intake governor shed (ISSUE 14): the explicit retriable NACK —
        the callback fires with RID_BUSY so the edge answers ``busy``
        (retry the SAME active after backoff) instead of a silent drop or
        a misleading ``not_active``.  ``cls`` labels the shed counter
        (client writes vs lease-era consensus-fallback reads)."""
        if callback is not None:
            self._held_callbacks.append((callback, _overload.RID_BUSY, None))
        self.stats["shed_requests"] += 1
        _overload.count_shed(cls, "intake", self._ov_node)
        return None

    @_locked
    def _propose_locked(self, name, payload, callback, stop, entry):
        """Slow path (cold or fenced groups): the original locked propose."""
        row = self._resident_row(name)
        if row is None:
            return None
        if row in self._stopped_rows:
            # stopped epoch: fail fast so the client can re-resolve actives
            if callback is not None:
                self._held_callbacks.append((callback, -1, None))
            self.stats["failed_requests"] += 1
            return None
        with self._rid_lock:
            rid = self._next_rid
            self._next_rid += 1
        if self.reqtrace.enabled:
            self.reqtrace.event(rid, "staged", name=name, path="slow")
        self._admit(rid, name, row, payload, callback, stop, entry)
        return rid

    def _admit(self, rid, name, row, payload, callback, stop, entry) -> None:
        """Insert one request into the per-row queues (manager lock held)."""
        if isinstance(payload, bytes):
            payload = self._paystore.intern(payload)
        members = np.where(self._member_np[:, row])[0]
        if entry is None or entry not in members:
            # spread entry replicas across the group's members (not the whole
            # replica set — a non-member never executes, so its callback
            # would be orphaned)
            entry = int(members[rid % len(members)]) if len(members) else 0
        rec = RequestRecord(rid, name, row, payload, stop, callback, entry)
        self.outstanding[rid] = rec
        self._row_outstanding[row] += 1
        self._queues[row].append(rid)
        self._last_active[row] = self.tick_num
        if self.reqtrace.enabled:
            self.reqtrace.event(rid, "admitted", row=row, entry=entry)

    def _drain_staged(self) -> None:
        """Admit every staged proposal (start of each tick, lock held).

        Re-entrancy guard: draining a request for a PAUSED group unpauses
        it, which under row pressure evicts via ``_pause_eligible`` — which
        itself drains staged work.  Without the guard that cycle double-
        unpauses a group (crash) or recurses once per staged cold item."""
        if self._draining:
            return
        self._draining = True
        try:
            while True:
                try:
                    rid, name, payload, callback, stop, entry, deadline = \
                        self._staged.popleft()
                except IndexError:
                    return
                if _overload.expired(deadline):
                    # deadline passed while staged: nobody is waiting, so
                    # admitting it would burn a device slot on dead work.
                    # RID_EXPIRED tells the edge to settle silently (the
                    # drop is counted ONCE, here at the detecting stage).
                    if callback is not None:
                        self._held_callbacks.append(
                            (callback, _overload.RID_EXPIRED, None))
                    self.stats["expired_drops"] += 1
                    _overload.count_expired("intake", self._ov_node)
                    continue
                row = self._resident_row(name)
                if row is None or row in self._stopped_rows:
                    # the group vanished or stopped between stage and drain
                    if callback is not None:
                        self._held_callbacks.append((callback, rid, None))
                    self.stats["failed_requests"] += 1
                    if self.reqtrace.enabled:
                        self.reqtrace.event(rid, "failed", name=name)
                    continue
                self._admit(rid, name, row, payload, callback, stop, entry)
        finally:
            self._draining = False

    def propose_stop(self, name: str, payload: bytes = b"", callback=None):
        return self.propose(name, payload, callback, stop=True)

    # -------------------------------------------------------- bulk (fast path)
    def _ensure_bulk(self) -> BulkStore:
        if self.bulk is None:
            self.bulk = BulkStore(self._bulk_cap)
            self._scr2_pos = np.zeros(self._bulk_cap, np.int64)
            self._scr2_gen = np.zeros(self._bulk_cap, np.int64)
        return self.bulk

    def _member_ordinals(self) -> np.ndarray:
        """[R, G] ordinal of each member within its group (cached; config
        changes invalidate)."""
        if self._member_ord is None:
            m = self._member_np.astype(np.int32)
            self._member_ord = np.cumsum(m, axis=0) - m
        return self._member_ord

    @_locked
    def propose_bulk(self, rows, payloads, stops=None,
                     callbacks=None, entries=None,
                     batch_sink=None,
                     cls: int = _overload.CLS_CLIENT) -> np.ndarray:
        """Vectorized propose: admit one request per entry of ``rows`` (row
        indices into the group table) in a single columnar operation.

        ``payloads``: one bytes object (shared by all — generated-load
        fan-out) or a sequence of per-request bytes.  Returns the assigned
        rid array (int64); negative entries were not admitted and no
        callback fires for them: -1 = target row unknown/stopped (client
        must re-resolve), -2 = store window full (transient backpressure —
        plain retry, nothing is wrong with the placement).
        ``callbacks``: optional per-request
        ``cb(rid, response_or_None)`` list aligned with ``rows``; fires
        through the durability-gated callback queue exactly like scalar
        proposes (log-before-respond).  Without callbacks, completion is
        observable through :meth:`bulk_stats` / :meth:`bulk_response`
        (the open-loop TESTPaxosClient model, testing/TESTPaxosClient.java:59).
        """
        if self._device_app and not getattr(self, "_in_kv_admit", False):
            raise ValueError(
                "device-app managers admit bulk work via propose_bulk_kv "
                "(a plain payload has no descriptor and could never place)"
            )
        if self.wal is not None and not self.wal.accepting_writes():
            # storage low-watermark / failed WAL: whole batch sheds with
            # the transient-backpressure code (-2, plain retry) — same
            # contract as a full store window; no callback fires
            self.wal.note_shed()
            n = len(rows)
            self.stats["shed_requests"] += n
            self.stats["failed_requests"] += n
            return np.full(n, -2, np.int64)
        if (cls == _overload.CLS_CLIENT and self.overload is not None
                and not self.overload.admit(cls)):
            # intake governor shed: whole batch refused with the transient
            # busy code (-2, retry the same active) — bulk is client-class
            # by default, the only control-class bulk caller is the RC
            # plane's own manager which passes CLS_CONTROL explicitly
            n = len(rows)
            self.stats["shed_requests"] += n
            _overload.count_shed(_overload.CLS_CLIENT, "intake",
                                 self._ov_node, n)
            return np.full(n, -2, np.int64)
        store = self._ensure_bulk()
        rows = np.asarray(rows, np.int64)
        out = np.full(len(rows), -1, np.int64)
        ok = (self._n_members_np[rows] > 0) & ~self._stopped_np[rows]
        if stops is not None:
            stops = np.asarray(stops, bool)
        if not ok.all():
            self.stats["failed_requests"] += int((~ok).sum())
            rows = rows[ok]
            if stops is not None:
                stops = stops[ok]
            if not isinstance(payloads, (bytes, bytearray)):
                payloads = [p for p, o in zip(payloads, ok) if o]
            if callbacks is not None:
                callbacks = [c for c, o in zip(callbacks, ok) if o]
        n = len(rows)
        if n == 0:
            return out
        # bounded-outstanding backpressure: admit only what the store
        # window can hold; the remainder returns -1 (retry later) instead
        # of raising mid-batch (MAX_OUTSTANDING_REQUESTS throttle analog)
        store._advance_lo()
        with self._rid_lock:
            rid0 = self._next_rid
            if store.n_live == 0:
                store.lo = rid0  # empty store: no slot can collide
                room = store.cap
            else:
                room = store.cap - (rid0 - store.lo)
            n_adm = max(0, min(n, room))
            self._next_rid += n_adm
        if self._next_rid >= 2**31:
            raise OverflowError("rid space exhausted (int32 device ids)")
        if n_adm == 0:
            self.stats["backpressured"] += n
            out[ok] = -2
            return out
        if n_adm < n:
            self.stats["backpressured"] += n - n_adm
            out[np.nonzero(ok)[0][n_adm:]] = -2
            rows = rows[:n_adm]
            if stops is not None:
                stops = stops[:n_adm]
            if not isinstance(payloads, (bytes, bytearray)):
                payloads = payloads[:n_adm]
        # spread entry duty across each group's members by rid rotation
        # (or pin it to a requested member — the edge node that owns the
        # client connection — falling back to rotation for non-members)
        nm = self._n_members_np[rows]
        k = ((rid0 + np.arange(n_adm)) % nm).astype(np.int32)
        om = self._member_ordinals()
        ent = np.zeros(n_adm, np.int32)
        for r in range(self.R):
            sel = self._member_np[r, rows] & (om[r, rows] == k)
            ent[sel] = r
        if entries is not None:
            e = int(entries)
            ent = np.where(self._member_np[e, rows], e, ent).astype(np.int32)
        if self.cfg.paxos.emulate_unreplicated:
            # measurement baseline (emulateUnreplicated,
            # PaxosManager.java:1751-1799): execute at the entry replica NOW,
            # respond, touch nothing else — no store, no tick, no journal
            resps = self._baseline_exec(
                rows, ent, payloads, rid0 + np.arange(n_adm, dtype=np.int64),
                callbacks, eager_fire=True,
            )
            if batch_sink is not None:
                # inline columnar delivery: no tick ever runs to route a
                # sink block, and the baseline has no durability to gate
                batch_sink(np.arange(n_adm, dtype=np.int64), resps)
            self.stats["decisions"] += n_adm
            out[np.nonzero(ok)[0][:n_adm]] = rid0 + np.arange(n_adm)
            return out
        if not isinstance(payloads, (bytes, bytearray)):
            # per-request bodies: intern so duplicates across the batch (and
            # across batches) collapse to one shared object before the store,
            # WAL, and batch frames ever see them
            payloads = [
                self._paystore.intern(p) if isinstance(p, bytes) else p
                for p in payloads
            ]
        rids = store.admit(rid0, rows.astype(np.int32), ent, stops,
                           payloads)
        if batch_sink is not None:
            # columnar completion: ONE sink call per tick delivers this
            # block's finished (offset, response) columns — no per-request
            # callback objects anywhere.  offsets are rid - rid0, i.e. the
            # caller's admitted-item order.
            self._sink_blocks.append([rid0, n_adm, n_adm, batch_sink])
        if callbacks is not None:
            for rid, cb in zip(rids, callbacks):
                if cb is not None:
                    self._bulk_cbs[int(rid)] = cb
        if self.cfg.paxos.lazy_propagation:
            # measurement baseline (emulateLazyPropagation /
            # EXECUTE_UPON_ACCEPT): the entry replica executes + responds
            # immediately; the admitted request still rides the normal
            # consensus stream, so the other replicas converge through
            # ordinary decisions (their mark_executed skips the entry's
            # pre-set bit — no double execution)
            idx = store.idx_of(rids)
            self._baseline_exec(rows, ent, store.payload[idx], rids,
                                callbacks=None, eager_fire=False,
                                store_idx=idx)
        self._bulk_chunks.append(rids)
        self._last_active[rows] = self.tick_num
        out[np.nonzero(ok)[0][:n_adm]] = rids
        return out

    def _baseline_exec(self, rows, ent, payloads, rids, callbacks,
                       eager_fire: bool, store_idx=None) -> list:
        """Entry-replica immediate execution for the two measurement
        baselines.  With ``store_idx`` (lazy mode) the store's entry exec
        bit + responded flag are pre-set so commit-time execution skips
        the entry replica and never re-responds.  Returns the responses
        aligned with the input order (b"" where the app returned none)."""
        if isinstance(payloads, (bytes, bytearray)):
            pa = np.empty(len(rows), object)
            pa[:] = bytes(payloads)
            payloads = pa
        payloads = np.asarray(payloads, object)
        rows = np.asarray(rows, np.int64)
        eager: list = []
        out_resps: list = [b""] * len(rows)
        for r in range(self.R):
            sel = ent == r
            if not sel.any():
                continue
            erb = getattr(self.apps[r], "execute_rows_batch", None)
            if erb is not None:
                resp = erb(rows[sel], payloads[sel], rids[sel])
            else:
                resp = self.apps[r].execute_batch(
                    self._row_name_np[rows[sel]], payloads[sel], rids[sel]
                )
            self.stats["executions"] += int(sel.sum())
            if store_idx is not None:
                si = store_idx[sel]
                self.bulk.exec_mask[si] |= np.int64(1) << r
                self.bulk.responded[si] = True
                if resp is not None:
                    ra = np.empty(len(si), object)
                    ra[:] = resp
                    self.bulk.response[si] = ra
                if self._bulk_cbs or self._sink_blocks:
                    self._bulk_fire(rids[sel],
                                    resp if resp is not None
                                    else [b""] * int(sel.sum()))
            else:
                for pos, j in enumerate(np.nonzero(sel)[0]):
                    r_j = resp[pos] if resp is not None else b""
                    out_resps[j] = r_j or b""
                    if eager_fire and callbacks is not None \
                            and callbacks[j] is not None:
                        # fired inline below — NEVER through the shared
                        # durability-gated queue, whose other occupants
                        # must keep waiting for their WAL sync
                        eager.append((callbacks[j], int(rids[j]),
                                      r_j or b""))
        if eager_fire:
            # the unreplicated baseline responds inline (no durability by
            # definition)
            for cb, rid, resp in eager:
                cb(rid, resp)
        return out_resps

    def _bulk_fire(self, rids, responses=None) -> None:
        """Queue completion callbacks for bulk rids that just reached their
        responded transition (durability-gated like every response)."""
        if self._sink_blocks:
            self._sink_route(rids, responses)
        if not self._bulk_cbs:
            return
        if responses is None:
            for rid in rids:
                cb = self._bulk_cbs.pop(int(rid), None)
                if cb is not None:
                    self._held_callbacks.append((cb, int(rid), None))
        else:
            import struct as _struct

            for rid, resp in zip(rids, responses):
                cb = self._bulk_cbs.pop(int(rid), None)
                if cb is not None:
                    if resp is not None and not isinstance(
                        resp, (bytes, bytearray)
                    ):
                        # device-app responses are i32 scalars
                        resp = _struct.pack("<i", int(resp))
                    self._held_callbacks.append((cb, int(rid), resp))

    def _sink_route(self, rids, responses) -> None:
        """Deliver completions to their rid-block sinks: vectorized block
        lookup, ONE durability-gated thunk per (sink, fire) instead of a
        Python callback per request.  ``responses`` None = failure."""
        a = np.asarray(rids, np.int64)
        if a.size == 0:
            return
        blocks = self._sink_blocks
        starts = np.fromiter((b[0] for b in blocks), np.int64,
                             count=len(blocks))
        bi = np.searchsorted(starts, a, "right") - 1
        gc = False
        for k in np.unique(bi):
            if k < 0:
                continue
            blk = blocks[k]
            sel = bi == k
            offs = (a[sel] - blk[0])
            inside = offs < blk[1]
            if not inside.any():
                continue
            offs = offs[inside]
            if responses is None:
                resp_sel = None
            else:
                idx = np.nonzero(sel)[0][inside]
                resp_sel = [responses[i] for i in idx]
            blk[2] -= len(offs)
            gc = gc or blk[2] <= 0
            sink = blk[3]

            def fire(_rid, _resp, s=sink, o=offs, rr=resp_sel):
                s(o, rr)

            self._held_callbacks.append((fire, -1, None))
        if gc:
            self._sink_blocks = [b for b in blocks if b[2] > 0]

    @_locked
    def propose_bulk_kv(self, rows, ops, keys, vals,
                        callbacks=None, entries=None) -> np.ndarray:
        """Device-app propose: admit requests whose execution is a KV
        descriptor (op, key, val) uploaded to the device table inside the
        fused tick — the decision stream never surfaces as host work.
        Returns rids like :meth:`propose_bulk` (-1 = rejected)."""
        assert self._device_app, "propose_bulk_kv needs cfg.paxos.device_app"
        self._in_kv_admit = True
        try:
            out = self.propose_bulk(rows, b"", callbacks=callbacks,
                                    entries=entries)
        finally:
            self._in_kv_admit = False
        adm = out >= 0
        if adm.any():
            self._kv_chunks.append((
                out[adm],
                np.asarray(ops, np.int32)[adm],
                np.asarray(keys, np.int32)[adm],
                np.asarray(vals, np.int32)[adm],
            ))
        return out

    def _take_kv_uploads(self):
        """Pull up to kv_reg_budget staged descriptors for this tick's
        fused upload; advances the placement watermark.  Returns padded
        [K] arrays (rid 0 = empty slot)."""
        K = self._kv_reg_budget
        take, total = [], 0
        while self._kv_chunks and total < K:
            c = self._kv_chunks[0]
            room = K - total
            if len(c[0]) <= room:
                take.append(c)
                total += len(c[0])
                self._kv_chunks.pop(0)
            else:
                take.append(tuple(a[:room] for a in c))
                self._kv_chunks[0] = tuple(a[room:] for a in c)
                total += room
        rids = np.zeros(K, np.int32)
        ops = np.zeros(K, np.int32)
        keys = np.zeros(K, np.int32)
        vals = np.zeros(K, np.int32)
        o = 0
        for c in take:
            n = len(c[0])
            rids[o:o + n] = c[0]
            ops[o:o + n] = c[1]
            keys[o:o + n] = c[2]
            vals[o:o + n] = c[3]
            o += n
        if o:
            self._kv_watermark = max(self._kv_watermark, int(rids[:o].max()))
        self._kv_uploaded = (rids[:o].copy(), ops[:o].copy(),
                             keys[:o].copy(), vals[:o].copy()) if o else None
        return rids, ops, keys, vals

    def bulk_response(self, rid: int):
        """Response payload of an entry-replica-completed bulk request.
        Retained until the request is fully executed everywhere and freed;
        None once freed (or unknown) — poll before the request completes on
        the LAST member, or use the scalar propose path for per-request
        callbacks.  Log-before-respond holds here exactly as for scalar
        callbacks: nothing is observable until the WAL covering the
        request's tick is fsynced."""
        if self.wal is not None and not self.wal.is_synced():
            return None
        s = self.bulk
        if s is None:
            return None
        i = rid & s.mask
        if s.valid[i] and s.rid[i] == rid:
            return s.response[i]
        return None

    def bulk_stats(self) -> dict:
        s = self.bulk
        return {
            "live": 0 if s is None else s.n_live,
            "done": 0 if s is None else s.done,
            "queued": int(self._bulk_leftover.size)
            + sum(len(c) for c in self._bulk_chunks),
        }

    def _first_occurrence(self, keys: np.ndarray, scr_pos, scr_gen) -> np.ndarray:
        """Mask of first occurrences of each key, order-preserving, O(n) —
        no sort (argsort/unique on the hot path was the round-3 lesson)."""
        self._gen += 1
        pos = np.arange(len(keys))
        # reversed scatter: the FIRST occurrence is written last and wins
        scr_pos[keys[::-1]] = pos[::-1]
        scr_gen[keys[::-1]] = self._gen
        return (scr_gen[keys] == self._gen) & (scr_pos[keys] == pos)

    def _purge_row_outstanding(self, row: int) -> None:
        """Drop placed-but-unfinished records of a removed group.  Without
        this the row's outstanding counter stays >0 forever (free_groups
        clears the member mask, so the sweep can never cover them) and the
        recycled row becomes permanently unpausable."""
        gone = [rid for rid, rec in self.outstanding.items() if rec.row == row]
        for rid in gone:
            rec = self.outstanding.pop(rid)
            if rec.callback is not None and not rec.responded:
                self._held_callbacks.append((rec.callback, rid, None))
        self._row_outstanding.pop(row, None)

    def _fail_queued(self, row: int) -> None:
        """Fail queued-but-never-committed requests for a stopped/removed
        group: fire callbacks with response None (client retries elsewhere,
        as the reference's clients do on an inactive-epoch error)."""
        q = self._queues.pop(row, None)
        if not q:
            return
        for rid in q:
            rec = self.outstanding.pop(rid, None)
            if rec is not None:
                self._row_outstanding[rec.row] -= 1
                if rec.callback is not None and not rec.responded:
                    self._held_callbacks.append((rec.callback, rid, None))
            self.stats["failed_requests"] += 1
            if self.reqtrace.enabled:
                self.reqtrace.event(rid, "failed", reason="group_fenced")

    # ------------------------------------------------------------------- tick
    def _build_inbox(self) -> TickInbox:
        self._drain_staged()
        # lazily clear last tick's placements instead of reallocating R*P*G
        req, stp = self._in_req, self._in_stp
        for _row, take in self._placed:
            for _rid, entry, p in take:
                req[entry, p, _row] = 0
                stp[entry, p, _row] = False
        if self._bulk_placed is not None:
            _r, _e, _p, _rw = self._bulk_placed
            req[_e, _p, _rw] = 0
            stp[_e, _p, _rw] = False
            self._bulk_placed = None
        placed = []
        for row, q in self._queues.items():
            used = collections.Counter()
            take = []
            while q and len(take) < self.P:
                rid = q.popleft()
                rec = self.outstanding.get(rid)
                if rec is None:
                    continue
                if not self.alive[rec.entry]:
                    # re-home the request to a live *member* so the response
                    # callback is not orphaned on a dead entry node
                    ms = np.where(self._member_np[:, row])[0]
                    live = [m for m in ms if self.alive[m]]
                    if not live:
                        q.appendleft(rid)
                        break
                    rec.entry = int(live[0])
                entry = rec.entry
                p = used[entry]
                if p >= self.P:
                    q.appendleft(rid)
                    break
                used[entry] += 1
                req[entry, p, row] = rid
                stp[entry, p, row] = rec.stop
                take.append((rid, entry, p))
                if self.reqtrace.enabled:
                    self.reqtrace.event(rid, "placed", tick=self.tick_num)
            if take:
                placed.append((row, take))
        self._placed = placed
        self._place_bulk(req, stp, placed)
        # hand the jit fresh copies (the staging buffers get mutated next
        # tick; a zero-copy dispatch aliasing them would race the async
        # step); the WAL reads inbox.alive without a device round-trip
        return TickInbox(req.copy(), stp.copy(), self.alive.copy())

    def _place_bulk(self, req, stp, placed) -> None:
        """Vectorized placement of the bulk queue into the staging arrays:
        first-occurrence per (entry, row) key (one new proposal per entry
        slot per tick on this path — at operating G that saturates the
        window), remainder stays queued in arrival order."""
        if not self._bulk_chunks and not self._bulk_leftover.size:
            return
        parts = ([self._bulk_leftover] if self._bulk_leftover.size else []) \
            + self._bulk_chunks
        self._bulk_chunks = []
        q = parts[0] if len(parts) == 1 else np.concatenate(parts)
        store = self.bulk
        idx, live = store.lookup(q)
        if not live.all():
            q, idx = q[live], idx[live]
        rows = store.row[idx]
        # rows gone dead under queued requests (removed/stopped): drop them
        bad = (self._n_members_np[rows] == 0) | self._stopped_np[rows]
        if bad.any():
            if self._bulk_cbs or self._sink_blocks:
                self._bulk_fire(q[bad])  # group gone: cb(None), client retries
            store.fail(idx[bad])
            self.stats["failed_requests"] += int(bad.sum())
            q, idx, rows = q[~bad], idx[~bad], rows[~bad]
        if not len(q):
            self._bulk_leftover = np.zeros(0, np.int64)
            return
        hold = np.zeros(0, np.int64)
        if self._device_app:
            # a request may only be placed once its descriptor upload is on
            # (or riding to) the device — rids beyond the watermark wait
            wm = q <= self._kv_watermark
            if not wm.all():
                hold = q[~wm]
                q, idx, rows = q[wm], idx[wm], rows[wm]
                if not len(q):
                    self._bulk_leftover = hold
                    return
        entries = store.entry[idx]
        if not self.alive.all():
            # re-home requests whose entry replica is dead to the first
            # live member of their group (response duty must stay live)
            dead = ~self.alive[entries]
            if dead.any():
                lm = self._member_np & self.alive[:, None]  # [R, G]
                has = lm.any(axis=0)
                flm = np.argmax(lm, axis=0).astype(np.int32)
                fixable = dead & has[rows]
                ei = idx[fixable]
                store.entry[ei] = flm[rows[fixable]]
                entries = store.entry[idx]
                # groups with no live member at all: keep queued
                keep = ~self.alive[entries]
                if keep.any():
                    sel = ~keep
                    qk = q[keep]
                    q, idx, rows, entries = (q[sel], idx[sel], rows[sel],
                                             entries[sel])
                else:
                    qk = np.zeros(0, np.int64)
            else:
                qk = np.zeros(0, np.int64)
        else:
            qk = np.zeros(0, np.int64)
        key = (entries.astype(np.int64) * self.G_total + rows).astype(np.intp)
        # up to P requests per (entry, row) per tick: P first-occurrence
        # passes assign p slots in arrival order (device admission is FIFO
        # across p for one entry, so per-key order is preserved)
        p = np.full(len(q), -1, np.int32)
        remaining = np.arange(len(q))
        for pp in range(self.P):
            if not len(remaining):
                break
            fo = self._first_occurrence(key[remaining], self._scr_pos,
                                        self._scr_gen)
            p[remaining[fo]] = pp
            remaining = remaining[~fo]
        # collision with slow-path placements at the same (entry, row):
        # shift this tick's bulk entries up past the used p slots
        if placed:
            used = collections.Counter()
            for row_, take in placed:
                for _rid, e_, _p in take:
                    used[(e_, row_)] += 1
            for (e_, row_), cnt in used.items():
                sel = (entries == e_) & (rows == row_) & (p >= 0)
                p[sel] += cnt
        fit = (p >= 0) & (p < self.P)
        if fit.any():
            fe, fp, fr = entries[fit], p[fit], rows[fit]
            req[fe, fp, fr] = q[fit].astype(np.int32)
            stp[fe, fp, fr] = store.stop[idx[fit]]
            self._bulk_placed = (q[fit], fe, fp, fr)
        rest = q[~fit]
        parts = [p for p in (rest, hold, qk) if p.size]
        self._bulk_leftover = (np.concatenate(parts) if len(parts) > 1
                               else (parts[0] if parts else rest))

    def _run_due_laggard_syncs(self) -> None:
        """Run checkpoint transfers noticed during tick completion.

        Runs at the top of tick(), after draining the pipeline: the
        transfer must capture the donor's device exec watermark and host
        app state at the SAME point — inside completion the device is one
        pipelined tick ahead of the host apps, and a laggard adopting that
        skewed pair permanently skips the slots between them (found live:
        a released write missing on every sync-repaired replica)."""
        due, self._lag_sync_due = self._lag_sync_due, []
        repaired, self._repaired_last = self._repaired_last, set()
        if not due:
            return
        if self._use_compact and self.cfg.paxos.device_donor_sel:
            # Control-summary path: O(due) host work, no [R, G] pulls.  The
            # drain completes the in-flight tick, so _lag_pending becomes
            # the LATEST tick's device-computed laggard table — which by
            # construction matches the current device state exactly (no
            # further tick has been dispatched).  An entry absent from that
            # table is no longer lagging (typically: repaired last call and
            # re-flagged from the pre-repair pipelined outbox — filtered
            # via _repaired_last before paying the drain).
            cand, seen = [], set()
            for r_, row_ in due:
                key = (int(r_), int(row_))
                if key in seen or key in repaired or not self.alive[key[0]]:
                    continue
                seen.add(key)
                cand.append(key)
            if not cand:
                return
            self.drain_pipeline()  # host apps catch up; refresh _lag_pending
            latest = {
                (int(r_), int(w_)): (int(d_), int(de_), int(ds_), int(le_))
                for r_, w_, d_, de_, ds_, le_ in zip(*self._lag_pending)
            }
            for key in cand:
                info = latest.get(key)
                if info is None or info[0] < 0:  # healed / no live donor
                    continue
                name = self.rows.name(key[1])
                if name is None:
                    continue
                if self._sync_from_summary(key[0], key[1], name, *info):
                    self._repaired_last.add(key)
            return
        # legacy host scan (full-outbox mode / device_donor_sel off):
        # re-check lag against CURRENT state first: pipelined completion
        # re-enqueues from the pre-repair outbox, and paying a pipeline
        # drain just to have every sync refuse (donor not ahead) would
        # stall the device/host overlap on the tick after every repair
        exec_slot = self._dev_exec_np()
        still, seen = [], set()
        for r_, row_ in due:
            key = (int(r_), int(row_))
            if key in seen or not self.alive[key[0]]:
                continue
            seen.add(key)
            ms = self._member_np[:, key[1]]
            if not ms[key[0]]:
                continue
            # per-row window: a register row (W=1) can never ring-replay,
            # so ANY lag routes through checkpoint transfer
            if (exec_slot[ms, key[1]].max() - exec_slot[key]
                    >= self._w_np[key[1]]):
                still.append(key)
        if not still:
            return
        self.drain_pipeline()  # host apps catch up to the device watermark
        for r_, row_ in still:
            name = self.rows.name(row_)
            if name:
                self.sync_laggard(r_, name)

    @_locked
    def tick(self):
        """One manager step.  Returns the tick's :class:`HostOutbox` (full
        mode) / :class:`CompactHostOutbox` (compact mode); in pipelined mode
        the return is the PREVIOUS tick's outbox (None on the first)."""
        pc = self._pc
        pc.begin()
        if self.overload is not None:
            # feed the intake governor once per tick: staged + queued +
            # in-flight scalar work + the live bulk window is the node's
            # client backlog (watermark-with-hysteresis shed, ISSUE 14)
            self.overload.update(
                self.pending_count() + len(self.outstanding)
                + (self.bulk.n_live if self.bulk is not None else 0))
        self._run_due_laggard_syncs()
        pc.mark("repair")
        if self._device_app:
            # descriptor upload rides the same fused program as the tick;
            # watermark must advance BEFORE the build so those rids place
            reg = self._take_kv_uploads()
        inbox = self._build_inbox()
        pc.mark("intake")
        placed = self._placed
        bulk_placed = self._bulk_placed
        lease_pack = None
        health_pack = None
        # dispatch first, journal second: the jitted step runs asynchronously
        # while the WAL appends+fsyncs this tick's record (SURVEY §2.2 item 3,
        # the BatchedLogger overlap, AbstractPaxosLogger.java:99-107).  Safe
        # because responses stay held until is_synced() (log-before-respond).
        if self._health is not None:
            # health builds: ONE generic jit covers every single-device
            # combination (compact/packed x lease x mixed planes) — absent
            # planes pass None and collapse out of the traced program.
            # device_app and mesh raise at init, so they never reach here.
            (self.state, self.rstate, self._lease, self._rlease,
             self._health, self._rhealth, pk_l, pk_r, lp_l, lp_r,
             hp_l, hp_r) = paxos_tick_health(
                self.state, self.rstate, self._lease, self._rlease,
                self._health, self._rhealth, inbox, -1,
                self._exec_budget if self._use_compact else 0,
                self._lag_budget, self._lease_horizon,
                self._use_compact, self._health_wedge,
                self._health_shift, self._health_topk)
            packed = pk_l if pk_r is None else (pk_l, pk_r)
            if lp_l is not None:
                lease_pack = lp_l if lp_r is None else (lp_l, lp_r)
            health_pack = hp_l if hp_r is None else (hp_l, hp_r)
        elif self._device_app:
            from ..models.device_kv import fused_compact

            self.state, self.kv, packed = fused_compact(
                self.state, self.kv, inbox, *reg, -1,
                self._exec_budget, self._lag_budget,
            )
        elif self._mesh_tick_compact is not None:
            # numpy inbox: committed to the mesh layout by in_shardings on
            # entry, as is the state after any eager admin-op mutation
            if self._demand_dev is not None:
                # placement: the demand EWMA folds inside the compact
                # dispatch (decided_now is donated away otherwise)
                self.state, packed, self._demand_dev = (
                    self._mesh_tick_compact(self.state, inbox,
                                            self._demand_dev)
                )
                self._placement.adopt_device(self._demand_dev)
            else:
                self.state, packed = self._mesh_tick_compact(self.state, inbox)
        elif self._mesh_tick is not None:
            self.state, packed = self._mesh_tick(self.state, inbox)
        elif self._use_compact:
            if self._lease is not None and self.rstate is not None:
                # lease twin of the mixed compact tick: both planes fold
                # their own lease columns; the [5, G] lease packs ride the
                # pending tuple and are pulled at completion
                (self.state, self.rstate, self._lease, self._rlease,
                 flat_l, flat_r, lp_l, lp_r) = paxos_tick_mixed_compact_lease(
                    self.state, self.rstate, self._lease, self._rlease,
                    inbox, -1, self._exec_budget, self._lag_budget,
                    self._lease_horizon,
                )
                packed = (flat_l, flat_r)
                lease_pack = (lp_l, lp_r)
            elif self._lease is not None:
                self.state, self._lease, packed, lease_pack = (
                    paxos_tick_compact_lease(
                        self.state, self._lease, inbox, -1,
                        self._exec_budget, self._lag_budget,
                        self._lease_horizon,
                    )
                )
            elif self.rstate is not None:
                # mixed planes: one fused program splits the composite
                # inbox at g_log, ticks both planes with their native W
                # (log ring vs register), and compacts each — merged back
                # into one composite outbox at completion
                self.state, self.rstate, flat_l, flat_r = (
                    paxos_tick_mixed_compact(
                        self.state, self.rstate, inbox, -1,
                        self._exec_budget, self._lag_budget,
                    )
                )
                packed = (flat_l, flat_r)
            elif self._demand_dev is not None:
                # placement: the intake-demand EWMA folds on device inside
                # the fused program (the mesh path's separate-dispatch twin
                # lives in make_shardmap_tick_compact)
                self.state, packed, self._demand_dev = (
                    paxos_tick_compact_demand(
                        self.state, inbox, self._demand_dev, -1,
                        self._exec_budget, self._lag_budget,
                        self._placement.decay,
                    )
                )
                self._placement.adopt_device(self._demand_dev)
            else:
                self.state, packed = paxos_tick_compact(
                    self.state, inbox, -1, self._exec_budget, self._lag_budget
                )
        elif self._lease is not None and self.rstate is not None:
            (self.state, self.rstate, self._lease, self._rlease,
             pk_l, pk_r, lp_l, lp_r) = paxos_tick_mixed_packed_lease(
                self.state, self.rstate, self._lease, self._rlease,
                inbox, -1, 0, self._lease_horizon)
            packed = (pk_l, pk_r)
            lease_pack = (lp_l, lp_r)
        elif self._lease is not None:
            self.state, self._lease, packed, lease_pack = (
                paxos_tick_packed_lease(self.state, self._lease, inbox, -1,
                                        0, self._lease_horizon))
        elif self.rstate is not None:
            self.state, self.rstate, pk_l, pk_r = paxos_tick_mixed_packed(
                self.state, self.rstate, inbox, -1, 0)
            packed = (pk_l, pk_r)
        else:
            self.state, packed = paxos_tick_packed(self.state, inbox, -1)
        # Device sweep frontier: computed ONLY at the dispatch whose
        # completion is scheduled to run _sweep_outstanding (1 in 64 ticks),
        # from THIS tick's post-state — it travels with the packed outbox so
        # the sweep consumes amin/base exactly as of the tick it completes.
        # The O(rows) frontier_rows gather is dispatched HERE too, right
        # behind sweep_frontier and before the next tick program enters the
        # stream: the rows holding records are host state already known at
        # dispatch, and a completion-time gather would queue behind (and on
        # CPU contend with) the next tick's O(G) program — the one device
        # round-trip this plane exists to avoid.  By completion the [rows]
        # results are long finished and the sweep is memcpy + O(records).
        # A drain that completes off-schedule just finds frontier=None and
        # falls back to the host reductions (correct, only slower).
        frontier = None
        done_at = self.tick_num + (2 if self.cfg.paxos.pipeline_ticks else 1)
        # mixed planes skip the device frontier: its [G]-indexed gathers
        # clip composite register rows onto log row G-1.  The host sweep
        # fallback reads the composite watermark via _dev_exec_np().
        if self.rstate is None and done_at % self._sweep_every == 0 and (
            self.outstanding or (self.bulk is not None and self.bulk.n_live)
        ):
            fr = sweep_frontier(
                self.state.exec_slot, self.state.member, inbox.alive
            )
            if fr is not None:
                frontier = self._frontier_gather(fr)
        if self._obs_block:
            # opt-in exact device step (bench.py's cumulative-prefix
            # measurement, online): costs the overlap the pipeline buys
            import jax

            jax.block_until_ready(packed)
            pc.mark(_BLOCKING_PHASE)
        pc.mark("dispatch")
        if self.wal is not None:
            self.wal.log_inbox(self.tick_num, inbox)
        pc.mark("wal_fsync")
        self.tick_num += 1
        if self.cfg.paxos.pipeline_ticks:
            # deferred unpack: _pending_out holds the still-on-device packed
            # buffer; the blocking device->host sync for tick N happens at
            # tick N+1's completion, so the device computes N while the host
            # builds N+1's inbox and the WAL fsyncs — ingest N+1 / device N
            # / app-exec N-1 genuinely concurrent (SURVEY §2.2 item 3; the
            # round-3 version unpacked eagerly, which blocked the host on
            # the device before any overlap could happen)
            if self._pending_out is not None:
                prev = self._pending_out
                self._pending_out = None  # before completing: _complete_tick
                # may reach drain_pipeline (pause_idle) — must not re-enter
                out = self._complete_tick(*prev)
            else:
                # nothing pending this tick — but drain_pipeline (laggard
                # sync, checkpoint) may have completed the previous tick's
                # outbox moments ago; hand that stashed result out instead
                # of dropping it, so callers polling tick() never miss a
                # completed outbox on sync-due ticks
                out, self._drained_out = self._drained_out, None
            self._pending_out = (packed, placed, bulk_placed, frontier,
                                 lease_pack, health_pack)
            # a due checkpoint must cover on-host effects of every tick the
            # device state contains — drain the one-tick pipeline first
            if self.wal is not None and self.wal.checkpoint_due():
                self.drain_pipeline()
        else:
            out = self._complete_tick(packed, placed, bulk_placed, frontier,
                                      lease_pack, health_pack)
        if self.wal is not None:
            self.wal.maybe_checkpoint()
        pc.end()
        return out

    def _complete_tick(self, packed, placed: list, bulk_placed=None,
                       frontier=None, lease_pack=None, health_pack=None):
        """Consume one tick's outbox (unpacking = the device sync point):
        requeue rejected intake, execute the ordered decision stream,
        release durable callbacks, periodic GC."""
        pc = self._pc
        # re-arm without observing: drain_pipeline completes a deferred tick
        # outside tick(), and cross-call idle time must not land in "tally"
        pc.touch()
        if lease_pack is not None:
            self._adopt_lease_pack(lease_pack)
        if health_pack is not None:
            self._adopt_health_pack(health_pack)
        if self._use_compact:
            if isinstance(packed, tuple):
                # mixed planes: two per-plane compact buffers; unpack each
                # against its own geometry, then merge with register rows
                # re-offset into the composite row space
                co_l = unpack_compact(np.asarray(packed[0]), self.R, self.G,
                                      self._exec_budget, self._lag_budget)
                co_r = unpack_compact(np.asarray(packed[1]), self.R,
                                      self.G_reg, self._exec_budget,
                                      self._lag_budget)
                out = merge_compact_outbox(co_l, co_r, self.G)
                flat = None
            else:
                flat = np.asarray(packed)
                out = unpack_compact(flat, self.R, self.G,
                                     self._exec_budget, self._lag_budget)
            e_resp = e_miss = None
            if self._device_app:
                # extras sliced through the shared layout descriptor —
                # fused_compact packs them through the same object
                e_resp, e_miss = self._compact_layout.kv_extras(flat)
            pc.mark("tally")
            self._process_compact(out, placed, bulk_placed, e_resp, e_miss)
        else:
            if isinstance(packed, HostOutbox):
                out = packed
            elif self.mesh is not None:
                # mesh full-outbox mode: the tick returns the raw sharded
                # TickOutbox — assemble per-field on the host (the on-device
                # pack miscompiles over mixed shardings; see shard_tick)
                from ..parallel.shard_tick import fetch_host_outbox

                out = fetch_host_outbox(packed)
            elif isinstance(packed, tuple):
                # mixed planes (full-outbox mode): unpack per plane —
                # register plane is W=1 / G_reg columns — and merge into a
                # composite [.., G_total] outbox (register exec lanes are
                # zero-padded up to W; exec_count there is at most 1)
                out_l = unpack_outbox(packed[0], self.R, self.P, self.W,
                                      self.G)
                out_r = unpack_outbox(packed[1], self.R, self.P, 1,
                                      self.G_reg)
                out = merge_outbox(out_l, out_r)
            else:
                out = unpack_outbox(packed, self.R, self.P, self.W, self.G)
            pc.mark("tally")
            self._process_outbox(out, placed, bulk_placed)
        pc.mark("execute")
        self._flush_callbacks()
        pc.mark("egress")
        if self.tick_num % self._sweep_every == 0:
            self._sweep_outstanding(frontier)
        if (
            self.cfg.paxos.deactivation_ticks > 0
            and self.tick_num % 256 == 0
            and len(self.rows) > 0
        ):
            self.pause_idle()
        pc.mark("sweep")
        return out

    @_locked
    def drain_pipeline(self) -> None:
        """Synchronously finish the pending pipelined outbox (no-op when
        nothing is pending or pipelining is off).  The completed outbox is
        stashed for the next tick() to return — draining (laggard sync, due
        checkpoint) must not make a tick's outbox vanish from the caller's
        point of view."""
        if self._pending_out is not None:
            prev = self._pending_out
            self._pending_out = None
            self._drained_out = self._complete_tick(*prev)

    def _flush_callbacks(self) -> None:
        """Release client responses only once the WAL covering their tick is
        durable (log-before-respond; with sync_every_ticks > 1 responses ride
        the next group commit)."""
        if not self._held_callbacks:
            return
        if self.wal is not None and not self.wal.is_synced():
            return
        held, self._held_callbacks = self._held_callbacks, []
        closers = [h() for h in self._flush_scope_hooks]
        try:
            for cb, rid, resp in held:
                cb(rid, resp)
        finally:
            for c in closers:
                c()

    def _process_outbox(self, out: HostOutbox, placed=None,
                        bulk_placed=None) -> None:
        taken = out.intake_taken
        for row, take in (self._placed if placed is None else placed):
            for rid, entry, p in reversed(take):
                if not taken[entry, p, row] and rid in self.outstanding:
                    self._queues[row].appendleft(rid)  # retry next tick
        if bulk_placed is not None:
            b_rids, b_e, b_p, b_r = bulk_placed
            tk = taken[b_e, b_p, b_r]
            rej = b_rids[~tk]
            if rej.size:  # oldest first: rejected re-enter at the front
                self._bulk_leftover = (
                    np.concatenate([rej, self._bulk_leftover])
                    if self._bulk_leftover.size else rej
                )
        er, es, eb, ec = out.exec_req, out.exec_stop, out.exec_base, out.exec_count
        if ec.any():
            for row in np.where(ec.sum(axis=0) > 0)[0]:
                name = self.rows.name(int(row))
                if name is None:
                    continue
                self._last_active[row] = self.tick_num
                for r in range(self.R):
                    n = int(ec[r, row])
                    for j in range(n):
                        rid = int(er[r, j, row])
                        slot = int(eb[r, row]) + j
                        is_stop = bool(es[r, j, row])
                        self._execute_one(r, int(row), name, rid, slot, is_stop)
        np.maximum(self._host_exec,
                   np.asarray(out.exec_base) + np.asarray(out.exec_count),
                   out=self._host_exec)
        self.stats["decisions"] += int(out.decided_now.sum())
        if self._placement is not None and self._demand_dev is None:
            # host demand fold (full-outbox path): per-group decisions are
            # visible here, unlike the compact flat buffer.  Placement
            # covers the log plane only — slice off register columns.
            self._placement.observe_intake(
                np.asarray(out.decided_now)[:self.G])
        # Self-heal laggards in FULL-outbox mode too (the compact path has
        # the twin block in _process_compact): a replica >= W behind can
        # never catch up by ring sync — its missed slots rotated out of
        # every decision ring — and in a quiescent system no later tick
        # will surface the lag through new decisions, so the stall is
        # permanent without this.  During journal replay repairs must come
        # only from journaled OP_SYNC records (see _process_compact).
        # Deferred to tick() for watermark/blob consistency (see
        # _run_due_laggard_syncs).
        if (self.cfg.paxos.auto_laggard_sync
                and getattr(self, "_replay_process", None) is None):
            # per-row window: register rows (W=1) flag at any lag — their
            # single ring plane was already overwritten
            lag = np.asarray(out.lag)
            self._lag_sync_due.extend(
                zip(*np.where(lag >= self._w_np[None, :lag.shape[1]])))

    def _execute_one(self, r: int, row: int, name: str, rid: int, slot: int,
                     is_stop: bool) -> None:
        if is_stop and row not in self._stopped_rows:
            self._stopped_rows.add(row)
            self._stopped_np[row] = True
            self._fail_queued(row)  # nothing after a stop can ever commit
        if rid == NO_REQUEST:
            self.stats["noops"] += 1
            return
        seen = self._seen[(r, row)]
        if rid in seen:
            self.stats["dup_commits"] += 1
            return
        seen[rid] = slot
        while len(seen) > self._seen_cap:
            seen.popitem(last=False)
        rec = self.outstanding.get(rid)
        if rec is None:
            if self.bulk is not None:
                sidx = rid & self.bulk.mask
                if self.bulk.valid[sidx] and self.bulk.rid[sidx] == rid:
                    self._store_exec_one(r, row, rid, slot, sidx)
                    return
            self.stats["orphan_execs"] += 1  # payload GC'd (laggard)
            return
        rec.slot = slot
        response = self.apps[r].execute(name, rec.payload, rid)
        rec.executed_by.add(r)
        self.stats["executions"] += 1
        if self.reqtrace.enabled:
            self.reqtrace.event(rid, "executed", slot=slot, replica=r)
        if r == rec.entry and not rec.responded:
            rec.responded = True
            if rec.callback is not None:
                self._held_callbacks.append((rec.callback, rid, response))
            if self.reqtrace.enabled:
                self.reqtrace.event(rid, "responded", slot=slot)
        members = int(self._n_members_np[row])
        if len(rec.executed_by) >= members and rec.responded:
            del self.outstanding[rid]
            self._row_outstanding[row] -= 1

    def _store_exec_one(self, r: int, row: int, rid: int, slot: int,
                        sidx: int) -> None:
        """Scalar execution of one bulk-store request (replay / full-outbox
        fallback; the compact hot path uses the vectorized twin below)."""
        s = self.bulk
        bit = np.int64(1) << r
        if s.exec_mask[sidx] & bit:
            self.stats["dup_commits"] += 1
            return
        s.exec_mask[sidx] |= bit
        if s.slot[sidx] < 0:
            s.slot[sidx] = slot
        name = self._row_name_np[row]
        payload = s.payload[sidx]
        desc_lost = False
        if self._device_app and len(payload or b"") == 0:
            # device-app store requests carry no host payload (the
            # descriptor lives in the device table); reaching the scalar
            # path with nothing to re-apply means the descriptor was lost
            # (sizing invariant violated).  Fail the request explicitly —
            # executing b"" would no-op into a silently-lost update
            # reported as an empty success.
            desc_lost = True
            resp = None
        else:
            resp = self.apps[r].execute(name, payload, rid)
            self.stats["executions"] += 1
        if s.entry[sidx] == r and not s.responded[sidx]:
            s.responded[sidx] = True
            s.response[sidx] = resp
            if desc_lost:
                self.stats["failed_requests"] += 1
                if self._bulk_cbs or self._sink_blocks:
                    self._bulk_fire([rid])  # cb(None): client-visible failure
            elif self._bulk_cbs or self._sink_blocks:
                self._bulk_fire([rid], [resp if resp is not None else b""])
        full = self._member_bits[row]
        if s.responded[sidx] and (s.exec_mask[sidx] & full) == full:
            s.valid[sidx] = False
            s.payload[sidx] = None
            s.response[sidx] = None
            s.n_live -= 1
            s.done += 1

    def _process_compact(self, co: CompactHostOutbox, placed=None,
                         bulk_placed=None, e_resp=None,
                         e_miss=None) -> None:
        """Vectorized twin of :meth:`_process_outbox` over the compacted
        stream: every lifecycle step is an index-array operation; only
        stops and non-store (dict) requests fall back to per-item code.

        e_resp/e_miss: device-app extras aligned with the exec stream —
        per-execution KV responses and descriptor-miss flags.  Misses
        route through the scalar path, whose app ``execute`` re-applies
        the descriptor host-side (or fails the request if the payload is
        gone)."""
        taken = co.taken_bits
        for row, take in (placed or []):
            for rid, entry, p in reversed(take):
                if (not (taken[entry, row] >> p) & 1
                        and rid in self.outstanding):
                    self._queues[row].appendleft(rid)
        if bulk_placed is not None:
            b_rids, b_e, b_p, b_r = bulk_placed
            tk = (taken[b_e, b_r] >> b_p) & 1
            rej = b_rids[tk == 0]
            if rej.size:
                self._bulk_leftover = (
                    np.concatenate([rej, self._bulk_leftover])
                    if self._bulk_leftover.size else rej
                )
        n = co.n_exec
        store = self.bulk
        if n:
            rids = co.e_rid[:n].astype(np.int64)
            reps = co.e_rep[:n]
            rows = co.e_row[:n]
            slots = co.e_slot[:n]
            stops = co.e_stop[:n]
            # host-applied execution watermark (see _host_exec): these
            # entries are being delivered to the apps RIGHT NOW
            np.maximum.at(self._host_exec, (reps, rows),
                          slots.astype(np.int32) + 1)
            valid = rids != NO_REQUEST
            # noop decisions (gap fills): stats parity with _execute_one
            self.stats["noops"] += int((~valid & ~stops).sum())
            self._last_active[rows] = self.tick_num
            if store is not None:
                idx, ok = store.lookup(rids)
                ok &= valid
            else:
                idx, ok = None, np.zeros(n, bool)
            # stops, dict-path/orphan rids, and device-app descriptor
            # misses: scalar path (rare at scale)
            per_item = (valid & ~ok) | stops
            vec = ok & ~stops
            if e_miss is not None:
                miss = e_miss[:n].astype(bool) & valid
                if miss.any():
                    self.stats["kv_misses"] += int(miss.sum())
                    per_item |= miss
                    vec &= ~miss
            for i in np.nonzero(per_item)[0]:
                row = int(rows[i])
                name = self.rows.name(row)
                if name is None:
                    continue
                self._execute_one(int(reps[i]), row, name, int(rids[i]),
                                  int(slots[i]), bool(stops[i]))
            touched = []
            for r in range(self.R):
                sel = vec & (reps == r)
                if not sel.any():
                    continue
                idx_r = idx[sel]
                # same rid committed twice in one tick (turnover re-propose):
                # keep the first (lowest-slot) occurrence
                fo = self._first_occurrence(idx_r, self._scr2_pos,
                                            self._scr2_gen)
                if not fo.all():
                    self.stats["dup_commits"] += int((~fo).sum())
                    idx_r = idx_r[fo]
                rid_r = rids[sel][fo]
                row_r = rows[sel][fo]
                slot_r = slots[sel][fo]
                fresh = store.mark_executed(idx_r, r)
                if not fresh.all():
                    self.stats["dup_commits"] += int((~fresh).sum())
                    idx_r, rid_r, row_r, slot_r = (
                        idx_r[fresh], rid_r[fresh], row_r[fresh],
                        slot_r[fresh],
                    )
                if not len(idx_r):
                    continue
                ns = store.slot[idx_r] < 0
                store.slot[idx_r[ns]] = slot_r[ns]
                if e_resp is not None:
                    # device app: execution already happened on-device
                    # inside the fused tick; only responses surface
                    resp = e_resp[:n][sel][fo]
                    if not fresh.all():
                        resp = resp[fresh]
                else:
                    erb = getattr(self.apps[r], "execute_rows_batch", None)
                    if erb is not None:
                        resp = erb(row_r, store.payload[idx_r], rid_r,
                                   lens=store.pay_len[idx_r])
                    else:
                        resp = self.apps[r].execute_batch(
                            self._row_name_np[row_r], store.payload[idx_r],
                            rid_r
                        )
                self.stats["executions"] += len(idx_r)
                em = (store.entry[idx_r] == r) & ~store.responded[idx_r]
                ri = idx_r[em]
                if len(ri):
                    store.responded[ri] = True
                    if resp is not None:
                        ra = np.empty(len(resp), object)
                        ra[:] = resp
                        store.response[ri] = ra[em]
                        if self._bulk_cbs or self._sink_blocks:
                            self._bulk_fire(store.rid[ri], list(ra[em]))
                    elif self._bulk_cbs or self._sink_blocks:
                        self._bulk_fire(store.rid[ri],
                                        [b""] * len(ri))
                touched.append(idx_r)
            if touched:
                ti = np.concatenate(touched)
                store.free_done(ti, self._member_bits[store.row[ti]])
        self.stats["decisions"] += co.decided_total
        if self._placement is not None and self._demand_dev is None:
            # host demand fold (single-device compact path): per-group
            # decisions are gone from the flat buffer, so fold the intake
            # acceptance bits instead — popcount of each row's taken mask
            bits = co.taken_bits.astype(np.int64)
            per_row = np.zeros(bits.shape[1], np.int64)
            for _ in range(self.P):
                per_row += (bits & 1).sum(axis=0)
                bits >>= 1
            # placement covers the log plane only: composite register
            # columns (rows >= G) are sliced off before the demand fold
            self._placement.observe_intake(per_row[:self.G])
        self._lag_pending = (co.l_rep.copy(), co.l_row.copy(),
                             co.l_donor.copy(), co.l_dexec.copy(),
                             co.l_dstat.copy(), co.l_lexec.copy())
        # During journal replay (_replay_process installed) laggard repair
        # must come ONLY from journaled OP_SYNC records: the live run's
        # donor choice may have been constrained by liveness that replay
        # (alive all-True by default) cannot see, and a replay-chosen donor
        # would restore a different checkpoint/watermark than the crash run.
        if (self.cfg.paxos.auto_laggard_sync and co.lag_n
                and getattr(self, "_replay_process", None) is None):
            # self-heal: a replica >= W behind can never catch up by ring
            # sync — its missed slots have rotated out of every decision
            # ring.  The budget's fair ordering prevents self-inflicted
            # lag, but crashes/recoveries still produce it.  DEFERRED to
            # tick() (see _run_due_laggard_syncs): a transfer captured
            # inside completion pairs the donor's device watermark with a
            # host app state one pipelined tick behind it, and the laggard
            # would permanently skip the difference.
            self._lag_sync_due.extend(zip(*self._lag_pending[:2]))

    def _sweep_outstanding(self, frontier=None) -> None:
        """Drop responded records whose payload can never be needed again:
        every member has executed past the slot, OR the slot has rotated
        out of every decision ring (slot <= base - W), in which case any
        replica still behind it can only catch up by checkpoint transfer,
        which carries the state, not the payload.

        A slot still inside the ring window of a DEAD member's gap must
        keep its payload: when that member revives with gap < W it
        catches up by ring REPLAY, and executing a swept slot would
        silently skip it (found live: a released write missing on the
        revived replica, then spread to others by checkpoint donation).

        ``frontier`` is the device control summary for this sweep —
        ``(urows, amin, base, live)``: the record rows collected at
        dispatch and the matching [rows] gathers of the reductions
        ``ops.tick.sweep_frontier`` computed from the completing tick's
        post-state — and routes to the O(records) path below.  ``None``
        (off-schedule drains, full-outbox mode, direct test calls) keeps
        the original [R, G] host reductions."""
        if not self.outstanding and (self.bulk is None
                                     or self.bulk.n_live == 0):
            return
        if frontier is not None:
            self._sweep_with_frontier(frontier)
            return
        # "passed" is judged against the HOST-APPLIED watermark (see
        # _host_exec): device exec includes the in-flight pipelined tick's
        # executions, whose host deliveries still need their payloads
        exec_slot = self._host_exec
        dev_exec = self._dev_exec_np()
        if self.bulk is not None and self.bulk.n_live:
            # vectorized twin for the store
            s = self.bulk
            member_exec = np.where(self._member_np, exec_slot,
                                   np.iinfo(np.int32).max)
            amin = member_exec.min(axis=0)  # [G] min ALL-member watermark
            # rotation uses the DEVICE watermark (ring overwrite is a
            # device-side fact); repair blobs cover it because transfers
            # capture pipeline-drained, host==device state
            base = np.where(self._member_np, dev_exec,
                            np.iinfo(np.int32).min).max(axis=0)  # [G]
            any_live = (self._member_np & self.alive[:, None]).any(axis=0)
            # rotation bound is STRICT: executed-through base-1 only proves
            # decisions through base-1, and slot s's ring plane survives
            # until s+W is decided — so s == base-W can still ride the
            # ring to a revived replica and must keep its payload
            sel = np.nonzero(
                s.valid & s.responded & (s.slot >= 0) & any_live[s.row]
                & ((s.slot < amin[s.row])
                   | (s.slot < base[s.row] - self._w_np[s.row]))
            )[0]
            if len(sel):
                s.valid[sel] = False
                s.payload[sel] = None
                s.response[sel] = None
                s.n_live -= len(sel)
                s.done += len(sel)
                self.stats["swept"] += len(sel)
        if not self.outstanding:
            return
        member = self._member_np
        dead = []
        for rid, rec in self.outstanding.items():
            if not rec.responded or rec.slot < 0:
                continue
            ms = np.where(member[:, rec.row])[0]
            if not any(self.alive[m] for m in ms):
                continue
            marks = [int(exec_slot[m, rec.row]) for m in ms]
            dbase = max(int(dev_exec[m, rec.row]) for m in ms)
            if (all(mk > rec.slot for mk in marks)
                    or rec.slot < dbase - self._w_np[rec.row]):  # strict
                dead.append(rid)
        for rid in dead:
            self._row_outstanding[self.outstanding[rid].row] -= 1
            del self.outstanding[rid]
            self.stats["swept"] += 1

    def _frontier_gather(self, fr):
        """Dispatch-time half of the frontier sweep: collect the rows
        holding live records (EVERY valid/outstanding record's row, placed
        or not — a record in flight at dispatch may be responded by the
        completion that consumes this gather) and enqueue the O(rows)
        ``frontier_rows`` gather right behind ``sweep_frontier``, clip-
        padded to a power-of-two bucket so the gather jit doesn't retrace
        per count.  Returns ``(urows, amin, base, live)`` with the [rows]
        results still on device — the completing tick blocks on nothing
        bigger than this."""
        s = self.bulk
        rows_parts = []
        if s is not None and s.n_live:
            rws = s.row[s.valid]
            if len(rws):
                rows_parts.append(rws.astype(np.int32))
        if self.outstanding:
            rows_parts.append(np.fromiter(
                (rec.row for rec in self.outstanding.values()),
                np.int32, len(self.outstanding)))
        if not rows_parts:
            return None
        urows = np.unique(np.concatenate(rows_parts)
                          if len(rows_parts) > 1 else rows_parts[0])
        k = max(16, 1 << int(len(urows) - 1).bit_length())
        padded = np.zeros(k, np.int32)
        padded[:len(urows)] = urows
        am, bs, lv = frontier_rows(*fr, padded)
        return urows, am, bs, lv

    def _sweep_with_frontier(self, frontier) -> None:
        """O(records) sweep off the device control summary: the [G]
        reductions (all-member exec min, device exec base, member liveness)
        ran inside ``sweep_frontier`` on the completing tick's post-state —
        which at consumption time IS the host-applied watermark, deliveries
        having just run — and the rows holding records were gathered back
        at dispatch (:meth:`_frontier_gather`), so the host cost here is a
        [rows] memcpy plus the record loop: it scales with live records,
        never [R, G], and never queues a device program mid-tick.

        A record whose row is missing from the dispatch-time gather (can
        only arise from repair/test paths mutating records between dispatch
        and completion) is conservatively kept for the next sweep.

        Equivalences with the host path: ``slot < amin[row]`` ⇔ every
        member's watermark is past the slot; ``base`` here is the completed
        tick's exec (the host path reads the in-flight tick's — i.e. this
        sweeps a one-tick-older rotation bound: strictly conservative).
        ``live`` is dispatch-time liveness — at most one pipelined tick
        staler than the host path's read of self.alive, and only ever a
        keep-guard."""
        urows, am, bs, lv = frontier
        amin = np.asarray(am)[:len(urows)]
        base = np.asarray(bs)[:len(urows)]
        live = np.asarray(lv)[:len(urows)]
        s = self.bulk
        if s is not None and s.n_live:
            cand = np.nonzero(s.valid & s.responded & (s.slot >= 0))[0]
            if len(cand):
                crows = s.row[cand]
                ix = np.minimum(np.searchsorted(urows, crows),
                                len(urows) - 1)
                sel = cand[(urows[ix] == crows) & live[ix]
                           & ((s.slot[cand] < amin[ix])
                              | (s.slot[cand] < base[ix] - self.W))]
                if len(sel):
                    s.valid[sel] = False
                    s.payload[sel] = None
                    s.response[sel] = None
                    s.n_live -= len(sel)
                    s.done += len(sel)
                    self.stats["swept"] += len(sel)
        if not self.outstanding:
            return
        dead = []
        for rid, rec in self.outstanding.items():
            if not rec.responded or rec.slot < 0:
                continue
            i = int(np.searchsorted(urows, rec.row))
            if i >= len(urows) or urows[i] != rec.row or not live[i]:
                continue
            if rec.slot < amin[i] or rec.slot < base[i] - self.W:
                dead.append(rid)
        for rid in dead:
            self._row_outstanding[self.outstanding[rid].row] -= 1
            del self.outstanding[rid]
            self.stats["swept"] += 1

    # --------------------------------------------------------------- liveness
    def set_alive(self, r: int, up: bool) -> None:
        self.alive[r] = up

    @_locked
    def sync_laggard(self, r: int, name: str, donor: Optional[int] = None) -> bool:
        """Checkpoint transfer for a replica lagging >= W on a group
        (StatePacket/handleCheckpoint analog,
        PaxosInstanceStateMachine.java:1852-1861): copy exec watermark from
        the most advanced live member and restore its app state.

        The transfer mutates device state outside the journaled tick
        stream, so it is journaled itself — as the EXACT transferred values
        (donor exec watermark, status, checkpoint blob), not just the donor
        id: under pipelined ticks the sync lands one tick behind the
        OP_TICK record appended at dispatch, so re-deriving the transfer
        from the donor's replay-time state would adopt a skewed watermark
        and the divergence compounds through every later replayed tick.
        """
        # the captured (watermark, blob) pair must be consistent: with a
        # pipelined tick in flight the device watermark is ahead of the
        # host apps by that tick's executions
        self.drain_pipeline()
        row = self.rows.row(name)
        if row is None:
            return False
        exec_slot = self._dev_exec_col(row)
        if donor is None:
            members = np.where(self._member_np[:, row])[0]
            donors = [m for m in members if self.alive[m] and m != r]
            if not donors:
                return False
            donor = max(donors, key=lambda m: exec_slot[m])
        if exec_slot[donor] <= exec_slot[r]:
            return False
        # "ship the register": for a register row the checkpoint IS the
        # register value — the same transfer covers both planes
        ckpt = self.apps[donor].checkpoint(name)
        donor_exec = int(exec_slot[donor])
        pst, prow = self._plane_state(row)
        donor_status = int(np.asarray(pst.status[donor, prow]))
        if self.wal is not None:
            self.wal.log_sync(r, name, int(donor), donor_exec, donor_status,
                              ckpt)
        self._apply_sync_values(r, int(row), name, donor_exec, donor_status,
                                ckpt)
        self.stats["checkpoint_transfers"] += 1
        return True

    def _sync_from_summary(self, r: int, row: int, name: str, donor: int,
                           donor_exec: int, donor_status: int,
                           old_exec: int) -> bool:
        """Checkpoint transfer driven entirely by the device control summary
        (the compact buffer's l_* columns): donor id, donor watermark/status
        and the laggard's own watermark all come from the last completed
        tick — which, after the caller's pipeline drain, IS the current
        device state — so nothing here reads ``[R, G]`` arrays.  Journals
        the same OP_SYNC record (exact transferred values) the host-scan
        :meth:`sync_laggard` would."""
        if not self.alive[donor] or not self._member_np[r, row]:
            # liveness/membership moved between the tick and the repair —
            # rare enough to pay the host scan, which re-derives the donor
            # from current state
            return self.sync_laggard(r, name)
        if donor_exec <= old_exec:
            return False
        ckpt = self.apps[donor].checkpoint(name)
        if self.wal is not None:
            self.wal.log_sync(r, name, int(donor), int(donor_exec),
                              int(donor_status), ckpt)
        self._apply_sync_values(r, int(row), name, int(donor_exec),
                                int(donor_status), ckpt,
                                old_exec=int(old_exec))
        self.stats["checkpoint_transfers"] += 1
        return True

    @_locked
    def apply_sync(self, r: int, name: str, donor_exec: int,
                   donor_status: int, ckpt: bytes) -> bool:
        """Journal-replay entry: re-apply a checkpoint transfer verbatim
        from its OP_SYNC record (no donor-state re-derivation)."""
        row = self.rows.row(name)
        if row is None:
            return False
        self._apply_sync_values(r, int(row), name, donor_exec, donor_status,
                                ckpt)
        self.stats["checkpoint_transfers"] += 1
        return True

    def _apply_sync_values(self, r: int, row: int, name: str,
                           donor_exec: int, donor_status: int,
                           ckpt: bytes, old_exec: Optional[int] = None) -> None:
        if old_exec is None:
            old_exec = int(self._dev_exec_col(row)[r])
        self.apps[r].restore(name, ckpt)
        self._host_exec[r, row] = max(int(self._host_exec[r, row]),
                                      donor_exec)
        self._set_exec_status(r, row, donor_exec, donor_status)
        self._seen.pop((r, row), None)
        # a transfer skips slots [old, donor) on r without ever reporting
        # them executed — settle the store's books or those requests stay
        # live forever.  Entry-duty requests whose response was skipped are
        # marked responded with no payload (client retries; at-least-once).
        if self.bulk is not None:
            s = self.bulk
            lo, hi = old_exec, donor_exec
            sel = np.nonzero(
                s.valid & (s.row == row) & (s.slot >= lo) & (s.slot < hi)
            )[0]
            if len(sel):
                s.exec_mask[sel] |= np.int64(1) << r
                ent = (s.entry[sel] == r) & ~s.responded[sel]
                s.responded[sel[ent]] = True
                if (self._bulk_cbs or self._sink_blocks) and ent.any():
                    self._bulk_fire(s.rid[sel[ent]])  # duty skipped: None
                s.free_done(sel, self._member_bits[s.row[sel]])

    @_locked
    def auto_sync_laggards(self, out=None) -> int:
        """Run checkpoint transfers where ring sync cannot catch up
        (lag >= W).  Accepts a full outbox; with None or a compacted one,
        uses the device-compacted laggard list of the last completed tick."""
        if out is None or isinstance(out, CompactHostOutbox):
            if out is None and not self._use_compact:
                # _lag_pending is only fed by the compact path; silently
                # iterating its (empty) initial value would strand laggards
                raise ValueError(
                    "auto_sync_laggards() needs the tick's outbox in "
                    "full-outbox mode"
                )
            if out is None and self.cfg.paxos.device_donor_sel:
                # control-summary path: after the drain, _lag_pending is the
                # latest completed tick's table and its donor columns match
                # the current device state — repair straight from it, no
                # [R, G] pulls (see _sync_from_summary)
                self.drain_pipeline()
                n = 0
                for r_, row_, d_, de_, ds_, le_ in zip(*self._lag_pending):
                    r = int(r_)
                    if not self.alive[r] or int(d_) < 0:
                        continue
                    name = self.rows.name(int(row_))
                    if name and self._sync_from_summary(
                            r, int(row_), name, int(d_), int(de_),
                            int(ds_), int(le_)):
                        n += 1
                return n
            src = out if out is not None else None
            l_rep = src.l_rep if src is not None else self._lag_pending[0]
            l_row = src.l_row if src is not None else self._lag_pending[1]
            pairs = zip(l_rep, l_row)
        else:
            lag = np.array(out.lag)
            pairs = zip(*np.where(lag >= self._w_np[None, :lag.shape[1]]))
        n = 0
        for r, row in pairs:
            if not self.alive[r]:
                continue
            name = self.rows.name(int(row))
            if name and self.sync_laggard(int(r), name):
                n += 1
        return n

    # ------------------------------------------------------------ conveniences
    def run_ticks(self, n: int) -> None:
        for _ in range(n):
            self.tick()

    @_locked
    def pending_count(self) -> int:
        n = sum(len(q) for q in self._queues.values()) + len(self._staged)
        n += int(self._bulk_leftover.size)
        n += sum(len(c) for c in self._bulk_chunks)
        if self._pending_out is not None:
            n += 1  # a pipelined outbox still needs a tick to complete
        return n

"""Tier-1 observability coverage gate (ISSUE 9 satellite 5).

Static source checks that keep the flight-deck honest as the code grows:
every phase a driver DECLARES (obs/phase.py DRIVER_PHASES — the contract
dashboards are built against) is actually marked in that driver's tick
path; every WAL durability point goes through the instrumented ``_sync``
(a bare ``journal.sync()`` would be an unmetered fsync); and the metric
families the README documents exist at their declared wiring sites.

Greps over source, not runtime: a forgotten ``pc.mark`` or a new direct
fsync fails here in milliseconds instead of silently holing a dashboard.
"""

import os
import re

from gigapaxos_tpu.obs.phase import BLOCKING_PHASE, DRIVER_PHASES

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DRIVER_FILES = {
    "modea": "gigapaxos_tpu/paxos/manager.py",
    "modeb": "gigapaxos_tpu/modeb/manager.py",
    "chain": "gigapaxos_tpu/chain/manager.py",
    "chain_modeb": "gigapaxos_tpu/chain/modeb.py",
}


def _src(rel: str) -> str:
    with open(os.path.join(ROOT, rel)) as f:
        return f.read()


def test_driver_phases_contract_is_sane():
    assert set(DRIVER_PHASES) == set(DRIVER_FILES)
    for driver, phases in DRIVER_PHASES.items():
        assert phases, driver
        assert len(phases) == len(set(phases)), f"{driver}: duplicate phase"
        # the opt-in blocking mark is extra, never part of the base contract
        assert BLOCKING_PHASE not in phases, driver
    # every driver journals and executes — the two phases any SLO story
    # starts from
    for driver, phases in DRIVER_PHASES.items():
        assert "wal_fsync" in phases, driver
        assert "execute" in phases, driver


def test_every_declared_phase_is_marked_in_its_driver():
    for driver, rel in DRIVER_FILES.items():
        src = _src(rel)
        assert re.search(r"phase_clock\(", src), f"{rel}: no phase clock"
        marked = set(re.findall(r'\.mark\(\s*["\']([a-z_]+)["\']', src))
        missing = set(DRIVER_PHASES[driver]) - marked
        assert not missing, f"{rel}: declared but never marked: {missing}"
        undeclared = marked - set(DRIVER_PHASES[driver]) - {BLOCKING_PHASE}
        assert not undeclared, (
            f"{rel}: marks {undeclared} not in DRIVER_PHASES[{driver!r}] — "
            f"add them to obs/phase.py so dashboards see the contract")
        # begin/end bracket the marks
        assert ".begin()" in src and ".end()" in src, rel


def test_wal_fsync_goes_through_instrumented_sync_only():
    """Every durability point must flow through ``_sync`` (timed +
    stall-counted); a bare ``journal.sync()`` anywhere else is an
    unmetered fsync."""
    for rel in ("gigapaxos_tpu/wal/logger.py",
                "gigapaxos_tpu/modeb/logger.py"):
        src = _src(rel)
        bare = len(re.findall(r"\.journal\.sync\(\)", src))
        defs = len(re.findall(r"def _sync\(", src))
        # modeb's logger may inherit _sync; either way the only permitted
        # journal.sync() calls are the bodies of _sync definitions
        assert bare == defs, (
            f"{rel}: {bare} journal.sync() calls vs {defs} _sync defs — "
            f"route new durability points through self._sync()")
    # across the rest of the tree nobody reaches around the logger
    for base, _dirs, files in os.walk(os.path.join(ROOT, "gigapaxos_tpu")):
        for fn in files:
            rel = os.path.relpath(os.path.join(base, fn), ROOT)
            if not fn.endswith(".py") or rel in (
                    "gigapaxos_tpu/wal/logger.py",
                    "gigapaxos_tpu/modeb/logger.py"):
                continue
            assert ".journal.sync()" not in _src(rel), (
                f"{rel}: direct journal.sync() bypasses wal_fsync_seconds")


WIRING = {
    # metric family -> file that must create it
    "tick_phase_seconds": "gigapaxos_tpu/obs/phase.py",
    "tick_seconds": "gigapaxos_tpu/obs/phase.py",
    "wal_fsync_seconds": "gigapaxos_tpu/wal/logger.py",
    "wal_fsync_stalls_total": "gigapaxos_tpu/wal/logger.py",
    "wal_appended_bytes_total": "gigapaxos_tpu/wal/logger.py",
    "wal_checkpoint_seconds": "gigapaxos_tpu/wal/logger.py",
    "transport_writev_batch_frames": "gigapaxos_tpu/net/transport.py",
    # overload plane (ISSUE 14): per-class backpressure sheds at the
    # transport edge; deadline drops / admission NACKs in overload.py
    "transport_backpressure_drop_class_total":
        "gigapaxos_tpu/net/transport.py",
    "overload_expired_drops_total": "gigapaxos_tpu/overload.py",
    "overload_admission_shed_total": "gigapaxos_tpu/overload.py",
    "overload_intake_shedding": "gigapaxos_tpu/overload.py",
    # ordering/dissemination split (ISSUE 12): coordinator egress economics
    # and ring-hop latency live in the Mode B manager
    "egress_bytes_per_decision": "gigapaxos_tpu/modeb/manager.py",
    "ring_hop_seconds": "gigapaxos_tpu/modeb/manager.py",
    # register mode (ISSUE 16): paystore sharing rates are first-class at
    # millions of register groups; the gauge sizes the register plane
    "paystore_hits_total": "gigapaxos_tpu/paxos/paystore.py",
    "paystore_misses_total": "gigapaxos_tpu/paxos/paystore.py",
    "paystore_evictions_total": "gigapaxos_tpu/paxos/paystore.py",
    "register_groups": "gigapaxos_tpu/paxos/manager.py",
    # lease plane (ISSUE 17): local-read economics — holder gauge, the
    # local/fallback split, and writes parked behind a prior holder's lease
    "lease_holder_groups": "gigapaxos_tpu/paxos/manager.py",
    "reads_local_total": "gigapaxos_tpu/paxos/manager.py",
    "reads_fallback_total": "gigapaxos_tpu/paxos/manager.py",
    "lease_waits_total": "gigapaxos_tpu/paxos/manager.py",
    "client_read_latency_seconds": "gigapaxos_tpu/client.py",
    "client_commit_latency_seconds": "gigapaxos_tpu/client.py",
    "client_batch_rtt_seconds": "gigapaxos_tpu/client.py",
    "commit_latency_seconds":
        "gigapaxos_tpu/reconfiguration/active_replica.py",
    "cell_up": "gigapaxos_tpu/cells/supervisor.py",
    "cell_restarts_total": "gigapaxos_tpu/cells/supervisor.py",
    "supervisor_restart_backoff_seconds":
        "gigapaxos_tpu/cells/supervisor.py",
    "supervisor_heartbeat_timeout_seconds":
        "gigapaxos_tpu/cells/supervisor.py",
    # group health plane (ISSUE 18): device-side fold gauges in the Mode A
    # manager (the Mode B twin registers its own subset), and the scenario
    # timeline recorder's sample/event counters
    "health_backlogged_groups": "gigapaxos_tpu/paxos/manager.py",
    "health_wedged_groups": "gigapaxos_tpu/paxos/manager.py",
    "health_max_stall_ticks": "gigapaxos_tpu/paxos/manager.py",
    "health_max_churn": "gigapaxos_tpu/paxos/manager.py",
    "health_lease_wait_groups": "gigapaxos_tpu/paxos/manager.py",
    "timeline_samples_total": "gigapaxos_tpu/obs/timeline.py",
    "timeline_events_total": "gigapaxos_tpu/obs/timeline.py",
}


def test_documented_metric_families_exist_at_their_sites():
    for name, rel in WIRING.items():
        assert f'"{name}"' in _src(rel), f"{name} not wired in {rel}"
    # transport mirrors its stats counters into transport_<key>_total, and
    # per-peer byte accounting (the once-per-peer-link verification
    # instrument) into transport_peer_<key>_total
    assert 'f"transport_{key}_total"' in _src("gigapaxos_tpu/net/transport.py")
    assert 'f"transport_peer_{key}_total"' in _src(
        "gigapaxos_tpu/net/transport.py")


def test_scrape_surfaces_are_wired():
    worker = _src("gigapaxos_tpu/cells/worker.py")
    # per-cell export over the control socket, cell-labelled
    assert "render_registry" in worker and '"cell": str(cell)' in worker
    for cmd in ('cmd == "metrics"', 'cmd == "trace"', 'cmd == "flight"',
                'cmd == "healthz"', 'cmd == "health"', 'cmd == "group"',
                'cmd == "timeline"'):
        assert cmd in worker, cmd
    sup = _src("gigapaxos_tpu/cells/supervisor.py")
    assert "merge_scrapes" in sup and "MetricsServer" in sup
    assert "merge_timelines" in sup  # /timeline composes per-cell series
    server = _src("gigapaxos_tpu/server.py")
    assert "MetricsServer" in server and "FlightRecorder" in server
    assert "TimelineRecorder" in server
    http = _src("gigapaxos_tpu/obs/http.py")
    for route in ('"/metrics"', '"/trace"', '"/flight"', '"/healthz"',
                  '"/health"', '"/group/"', '"/timeline"'):
        assert route in http, route


def test_every_http_route_is_documented_in_module_docstring():
    """Every route string obs/http.py serves must appear in its module
    docstring — the docstring is the route inventory operators read, and
    an undocumented route is an unowned surface."""
    import gigapaxos_tpu.obs.http as http_mod

    doc = http_mod.__doc__ or ""
    src = _src("gigapaxos_tpu/obs/http.py")
    handler = src[src.index("def do_GET"):src.index("do_HEAD")]
    routes = set(re.findall(r'"(/[a-z]+/?)"', handler))
    assert routes, "no routes parsed out of do_GET"
    for route in routes:
        assert route.rstrip("/") in doc, (
            f"obs/http.py serves {route} but its module docstring does not "
            f"document it")


def test_readme_documents_the_observability_plane():
    readme = _src("README.md")
    assert "## Observability" in readme
    for name in ("tick_phase_seconds", "commit_latency_seconds",
                 "wal_fsync_seconds", "GPTPU_METRICS"):
        assert name in readme, f"README Observability section missing {name}"

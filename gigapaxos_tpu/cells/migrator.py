"""Cross-cell live migration of a Paxos group, over the worker protocol.

Same epoch machinery as the intra-mesh migrator (placement/migrator.py) —
stop the old epoch, drain the donor checkpoint, birth ``name#(e+1)`` from
the blob via the journaled targeted create (OP_CREATE_AT) — except source
and destination are different OS PROCESSES, so each step is a line-protocol
RPC against the owning cell's worker:

  1. ``migrate_out <name>``            (source: stop + drained blob)
  2. ``migrate_in <name> <e+1> <hex>`` (destination: journaled create-at)
  3. ``migrate_drop <name> <e>``       (source: GC the stopped epoch)
  4. ``broadcast_override``            (router + every edge's directory)

Crash safety is inherited from the journaled steps: a crash after (2)
leaves both cells with journaled state and the drop re-runs on retry; a
crash before (2) leaves the source epoch intact (stopped at worst, where
the name continues in a new epoch on the SOURCE cell via the normal
reconfiguration retry path).  The override broadcast is volatile per
worker but deterministic from the supervisor's router, which re-seeds a
restarted cell through its spec.
"""

from __future__ import annotations

from typing import Optional

from .supervisor import CellSupervisor


class CellMigrator:
    """Drives one-group moves between a supervisor's cells."""

    def __init__(self, sup: CellSupervisor, timeout_s: float = 60.0):
        self.sup = sup
        self.timeout_s = timeout_s
        self.moved = 0
        self.aborted = 0

    def migrate(self, name: str, dst_cell: int) -> bool:
        sup = self.sup
        src_cell = sup.router.cell(name)
        if dst_cell == src_cell:
            return True
        if not (0 <= dst_cell < sup.n_cells):
            raise ValueError(f"cell {dst_cell} out of range")
        src, dst = sup.cells[src_cell], sup.cells[dst_cell]
        t = self.timeout_s
        out = src.rpc(f"migrate_out {name}", "migrat", t)
        if out.startswith("migrate_err"):
            self.aborted += 1
            return False
        _tag, _n, epoch, blob = out.split(" ", 3)
        resp = dst.rpc(f"migrate_in {name} {int(epoch) + 1} {blob}",
                       "migrat", t)
        if resp.startswith("migrate_err"):
            self.aborted += 1
            return False
        src.rpc(f"migrate_drop {name} {epoch}", "migrate_dropped", t)
        sup.broadcast_override(name, dst_cell)
        self.moved += 1
        return True


class CellRebalancer:
    """Tiny demand-driven policy: move the hottest group off the busiest
    cell when its group count exceeds the mean by ``skew_threshold``.
    Group counts (worker ``stats``) stand in for load on a host where every
    cell runs the same workload mix; richer demand wiring rides the
    placement plane."""

    def __init__(self, sup: CellSupervisor, migrator: Optional[CellMigrator]
                 = None, skew_threshold: float = 1.5):
        self.sup = sup
        self.migrator = migrator or CellMigrator(sup)
        self.skew_threshold = skew_threshold

    def run_once(self, candidates) -> int:
        """``candidates``: name -> owner-cell mapping the caller knows
        (e.g. the created names); returns groups moved."""
        counts = {}
        for k, h in self.sup.cells.items():
            if h.alive():
                counts[k] = h.stats().get("groups", 0)
        if len(counts) < 2:
            return 0
        mean = sum(counts.values()) / len(counts)
        busiest = max(counts, key=counts.get)
        coolest = min(counts, key=counts.get)
        if counts[busiest] < max(self.skew_threshold * mean, mean + 1):
            return 0
        for name in candidates:
            if self.sup.router.cell(name) == busiest:
                return int(self.migrator.migrate(name, coolest))
        return 0

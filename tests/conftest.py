"""Test harness setup: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of emulating a whole cluster inside one
process (``TESTReconfigurationMain.startLocalServers``,
reconfiguration/testing/TESTReconfigurationMain.java:86) — here the "machines"
are virtual XLA CPU devices.

Note: the dev image's sitecustomize registers a tunneled TPU backend and
forces ``jax.config.jax_platforms = "axon,cpu"``; env vars alone cannot
override that, so we update jax.config directly (before any jax op runs).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ.get("GPTPU_TEST_PLATFORM", "cpu"))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (multi-process, soak)"
    )
    config.addinivalue_line(
        "markers",
        "multicore: needs real parallel cores (cell scaling asserts); "
        "auto-skipped when os.cpu_count() < 4",
    )


def pytest_collection_modifyitems(config, items):
    import pytest

    if (os.cpu_count() or 1) >= 4:
        return
    skip = pytest.mark.skip(
        reason=f"multicore test needs >=4 cores, have {os.cpu_count()}")
    for item in items:
        if "multicore" in item.keywords:
            item.add_marker(skip)

"""ActiveReplica: the data-plane node's control-plane face.

Analog of ``reconfiguration/ActiveReplica.java:131``: wraps a replica
coordinator and executes the epoch lifecycle ops sent by reconfigurators —

* ``handleStartEpoch`` (:891)  → :meth:`_on_start_epoch` (create the new
  epoch's group, fetching the previous epoch's final state if needed via
  ``WaitEpochFinalState``, reconfigurationprotocoltasks/WaitEpochFinalState.java:47);
* ``handleStopEpoch`` (:1012) → :meth:`_on_stop_epoch` (propose the epoch
  stop through the coordinator, ack when the fence commits);
* ``handleDropEpochFinalState`` (:1063) → :meth:`_on_drop_epoch`;
* ``handleRequestEpochFinalState`` (:1179) → :meth:`_on_request_final_state`;
* ``handleEchoRequest`` (:1126) → :meth:`_on_echo`;

plus the client-facing app-request path (coordinate + respond) and
demand reporting (``DemandReport`` sends to the name's RC group, §3.4).

TPU shape: many ActiveReplica objects (one per active node id) share one
dense-device coordinator in-process — the node ids are replica *slots* of
one mesh program, so "create group on 3 actives" is one row insertion with a
3-bit member mask, and a StartEpoch raced by several ARs is naturally
idempotent.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import overload as _ov
from ..net import binbatch
from ..net.bulk import BulkTransfer
from ..net.messenger import Messenger
from ..net.transport import SendFailure
from ..obs.metrics import registry as _obs_registry
from ..utils.reqtrace import xtracer as _xtracer
from ..protocoltask.executor import ProtocolExecutor, ProtocolTask
from . import packets as pkt
from .consistent_hashing import ConsistentHashRing
from .coordinator import AbstractReplicaCoordinator
from .demand import AbstractDemandProfile, DemandProfile


#: batch-admission rejection codes -> client-visible errors.  "busy" is
#: transient backpressure: retry at the SAME active (re-resolving actives
#: on it would storm the RC plane for nothing); "not_active" means
#: re-resolve; "bad_request" is permanent.
_REJECT = {1: "not_active", 2: "busy", 3: "bad_request"}


class WaitEpochFinalState(ProtocolTask):
    """Fetch a stopped epoch's final state from its previous actives, then
    create the new epoch's group (WaitEpochFinalState.java:47)."""

    period_s = 0.5
    max_restarts = 240  # big states take a while; the RC retries anyway
    #: after a bulk announcement, hold off re-requesting for this long —
    #: every duplicate request triggers a full re-send of the state
    announce_patience_s = 30.0

    #: never conclude "the previous epoch was GC'd" before this much real
    #: time: a denial burst can just be the previous actives' stops still
    #: executing under load, and a premature empty birth costs a repair
    give_up_floor_s = 12.0

    def __init__(self, ar: "ActiveReplica", packet: dict):
        self.ar = ar
        self.p = packet
        self._i = 0
        self._gone = False  # some previous active reported the state GC'd
        self._born = time.monotonic()
        self._announced_at: Optional[float] = None

    @property
    def key(self) -> str:
        return f"WaitEpochFinalState:{self.p['name']}:{self.p['epoch']}"

    def start(self):
        if self._announced_at is not None:
            if time.monotonic() - self._announced_at < self.announce_patience_s:
                return []  # chunks in flight; don't provoke duplicate sends
            self._announced_at = None  # transfer presumably died: re-request
        name, prev = self.p["name"], self.p["prev_epoch"]
        # our own copy may have materialized since the last round (this
        # member's stop executed late): never poll remotely for state we
        # hold locally
        state = self.ar.coord.get_final_state(name, prev)
        if state is not None:
            self.ar.executor.handle_event(
                self.key, {"found": True, "state_bytes": state}
            )
            return []
        targets = [a for a in self.p["prev_actives"] if a != self.ar.node_id]
        if not targets:
            return []
        # round-robin over previous actives until one has the state
        dest = targets[self._i % len(targets)]
        self._i += 1
        return [(dest, pkt.request_epoch_final_state(name, prev, self.ar.node_id))]

    def handle(self, event: dict):
        if not event.get("found"):
            # Liveness hole this guards (round-5 root cause of the
            # migrate/recreate stalls): the complete commits at a MAJORITY
            # of AckStarts, after which WaitAckDropEpoch GCs the previous
            # epoch — a slow member (typically the newcomer, the one that
            # must fetch remotely) could then find NO donor forever and
            # serve not_active for good.  A donor distinguishes "not
            # stopped yet" (transient; keep polling — giving up here could
            # taint EVERY new member and lose the state) from "dropped by
            # GC" (gone=True).  Gone implies the complete committed, which
            # implies a MAJORITY of the new epoch holds the real state —
            # so it is provably safe to birth EMPTY + TAINTED and let the
            # data plane's checkpoint transfer repair this member from a
            # caught-up peer (the tainted row refuses to serve/donate
            # until then).
            self._gone = self._gone or bool(event.get("gone"))
            if (self._gone
                    and time.monotonic() - self._born
                    >= self.give_up_floor_s):
                # one more local check: our own stop may have completed
                # while we were polling remotely
                state = self.ar.coord.get_final_state(
                    self.p["name"], self.p["prev_epoch"]
                )
                if state is not None:
                    self.ar._create_started_epoch(self.p, state)
                else:
                    self.ar._create_started_epoch(self.p, b"", tainted=True)
                return [], True
            return [], False
        if "state_bytes" in event:  # assembled bulk transfer
            state = event["state_bytes"]
        elif event.get("bulk"):
            self._announced_at = time.monotonic()
            return [], False  # announced; the chunks are still in flight
        else:
            state = pkt.b64d(event.get("state")) or b""
        self.ar._create_started_epoch(self.p, state)
        return [], True

    def on_done(self) -> None:
        # max_restarts exhausted without a donor (every previous active
        # denied for the whole budget): last-resort tainted birth so the
        # member regains liveness; checkpoint repair or a later epoch
        # supersedes.  No-op when the fetch completed normally.
        cur = self.ar.coord.current_epoch(self.p["name"])
        if cur is None or cur < self.p["epoch"]:
            self.ar._create_started_epoch(self.p, b"", tainted=True)


class ActiveReplica:
    def __init__(
        self,
        node_id: str,
        messenger: Messenger,
        coordinator: AbstractReplicaCoordinator,
        rc_ids: List[str],
        demand_profile_factory: Callable[[str], AbstractDemandProfile] = DemandProfile,
        rc_group_size: int = 3,
    ):
        self.node_id = node_id
        self.m = messenger
        self.coord = coordinator
        self.rc_ring = ConsistentHashRing(rc_ids)
        self.rc_k = min(rc_group_size, max(1, len(rc_ids)))
        self.profile_factory = demand_profile_factory
        self._profiles: Dict[str, AbstractDemandProfile] = {}
        self._plock = threading.Lock()
        self.executor = ProtocolExecutor(self.m.send, name=f"ar-{node_id}")
        # chunked out-of-band channel for big epoch-final checkpoints
        # (LargeCheckpointer analog, paxosutil/LargeCheckpointer.java:39)
        self.bulk = BulkTransfer(self.m)
        self.bulk.register_prefix("efs:", self._on_bulk_final_state)
        # binary batched-request frames (SoA wire, net/binbatch.py); the
        # deduped GBR2 kind shares the handler — decode sniffs the magic
        binbatch.chain_bytes_handler(self.m.demux, binbatch.REQ_MAGIC,
                                     self._on_binary_batch)
        binbatch.chain_bytes_handler(self.m.demux, binbatch.REQ2_MAGIC,
                                     self._on_binary_batch)
        # response egress coalesced per (client, tick): the manager's
        # callback flush opens a scope, every bid finished inside it stages,
        # and each client's frames leave as one generation-stamped list
        self._egress = binbatch.ClientEgress(self.m)
        mgr = getattr(coordinator, "manager", None)
        if mgr is not None and hasattr(mgr, "_flush_scope_hooks"):
            mgr._flush_scope_hooks.append(self._egress.open_scope)
        # (client, rid) -> None while in flight, response packet once done;
        # absorbs same-rid retransmissions (GCConcurrentHashMap analog)
        self._req_dedup: "collections.OrderedDict[tuple, Optional[dict]]" = (
            collections.OrderedDict()
        )
        self._dedup_cap = 4096
        #: insertion time of in-flight (None) markers: markers whose client
        #: died before the callback ever fires must age out, or a map full
        #: of in-flight entries grows unbounded (advisor round 2)
        self._dedup_born: Dict[tuple, float] = {}
        self._dedup_inflight_ttl_s = 60.0
        self._dedup_lock = threading.Lock()
        #: anycast forwards awaiting an actives answer: qrid -> (reply_to, p)
        self._any_pending: "collections.OrderedDict[int, tuple]" = (
            collections.OrderedDict()
        )
        self._any_lock = threading.Lock()
        self._any_next = 1 << 40  # disjoint from client rids
        #: server-side commit-latency SLO histogram: request arrival at this
        #: replica -> response release (covers propose + tick + WAL + flush)
        self._lat_h = _obs_registry().histogram(
            "commit_latency_seconds",
            help="AR-observed request->response latency", node=node_id)
        #: cross-process tracing hop: records whenever a frame carries a
        #: trace id (presence IS the flag — the client side gates stamping)
        self._xt = _xtracer()
        for ptype, h in [
            (pkt.APP_REQUEST, self._on_app_request),
            (pkt.APP_READ, self._on_app_read),
            (pkt.APP_REQUEST_BATCH, self._on_app_request_batch),
            (pkt.ACTIVES_RESPONSE, self._on_actives_response),
            (pkt.STOP_EPOCH, self._on_stop_epoch),
            (pkt.START_EPOCH, self._on_start_epoch),
            (pkt.DROP_EPOCH, self._on_drop_epoch),
            (pkt.REQUEST_EPOCH_FINAL_STATE, self._on_request_final_state),
            (pkt.EPOCH_FINAL_STATE, self._on_epoch_final_state),
            (pkt.ECHO_REQUEST, self._on_echo),
        ]:
            self.m.register(ptype, h)

    def close(self) -> None:
        self.executor.stop()
        self.m.close()

    # ------------------------------------------------------------ app requests
    def _on_app_request(self, sender: str, p: dict) -> None:
        pkt.register_client(self.m.nodemap, p)
        name, rid = p["name"], p["rid"]
        if _ov.expired(p.get("deadline")):
            # dead on arrival: the client already gave up — never propose,
            # never respond (count-once: this stage detected it)
            _ov.count_expired("ar_ingress", self.node_id)
            return
        # anycast entry (sendRequestAnycast, ReconfigurableAppClientAsync
        # :1357): the client sent to an arbitrary active; if we don't host
        # the name, resolve its actives from the RC plane and forward — the
        # hosting replica answers the client directly via reply_to
        reply_to = p.get("reply_to") or sender
        if (p.get("anycast") and not p.get("fwd")
                and self.coord.current_epoch(name) is None):
            tid = p.get("trace")
            if tid is not None:  # dict forwarded verbatim: the id survives
                self._xt.event(tid, "ar_forward", node=self.node_id, req=rid)
            self._anycast_forward(reply_to, p)
            return
        sender = reply_to
        # retransmission dedup: the client reuses its rid on retry, so a
        # duplicate arriving while the first copy is in flight is dropped
        # (its response will carry the same rid) and one arriving after
        # completion gets the cached response instead of a second proposal
        key = (sender, rid)
        dup, cached = self._dedup_check_insert(key)
        if dup:
            if cached is not None:
                self.m.send(sender, cached, cls=_ov.CLS_CLIENT)
            return
        try:
            self._handle_app_request(sender, p, key)
        except Exception:
            # never strand the in-flight marker: a parse error (e.g. corrupt
            # base64 payload) must not black-hole every retransmission of
            # this rid forever
            with self._dedup_lock:
                self._req_dedup.pop(key, None)
                self._dedup_born.pop(key, None)
            raise

    def _dedup_check_insert(self, key):
        """Dedup-map admission shared by the scalar and batch paths.
        Returns (is_duplicate, cached_response_or_None); on a miss inserts
        the in-flight marker and enforces the cap: evict the oldest
        COMPLETED entry — dropping a live in-flight (None) marker would
        let a retransmission of a slow request start the second proposal
        the map exists to prevent — and when all entries are in-flight,
        age out markers past the max plausible commit latency."""
        now = time.monotonic()
        with self._dedup_lock:
            if key in self._req_dedup:
                return True, self._req_dedup[key]
            self._req_dedup[key] = None
            self._dedup_born[key] = now
            if len(self._req_dedup) > self._dedup_cap:
                victim = None
                for k in self._req_dedup:
                    if self._req_dedup[k] is not None:
                        victim = k
                        break
                if victim is not None:
                    del self._req_dedup[victim]
                else:
                    stale = [
                        k for k, born in self._dedup_born.items()
                        if now - born > self._dedup_inflight_ttl_s
                    ]
                    for k in stale:
                        self._req_dedup.pop(k, None)
                        self._dedup_born.pop(k, None)
        return False, None

    def _dedup_clear(self, key) -> None:
        with self._dedup_lock:
            self._req_dedup.pop(key, None)
            self._dedup_born.pop(key, None)

    def _handle_app_request(self, sender: str, p: dict, key) -> None:
        name, rid = p["name"], p["rid"]
        t0 = time.perf_counter()
        tid = p.get("trace")
        if tid is not None:
            self._xt.event(tid, "ar_recv", node=self.node_id, req=rid,
                           name=name)
        epoch = self.coord.current_epoch(name)
        if epoch is None:
            self._finish_request(sender, key, {
                "type": pkt.APP_RESPONSE, "rid": rid, "ok": False,
                "error": "not_active", "name": name,
            }, cache=False)
            return
        # classed admission: the scalar propose path both fires the callback
        # AND returns None on refusal, so shed HERE (one response, at the
        # edge) rather than mapping the manager's held RID_BUSY callback
        gov = getattr(self.coord, "intake_governor", None)
        if gov is not None and not gov.admit(_ov.CLS_CLIENT):
            _ov.count_shed(_ov.CLS_CLIENT, "ar_ingress", self.node_id)
            self._finish_request(sender, key, {
                "type": pkt.APP_RESPONSE, "rid": rid, "ok": False,
                "error": "busy", "name": name,
            }, cache=False)
            return
        self._register_demand(name, sender, epoch)
        need = p.get("need_response", True)
        dl = p.get("deadline")
        dl = dl if isinstance(dl, int) and dl > 0 else None

        def cb(req_id: int, resp: Optional[bytes]) -> None:
            if req_id == _ov.RID_EXPIRED:
                # deadline passed mid-pipeline (counted by the detecting
                # stage): settle the marker, never respond
                self._dedup_clear(key)
                return
            if not need:
                # fire-and-forget: still resolve the marker (cache success so
                # a retransmit doesn't re-commit; clear on failure)
                ok = req_id >= 0 and resp is not None
                with self._dedup_lock:
                    if ok:
                        self._req_dedup[key] = {"type": pkt.APP_RESPONSE,
                                                "rid": rid, "ok": True,
                                                "name": name}
                    else:
                        self._req_dedup.pop(key, None)
                    self._dedup_born.pop(key, None)
                return
            ok = not (req_id < 0 or resp is None)
            if ok and _ov.expired(dl):
                # committed but nobody is waiting: drop the response
                _ov.count_expired("egress", self.node_id)
                self._dedup_clear(key)
                return
            self._lat_h.observe(time.perf_counter() - t0)
            if tid is not None:
                self._xt.event(tid, "ar_responded", node=self.node_id,
                               req=rid, ok=ok)
            if not ok:
                # busy = transient admission NACK (retry same active);
                # anything else = epoch stopped underneath us (re-resolve)
                err = "busy" if req_id == _ov.RID_BUSY else "stopped"
                self._finish_request(sender, key, {
                    "type": pkt.APP_RESPONSE, "rid": rid, "ok": False,
                    "error": err, "name": name,
                }, cache=False)
            else:
                self._finish_request(sender, key, {
                    "type": pkt.APP_RESPONSE, "rid": rid, "ok": True,
                    "name": name, "response": pkt.b64e(resp),
                }, cache=True)

        r = self.coord.coordinate_request(
            name, epoch, pkt.b64d(p["payload"]) or b"", cb,
            entry=self.node_id, deadline=dl,
        )
        if r is None:
            if need:
                self._finish_request(sender, key, {
                    "type": pkt.APP_RESPONSE, "rid": rid, "ok": False,
                    "error": "not_active", "name": name,
                }, cache=False)
            else:
                with self._dedup_lock:
                    self._req_dedup.pop(key, None)
                    self._dedup_born.pop(key, None)

    def _on_app_read(self, sender: str, p: dict) -> None:
        """Lease-era read entry (ISSUE 17).  Reads are side-effect-free by
        contract, so retransmissions are harmless — no dedup-map traffic:
        a retried rid simply reads again.  Responses reuse APP_RESPONSE
        (same client callback path) but travel CLS_READ, so a read flood
        backpressures reads without touching writes or control."""
        pkt.register_client(self.m.nodemap, p)
        name, rid = p["name"], p["rid"]
        if _ov.expired(p.get("deadline")):
            _ov.count_expired("ar_ingress", self.node_id)
            return
        reply_to = p.get("reply_to") or sender

        def refuse(err: str) -> None:
            self.m.send(reply_to, {
                "type": pkt.APP_RESPONSE, "rid": rid, "ok": False,
                "error": err, "name": name}, cls=_ov.CLS_READ)

        epoch = self.coord.current_epoch(name)
        if epoch is None:
            refuse("not_active")
            return
        gov = getattr(self.coord, "intake_governor", None)
        if gov is not None and not gov.admit(_ov.CLS_READ):
            _ov.count_shed(_ov.CLS_READ, "ar_ingress", self.node_id)
            refuse("busy")
            return
        dl = p.get("deadline")
        dl = dl if isinstance(dl, int) and dl > 0 else None

        def cb(req_id: int, resp: Optional[bytes]) -> None:
            if req_id == _ov.RID_EXPIRED:
                return  # counted by the detecting stage; never respond
            if req_id < 0 or resp is None:
                refuse("busy" if req_id == _ov.RID_BUSY else "stopped")
                return
            if _ov.expired(dl):
                _ov.count_expired("egress", self.node_id)
                return
            self.m.send(reply_to, {
                "type": pkt.APP_RESPONSE, "rid": rid, "ok": True,
                "name": name, "response": pkt.b64e(resp),
                "local": req_id == 0}, cls=_ov.CLS_READ)

        payload = pkt.b64d(p["payload"]) or b""
        read = getattr(self.coord, "coordinate_read", None)
        if read is not None:
            r = read(name, epoch, payload, cb, deadline=dl)
        else:
            # coordinator without a lease plane (chain/Mode-B shims):
            # plain consensus read through the ordered stream
            r = self.coord.coordinate_request(
                name, epoch, payload, cb, entry=self.node_id, deadline=dl)
        if r is None:
            refuse("not_active")

    def _on_app_request_batch(self, sender: str, p: dict) -> None:
        """Coalesced client edge: one frame of requests in, one frame of
        responses out (RequestPacket.java:189-233 ``batched[]``).  Dedup is
        batch-granular — the batch id, not each rid, keys the
        retransmission cache, so absorbing a retransmitted batch costs one
        map lookup instead of len(batch)."""
        pkt.register_client(self.m.nodemap, p)
        reply_to = p.get("reply_to") or sender
        bid = p["bid"]
        dl = p.get("deadline")
        if _ov.expired(dl):
            # whole frame dead on arrival: the client gave up already
            _ov.count_expired("ar_ingress", self.node_id,
                              n=len(p.get("reqs") or ()))
            return
        key = (reply_to, ("b", bid))
        dup, cached = self._dedup_check_insert(key)
        if dup:
            if cached is not None:
                self.m.send(reply_to, cached, cls=_ov.CLS_CLIENT)
            return
        reqs = p["reqs"]
        dl = dl if isinstance(dl, int) and dl > 0 else None
        if not reqs:
            self._dedup_clear(key)
            self.m.send(reply_to, {"type": pkt.APP_RESPONSE_BATCH,
                                   "bid": bid, "results": []},
                        cls=_ov.CLS_CLIENT)
            return
        results: list = [None] * len(reqs)
        lock = threading.Lock()
        remaining = [len(reqs)]
        settled = [False] * len(reqs)

        def finish() -> None:
            resp = {"type": pkt.APP_RESPONSE_BATCH, "bid": bid,
                    "results": results}
            # like the scalar path, only all-success frames are cached for
            # retransmission replay; a frame with transient failures clears
            # the marker so a retry can re-coordinate
            with self._dedup_lock:
                if all(r[1] for r in results):
                    self._req_dedup[key] = resp
                else:
                    self._req_dedup.pop(key, None)
                self._dedup_born.pop(key, None)
            try:
                self.m.send(reply_to, resp, cls=_ov.CLS_CLIENT)
            except SendFailure:
                pass  # client/transport gone: completions delivered on the
                # tick thread must never kill the driver

        def settle(i: int, rid, entry) -> None:
            with lock:
                # idempotent per index: a manager that both fires the
                # failure callback AND returns a rejection (WAL shed,
                # admission shed) must not double-decrement the remainder
                if settled[i]:
                    return
                settled[i] = True
                results[i] = entry
                remaining[0] -= 1
                done = remaining[0] == 0
            if done:
                finish()

        # demand accounting once per (name, batch), not per request
        name_counts: Dict[str, int] = {}
        for name, _rid, _pl in reqs:
            name_counts[name] = name_counts.get(name, 0) + 1
        for name, cnt in name_counts.items():
            epoch = self.coord.current_epoch(name)
            if epoch is not None:
                self._register_demand_batch(name, reply_to, epoch, cnt)
        def make_cb(i: int, rid):
            def cb(req_id: int, resp) -> None:
                if req_id < 0 or resp is None:
                    err = ("busy" if req_id == _ov.RID_BUSY else
                           "expired" if req_id == _ov.RID_EXPIRED else
                           "stopped")
                    settle(i, rid, [rid, False, err])
                else:
                    settle(i, rid, [rid, True, pkt.b64e(resp)])

            return cb

        try:
            crb = getattr(self.coord, "coordinate_requests_batch", None)
            if crb is not None:
                # columnar admission: the whole frame enters the manager's
                # bulk path in one operation
                items, live_idx = [], []
                for i, (name, rid, payload_b64) in enumerate(reqs):
                    epoch = self.coord.current_epoch(name)
                    if epoch is None:
                        settle(i, rid, [rid, False, "not_active"])
                        continue
                    items.append((name, epoch, pkt.b64d(payload_b64) or b"",
                                  make_cb(i, rid)))
                    live_idx.append(i)
                if items:
                    rids2 = crb(items, entry=self.node_id)
                    for i, r2 in zip(live_idx, rids2):
                        if r2 < 0:
                            rid = reqs[i][1]
                            settle(i, rid, [rid, False, _REJECT[min(-r2, 3)]])
                return
            for i, (name, rid, payload_b64) in enumerate(reqs):
                epoch = self.coord.current_epoch(name)
                if epoch is None:
                    settle(i, rid, [rid, False, "not_active"])
                    continue
                r = self.coord.coordinate_request(
                    name, epoch, pkt.b64d(payload_b64) or b"",
                    make_cb(i, rid), entry=self.node_id, deadline=dl,
                )
                if r is None:
                    settle(i, rid, [rid, False, "not_active"])
        except Exception:
            # never strand the in-flight marker: a parse/admission error
            # must not black-hole every retransmission of this bid
            self._dedup_clear(key)
            raise

    def _on_binary_batch(self, sender: str, buf: bytes) -> None:
        """Binary twin of :meth:`_on_app_request_batch`: columnar decode,
        one bulk admission, columnar response frame."""
        (bid, dl, addr, client_id, names, name_idx, rids,
         payloads) = binbatch.decode_request(buf)
        if _ov.expired(dl):
            # whole frame dead on arrival (one deadline per frame: a client
            # tick's batch shares a send instant)
            _ov.count_expired("ar_ingress", self.node_id, n=len(rids))
            return
        if self.m.nodemap(client_id) is None:
            self.m.nodemap.add(client_id, addr[0], int(addr[1]))
        key = (client_id, ("bb", bid))
        dup, cached = self._dedup_check_insert(key)
        if dup:
            if cached is not None:
                self.m.send_bytes(client_id, cached, cls=_ov.CLS_CLIENT)
            return
        n = len(rids)
        if n == 0:
            self._dedup_clear(key)
            self.m.send_bytes(client_id,
                              binbatch.encode_response(bid, [], [], []),
                              cls=_ov.CLS_CLIENT)
            return
        statuses = np.zeros(n, np.uint8)
        bodies: list = [b""] * n
        lock = threading.Lock()
        remaining = [n]
        settled = np.zeros(n, bool)

        def finish() -> None:
            frame = binbatch.encode_response(bid, rids, statuses, bodies)
            # cache only all-success frames (see _on_app_request_batch)
            with self._dedup_lock:
                if statuses.all():
                    self._req_dedup[key] = frame
                else:
                    self._req_dedup.pop(key, None)
                self._dedup_born.pop(key, None)
            # in-scope (tick-thread callback flush): staged and sent as one
            # per-client frame list; off-scope: immediate.  Either way a
            # closing transport must never kill the driver — the response
            # is simply undeliverable (ClientEgress swallows SendFailure)
            self._egress.emit(client_id, frame)

        def settle(i: int, ok: bool, body: bytes) -> None:
            with lock:
                # idempotent per index (see _on_app_request_batch.settle)
                if settled[i]:
                    return
                settled[i] = True
                statuses[i] = 1 if ok else 0
                bodies[i] = body
                remaining[0] -= 1
                done = remaining[0] == 0
            if done:
                finish()

        epochs = [self.coord.current_epoch(nm) for nm in names]
        counts = np.bincount(name_idx, minlength=len(names))
        for j, nm in enumerate(names):
            if epochs[j] is not None and counts[j]:
                self._register_demand_batch(nm, client_id, epochs[j],
                                            int(counts[j]))

        def make_cb(i: int):
            def cb(req_id: int, resp) -> None:
                if req_id < 0 or resp is None:
                    err = (b"busy" if req_id == _ov.RID_BUSY else
                           b"expired" if req_id == _ov.RID_EXPIRED else
                           b"stopped")
                    settle(i, False, err)
                else:
                    settle(i, True, resp)

            return cb

        try:
            crb = getattr(self.coord, "coordinate_requests_batch", None)
            use_sink = (crb is not None
                        and getattr(self.coord, "supports_batch_sink", False))
            items, live_idx = [], []
            for i in range(n):
                ep = epochs[name_idx[i]]
                if ep is None:
                    settle(i, False, b"not_active")
                    continue
                if use_sink:
                    items.append((names[name_idx[i]], ep, payloads[i], None))
                    live_idx.append(i)
                elif crb is not None:
                    items.append((names[name_idx[i]], ep, payloads[i],
                                  make_cb(i)))
                    live_idx.append(i)
                else:
                    r = self.coord.coordinate_request(
                        names[name_idx[i]], ep, payloads[i], make_cb(i),
                        entry=self.node_id,
                        deadline=int(dl) if dl else None,
                    )
                    if r is None:
                        settle(i, False, b"not_active")
            if items and use_sink:
                # columnar completion: the manager delivers (offsets,
                # responses) per tick for the admitted block — zero
                # per-request callback objects on this edge.  Early fires
                # (completion racing this thread's index build) buffer.
                admitted: list = []
                early: list = []
                built = [False]

                def deliver(offs, resps) -> None:
                    fin = False
                    with lock:
                        for k2, off in enumerate(offs):
                            i2 = admitted[off]
                            r2 = None if resps is None else resps[k2]
                            if r2 is None:
                                # same semantics as the per-rid callback
                                # path: a None response is a retryable
                                # failure, never an empty success
                                bodies[i2] = b"stopped"
                            else:
                                statuses[i2] = 1
                                bodies[i2] = r2
                        remaining[0] -= len(offs)
                        fin = remaining[0] == 0
                    if fin:
                        finish()

                def sink(offs, resps) -> None:
                    with lock:
                        if not built[0]:
                            early.append((offs, resps))
                            return
                    deliver(offs, resps)

                out = crb(items, entry=self.node_id, batch_sink=sink)
                for j, r2 in enumerate(out):
                    i = live_idx[j]
                    if r2 < 0:
                        settle(i, False, _REJECT[min(-r2, 3)].encode())
                    else:
                        admitted.append(i)
                with lock:
                    built[0] = True
                    drain, early[:] = early[:], []
                for offs, resps in drain:
                    deliver(offs, resps)
            elif items:
                out = crb(items, entry=self.node_id)
                for i, r2 in zip(live_idx, out):
                    if r2 < 0:
                        settle(i, False, _REJECT[min(-r2, 3)].encode())
        except Exception:
            self._dedup_clear(key)
            raise

    def _anycast_forward(self, reply_to: str, p: dict) -> None:
        """Resolve the name's actives from its RC group, then re-send the
        request to a hosting replica with explicit client reply routing."""
        name = p["name"]
        with self._any_lock:
            qrid = self._any_next
            self._any_next += 1
            self._any_pending[qrid] = (reply_to, dict(p))
            while len(self._any_pending) > 1024:
                self._any_pending.popitem(last=False)
        rcs = self.rc_ring.replicated_servers(name, self.rc_k)
        # random member: a dead fixed target must not blackhole every retry
        import random as _random

        self.m.send(_random.choice(rcs), pkt.request_active_replicas(name, qrid))

    def _on_actives_response(self, sender: str, p: dict) -> None:
        with self._any_lock:
            ent = self._any_pending.pop(p.get("rid"), None)
        if ent is None:
            return
        reply_to, req = ent
        if not p.get("ok") or not p.get("actives"):
            self.m.send(reply_to, {
                "type": pkt.APP_RESPONSE, "rid": req["rid"], "ok": False,
                "error": "not_active", "name": req["name"],
            }, cls=_ov.CLS_CLIENT)
            return
        for a, addr in (p.get("addrs") or {}).items():
            if self.m.nodemap(a) is None:
                self.m.nodemap.add(a, addr[0], int(addr[1]))
        import random as _random

        # random hosting replica: client retries then spread across the
        # group instead of deterministically re-hitting a dead first member
        target = _random.choice(p["actives"])
        req["reply_to"] = reply_to
        req["fwd"] = 1
        self.m.send(target, req, cls=_ov.CLS_CLIENT)

    def _finish_request(self, sender: str, key, packet: dict,
                        cache: bool) -> None:
        """Answer an app request.  Successful responses are cached for
        retransmission replay; errors clear the pending marker so a retry
        after e.g. an epoch change gets a fresh attempt."""
        with self._dedup_lock:
            if cache:
                self._req_dedup[key] = packet
            else:
                self._req_dedup.pop(key, None)
            self._dedup_born.pop(key, None)
        self.m.send(sender, packet, cls=_ov.CLS_CLIENT)

    def _register_demand(self, name: str, sender: str, epoch: int) -> None:
        self._register_demand_batch(name, sender, epoch, 1)

    def _register_demand_batch(self, name: str, sender: str, epoch: int,
                               n: int) -> None:
        with self._plock:
            prof = self._profiles.get(name)
            if prof is None:
                prof = self._profiles[name] = self.profile_factory(name)
            if n == 1:
                prof.register_request(sender)
            else:
                prof.register_requests(sender, n)
            stats = prof.get_stats() if prof.should_report() else None
        if stats is not None:
            # ship to the name's RC group (handleDemandReport aggregates and
            # decides; sending to all k members tolerates RC failures)
            for rc in self.rc_ring.replicated_servers(name, self.rc_k):
                self.m.send(rc, pkt.demand_report(name, epoch, stats, self.node_id))

    # ---------------------------------------------------------- epoch lifecycle
    def _on_stop_epoch(self, sender: str, p: dict) -> None:
        name, epoch, initiator = p["name"], p["epoch"], p["initiator"]
        ack = {"type": pkt.ACK_STOP_EPOCH, "name": name, "epoch": epoch}
        cur = self.coord.current_epoch(name)
        if cur is None or cur > epoch:
            # unknown or already moved on — the stop is moot (idempotent ack)
            self.m.send(initiator, ack)
            return

        def done(ok: bool) -> None:
            self.m.send(initiator, ack)

        started = self.coord.stop_replica_group(name, epoch, done)
        if not started:
            self.m.send(initiator, ack)

    def _on_start_epoch(self, sender: str, p: dict) -> None:
        name, epoch = p["name"], p["epoch"]
        cur = self.coord.current_epoch(name)
        if cur is not None and cur >= epoch:
            self._ack_start(p)  # duplicate/raced StartEpoch
            return
        if p["prev_epoch"] < 0:
            # creation: seed with the client-provided initial state
            self._create_started_epoch(p, pkt.b64d(p["initial_state"]) or b"")
            return
        # migration: the previous epoch's final state may be local (shared
        # dense coordinator) or remote (fetch task)
        state = self.coord.get_final_state(name, p["prev_epoch"])
        if state is not None:
            self._create_started_epoch(p, state)
        elif [a for a in p["prev_actives"] if a != self.node_id]:
            self.executor.schedule(WaitEpochFinalState(self, p))
        else:
            # no previous active to ask and no local copy: born tainted,
            # repaired by checkpoint transfer from the new epoch's peers
            self._create_started_epoch(p, b"", tainted=True)

    def _create_started_epoch(self, p: dict, state: bytes,
                              tainted: bool = False) -> None:
        self.coord.create_replica_group(p["name"], p["epoch"], state,
                                        p["actives"], tainted=tainted)
        self._ack_start(p)

    def _ack_start(self, p: dict) -> None:
        self.m.send(p["initiator"], {
            "type": pkt.ACK_START_EPOCH, "name": p["name"], "epoch": p["epoch"],
        })

    def _on_drop_epoch(self, sender: str, p: dict) -> None:
        name, epoch = p["name"], p["epoch"]
        self.coord.drop_final_state(name, epoch)
        # drop the demand profile too: if the name migrated away or died,
        # the profile must not linger (it is recreated on the next request)
        with self._plock:
            self._profiles.pop(name, None)
        self.m.send(p["initiator"], {
            "type": pkt.ACK_DROP_EPOCH, "name": name, "epoch": epoch,
        })

    #: checkpoints above this ride the chunked bulk channel instead of one
    #: base64 JSON frame (LargeCheckpointer threshold idea)
    inline_state_limit = 256 * 1024

    def _on_request_final_state(self, sender: str, p: dict) -> None:
        name, epoch = p["name"], p["epoch"]
        state = self.coord.get_final_state(name, epoch)
        if state is None:
            # distinguish "not stopped yet" (asker keeps polling) from
            # "dropped by GC" (asker may give up and birth tainted — a
            # gone answer implies the complete committed, so a majority of
            # the new epoch holds the real state)
            fsg = getattr(self.coord, "final_state_gone", None)
            reply = pkt.epoch_final_state(name, epoch, None)
            if fsg is not None and fsg(name, epoch):
                reply["gone"] = True
            self.m.send(p["requester"], reply)
            return
        if len(state) > self.inline_state_limit:
            self.m.send(p["requester"], {
                "type": pkt.EPOCH_FINAL_STATE, "name": name, "epoch": epoch,
                "found": True, "bulk": True,
            })
            # epoch leads in the key: names may themselves contain ':'.
            # Worker thread: this handler runs on a transport reader thread,
            # and a paced multi-GB send must not stall inbound processing.
            threading.Thread(
                target=self.bulk.send,
                args=(p["requester"], f"efs:{epoch}:{name}", state),
                name=f"efs-send-{name}", daemon=True,
            ).start()
            return
        self.m.send(p["requester"], pkt.epoch_final_state(name, epoch, state))

    def _on_bulk_final_state(self, sender: str, key: str, data: bytes) -> None:
        epoch_s, name = key[len("efs:"):].split(":", 1)
        self.executor.handle_event(
            f"WaitEpochFinalState:{name}:{int(epoch_s) + 1}",
            {"found": True, "state_bytes": data},
        )

    def _on_epoch_final_state(self, sender: str, p: dict) -> None:
        self.executor.handle_event(
            f"WaitEpochFinalState:{p['name']}:{p['epoch'] + 1}", p
        )

    # ------------------------------------------------------------------- echo
    def _on_echo(self, sender: str, p: dict) -> None:
        pkt.register_client(self.m.nodemap, p)
        self.m.send(sender, {
            "type": pkt.ECHO_REPLY, "ts": p.get("ts", time.time()),
            "rid": p.get("rid"), "node": self.node_id,
        })

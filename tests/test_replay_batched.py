"""Batched (columnar) WAL replay vs. the record-at-a-time reference arm
(ISSUE 19): bit-identity of recovered state, apps, host bookkeeping and
re-logged journal bytes across all dispatch modes, the mixed register
plane and the lease plane; torn-tail/scribble verdict parity of the
bounded-memory (meta_only) scanner; overflow fallback correctness."""

import shutil

import numpy as np
import pytest

from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.paxos.manager import PaxosManager  # noqa: F401 (mk)
from gigapaxos_tpu.paxos.state import PaxosState
from gigapaxos_tpu.wal import logger as wal_logger
from gigapaxos_tpu.wal.journal import (PyJournal, iter_scan_records,
                                       scan_journal)
from gigapaxos_tpu.wal.logger import PaxosLogger, recover

R = 3

MODES = {
    "full_eager": dict(compact=False, pipe=False),
    "full_pipe": dict(compact=False, pipe=True),
    "compact_eager": dict(compact=True, pipe=False),
    "compact_pipe": dict(compact=True, pipe=True),
}


def mk(path, compact=False, pipe=False, register=0, leases=False,
       exec_budget=0):
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 32
    cfg.paxos.compact_outbox = compact
    cfg.paxos.pipeline_ticks = pipe
    cfg.paxos.register_groups = register
    if exec_budget:
        cfg.paxos.exec_budget = exec_budget
    if leases:
        cfg.paxos.read_leases = True
        cfg.paxos.lease_ticks = 16
    apps = [KVApp() for _ in range(R)]
    wal = PaxosLogger(str(path), native=False)
    return cfg, apps, PaxosManager(cfg, R, apps, wal=wal)


def drive(m, mixed=False, rounds=6, per_round=3):
    """A workload that exercises every record kind the replay arms see:
    creates, multi-tick proposal traffic (several batch windows), a
    pause/unpause admin barrier mid-journal, and a stop."""
    for g in range(4):
        m.create_paxos_instance(f"kv{g}", [0, 1, 2])
    if mixed:
        m.create_paxos_instance("reg0", [0, 1, 2], register=True)
        m.create_paxos_instance("reg1", [0, 1, 2], register=True)
    for i in range(rounds):
        for g in range(4):
            for j in range(per_round):
                m.propose(f"kv{g}", f"PUT k{i}.{j} v{g}.{i}.{j}".encode())
        if mixed:
            m.propose("reg0", f"PUT rk v{i}".encode())
            m.propose("reg1", f"PUT rk2 w{i}".encode())
        m.run_ticks(2)
    # admin barrier mid-journal: pause a quiescent group, then the next
    # propose transparently unpauses it (OP_PAUSE + OP_UNPAUSE records
    # splitting the OP_TICK stream)
    m.run_ticks(2)
    m._sweep_outstanding()
    m._do_pause(["kv2"])
    m.wal.log_pause(["kv2"])
    m.propose("kv2", b"PUT back alive")
    m.run_ticks(2)
    m.propose_stop("kv3")
    m.run_ticks(3)


def assert_identical(ma, mb):
    for f in PaxosState._fields:
        assert np.array_equal(np.asarray(getattr(ma.state, f)),
                              np.asarray(getattr(mb.state, f))), \
            f"log-plane state field {f} differs"
    if ma.rstate is not None:
        for f in PaxosState._fields:
            assert np.array_equal(np.asarray(getattr(ma.rstate, f)),
                                  np.asarray(getattr(mb.rstate, f))), \
                f"register-plane state field {f} differs"
    if ma._lease is not None:
        from gigapaxos_tpu.ops.tick import LeaseState

        for f in LeaseState._fields:
            assert np.array_equal(np.asarray(getattr(ma._lease, f)),
                                  np.asarray(getattr(mb._lease, f))), \
                f"lease field {f} differs"
            if ma._rlease is not None:
                assert np.array_equal(np.asarray(getattr(ma._rlease, f)),
                                      np.asarray(getattr(mb._rlease, f))), \
                    f"register lease field {f} differs"
        assert np.array_equal(ma._lease_np, mb._lease_np)
        assert ma._lease_clock == mb._lease_clock
    assert ma.tick_num == mb.tick_num
    assert ma._next_rid == mb._next_rid
    assert np.array_equal(ma._host_exec, mb._host_exec)
    for r in range(R):
        assert ma.apps[r].db == mb.apps[r].db, f"replica {r} app diverged"
    assert dict(ma.rows.items()) == dict(mb.rows.items())
    assert ma._stopped_rows == mb._stopped_rows
    assert set(ma.outstanding) == set(mb.outstanding)
    qa = {k: list(v) for k, v in ma._queues.items() if v}
    qb = {k: list(v) for k, v in mb._queues.items() if v}
    assert qa == qb


def journal_bytes(path):
    import glob
    import os

    out = []
    for p in sorted(glob.glob(os.path.join(str(path), "journal.*.log"))):
        with open(p, "rb") as f:
            out.append(f.read())
    return out


def recover_both(tmp_path, cfg, crash_dir, **kw):
    """Recover the crashed dir through both arms (batched arm on a copy)
    and return the two managers."""
    b = tmp_path / "copy"
    shutil.copytree(crash_dir, b)
    m_ref = recover(cfg, R, [KVApp() for _ in range(R)], str(crash_dir),
                    native=False, replay_mode="reference", **kw)
    m_bat = recover(cfg, R, [KVApp() for _ in range(R)], str(b),
                    native=False, replay_mode="batched", **kw)
    return m_ref, m_bat, b


def post_traffic(m):
    for i in range(3):
        m.propose("kv0", f"PUT post{i} p{i}".encode())
        m.propose("kv1", f"PUT post{i} q{i}".encode())
    m.run_ticks(3)


@pytest.mark.parametrize("mode", [
    m if m in ("compact_eager", "full_pipe")
    else pytest.param(m, marks=pytest.mark.slow)
    for m in sorted(MODES)
])
def test_batched_replay_bit_identity(tmp_path, mode):
    a = tmp_path / "a"
    a.mkdir()
    cfg, apps, m = mk(a, **MODES[mode])
    drive(m)
    m.wal.close()  # crash

    m_ref, m_bat, b = recover_both(tmp_path, cfg, a)
    assert_identical(m_ref, m_bat)
    # identical post-recovery traffic must re-log identical journal bytes
    post_traffic(m_ref)
    post_traffic(m_bat)
    assert_identical(m_ref, m_bat)
    m_ref.wal.close()
    m_bat.wal.close()
    assert journal_bytes(a) == journal_bytes(b)


def test_batched_replay_mixed_register_plane(tmp_path):
    a = tmp_path / "a"
    a.mkdir()
    cfg, apps, m = mk(a, compact=True, register=8)
    drive(m, mixed=True)
    m.wal.close()

    m_ref, m_bat, b = recover_both(tmp_path, cfg, a)
    assert_identical(m_ref, m_bat)
    for mm in (m_ref, m_bat):
        mm.propose("reg0", b"PUT rk post")
        post_traffic(mm)
    assert_identical(m_ref, m_bat)
    m_ref.wal.close()
    m_bat.wal.close()
    assert journal_bytes(a) == journal_bytes(b)


@pytest.mark.parametrize("register", [
    0, pytest.param(8, marks=pytest.mark.slow)
])
def test_batched_replay_lease_plane(tmp_path, register):
    a = tmp_path / "a"
    a.mkdir()
    cfg, apps, m = mk(a, compact=True, register=register, leases=True)
    drive(m, mixed=bool(register), rounds=4)
    m.wal.close()

    m_ref, m_bat, b = recover_both(tmp_path, cfg, a)
    assert_identical(m_ref, m_bat)
    post_traffic(m_ref)
    post_traffic(m_bat)
    assert_identical(m_ref, m_bat)
    m_ref.wal.close()
    m_bat.wal.close()
    assert journal_bytes(a) == journal_bytes(b)


def test_batched_overflow_falls_back_to_reference(tmp_path, monkeypatch):
    """A tick whose true execution count exceeds the replay scatter
    budget must be detected from the compact header and re-run through
    the exact record-at-a-time body — bit-identity holds even when every
    window overflows."""
    monkeypatch.setattr(wal_logger, "_REPLAY_SCAT_MIN", 1)
    calls = []
    orig = wal_logger._BatchedReplay._reference_tick
    monkeypatch.setattr(
        wal_logger._BatchedReplay, "_reference_tick",
        lambda self, slab, t: (calls.append(t), orig(self, slab, t))[1])

    a = tmp_path / "a"
    a.mkdir()
    # full mode with a tiny exec budget: state evolution is unbudgeted
    # (budget=0 on the tick), but the replay scatter budget inherits the
    # tiny _exec_budget, so windows overflow
    cfg, apps, m = mk(a, compact=False, exec_budget=4)
    drive(m, rounds=4, per_round=4)  # 16 execs/tick >> budget 4
    m.wal.close()

    m_ref, m_bat, b = recover_both(tmp_path, cfg, a)
    assert calls, "expected overflow fallback through _reference_tick"
    assert_identical(m_ref, m_bat)
    m_ref.wal.close()
    m_bat.wal.close()


@pytest.mark.parametrize("register", [0, 8])
def test_sparse_window_replay_bit_identity(tmp_path, monkeypatch,
                                           register):
    """Sparse window replay (gather journal-touched rows → scan at width
    A → scatter back) must be bit-identical to the reference arm.  Forced
    on via GPTPU_REPLAY_SPARSE so the small test plane takes the sparse
    path it would normally skip; the dispatcher counter proves it
    engaged."""
    monkeypatch.setenv("GPTPU_REPLAY_SPARSE", "1")
    a = tmp_path / "a"
    a.mkdir()
    cfg, apps, m = mk(a, compact=True, register=register)
    drive(m, mixed=bool(register))
    m.wal.close()

    m_ref, m_bat, b = recover_both(tmp_path, cfg, a)
    assert m_bat._replay_sparse_windows > 0, "sparse path never engaged"
    assert m_bat._replay_overflows == 0
    assert_identical(m_ref, m_bat)
    post_traffic(m_ref)
    post_traffic(m_bat)
    assert_identical(m_ref, m_bat)
    m_ref.wal.close()
    m_bat.wal.close()
    assert journal_bytes(a) == journal_bytes(b)


def test_sparse_auto_threshold(tmp_path):
    """In auto mode a dense little plane (active rows a large fraction of
    G) must NOT take the sparse path — the crossover heuristic keeps it
    on the dense scan."""
    a = tmp_path / "a"
    a.mkdir()
    cfg, apps, m = mk(a, compact=True)  # G=32, 4 active rows → 8 padded
    drive(m)
    m.wal.close()
    b = tmp_path / "copy"
    shutil.copytree(a, b)
    m_bat = recover(cfg, R, [KVApp() for _ in range(R)], str(b),
                    native=False, replay_mode="batched")
    # 8 padded rows * factor 4 == G: the heuristic rejects sparse here
    assert m_bat._replay_sparse_windows == 0
    assert m_bat._replay_windows > 0
    m_bat.wal.close()


@pytest.mark.slow
def test_batched_window_tail_sizes(tmp_path):
    """Batch sizes that do not divide the tick count exercise the <K tail
    path; K larger than the journal exercises pure-tail replay."""
    a = tmp_path / "a"
    a.mkdir()
    cfg, apps, m = mk(a, compact=True)
    drive(m, rounds=5)
    m.wal.close()

    for K in (3, 1000):
        b = tmp_path / f"copy{K}"
        shutil.copytree(a, b)
        apps_b = [KVApp() for _ in range(R)]
        import os

        os.environ["GPTPU_REPLAY_BATCH"] = str(K)
        try:
            m_bat = recover(cfg, R, apps_b, str(b), native=False,
                            replay_mode="batched")
        finally:
            del os.environ["GPTPU_REPLAY_BATCH"]
        m_ref = recover(cfg, R, [KVApp() for _ in range(R)], str(a),
                        native=False, replay_mode="reference")
        assert_identical(m_ref, m_bat)
        m_ref.wal.close()
        m_bat.wal.close()


# ---------------------------------------------------------------- scanner


def _mk_journal(path, n=8, sync_every=3):
    j = PyJournal(str(path))
    for i in range(n):
        j.append(f"record-{i:04d}".encode() * 4)
        if (i + 1) % sync_every == 0:
            j.sync()
    j.close()


def _assert_scan_parity(path):
    full = scan_journal(str(path))
    meta = scan_journal(str(path), meta_only=True)
    assert meta.kind == full.kind
    assert meta.version == full.version
    assert meta.good_len == full.good_len
    assert meta.bad_offset == full.bad_offset
    assert meta.resync_offset == full.resync_offset
    assert meta.last_seq == full.last_seq
    assert meta.n_synced == full.n_synced
    assert meta.n_records == full.n_records == len(full.records)
    assert meta.n_suffix == full.n_suffix == len(full.suffix)
    assert meta.records == [] and meta.suffix == []
    assert list(iter_scan_records(str(path), meta)) == full.records
    return full


def test_meta_scan_clean_parity(tmp_path):
    p = tmp_path / "j.log"
    _mk_journal(p)
    full = _assert_scan_parity(p)
    assert full.kind == "clean" and full.n_records == 8


def test_meta_scan_torn_tail_parity(tmp_path):
    p = tmp_path / "j.log"
    _mk_journal(p)
    with open(p, "ab") as f:
        f.write(b"\x40\x00\x00\x00\xde\xad\xbe")  # half a frame
    full = _assert_scan_parity(p)
    assert full.kind == "torn_tail" and full.n_records == 8


def test_meta_scan_scribble_parity(tmp_path):
    p = tmp_path / "j.log"
    _mk_journal(p, n=10, sync_every=2)
    # flip a byte inside an early (fsynced, barrier-covered) frame
    with open(p, "r+b") as f:
        f.seek(30)
        c = f.read(1)
        f.seek(30)
        f.write(bytes([c[0] ^ 0xFF]))
    full = _assert_scan_parity(p)
    assert full.kind == "scribble"
    assert full.n_suffix > 0  # intact frames resynced after the damage

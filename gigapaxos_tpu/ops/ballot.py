"""Ballot comparison kernels.

A ballot is the totally ordered pair (ballotNumber, coordinatorID)
(``gigapaxos/paxosutil/Ballot.java:34-73``).  The reference stores the two
ints separately in the acceptor to save object overhead
(``PaxosAcceptor.java:95-97``); we do the same with two ``int32`` arrays and
compare lexicographically with branch-free arithmetic, which XLA fuses into
the surrounding elementwise graph.  Slot comparison is two's-complement
subtraction, wraparound-aware like the reference's ``a - b > 0`` idiom.
"""

from __future__ import annotations

import jax.numpy as jnp


def bal_gt(an, ac, bn, bc):
    """(an, ac) > (bn, bc) lexicographically; any broadcastable int32 arrays."""
    return (an > bn) | ((an == bn) & (ac > bc))


def bal_ge(an, ac, bn, bc):
    return (an > bn) | ((an == bn) & (ac >= bc))


def bal_eq(an, ac, bn, bc):
    return (an == bn) & (ac == bc)


def bal_max(an, ac, bn, bc):
    """Elementwise lexicographic max of two ballots -> (num, coord)."""
    take_a = bal_ge(an, ac, bn, bc)
    return jnp.where(take_a, an, bn), jnp.where(take_a, ac, bc)


def bal_consecutive(an, bn):
    """True where ballot number ``an`` is the immediate successor of ``bn``.

    The consecutive-ballots optimization (arxiv 2006.01885) keys on ballot
    *numbers* only: a coordinator taking over at bn+1 whose own promised
    ballot already equals the group maximum has seen every accept the
    predecessor could have pushed, so the prepare round's snapshot would be
    redundant.  Coordinator ids break ties elsewhere (bal_gt/bal_ge); the
    consecutive test is purely numeric.
    """
    return an == bn + 1


def slot_after(a, b):
    """True where slot a is logically after slot b (wraparound-aware)."""
    return (a - b).astype(jnp.int32) > 0


def slot_at_or_after(a, b):
    return (a - b).astype(jnp.int32) >= 0

"""Lease plane tests (ISSUE 17): linearizable local reads.

Mode A: grant/renew/expiry are ``[G]`` columns folded inside the fused
tick; the holder serves reads locally iff its lease mirror validates and
the group is quiescent (executed frontier == accepted frontier); a new
coordinator waits out the prior holder's lease (+ skew margin) before
admitting writes.  Mode B keeps a pragmatic tick-denominated host twin
whose renewals are anchored at majority-contact time.

Covered here: grant/renew/local-read across every dispatch mode, the
register plane as a lease target, consensus fallback, the write fence on
failover, WAL recovery with leases on, the skew guard, config gates, the
``read_leases`` off bit-identity guarantee, and a multi-seed chaos soak
(crash/partition/fast-reelection flaps + bounded clock skew) with a
linearizability checker over a monotone register plus the per-slot S1
safety ledger.
"""

import os

import numpy as np
import pytest

from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.modeb import ModeBNode
from gigapaxos_tpu.paxos.manager import PaxosManager
from gigapaxos_tpu.testing.chaos import SafetyLedger
from gigapaxos_tpu.testing.simnet import SimNet
from gigapaxos_tpu.wal.logger import PaxosLogger, recover


def mk_cfg(G=8, G_reg=0, compact=False, pipeline=False, leases=True,
           horizon=16, margin=4, window=None):
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = G
    cfg.paxos.register_groups = G_reg
    cfg.paxos.compact_outbox = compact
    cfg.paxos.pipeline_ticks = pipeline
    cfg.paxos.read_leases = leases
    cfg.paxos.lease_ticks = horizon
    cfg.paxos.lease_margin_ticks = margin
    if window is not None:
        cfg.paxos.window = window
    return cfg


def pump(m, n):
    for _ in range(n):
        m.tick()
    m.drain_pipeline()


# ------------------------------------------------------------ mode A basics

@pytest.mark.parametrize("compact,pipeline,g_reg",
                         [(False, False, 0), (False, True, 0),
                          (True, False, 4), (True, True, 4)])
def test_lease_grant_renew_and_local_read(compact, pipeline, g_reg):
    """The stable-coordinator path in every dispatch mode: a lease is
    granted to the winning coordinator, renewed each tick, and a read is
    answered locally (rid 0, synchronous callback) with the latest
    committed value."""
    m = PaxosManager(mk_cfg(compact=compact, pipeline=pipeline, G_reg=g_reg),
                     3, [KVApp() for _ in range(3)])
    m.create_paxos_instance("svc", [0, 1, 2])
    for i in range(5):
        m.propose("svc", f"PUT k v{i}".encode())
        m.tick()
    pump(m, 10)
    info = m.lease_info("svc")
    assert info is not None
    assert info["holder"] == 0 and info["epoch"] >= 1
    assert info["until"] > info["clock"]  # renewal keeps it ahead
    got = {}
    rid = m.read("svc", b"GET k",
                 lambda r, resp: got.update(rid=r, resp=resp))
    assert rid == 0 and got["rid"] == 0 and got["resp"] == b"v4"
    assert m.stats["local_reads"] >= 1


def test_register_group_lease_read():
    """Register groups (PR 16) are first-class lease targets: the W=1
    plane grants/renews through the same fold and serves local reads."""
    m = PaxosManager(mk_cfg(G_reg=4, compact=True), 3,
                     [KVApp() for _ in range(3)])
    m.create_paxos_instance("reg", [0, 1, 2], register=True)
    for i in range(6):
        m.propose("reg", f"PUT k r{i}".encode())
        m.tick()
    pump(m, 10)
    info = m.lease_info("reg")
    assert info is not None and info["holder"] == 0
    got = {}
    rid = m.read("reg", b"GET k", lambda r, resp: got.update(resp=resp))
    assert rid == 0 and got["resp"] == b"r5"


def test_read_falls_back_without_lease():
    """``read_leases`` off: the read API still works, but every read is a
    consensus round (CLS_READ propose through the ordered stream)."""
    m = PaxosManager(mk_cfg(leases=False), 3, [KVApp() for _ in range(3)])
    m.create_paxos_instance("svc", [0, 1, 2])
    for i in range(3):
        m.propose("svc", f"PUT k v{i}".encode())
        m.tick()
    pump(m, 8)
    assert m.lease_info("svc") is None
    got = {}
    rid = m.read("svc", b"GET k", lambda r, resp: got.update(resp=resp))
    assert rid != 0 and rid is not None
    pump(m, 8)
    assert got["resp"] == b"v2"
    assert m.stats["local_reads"] == 0


def test_skew_guard_blocks_local_reads():
    """The host-side validity check subtracts the configured skew
    allowance; a mirror clock assumed further ahead than the lease end
    must refuse local serving and fall back."""
    m = PaxosManager(mk_cfg(horizon=8, margin=2), 3,
                     [KVApp() for _ in range(3)])
    m.create_paxos_instance("svc", [0, 1, 2])
    m.propose("svc", b"PUT k v")
    pump(m, 6)
    assert m.read("svc", b"GET k") == 0  # sanity: local read works
    m._lease_skew_ticks = -100  # host clock effectively past any until
    got = {}
    rid = m.read("svc", b"GET k", lambda r, resp: got.update(resp=resp))
    assert rid != 0
    pump(m, 8)
    assert got["resp"] == b"v"


def test_write_fence_delays_failover_writes():
    """After the holder dies, the new coordinator may not ack writes
    until the prior lease (+ margin) has run out — and local reads at the
    dead holder are refused immediately."""
    horizon, margin = 12, 4
    m = PaxosManager(mk_cfg(horizon=horizon, margin=margin), 3,
                     [KVApp() for _ in range(3)])
    m.create_paxos_instance("svc", [0, 1, 2])
    m.propose("svc", b"PUT k old")
    pump(m, 5)
    assert m.lease_info("svc")["holder"] == 0
    m.set_alive(0, False)
    got = {}
    rid = m.read("svc", b"GET k", lambda r, resp: got.update(resp=resp))
    assert rid != 0  # dead holder: no local serving
    acks = []
    m.propose("svc", b"PUT k new", lambda r, resp: acks.append(resp))
    waited = 0
    for _ in range(4 * (horizon + margin)):
        m.tick()
        m.drain_pipeline()
        if acks:
            break
        waited += 1
    assert acks == [b"OK"]
    # the write really waited out the fence (several ticks, not one)
    assert waited >= margin, waited
    info = m.lease_info("svc")
    assert info["holder"] == 1 and info["epoch"] >= 2
    # and the new holder serves reads locally again
    got2 = {}
    assert m.read("svc", b"GET k",
                  lambda r, resp: got2.update(resp=resp)) == 0
    assert got2["resp"] == b"new"


def test_lease_cleared_on_remove_and_recreate():
    """Row lifecycle: removing a group drops its lease columns; a
    recreated group re-elects and re-grants from scratch (no stale
    holder resurrection through the row recycler)."""
    m = PaxosManager(mk_cfg(), 3, [KVApp() for _ in range(3)])
    m.create_paxos_instance("svc", [0, 1, 2])
    m.propose("svc", b"PUT k a")
    pump(m, 6)
    assert m.lease_info("svc")["holder"] == 0
    m.remove_paxos_instance("svc")
    assert m.lease_info("svc") is None
    m.create_paxos_instance("svc2", [0, 1, 2])
    m.propose("svc2", b"PUT k b")
    pump(m, 6)
    got = {}
    assert m.read("svc2", b"GET k",
                  lambda r, resp: got.update(resp=resp)) == 0
    assert got["resp"] == b"b"


def test_wal_recover_with_leases(tmp_path):
    """Crash + recover with leases on: the snapshot carries the lease
    plane, replayed ticks re-drive the fold, and the recovered manager
    keeps serving local reads."""
    cfg = mk_cfg(compact=True, pipeline=True)
    d = os.path.join(str(tmp_path), "wal")
    wal = PaxosLogger(d, checkpoint_every_ticks=10)
    apps = [KVApp() for _ in range(3)]
    m = PaxosManager(cfg, 3, apps, wal=wal)
    m.create_paxos_instance("svc", [0, 1, 2])
    for i in range(25):
        m.propose("svc", f"PUT k v{i}".encode())
        m.tick()
    pump(m, 10)
    want = m.exec_watermarks("svc").copy()
    info = m.lease_info("svc")
    assert info["holder"] == 0
    wal.close()
    apps2 = [KVApp() for _ in range(3)]
    m2 = recover(cfg, 3, apps2, d)
    assert np.array_equal(m2.exec_watermarks("svc"), want)
    info2 = m2.lease_info("svc")
    assert info2 is not None and info2["holder"] == 0
    assert info2["clock"] == info["clock"]
    pump(m2, 3)  # renewals continue post-recovery
    got = {}
    assert m2.read("svc", b"GET k",
                   lambda r, resp: got.update(resp=resp)) == 0
    assert got["resp"] == b"v24"


def test_leases_off_bit_identity(tmp_path):
    """The flag-off guarantee, and its stronger cousin: with a stable
    coordinator the lease fold never perturbs consensus — the log-plane
    state arrays and journal bytes are identical with leases on or off."""
    results = []
    for leases, sub in ((False, "off"), (True, "on")):
        cfg = mk_cfg(leases=leases, compact=True)
        d = os.path.join(str(tmp_path), sub)
        wal = PaxosLogger(d, checkpoint_every_ticks=1000)
        m = PaxosManager(cfg, 3, [KVApp() for _ in range(3)], wal=wal)
        m.create_paxos_instance("svc", [0, 1, 2])
        for i in range(12):
            m.propose("svc", f"PUT k{i} v{i}".encode())
            m.tick()
        pump(m, 8)
        wal.close()
        state = {f: np.asarray(getattr(m.state, f)) for f in m.state._fields}
        jpaths = sorted(p for p in os.listdir(d) if p.startswith("journal."))
        blobs = [open(os.path.join(d, p), "rb").read() for p in jpaths]
        results.append((state, jpaths, blobs))
    (st_a, jp_a, bl_a), (st_b, jp_b, bl_b) = results
    for f in st_a:
        assert np.array_equal(st_a[f], st_b[f]), f
    assert jp_a == jp_b
    assert bl_a == bl_b


def test_lease_config_gates():
    cfg = GigapaxosTpuConfig()
    cfg.paxos.read_leases = True
    cfg.paxos.lease_ticks = 0
    with pytest.raises(ValueError):
        cfg.paxos.__post_init__()
    cfg2 = GigapaxosTpuConfig()
    cfg2.paxos.lease_margin_ticks = -1
    with pytest.raises(ValueError):
        cfg2.paxos.__post_init__()


# --------------------------------------------------------- mode A chaos soak

@pytest.mark.parametrize("seed", [11, 12, 13])
def test_lease_soak_mode_a_linearizable(seed):
    """Randomized holder crash/revive with skew injection on the shared
    device plane: every read that returns must be linearizable against
    the closed-loop monotone writer (floor = acked at invocation, ceiling
    = issued at response)."""
    horizon, margin = 12, 4
    m = PaxosManager(mk_cfg(horizon=horizon, margin=margin, compact=True),
                     3, [KVApp() for _ in range(3)])
    m.create_paxos_instance("svc", [0, 1, 2])
    rng = np.random.default_rng(seed)
    state = {"acked": 0, "issued": 0, "outstanding": None}
    failures = []

    def write():
        val = state["issued"] + 1
        state["issued"] = val
        state["outstanding"] = val

        def cb(r, resp):
            if resp == b"OK":
                state["acked"] = max(state["acked"], val)
                if state["outstanding"] == val:
                    state["outstanding"] = None
        m.propose("svc", f"PUT k {val}".encode(), cb)

    def read(t):
        floor = state["acked"]

        def cb(r, resp, _floor=floor, _t=t):
            hi = state["issued"]
            if resp is None:
                return
            v = 0 if resp == b"NF" else int(resp)
            if not (_floor <= v <= hi):
                failures.append((_t, v, _floor, hi))
        m.read("svc", b"GET k", cb)

    down = None  # (replica, revive_tick)
    for t in range(320):
        if down is None and t > 20 and rng.random() < 0.02:
            victim = int(m.lease_info("svc")["holder"]) \
                if m.lease_info("svc") else 0
            if victim >= 0:
                m.set_alive(victim, False)
                down = (victim, t + int(rng.integers(
                    horizon + margin + 5, 3 * horizon)))
        if down is not None and t >= down[1]:
            m.set_alive(down[0], True)
            down = None
        if t % 40 == 7:  # bounded host-side skew assumption
            m._lease_skew_ticks = int(rng.integers(0, margin + 1))
        if state["outstanding"] is None and t % 3 == 0:
            write()
        if t % 2 == 0:
            read(t)
        m.tick()
        m.drain_pipeline()
    if down is not None:
        m.set_alive(down[0], True)
    pump(m, 60)
    assert not failures, failures[:5]
    assert state["acked"] > 20
    assert m.stats["local_reads"] > 0


# --------------------------------------------------------- mode B chaos soak

IDS = ["N0", "N1", "N2"]


def _build_modeb(seed, horizon, margin):
    net = SimNet(seed=seed)
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 8
    cfg.paxos.window = 8
    cfg.paxos.fast_reelection = True
    cfg.paxos.read_leases = True
    cfg.paxos.lease_ticks = horizon
    cfg.paxos.lease_margin_ticks = margin
    apps = {n: KVApp() for n in IDS}
    nodes = {n: ModeBNode(cfg, IDS, n, apps[n], net.messenger(n),
                          anti_entropy_every=8) for n in IDS}
    for nd in nodes.values():
        nd.create_group("svc", [0, 1, 2])
    return net, nodes, apps


def test_modeb_local_read_and_takeover_fence():
    """Per-process twin: the winning coordinator serves local reads once
    its (bootstrap-fenced) lease settles; non-coordinators always fall
    back to a consensus round; a partition takeover write-fences."""
    horizon, margin = 8, 2
    net, nodes, apps = _build_modeb(3, horizon, margin)

    def spin(k, only=None):
        for _ in range(k):
            for nid, nd in nodes.items():
                if only is None or nid in only:
                    nd.tick()
            net.pump()

    done = []
    nodes["N0"].propose("svc", b"PUT k v1", lambda r, x: done.append(x))
    spin(60)
    assert done == [b"OK"]
    got = {}
    rid = nodes["N0"].read("svc", b"GET k",
                           lambda r, resp: got.update(resp=resp))
    assert rid == 0 and got["resp"] == b"v1"
    assert nodes["N0"].stats["local_reads"] >= 1
    # a non-coordinator never serves locally
    got2 = {}
    rid2 = nodes["N1"].read("svc", b"GET k",
                            lambda r, resp: got2.update(resp=resp))
    assert rid2 != 0
    spin(20)
    assert got2["resp"] == b"v1"
    # partition the holder away; the successor's writes wait out the fence
    net.partition({"N0"}, {"N1", "N2"})
    for nid in ("N1", "N2"):
        nodes[nid].set_alive(0, False)
    done2 = []
    nodes["N1"].propose("svc", b"PUT k v2", lambda r, x: done2.append(x))
    waited = 0
    for _ in range(8 * (horizon + margin)):
        spin(1, only=("N1", "N2"))
        if done2:
            break
        waited += 1
    assert done2 == [b"OK"]
    assert waited >= margin, waited  # fence delayed the takeover write
    # the isolated ex-holder's lease has lapsed: no local serving
    assert nodes["N0"].read("svc", b"GET k") != 0


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5, 6])
def test_lease_chaos_soak_modeb(seed):
    """The ISSUE 17 lease-safety soak: partition flaps with fast
    re-election, failure-detector driven takeovers, and bounded tick-skew
    stalls (<= margin per lease window).  Reads — including at isolated
    stale holders — must stay linearizable against the closed-loop
    monotone writer, and the cluster-wide per-slot S1 ledger must stay
    clean."""
    horizon, margin = 24, 6
    net, nodes, apps = _build_modeb(seed, horizon, margin)
    ledger = SafetyLedger()
    for nid, nd in nodes.items():
        ledger.attach(nid, nd)
    rng = np.random.default_rng(seed)
    T = 650
    # precomputed, non-overlapping isolation windows
    events = []
    t = 80
    while t < T - 120:
        victim = IDS[int(rng.integers(0, 3))]
        dur = int(rng.integers(horizon // 2, 2 * (horizon + margin)))
        events.append((t, t + dur, victim))
        t += dur + int(rng.integers(30, 70))

    def isolated(nid, tick):
        return any(s <= tick < e for (s, e, v) in events if v == nid)

    state = {"acked": 0, "issued": 0, "outstanding": None}
    failures = []

    def write(at):
        val = state["issued"] + 1
        state["issued"] = val
        state["outstanding"] = val

        def cb(r, resp):
            if resp == b"OK":
                state["acked"] = max(state["acked"], val)
                if state["outstanding"] == val:
                    state["outstanding"] = None
        nodes[at].propose("svc", f"PUT k {val}".encode(), cb)

    def read(at, tick):
        floor = state["acked"]

        def cb(r, resp, _floor=floor, _t=tick, _n=at):
            hi = state["issued"]
            if resp is None:
                return
            v = 0 if resp == b"NF" else int(resp)
            if not (_floor <= v <= hi):
                failures.append((_n, _t, v, _floor, hi))
        nodes[at].read("svc", b"GET k", cb)

    stalls = {n: 0 for n in IDS}
    for t in range(T):
        for (s, e, v) in events:
            if t == s:
                net.partition({v}, set(n for n in IDS if n != v))
            if t == s + 4 and t < e:  # failure-detector lag
                r = IDS.index(v)
                for nid, nd in nodes.items():
                    if nid != v:
                        nd.set_alive(r, False)
            if t == e:
                net.heal()
                for nd in nodes.values():
                    for r in range(3):
                        nd.set_alive(r, True)
        # bounded clock-skew injection: at most one stall per node per
        # >horizon window, each <= margin ticks (the lease assumption)
        if t % 60 == 17:
            stalls[IDS[int(rng.integers(0, 3))]] = int(
                rng.integers(1, margin + 1))
        # closed-loop writer at a node with no isolation in sight
        if state["outstanding"] is None and t % 3 == 0:
            cands = [n for n in IDS
                     if not any(v == n and s <= t + 50 and e > t
                                for (s, e, v) in events)]
            if cands:
                write(cands[int(rng.integers(0, len(cands)))])
        # reads everywhere, isolated stale holders very much included
        if t % 2 == 0:
            read(IDS[int(rng.integers(0, 3))], t)
        for nid, nd in nodes.items():
            if stalls[nid] > 0:
                stalls[nid] -= 1
                continue
            nd.tick()
        net.pump()
    net.heal()
    for nd in nodes.values():
        for r in range(3):
            nd.set_alive(r, True)
    for _ in range(90):
        for nd in nodes.values():
            nd.tick()
        net.pump()
    ledger.assert_safe()
    assert not failures, failures[:5]
    assert state["acked"] > 20, state
    assert sum(nd.stats["local_reads"] for nd in nodes.values()) > 0

"""Micro-bench: frames per recv() syscall, per-frame reads vs FrameReader.

The transport's reader used to issue TWO blocking ``recv`` calls per frame
(exact header, then exact payload).  At Mode B's capacity knee the inbound
control plane is thousands of tiny frames per tick, so the syscall pair per
frame dominated the reader thread.  ``FrameReader`` batches: one recv pulls
up to ``_RECV_CHUNK`` bytes and complete frames are sliced out of the buffer
without touching the socket again until it runs dry.

This bench pushes N small frames (Mode-B-knee sized: tens of bytes) through
a loopback socketpair and measures frames/syscall and wall time for

* ``per_frame`` — the old two-recv-per-frame pattern, reimplemented here
  verbatim as the "before" arm (it no longer exists in transport.py), and
* ``batched`` — the live ``FrameReader``.

Acceptance target: >= 4x frames/syscall on the batched arm.  In practice the
ratio is bounded only by how many frames fit in one ``_RECV_CHUNK`` (~4900
at 53B/frame), so it lands orders of magnitude above the bar.

The SEND side mirrors it: the writer used to issue one ``sendall`` per
queued frame; the live writer drains its backlog into ``sendmsg`` (writev)
vectors of up to ``_IOV_MAX//2`` frames.  The ``send`` section measures
egress frames/syscall for

* ``per_frame`` — one sendall per frame (the old writer's pattern), and
* ``batched``   — the live ``_send_frames`` writev drain at the writer's
  default coalescing window.

Usage:  python benchmarks/bench_transport.py [--frames N] [--payload B]
                                             [--out results.json]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gigapaxos_tpu.net.transport import _HDR, _IOV_MAX, FrameReader, \
    _send_frames


def _sender(sock: socket.socket, n_frames: int, payload: bytes) -> None:
    """Stream n_frames as fast as the socket accepts them.

    Frames are coalesced into sendall batches — mirroring the writer
    thread's queue drain — so the receive side, not the send side, is the
    bottleneck under measurement."""
    frame = _HDR.pack(len(payload) + 1, 1) + payload
    batch = frame * 256
    full, rest = divmod(n_frames, 256)
    try:
        for _ in range(full):
            sock.sendall(batch)
        if rest:
            sock.sendall(frame * rest)
    finally:
        sock.shutdown(socket.SHUT_WR)


# ---------------------------------------------------------------- before arm
def _recv_exact(sock: socket.socket, n: int, counter: list) -> bytes:
    """The pre-batching reader: loop recv(exactly-what's-missing)."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        counter[0] += 1
        if not chunk:
            raise ConnectionError("eof")
        buf += chunk
    return buf


def run_per_frame(sock: socket.socket, n_frames: int) -> dict:
    syscalls = [0]
    t0 = time.perf_counter()
    got = 0
    for _ in range(n_frames):
        hdr = _recv_exact(sock, _HDR.size, syscalls)
        ln, _kind = _HDR.unpack(hdr)
        _recv_exact(sock, ln - 1, syscalls)
        got += 1
    dt = time.perf_counter() - t0
    return {"frames": got, "syscalls": syscalls[0], "seconds": dt}


# ----------------------------------------------------------------- after arm
def run_batched(sock: socket.socket, n_frames: int) -> dict:
    reader = FrameReader(sock)
    t0 = time.perf_counter()
    got = 0
    while got < n_frames:
        if reader.next_frame() is None:
            raise ConnectionError("eof before all frames arrived")
        got += 1
    dt = time.perf_counter() - t0
    return {"frames": got, "syscalls": reader.syscalls, "seconds": dt}


def run_arm(arm, n_frames: int, payload_bytes: int) -> dict:
    a, b = socket.socketpair()
    payload = b"\x42" * payload_bytes
    tx = threading.Thread(target=_sender, args=(a, n_frames, payload),
                          daemon=True)
    tx.start()
    try:
        res = arm(b, n_frames)
    finally:
        tx.join(timeout=30)
        a.close()
        b.close()
    res["frames_per_syscall"] = res["frames"] / max(res["syscalls"], 1)
    res["frames_per_sec"] = res["frames"] / max(res["seconds"], 1e-9)
    return res


# ------------------------------------------------------------------ send side
def _drain(sock: socket.socket, total_bytes: int) -> None:
    got = 0
    while got < total_bytes:
        chunk = sock.recv(1 << 20)
        if not chunk:
            return
        got += len(chunk)


def run_send_per_frame(sock: socket.socket, n_frames: int,
                       payload: bytes) -> dict:
    """The old writer: one sendall per queued frame (1+ syscalls each)."""
    frame = _HDR.pack(len(payload) + 1, 1) + payload
    t0 = time.perf_counter()
    for _ in range(n_frames):
        sock.sendall(frame)
    dt = time.perf_counter() - t0
    return {"frames": n_frames, "syscalls": n_frames, "seconds": dt}


def run_send_batched(sock: socket.socket, n_frames: int,
                     payload: bytes) -> dict:
    """The live writer's drain: ``_send_frames`` over batches at the
    default coalescing window (``_IOV_MAX//2`` frames per writev)."""
    window = _IOV_MAX // 2
    syscalls = 0
    t0 = time.perf_counter()
    left = n_frames
    while left:
        k = min(left, window)
        syscalls += _send_frames(sock, [(0, 1, payload)] * k)
        left -= k
    dt = time.perf_counter() - t0
    return {"frames": n_frames, "syscalls": syscalls, "seconds": dt}


def run_send_arm(arm, n_frames: int, payload_bytes: int) -> dict:
    a, b = socket.socketpair()
    payload = b"\x42" * payload_bytes
    total = n_frames * (_HDR.size + 1 + payload_bytes)
    rx = threading.Thread(target=_drain, args=(b, total), daemon=True)
    rx.start()
    try:
        res = arm(a, n_frames, payload)
        a.shutdown(socket.SHUT_WR)
    finally:
        rx.join(timeout=30)
        a.close()
        b.close()
    res["frames_per_syscall"] = res["frames"] / max(res["syscalls"], 1)
    res["frames_per_sec"] = res["frames"] / max(res["seconds"], 1e-9)
    return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--frames", type=int, default=200_000)
    ap.add_argument("--payload", type=int, default=48,
                    help="payload bytes per frame (Mode B knee: tens of B)")
    ap.add_argument("--out", default=None, help="write result JSON here")
    args = ap.parse_args(argv)

    before = run_arm(run_per_frame, args.frames, args.payload)
    after = run_arm(run_batched, args.frames, args.payload)
    ratio = after["frames_per_syscall"] / max(
        before["frames_per_syscall"], 1e-9)
    s_before = run_send_arm(run_send_per_frame, args.frames, args.payload)
    s_after = run_send_arm(run_send_batched, args.frames, args.payload)
    s_ratio = s_after["frames_per_syscall"] / max(
        s_before["frames_per_syscall"], 1e-9)
    result = {
        "bench": "transport_frames_per_syscall",
        "frames": args.frames,
        "payload_bytes": args.payload,
        "frame_bytes": _HDR.size + 1 + args.payload,
        "per_frame": before,
        "batched": after,
        "speedup_frames_per_syscall": ratio,
        "meets_4x_target": ratio >= 4.0,
        "send": {
            "per_frame": s_before,
            "batched": s_after,
            "writev_window_frames": _IOV_MAX // 2,
            "speedup_frames_per_syscall": s_ratio,
            "meets_4x_target": s_ratio >= 4.0,
        },
    }
    print(json.dumps(result, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    return 0 if (ratio >= 4.0 and s_ratio >= 4.0) else 1


if __name__ == "__main__":
    sys.exit(main())

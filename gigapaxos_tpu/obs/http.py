"""The scrape endpoint: a tiny threaded HTTP server (stdlib only).

Routes:

* ``GET /metrics``      -> Prometheus text (the ``scrape`` callback)
* ``GET /trace/<tid>``  -> JSON timeline for one trace id (``trace`` cb)
* ``GET /trace``        -> JSON list of recent trace ids
* ``GET /flight``       -> JSON flight-recorder ring (``flight`` cb)

Bound to ``127.0.0.1`` by default — operators front it with their own
ingress; port 0 picks an ephemeral port (tests), ``.port`` reports it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


class MetricsServer:
    def __init__(self, scrape: Callable[[], str],
                 trace: Optional[Callable[[Optional[str]], object]] = None,
                 flight: Optional[Callable[[], object]] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        self._scrape = scrape
        self._trace = trace
        self._flight = flight
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no stderr chatter
                pass

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0].rstrip("/") or "/"
                    if path == "/metrics":
                        self._send(200, outer._scrape(),
                                   "text/plain; version=0.0.4")
                    elif path == "/trace" and outer._trace is not None:
                        self._send(200, json.dumps(outer._trace(None)),
                                   "application/json")
                    elif (path.startswith("/trace/")
                          and outer._trace is not None):
                        tid = path[len("/trace/"):]
                        self._send(200, json.dumps(outer._trace(tid)),
                                   "application/json")
                    elif path == "/flight" and outer._flight is not None:
                        self._send(200, json.dumps(outer._flight()),
                                   "application/json")
                    else:
                        self._send(404, "not found\n", "text/plain")
                except BrokenPipeError:
                    pass
                except Exception as e:  # a broken source must not kill serve
                    try:
                        self._send(500, f"{type(e).__name__}: {e}\n",
                                   "text/plain")
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name=f"metrics-http:{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=2)

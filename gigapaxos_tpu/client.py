"""Async client library.

Analog of ``reconfiguration/ReconfigurableAppClientAsync.java:35`` (plus the
paxos-only ``PaxosClientAsync.java:48``): a client endpoint that

* manages names through any reconfigurator (create/delete/reconfigure,
  retrying across RCs);
* caches each name's active-replica set with a TTL and re-resolves on
  ``not_active``/``stopped`` errors (the actives cache + invalidate-on-error
  loop, ReconfigurableAppClientAsync.java:43 MIN_REQUEST_ACTIVES_INTERVAL);
* redirects each request to the lowest-latency active by EWMA RTT with
  occasional exploration (E2ELatencyAwareRedirector.java:18 +
  RTTEstimator.java:28);
* correlates responses by request id, with both sync helpers and async
  callbacks (RequestCallbackFuture analog).

The client binds its own ephemeral port and stamps ``client_addr`` on every
packet so servers can address it back over the node transport.
"""

from __future__ import annotations

import collections
import itertools
import random
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from . import overload as _overload
from .config import NodeConfig
from .net import binbatch
from .net.messenger import Messenger, NodeMap
from .obs.metrics import registry as _obs_registry
from .reconfiguration import packets as pkt
from .utils.reqtrace import XNS as _XNS
from .utils.reqtrace import new_trace_id, tracer as _tracer


class ClientError(Exception):
    pass


class ReconfigurableAppClient:
    def __init__(
        self,
        nodes: NodeConfig,
        client_id: Optional[str] = None,
        bind_host: str = "127.0.0.1",
        actives_ttl_s: float = 30.0,
        explore_prob: float = 0.1,
        security=None,
        placement_table=None,
        trace_wire: "bool | None" = None,
        retry_fraction: float = 0.1,
        breaker_threshold: int = 5,
        breaker_cooloff_s: float = 1.0,
        default_deadline_s: float = 15.0,
    ):
        """``security``: a ``TransportSecurity`` for TLS deployments — under
        MUTUAL_AUTH it must carry a CA-signed client certificate (the
        reference's mutual-auth client types,
        ReconfigurableAppClientAsync.java:35).

        ``placement_table``: an optional ``placement.PlacementTable`` fed by
        the deployment wiring (the http_edge idiom).  When present, names
        with a migration override route straight to the override's server —
        the actives cache and RC never need to chase the placement."""
        self.node_id = client_id or f"C{uuid.uuid4().hex[:8]}"
        self.nodemap = NodeMap(nodes)
        self.m = Messenger(self.node_id, (bind_host, 0), self.nodemap,
                           security=security)
        self.addr = (bind_host, self.m.port)
        self.rc_ids = list(nodes.reconfigurator_ids())
        if not self.rc_ids:
            raise ClientError("no reconfigurators in topology")
        self._rc_rr = itertools.cycle(self.rc_ids)
        self.actives_ttl_s = actives_ttl_s
        self.explore_prob = explore_prob
        self.placement_table = placement_table
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._next_rid = random.randrange(1, 1 << 30)
        # bounded: late responses to abandoned rids and callbacks for
        # requests that never get answered must not accumulate forever
        # (the reference GC's its callback maps the same way,
        # GCConcurrentHashMap in ReconfigurableAppClientAsync)
        self._results: "collections.OrderedDict[int, dict]" = collections.OrderedDict()
        self._results_cap = 2048
        self._callbacks: Dict[int, Callable[[dict], None]] = {}
        self._cb_deadline: Dict[int, float] = {}
        self._cb_ttl_s = 120.0
        #: name -> (expiry_monotonic, actives list)
        self._actives: Dict[str, Tuple[float, List[str]]] = {}
        #: name -> (placement-table epoch at fill time, chosen target): the
        #: per-name route memo.  Entries die on a table epoch bump (a
        #: placement/cell override changed somewhere) or an explicit
        #: _drop_route (the target failed / redirected this client).
        self._route_cache: Dict[str, Tuple[int, str]] = {}
        self._route_cache_cap = 4096
        #: name -> current re-resolution backoff (full-jitter exponential,
        #: the _rpc_rc scheme applied per name): a moved/bouncing name must
        #: not hammer the RC with synchronized re-resolves
        self._route_backoff: Dict[str, float] = {}
        self._rtt: Dict[str, float] = {}  # active id -> EWMA seconds
        self._sent_at: Dict[int, Tuple[str, float]] = {}
        for t in (pkt.CREATE_RESPONSE, pkt.CREATE_BATCH_RESPONSE,
                  pkt.DELETE_RESPONSE,
                  pkt.ACTIVES_RESPONSE, pkt.RECONFIGURE_RESPONSE,
                  pkt.APP_RESPONSE, pkt.ECHO_REPLY,
                  pkt.NODE_CONFIG_RESPONSE):
            self.m.register(t, self._on_response)
        self.m.register(pkt.APP_RESPONSE_BATCH, self._on_batch_response)
        binbatch.chain_bytes_handler(self.m.demux, binbatch.RESP_MAGIC,
                                     self._on_binary_batch_response)
        # randomized like _next_rid: a restarted client with a stable id
        # must not hit the server's batch-dedup cache from its past life
        self._next_bid = random.randrange(1, 1 << 30)
        #: bid -> (target, send time): one RTT sample per batch FRAME (the
        #: per-rid _sent_at writes were the staging hot path's top cost)
        self._batch_sent: "collections.OrderedDict[int, tuple]" = (
            collections.OrderedDict()
        )
        #: commit-latency SLO histogram (client-observed RTT; the AR-side
        #: twin is commit_latency_seconds in reconfiguration/active_replica)
        self._lat_h = _obs_registry().histogram(
            "client_commit_latency_seconds",
            help="client-observed request->response latency")
        self._batch_lat_h = _obs_registry().histogram(
            "client_batch_rtt_seconds",
            help="per-batch-frame round-trip latency")
        #: read-latency SLO histogram (ISSUE 17): lease-local reads answer
        #: without a consensus round, so reads get their own distribution
        #: instead of polluting the commit-latency one
        self._read_lat_h = _obs_registry().histogram(
            "client_read_latency_seconds",
            help="client-observed read request->response latency")
        #: rids in flight on the READ path (routes the RTT sample to the
        #: read histogram; bounded by the same reaping as _sent_at)
        self._read_rids: set = set()
        #: cross-process tracing: when enabled (GPTPU_REQTRACE, or set
        #: ``client.trace.enabled = True``), app requests carry a trace id
        #: on the wire ("trace") that every hop records against — see
        #: utils/reqtrace.py "Cross-process tracing"
        self.trace = _tracer(_XNS)
        if trace_wire is not None:  # cfg.obs.trace_wire plumbs through here
            self.trace.enabled = bool(trace_wire)
        self._trace_ids: "collections.OrderedDict[int, int]" = (
            collections.OrderedDict()
        )
        # ---- overload plane (ISSUE 14): storm dampers + wire deadlines ----
        #: retry budget: retries spend from a bucket funded at
        #: ``retry_fraction`` per fresh request — a brownout triggers at
        #: most ~10% retry amplification instead of tries× (SRE retry
        #: budget; the transport's own frame retries are unaffected)
        self.retry_budget = _overload.TokenBucket(fraction=retry_fraction)
        #: per-active circuit breakers driven by NACK/timeout rate; consulted
        #: non-consumingly by the redirector so a browned-out destination is
        #: avoided for a cooloff instead of hammered
        self._breakers: Dict[str, _overload.CircuitBreaker] = {}
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_cooloff_s = float(breaker_cooloff_s)
        #: default wire deadline for async sends (sync paths derive the
        #: deadline from their own timeout argument)
        self.default_deadline_s = float(default_deadline_s)

    def close(self) -> None:
        self.m.close()

    def _wire_deadline(self) -> int:
        """Default async-path wire deadline; 0 (no deadline) when stamping
        is disabled with ``default_deadline_s <= 0``."""
        return (_overload.deadline_at(self.default_deadline_s)
                if self.default_deadline_s > 0 else 0)

    # ------------------------------------------------------------- plumbing
    def _rid(self) -> int:
        with self._lock:
            self._next_rid += 1
            return self._next_rid

    def _stamp(self, p: dict) -> dict:
        p["client_addr"] = [self.addr[0], self.addr[1]]
        if self.trace.enabled and p.get("type") == pkt.APP_REQUEST:
            rid = p.get("rid")
            with self._lock:
                # retries reuse the rid AND the trace id: one timeline
                tid = self._trace_ids.get(rid)
                if tid is None:
                    tid = self._trace_ids[rid] = new_trace_id()
                    while len(self._trace_ids) > 4096:
                        self._trace_ids.popitem(last=False)
            p["trace"] = tid
            self.trace.event(tid, "client_sent", req=rid, name=p.get("name"))
        return p

    def _on_response(self, sender: str, p: dict) -> None:
        rid = p.get("rid")
        cb = None
        with self._lock:
            if rid is not None:
                sa = self._sent_at.get(rid)
                if sa is not None and sa[0] == sender:
                    # only credit the RTT to the node that actually answered:
                    # with rid reuse across retries, a LATE response from
                    # attempt k must not consume (and mis-attribute) the
                    # timing entry written by attempt k+1
                    del self._sent_at[rid]
                    node, t0 = sa
                    rtt = time.monotonic() - t0
                    if rid in self._read_rids:
                        self._read_rids.discard(rid)
                        self._read_lat_h.observe(rtt)
                    else:
                        self._lat_h.observe(rtt)
                    prev = self._rtt.get(node)
                    self._rtt[node] = rtt if prev is None else 0.875 * prev + 0.125 * rtt
                tid = self._trace_ids.pop(rid, None)
                cb = self._callbacks.pop(rid, None)
                self._cb_deadline.pop(rid, None)
                if cb is None:
                    self._results[rid] = p
                    while len(self._results) > self._results_cap:
                        self._results.popitem(last=False)
                    self._cv.notify_all()
        if rid is not None and tid is not None:
            self.trace.event(tid, "client_responded", req=rid,
                             ok=bool(p.get("ok")))
        if cb is not None:
            cb(p)

    def _breaker(self, target: str) -> _overload.CircuitBreaker:
        with self._lock:
            br = self._breakers.get(target)
            if br is None:
                br = self._breakers[target] = _overload.CircuitBreaker(
                    threshold=self._breaker_threshold,
                    cooloff_s=self._breaker_cooloff_s)
            return br

    def _reap(self, rid: int) -> None:
        """Drop every per-rid map entry for an abandoned request.  Without
        this, a sustained-timeout workload (dead active, partitioned
        client) grows _sent_at/_trace_ids without bound — each timed-out
        rid's entries survived because only the response path popped them."""
        with self._lock:
            self._sent_at.pop(rid, None)
            self._callbacks.pop(rid, None)
            self._cb_deadline.pop(rid, None)
            self._trace_ids.pop(rid, None)
            self._read_rids.discard(rid)

    def _await(self, rid: int, timeout: float) -> dict:
        deadline = time.monotonic() + timeout
        with self._lock:
            while rid not in self._results:
                left = deadline - time.monotonic()
                if left <= 0:
                    # reap the rid's tracking entries (not just _sent_at):
                    # an abandoned rid must not leak its trace-id/callback
                    # bookkeeping when no response ever arrives
                    self._sent_at.pop(rid, None)
                    self._callbacks.pop(rid, None)
                    self._cb_deadline.pop(rid, None)
                    self._trace_ids.pop(rid, None)
                    raise TimeoutError(f"rid {rid}")
                self._cv.wait(timeout=left)
            return self._results.pop(rid)

    def _rc_cycle_for(self, name: Optional[str]):
        """The RC rotation for one name: with a cell-aware router attached
        (cells.CellRouter duck-types ``rc_ids``), only the owner cell's
        reconfigurators hold the name's records — rotating through foreign
        cells' RCs would answer unknown_name.  Plain tables / no table:
        the shared round-robin."""
        t = self.placement_table
        if name is not None and t is not None:
            fn = getattr(t, "rc_ids", None)
            if fn is not None:
                ids = [r for r in fn(name)
                       if r in self.rc_ids or self.nodemap(r) is not None]
                if ids:
                    return itertools.cycle(ids)
        return self._rc_rr

    def _rpc_rc(self, packet: dict, timeout: float, tries: int = 3,
                on_reply=None, name: Optional[str] = None) -> dict:
        """Send a control request to reconfigurators, rotating on timeout.

        ``on_reply(resp, retried)`` may map the response before it is
        returned; ``retried`` is True when an earlier attempt timed out
        (it may have committed server-side).  ``name``: scope the rotation
        to the name's owner-cell RCs when a cell router is attached.

        Retries back off exponentially with full random jitter (the AWS
        "full jitter" scheme): a failed-over RC otherwise gets every
        client's retry k at exactly t0 + k*per — a synchronized retry storm
        arriving the instant it is least able to absorb it.  The jittered
        sleep spreads the herd over the backoff window; the per-try await
        still bounds total latency."""
        last: Optional[Exception] = None
        per = max(timeout / tries, 0.5)
        retried = False
        backoff = 0.1
        rr = self._rc_cycle_for(name)
        for attempt in range(tries):
            if attempt > 0:
                # full jitter: uniform in (0, backoff]; doubles per retry
                time.sleep(random.uniform(0.0, backoff))
                backoff = min(backoff * 2, 2.0)
            rc = next(rr)
            p = dict(packet)
            p["rid"] = self._rid()
            try:
                self.m.send(rc, self._stamp(p))
                resp = self._await(p["rid"], per)
            except TimeoutError as e:
                last = e
                retried = True
                continue
            return on_reply(resp, retried) if on_reply else resp
        raise TimeoutError(str(last))

    # ------------------------------------------------------- name management
    def create(self, name: str, initial_state: bytes = b"",
               timeout: float = 15.0) -> dict:
        """Create a service name.

        Caveat on retried creates: if an attempt times out and a retry
        answers "exists", the result maps to ok=True with
        ``note="created_by_earlier_attempt"`` — the usual cause is our own
        first attempt committing late.  It is however AMBIGUOUS: another
        client may have created the name first, in which case OUR
        initial_state was silently not applied.  Callers that care must
        disambiguate (read the state back, or encode a creator token in
        initial_state); the reference client has the same hole
        (DUPLICATE_ERROR tolerance, ReconfigurableAppClientAsync.java:35).
        """
        def on_reply(resp: dict, retried: bool) -> dict:
            if (not resp.get("ok") and resp.get("error") == "exists"
                    and retried):
                # a retransmission racing our own earlier (slow) attempt:
                # the name exists because WE created it — idempotent success
                # (the reference's client tolerates DUPLICATE_ERROR on
                # retried creates the same way,
                # ReconfigurableAppClientAsync.java:35)
                return dict(resp, ok=True, note="created_by_earlier_attempt")
            return resp

        return self._rpc_rc(
            pkt.create_service_name(name, initial_state, 0), timeout,
            on_reply=on_reply, name=name,
        )

    def create_batch(self, items, timeout: float = 30.0) -> dict:
        """Create many names with one RC commit per RC group
        (BatchedCreateServiceName.java; the client partitions by the names'
        RC groups like ReconfigurableAppClientAsync does).

        items: iterable of ``name`` or ``(name, initial_state)``.
        Returns {"ok": all_ok, "results": {name: {...}}}.
        """
        from .reconfiguration.consistent_hashing import ConsistentHashRing

        creates = [
            (it, b"") if isinstance(it, str) else (it[0], it[1])
            for it in items
        ]
        ring = ConsistentHashRing(sorted(self.rc_ids))
        parts: Dict[str, list] = {}
        for n, s in creates:
            parts.setdefault(ring.replicated_servers(n, 1)[0], []).append((n, s))
        results: Dict[str, dict] = {}
        rids = []
        for primary, batch in parts.items():
            p = pkt.create_batch(batch, self._rid())
            rids.append((primary, p))
            self.m.send(primary, self._stamp(p))
        deadline = time.monotonic() + timeout
        for primary, p in rids:
            left = max(deadline - time.monotonic(), 0.5)
            try:
                resp = self._await(p["rid"], left)
            except TimeoutError:
                # one retry through a rotated RC (the commit is idempotent
                # per name: duplicates come back as per-name "exists")
                p2 = dict(p)
                p2["rid"] = self._rid()
                self.m.send(next(self._rc_rr), self._stamp(p2))
                resp = self._await(p2["rid"], max(deadline - time.monotonic(), 0.5))
            results.update(resp.get("results") or {})
        return {"ok": all(r.get("ok") for r in results.values()) and bool(results),
                "results": results}

    def delete(self, name: str, timeout: float = 15.0) -> dict:
        resp = self._rpc_rc(pkt.delete_service_name(name, 0), timeout,
                            name=name)
        with self._lock:
            self._actives.pop(name, None)
            self._route_cache.pop(name, None)
        return resp

    def reconfigure(self, name: str, new_actives: List[str],
                    timeout: float = 20.0) -> dict:
        resp = self._rpc_rc(pkt.client_reconfigure(name, new_actives, 0),
                            timeout, name=name)
        with self._lock:
            self._actives.pop(name, None)
            self._route_cache.pop(name, None)
        return resp

    # ------------------------------------------------------ node elasticity
    def add_active(self, node: str, host: str, port: int,
                   timeout: float = 15.0) -> dict:
        """Admin: add an active node to the deployment's pool
        (ReconfigureActiveNodeConfig analog)."""
        resp = self._rpc_rc({"type": pkt.ADD_ACTIVE, "node": node,
                             "addr": [host, port]}, timeout)
        if resp.get("ok"):
            self.nodemap.add(node, host, port)
        return resp

    def remove_active(self, node: str, timeout: float = 15.0) -> dict:
        resp = self._rpc_rc({"type": pkt.REMOVE_ACTIVE, "node": node}, timeout)
        with self._lock:
            self._actives.clear()  # placements may be migrating
        return resp

    def add_reconfigurator(self, node: str, host: str, port: int,
                           timeout: float = 15.0) -> dict:
        """Admin: splice a reconfigurator into the RC pool at runtime
        (ReconfigureRCNodeConfig analog, Reconfigurator.java:1044)."""
        resp = self._rpc_rc({"type": pkt.ADD_RC, "node": node,
                             "addr": [host, port]}, timeout)
        if resp.get("ok"):
            self.nodemap.add(node, host, port)
            if node not in self.rc_ids:
                self.rc_ids.append(node)
                self._rc_rr = itertools.cycle(sorted(self.rc_ids))
        return resp

    def remove_reconfigurator(self, node: str, timeout: float = 15.0) -> dict:
        resp = self._rpc_rc({"type": pkt.REMOVE_RC, "node": node}, timeout)
        if resp.get("ok") and node in self.rc_ids:
            self.rc_ids.remove(node)
            self._rc_rr = itertools.cycle(sorted(self.rc_ids))
        return resp

    def request_actives(self, name: str, timeout: float = 10.0,
                        force: bool = False) -> List[str]:
        with self._lock:
            hit = self._actives.get(name)
            if hit is not None and not force and hit[0] > time.monotonic():
                return list(hit[1])
        # cell router fast path: static hash placement + the override map
        # IS the directory, so the owner cell's actives come back with zero
        # RC round-trips (and a migrated name resolves even though the
        # destination cell's RC never heard of it).  force falls through —
        # a failing name deserves the authoritative RC answer.
        t = self.placement_table
        if t is not None and not force and name != pkt.ALL_ACTIVES:
            fn = getattr(t, "actives_of", None)
            if fn is not None:
                acts = fn(name)
                if acts:
                    return list(acts)
        resp = self._rpc_rc(pkt.request_active_replicas(name, 0), timeout,
                            name=name)
        if not resp.get("ok"):
            # a migrated name is unknown to its destination cell's RC (the
            # move rode the epoch machinery, not an RC create) — the router
            # override is the directory of record, so answer from it
            if t is not None and name != pkt.ALL_ACTIVES:
                fn = getattr(t, "actives_of", None)
                if fn is not None:
                    acts = fn(name)
                    if acts:
                        return list(acts)
            raise ClientError(resp.get("error", "unknown_name"))
        actives = resp["actives"]
        for a, addr in resp.get("addrs", {}).items():
            if self.nodemap(a) is None:
                self.nodemap.add(a, addr[0], int(addr[1]))
        with self._lock:
            self._actives[name] = (time.monotonic() + self.actives_ttl_s, actives)
        return list(actives)

    # ----------------------------------------------------------- app requests
    def attach_placement(self, table) -> None:
        """Wire a ``PlacementTable`` after construction (deployment wiring
        may build the client before the table exists)."""
        self.placement_table = table

    def _route(self, name: str, actives: List[str], avoid=()) -> str:
        """Placement-table answer when present, RC answer otherwise.

        A name with a migration override routes to the override's server
        even when it is not (yet) in the cached actives list — the table is
        newer truth than the RC answer, so a migrated group's requests reach
        the new shard without an RC round-trip.  Names without an override
        (and overrides whose server has already failed this request) fall
        through to the RTT redirector over the RC's actives.

        The pick is memoized per name, keyed by the table's version epoch:
        a placement/cell override committed anywhere bumps the epoch and
        every cached route re-resolves on next use (stale routes otherwise
        chase a migrated group through a full error round-trip first).  A
        target that failed this request (``avoid``) bypasses and drops the
        memo — the redirect path."""
        t = self.placement_table
        epoch = getattr(t, "epoch", None) if t is not None else None
        if epoch is not None:
            with self._lock:
                hit = self._route_cache.get(name)
                if hit is not None:
                    if (not avoid and hit[0] == epoch
                            and (hit[1] in actives
                                 or self.nodemap(hit[1]) is not None)):
                        return hit[1]
                    del self._route_cache[name]  # epoch bump / failed target
        target = None
        if t is not None:
            lead = t.lead_server(name)
            if (lead is not None and lead not in avoid
                    and (lead in actives or self.nodemap(lead) is not None)):
                target = lead
        if target is None:
            target = self._pick_active(actives, avoid)
        if epoch is not None and not avoid:
            with self._lock:
                self._route_cache[name] = (epoch, target)
                while len(self._route_cache) > self._route_cache_cap:
                    self._route_cache.pop(next(iter(self._route_cache)))
        return target

    def _drop_route(self, name: str) -> None:
        """Invalidate the name's memoized route + actives cache (cell-moved
        redirect, failed target): the next request re-resolves."""
        with self._lock:
            self._route_cache.pop(name, None)
            self._actives.pop(name, None)

    def _resolve_backoff_sleep(self, name: str) -> None:
        """Per-name full-jitter exponential backoff between re-resolution
        attempts (the _rpc_rc scheme, keyed by name): every client chasing
        one migrated group must not re-resolve in lockstep."""
        with self._lock:
            bo = self._route_backoff.get(name, 0.05)
            self._route_backoff[name] = min(bo * 2, 2.0)
        time.sleep(random.uniform(0.0, bo))

    def _resolve_backoff_reset(self, name: str) -> None:
        with self._lock:
            self._route_backoff.pop(name, None)

    def _pick_active(self, actives: List[str], avoid=()) -> str:
        """Lowest-EWMA-RTT active, with epsilon exploration so a recovered
        replica gets re-measured (E2ELatencyAwareRedirector's probe idea).
        ``avoid``: targets that already failed THIS request (e.g. answered
        not_active while still birthing the epoch) — excluded unless that
        empties the pool."""
        pool = [a for a in actives if a not in avoid] or list(actives)
        # breaker screen (non-consuming): skip destinations in cooloff.
        # Fail open when every candidate's breaker is open — some target
        # must carry the probe that lets a breaker half-open and close.
        with self._lock:
            live = [a for a in pool
                    if a not in self._breakers or self._breakers[a].allow()]
        pool = live or pool
        unknown = [a for a in pool if a not in self._rtt]
        if unknown or random.random() < self.explore_prob:
            return random.choice(unknown or pool)
        return min(pool, key=lambda a: self._rtt.get(a, float("inf")))

    def send_request(
        self,
        name: str,
        payload: bytes,
        callback: Callable[[dict], None],
        active: Optional[str] = None,
    ) -> int:
        """Fire one app request; the callback gets the raw response packet
        (``ok``/``response``/``error``).  Actives must be resolvable."""
        target = active or self._route(name, self.request_actives(name))
        rid = self._rid()
        now = time.monotonic()
        with self._lock:
            if len(self._callbacks) > 4096:
                dead = [r for r, d in self._cb_deadline.items() if d < now]
                for r in dead:
                    self._callbacks.pop(r, None)
                    self._cb_deadline.pop(r, None)
                    self._sent_at.pop(r, None)
            self._callbacks[rid] = callback
            self._cb_deadline[rid] = now + self._cb_ttl_s
            self._sent_at[rid] = (target, now)
        p = pkt.app_request(name, payload, rid)
        p["deadline"] = self._wire_deadline()
        self.m.send(target, self._stamp(p), cls=_overload.CLS_CLIENT)
        return rid

    def send_read(
        self,
        name: str,
        payload: bytes,
        callback: Callable[[dict], None],
        active: Optional[str] = None,
    ) -> int:
        """Fire one linearizable READ (ISSUE 17): travels CLS_READ end to
        end and is answered from the lease holder's local state when the
        server's lease validates (response carries ``local: true``), else
        through a consensus round.  ``payload`` must be side-effect-free
        under the app.  The callback gets the raw response packet."""
        target = active or self._route(name, self.request_actives(name))
        rid = self._rid()
        now = time.monotonic()
        with self._lock:
            if len(self._callbacks) > 4096:
                dead = [r for r, d in self._cb_deadline.items() if d < now]
                for r in dead:
                    self._callbacks.pop(r, None)
                    self._cb_deadline.pop(r, None)
                    self._sent_at.pop(r, None)
                    self._read_rids.discard(r)
            self._callbacks[rid] = callback
            self._cb_deadline[rid] = now + self._cb_ttl_s
            self._sent_at[rid] = (target, now)
            self._read_rids.add(rid)
        p = pkt.app_read(name, payload, rid)
        p["deadline"] = self._wire_deadline()
        self.m.send(target, self._stamp(p), cls=_overload.CLS_READ)
        return rid

    def _batch_rtt(self, bid) -> None:
        """Per-frame RTT sample for the redirector's EWMA."""
        ent = None
        with self._lock:
            ent = self._batch_sent.pop(bid, None)
        if ent is None:
            return
        target, t0 = ent
        rtt = time.monotonic() - t0
        self._batch_lat_h.observe(rtt)
        with self._lock:
            prev = self._rtt.get(target)
            self._rtt[target] = (rtt if prev is None
                                 else 0.875 * prev + 0.125 * rtt)

    def _on_batch_response(self, sender: str, p: dict) -> None:
        """Fan a batched response frame back out to the per-rid callbacks
        (same completion semantics as APP_RESPONSE, one frame for all)."""
        self._batch_rtt(p.get("bid"))
        for rid, ok, body in p.get("results") or []:
            if ok:
                self._on_response(sender, {"type": pkt.APP_RESPONSE,
                                           "rid": rid, "ok": True,
                                           "response": body})
            else:
                self._on_response(sender, {"type": pkt.APP_RESPONSE,
                                           "rid": rid, "ok": False,
                                           "error": body})

    def _stage_batch(self, items, callback, active):
        """Shared staging for the batch senders: group by target, assign
        rids, register callbacks, allocate batch ids.  Returns
        (by_target dict, rids in item order, first bid)."""
        by_target: Dict[str, list] = {}
        rids: List[int] = []
        now = time.monotonic()
        # one target per unique NAME per batch: rolling the epsilon-greedy
        # pick per item would fan a single hot name across several actives
        # and defeat the coalescing this path exists for
        target_of: Dict[str, str] = {}
        for name, payload in items:
            target = active or target_of.get(name)
            if target is None:
                target = self._route(name, self.request_actives(name))
                target_of[name] = target
            rid = self._rid()
            rids.append(rid)
            by_target.setdefault(target, []).append((name, rid, payload))
        with self._lock:
            if len(self._callbacks) > 4096:
                # same expired-callback sweep as send_request: lost
                # responses must not grow the maps without bound
                dead = [r for r, d in self._cb_deadline.items() if d < now]
                for r in dead:
                    self._callbacks.pop(r, None)
                    self._cb_deadline.pop(r, None)
                    self._sent_at.pop(r, None)
            ttl = now + self._cb_ttl_s
            for target, reqs in by_target.items():
                for _name, rid, _p in reqs:
                    self._callbacks[rid] = callback
                    self._cb_deadline[rid] = ttl
                    # no per-rid _sent_at: batch responses arrive as one
                    # columnar frame — per-request RTT attribution would
                    # cost a dict write per request on the hot path for a
                    # signal the redirector only needs per target
            bid = self._next_bid
            self._next_bid += len(by_target)
            # ONE per-frame RTT sample per target instead: batched traffic
            # must keep feeding the redirector's EWMA or a once-penalized
            # (since recovered) replica could never be re-measured
            for i, target in enumerate(by_target):
                self._batch_sent[bid + i] = (target, now)
            while len(self._batch_sent) > 1024:
                self._batch_sent.popitem(last=False)
        return by_target, rids, bid

    def send_request_batch(
        self,
        items,
        callback: Callable[[dict], None],
        active: Optional[str] = None,
    ) -> List[int]:
        """Fire many app requests in ONE frame per target active (the
        client half of the reference's request batching,
        RequestPacket.java:189-233).  ``items``: (name, payload) pairs;
        ``callback`` gets each raw per-request response packet.  Returns
        the assigned rids in item order."""
        by_target, rids, bid = self._stage_batch(items, callback, active)
        dl = self._wire_deadline()
        for i, (target, reqs) in enumerate(by_target.items()):
            p = pkt.app_request_batch(reqs, bid + i)
            p["deadline"] = dl  # one deadline per frame: shared send instant
            self.m.send(target, self._stamp(p), cls=_overload.CLS_CLIENT)
        return rids

    def read(self, name: str, payload: bytes = b"", timeout: float = 15.0,
             tries: int = 4) -> bytes:
        """Sync linearizable read (ISSUE 17): :meth:`request`'s
        redirection/retry loop over the CLS_READ wire path.  Lease-local
        on the server when valid, consensus fallback otherwise — either
        way the answer reflects every acked write.  ``payload`` must be
        side-effect-free under the app (it may execute once locally or R
        times via the fallback; retries are harmless)."""
        return self.request(name, payload, timeout, tries,
                            _mk=pkt.app_read, _cls=_overload.CLS_READ)

    def request(self, name: str, payload: bytes, timeout: float = 15.0,
                tries: int = 4, _mk=None, _cls=None) -> bytes:
        """Sync request with redirection: on not_active/stopped, invalidate
        the cache, re-resolve and retry (the client's reconfiguration-chase
        loop).

        Retransmissions reuse the SAME rid, so a retry to the same active is
        absorbed by its dedup cache instead of committing twice.  A retry to
        a *different* active after a timeout is still at-least-once (the
        original proposal may commit later), matching the reference client's
        semantics — use idempotent requests or app-level dedup if that
        matters.
        """
        per = max(timeout / tries, 0.5)
        last = "timeout"
        mk = _mk or pkt.app_request
        cls = _overload.CLS_CLIENT if _cls is None else _cls
        rid = self._rid()  # one rid for every attempt (retransmission dedup)
        if cls == _overload.CLS_READ:
            with self._lock:
                self._read_rids.add(rid)  # RTT sample -> read histogram
        # one wire deadline for the whole request: every attempt carries it,
        # and any stage that sees it expired drops the work instead of
        # finishing it for a caller that already gave up
        wire_deadline = _overload.deadline_at(timeout)
        self.retry_budget.deposit()  # fresh request funds the retry budget
        bad: set = set()  # targets that failed this request (rotate away:
        # after an epoch change one member may still be birthing the group,
        # and RTT-greedy picking would hammer it until the budget dies)
        # only overload signals (timeout, busy) spend the retry budget: a
        # not_active/stopped/wrong_cell redirect is a fast rejection from a
        # healthy node — chasing a migrated group must not starve the budget
        charge_retry = False
        try:
            for attempt in range(tries):
                if (attempt > 0 and charge_retry
                        and not self.retry_budget.take()):
                    # budget dry: fail fast rather than amplify a brownout
                    raise TimeoutError(
                        f"{name}: retry budget exhausted ({last})")
                try:
                    actives = self.request_actives(name, force=attempt > 0)
                except ClientError as e:
                    raise ClientError(f"{name}: {e}") from e
                target = self._route(name, actives, avoid=bad)
                with self._lock:
                    self._sent_at[rid] = (target, time.monotonic())
                p = mk(name, payload, rid)
                p["deadline"] = wire_deadline
                self.m.send(target, self._stamp(p), cls=cls)
                try:
                    resp = self._await(rid, per)
                except TimeoutError:
                    last = f"timeout via {target}"
                    charge_retry = True
                    self._penalize(target, per)
                    self._breaker(target).record(False)
                    bad.add(target)
                    self._drop_route(name)
                    self._resolve_backoff_sleep(name)
                    continue
                if resp.get("ok"):
                    self._resolve_backoff_reset(name)
                    self._breaker(target).record(True)
                    return pkt.b64d(resp["response"]) or b""
                last = resp.get("error", "error")
                # busy = the destination is shedding (overload NACK): a
                # breaker failure.  Other rejections mean the node is alive
                # and fast — routing signals, not overload.
                self._breaker(target).record(last != "busy")
                charge_retry = last == "busy"
                if last not in ("not_active", "stopped", "busy",
                                "wrong_cell"):
                    raise ClientError(f"{name}: {last}")
                # the target disowned the name (epoch change, cell move):
                # drop the memoized route and re-resolve under per-name
                # exponential backoff instead of a fixed lockstep sleep
                bad.add(target)
                self._drop_route(name)
                self._resolve_backoff_sleep(name)
            raise TimeoutError(f"{name}: {last}")
        finally:
            # a late response from an earlier attempt's target leaves the
            # newest _sent_at entry unconsumed (sender mismatch keeps it);
            # the sync path owns this rid end-to-end, so always reap it
            # (trace ids ride the rid too)
            self._reap(rid)

    def _penalize(self, target: str, timeout_s: float) -> None:
        """Feed a timeout into the target's EWMA as a huge latency sample —
        without this, a dead replica keeps its excellent pre-crash RTT and
        the lowest-RTT redirector keeps picking it forever (the reference's
        redirector learns failed probes the same way,
        E2ELatencyAwareRedirector.java:18)."""
        with self._lock:
            prev = self._rtt.get(target, 0.0)
            self._rtt[target] = max(prev * 4, timeout_s)

    def request_anycast(self, name: str, payload: bytes,
                        timeout: float = 15.0, tries: int = 4) -> bytes:
        """Send WITHOUT resolving the name's replica set: resolve the whole
        active pool once (cached) and send to a random active; a non-hosting
        active forwards to a hosting one, which answers us directly
        (sendRequestAnycast, ReconfigurableAppClientAsync.java:1357)."""
        per = max(timeout / tries, 0.5)
        last = "timeout"
        rid = self._rid()
        wire_deadline = _overload.deadline_at(timeout)
        self.retry_budget.deposit()
        charge_retry = False  # same rule as request(): redirects retry free
        try:
            for attempt in range(tries):
                if (attempt > 0 and charge_retry
                        and not self.retry_budget.take()):
                    raise TimeoutError(
                        f"{name}: retry budget exhausted ({last})")
                pool = self.request_actives(pkt.ALL_ACTIVES,
                                            force=attempt > 0)
                with self._lock:
                    live = [a for a in pool
                            if a not in self._breakers
                            or self._breakers[a].allow()]
                target = random.choice(live or pool)
                p = pkt.app_request(name, payload, rid)
                p["anycast"] = True
                p["deadline"] = wire_deadline
                with self._lock:
                    self._sent_at[rid] = (target, time.monotonic())
                self.m.send(target, self._stamp(p), cls=_overload.CLS_CLIENT)
                try:
                    resp = self._await(rid, per)
                except TimeoutError:
                    last = f"timeout via {target}"
                    charge_retry = True
                    self._penalize(target, per)
                    self._breaker(target).record(False)
                    continue
                if resp.get("ok"):
                    self._breaker(target).record(True)
                    return pkt.b64d(resp["response"]) or b""
                last = resp.get("error", "error")
                self._breaker(target).record(last != "busy")
                charge_retry = last == "busy"
                if last not in ("not_active", "stopped", "busy"):
                    raise ClientError(f"{name}: {last}")
                time.sleep(min(0.1 * (attempt + 1), 0.5))
            raise TimeoutError(f"{name}: {last}")
        finally:
            self._reap(rid)

    def _on_binary_batch_response(self, sender: str, buf: bytes) -> None:
        """Columnar response frame -> per-rid callbacks.  One lock
        acquisition covers the whole frame's bookkeeping."""
        _bid, rids, statuses, bodies = binbatch.decode_response(buf)
        self._batch_rtt(_bid)
        fire = []
        with self._lock:
            for rid, ok, body in zip(rids, statuses, bodies):
                rid = int(rid)
                self._sent_at.pop(rid, None)
                cb = self._callbacks.pop(rid, None)
                self._cb_deadline.pop(rid, None)
                if cb is not None:
                    if ok:
                        fire.append((cb, {"type": pkt.APP_RESPONSE,
                                          "rid": rid, "ok": True,
                                          "response_raw": body}))
                    else:
                        fire.append((cb, {"type": pkt.APP_RESPONSE,
                                          "rid": rid, "ok": False,
                                          "error": body.decode(
                                              "utf-8", "replace")}))
        for cb, p in fire:
            cb(p)

    def send_request_batch_binary(
        self,
        items,
        callback: Callable[[dict], None],
        active: Optional[str] = None,
    ) -> List[int]:
        """Binary twin of :meth:`send_request_batch` (net/binbatch.py SoA
        frames).  Successful responses carry raw bytes under
        ``response_raw`` (no base64 round-trip)."""
        by_target, rids, bid = self._stage_batch(items, callback, active)
        dl = self._wire_deadline()
        for i, (target, reqs) in enumerate(by_target.items()):
            self.m.send_bytes(target, binbatch.encode_request(
                bid + i, self.addr[0], self.addr[1], self.node_id, reqs,
                deadline=dl,
            ), cls=_overload.CLS_CLIENT)
        return rids

    def batching(self, max_batch: int = 128,
                 flush_interval_s: float = 0.002,
                 binary: bool = True) -> "BatchingSender":
        """A coalescing sender bound to this client (see BatchingSender)."""
        return BatchingSender(self, max_batch, flush_interval_s, binary)

    # ------------------------------------------------------------------ echo
    def echo(self, active: str, timeout: float = 5.0) -> float:
        """RTT-probe one active (handleEchoRequest analog); returns seconds."""
        rid = self._rid()
        t0 = time.monotonic()
        self.m.send(active, self._stamp({
            "type": pkt.ECHO_REQUEST, "ts": t0, "rid": rid,
        }))
        self._await(rid, timeout)
        rtt = time.monotonic() - t0
        prev = self._rtt.get(active)
        self._rtt[active] = rtt if prev is None else 0.875 * prev + 0.125 * rtt
        return rtt


class BatchingSender:
    """Auto-coalescing request front: submitted requests accumulate for up
    to ``flush_interval_s`` (or ``max_batch``) and leave as ONE
    APP_REQUEST_BATCH frame per target active — the client-side
    ``RequestBatcher`` (gigapaxos/RequestBatcher.java:25-60; batched
    RequestPacket, paxospackets/RequestPacket.java:189-233).  Per-frame JSON
    + syscall cost amortizes across the batch, which is what moves the
    loopback capacity knee (testing/capacity.py --batch).
    """

    def __init__(self, client: ReconfigurableAppClient, max_batch: int = 128,
                 flush_interval_s: float = 0.002, binary: bool = True):
        self.c = client
        self.max_batch = max_batch
        self.interval = flush_interval_s
        self.binary = binary
        self._buf: list = []  # (name, payload, callback)
        self._lock = threading.Lock()
        self._closed = False
        self._flusher = threading.Thread(target=self._run, daemon=True,
                                         name="batch-flusher")
        self._flusher.start()

    def submit(self, name: str, payload: bytes,
               callback: Callable[[dict], None]) -> None:
        flush_now = False
        with self._lock:
            self._buf.append((name, payload, callback))
            if len(self._buf) >= self.max_batch:
                flush_now = True
        if flush_now:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf:
            return
        # per-request callbacks ride the shared dispatcher; the rid->cb map
        # fills after the send returns (the loopback short-circuit can
        # deliver a response before this thread runs the fill loop).  Early
        # responses are BUFFERED, never block the client's demux thread —
        # a stalled send must not freeze unrelated responses for this
        # client, and a slow fill must not drop callbacks.
        cbs = {}
        early: list = []
        filled = [False]
        gate = threading.Lock()

        def dispatch(p: dict) -> None:
            with gate:
                if not filled[0]:
                    early.append(p)
                    return
            cb = cbs.pop(p.get("rid"), None)
            if cb is not None:
                cb(p)

        send = (self.c.send_request_batch_binary if self.binary
                else self.c.send_request_batch)
        try:
            rids = send([(n, pl) for n, pl, _ in buf], dispatch)
        except Exception as e:
            # resolve/send failure must not silently strand the whole
            # buffered batch: every callback gets an error packet.  Open
            # the gate with an empty cb map — a partially-sent batch's
            # real responses must be dropped (their callbacks just fired
            # with the error), not buffered in `early` forever
            with gate:
                filled[0] = True
                early[:] = []
            for _n, _p, cb in buf:
                try:
                    cb({"ok": False, "error": f"{type(e).__name__}: {e}"})
                except Exception:
                    pass
            return
        for rid, (_n, _p, cb) in zip(rids, buf):
            cbs[rid] = cb
        with gate:
            filled[0] = True
            drain, early[:] = early[:], []
        for p in drain:  # delivered on the flusher thread, in arrival order
            cb = cbs.pop(p.get("rid"), None)
            if cb is not None:
                cb(p)

    def _run(self) -> None:
        while not self._closed:
            time.sleep(self.interval)
            try:
                self.flush()
            except Exception:
                pass  # transient resolve/send errors: requests time out

    def close(self) -> None:
        self._closed = True
        self.flush()

"""Per-node WAL + recovery for chain Mode B.

Same shape as the paxos flavor (``modeb/logger.py``): the chain node step is
deterministic given (state, staged frames, placed intake, alive mask), so
the journal records exactly those inputs in arrival order and recovery is
snapshot + in-order replay through the same jitted kernel, followed by
``request_sync()`` to refresh mirrors from live peers.
"""

from __future__ import annotations

import io

import numpy as np

from ..wal import records

from ..modeb.logger import ModeBLogger, replay_node_journals


class ChainBLogger(ModeBLogger):
    """Only the snapshot metadata differs from the paxos flavor — frame/
    ckpt/intake journaling (including the fsync group-commit policy) is
    inherited so durability fixes live in ONE place.  ModeBLogger's
    ``log_inbox`` already reads the shared ``_placed``/``outstanding``/
    ``payloads`` shapes both node flavors expose."""

    def _meta(self, m) -> dict:
        return {
            "tick_num": m.tick_num,
            "members": list(m.members),
            "next_seq": m._next_seq,
            "rows": dict(m.rows.items()),
            "free_rows": list(m.rows._free),
            "row_meta": dict(m._row_meta),
            "stopped_rows": set(m._stopped_rows),
            "tainted_rows": set(m._tainted_rows),
            "payloads": list(m.payloads.items()),
            "outstanding": [
                (r.rid, r.name, r.row, r.payload, r.stop, r.responded,
                 r.born_tick)
                for r in m.outstanding.values()
            ],
            "queues": {row: list(q) for row, q in m._queues.items() if q},
            "frame_applied": dict(m._frame_applied_tick),
            "app": {name: m.app.checkpoint(name) for name in m.rows.names()},
        }


def recover_chain_modeb(cfg, member_ids, node_id, app, log_dir: str,
                        native: bool = True):
    """Rebuild a ChainModeBNode from its own disk; attach a messenger and
    call ``request_sync()`` afterwards to rejoin the chain set."""
    import collections

    import jax.numpy as jnp

    from ..modeb import wire
    from .modeb import (CH_BITS, CH_MAGIC, CH_RINGS, CH_SCALARS,
                        ChainBRecord, ChainModeBNode,
                        unpack_chain_node_tick)
    from .state import ChainState
    from .tick import ChainInbox

    logger = ChainBLogger(log_dir, native=native)
    snap_seq = logger._latest_snapshot_seq()
    meta = npz_blob = None
    if snap_seq is not None:
        with open(logger._snapshot_path(snap_seq), "rb") as f:
            meta, npz_blob = records.loads(f.read())
    # a runtime-expanded universe supersedes the boot topology (see
    # modeb/logger.recover_modeb); journaled OP_EXPANDs extend it further
    members = list(meta.get("members", member_ids)) if meta else member_ids
    node = ChainModeBNode(cfg, members, node_id, app)
    start_seq = 0
    if snap_seq is not None:
        arrs = np.load(io.BytesIO(npz_blob))
        node.state = ChainState(
            **{f: jnp.asarray(arrs[f]) for f in ChainState._fields}
        )
        node.tick_num = meta["tick_num"]
        node._next_seq = meta["next_seq"]
        node.rows.restore(meta["rows"], meta["free_rows"])
        for _row in meta["rows"].values():
            node._occupied[_row] = True  # frame-target mask (anti-entropy)
        node._gid_row = {wire.gid_of(n): row for n, row in meta["rows"].items()}
        node._row_meta = dict(meta["row_meta"])
        node._stopped_rows = set(meta["stopped_rows"])
        node._tainted_rows = set(meta.get("tainted_rows", ()))
        for rid, pl in meta["payloads"]:
            node.payloads[rid] = pl
        for rid, name, row, payload, stop, responded, born in meta[
            "outstanding"
        ]:
            rec = ChainBRecord(rid, name, row, payload, stop, None, born)
            rec.responded = responded
            node.outstanding[rid] = rec
        for row, rids in meta["queues"].items():
            node._queues[int(row)] = collections.deque(rids)
        node._frame_applied_tick = dict(meta["frame_applied"])
        for name, blob in meta["app"].items():
            node.app.restore(name, blob)
        start_seq = snap_seq

    def stage(raw: bytes) -> None:
        node._stage_frame(wire.decode_frame(
            raw, scalar_fields=CH_SCALARS, ring_fields=CH_RINGS,
            bit_fields=CH_BITS, magic=CH_MAGIC,
        ))

    def new_buffers():
        return (np.zeros((node.P, node.G), np.int32),
                np.zeros((node.P, node.G), bool))

    def place(bufs, p, row, rid, stop):
        bufs[0][p, row] = rid
        bufs[1][p, row] = stop

    def run_tick(bufs, alive):
        inbox = ChainInbox(jnp.asarray(bufs[0]), jnp.asarray(bufs[1]),
                           jnp.asarray(alive))
        node.state, packed = node._tick_packed(node.state, inbox)
        return unpack_chain_node_tick(packed, node.R, node.P, node.W, node.G)

    replay_node_journals(node, log_dir, start_seq, stage=stage,
                         new_buffers=new_buffers, place=place,
                         run_tick=run_tick)

    node._flush_mirrors()
    node._held_callbacks = []  # no live clients to answer during replay
    node._await_commit = []  # their clients are gone too; peers re-ack
    # close the rid-regression hole: any rid that could ever commit is
    # visible in some ring or payload/outstanding table (a rid forwarded to
    # the head never enters the local journal as intake)
    node.bump_seq(np.asarray(node.state.c_req))
    node.bump_seq(np.fromiter(node.payloads.keys(), np.int64,
                              len(node.payloads)))
    node.bump_seq(np.fromiter(node.outstanding.keys(), np.int64,
                              len(node.outstanding)))
    logger.attach(node)
    node.wal = logger
    node._force_full = True
    return node

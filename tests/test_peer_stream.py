"""Parallel peer snapshot streaming during recovery (ISSUE 19): a
restarting Mode B node fetches checkpoint blobs from multiple donors
concurrently with its local WAL replay, and adopts them through the
watermark-checked transfer path — missed writes land without waiting for
post-recovery anti-entropy, and stale blobs can never regress state."""

from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.modeb import PeerCheckpointStreamer, recover_modeb
from test_modeb import IDS, Cluster, make_cfg


def _streamer(cl, donors, window=2):
    return PeerCheckpointStreamer(
        {nid: cl.nodes[nid].donate_ckpt for nid in donors}, window=window)


def test_peer_stream_recovers_missed_writes(tmp_path):
    cfg = make_cfg()
    cl = Cluster(cfg, wal_root=tmp_path)
    try:
        cl.create("svc")
        cl.create("svc2")
        cl.commit("N0", "svc", b"PUT a 1")
        cl.commit("N0", "svc2", b"PUT x 9")
        cl.kill("N0")
        cl.drop_backlog("N0")
        for i in range(4):
            cl.commit("N1", "svc", f"PUT b{i} v{i}".encode(),
                      only={"N1", "N2"})
        cl.commit("N1", "svc2", b"PUT y 10", only={"N1", "N2"})

        ps = _streamer(cl, ("N1", "N2"))
        cl.apps["N0"] = KVApp()
        node = recover_modeb(cfg, IDS, "N0", cl.apps["N0"],
                             str(tmp_path / "N0"), native=False,
                             peer_stream=ps)
        # both rows were fetched and adopted (replay alone could not know
        # the writes committed while the node was dead)
        assert ps.stats["fetched"] == 2
        assert ps.stats["applied"] == 2
        assert node.stats["ckpt_transfers"] == 2
        db = cl.apps["N0"].db
        for i in range(4):
            assert db["svc"].get(f"b{i}") == f"v{i}"
        assert db["svc2"].get("y") == "10"
        node.close()
    finally:
        cl.close()


def test_peer_stream_stale_blobs_dropped(tmp_path):
    """A node that crashed with a complete journal replays to the donors'
    watermark — every streamed blob is stale and must be dropped without
    touching state."""
    cfg = make_cfg()
    cl = Cluster(cfg, wal_root=tmp_path)
    try:
        cl.create("svc")
        cl.commit("N0", "svc", b"PUT a 1")
        cl.commit("N0", "svc", b"PUT b 2")
        # quiesce so every node holds the same watermark, then crash N0
        cl.ticks(4)
        cl.kill("N0")
        cl.drop_backlog("N0")

        ps = _streamer(cl, ("N1", "N2"))
        cl.apps["N0"] = KVApp()
        node = recover_modeb(cfg, IDS, "N0", cl.apps["N0"],
                             str(tmp_path / "N0"), native=False,
                             peer_stream=ps)
        assert ps.stats["fetched"] == 1
        assert ps.stats["applied"] == 0
        assert ps.stats["stale"] == 1
        assert node.stats["ckpt_transfers"] == 0
        assert cl.apps["N0"].db["svc"] == {"a": "1", "b": "2"}
        node.close()
    finally:
        cl.close()


def test_peer_stream_donor_failover(tmp_path):
    """A refusing donor (fetch returns None / raises) rotates to the next
    one instead of starving the stream."""
    cfg = make_cfg()
    cl = Cluster(cfg, wal_root=tmp_path)
    try:
        cl.create("svc")
        cl.commit("N0", "svc", b"PUT a 1")
        cl.kill("N0")
        cl.drop_backlog("N0")
        cl.commit("N1", "svc", b"PUT c 3", only={"N1", "N2"})

        def broken(gid):
            raise RuntimeError("donor down")

        ps = PeerCheckpointStreamer(
            {"N1": broken, "N2": cl.nodes["N2"].donate_ckpt}, window=2)
        cl.apps["N0"] = KVApp()
        node = recover_modeb(cfg, IDS, "N0", cl.apps["N0"],
                             str(tmp_path / "N0"), native=False,
                             peer_stream=ps)
        assert ps.stats["fetched"] == 1
        assert ps.stats["applied"] == 1
        assert cl.apps["N0"].db["svc"].get("c") == "3"
        node.close()
    finally:
        cl.close()

"""Read-mostly throughput artifact for the lease plane (ISSUE 17).

The tentpole claim: with leader leases folded into the fused tick, a
95/5 read-mostly workload is served mostly from the lease holder's local
state — no consensus round per read — so sustained op throughput beats
the all-consensus baseline (every read a CLS_READ round through the
ordered stream) by >= 5x on the same plane.

Shape: one dense Mode A plane holding >= 100k live groups (created in
batches, every group elects + takes a lease), with the measured 95/5
traffic on a hot subset (realistic skew) and a uniform local-serve probe
across the full width.  Both legs run the same ``PaxosManager.read``
API; the baseline simply has ``read_leases`` off, which routes every
read through the consensus fallback.  Reported per leg: ops/s, local
read fraction, and read-latency p50/p99.  Gate: ``speedup_x >= 5`` at
``groups >= 100_000``.

Run: ``python benchmarks/read_bench.py [--json PATH] [--quick]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("GPTPU_BENCH_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["GPTPU_BENCH_PLATFORM"])

import numpy as np  # noqa: E402

R = 3
READ_FRAC = 0.95  # exactly 19 reads per write (i % 20 != 0)


def build(leases: bool, groups: int, batch: int = 8192):
    from gigapaxos_tpu.config import GigapaxosTpuConfig
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.paxos.manager import PaxosManager

    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = groups
    cfg.paxos.compact_outbox = True
    cfg.paxos.window = 8
    cfg.paxos.read_leases = leases
    cfg.paxos.lease_ticks = 64
    cfg.paxos.lease_margin_ticks = 8
    m = PaxosManager(cfg, R, [KVApp() for _ in range(R)])
    names = [f"g{i}" for i in range(groups)]
    for i in range(0, groups, batch):
        m.create_paxos_instances(names[i:i + batch], [0, 1, 2])
    return m, names


def drain(m, pending, max_spins=20000):
    spins = 0
    while pending[0] > 0 and spins < max_spins:
        m.tick()
        m.drain_pipeline()
        spins += 1
    return spins


def warm(m, hot_names):
    """Seed the hot set (one committed write each) and let leases grant."""
    pending = [0]

    def cb(r, resp):
        pending[0] -= 1
    for n in hot_names:
        pending[0] += 1
        m.propose(n, b"PUT k 0", cb)
    drain(m, pending)
    m.tick()
    m.drain_pipeline()


def run_leg(m, hot_names, ops_per_round, rounds, seed=0):
    """The measured 95/5 closed-loop: issue a round of ops against the
    hot set, then drive ticks until every callback has fired."""
    rng = np.random.default_rng(seed)
    lat = []
    pending = [0]
    reads = writes = 0
    local0 = m.stats["local_reads"]
    t0 = time.perf_counter()
    for _ in range(rounds):
        gidx = rng.integers(0, len(hot_names), size=ops_per_round)
        for i in range(ops_per_round):
            name = hot_names[int(gidx[i])]
            if i % 20 == 0:  # the 5% write share
                writes += 1
                pending[0] += 1

                def wcb(r, resp, _p=pending):
                    _p[0] -= 1
                m.propose(name, b"PUT k w", wcb)
            else:
                reads += 1
                pending[0] += 1
                ts = time.perf_counter()

                def rcb(r, resp, _p=pending, _ts=ts, _lat=lat):
                    _p[0] -= 1
                    _lat.append(time.perf_counter() - _ts)
                m.read(name, b"GET k", rcb)
        drain(m, pending)
    dt = time.perf_counter() - t0
    lat_ms = np.sort(np.array(lat)) * 1e3
    done = reads + writes - pending[0]
    return {
        "ops": reads + writes,
        "completed": int(done),
        "reads": reads,
        "writes": writes,
        "seconds": round(dt, 3),
        "ops_per_s": round(done / dt, 1),
        "local_reads": int(m.stats["local_reads"] - local0),
        "local_read_fraction": round(
            (m.stats["local_reads"] - local0) / max(reads, 1), 4),
        "read_p50_ms": round(float(lat_ms[len(lat_ms) // 2]), 4),
        "read_p99_ms": round(float(lat_ms[int(len(lat_ms) * 0.99)]), 4),
    }


def uniform_probe(m, names, n=4096, seed=1):
    """Local-serve fraction across the FULL plane width: every created
    group elects and takes a lease, so uniform reads serve locally too."""
    rng = np.random.default_rng(seed)
    local0 = m.stats["local_reads"]
    pending = [0]

    def cb(r, resp):
        pending[0] -= 1
    for i in rng.integers(0, len(names), size=n):
        pending[0] += 1
        m.read(names[int(i)], b"GET k", cb)
    drain(m, pending)
    return {
        "reads": int(n),
        "local_fraction": round((m.stats["local_reads"] - local0) / n, 4),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None,
                    help="also write the artifact to this path")
    ap.add_argument("--groups", type=int, default=1 << 17,
                    help="live groups on the plane (gate needs >= 100k)")
    ap.add_argument("--hot", type=int, default=256,
                    help="hot-set size carrying the 95/5 traffic")
    ap.add_argument("--ops", type=int, default=1 << 15,
                    help="ops per measured round")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--quick", action="store_true",
                    help="small shapes for smoke testing")
    args = ap.parse_args()
    if args.quick:
        args.groups, args.hot = 1 << 12, 64
        args.ops, args.rounds = 1 << 11, 2

    legs = {}
    for leases, key in ((True, "leases"), (False, "all_consensus")):
        m, names = build(leases, args.groups)
        hot = names[:args.hot]
        warm(m, hot)
        legs[key] = run_leg(m, hot, args.ops, args.rounds)
        if leases:
            legs["uniform_probe"] = uniform_probe(m, names)
        del m

    speedup = legs["leases"]["ops_per_s"] / legs["all_consensus"]["ops_per_s"]
    result = {
        "metric": "read_mostly_95_5_speedup_over_all_consensus",
        "value": round(speedup, 2),
        "unit": "x ops/s (gate >= 5x at >= 100k groups)",
        "platform": jax.devices()[0].platform,
        "groups": args.groups,
        "hot_groups": args.hot,
        "read_fraction": READ_FRAC,
        "leases": legs["leases"],
        "all_consensus": legs["all_consensus"],
        "uniform_probe": legs["uniform_probe"],
        "gate_pass": bool(speedup >= 5.0 and args.groups >= 100_000),
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
        result["written"] = args.json
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""Serving-cell worker process: one crash-isolated Mode A cluster per core.

A cell is a full :class:`~gigapaxos_tpu.node.InProcessCluster` (dense-device
data plane + RC plane) running in its own OS process, pinned to one CPU
core, owning a static shard of the group space (``routing.cell_of``), with
its own WAL directories and transport endpoints.  The process is spawned and
supervised by :class:`~gigapaxos_tpu.cells.supervisor.CellSupervisor`; node
ids are cell-qualified (``c{k}.AR0``, ``c{k}.RC0``) so every cell's
endpoints coexist in one merged NodeConfig for clients.

Spawned as ``python -m gigapaxos_tpu.cells.worker '<spec json>'`` with::

  {"cell": 0, "n_cells": 2,
   "actives": {"c0.AR0": ["127.0.0.1", p]},        # THIS cell's nodes only
   "reconfigurators": {"c0.RC0": ["127.0.0.1", p]},
   "peers": {"c1.AR0": [...], "SUP": [...]},       # other cells + supervisor
   "wal_dir": "...", "rc_wal_dir": "...",
   "core": 0,                                       # sched_setaffinity pin
   "edge": ["127.0.0.1", p],                        # SO_REUSEPORT shared edge
   "overrides": {"name": 1},                        # migrated-name directory
   "paxos": {"max_groups": 16},                     # cfg.paxos attr overrides
   "cfg": {"native_journal": true},                 # top-level cfg overrides
   "ledger": true,                                  # record (r,name,slot,rid)
   "flight": ".../flight.json",                     # crash recorder artifact
   "stats_interval_s": 2.0,                         # StatsReporter cadence
   "drain_timeout_s": 10.0}

Line protocol on stdin/stdout (the Mode B worker's idiom, extended):

  create <name>                 -> "created <name>" (direct local create)
  propose <name> <hex>          -> (async) "resp <rid> <hex|NONE>"
  db [r]                        -> "db <json>" (replica r's app state)
  stats                         -> "stats <json>"
  metrics                       -> "metrics <json>" (Prometheus text body,
                                    every series labelled cell="k")
  trace [tid]                   -> "trace <json>" (cross-process trace dump)
  flight                        -> "flight <path>" (force a recorder dump)
  ledger                        -> "ledger <json>" (execution observations)
  drain                         -> "drained ok|timeout"
  override <name> <cell>        -> "override_ok <name>" (edge routing)
  migrate_out <name>            -> "migrated_out <name> <epoch> <hex>"
  migrate_in <name> <ep> <hex>  -> "migrated_in <name> <ep>"
  migrate_drop <name> <ep>      -> "migrate_dropped <name>"
  exit                          -> graceful shutdown, process exits

SIGTERM triggers the graceful path (drain in-flight tick, flush + close
WAL, close transports); SIGKILL emulates a core crash — the supervisor
restarts the cell against the same WAL dirs and replay rebuilds it.
"""

import json
import os
import sys
import threading
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# --------------------------------------------------------------- S1 ledger
#: execution observations [r, name, slot, rid, is_stop] — appended by the
#: class-level `_execute_one` wrap BELOW the WAL replay, so a restarted
#: worker's ledger covers replayed history too (tests feed pre-kill and
#: post-restart dumps into testing.chaos.SafetyLedger and assert no
#: (name, slot) ever decided two rids across the crash)
_LEDGER: list = []
_LEDGER_LOCK = threading.Lock()


def _install_ledger() -> None:
    from gigapaxos_tpu.paxos import manager as mgr_mod

    orig = mgr_mod.PaxosManager._execute_one

    def _observed(self, r, row, name, rid, slot, is_stop):
        with _LEDGER_LOCK:
            _LEDGER.append([int(r), str(name), int(slot), int(rid),
                            bool(is_stop)])
        return orig(self, r, row, name, rid, slot, is_stop)

    mgr_mod.PaxosManager._execute_one = _observed


def _pin_core(core) -> None:
    if core is None or not hasattr(os, "sched_setaffinity"):
        return
    try:
        ncpu = os.cpu_count() or 1
        os.sched_setaffinity(0, {int(core) % ncpu})
    except OSError:
        pass  # cgroup-restricted masks: run unpinned rather than die


def main() -> None:
    spec = json.loads(sys.argv[1])
    cell = int(spec["cell"])
    n_cells = int(spec.get("n_cells", 1))
    _pin_core(spec.get("core"))
    if spec.get("ledger"):
        _install_ledger()

    from gigapaxos_tpu import overload as _overload
    from gigapaxos_tpu.config import GigapaxosTpuConfig
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.net.failure_detection import FailureDetection
    from gigapaxos_tpu.net.messenger import Messenger
    from gigapaxos_tpu.node import InProcessCluster
    from gigapaxos_tpu.obs import registry as obs_registry
    from gigapaxos_tpu.obs.flight import FlightRecorder
    from gigapaxos_tpu.obs.prom import render_registry
    from gigapaxos_tpu.reconfiguration import packets as pkt
    from gigapaxos_tpu.utils import reqtrace
    from gigapaxos_tpu.utils.observability import (StatsReporter,
                                                   node_stats_source,
                                                   shard_load_source,
                                                   transport_stats_source)

    from .routing import cell_of

    cfg = GigapaxosTpuConfig()
    for k, v in (spec.get("paxos") or {}).items():
        setattr(cfg.paxos, k, v)
    for k, v in (spec.get("cfg") or {}).items():
        setattr(cfg, k, v)
    cfg.nodes.actives = {n: tuple(a) for n, a in spec["actives"].items()}
    cfg.nodes.reconfigurators = {
        n: tuple(a) for n, a in spec["reconfigurators"].items()
    }

    out_lock = threading.Lock()

    def emit(line: str) -> None:
        with out_lock:
            sys.stdout.write(line + "\n")
            sys.stdout.flush()

    # a restart against a WAL directory that already holds state replays
    # before serving: note it so the timeline shows the recovery span and
    # healthz can report how much history was rolled forward
    def _has_state(d: str) -> bool:
        return os.path.isdir(d) and any(
            fn.startswith(("journal.", "snapshot.")) for fn in os.listdir(d))

    recovering = _has_state(spec["wal_dir"]) or _has_state(spec["rc_wal_dir"])
    recovery_t0 = time.time()
    try:
        cluster = InProcessCluster(
            cfg, KVApp,
            replicas_per_name=len(cfg.nodes.actives),
            rc_group_size=len(cfg.nodes.reconfigurators),
            wal_dir=spec["wal_dir"],
            rc_wal_dir=spec["rc_wal_dir"],
        )
    except Exception as e:  # startup must be observable, not a silent death
        emit(f"startup_failed {type(e).__name__}: {e}")
        sys.exit(1)
    recovery_t1 = time.time()

    # other cells' endpoints + the supervisor: reachable for edge forwarding
    # and control pings, but NOT part of this cell's consensus topology
    for nid, (host, port) in (spec.get("peers") or {}).items():
        cluster.nodemap.add(nid, host, int(port))

    active_ids = sorted(cluster.actives)
    ar0 = cluster.actives[active_ids[0]]
    # answering the supervisor's EWMA heartbeats only needs the PING handler
    # a (non-monitoring) detector registers on AR0's messenger
    fd = FailureDetection(ar0.m, monitored=())

    # ------------------------------------------------- flight deck
    # crash flight recorder: a SIGKILL'd cell leaves its last ring of
    # stats snapshots and events on disk for the supervisor/chaos log
    flight_path = spec.get("flight") or os.path.join(
        os.path.dirname(spec["wal_dir"]), "flight.json")
    flight = FlightRecorder(flight_path, cap=cfg.obs.flight_cap,
                            node=f"c{cell}")
    flight.install_signal()      # SIGUSR2 -> on-demand dump
    flight.install_excepthook()  # crash-by-exception -> dump
    flight.record("boot", cell=cell, pid=os.getpid(),
                  core=spec.get("core"))

    # storage fail-stop: a WalFailedError/WalQuarantinedError escaping a
    # tick loop means the WAL can no longer make acks durable — dump the
    # flight ring and die nonzero so the supervisor restarts this cell
    # onto intact storage (replay re-derives state from what DID reach
    # disk; anything unacked is the client's retry)
    from gigapaxos_tpu.paxos import driver as _tick_driver_mod

    def _wal_failstop(exc: BaseException) -> None:
        flight.record("wal_failstop", error=f"{type(exc).__name__}: {exc}")
        flight.dump("wal_failstop")
        emit(f"wal_failstop {type(exc).__name__}: {exc}")
        os._exit(3)

    _tick_driver_mod.FATAL_HANDLER = _wal_failstop
    # group-health transitions (newly wedged/recovered, top-K churn) land
    # in the same ring — a SIGKILL'd cell's dump names its sick groups
    cluster.manager.flight = flight
    reporter = StatsReporter(
        f"c{cell}", interval_s=float(spec.get("stats_interval_s", 2.0)),
        sink=flight.snapshot_sink)
    reporter.add_source("ar", node_stats_source(cluster.manager))
    reporter.add_source("rc", node_stats_source(cluster.rc_manager))
    reporter.add_source("transport", transport_stats_source(ar0.m.transport))
    reporter.add_source("shards", shard_load_source(cluster.manager))
    reporter.start()

    # scenario timeline (ISSUE 18): sampled metric series vs wall clock,
    # with event annotations; the supervisor merges every cell's snapshot
    # into one host-level /timeline body (ROADMAP item 5's instrument)
    from gigapaxos_tpu.obs.timeline import TimelineRecorder, registry_sampler

    timeline = TimelineRecorder(
        registry_sampler(
            "health_backlogged_groups", "health_wedged_groups",
            "overload_admission_shed_total", "overload_expired_drops_total",
            "reads_local_total", "tick_seconds"),
        interval_s=float(spec.get("timeline_interval_s", 0.25)),
        node=f"c{cell}")
    timeline.start()
    timeline.annotate("boot", cell=cell, pid=os.getpid())
    if recovering:
        # the replay ran before the recorder existed; the annotations carry
        # their own wall times so the span still renders correctly
        rep = obs_registry().gauge("wal_replay_records_done").value
        timeline.annotate("recovery_start", cell=cell, at=recovery_t0)
        timeline.annotate("recovery_finish", cell=cell, at=recovery_t1,
                          seconds=recovery_t1 - recovery_t0,
                          records=int(rep))
    # readiness state for the healthz command (503 while draining or after
    # a sticky WAL failure — supervisors stop routing, diagnostics stay up)
    ready_state = {"draining": False}

    def _healthz_doc() -> dict:
        wal_failed = any(
            getattr(getattr(p, "wal", None), "failed", False)
            for p in (cluster.manager, cluster.rc_manager))
        return {
            "ok": not ready_state["draining"] and not wal_failed,
            "cell": cell,
            "tick": int(cluster.manager.tick_num),
            "draining": ready_state["draining"],
            "wal_failed": wal_failed,
            # a worker answering this RPC is past replay by construction;
            # mid-replay the supervisor reads the replay_progress.json
            # sidecar instead and reports recovering=True for the cell
            "recovering": False,
            "wal_replay_progress": float(
                obs_registry().gauge("wal_replay_progress").value),
        }

    # migrated-name directory for edge routing, updated by `override` lines
    overrides: dict = {str(k): int(v)
                       for k, v in (spec.get("overrides") or {}).items()}

    # ------------------------------------------------- SO_REUSEPORT edge
    # every cell binds the SAME edge port; the kernel spreads incoming
    # client connections across cells, and a mis-routed first request is
    # forwarded to its owner cell, which answers the client directly
    # (reply_to + client_addr registration — zero extra hop once cached)
    edge_m = None
    if spec.get("edge"):
        host, port = spec["edge"]
        edge_m = Messenger(f"c{cell}.EDGE", (host, int(port)),
                           cluster.nodemap, reuse_port=True)

        xt = reqtrace.xtracer()

        def on_edge_request(sender: str, p: dict) -> None:
            name = p.get("name", "")
            if _overload.expired(p.get("deadline")):
                # dead on arrival at the edge: don't burn a cross-cell
                # forward (or an owner-cell propose) on abandoned work
                _overload.count_expired("edge_forward", f"c{cell}")
                return
            owner = overrides.get(name)
            if owner is None:
                owner = cell_of(name, n_cells)
            p.setdefault("reply_to", p.get("sender") or sender)
            if owner == cell:
                ar0._on_app_request(sender, p)
            else:
                tid = p.get("trace")
                if tid is not None:
                    xt.event(tid, "edge_forward", src=cell, dst=owner,
                             name=name)
                edge_m.send(f"c{owner}.AR0", p, cls=_overload.CLS_CLIENT)

        edge_m.register(pkt.APP_REQUEST, on_edge_request)

    cluster.install_sigterm(
        drain_timeout_s=float(spec.get("drain_timeout_s", 10.0)),
        on_exit=(edge_m.close if edge_m is not None else None),
    )
    emit("ready")

    m = cluster.manager
    coord = cluster.coordinator

    def pump() -> None:
        cluster.kick()
        time.sleep(0.002)

    for line in sys.stdin:
        parts = line.strip().split(" ")
        if not parts or not parts[0]:
            continue
        cmd = parts[0]
        try:
            if cmd == "create":
                coord.create_replica_group(parts[1], 0, b"", active_ids)
                emit(f"created {parts[1]}")
            elif cmd == "propose":
                name, payload = parts[1], bytes.fromhex(parts[2])
                epoch = coord.current_epoch(name)
                if epoch is None:
                    emit(f"err propose no_epoch:{name}")
                    continue

                def cb(rid, resp):
                    emit("resp %s %s" % (
                        rid, resp.hex() if resp is not None else "NONE"))

                if coord.coordinate_request(name, epoch, payload, cb) is None:
                    emit(f"err propose rejected:{name}")
                cluster.kick()
            elif cmd == "db":
                r = int(parts[1]) if len(parts) > 1 else 0
                emit("db " + json.dumps(m.apps[r].db, sort_keys=True))
            elif cmd == "stats":
                emit("stats " + json.dumps({
                    "pid": os.getpid(), "cell": cell,
                    "tick": int(m.tick_num),
                    "rc_tick": int(cluster.rc_manager.tick_num),
                    "groups": len(list(m.rows.names())),
                    "overrides": dict(overrides),
                }, sort_keys=True))
            elif cmd == "metrics":
                # per-cell export for the supervisor's host-level scrape:
                # every series this process owns, labelled with its cell
                body = render_registry(obs_registry(),
                                       extra_labels={"cell": str(cell)})
                emit("metrics " + json.dumps(body))
            elif cmd == "trace":
                if len(parts) > 1:
                    tid = parts[1]
                    dump = {k: v for k, v in reqtrace.dump_ns().items()
                            if k == tid}
                else:
                    dump = reqtrace.dump_ns()
                emit("trace " + json.dumps(dump))
            elif cmd == "flight":
                emit("flight " + flight.dump("rpc"))
            elif cmd == "healthz":
                emit("healthz " + json.dumps(_healthz_doc(),
                                             sort_keys=True))
            elif cmd == "health":
                emit("health " + json.dumps(m.health_snapshot()))
            elif cmd == "group":
                emit("group " + json.dumps(m.group_info(parts[1])))
            elif cmd == "timeline":
                emit("timeline " + json.dumps(timeline.snapshot()))
            elif cmd == "ledger":
                with _LEDGER_LOCK:
                    emit("ledger " + json.dumps(_LEDGER))
            elif cmd == "drain":
                ready_state["draining"] = True
                timeline.annotate("drain", cell=cell)
                ok = cluster.drain(float(spec.get("drain_timeout_s", 10.0)))
                emit("drained " + ("ok" if ok else "timeout"))
            elif cmd == "override":
                name, dst = parts[1], int(parts[2])
                if dst == cell:
                    overrides.pop(name, None)
                else:
                    overrides[name] = dst
                emit(f"override_ok {name}")
            elif cmd == "migrate_out":
                name = parts[1]
                epoch = coord.current_epoch(name)
                if epoch is None:
                    emit(f"migrate_err {name} no_epoch")
                    continue
                coord.stop_replica_group(name, epoch, lambda ok: None)
                blob, ticks = coord.get_final_state(name, epoch), 0
                while blob is None and ticks < 1024:
                    pump()
                    ticks += 1
                    blob = coord.get_final_state(name, epoch)
                if blob is None:
                    emit(f"migrate_err {name} drain_timeout")
                else:
                    timeline.annotate("migrate_out", name=name, cell=cell)
                    emit(f"migrated_out {name} {epoch} {blob.hex()}")
            elif cmd == "migrate_in":
                name, epoch = parts[1], int(parts[2])
                blob = bytes.fromhex(parts[3])
                with m.lock:
                    row = m.rows.free_in_range(0, m.G)
                    ok = (row is not None
                          and coord.create_replica_group_at(
                              name, epoch, blob, active_ids, row))
                if ok:
                    overrides.pop(name, None)  # we ARE the owner now
                    timeline.annotate("migrate_in", name=name, cell=cell)
                    emit(f"migrated_in {name} {epoch}")
                else:
                    emit(f"migrate_err {name} no_row")
            elif cmd == "migrate_drop":
                coord.drop_final_state(parts[1], int(parts[2]))
                emit(f"migrate_dropped {parts[1]}")
            elif cmd == "exit":
                break
            else:
                emit(f"err unknown_cmd {cmd}")
        except Exception as e:
            emit(f"err {cmd} {type(e).__name__}: {e}")

    reporter.stop()
    timeline.stop()
    flight.dump("graceful_exit")
    fd.close()
    if edge_m is not None:
        edge_m.close()
    cluster.shutdown(float(spec.get("drain_timeout_s", 10.0)))


if __name__ == "__main__":
    main()

"""Storage fault injection: a journal shim that makes disks lie on cue.

The WAL's fault model (wal/journal.py, wal/logger.py) claims four
recoverable disk behaviors: torn writes (power cut mid-append), scribbles
(firmware/bit-rot damaging fsynced bytes), fsync errors (the fsyncgate
class — the kernel reported EIO and dropped the dirty pages), and
disk-full.  This module manufactures all four deterministically so the
chaos plane (testing/chaos.py) and the storage soak
(benchmarks/storage_fault_soak.py) can drive them against either journal
backend and assert the recovery contract: every acked decision survives,
or the node visibly fail-stops — never a silent divergence.

Two injection paths:

* in-process — ``install()`` registers :class:`FaultyJournal` as the
  logger-level journal wrapper (``wal.logger.set_journal_wrapper``); an
  :class:`Injector` arms faults per WAL directory.
* cross-process — a worker started with ``GPTPU_WAL_FAULTS=1`` wraps its
  journals via :func:`wrap_from_env`, which reads a ``FAULT.json`` plan
  the runner drops next to the journal (the only channel into a child the
  runner cannot reach in-process).

File-level helpers (:func:`flip_byte`, :func:`tear_tail`,
:func:`newest_journal`) operate on a *crashed* node's directory — the
moral equivalent of what a bad disk does while nobody is looking.
"""

from __future__ import annotations

import errno
import glob
import json
import os
import random
from typing import Dict, Optional

#: fault kinds an armed journal understands (file-level bit_flip is a
#: helper on dead files, not a journal behavior)
KINDS = ("torn_write", "fsync_error", "disk_full")


class FaultyJournal:
    """Wraps a ``PyJournal``/``NativeJournal`` and fails on command.

    * ``torn_write``  — the next append leaves a *partial* frame on disk
      (pure-Python inner: a real mid-frame tear; native inner: the frame
      is dropped at the boundary — still a tear, at offset 0) and raises
      ``OSError`` as the "crash".
    * ``fsync_error`` — the next sync raises ``EIO`` without fsyncing.
    * ``disk_full``   — sticky ``ENOSPC`` on every append until cleared.

    All faults mark the journal ``failed`` (sticky), matching the real
    backends: after fsyncgate the write may be gone from the page cache,
    so retrying would ack vapor.
    """

    def __init__(self, inner, path: str):
        self.inner = inner
        self.path = path
        self.armed: Dict[str, dict] = {}
        self.counts: Dict[str, int] = {}

    # journal protocol ----------------------------------------------------
    @property
    def failed(self) -> bool:
        return getattr(self.inner, "failed", False)

    @failed.setter
    def failed(self, v: bool) -> None:
        self.inner.failed = v

    def arm(self, kind: str, **args) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        self.armed[kind] = args

    def clear(self, kind: str) -> None:
        self.armed.pop(kind, None)

    def _trip(self, kind: str) -> dict:
        self.counts[kind] = self.counts.get(kind, 0) + 1
        return self.armed.pop(kind)

    def append(self, record: bytes) -> None:
        if "disk_full" in self.armed:
            # sticky: re-arm (ENOSPC does not clear itself)
            self.counts["disk_full"] = self.counts.get("disk_full", 0) + 1
            self.inner.failed = True
            raise OSError(errno.ENOSPC, "no space left on device (injected)")
        if "torn_write" in self.armed:
            args = self._trip("torn_write")
            self._tear(record, args)
            raise OSError(errno.EIO, "torn write (injected power cut)")
        self.inner.append(record)

    def sync(self) -> None:
        if "fsync_error" in self.armed:
            self._trip("fsync_error")
            self.inner.failed = True
            raise OSError(errno.EIO, "fsync failed (injected)")
        self.inner.sync()

    def close(self) -> None:
        self.inner.close()

    # tear mechanics ------------------------------------------------------
    def _tear(self, record: bytes, args: dict) -> None:
        """Leave a partial frame of ``record`` on disk, then fail the
        journal (nothing may land after a power cut)."""
        inner = self.inner
        f = getattr(inner, "_f", None)
        if f is not None and hasattr(inner, "_frame"):
            # PyJournal: staged-but-unwritten frames reached "the page
            # cache" first, then the torn frame's prefix lands after them
            inner._flush_pending()
            # materialize the real frame bytes and write a strict prefix
            # straight through to the OS
            if inner._version == 2:
                frame = inner._frame(0, record)  # KIND_DATA
            else:
                import struct
                import zlib
                frame = (struct.pack("<II", len(record), zlib.crc32(record))
                         + record)
            keep = int(args.get("keep_bytes",
                                max(1, len(frame) // 2)))
            keep = min(keep, len(frame) - 1)
            f.write(frame[:keep])
            f.flush()
        # native inner: the frame never reaches the C buffer — a tear at
        # the frame boundary (keep == 0), which scan_journal treats the
        # same way (clean truncation point)
        inner.failed = True


# ----------------------------------------------------------- in-process arm
class Injector:
    """Process-wide fault director: tracks every FaultyJournal created by
    the logger wrapper, keyed by WAL directory, so a chaos runner can arm
    faults on "node N's disk" without holding journal references."""

    def __init__(self):
        self.journals: Dict[str, FaultyJournal] = {}  # dir -> newest shim

    def wrap(self, j, path: str) -> FaultyJournal:
        fj = FaultyJournal(j, path)
        self.journals[os.path.dirname(os.path.abspath(path))] = fj
        return fj

    def for_dir(self, log_dir: str) -> Optional[FaultyJournal]:
        return self.journals.get(os.path.abspath(log_dir))

    def arm(self, log_dir: str, kind: str, **args) -> bool:
        fj = self.for_dir(log_dir)
        if fj is None:
            return False
        fj.arm(kind, **args)
        return True

    def clear(self, log_dir: str, kind: str) -> bool:
        fj = self.for_dir(log_dir)
        if fj is None:
            return False
        fj.clear(kind)
        return True


def install() -> Injector:
    """Route every journal the loggers open through a fresh Injector.
    Returns it; call :func:`uninstall` when done (tests)."""
    from ..wal import logger as wal_logger

    inj = Injector()
    wal_logger.set_journal_wrapper(inj.wrap)
    return inj


def uninstall() -> None:
    from ..wal import logger as wal_logger

    wal_logger.set_journal_wrapper(None)


# -------------------------------------------------------- cross-process arm
def plan_path(log_dir: str) -> str:
    return os.path.join(log_dir, "FAULT.json")


def write_plan(log_dir: str, plan: dict) -> str:
    """Drop a fault plan a GPTPU_WAL_FAULTS worker will pick up when it
    (re)opens its journal.  Keys: ``fsync_error_after`` (syncs),
    ``disk_full_after`` / ``torn_write_after`` (appends); 0 = immediately.
    """
    p = plan_path(log_dir)
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(plan, f)
    os.replace(tmp, p)
    return p


class _PlannedJournal(FaultyJournal):
    """FaultyJournal driven by a countdown plan instead of explicit arms."""

    def __init__(self, inner, path: str, plan: dict):
        super().__init__(inner, path)
        self._appends = 0
        self._syncs = 0
        self.plan = plan

    def append(self, record: bytes) -> None:
        if self._countdown("disk_full_after", self._appends):
            self.arm("disk_full")
        if self._countdown("torn_write_after", self._appends):
            self.arm("torn_write")
        self._appends += 1
        super().append(record)

    def sync(self) -> None:
        if self._countdown("fsync_error_after", self._syncs):
            self.arm("fsync_error")
        self._syncs += 1
        super().sync()

    def _countdown(self, key: str, done: int) -> bool:
        v = self.plan.get(key)
        return v is not None and done >= int(v)


def wrap_from_env(j, path: str):
    """Hook used by ``wal.logger._new_journal`` under GPTPU_WAL_FAULTS=1:
    if a FAULT.json plan sits next to the journal, wrap it; otherwise the
    journal passes through untouched (workers whose disks behave)."""
    p = plan_path(os.path.dirname(os.path.abspath(path)))
    if not os.path.exists(p):
        return j
    try:
        with open(p) as f:
            plan = json.load(f)
    except (OSError, ValueError):
        return j
    return _PlannedJournal(j, path, plan)


# ---------------------------------------------------------- dead-file tools
def newest_journal(log_dir: str) -> Optional[str]:
    js = sorted(glob.glob(os.path.join(log_dir, "journal.*.log")))
    return js[-1] if js else None


def flip_byte(path: str, offset: Optional[int] = None,
              rng: Optional[random.Random] = None) -> int:
    """Flip one bit of ``path`` in place (the classic latent scribble).
    Returns the chosen offset.  Offsets inside the 8-byte magic model a
    damaged header; anywhere else damages a frame."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty; nothing to flip")
    if offset is None:
        offset = (rng or random).randrange(size)
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ (1 << ((rng or random).randrange(8)))]))
        f.flush()
        os.fsync(f.fileno())
    return offset


def tear_tail(path: str, drop_bytes: Optional[int] = None,
              rng: Optional[random.Random] = None) -> int:
    """Truncate ``drop_bytes`` off the end of ``path`` (a torn write
    observed post-crash).  Returns how many bytes were dropped."""
    size = os.path.getsize(path)
    if size <= 8:  # magic only — nothing to tear
        return 0
    if drop_bytes is None:
        drop_bytes = (rng or random).randrange(1, min(64, size - 8) + 1)
    drop_bytes = min(drop_bytes, size - 8)
    with open(path, "r+b") as f:
        f.truncate(size - drop_bytes)
        f.flush()
        os.fsync(f.fileno())
    return drop_bytes

"""Digest-only accepts (cfg.paxos.digest_accepts) over Mode B clusters.

The reference cuts coordinator egress by broadcasting each request's payload
from its ENTRY replica and sending digest-only ACCEPTs
(paxosutil/PendingDigests.java:23; match/release
PaxosInstanceStateMachine.java:1089-1102; undigest fetch :1257-1268).  The
dense wire design's accept rings are rid-only already, so digest mode here
is: rid-only proposal forwards, entry-replica payload broadcast on frames,
and an execution-side stall + undigest fetch for a committed rid whose
payload has not arrived.
"""

import time

from test_modeb import IDS, Cluster, make_cfg


def _digest_cfg(groups=16):
    cfg = make_cfg(groups=groups)
    cfg.paxos.digest_accepts = True
    return cfg


def test_digest_commit_correctness_all_entries():
    """Commits succeed and replicas converge with the flag on, from every
    entry node (coordinator and non-coordinator alike)."""
    cl = Cluster(_digest_cfg())
    try:
        cl.create("svc")
        for i, nid in enumerate(IDS * 2):
            resp = cl.commit(nid, "svc", f"PUT k{i} v{i}".encode())
            assert resp == b"OK", (nid, resp)
        cl.ticks(20)
        dbs = [cl.apps[nid].db.get("svc", {}) for nid in IDS]
        assert dbs[0] == dbs[1] == dbs[2]
        assert len(dbs[0]) == 6
    finally:
        cl.close()


def test_digest_cuts_coordinator_frame_bytes():
    """With KB payloads entering at a NON-coordinator node, the
    coordinator's frame bytes drop materially: payload dissemination moved
    from the coordinator's broadcast to the entry replica's."""
    payload = b"PUT big " + b"x" * 4096
    byte_counts = {}
    for digest in (False, True):
        cfg = make_cfg()
        cfg.paxos.digest_accepts = digest
        cl = Cluster(cfg)
        try:
            cl.create("svc")
            cl.ticks(5)  # settle coordinator election (slot 0 = N0)
            for n in cl.nodes.values():
                n.stats["frame_bytes_sent"] = 0
            for i in range(12):
                assert cl.commit("N1", "svc", payload) == b"OK"
            cl.ticks(5)
            byte_counts[digest] = cl.nodes["N0"].stats["frame_bytes_sent"]
            # correctness unchanged
            assert cl.apps["N0"].db["svc"]["big"] == "x" * 4096
        finally:
            cl.close()
    # coordinator egress must drop by at least the payload volume
    assert byte_counts[True] < byte_counts[False] - 10 * len(payload), (
        byte_counts
    )


def test_undigest_fetch_recovers_suppressed_broadcast():
    """A replica that learns a commit before the payload stalls its row and
    fetches the payload from the rid's origin (the undigest request,
    PaxosInstanceStateMachine.java:1257-1268) — no taint, no divergence."""
    cl = Cluster(_digest_cfg())
    try:
        cl.create("svc")
        cl.ticks(5)
        entry = cl.nodes["N1"]
        # sabotage the entry broadcast: drop the staged extra payloads
        # INSIDE the tick, before the frame build — no peer ever receives
        # the payload on frames, so only undigest can recover
        orig_build = entry._build_frames

        def sabotaged_build():
            entry._extra_pay.clear()
            return orig_build()

        entry._build_frames = sabotaged_build
        done = []
        rid = entry.propose("svc", b"PUT k lost",
                            lambda _r, resp: done.append(resp))
        assert rid is not None
        for _ in range(200):
            cl.ticks(1)
            if done and all(
                cl.apps[nid].db.get("svc", {}).get("k") == "lost"
                for nid in IDS
            ):
                break
        assert done and done[0] == b"OK"
        for nid in IDS:
            assert cl.apps[nid].db["svc"]["k"] == "lost", nid
            assert not cl.nodes[nid]._tainted_rows
            assert not cl.nodes[nid]._stalled
        fills = sum(cl.nodes[nid].stats["undigest_fills"] for nid in IDS)
        assert fills >= 1  # at least one node resolved by fetch
    finally:
        cl.close()


def test_digest_survives_crash_recovery(tmp_path):
    """WAL replay of a digest-mode node: digest placements journal with
    payload=None, frames/undigest fills re-learn payloads, and the
    recovered node matches the survivors."""
    cl = Cluster(_digest_cfg(), wal_root=tmp_path)
    try:
        cl.create("svc")
        for i in range(6):
            # alternate entry so both forward directions journal
            assert cl.commit(IDS[i % 3], "svc",
                             f"PUT k{i} v{i}".encode()) == b"OK"
        cl.ticks(10)
        expect = dict(cl.apps["N1"].db.get("svc", {}))
        cl.kill("N1")
        cl.drop_backlog("N1")
        # survivors keep committing while N1 is down
        assert cl.commit("N0", "svc", b"PUT late 1",
                         only=("N0", "N2")) == b"OK"
        cl.restart("N1")
        # replay alone reproduced every pre-crash commit
        assert dict(cl.apps["N1"].db.get("svc", {})) == expect
        # the rejoiner catches up (including the commit it missed)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            cl.ticks(2)
            if cl.apps["N1"].db.get("svc", {}).get("late") == "1":
                break
        assert cl.apps["N1"].db["svc"]["late"] == "1"
        # and keeps serving new digest-mode commits
        assert cl.commit("N1", "svc", b"PUT post 2") == b"OK"
    finally:
        cl.close()


def test_digest_default_at_scale_with_ring_relay():
    """`digest_min_replicas` flips digest ordering on by DEFAULT once the
    universe reaches 5 replicas (no explicit digest_accepts), and payload
    bytes then ride the dissemination ring: every write converges while
    relay slabs — not broadcast frames — carry the bodies."""
    from gigapaxos_tpu.modeb import ModeBNode
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.testing.simnet import SimNet

    ids = [f"N{i}" for i in range(5)]
    net = SimNet(seed=3)
    cfg = make_cfg(window=4)
    assert not cfg.paxos.digest_accepts            # not explicitly on
    assert cfg.paxos.digest_min_replicas == 5      # scale threshold
    apps = {n: KVApp() for n in ids}
    nodes = {n: ModeBNode(cfg, ids, n, apps[n], net.messenger(n),
                          anti_entropy_every=8) for n in ids}
    for nd in nodes.values():
        assert nd._digest_accepts and nd._ring_dissemination
        nd.create_group("svc", [0, 1, 2, 3, 4])

    done = []
    payload_tail = "y" * 600
    for i in range(8):
        nodes["N2"].propose(
            "svc", f"PUT k{i} v{i}-{payload_tail}".encode(),
            lambda _rid, resp: done.append(resp))
        for _ in range(4):
            for nd in nodes.values():
                nd.tick()
            net.pump()
    for _ in range(30):
        for nd in nodes.values():
            nd.tick()
        net.pump()
    assert done and all(r == b"OK" for r in done), done
    dbs = [apps[n].db.get("svc", {}) for n in ids]
    assert all(d == dbs[0] for d in dbs), dbs
    assert len(dbs[0]) == 8
    relayed = sum(nd.stats["relay_payloads"] for nd in nodes.values())
    assert relayed > 0, {n: dict(nd.stats) for n, nd in nodes.items()}

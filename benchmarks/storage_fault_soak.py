"""Storage-fault soak: disks join the fault model, acked data must survive.

Drives a 3-node Mode B cluster on SimNet through randomized storage-fault
schedules — journal bit flips (scribbles), torn writes, injected fsync
errors (the fsyncgate class), and disk-full shedding — interleaved with
node crashes and *real* recoveries (the crashed node is rebuilt from its
own, possibly damaged, WAL directory via ``recover_modeb``, not restored
from memory).  Two invariants are asserted on every run:

* S1 — the per-slot safety ledger stays clean across every crash,
  scribble, and degraded recovery;
* no silently lost acks — every proposal whose callback returned OK is
  present in the final state of every live replica.  A node may visibly
  fail-stop (quarantined log, failed fsync) and stay down; it may never
  serve from doubted state.

Also measures the v2 framing overhead: CRC+seq frames plus one barrier
per group commit vs the v1 format, interleaved A/B on the same disk,
gated < 2% (the fsync dominates; the barrier is ~21 bytes riding it).

Usage:
    python benchmarks/storage_fault_soak.py [--seeds 6] [--ticks 360]
        [--out PATH]

Prints one JSON line; writes ``benchmarks/results_storage_faults_pr10.json``
unless ``--out -``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import statistics
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gigapaxos_tpu.config import GigapaxosTpuConfig  # noqa: E402
from gigapaxos_tpu.models.replicable import KVApp  # noqa: E402
from gigapaxos_tpu.modeb import ModeBLogger, ModeBNode, recover_modeb  # noqa: E402
from gigapaxos_tpu.testing import faultdisk  # noqa: E402
from gigapaxos_tpu.testing.chaos import (ChaosEvent, ChaosSchedule,  # noqa: E402
                                         SimChaosRunner)
from gigapaxos_tpu.testing.simnet import SimNet  # noqa: E402

IDS = ["N0", "N1", "N2"]
FAULT_CLASSES = ("bit_flip", "torn_write", "fsync_error", "disk_full")


def make_schedule(seed: int, total: int, every: int = 4,
                  classes=FAULT_CLASSES):
    """Randomized episodes, one victim at a time (a majority must always
    hold — the invariant under test is storage safety, not availability
    under double faults).  Proposals enter at N1 with unique keys so every
    ack is individually checkable in the final state; disk-full targets
    the entry node (shedding is a propose-path behavior)."""
    rng = random.Random(seed)
    events = [ChaosEvent(t, "propose",
                         {"node": "N1", "group": "svc",
                          "payload": f"PUT k{t} v{t}"})
              for t in range(2, total, every)]
    episodes = []
    t = 30
    while t < total - 70:
        cls = classes[len(episodes) % len(classes)] if seed % 2 == 0 \
            else rng.choice(classes)
        if cls in ("bit_flip", "torn_write"):
            victim = rng.choice(["N0", "N2"])
            events += [
                ChaosEvent(t, "crash", {"node": victim, "detect_after": 3}),
                ChaosEvent(t + 2, cls, {"node": victim}),
                ChaosEvent(t + 22, "recover", {"node": victim}),
            ]
            end = t + 22
        elif cls == "fsync_error":
            victim = rng.choice(["N0", "N2"])
            events += [
                ChaosEvent(t, "fsync_error", {"node": victim}),
                ChaosEvent(t + 20, "recover", {"node": victim}),
            ]
            end = t + 20
        else:  # disk_full: low-watermark shed at the propose entry
            victim = "N1"
            events += [
                ChaosEvent(t, "disk_full", {"node": victim}),
                ChaosEvent(t + 12, "disk_ok", {"node": victim}),
            ]
            end = t + 12
        episodes.append({"class": cls, "victim": victim,
                         "at": t, "until": end})
        t = end + rng.randrange(12, 26)
    return ChaosSchedule(f"storage_faults_{seed}", events, seed=seed), episodes


def soak(seed: int, total: int = 360, every: int = 4,
         classes=FAULT_CLASSES, wal_root: str | None = None) -> dict:
    """One seeded run.  Returns per-episode outcomes, the S1 summary, and
    the acked-survival audit."""
    own_tmp = wal_root is None
    wal_root = wal_root or tempfile.mkdtemp(prefix="gptpu_sfs_")
    injector = faultdisk.install()
    try:
        net = SimNet(seed=seed)
        cfg = GigapaxosTpuConfig()
        cfg.paxos.max_groups = 8
        apps = {}
        nodes = {}
        wal_dirs = {n: os.path.join(wal_root, n) for n in IDS}
        for n in IDS:
            apps[n] = KVApp()
            nodes[n] = ModeBNode(
                cfg, IDS, n, apps[n], net.messenger(n),
                wal=ModeBLogger(wal_dirs[n], native=False),
                anti_entropy_every=8)
        for nd in nodes.values():
            nd.create_group("svc", [0, 1, 2])

        def restart(nid):
            apps[nid] = KVApp()
            node = recover_modeb(cfg, IDS, nid, apps[nid], wal_dirs[nid],
                                 native=False)
            node.attach_messenger(net.messenger(nid))
            node.request_sync()
            return node

        sched, episodes = make_schedule(seed, total, every, classes)
        runner = SimChaosRunner(
            net, nodes, sched, wal_dirs=wal_dirs, injector=injector,
            restart=restart, rng=random.Random(seed ^ 0x5F5F))
        runner.run(total)
        # drain with no new faults until live replicas converge (taint
        # repair + anti-entropy need room after the last recovery)
        live = lambda: [n for n in IDS if n not in runner.crashed]  # noqa: E731

        def dbs():
            return [json.dumps(apps[n].db, sort_keys=True) for n in live()]

        drained = 0
        while drained < 600 and len(set(dbs())) > 1:
            runner.run(20)
            drained += 20
        runner.run(20)  # settle in-flight callbacks
        drained += 20

        runner.ledger.assert_safe()

        # acked-survival audit: every OK'd proposal must be in every live db
        acked = [p for p in runner.proposals if p["resp"] == "OK"]
        shed = [p for p in runner.proposals if p["resp"] is None]
        lost = []
        live_tables = [apps[n].db.get("svc", {}) for n in live()]
        for p in acked:
            _, k, v = p["payload"].split(" ")
            for t in live_tables:
                if t.get(k) != v:
                    lost.append({"key": k, "want": v, "got": t.get(k)})
                    break

        # per-episode outcome from the applied-event log
        recs = runner.log.records
        for ep in episodes:
            if ep["class"] == "disk_full":
                n_shed = sum(1 for p in shed
                             if ep["at"] <= p["tick"] <= ep["until"] + 2)
                resumed = any(p["resp"] == "OK" for p in runner.proposals
                              if p["tick"] > ep["until"] + 2)
                ep["outcome"] = "shed_then_resumed" if resumed else "shed"
                ep["shed_proposals"] = n_shed
                continue
            rec = next((r for r in recs
                        if r["action"] == "recover"
                        and r["args"].get("node") == ep["victim"]
                        and r["tick"] == ep["until"]), None)
            info = (rec or {}).get("info", {})
            if rec is None or info.get("skipped"):
                ep["outcome"] = "fault_not_tripped"
            elif "failstop" in info:
                ep["outcome"] = "stayed_down"
            elif info.get("recovered_degraded"):
                ep["outcome"] = "recovered_degraded"
            else:
                ep["outcome"] = "recovered_clean"

        by_class: dict = {c: {} for c in classes}
        for ep in episodes:
            d = by_class[ep["class"]]
            d[ep["outcome"]] = d.get(ep["outcome"], 0) + 1

        return {
            "seed": seed,
            "ticks": total,
            "drain_ticks": drained,
            "episodes": episodes,
            "outcomes_by_class": by_class,
            "proposals": len(runner.proposals),
            "acked": len(acked),
            "shed_or_unanswered": len(shed),
            "lost_acked": lost,
            "failstops": runner.failstops,
            "stayed_down": [n for n in IDS if n in runner.crashed],
            "safety": {"observations": runner.ledger.observations,
                       "violations": len(runner.ledger.violations)},
            "live_dbs_converged": len(set(dbs())) == 1,
        }
    finally:
        faultdisk.uninstall()
        if own_tmp:
            shutil.rmtree(wal_root, ignore_errors=True)


# ------------------------------------------------------- framing overhead
def framing_overhead(n: int = 1000, reps: int = 5,
                     payload_bytes: int = 48) -> dict:
    """Per-operation paired A/B: each iteration times one append+fsync on
    the v1-format journal and one on the v2 journal, adjacent in time and
    in alternating order, and each rep's estimate is the MEDIAN of the
    per-pair time differences (normalized by the median v1 op).  fsync
    wall time on a shared box is noisy at the 10%+ level — far above the
    true framing delta (one barrier frame + ~26 bytes per group commit) —
    so an unpaired min-of-runs estimator flaps wildly; op-level pairing
    cancels load drift and the median discards the fsync-stall tail.
    The reported value is the BEST (smallest) rep, same rationale as
    ``obs_overhead.py``'s best-of-N: the delta lives in syscall time, so
    box contention only ever inflates it — the least-contended rep is the
    closest estimate of the real framing cost.  All reps are recorded."""
    from gigapaxos_tpu.wal.journal import MAGIC, PyJournal

    payload = b"x" * payload_bytes
    tmp = tempfile.mkdtemp(prefix="gptpu_framing_")
    try:
        per_rep = []
        v1_us, v2_us = [], []
        for rep in range(reps):
            p1 = os.path.join(tmp, f"v1_{rep}.log")
            with open(p1, "wb") as f:
                f.write(MAGIC)  # seed v1 magic: PyJournal continues it
            j1 = PyJournal(p1)
            j2 = PyJournal(os.path.join(tmp, f"v2_{rep}.log"))
            diffs, t1s, t2s = [], [], []
            for i in range(n):
                order = ((j1, t1s), (j2, t2s)) if i % 2 \
                    else ((j2, t2s), (j1, t1s))
                for j, ts in order:
                    t0 = time.perf_counter()
                    j.append(payload)
                    j.sync()
                    ts.append(time.perf_counter() - t0)
                diffs.append(t2s[-1] - t1s[-1])
            j1.close()
            j2.close()
            m1 = statistics.median(t1s)
            v1_us.append(round(m1 * 1e6, 2))
            v2_us.append(round(statistics.median(t2s) * 1e6, 2))
            per_rep.append(statistics.median(diffs) / m1 * 100.0)
        raw = min(per_rep)
        return {
            "metric": "wal_v2_framing_overhead_pct",
            "value": round(raw, 2),
            "unit": "% per append+fsync vs v1 framing (best-of-reps "
                    "median of per-pair deltas)",
            "v1_us_per_op": min(v1_us),
            "v2_us_per_op": min(v2_us),
            "pairs_per_rep": n,
            "reps": reps,
            "per_rep_overhead_pct": [round(x, 2) for x in per_rep],
            "median_us_per_rep": {"v1": v1_us, "v2": v2_us},
            "pass_lt_pct": 2.0,
            # a negative reading means the residual noise floor still
            # exceeds the true delta, not that v2 is faster
            "pass": raw < 2.0,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=6)
    ap.add_argument("--ticks", type=int, default=360)
    ap.add_argument("--every", type=int, default=4)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    t0 = time.monotonic()
    # framing A/B first: the process is quiet before the soak seeds churn
    # allocator/page-cache state, and the delta being measured is pure
    # syscall time that contention can only inflate
    framing = framing_overhead()
    runs = [soak(seed, total=args.ticks, every=args.every)
            for seed in range(args.seeds)]
    agg: dict = {c: {} for c in FAULT_CLASSES}
    for r in runs:
        for cls, outs in r["outcomes_by_class"].items():
            for k, v in outs.items():
                agg[cls][k] = agg[cls].get(k, 0) + v
    result = {
        "generated_unix": int(time.time()),
        "environment": {"cpu_count": os.cpu_count(),
                        "python": sys.version.split()[0]},
        "seeds": args.seeds,
        "ticks_per_seed": args.ticks,
        "total_violations": sum(r["safety"]["violations"] for r in runs),
        "total_lost_acked": sum(len(r["lost_acked"]) for r in runs),
        "total_acked": sum(r["acked"] for r in runs),
        "total_failstops": sum(len(r["failstops"]) for r in runs),
        "outcomes_by_class": agg,
        "framing_overhead": framing,
        "runs": runs,
    }
    result["wall_clock_s"] = round(time.monotonic() - t0, 1)
    assert result["total_violations"] == 0, "S1 violated under storage faults"
    assert result["total_lost_acked"] == 0, \
        f"silently lost acked decisions: {result['total_lost_acked']}"
    assert result["framing_overhead"]["pass"], result["framing_overhead"]

    out = args.out
    if out != "-":
        out = out or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "results_storage_faults_pr10.json")
        with open(out, "w") as f:
            json.dump(result, f, indent=1)
        result["written"] = out
    print(json.dumps(result))


if __name__ == "__main__":
    main()

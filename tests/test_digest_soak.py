"""Digest-accepts soak: the randomized Mode B crash/recover property with
``cfg.paxos.digest_accepts`` ON across a seed sweep (ROADMAP item 9).

``tests/test_modeb_digest.py`` proves digest mode correct on targeted
scenarios (entry-replica broadcast, sabotaged broadcast + undigest fetch,
WAL replay); what it lacked was a long soak under randomized kills and
journal restarts — the regime where a payload can be lost in EVERY way at
once (dead entry replica, dropped backlog, replay with payload=None) and
only the undigest fetch + anti-entropy machinery keeps released writes
convergent.

Each seed runs ``run_random_kill_restart`` (tests/test_modeb.py) — the same
property the non-digest build soaks under — with digests on, asserting every
client-released response converges onto every node's app.

Run directly to (re)generate the committed artifact::

    python tests/test_digest_soak.py   # -> benchmarks/results_digest_soak.json
"""

import json
import os
import sys
import tempfile
import time
from pathlib import Path

import pytest

# repo root, for direct `python tests/test_digest_soak.py` runs (the script
# dir is on sys.path but the gigapaxos_tpu package root is not)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from test_modeb import make_cfg, run_random_kill_restart

SEEDS = [1, 4, 9, 17, 33, 77]


def _digest_cfg():
    cfg = make_cfg(window=4)
    cfg.paxos.digest_accepts = True
    return cfg


@pytest.mark.slow
@pytest.mark.parametrize("seed", SEEDS)
def test_digest_soak_random_kill_restart(tmp_path, seed):
    stats = run_random_kill_restart(tmp_path, seed, cfg=_digest_cfg())
    # the property itself asserts convergence; here we also demand the run
    # exercised digest mode's failure machinery over the sweep: every seed
    # must release writes, and each scheduled at least one kill
    assert stats["released"] > 0
    assert stats["kills"] >= 1, stats


def main() -> int:
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks",
        "results_digest_soak.json")
    runs = []
    for seed in SEEDS:
        with tempfile.TemporaryDirectory() as td:
            t0 = time.perf_counter()
            stats = run_random_kill_restart(Path(td), seed,
                                            cfg=_digest_cfg())
            stats["seconds"] = round(time.perf_counter() - t0, 2)
        print(json.dumps(stats))
        runs.append(stats)
    result = {
        "bench": "digest_soak",
        "property": "run_random_kill_restart (tests/test_modeb.py) with "
                    "cfg.paxos.digest_accepts=True",
        "seeds": SEEDS,
        "all_converged": True,  # each run asserts convergence or raises
        "total_released": sum(r["released"] for r in runs),
        "total_kills": sum(r["kills"] for r in runs),
        "total_restarts": sum(r["restarts"] for r in runs),
        "total_undigest_fills": sum(r["undigest_fills"] for r in runs),
        "runs": runs,
    }
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Sustained reconfiguration rate through the full control plane.

The reference's ``TESTReconfigurationClient`` measures how fast names can
be migrated end-to-end (``testReconfigureRate``-style ordered tests,
``reconfiguration/testing/TESTReconfigurationClient.java:676-1002``): each
reconfiguration is a full epoch change — RC paxos commit of the intent,
StopEpoch at the old actives (a consensus stop), final-state transfer,
StartEpoch + acks, record READY — so the rate measures the whole epoch
pipeline, not a metadata flip.

Drives an in-process deployment (5 ARs + 3 RCs over real loopback
sockets, the ``TESTReconfigurationMain.startLocalServers`` shape) with K
names round-robining across active subsets, k names in flight at a time.

Usage: python benchmarks/reconfig_rate.py [--names N] [--rounds R]
       [--inflight K]
Prints one JSON line; commit the output into results_r5.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--names", type=int, default=8)
    ap.add_argument("--rounds", type=int, default=6,
                    help="migrations per name")
    ap.add_argument("--inflight", type=int, default=4)
    ap.add_argument("--actives", type=int, default=5)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from gigapaxos_tpu.client import ReconfigurableAppClient
    from gigapaxos_tpu.config import GigapaxosTpuConfig
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.node import InProcessCluster

    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 4 * args.names + 16
    for i in range(args.actives):
        cfg.nodes.actives[f"AR{i}"] = ("127.0.0.1", 0)
    for i in range(3):
        cfg.nodes.reconfigurators[f"RC{i}"] = ("127.0.0.1", 0)

    cluster = InProcessCluster(cfg, KVApp)
    client = ReconfigurableAppClient(cfg.nodes)
    ar = [f"AR{i}" for i in range(args.actives)]
    names = [f"rr{i}" for i in range(args.names)]
    try:
        for n in names:
            assert client.create(n)["ok"]
            assert client.request(n, b"PUT v 1") == b"OK"

        t0 = time.monotonic()
        ok_count = [0]
        fail = []
        sem = threading.Semaphore(args.inflight)
        lock = threading.Lock()

        def worker(idx: int, name: str) -> None:
            # rounds are SERIAL per name (overlapping reconfigurations of
            # one name are rejected as busy by the RC); the semaphore bounds
            # how many distinct names migrate concurrently.  Deterministic
            # rotation through 3-subsets of the active set (no hash(): that
            # is randomized per process and would vary the migration
            # pattern run to run).
            for r in range(args.rounds):
                base = (idx + r) % len(ar)
                new = [ar[(base + j) % len(ar)] for j in range(3)]
                with sem:
                    try:
                        resp = client.reconfigure(name, new, timeout=120)
                        with lock:
                            if resp.get("ok"):
                                ok_count[0] += 1
                            else:
                                fail.append((name, r, resp))
                    except Exception as e:  # noqa: BLE001 - record, continue
                        with lock:
                            fail.append((name, r, str(e)))

        threads = [
            threading.Thread(target=worker, args=(i, n))
            for i, n in enumerate(names)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        dt = time.monotonic() - t0

        # every name still serves its state after all the epoch churn
        survivors = sum(
            1 for n in names if client.request(n, b"GET v", timeout=60) == b"1"
        )
        print(json.dumps({
            "metric": "reconfigurations_per_sec_e2e",
            "value": round(ok_count[0] / dt, 2),
            "unit": "reconfigurations/s",
            "vs_baseline": 0.0,
            "detail": {
                "completed": ok_count[0],
                "attempted": args.names * args.rounds,
                "failed": len(fail),
                "elapsed_s": round(dt, 2),
                "inflight": args.inflight,
                "names": args.names,
                "state_survivors": survivors,
            },
        }))
        if fail[:3]:
            print("failures:", fail[:3], file=sys.stderr)
    finally:
        client.close()
        cluster.close()


if __name__ == "__main__":
    main()

"""Deterministic in-process network simulator: partitions + link delays.

The reference tests liveness/failover at loopback RTT and emulates WAN
latency by delaying JSON sends inside the transport
(``nio/JSONDelayEmulator.java:39-77``, enabled by
``TESTPaxosConfig``); partitions are emulated by crashing nodes
(``TESTPaxosConfig.crash``).  This module gives the TPU framework both
knobs with *deterministic* delivery: messages move only when the harness
calls :meth:`SimNet.pump`, so a test can interleave ticks and delivery
rounds exactly, hold a frame in flight across a coordinator change, or cut
any directed link mid-protocol.

:class:`SimMessenger` exposes the same surface as ``net.messenger.Messenger``
(``demux``/``register``/``send``/``multicast``/``send_bytes``/``close``), so
anything that speaks Messenger — ``ModeBNode``, protocol executors, the
failure detector — runs unmodified over the simulator.
"""

from __future__ import annotations

import collections
import heapq
import json
from typing import Dict, Iterable, Optional, Tuple

from ..net.transport import KIND_BYTES, KIND_JSON, JsonDemux


class SimMessenger:
    """One simulated node endpoint (Messenger-compatible)."""

    def __init__(self, net: "SimNet", node_id: str):
        self.net = net
        self.node_id = node_id
        self.demux = JsonDemux()
        self.closed = False
        self.port = 0  # no socket; Messenger-surface compatibility

    def register(self, ptype, handler) -> None:
        self.demux.register(ptype, handler)

    def send(self, dest: str, packet: dict) -> None:
        packet.setdefault("sender", self.node_id)
        self.net._enqueue(self.node_id, dest, KIND_JSON,
                          json.dumps(packet).encode())

    def multicast(self, dests: Iterable[str], packet: dict) -> None:
        packet.setdefault("sender", self.node_id)
        for d in dests:
            if d is not None:
                self.net._enqueue(self.node_id, d, KIND_JSON,
                                  json.dumps(packet).encode())

    def send_bytes(self, dest: str, payload: bytes) -> None:
        self.net._enqueue(self.node_id, dest, KIND_BYTES, payload)

    def close(self) -> None:
        self.closed = True


class SimNet:
    """The wire: directed links with up/down state and per-link delay.

    Delay unit is *pump rounds* (a message sent at round t with link delay d
    is delivered during the pump that advances past round t+d).  Default
    delay 0 = delivered by the next ``pump()``.
    """

    def __init__(self):
        self.endpoints: Dict[str, SimMessenger] = {}
        self.round = 0
        self._seq = 0
        self._heap: list = []  # (due_round, seq, src, dst, kind, payload)
        self._down: set = set()  # directed (src, dst)
        self._delay: Dict[Tuple[str, str], int] = {}
        self.default_delay = 0
        self.stats = collections.Counter()

    # ------------------------------------------------------------- topology
    def messenger(self, node_id: str) -> SimMessenger:
        m = SimMessenger(self, node_id)
        self.endpoints[node_id] = m
        return m

    def set_delay(self, src: str, dst: str, rounds: int,
                  both_ways: bool = True) -> None:
        self._delay[(src, dst)] = rounds
        if both_ways:
            self._delay[(dst, src)] = rounds

    def set_link(self, src: str, dst: str, up: bool,
                 both_ways: bool = True) -> None:
        pairs = [(src, dst)] + ([(dst, src)] if both_ways else [])
        for p in pairs:
            if up:
                self._down.discard(p)
            else:
                self._down.add(p)

    def partition(self, *sides: Iterable[str]) -> None:
        """Cut every link between nodes of different sides (both ways)."""
        groups = [set(s) for s in sides]
        for i, a in enumerate(groups):
            for b in groups[i + 1:]:
                for x in a:
                    for y in b:
                        self._down.add((x, y))
                        self._down.add((y, x))

    def heal(self) -> None:
        self._down.clear()

    def drop_pending(self, src: Optional[str] = None,
                     dst: Optional[str] = None) -> int:
        """Discard in-flight messages (long-outage emulation: the real
        transport's retries exhausted).  Returns how many were dropped."""
        keep, dropped = [], 0
        for item in self._heap:
            if ((src is None or item[2] == src)
                    and (dst is None or item[3] == dst)):
                dropped += 1
            else:
                keep.append(item)
        heapq.heapify(keep)
        self._heap = keep
        self.stats["dropped_pending"] += dropped
        return dropped

    # ------------------------------------------------------------- transfer
    def _enqueue(self, src: str, dst: str, kind: int, payload: bytes) -> None:
        if (src, dst) in self._down:
            self.stats["dropped_down"] += 1
            return
        d = self._delay.get((src, dst), self.default_delay)
        self._seq += 1
        heapq.heappush(self._heap,
                       (self.round + d, self._seq, src, dst, kind, payload))
        self.stats["sent"] += 1

    def pump(self, rounds: int = 1) -> int:
        """Advance time and deliver everything due.  Returns deliveries."""
        n = 0
        for _ in range(rounds):
            self.round += 1
            while self._heap and self._heap[0][0] < self.round:
                _, _, src, dst, kind, payload = heapq.heappop(self._heap)
                ep = self.endpoints.get(dst)
                if ep is None or ep.closed:
                    self.stats["dropped_dead"] += 1
                    continue
                # a link cut while the message was in flight loses it, like
                # a TCP connection reset mid-outage
                if (src, dst) in self._down:
                    self.stats["dropped_down"] += 1
                    continue
                try:
                    ep.demux(src, kind, payload)
                except Exception:
                    self.stats["demux_errors"] += 1
                n += 1
                self.stats["delivered"] += 1
        return n

"""Mode B: independent per-node managers, replica traffic over the transport.

The defining capability of the reference deployment shape — every node its
own process-equivalent failure domain with its own WAL
(gigapaxos/PaxosManager.java:104-119, SQLPaxosLogger.java:123) — exercised
the way the reference tests it (TESTReconfigurationMain-style: real
loopback sockets in one process, gigapaxos/testing): kill a node, commit
with the majority, restart it from ITS OWN journal.
"""

import time

import numpy as np
import pytest

from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.modeb import (
    ModeBLogger,
    ModeBNode,
    decode_frame,
    encode_frame,
    gid_of,
    recover_modeb,
)
from gigapaxos_tpu.modeb import wire
from gigapaxos_tpu.net.messenger import Messenger, NodeMap

IDS = ["N0", "N1", "N2"]


def make_cfg(groups=16, window=8):
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = groups
    cfg.paxos.window = window
    return cfg


class Cluster:
    """3 fully-independent nodes: each its own Messenger (own sockets) and,
    when ``wal_root`` is given, its own journal+snapshot directory."""

    def __init__(self, cfg, wal_root=None, anti_entropy_every=16):
        self.cfg = cfg
        self.wal_root = wal_root
        self.nodemap = NodeMap()
        self.msgs = {}
        self.apps = {}
        self.nodes = {}
        for nid in IDS:
            m = Messenger(nid, ("127.0.0.1", 0), self.nodemap)
            self.nodemap.add(nid, "127.0.0.1", m.port)
            self.msgs[nid] = m
        for nid in IDS:
            wal = None
            if wal_root is not None:
                wal = ModeBLogger(str(wal_root / nid), native=False)
            self.apps[nid] = KVApp()
            self.nodes[nid] = ModeBNode(
                cfg, IDS, nid, self.apps[nid], self.msgs[nid], wal=wal,
                anti_entropy_every=anti_entropy_every,
            )

    def create(self, name, members=(0, 1, 2)):
        for n in self.nodes.values():
            n.create_group(name, list(members))

    def ticks(self, k, only=None, sleep=0.005):
        for _ in range(k):
            for nid, n in self.nodes.items():
                if only is None or nid in only:
                    n.tick()
            if sleep:
                time.sleep(sleep)

    def commit(self, at, name, payload, timeout_ticks=120, only=None):
        """Propose at node ``at`` and tick until the response arrives."""
        done = []
        rid = self.nodes[at].propose(
            name, payload, lambda _r, resp: done.append(resp)
        )
        assert rid is not None
        for _ in range(timeout_ticks):
            self.ticks(1, only=only)
            if done:
                return done[0]
        raise AssertionError(f"no commit of {payload!r} at {at}")

    def kill(self, nid):
        """Process-death emulation: transport gone, ticking stops, in-memory
        state discarded.  Survivors mark the slot dead (the FD's job)."""
        self.nodes[nid].close()
        dead_r = IDS.index(nid)
        del self.nodes[nid]
        for n in self.nodes.values():
            n.set_alive(dead_r, False)

    def drop_backlog(self, nid):
        """Discard frames the survivors queued for a dead peer (emulates a
        long outage where the transport exhausted its retries — without
        this, reconnect delivers the whole backlog like a mailbox).
        Transport.reset_peer also strands the frame a writer thread may be
        holding mid-reconnect-retry, which a queue drain cannot see — one
        such survivor delivered after restart() can tile a laggard's gap
        and mask the mechanism under test."""
        for other in self.nodes.values():
            other.m.transport.reset_peer(nid)

    def restart(self, nid):
        """Rebuild the node from its own WAL and rejoin."""
        assert self.wal_root is not None
        self.apps[nid] = KVApp()
        node = recover_modeb(self.cfg, IDS, nid, self.apps[nid],
                             str(self.wal_root / nid), native=False)
        m = Messenger(nid, ("127.0.0.1", 0), self.nodemap)
        self.nodemap.add(nid, "127.0.0.1", m.port)
        self.msgs[nid] = m
        node.attach_messenger(m)
        node.request_sync()
        self.nodes[nid] = node
        back_r = IDS.index(nid)
        for n in self.nodes.values():
            n.set_alive(back_r, True)
        return node

    def close(self):
        for n in self.nodes.values():
            n.close()


@pytest.fixture()
def cluster():
    cl = Cluster(make_cfg())
    yield cl
    cl.close()


def test_wire_roundtrip():
    rng = np.random.default_rng(7)
    n, W = 5, 8
    gids = rng.integers(1, 2**60, n).astype(np.uint64)
    scalars = {f: rng.integers(-5, 100, n).astype(np.int32)
               for f in wire.SCALARS}
    flags = rng.integers(0, 4, n).astype(np.int32)
    rings = {f: rng.integers(-2, 50, (n, W)).astype(np.int32)
             for f in wire.RINGS}
    bits = {f: rng.random((n, W)) < 0.5 for f in wire.RING_BITS}
    pay = [(123, False, b"hello"), (456, True, b""), (789, False, b"\x00\xff")]
    buf = encode_frame(2, 99, W, gids, scalars, flags, rings, bits, pay,
                       full=True)
    fr = decode_frame(buf)
    assert fr.sender_r == 2 and fr.tick == 99 and fr.W == W and fr.full
    assert np.array_equal(fr.gids, gids)
    for f in wire.SCALARS:
        assert np.array_equal(fr.scalars[f], scalars[f])
    assert np.array_equal(fr.flags, flags)
    for f in wire.RINGS:
        assert np.array_equal(fr.rings[f], rings[f])
    for f in wire.RING_BITS:
        assert np.array_equal(fr.ring_bits[f], bits[f])
    assert fr.payloads == pay
    assert gid_of("alice") == gid_of("alice") != gid_of("bob")


def test_commit_from_every_node(cluster):
    cluster.create("svc")
    assert cluster.commit("N0", "svc", b"PUT a 0") == b"OK"
    assert cluster.commit("N1", "svc", b"PUT b 1") == b"OK"
    assert cluster.commit("N2", "svc", b"PUT c 2") == b"OK"
    cluster.ticks(20)  # let decisions propagate everywhere
    want = {"a": "0", "b": "1", "c": "2"}
    for nid in IDS:
        assert cluster.apps[nid].db["svc"] == want, nid


def test_coordinator_kill_majority_commits(cluster):
    cluster.create("svc")
    assert cluster.commit("N1", "svc", b"PUT pre 1") == b"OK"
    row = cluster.nodes["N1"].rows.row("svc")
    assert int(cluster.nodes["N1"]._coord_view[row]) == 0  # N0 leads
    cluster.kill("N0")  # kill the coordinator
    # survivors elect a new coordinator and keep committing
    assert cluster.commit("N1", "svc", b"PUT post 2",
                          only=("N1", "N2")) == b"OK"
    assert cluster.commit("N2", "svc", b"PUT post2 3",
                          only=("N1", "N2")) == b"OK"
    cluster.ticks(20, only=("N1", "N2"))
    for nid in ("N1", "N2"):
        assert cluster.apps[nid].db["svc"]["post"] == "2"
        assert cluster.apps[nid].db["svc"]["post2"] == "3"
    assert int(cluster.nodes["N1"]._coord_view[row]) == 1  # next-in-line


def test_kill_restart_from_own_journal(tmp_path):
    cl = Cluster(make_cfg(), wal_root=tmp_path)
    try:
        cl.create("svc")
        assert cl.commit("N2", "svc", b"PUT k1 v1") == b"OK"
        cl.ticks(10)
        db_n2 = dict(cl.apps["N2"].db)
        cl.kill("N2")
        # majority keeps committing while N2 is down (few slots: ring sync)
        assert cl.commit("N0", "svc", b"PUT k2 v2",
                         only=("N0", "N1")) == b"OK"
        # restart N2 from ITS OWN journal: pre-crash state must be back
        n2 = cl.restart("N2")
        assert cl.apps["N2"].db == db_n2  # recovered locally, not copied
        # and it catches up on what it missed while dead
        for _ in range(150):
            cl.ticks(1)
            if cl.apps["N2"].db.get("svc", {}).get("k2") == "v2":
                break
        assert cl.apps["N2"].db["svc"] == {"k1": "v1", "k2": "v2"}
        # the rejoined node serves new traffic
        assert cl.commit("N2", "svc", b"PUT k3 v3") == b"OK"
        assert n2.wal is not None and n2.wal.is_synced()
    finally:
        cl.close()


def test_deep_laggard_checkpoint_transfer(tmp_path):
    """A node that misses more decisions than the window W cannot catch up
    by ring sync — the network checkpoint transfer must kick in."""
    cl = Cluster(make_cfg(window=4), wal_root=tmp_path)
    try:
        cl.create("svc")
        assert cl.commit("N0", "svc", b"PUT seed 0") == b"OK"
        cl.ticks(10)
        cl.kill("N2")
        for i in range(10):  # 10 > W=4 decisions missed
            assert cl.commit("N0", "svc", f"PUT k{i} {i}".encode(),
                             only=("N0", "N1")) == b"OK"
        cl.drop_backlog("N2")  # long outage: sender retries exhausted
        cl.restart("N2")
        # wall-clock bounded: the checkpoint request/response rides real
        # messenger threads that can lag far behind a tight tick loop on a
        # starved 1-core CI box
        # wait for BOTH the state and the mechanism counter: the transfer
        # apply runs on a transport reader thread and fills the app db
        # (restore) several JAX dispatches BEFORE it bumps ckpt_transfers —
        # polling the db alone races that window and reads the counter as 0
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            cl.ticks(1)
            if (cl.apps["N2"].db.get("svc", {}).get("k9") == "9"
                    and cl.nodes["N2"].stats.get("ckpt_transfers", 0) >= 1):
                break
            time.sleep(0.01)
        assert cl.apps["N2"].db["svc"]["k9"] == "9"
        assert cl.nodes["N2"].stats["ckpt_transfers"] >= 1, \
            dict(cl.nodes["N2"].stats)
        # and the transfer is durable: crash N2 again right after, recover
        cl.kill("N2")
        n2 = cl.restart("N2")
        assert cl.apps["N2"].db["svc"]["k9"] == "9"
        assert n2 is not None
    finally:
        cl.close()


def test_stop_request_fences_group(cluster):
    cluster.create("svc")
    assert cluster.commit("N0", "svc", b"PUT a 1") == b"OK"
    done = []
    cluster.nodes["N0"].propose_stop("svc", callback=lambda r, x: done.append(x))
    cluster.ticks(40)
    assert done, "stop never committed"
    for nid in IDS:
        assert cluster.nodes[nid].is_stopped("svc"), nid
    # post-stop proposals fail fast
    got = []
    assert cluster.nodes["N1"].propose(
        "svc", b"PUT b 2", lambda r, x: got.append(x)
    ) is None
    cluster.ticks(5)
    assert got == [None]


def test_missed_birthing_whois(cluster):
    """A node that missed the create learns the group via whois when the
    first frame (or forwarded proposal) for the unknown gid arrives
    (FindReplicaGroupPacket analog, PaxosManager.java:2459-2469)."""
    for nid in ("N0", "N1"):  # N2 never hears the create
        cluster.nodes[nid].create_group("late", [0, 1, 2])
    assert cluster.commit("N0", "late", b"PUT x 9") == b"OK"
    for _ in range(100):
        cluster.ticks(1)
        if "late" in cluster.nodes["N2"].rows:
            break
    assert "late" in cluster.nodes["N2"].rows
    cluster.ticks(40)
    assert cluster.apps["N2"].db.get("late", {}).get("x") == "9"


def test_ckpt_donation_consistent_under_pipelined_tick(tmp_path):
    """A checkpoint donor with a pipelined tick in flight must not pair
    the device exec watermark with an app blob that lacks that tick's
    undelivered executions — the asker would adopt the watermark and
    permanently skip the difference (the Mode A twin of this skew lost
    acknowledged writes; see paxos/manager.py sync_laggard)."""
    import json as _json

    cfg = make_cfg(window=4)
    cfg.paxos.pipeline_ticks = True
    nm = NodeMap()
    m0 = Messenger("N0", ("127.0.0.1", 0), nm)
    nm.add("N0", "127.0.0.1", m0.port)
    app = KVApp()
    node = ModeBNode(cfg, ["N0"], "N0", app, m0)
    sent = []
    node.m.send = lambda dest, pkt: sent.append((dest, pkt))
    try:
        node.create_group("svc", [0])
        done = []
        node.propose("svc", b"PUT a 1", lambda r, v: done.append(v))
        for _ in range(12):
            node.tick()
            if done:
                break
        assert done == [b"OK"]
        # put one more decision INTO the pipeline: tick once so the device
        # has executed it but the host has not delivered it yet
        node.propose("svc", b"PUT b 2", lambda r, v: done.append(v))
        node.tick()
        row = node.rows.row("svc")
        import gigapaxos_tpu.modeb.wire as wire
        node._on_ckpt_req("N9", {"gid": str(wire.gid_of("svc"))})
        assert sent, "no checkpoint reply produced"
        reply = sent[-1][1]
        blob = bytes.fromhex(reply["state"])
        db = _json.loads(blob.decode()) if blob else {}
        wm = int(reply["exec_slot"])
        have = int(np.asarray(node.state.exec_slot[0, row]))
        assert wm == have, (wm, have)
        # the blob must contain EVERYTHING the watermark claims: if the
        # device executed 'PUT b 2' (watermark advanced), it is in the blob
        if wm >= 3:  # create-noop + two puts
            assert db.get("b") == "2", (wm, db)
        assert db.get("a") == "1", (wm, db)
    finally:
        node.close()


def run_random_kill_restart(tmp_path, seed, cfg=None, steps=30):
    """Randomized Mode B durability property: random commits at random nodes
    under random single-node deaths + journal restarts (majority always
    alive, backlogs dropped on outage) — every response RELEASED to a client
    must converge onto every node's app.  The per-process twin of the Mode A
    crash/recover property (tests/test_safety_random.py).

    Reused by the digest soak (tests/test_digest_soak.py), which runs it
    with ``cfg.paxos.digest_accepts = True`` across a seed sweep.  Returns a
    stats dict so the soak can commit its artifact."""
    rng = np.random.default_rng(seed)
    cl = Cluster(cfg if cfg is not None else make_cfg(window=4),
                 wal_root=tmp_path)
    pending = {}  # key -> (value, done-list); folded into released at end
    dead = None
    kills = restarts = 0
    try:
        cl.create("svc")
        n = 0
        for step in range(steps):
            if dead is None and rng.random() < 0.2:
                dead = IDS[int(rng.integers(0, 3))]
                cl.kill(dead)
                kills += 1
            elif dead is not None and rng.random() < 0.4:
                cl.drop_backlog(dead)
                cl.restart(dead)
                restarts += 1
                dead = None
            at = str(rng.choice([i for i in IDS if i != dead]))
            n += 1
            k, v = f"k{n}", str(step)
            done = []
            # kill() removed the dead node from cl.nodes; ticks() only
            # drives survivors
            if cl.nodes[at].propose("svc", f"PUT {k} {v}".encode(),
                                    lambda _r, x: done.append(x)) is None:
                continue
            pending[k] = (v, done)
            for _ in range(240):
                cl.ticks(1)
                if done:
                    break
        if dead is not None:
            cl.drop_backlog(dead)
            cl.restart(dead)
            restarts += 1

        def released():
            # late releases count: a response that fired after its
            # submitter stopped waiting is still a client-visible promise
            return {k: v for k, (v, d) in pending.items() if b"OK" in d}

        deadline = time.monotonic() + 150
        while time.monotonic() < deadline:
            cl.ticks(1)
            rel = released()
            if rel and all(cl.apps[nid].db.get("svc", {}).get(k) == v
                           for nid in IDS for k, v in rel.items()):
                break
            time.sleep(0.01)
        rel = released()
        for nid in IDS:
            db = cl.apps[nid].db.get("svc", {})
            missing = {k: v for k, v in rel.items() if db.get(k) != v}
            assert not missing, (nid, len(missing), dict(
                list(missing.items())[:4]))
        assert rel  # the run must have exercised something
        return {
            "seed": int(seed),
            "steps": int(steps),
            "proposed": len(pending),
            "released": len(rel),
            "kills": kills,
            "restarts": restarts,
            "undigest_fills": sum(
                node.stats.get("undigest_fills", 0)
                for node in cl.nodes.values()
            ),
        }
    finally:
        cl.close()


@pytest.mark.parametrize("seed", [1, 4, 9])
def test_random_kill_restart_released_writes_converge(tmp_path, seed):
    run_random_kill_restart(tmp_path, seed)

"""Device-resident KV app: decisions execute on-device, fused with the tick.

Reference workload app: gigapaxos/testing/TESTPaxosApp.java:60 (state
updates driven by the decision stream).  Correctness is checked against a
plain-python dict model over randomized op sequences.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from gigapaxos_tpu.models.device_kv import (
    OP_DEL,
    OP_GET,
    OP_PUT,
    DeviceKVApp,
    fused_step_jit,
    init_kv,
    kv_apply,
    register_requests,
)
from gigapaxos_tpu.ops.tick import TickInbox
from gigapaxos_tpu.paxos import state as st

R, G, W, S = 3, 4, 8, 8


def make_exec(planned):
    """planned: list of (r, g, [rids...]) -> (exec_req [R,W,G], exec_count)."""
    req = np.zeros((R, W, G), np.int32)
    cnt = np.zeros((R, G), np.int32)
    for r, g, rids in planned:
        for j, rid in enumerate(rids):
            req[r, j, g] = rid
        cnt[r, g] = len(rids)
    return jnp.asarray(req), jnp.asarray(cnt)


def test_put_get_del_semantics():
    kv = init_kv(R, G, slots=S, table=1 << 10)
    # rid: 1 PUT k5=77 | 2 GET k5 | 3 DEL k5 | 4 GET k5
    kv = register_requests(
        kv,
        [1, 2, 3, 4],
        [OP_PUT, OP_GET, OP_DEL, OP_GET],
        [5, 5, 5, 5],
        [77, 0, 0, 0],
    )
    req, cnt = make_exec([(r, 0, [1, 2, 3, 4]) for r in range(R)])
    kv2, resp, miss = kv_apply(kv, req, cnt)
    resp = np.asarray(resp)
    for r in range(R):
        assert resp[r, 0, 0] == 77  # PUT echoes value
        assert resp[r, 1, 0] == 77  # GET sees the same-tick earlier PUT
        assert resp[r, 2, 0] == 77  # DEL returns the old value
        assert resp[r, 3, 0] == 0   # GET after DEL: absent
    assert not np.asarray(miss).any()
    # state persists across ticks: k5 deleted
    kv3 = register_requests(kv2, [9], [OP_GET], [5], [0])
    req2, cnt2 = make_exec([(0, 0, [9])])
    _, resp2, _ = kv_apply(kv3, req2, cnt2)
    assert np.asarray(resp2)[0, 0, 0] == 0


def test_unregistered_rid_is_miss():
    kv = init_kv(R, G, slots=S, table=1 << 10)
    req, cnt = make_exec([(0, 1, [1234])])
    kv2, resp, miss = kv_apply(kv, req, cnt)
    assert bool(np.asarray(miss)[0, 0, 1])
    assert np.asarray(resp)[0, 0, 1] == 0
    # app state untouched
    assert np.asarray(kv2.key).sum() == 0


def test_randomized_against_dict_model():
    rng = np.random.default_rng(3)
    kv = init_kv(1, 1, slots=S, table=1 << 12)
    model = {}
    next_rid = 1
    for _tick in range(20):
        n = int(rng.integers(1, W + 1))
        rids, ops, keys, vals = [], [], [], []
        for _ in range(n):
            rids.append(next_rid)
            next_rid += 1
            ops.append(int(rng.choice([OP_PUT, OP_GET, OP_DEL])))
            # keys within one cache-slot-collision-free set: the
            # direct-mapped store evicts colliding keys, the dict does not
            keys.append(int(rng.integers(1, S + 1)))
            vals.append(int(rng.integers(1, 1000)))
        kv = register_requests(kv, rids, ops, keys, vals)
        req, cnt = make_exec([(0, 0, rids)])
        kv, resp, miss = kv_apply(kv, req, cnt)
        resp = np.asarray(resp)[0]
        assert not np.asarray(miss).any()
        for j in range(n):
            k, v, op = keys[j], vals[j], ops[j]
            if op == OP_PUT:
                expect = v
                model[k] = v
            elif op == OP_GET:
                expect = model.get(k, 0)
            else:
                expect = model.pop(k, 0)
            assert resp[j, 0] == expect, (j, op, k, v, model)


def test_fused_step_consensus_to_device_execution():
    """Requests flow: inbox -> consensus tick -> on-device execution, no
    host round-trip; every replica's app state converges identically."""
    state = st.create_groups(
        st.init_state(R, G, W), np.arange(G, dtype=np.int32),
        np.ones((G, R), bool),
    )
    kv = init_kv(R, G, slots=S, table=1 << 12)
    kv = register_requests(
        kv, [101, 102], [OP_PUT, OP_PUT], [3, 4], [31, 41]
    )
    req = np.zeros((R, 4, G), np.int32)
    req[0, 0, 0] = 101
    req[0, 1, 2] = 102
    inbox = TickInbox(jnp.asarray(req),
                      jnp.zeros((R, 4, G), jnp.bool_),
                      jnp.ones((R,), jnp.bool_))
    empty = TickInbox(jnp.zeros((R, 4, G), jnp.int32),
                      jnp.zeros((R, 4, G), jnp.bool_),
                      jnp.ones((R,), jnp.bool_))
    executed = 0
    for i in range(6):
        state, kv, out, resp, miss = fused_step_jit(
            state, kv, inbox if i == 0 else empty
        )
        executed += int(np.asarray(out.exec_count).sum())
        assert not np.asarray(miss).any()
    assert executed >= 2 * R  # both requests executed on every replica
    keys = np.asarray(kv.key)
    vals = np.asarray(kv.val)
    for r in range(R):
        assert vals[r, 0, 3 & (S - 1)] == 31 and keys[r, 0, 3 & (S - 1)] == 3
        assert vals[r, 2, 4 & (S - 1)] == 41
    # all replicas converged to identical app state
    for r in range(1, R):
        assert np.array_equal(keys[0], keys[r])
        assert np.array_equal(vals[0], vals[r])


def test_checkpoint_restore_roundtrip():
    kv = init_kv(R, G, slots=S, table=1 << 10)
    kv = register_requests(kv, [1, 2], [OP_PUT, OP_PUT], [3, 6], [30, 60])
    req, cnt = make_exec([(0, 1, [1, 2])])
    kv, _, _ = kv_apply(kv, req, cnt)
    class Holder:  # any object with a mutable .kv (the manager, in prod)
        pass

    owner = Holder()
    owner.kv = kv
    app = DeviceKVApp(owner, replica=0, row_of=lambda name: 1)
    blob = app.checkpoint("svc")
    assert blob
    # wipe and restore
    app.kv = app.kv._replace(
        key=app.kv.key.at[0, 1].set(0), val=app.kv.val.at[0, 1].set(0)
    )
    app.restore("svc", blob)
    assert int(app.kv.val[0, 1, 3 & (S - 1)]) == 30
    assert int(app.kv.val[0, 1, 6 & (S - 1)]) == 60
    # the scalar fallback applies descriptors with kv_apply's semantics
    from gigapaxos_tpu.models.device_kv import pack_desc

    resp = app.execute("svc", pack_desc(OP_PUT, 3, 99), 7)
    assert resp == (99).to_bytes(4, "little")
    assert int(app.kv.val[0, 1, 3 & (S - 1)]) == 99
    # non-descriptor payloads are inert (control-plane noops)
    assert app.execute("svc", b"x", 8) == b""

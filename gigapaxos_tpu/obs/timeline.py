"""Scenario timeline recorder (ISSUE 18).

ROADMAP item 5's bad-day scenarios (mass recovery, evacuation storms)
have exit criteria that are all TIME-SERIES measurements — goodput dip
depth, shed window length, time-to-recover — which the point-in-time
scrape plane (obs/metrics.py) cannot answer.  This module is the
instrument: a background sampler that records metric series against wall
clock into a bounded ring, with EVENT ANNOTATIONS (crash, restart,
migration, replay progress) interleaved on the same clock, so a plot of
"goodput vs t" can be read against "node 2 was SIGKILLed here".

One :class:`TimelineRecorder` runs per node/worker; the cell supervisor
merges per-cell snapshots with :func:`merge_timelines` onto one clock
(wall time is the shared axis — cells run on one host, so skew is the
process-scheduling noise floor, well under the sample interval).  The
``results_recovery_*`` artifacts are read straight from the merged doc.

Families registered here (tests/test_obs_coverage.py WIRING):
``timeline_samples_total``, ``timeline_events_total``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional

from .metrics import registry as _registry


def registry_sampler(*families: str) -> Callable[[], Dict[str, float]]:
    """A ``sample_fn`` reading named families from the process registry,
    summing across label sets (a node's per-class counters collapse to
    one series).  Histograms contribute their p99."""

    def sample() -> Dict[str, float]:
        snap = _registry().snapshot()
        out: Dict[str, float] = {}
        for key, val in snap.items():
            fam = key.split("{", 1)[0]
            if fam not in families:
                continue
            if isinstance(val, dict):  # histogram snapshot -> p99 series
                out[fam + "_p99"] = val.get("p99", 0.0)
            else:
                out[fam] = out.get(fam, 0.0) + val
        return out

    return sample


class TimelineRecorder:
    """Samples ``sample_fn()`` every ``interval_s`` into a bounded ring.

    ``annotate(kind, **data)`` interleaves an event on the same wall
    clock from any thread.  ``snapshot()`` returns the JSON document the
    ``/timeline`` route serves; ``merge_timelines`` composes several.
    """

    def __init__(self, sample_fn: Callable[[], Dict[str, float]],
                 interval_s: float = 0.25, cap: int = 4096,
                 node: str = "?"):
        self.node = node
        self.interval_s = max(0.01, float(interval_s))
        self._sample_fn = sample_fn
        self._samples: "collections.deque[dict]" = collections.deque(
            maxlen=cap)
        self._events: "collections.deque[dict]" = collections.deque(
            maxlen=cap)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.t0 = time.time()
        self._samples_c = _registry().counter(
            "timeline_samples_total",
            help="timeline metric samples recorded", node=node)
        self._events_c = _registry().counter(
            "timeline_events_total",
            help="timeline event annotations recorded", node=node)

    # --------------------------------------------------------------- sampling
    def sample_once(self) -> dict:
        """Take one sample now (the thread's body; also the test hook —
        deterministic tests drive the clock without the thread)."""
        row = {"t": time.time()}
        try:
            row.update(self._sample_fn())
        except Exception:
            # a broken source must not kill the sampler; the gap itself
            # is visible in the series
            row["sample_error"] = 1
        with self._lock:
            self._samples.append(row)
        self._samples_c.inc()
        return row

    def annotate(self, kind: str, **data) -> None:
        ev = {"t": time.time(), "kind": kind}
        ev.update(data)
        with self._lock:
            self._events.append(ev)
        self._events_c.inc()

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "TimelineRecorder":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"timeline:{self.node}", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    # --------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "node": self.node,
                "t0": self.t0,
                "interval_s": self.interval_s,
                "samples": list(self._samples),
                "events": list(self._events),
            }


def merge_timelines(snaps: Iterable[dict]) -> dict:
    """Compose per-cell timeline snapshots onto one wall clock: samples
    stay per-source (series have different columns per cell), events
    merge into one list sorted by time with a ``node`` tag — the
    supervisor's ``/timeline`` body."""
    snaps = [s for s in snaps if s]
    events: List[dict] = []
    sources = {}
    for s in snaps:
        node = str(s.get("node", "?"))
        sources[node] = {
            "t0": s.get("t0"),
            "interval_s": s.get("interval_s"),
            "samples": s.get("samples", []),
        }
        for ev in s.get("events", []):
            events.append(dict(ev, node=node))
    events.sort(key=lambda e: e.get("t", 0.0))
    return {
        "t0": min((s.get("t0") or 0.0) for s in snaps) if snaps else 0.0,
        "sources": sources,
        "events": events,
    }

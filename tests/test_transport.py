"""Transport/messenger tests: loopback sockets, reconnect, demux routing.

Mirrors the reference's NIO tests (``nio/nioutils/NIOTester*.java``): real
sockets on 127.0.0.1, no mocks.
"""

import threading
import time

from gigapaxos_tpu.net import JsonDemux, Messenger, NodeMap


class Sink:
    def __init__(self):
        self.got = []
        self.cv = threading.Condition()

    def __call__(self, sender, packet):
        with self.cv:
            self.got.append((sender, packet))
            self.cv.notify_all()

    def wait_for(self, n, timeout=5.0):
        deadline = time.monotonic() + timeout
        with self.cv:
            while len(self.got) < n:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self.cv.wait(timeout=left)
        return True


def make_pair():
    nm = NodeMap()
    a = Messenger("A", ("127.0.0.1", 0), nm)
    b = Messenger("B", ("127.0.0.1", 0), nm)
    nm.add("A", "127.0.0.1", a.port)
    nm.add("B", "127.0.0.1", b.port)
    return nm, a, b


def test_send_recv_and_sender_stamp():
    nm, a, b = make_pair()
    try:
        sink = Sink()
        b.register("hello", sink)
        a.send("B", {"type": "hello", "x": 1})
        assert sink.wait_for(1)
        sender, pkt = sink.got[0]
        assert sender == "A" and pkt["sender"] == "A" and pkt["x"] == 1
    finally:
        a.close()
        b.close()


def test_loopback_short_circuit():
    nm, a, b = make_pair()
    try:
        sink = Sink()
        a.register("self", sink)
        a.send("A", {"type": "self"})
        assert sink.wait_for(1, timeout=1)
        assert a.transport.stats.get("loopback") == 1
        assert a.transport.stats.get("sent") is None  # no socket involved
    finally:
        a.close()
        b.close()


def test_multicast_and_bytes():
    nm = NodeMap()
    nodes = {nid: Messenger(nid, ("127.0.0.1", 0), nm) for nid in "ABC"}
    for nid, m in nodes.items():
        nm.add(nid, "127.0.0.1", m.port)
    try:
        sinks = {}
        for nid, m in nodes.items():
            sinks[nid] = Sink()
            m.register("mc", sinks[nid])
        nodes["A"].multicast(["A", "B", "C"], {"type": "mc"})
        for nid in "ABC":
            assert sinks[nid].wait_for(1), nid
        # raw bytes path
        blob = []
        ev = threading.Event()

        def on_bytes(sender, payload):
            blob.append((sender, payload))
            ev.set()

        nodes["B"].demux.bytes_handler = on_bytes
        nodes["A"].send_bytes("B", b"\x00\x01binary")
        assert ev.wait(5)
        assert blob[0] == ("A", b"\x00\x01binary")
    finally:
        for m in nodes.values():
            m.close()


def test_reconnect_after_peer_restart():
    nm = NodeMap()
    a = Messenger("A", ("127.0.0.1", 0), nm)
    b = Messenger("B", ("127.0.0.1", 0), nm)
    nm.add("A", "127.0.0.1", a.port)
    nm.add("B", "127.0.0.1", b.port)
    sink = Sink()
    b.register("m", sink)
    try:
        a.send("B", {"type": "m", "i": 0})
        assert sink.wait_for(1)
        # "crash" B and restart it on the same port
        port = b.port
        b.close()
        time.sleep(0.1)
        b2 = Messenger("B", ("127.0.0.1", port), nm)
        sink2 = Sink()
        b2.register("m", sink2)
        # a frame written into the dead socket can be silently lost (TCP
        # buffers it before the RST arrives) — end-to-end liveness is the
        # protocol-task layer's job, so retry like one until delivery; the
        # transport must reconnect underneath without intervention
        deadline = time.monotonic() + 10
        i = 0
        while not sink2.got and time.monotonic() < deadline:
            i += 1
            a.send("B", {"type": "m", "i": i})
            time.sleep(0.1)
        assert sink2.wait_for(1, timeout=1)
        b2.close()
    finally:
        a.close()


def test_reset_peer_strands_backlog_and_in_hand_frame():
    """A dead peer's backlog — including the frame the writer thread holds
    through its reconnect-retry window, which no queue drain can reach —
    must not be delivered to a later incarnation after reset_peer; new
    sends afterwards flow normally (pendingWrites cleanup on node failure,
    ``nio/NIOTransport.java:65-114``)."""
    nm, a, b = make_pair()
    try:
        sink = Sink()
        b.register("m", sink)
        a.send("B", {"type": "m", "i": 0})
        assert sink.wait_for(1)
        b.close()
        time.sleep(0.1)
        # drop A's established-but-dead socket so the next send is forced
        # into the connect path (writing into the dead TCP buffer can
        # otherwise "succeed" locally and vacate the writer's hand)
        a.transport.reset_peer("B")
        # the writer pops i=1 and sits in connect-retry (~3s) holding it;
        # i=2/i=3 stay in the queue
        for i in (1, 2, 3):
            a.send("B", {"type": "m", "i": i})
        time.sleep(0.3)
        a.transport.reset_peer("B")
        # restart B on a fresh port well inside the old retry window: the
        # stranded frame would be delivered here if reset didn't stamp it
        b2 = Messenger("B", ("127.0.0.1", 0), nm)
        nm.add("B", "127.0.0.1", b2.port)
        sink2 = Sink()
        b2.register("m", sink2)
        deadline = time.monotonic() + 3.5  # outlasts the retry/backoff span
        while time.monotonic() < deadline:
            assert not sink2.got, f"stale frame delivered: {sink2.got}"
            time.sleep(0.1)
        assert a.transport.stats.get("reset_drops", 0) >= 1
        a.send("B", {"type": "m", "i": 4})
        assert sink2.wait_for(1)
        assert [p["i"] for _s, p in sink2.got] == [4]
        b2.close()
    finally:
        a.close()


def test_send_bytes_many_coalesces_syscalls_and_preserves_order():
    """A burst handed over as one ``send_bytes_many`` call leaves in fewer
    writev syscalls than frames: the writer drains the whole backlog into
    one ``sendmsg`` vector (up to the coalescing window) instead of one
    ``sendall`` per frame."""
    nm, a, b = make_pair()
    try:
        got = []
        cv = threading.Condition()

        def on_bytes(sender, payload):
            with cv:
                got.append(payload)
                cv.notify_all()

        b.demux.bytes_handler = on_bytes
        payloads = [b"x%03d" % i for i in range(400)]
        # no warm-up send: the first frame rides the connect path, so the
        # rest of the burst is queued by the time the writer drains
        a.send_bytes_many("B", payloads)
        deadline = time.monotonic() + 10
        with cv:
            while len(got) < 400:
                left = deadline - time.monotonic()
                assert left > 0, f"only {len(got)}/400 frames arrived"
                cv.wait(timeout=left)
        assert got == payloads  # batching must not reorder
        # delivery on B's reader can outrun A's writer thread bumping its
        # counters (one-core boxes): give the stats a moment to settle
        stats = a.transport.stats
        deadline = time.monotonic() + 5
        while stats.get("sent", 0) < 400 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert stats.get("sent") == 400
        assert 0 < stats.get("send_syscalls", 0) < stats["sent"], stats
    finally:
        a.close()
        b.close()


def test_batched_sends_never_interleave_across_generations():
    """reset_peer in the middle of a staged batch: every frame stamped with
    the old generation — including the batch the writer already holds in
    hand through its reconnect window — is dropped wholesale, and only the
    post-reset batch reaches the peer's next incarnation, in order.  A
    drained writev batch is generation-HOMOGENEOUS by construction; this is
    the observable guarantee."""
    nm, a, b = make_pair()
    try:
        sink = Sink()
        b.register("m", sink)
        a.send("B", {"type": "m", "i": 0})
        assert sink.wait_for(1)
        b.close()
        time.sleep(0.1)
        a.transport.reset_peer("B")  # force the next send into connect-retry
        a.send_bytes_many("B", [b"old%d" % i for i in range(10)])
        time.sleep(0.3)  # writer now holds the old-gen batch mid-retry
        a.transport.reset_peer("B")
        a.send_bytes_many("B", [b"new%d" % i for i in range(10)])
        b2 = Messenger("B", ("127.0.0.1", 0), nm)
        nm.add("B", "127.0.0.1", b2.port)
        got = []
        cv = threading.Condition()

        def on_bytes(sender, payload):
            with cv:
                got.append(payload)
                cv.notify_all()

        b2.demux.bytes_handler = on_bytes
        deadline = time.monotonic() + 15
        with cv:
            while len(got) < 10:
                left = deadline - time.monotonic()
                assert left > 0, f"only {len(got)}/10 new frames: {got}"
                cv.wait(timeout=left)
        time.sleep(0.3)  # grace window: a stale frame would surface here
        assert got == [b"new%d" % i for i in range(10)], got
        assert a.transport.stats.get("reset_drops", 0) >= 10
        b2.close()
    finally:
        a.close()


def test_unknown_type_goes_to_default_handler():
    nm, a, b = make_pair()
    try:
        sink = Sink()
        b.demux.default_handler = sink
        a.send("B", {"type": "mystery"})
        assert sink.wait_for(1)
        assert sink.got[0][1]["type"] == "mystery"
    finally:
        a.close()
        b.close()


def test_unresolvable_destination_drops_without_crash():
    nm, a, b = make_pair()
    try:
        a.send("GHOST", {"type": "m"})  # no address for GHOST
        deadline = time.monotonic() + 8
        while time.monotonic() < deadline:
            if a.transport.stats.get("dropped", 0) >= 1:
                break
            time.sleep(0.05)
        assert a.transport.stats.get("dropped", 0) >= 1
    finally:
        a.close()
        b.close()


def test_demux_handler_exception_does_not_kill_reader():
    nm, a, b = make_pair()
    try:
        sink = Sink()

        def bad(sender, packet):
            raise RuntimeError("boom")

        b.register("bad", bad)
        b.register("good", sink)
        a.send("B", {"type": "bad"})
        a.send("B", {"type": "good"})
        assert sink.wait_for(1)
    finally:
        a.close()
        b.close()

"""Shared locking helper for the host managers.

Both data-plane managers (paxos, chain) serialize their public API against
the tick driver on a reentrant ``self.lock`` (the reference synchronizes on
the instance map the same way, PaxosManager.java:2284-2412); this decorator
is that convention in one place.
"""

from __future__ import annotations

import functools


def locked(fn):
    """Serialize a method on ``self.lock`` (reentrant: callbacks that
    re-enter the manager from the tick thread are fine)."""

    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        with self.lock:
            return fn(self, *a, **kw)

    return wrapper

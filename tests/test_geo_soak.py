"""Geo soak smoke + slow full run (``benchmarks/geo_soak.py``).

The tier-1 smoke drives one shortened region-loss soak on the us3
topology with fast re-election on: commits must flow in every phase
(before / during / after the region cut), the S1 per-slot ledger must
stay clean, replicas must converge after the drain, and a new
coordinator must be seated within the detection fuse plus a small
election allowance.  The ``slow`` test runs the artifact-sized
parameters for both election modes and pins the headline ordering —
fast re-election seats a coordinator strictly sooner than a classical
full prepare.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "benchmarks"))

import geo_soak  # noqa: E402


def test_geo_soak_smoke_region_loss_slo():
    r = geo_soak.soak("us3", fast=True, seed=0, ticks_per_phase=60,
                      every=6, ms_per_round=10.0)
    assert r["safety"]["violations"] == 0
    assert r["safety"]["observations"] > 0  # ledger actually attached
    assert r["dbs_converged"]
    # liveness in every phase: the majority keeps committing through the
    # region loss, and the healed region doesn't wedge anything
    for ph in ("before", "during", "after"):
        assert r["slo"][ph]["n"] >= 1, r["slo"]
        assert r["slo"][ph]["p50_ms"] is not None
    # a survivor was seated promptly: detection fuse + a few ticks
    assert r["ticks_to_new_coordinator"] is not None
    assert r["ticks_to_new_coordinator"] <= r["detect_after_ticks"] + 6, r


def test_geo_failover_ab_smoke_fast_beats_full():
    ab = geo_soak.failover_ab("us3", seed=0, ms_per_round=10.0)
    f, c = ab["fast"], ab["full_prepare"]
    assert f["ticks_to_coordinator"] < c["ticks_to_coordinator"], ab
    assert f["ticks_to_first_commit"] < c["ticks_to_first_commit"], ab
    assert ab["coordinator_speedup"] > 1.0


@pytest.mark.slow
def test_geo_soak_full_artifact_parameters():
    """Artifact-sized run (what ``python benchmarks/geo_soak.py`` writes):
    both election modes, safety + convergence + per-phase liveness, and
    the fast mode reaching a new coordinator no later than the classical
    one."""
    runs = {fast: geo_soak.soak("us3", fast=fast, seed=0,
                                ticks_per_phase=160, every=4,
                                ms_per_round=10.0)
            for fast in (False, True)}
    for r in runs.values():
        assert r["safety"]["violations"] == 0
        assert r["dbs_converged"]
        for ph in ("before", "during", "after"):
            assert r["slo"][ph]["n"] >= 10
    assert (runs[True]["ticks_to_new_coordinator"]
            <= runs[False]["ticks_to_new_coordinator"]), runs

"""Directory-free cell routing.

Each serving cell owns a static shard of the group-name space:
``cell_of(name, n) = crc32(name) % n``.  Any client (or edge) computes the
owner with zero metadata — the consistent-hashing idea one level down, with
a fixed modulus because the cell count of a host is a deployment constant,
not an elastic membership.  Names migrated across cells
(migrator.CellMigrator) are the exceptions; they live in the override map,
exactly like the placement table layers overrides on the hash ring.

:class:`CellRouter` is the client-side directory.  It duck-types the
placement-table surface ``client._route`` consults (``lead_server`` /
``order_actives`` / ``epoch``) and adds the cell extensions the client uses
when present:

* ``rc_ids(name)``   — the owner cell's reconfigurators (control RPCs for a
  name must go to the cell that holds its records);
* ``actives_of(name)`` — the owner cell's active set, answered with NO RC
  round-trip: static hash placement plus the override map IS the directory,
  which is how a first request reaches the right cell with zero extra hops.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence


def cell_of(name: str, n_cells: int) -> int:
    """The cell owning ``name`` under static hash placement."""
    if n_cells <= 1:
        return 0
    return zlib.crc32(name.encode()) % n_cells


class CellRouter:
    """name -> owner cell -> that cell's node ids, for one host.

    ``actives_by_cell[k]`` / ``rcs_by_cell[k]`` list cell k's node ids in
    the merged topology the supervisor hands to clients (cell-qualified ids
    like ``c0.AR1``).  ``epoch`` bumps on every override change so client
    route caches invalidate (client._route).
    """

    def __init__(self, actives_by_cell: Sequence[Sequence[str]],
                 rcs_by_cell: Sequence[Sequence[str]]):
        if len(actives_by_cell) != len(rcs_by_cell):
            raise ValueError("need one active set and one RC set per cell")
        self.actives_by_cell = [list(c) for c in actives_by_cell]
        self.rcs_by_cell = [list(c) for c in rcs_by_cell]
        self.n_cells = len(self.actives_by_cell)
        self.overrides: Dict[str, int] = {}
        self.epoch = 0
        self._cell_of_node = {
            n: k for k, cell in enumerate(self.actives_by_cell) for n in cell
        }

    # ------------------------------------------------------------- directory
    def cell(self, name: str) -> int:
        ov = self.overrides.get(name)
        return cell_of(name, self.n_cells) if ov is None else ov

    def rc_ids(self, name: str) -> List[str]:
        return list(self.rcs_by_cell[self.cell(name)])

    def actives_of(self, name: str) -> List[str]:
        return list(self.actives_by_cell[self.cell(name)])

    # ------------------------------------------------------------- overrides
    def set_override(self, name: str, cell: int) -> None:
        if not (0 <= cell < self.n_cells):
            raise ValueError(f"cell {cell} out of range")
        self.overrides[name] = int(cell)
        self.epoch += 1

    def clear_override(self, name: str) -> None:
        if self.overrides.pop(name, None) is not None:
            self.epoch += 1

    def load_table(self, table) -> None:
        """Adopt the cell overrides a PlacementTable carries (its
        ``cell_overrides`` map, host shard ignored on a single host) plus
        its epoch, so replicated placement commands drive this router."""
        self.overrides = {
            n: cell for n, (_shard, cell) in table.cell_overrides.items()
            if 0 <= cell < self.n_cells
        }
        self.epoch = int(table.epoch)

    # ------------------------------------- placement-table duck-type surface
    def lead_server(self, name: str) -> Optional[str]:
        """None: within the owner cell the client's RTT-ranked pick decides
        (the cell, not the node, is what this router constrains)."""
        return None

    def order_actives(self, name: str, actives: Sequence[str]) -> List[str]:
        """Owner cell's nodes first, foreign-cell nodes (stale caller list)
        after — a client iterating the result converges on the owner."""
        own = self.cell(name)
        mine = [a for a in actives if self._cell_of_node.get(a) == own]
        rest = [a for a in actives if self._cell_of_node.get(a) != own]
        return mine + rest

"""Group health plane tests (ISSUE 18): needle-in-a-million detection.

Mode A: per-group last-commit age, coordinator churn, wedged detection
and lease-wait pressure are folded INSIDE the fused tick and reduced on
device into log2 histograms + scalar gauges + top-K anomaly columns, so
the host learns which of a million groups are sick at O(K)/tick.
Mode B keeps a numpy host twin of the same fold.

Covered here: wedge detection across dispatch modes, top-K extraction
naming the sick row, flight-recorder wedge/recover transitions, the
single-group drill-down (``group_info``) including bare-name epoch
resolution and the WAL tail, row-lifecycle clearing, the ``merge_health``
composite, config gates, the ``group_health`` off bit-identity guarantee
(journal bytes identical with the fold on or off), and a chaos-driven
Mode B scenario where a quorum-loss wedge surfaces in the top-K within a
bounded number of ticks and clears on recovery.
"""

import json
import os

import numpy as np
import pytest

from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.modeb import ModeBNode
from gigapaxos_tpu.obs.flight import FlightRecorder
from gigapaxos_tpu.ops.tick import HB, HealthView, merge_health
from gigapaxos_tpu.paxos.manager import PaxosManager
from gigapaxos_tpu.testing.chaos import (ChaosEvent, ChaosSchedule,
                                         SimChaosRunner)
from gigapaxos_tpu.testing.simnet import SimNet
from gigapaxos_tpu.wal.logger import PaxosLogger


def mk_cfg(G=8, G_reg=0, compact=False, pipeline=False, health=True,
           wedge=4, topk=4, leases=False):
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = G
    cfg.paxos.register_groups = G_reg
    cfg.paxos.compact_outbox = compact
    cfg.paxos.pipeline_ticks = pipeline
    cfg.paxos.group_health = health
    cfg.paxos.health_wedge_ticks = wedge
    cfg.paxos.health_topk = topk
    if leases:
        cfg.paxos.read_leases = True
        cfg.paxos.lease_ticks = 16
        cfg.paxos.lease_margin_ticks = 4
    return cfg


def pump(m, n):
    for _ in range(n):
        m.tick()
    m.drain_pipeline()


# ------------------------------------------------------------ mode A basics

@pytest.mark.parametrize("compact,pipeline,g_reg",
                         [(False, False, 0), (False, True, 0),
                          (True, False, 4), (True, True, 4)])
def test_wedge_detected_in_topk(compact, pipeline, g_reg):
    """THE needle: kill a quorum under one of several groups, offer it
    work, and the health fold must name that row in top_stuck within
    wedge_ticks + a small pipeline slack — in every dispatch mode."""
    m = PaxosManager(mk_cfg(compact=compact, pipeline=pipeline,
                            G_reg=g_reg), 3, [KVApp() for _ in range(3)])
    for i in range(3):
        m.create_paxos_instance(f"svc{i}", [0, 1, 2])
    for i in range(3):
        m.propose(f"svc{i}", b"PUT k v")
    pump(m, 6)
    h = m.health_snapshot()
    assert h is not None and h["allocated"] >= 3
    assert h["wedged"] == 0

    m.set_alive(1, False)
    m.set_alive(2, False)
    m.propose("svc1", b"PUT k w")  # offered work that cannot commit
    detected_at = None
    for t in range(4 + 2 * 4 + 8):  # wedge_ticks=4 + slack
        pump(m, 1)
        h = m.health_snapshot()
        stuck = [e["name"] for e in h["top_stuck"]]
        if h["wedged"] >= 1 and "svc1" in stuck:
            detected_at = t
            break
    assert detected_at is not None, m.health_snapshot()
    assert h["backlogged"] >= 1
    assert h["max_stall_ticks"] >= 4
    # the log2 stall histogram sees the sick group in a nonzero bucket
    assert sum(h["hist_stall"][1:]) >= 1
    # healthy groups did not wedge
    assert h["wedged"] <= 3

    # quorum back: the group must drain and leave the wedged set
    m.set_alive(1, True)
    m.set_alive(2, True)
    for _ in range(30):
        pump(m, 1)
        if m.health_snapshot()["wedged"] == 0:
            break
    assert m.health_snapshot()["wedged"] == 0


def test_flight_records_wedge_and_recover_transitions(tmp_path):
    """Health transitions feed the crash flight recorder: newly wedged
    and newly recovered groups each leave one event."""
    m = PaxosManager(mk_cfg(), 3, [KVApp() for _ in range(3)])
    m.flight = FlightRecorder(str(tmp_path / "f.json"), node="t")
    m.create_paxos_instance("svc", [0, 1, 2])
    m.propose("svc", b"PUT k v")
    pump(m, 4)
    m.set_alive(1, False)
    m.set_alive(2, False)
    m.propose("svc", b"PUT k w")
    pump(m, 12)
    m.set_alive(1, True)
    m.set_alive(2, True)
    pump(m, 20)
    doc = FlightRecorder.read(m.flight.persist())
    kinds = [e["kind"] for e in doc["events"]]
    assert "group_wedged" in kinds
    assert "group_recovered" in kinds
    wedge_ev = next(e for e in doc["events"] if e["kind"] == "group_wedged")
    assert wedge_ev["name"] == "svc"
    assert wedge_ev["stall_ticks"] >= 4


def test_coordinator_churn_counted():
    """Coordinator handoffs raise the churn EWMA for exactly the flapped
    group; stable groups stay at zero churn."""
    m = PaxosManager(mk_cfg(wedge=16), 4, [KVApp() for _ in range(4)])
    m.create_paxos_instance("flappy", [0, 1, 2])
    m.create_paxos_instance("calm", [1, 2, 3])  # no member flaps
    for n in ("flappy", "calm"):
        m.propose(n, b"PUT k v")
    pump(m, 6)
    for _ in range(3):  # kill / revive the coordinator: forced handoffs
        m.set_alive(0, False)
        pump(m, 10)
        m.set_alive(0, True)
        pump(m, 10)
    h = m.health_snapshot()
    churny = {e["name"]: e["value"] for e in h["top_churny"]}
    assert churny.get("flappy", 0) > 0
    assert h["max_churn"] > 0
    gi = m.group_info("calm")
    assert gi["health"]["churn"] == 0.0


def test_group_info_drilldown_and_wal_tail(tmp_path):
    """The ``/group/<name>`` body: full replica table from one row-gather,
    health columns, pending intake, and a bounded WAL tail naming the
    recent journal records that touched the row."""
    cfg = mk_cfg(leases=True)
    wal = PaxosLogger(os.path.join(str(tmp_path), "wal"))
    m = PaxosManager(cfg, 3, [KVApp() for _ in range(3)], wal=wal)
    m.create_paxos_instance("svc", [0, 1, 2])
    for i in range(4):
        m.propose("svc", f"PUT k v{i}".encode())
        pump(m, 2)
    gi = m.group_info("svc")
    assert gi["name"] == "svc" and gi["mode"] == "log"
    assert gi["members"] == [0, 1, 2]
    assert set(gi["replicas"]) == {0, 1, 2}
    r0 = gi["replicas"][0]
    assert r0["alive"] and r0["exec_slot"] >= 4
    assert sum(1 for r in gi["replicas"].values() if r["coordinator"]) == 1
    assert gi["health"]["stall_ticks"] >= 0
    assert "lease" in gi
    ops = [rec["op"] for rec in gi["wal_tail"]]
    assert "create" in ops or "tick" in ops
    placed = [p for rec in gi["wal_tail"] if rec["op"] == "tick"
              for p in rec["placed"]]
    assert placed, gi["wal_tail"]
    assert m.group_info("nope") is None
    # the whole doc is JSON-serializable (it is an HTTP body)
    json.dumps(gi)
    wal.close()


def test_health_cleared_on_remove_and_recreate():
    """Row lifecycle: removing a wedged group drops its health columns —
    no ghost needle through the row recycler."""
    m = PaxosManager(mk_cfg(), 3, [KVApp() for _ in range(3)])
    m.create_paxos_instance("svc", [0, 1, 2])
    m.propose("svc", b"PUT k v")
    pump(m, 4)
    m.set_alive(1, False)
    m.set_alive(2, False)
    m.propose("svc", b"PUT k w")
    pump(m, 10)
    assert m.health_snapshot()["wedged"] == 1
    m.set_alive(1, True)
    m.set_alive(2, True)
    m.remove_paxos_instance("svc")
    pump(m, 2)
    h = m.health_snapshot()
    assert h["wedged"] == 0
    assert all(e["name"] != "svc" for e in h["top_stuck"])
    # the recycled row starts cold
    m.create_paxos_instance("svc2", [0, 1, 2])
    pump(m, 2)
    gi = m.group_info("svc2")
    assert gi["health"]["stall_ticks"] <= 2
    assert gi["health"]["churn"] == 0.0


def test_register_plane_health_and_merge():
    """Mixed planes: a wedged register group surfaces through the same
    top-K with its composite row id (register rows live above G_log)."""
    m = PaxosManager(mk_cfg(compact=True, G_reg=4), 3,
                     [KVApp() for _ in range(3)])
    m.create_paxos_instance("log0", [0, 1, 2])
    m.create_paxos_instance("reg0", [0, 1, 2], register=True)
    m.propose("reg0", b"PUT k v")
    m.propose("log0", b"PUT k v")
    pump(m, 6)
    h = m.health_snapshot()
    assert h["allocated"] >= 2
    names = {e["name"] for e in h["top_hot"]}
    assert "reg0" in names or "log0" in names
    gi = m.group_info("reg0")
    assert gi["mode"] == "register"
    assert "version" in gi


def test_merge_health_unit():
    """The two-plane composite: counts sum, maxima max, histograms add,
    top-K re-ranks with register rows offset into composite row space."""
    K = 4

    def hv(vals, rows, alloc, hist0):
        z = np.zeros(K, np.int32)
        hist = np.zeros(HB, np.int32)
        hist[0] = hist0
        return HealthView(
            alloc=alloc, backlog=1, wedged=1, max_stall=int(max(vals)),
            max_churn=2, lease_wait=0,
            hist_stall=hist, hist_churn=hist.copy(),
            stuck_val=np.array(vals, np.int32),
            stuck_row=np.array(rows, np.int32),
            churn_val=z, churn_row=z.copy(),
            heat_val=z.copy(), heat_row=z.copy())

    left = hv([9, 3, 0, 0], [5, 1, 0, 0], 4, 2)
    right = hv([7, 4, 0, 0], [2, 0, 0, 0], 2, 3)
    g_log = 16
    out = merge_health(left, right, g_log, K)
    assert out.alloc == 6 and out.backlog == 2 and out.wedged == 2
    assert out.max_stall == 9
    assert int(out.hist_stall[0]) == 5
    # 9@row5 (log), 7@row 16+2 (register), 4@row 16+0, 3@row1
    assert list(out.stuck_val[:4]) == [9, 7, 4, 3]
    assert list(out.stuck_row[:4]) == [5, g_log + 2, g_log + 0, 1]


def test_health_config_gates():
    cfg = GigapaxosTpuConfig()
    cfg.paxos.group_health = True
    cfg.paxos.health_topk = 0
    with pytest.raises(ValueError):
        cfg.paxos.__post_init__()
    cfg2 = GigapaxosTpuConfig()
    cfg2.paxos.group_health = True
    cfg2.paxos.health_wedge_ticks = 0
    with pytest.raises(ValueError):
        cfg2.paxos.__post_init__()
    cfg3 = GigapaxosTpuConfig()
    cfg3.paxos.group_health = True
    cfg3.paxos.device_app = True
    with pytest.raises(ValueError):
        PaxosManager(cfg3, 3, [KVApp() for _ in range(3)])


# ----------------------------------------------------------- off = free

def test_health_off_bit_identity(tmp_path):
    """The flag-off guarantee and its stronger cousin: the fold is pure
    observation — the log-plane state arrays and the journal BYTES are
    identical with group_health on or off."""
    results = []
    for health, sub in ((False, "off"), (True, "on")):
        cfg = mk_cfg(health=health, compact=True)
        d = os.path.join(str(tmp_path), sub)
        wal = PaxosLogger(d, checkpoint_every_ticks=1000)
        m = PaxosManager(cfg, 3, [KVApp() for _ in range(3)], wal=wal)
        m.create_paxos_instance("svc", [0, 1, 2])
        for i in range(12):
            m.propose("svc", f"PUT k{i} v{i}".encode())
            m.tick()
        pump(m, 8)
        wal.close()
        state = {f: np.asarray(getattr(m.state, f))
                 for f in m.state._fields}
        jpaths = sorted(p for p in os.listdir(d)
                        if p.startswith("journal."))
        blobs = [open(os.path.join(d, p), "rb").read() for p in jpaths]
        results.append((state, jpaths, blobs))
    (st_a, jp_a, bl_a), (st_b, jp_b, bl_b) = results
    for f in st_a:
        assert np.array_equal(st_a[f], st_b[f]), f
    assert jp_a == jp_b
    assert bl_a == bl_b


# ------------------------------------------------------- mode B host twin

IDS = ["N0", "N1", "N2"]


def _build_modeb(seed):
    net = SimNet(seed=seed)
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 8
    cfg.paxos.window = 8
    cfg.paxos.group_health = True
    cfg.paxos.health_wedge_ticks = 8
    cfg.paxos.health_topk = 4
    apps = {n: KVApp() for n in IDS}
    nodes = {n: ModeBNode(cfg, IDS, n, apps[n], net.messenger(n),
                          anti_entropy_every=8) for n in IDS}
    for nd in nodes.values():
        # epoch-qualified paxos names, as the reconfiguration layer makes
        # them — the drill-down's bare-name resolution is exercised below
        nd.create_group("svc#0", [0, 1, 2])
        nd.create_group("bystander#0", [0, 1, 2])
    return net, nodes, apps


def test_modeb_chaos_wedge_detected_and_recovered(tmp_path):
    """Chaos-driven detection, per-process twin: a scripted quorum loss
    under one group wedges it; the surviving coordinator's health fold
    must surface the row in top_stuck within wedge_ticks + detection
    slack, record the flight transition, and clear it after recovery."""
    sched = ChaosSchedule("quorum_loss_wedge", [
        ChaosEvent(5, "propose",
                   {"node": "N0", "group": "svc#0", "payload": "PUT k v1"}),
        ChaosEvent(30, "crash", {"node": "N1", "detect_after": 2}),
        ChaosEvent(31, "crash", {"node": "N2", "detect_after": 2}),
        ChaosEvent(40, "propose",
                   {"node": "N0", "group": "svc#0", "payload": "PUT k v2"}),
        ChaosEvent(90, "recover", {"node": "N1"}),
    ], seed=7)
    net, nodes, apps = _build_modeb(seed=7)
    fr = FlightRecorder(str(tmp_path / "f.json"), node="N0")
    nodes["N0"].flight = fr
    runner = SimChaosRunner(net, nodes, sched)

    detect = {"at": None, "cleared": None}

    def on_tick(t):
        h = nodes["N0"].health_snapshot()
        if h is None:
            return
        stuck = {e["name"] for e in h["top_stuck"]}
        if (detect["at"] is None and h["wedged"] >= 1
                and "svc#0" in stuck):
            detect["at"] = t
        if (detect["at"] is not None and detect["cleared"] is None
                and t > 95 and h["wedged"] == 0):
            detect["cleared"] = t

    runner.run(160, on_tick=on_tick)
    # bounded detection: wedge began when the quorum-less propose landed
    # (tick 40); wedge_ticks=8 plus a small fold/FD slack
    assert detect["at"] is not None, nodes["N0"].health_snapshot()
    assert detect["at"] <= 40 + 8 + 12
    assert detect["cleared"] is not None, nodes["N0"].health_snapshot()
    # the undamaged group never wedged alongside
    assert all(e["name"] != "bystander#0"
               for e in nodes["N0"].health_snapshot()["top_stuck"])
    kinds = [e["kind"] for e in FlightRecorder.read(fr.persist())["events"]]
    assert "group_wedged" in kinds and "group_recovered" in kinds
    # the committed write from before the outage stayed committed
    assert runner.proposals[0]["resp"] == "OK"


# ------------------------------------------------- 2-cell host e2e (slow)

def _get(url, timeout=30.0, method="GET"):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


@pytest.mark.slow
def test_two_cell_host_health_routes(tmp_path):
    """The ISSUE 18 acceptance route check on a live 2-cell host:
    ``/healthz`` aggregates per-cell readiness, ``/health`` merges both
    cells' folds, ``/group/<name>`` resolves the OWNER cell through the
    same directory the edge uses, ``/timeline`` merges cell series with
    supervisor lifecycle events, and every route answers HEAD."""
    from gigapaxos_tpu.cells.supervisor import CellSupervisor
    from gigapaxos_tpu.config import CellsConfig

    cc = CellsConfig(enabled=True, n_cells=2, n_actives=3,
                     n_reconfigurators=1, pin_cores=False,
                     restart_backoff_s=0.2)
    sup = CellSupervisor(
        str(tmp_path / "cells"), cells=cc,
        paxos_overrides={"max_groups": 16, "group_health": True,
                         "health_topk": 4},
        http_port=0).start()
    try:
        c = sup.make_client()
        # s0/s1 hash to cell 0, s4/s5 to cell 1 (crc32 % 2)
        names = ["s0", "s1", "s4", "s5"]
        for n in names:
            assert c.create(n).get("ok"), n
        for i, n in enumerate(names):
            assert c.request(n, f"PUT k{i} v{i}".encode()) == b"OK"
        url = sup.metrics_server.url

        st, body = _get(url + "/healthz")
        assert st == 200, body
        doc = json.loads(body)
        assert doc["ok"] and set(doc["cells"]) == {"0", "1"}
        assert all(cd["up"] and cd["ok"] and not cd["draining"]
                   and not cd["wal_failed"]
                   for cd in doc["cells"].values())

        st, body = _get(url + "/health")
        assert st == 200, body
        hd = json.loads(body)
        assert hd["allocated"] == 4
        assert hd["wedged"] == 0
        # top lists carry the owning cell and both cells contributed
        assert {e["cell"] for e in hd["top_hot"]} == {0, 1}

        # drill-down finds each group on its OWNER cell
        seen_cells = set()
        for n in names:
            st, body = _get(url + f"/group/{n}")
            assert st == 200, (n, body)
            gd = json.loads(body)
            assert gd["name"].split("#")[0] == n
            assert "replicas" in gd and "health" in gd
            seen_cells.add(gd["cell"])
        assert seen_cells == {0, 1}  # 4 names spread over both cells
        st, _ = _get(url + "/group/doesnotexist")
        assert st == 404

        st, body = _get(url + "/timeline")
        assert st == 200, body
        tl = json.loads(body)
        assert {"SUP", "c0", "c1"} <= set(tl["sources"])
        assert any(e["kind"] == "boot" for e in tl["events"])
        assert any(len(s["samples"]) > 0
                   for k, s in tl["sources"].items() if k != "SUP")

        for p in ("/metrics", "/healthz", "/health", "/group/s0",
                  "/timeline"):
            st, body = _get(url + p, method="HEAD")
            assert st == 200 and body == "", (p, st)
    finally:
        sup.stop()


def test_modeb_group_info_bare_name_and_health():
    net, nodes, apps = _build_modeb(seed=3)
    for _ in range(20):
        for nd in nodes.values():
            nd.tick()
        net.pump()
    gi = nodes["N0"].group_info("svc")  # bare name -> svc#0
    assert gi is not None and gi["name"] == "svc#0"
    assert gi["members"] == [0, 1, 2] and gi["epoch"] == 0
    assert gi["coordinator"] in (0, 1, 2)
    assert gi["health"]["stall_ticks"] >= 0
    assert nodes["N0"].group_info("ghost") is None
    json.dumps(gi)

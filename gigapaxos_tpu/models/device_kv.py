"""Device-resident KV application: execution fused behind the consensus tick.

The reference's workload app (``gigapaxos/testing/TESTPaxosApp.java:60``)
executes inside the JVM next to the acceptor; every decision still crosses
the per-request handler stack.  Host apps here have the same shape — the
decision stream leaves the device and ``Replicable.execute`` runs
interpreted Python per request (``paxos/manager.py``), which caps e2e
throughput orders of magnitude below the raw kernel.

:class:`DeviceKV` moves the app itself into device arrays so the decision
stream NEVER leaves the device:

* app state — a direct-mapped KV cache per (replica, group):
  ``key[R, G, S]`` / ``val[R, G, S]`` int32 (0 = empty slot — key 0 is
  RESERVED as that sentinel, clients use keys >= 1; key k lives at
  slot ``k & (S-1)``, last-writer-wins on collision, deterministic on every
  replica by construction);
* request descriptors — clients register ``rid -> (op, key, val)`` in a
  hashed device table ``[T]`` (op PUT=1/GET=2/DEL=3); the tick's executed
  rids gather their descriptors and a vectorized apply updates the KV
  arrays for every group at once;
* misses (descriptor evicted/never uploaded) surface in a ``miss`` mask so
  the host can repair via its slow path — mirroring the dense design's
  general fast-path/slow-path split (SURVEY §7 hard part f).

``fused_step`` runs ``paxos_tick`` and the KV apply in ONE jitted program —
XLA fuses the gather/scatter chain with the tick's phase-4 extraction, so
"execute" costs one more fused elementwise pass over ``[R, W, G]``, not a
host round-trip per decision.
"""

from __future__ import annotations

import json
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.tick import TickInbox, paxos_tick_impl
from ..types import NO_REQUEST

OP_NONE = 0
OP_PUT = 1
OP_GET = 2
OP_DEL = 3

I32 = jnp.int32


class DeviceKVState(NamedTuple):
    """Dense app state + request-descriptor table (all device arrays)."""

    key: jnp.ndarray   # i32 [R, G, S]   stored key per slot (0 = empty)
    val: jnp.ndarray   # i32 [R, G, S]
    t_rid: jnp.ndarray  # i32 [T] descriptor table: registered rid (0 = none)
    t_op: jnp.ndarray   # i32 [T]
    t_key: jnp.ndarray  # i32 [T]
    t_val: jnp.ndarray  # i32 [T]

    @property
    def slots(self) -> int:
        return self.key.shape[2]

    @property
    def table(self) -> int:
        return self.t_rid.shape[0]


def init_kv(n_replicas: int, n_groups: int, slots: int = 16,
            table: int = 1 << 16) -> DeviceKVState:
    assert slots & (slots - 1) == 0 and table & (table - 1) == 0
    R, G = n_replicas, n_groups
    return DeviceKVState(
        key=jnp.zeros((R, G, slots), I32),
        val=jnp.zeros((R, G, slots), I32),
        t_rid=jnp.zeros((table,), I32),
        t_op=jnp.zeros((table,), I32),
        t_key=jnp.zeros((table,), I32),
        t_val=jnp.zeros((table,), I32),
    )


def _table_idx(rids, table: int, mix: bool):
    """Descriptor-table index for a batch of rids.

    ``mix=False`` (Mode A): plain low-bits mask — manager rids are one
    sequential stream, so any live window of <= table consecutive rids maps
    injectively (the eviction-safety invariant in paxos/manager.py).
    ``mix=True`` (Mode B): rids are origin-tagged ``(origin << 24) | seq``
    and every origin's seq streams advance together, so the plain mask
    would collide ALL origins at equal seqs; a multiplicative (Fibonacci)
    hash spreads them — a rare collision evicts a descriptor, which is a
    miss, which is the (correct) scalar fallback."""
    if not mix:
        return jnp.bitwise_and(rids, table - 1)
    h = (rids * jnp.int32(-1640531527)).astype(jnp.uint32)  # 0x9E3779B9
    return jnp.bitwise_and(h >> jnp.uint32(8), table - 1).astype(I32)


def register_requests(kv: DeviceKVState, rids, ops, keys, vals,
                      mix: bool = False) -> DeviceKVState:
    """Upload request descriptors (host batch -> one scatter).  Clients call
    this before proposing the rids; collisions evict (the evicted request
    will execute as a miss and fall back to the host slow path).

    rid 0 marks an EMPTY upload slot (fixed-size batches pad with zeros) —
    those scatter out of bounds and drop, instead of clobbering whatever
    live descriptor hashes to index 0 on every padded upload."""
    rids = jnp.asarray(rids, I32)
    idx = jnp.where(rids == 0, kv.table, _table_idx(rids, kv.table, mix))
    return kv._replace(
        t_rid=kv.t_rid.at[idx].set(rids, mode="drop"),
        t_op=kv.t_op.at[idx].set(jnp.asarray(ops, I32), mode="drop"),
        t_key=kv.t_key.at[idx].set(jnp.asarray(keys, I32), mode="drop"),
        t_val=kv.t_val.at[idx].set(jnp.asarray(vals, I32), mode="drop"),
    )


def kv_apply(kv: DeviceKVState, exec_req: jnp.ndarray,
             exec_count: jnp.ndarray,
             mix: bool = False) -> Tuple[DeviceKVState, jnp.ndarray,
                                         jnp.ndarray]:
    """Vectorized execution of one tick's decision stream.

    exec_req: i32 [R, W, G] executed rids in window order (0 = none);
    exec_count: i32 [R, G].
    Returns (kv', responses i32 [R, W, G] — PUT echoes the value, GET/DEL
    return the pre-op value (0 = absent) — and miss bool [R, W, G]).

    Window plane j executes slot base+j, so planes apply in order: a
    ``lax.scan`` over the W axis (W is small and static) threads the store
    through the planes — each step is fully vectorized over [R, G], and XLA
    unrolls/fuses the short scan into the surrounding program.  This is the
    TPU idiom for the reference's in-order ``execute`` loop
    (PaxosInstanceStateMachine.java:1755-1842) with read-your-writes inside
    one tick's batch.
    """
    from jax import lax

    R, W, G = exec_req.shape
    S = kv.slots
    ji = jnp.arange(W, dtype=I32)
    valid = (exec_req != NO_REQUEST) & (ji[None, :, None] < exec_count[:, None, :])

    tix = _table_idx(exec_req, kv.table, mix)  # [R, W, G]
    hit = valid & (kv.t_rid[tix] == exec_req)
    op = jnp.where(hit, kv.t_op[tix], OP_NONE)
    k = kv.t_key[tix]
    v = kv.t_val[tix]
    slot = jnp.bitwise_and(k, S - 1)  # [R, W, G]

    rr = jnp.arange(R, dtype=I32)[:, None]
    gg = jnp.arange(G, dtype=I32)[None, :]

    def plane(carry, xs):
        key_s, val_s = carry  # [R, G, S]
        op_j, k_j, v_j, slot_j = xs  # [R, G]
        cur_key = key_s[rr, gg, slot_j]
        cur_val = val_s[rr, gg, slot_j]
        present = cur_key == k_j
        resp = jnp.where(
            op_j == OP_PUT, v_j, jnp.where(present, cur_val, 0)
        )
        # DEL writes only when the key is actually resident: deleting an
        # absent key must not erase a colliding occupant (and must match
        # the scalar fallback's semantics exactly)
        wr = (op_j == OP_PUT) | ((op_j == OP_DEL) & present)
        wslot = jnp.where(wr, slot_j, S)  # S -> drop
        nk = jnp.where(op_j == OP_DEL, 0, k_j)
        nv = jnp.where(op_j == OP_DEL, 0, v_j)
        key_s = key_s.at[rr, gg, wslot].set(nk, mode="drop")
        val_s = val_s.at[rr, gg, wslot].set(nv, mode="drop")
        return (key_s, val_s), resp

    xs = (op.transpose(1, 0, 2), k.transpose(1, 0, 2),
          v.transpose(1, 0, 2), slot.transpose(1, 0, 2))
    (key_s, val_s), resps = lax.scan(plane, (kv.key, kv.val), xs)
    responses = jnp.where(hit, resps.transpose(1, 0, 2), 0)
    kv2 = kv._replace(key=key_s, val=val_s)
    miss = valid & ~hit
    return kv2, responses, miss


def fused_step(state, kv: DeviceKVState, inbox: TickInbox, own_row: int = -1,
               fast_elect: bool = False):
    """One consensus tick + device app execution in a single program."""
    new_state, out = paxos_tick_impl(state, inbox, own_row,
                                     fast_elect=fast_elect)
    kv2, responses, miss = kv_apply(kv, out.exec_req, out.exec_count)
    return new_state, kv2, out, responses, miss


fused_step_jit = jax.jit(fused_step, donate_argnums=(0, 1),
                         static_argnums=(3, 4))


def _fused_compact_impl(state, kv: DeviceKVState, inbox: TickInbox,
                        reg_rids, reg_ops, reg_keys, reg_vals,
                        own_row: int, exec_budget: int, lag_budget: int,
                        fast_elect: bool = False):
    """Descriptor upload + consensus tick + KV apply + outbox compaction in
    ONE device program: the deployment-path twin of :func:`fused_step`.

    The compacted buffer grows one extra array vs the consensus-only
    compaction: per-execution KV responses (e_resp), scattered with the
    same prefix-sum ranks, so entry replicas answer clients without any
    O(R*W*G) transfer.  reg_*: this tick's new request descriptors
    ([K] i32; rid 0 = empty slot — a fixed-size upload keeps the jit
    signature static).
    """
    from ..ops.tick import _compact_outbox_impl, paxos_tick_impl

    kv = register_requests(kv, reg_rids, reg_ops, reg_keys, reg_vals)
    new_state, out = paxos_tick_impl(state, inbox, own_row, exec_budget,
                                     fast_elect=fast_elect)
    kv2, responses, miss = kv_apply(kv, out.exec_req, out.exec_count)
    packed = _compact_outbox_impl(out, exec_budget, lag_budget)
    # responses ride a second scatter with the same ranks as the exec stream
    R, W, G = out.exec_req.shape
    ji = jnp.arange(W, dtype=I32)[None, :, None]
    mask = ji < out.exec_count[:, None, :]
    mf = mask.reshape(-1)
    mi = mf.astype(I32)
    rank = jnp.cumsum(mi) - mi
    idx = jnp.where(mf, rank, exec_budget)
    e_resp = jnp.zeros((exec_budget,), I32).at[idx].set(
        responses.reshape(-1), mode="drop"
    )
    e_miss = jnp.zeros((exec_budget,), I32).at[idx].set(
        miss.astype(I32).reshape(-1), mode="drop"
    )
    flat = jnp.concatenate([packed, e_resp, e_miss])
    # pack/unpack agreement enforced at trace time against the shared
    # layout descriptor (consumers slice via CompactLayout.kv_extras)
    from ..ops.tick import CompactLayout

    L = CompactLayout(R, G, exec_budget, lag_budget)
    assert flat.shape[0] == L.total_device, (flat.shape, L.total_device)
    assert packed.shape[0] == L.o_resp
    return new_state, kv2, flat


fused_compact = jax.jit(_fused_compact_impl, donate_argnums=(0, 1),
                        static_argnums=(7, 8, 9, 10))


#: descriptor wire format for device-app request payloads: op, key, value
DESC = "<iii"
DESC_LEN = 12


def pack_desc(op: int, key: int, val: int) -> bytes:
    import struct

    return struct.pack(DESC, op, key, val)


class DeviceKVApp:
    """Replicable face of the MANAGER-OWNED device KV state.

    One source of truth: ``owner.kv`` is the live DeviceKVState the fused
    tick evolves (``PaxosManager.kv`` in device-app mode); this wrapper
    gives the control plane (checkpoint transfer, epoch final state,
    recovery seeding) row-granular views of it.  The hot path never calls
    ``execute`` — decisions execute on-device inside ``fused_compact``;
    the scalar ``execute`` below applies one descriptor through the same
    semantics for the rare host fallbacks (control-plane proposes, WAL
    scalar replay).

    ``row_of(name)`` maps service names to group rows (wire it to the
    manager's RowAllocator).
    """

    def __init__(self, owner, replica: int, row_of=None):
        self.owner = owner  # any object with a mutable .kv attribute
        self.replica = replica
        self.row_of = row_of or (lambda name: None)

    def _lock(self):
        """Every access to owner.kv must exclude the fused tick: the tick
        DONATES the kv buffers, so a concurrent read races buffer deletion.
        The owner's lock is reentrant (tick-held paths still work)."""
        import contextlib

        lk = getattr(self.owner, "lock", None)
        return lk if lk is not None else contextlib.nullcontext()

    @property
    def kv(self) -> DeviceKVState:
        return self.owner.kv

    @kv.setter
    def kv(self, v: DeviceKVState) -> None:
        self.owner.kv = v

    def execute(self, name: str, request: bytes, request_id: int) -> bytes:
        """Scalar fallback: apply one 12-byte descriptor to this replica's
        row (same semantics as the vectorized kv_apply plane step)."""
        import struct

        row = self.row_of(name)
        if row is None or len(request) != DESC_LEN:
            return b""
        op, k, v = struct.unpack(DESC, request)
        with self._lock():
            kv = self.kv
            slot = k & (kv.slots - 1)
            cur_k = int(kv.key[self.replica, row, slot])
            cur_v = int(kv.val[self.replica, row, slot])
            present = cur_k == k
            if op == OP_PUT:
                self.kv = kv._replace(
                    key=kv.key.at[self.replica, row, slot].set(k),
                    val=kv.val.at[self.replica, row, slot].set(v),
                )
                resp = v
            elif op == OP_DEL:
                if present:
                    self.kv = kv._replace(
                        key=kv.key.at[self.replica, row, slot].set(0),
                        val=kv.val.at[self.replica, row, slot].set(0),
                    )
                resp = cur_v if present else 0
            else:  # GET / NONE
                resp = cur_v if present else 0
        return struct.pack("<i", resp)

    def checkpoint(self, name: str) -> bytes:
        row = self.row_of(name)
        if row is None:
            return b""
        with self._lock():
            keys = np.asarray(self.kv.key[self.replica, row])
            vals = np.asarray(self.kv.val[self.replica, row])
        live = keys != 0
        return json.dumps({
            "k": keys[live].tolist(), "v": vals[live].tolist(),
        }).encode()

    def restore(self, name: str, state: bytes) -> None:
        row = self.row_of(name)
        if row is None:
            return
        with self._lock():
            S = self.kv.slots
            keys = np.zeros(S, np.int32)
            vals = np.zeros(S, np.int32)
            if state:
                d = json.loads(state.decode())
                for k, v in zip(d["k"], d["v"]):
                    keys[k & (S - 1)] = k
                    vals[k & (S - 1)] = v
            self.kv = self.kv._replace(
                key=self.kv.key.at[self.replica, row].set(jnp.asarray(keys)),
                val=self.kv.val.at[self.replica, row].set(jnp.asarray(vals)),
            )

"""Host control-plane cost vs G under the device control-summary plane.

The PR-4 tentpole claim: per-tick HOST work for laggard repair, payload
sweep, and demand accounting is O(actual laggards), not O(G) — donor
selection, the sweep frontier, and the intake-demand fold all run inside
the tick program, and the host touches only the compact laggard columns, an
O(rows) frontier gather, and an O(1) demand handle.

This bench pins the laggard count (one dead replica, a fixed set of groups
pushed past the ring window) and scales G 64k -> 1M, timing the four host
entry points of the control plane per tick:

* ``_process_compact``   — compact-buffer bookkeeping (exec stream, laggard
  columns, due scheduling),
* ``_run_due_laggard_syncs`` — the repair path consuming device-selected
  donors,
* ``_sweep_outstanding``  — frontier-based payload sweep (forced every tick
  here; production paces it),
* ``PlacementCounters.adopt_device`` — the per-tick demand fold handle.

For contrast it also times the LEGACY O(G) host equivalents at each G: the
host-reduction sweep body (full [R, G] pulls), the host demand popcount
fold, and the per-laggard watermark scan the old donor selection used.

Honesty note: this runs on the CPU backend (the device tick itself is then
host work and O(G) — that column is reported but is NOT the claim; on TPU
it's the device's problem).  The claim under test is the host_new column
staying flat (<= 2x drift) across the G sweep.

Usage: python benchmarks/control_summary_bench.py
           [--groups 65536,262144,1048576] [--ticks 24]
           [--out benchmarks/results_control_summaries_pr4.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

R = 3
W = 8
N_GROUPS = 32    # named groups (fixed while G = max_groups scales)
N_LAGGARD = 8    # groups pushed past the window behind the dead replica
TRAFFIC = 4      # groups receiving steady measured-phase traffic


def build(G, wal_dir):
    from gigapaxos_tpu.config import GigapaxosTpuConfig
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.paxos.manager import PaxosManager
    from gigapaxos_tpu.wal.logger import PaxosLogger

    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = G
    cfg.paxos.window = W
    cfg.paxos.compact_outbox = True
    cfg.paxos.pipeline_ticks = True
    cfg.placement.enabled = True
    wal = PaxosLogger(wal_dir, native=False, sync_every_ticks=4)
    apps = [KVApp() for _ in range(R)]
    m = PaxosManager(cfg, R, apps, wal=wal)
    for g in range(N_GROUPS):
        m.create_paxos_instance(f"svc{g}", list(range(R)))
    return m, wal


def _wrap_timer(obj, name, acc, sync_args=False):
    orig = getattr(obj, name)

    def timed(*a, **k):
        if sync_args and a and a[0] is not None:
            # CPU-backend correction: the frontier device arrays may still
            # be computing (the "device" IS the host CPU here); block
            # OUTSIDE the timed region so the bucket measures the host
            # gather+apply work, not device compute that overlaps on TPU
            import jax

            jax.block_until_ready(a[0])
        t0 = time.perf_counter()
        r = orig(*a, **k)
        acc[name] = acc.get(name, 0.0) + (time.perf_counter() - t0)
        return r

    setattr(obj, name, timed)


def run_point(G, ticks):
    with tempfile.TemporaryDirectory() as td:
        m, wal = build(G, os.path.join(td, "wal"))
        try:
            t_setup = time.perf_counter()
            # warm traffic so every group decides something
            for t in range(4):
                for g in range(N_GROUPS):
                    m.propose(f"svc{g}", f"PUT w{t} x".encode(), None)
                m.tick()
            # fixed laggard population: kill one replica, push N_LAGGARD
            # groups past the ring window so they stay flagged (the dead
            # replica can't be repaired, so the flag — and the host's
            # per-tick O(laggards) handling of it — persists every tick)
            m.set_alive(R - 1, False)
            for t in range(W + 4):
                for g in range(N_LAGGARD):
                    m.propose(f"svc{g}", f"PUT lag{t} y".encode(), None)
                m.tick()
            m.drain_pipeline()
            t_setup = time.perf_counter() - t_setup

            m._sweep_every = 1  # force the sweep every tick (worst case)
            host = {}
            _wrap_timer(m, "_process_compact", host)
            _wrap_timer(m, "_run_due_laggard_syncs", host)
            _wrap_timer(m, "_sweep_outstanding", host, sync_args=True)
            if m._placement is not None:
                _wrap_timer(m._placement, "adopt_device", host)

            # measure with the one-tick pipeline drained and DISABLED: on
            # the CPU backend the in-flight next tick's O(G) device program
            # executes on the same cores the host buckets need (on TPU that
            # compute is on the accelerator), so overlapped measurement
            # times host-numpy-under-contention, scaling with G for reasons
            # that have nothing to do with host work.  Setup and warm-up
            # above/below still exercise the pipelined code paths.
            m.drain_pipeline()
            m.cfg.paxos.pipeline_ticks = False

            # steady-state warm-up: the first per-tick sweeps/folds compile
            # their jits (frontier gather bucket, demand fold) — one-time
            # costs that would otherwise inflate the smallest-G point's
            # per-tick average and read as inverse scaling
            for t in range(4):
                for g in range(N_LAGGARD, N_LAGGARD + TRAFFIC):
                    m.propose(f"svc{g}", f"PUT warm{t} z".encode(), None)
                m.tick()
            m.drain_pipeline()
            host.clear()

            t0 = time.perf_counter()
            for t in range(ticks):
                for g in range(N_LAGGARD, N_LAGGARD + TRAFFIC):
                    m.propose(f"svc{g}", f"PUT m{t} z".encode(), None)
                m.tick()
            m.drain_pipeline()
            wall = time.perf_counter() - t0

            host_ms = {k: round(1e3 * v / ticks, 4) for k, v in host.items()}
            host_total = round(sum(host_ms.values()), 4)

            # ---- legacy O(G) host equivalents, timed standalone ----
            reps = 3
            # legacy sweep: the pre-frontier host body (full [R, G] pulls);
            # type(m) bypasses the instance timer wrapper installed above
            t0 = time.perf_counter()
            for _ in range(reps):
                type(m)._sweep_outstanding(m)  # frontier=None -> host body
            legacy_sweep = 1e3 * (time.perf_counter() - t0) / reps
            # legacy demand fold: taken_bits popcount + host EWMA, O(G*P)
            tb = np.zeros((R, G), np.int32)
            t0 = time.perf_counter()
            for _ in range(reps):
                per_row = np.zeros(G, np.float32)
                for p in range(4):
                    per_row += ((tb >> p) & 1).sum(axis=0)
                m._placement.demand * 0.9  # the EWMA fold's mult
            legacy_demand = 1e3 * (time.perf_counter() - t0) / reps
            # legacy donor scan: per-laggard watermark pull + argmax (what
            # sync_laggard re-derived before the device summary), O(R)
            # device gathers per laggard — small per row, but every pull
            # syncs the dispatch queue
            t0 = time.perf_counter()
            for _ in range(reps):
                for g in range(N_LAGGARD):
                    wm = m.exec_watermarks(f"svc{g}")
                    int(np.argmax(wm))
            legacy_donor = 1e3 * (time.perf_counter() - t0) / reps

            lag_rows = len(m._lag_pending[0]) if m._lag_pending else 0
            return {
                "groups": G,
                "ticks": ticks,
                "laggard_rows_pending": int(lag_rows),
                "setup_s": round(t_setup, 2),
                "tick_wall_ms": round(1e3 * wall / ticks, 3),
                "host_new_ms_per_tick": host_ms,
                "host_new_total_ms_per_tick": host_total,
                "host_legacy_ms": {
                    "sweep_host_reductions": round(legacy_sweep, 3),
                    "demand_popcount_fold": round(legacy_demand, 3),
                    "donor_watermark_scan_8_laggards": round(
                        legacy_donor, 3),
                },
            }
        finally:
            wal.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--groups", default="65536,262144,1048576")
    ap.add_argument("--ticks", type=int, default=24)
    ap.add_argument("--out",
                    default=os.path.join(os.path.dirname(
                        os.path.abspath(__file__)),
                        "results_control_summaries_pr4.json"))
    args = ap.parse_args(argv)

    points = []
    for G in (int(g) for g in args.groups.split(",")):
        pt = run_point(G, args.ticks)
        print(json.dumps(pt))
        points.append(pt)

    totals = [p["host_new_total_ms_per_tick"] for p in points]
    drift = max(totals) / max(min(totals), 1e-9)
    result = {
        "bench": "control_summary_host_cost_vs_G",
        "backend": "cpu",
        "caveat": ("CPU backend: tick_wall_ms includes the device program "
                   "executing ON the host CPU and is expected to grow with "
                   "G; the claim under test is host_new_total_ms_per_tick "
                   "staying flat with a fixed laggard population.  The "
                   "measured window runs with the one-tick pipeline "
                   "disabled so the next tick's device program does not "
                   "steal the host buckets' cores (a CPU-only artifact; "
                   "setup and warm-up run pipelined)"),
        "replicas": R,
        "window": W,
        "laggard_groups": N_LAGGARD,
        "points": points,
        "host_new_drift_max_over_min": round(drift, 3),
        "flat_within_2x": drift <= 2.0,
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}  drift={drift:.2f}x  flat={drift <= 2.0}")
    return 0 if drift <= 2.0 else 1


if __name__ == "__main__":
    sys.exit(main())

"""Write-ahead logging and recovery (host-side persistence layer)."""

from .chain_logger import ChainLogger, recover_chain
from .logger import PaxosLogger, recover

__all__ = ["PaxosLogger", "recover", "ChainLogger", "recover_chain"]

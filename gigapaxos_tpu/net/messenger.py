"""Typed messenger over the framed transport.

``JSONMessenger`` analog (``nio/JSONMessenger.java:44-52``): multicast of a
packet to a node set (``GenericMessagingTask`` sends), sender stamping, and
the glue that lets a ``ProtocolExecutor`` emit ``(dest, packet)`` messages
directly.  The reference's exponential-backoff retransmission
(``JSONMessenger.java:323-348``) lives in two places here: the transport
retries frames across reconnects, and workflow liveness comes from
protocol-task restarts — so the messenger itself stays stateless.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, Optional, Tuple

from ..config import NodeConfig
from ..overload import CLS_CONTROL
from .transport import KIND_JSON, JsonDemux, Transport


class NodeMap:
    """node id -> (host, port) resolver over NodeConfig, mutable at runtime
    (elastic node add/remove, Reconfigurator.handleReconfigureRCNodeConfig)."""

    def __init__(self, nodes: Optional[NodeConfig] = None):
        self._addr: Dict[str, Tuple[str, int]] = {}
        if nodes is not None:
            self._addr.update(nodes.actives)
            self._addr.update(nodes.reconfigurators)

    def add(self, node_id: str, host: str, port: int) -> None:
        self._addr[node_id] = (host, port)

    def remove(self, node_id: str) -> None:
        self._addr.pop(node_id, None)

    def __call__(self, node_id: str) -> Optional[Tuple[str, int]]:
        return self._addr.get(node_id)

    def ids(self):
        return sorted(self._addr)


class Messenger:
    """One node's typed messaging endpoint.

    Construction binds the server socket; register handlers on ``demux``
    before traffic arrives.  ``send``/``multicast`` stamp the packet with
    ``sender`` so handlers can reply without trusting the TCP hello alone.
    """

    def __init__(
        self,
        node_id: str,
        bind: Tuple[str, int],
        nodemap: NodeMap,
        **transport_kw,
    ):
        self.node_id = node_id
        self.nodemap = nodemap
        self.demux = JsonDemux()
        self.transport = Transport(
            node_id, bind, self.demux, nodemap, **transport_kw
        )
        self.port = self.transport.port

    def register(self, ptype, handler) -> None:
        self.demux.register(ptype, handler)

    def send(self, dest: str, packet: dict, cls: int = CLS_CONTROL) -> None:
        packet.setdefault("sender", self.node_id)
        self.transport.send(dest, packet, cls=cls)

    def multicast(self, dests: Iterable[str], packet: dict,
                  cls: int = CLS_CONTROL) -> None:
        # serialize ONCE and fan the same byte buffer to every destination
        # (GenericMessagingTask sends one marshalled packet to a node set)
        packet.setdefault("sender", self.node_id)
        data = json.dumps(packet).encode()
        for d in dests:
            if d is not None:
                self.transport.send_raw(d, KIND_JSON, data, cls=cls)

    def send_bytes(self, dest: str, payload: bytes,
                   cls: int = CLS_CONTROL) -> None:
        self.transport.send_bytes(dest, payload, cls=cls)

    def send_bytes_many(self, dest: str, payloads,
                        cls: int = CLS_CONTROL) -> None:
        """A tick's frame list for one peer: stamped under one transport
        generation so the writer can drain them in a single writev."""
        self.transport.send_bytes_many(dest, payloads, cls=cls)

    def close(self) -> None:
        self.transport.close()

"""Batched client edge: APP_REQUEST_BATCH/APP_RESPONSE_BATCH end-to-end.

The reference coalesces client requests into batched RequestPackets
(``paxospackets/RequestPacket.java:189-233`` ``batched[]``,
``RequestBatcher.java:25-60``); these tests drive the analog over real
loopback sockets: one frame in, one frame out, batch-granular
retransmission dedup, per-request error isolation.
"""

import threading
import time

from gigapaxos_tpu.reconfiguration import packets as pkt
from gigapaxos_tpu.testing.capacity import make_loopback_cluster


def _collect(n):
    got, lock, ev = [], threading.Lock(), threading.Event()

    def cb(p):
        with lock:
            got.append(p)
            if len(got) >= n:
                ev.set()

    return got, cb, ev


def test_batch_roundtrip():
    cluster, client = make_loopback_cluster(n_groups=4)
    try:
        items = [(f"g{i % 4}", f"req{i}".encode()) for i in range(32)]
        got, cb, ev = _collect(32)
        rids = client.send_request_batch(items, cb)
        assert len(set(rids)) == 32
        assert ev.wait(20), f"only {len(got)} responses"
        assert all(p.get("ok") for p in got)
        bodies = {pkt.b64d(p["response"]) for p in got}
        assert bodies == {b"ok:" + f"req{i}".encode() for i in range(32)}
    finally:
        client.close()
        cluster.close()


def test_batch_error_isolation():
    """Unknown names inside a batch fail individually; the rest commit."""
    cluster, client = make_loopback_cluster(n_groups=2)
    try:
        # target a specific active so the unknown name can't raise at
        # resolve time on the client
        actives = client.request_actives("g0")
        items = [("g0", b"a"), ("nope", b"b"), ("g1", b"c")]
        got, cb, ev = _collect(3)
        client.send_request_batch(items, cb, active=actives[0])
        assert ev.wait(20)
        by_ok = sorted(p.get("ok", False) for p in got)
        assert by_ok == [False, True, True]
        bad = [p for p in got if not p.get("ok")][0]
        assert bad["error"] == "not_active"
    finally:
        client.close()
        cluster.close()


def test_batching_sender_coalesces():
    cluster, client = make_loopback_cluster(n_groups=4)
    try:
        sender = client.batching(max_batch=16, flush_interval_s=0.01)
        got, cb, ev = _collect(64)
        for i in range(64):
            sender.submit(f"g{i % 4}", f"p{i}".encode(), cb)
        assert ev.wait(20), f"only {len(got)} responses"
        assert all(p.get("ok") for p in got)
        sender.close()
    finally:
        client.close()
        cluster.close()


def test_batch_retransmission_dedup():
    """Retransmitting the same batch frame must not re-commit: the server
    replays the cached batch response."""
    cluster, client = make_loopback_cluster(n_groups=1)
    try:
        items = [("g0", b"x1"), ("g0", b"x2")]
        got, cb, ev = _collect(2)
        client.send_request_batch(items, cb)
        assert ev.wait(20)
        # reach into the wire: resend an identical hand-built frame
        execs_before = cluster.manager.stats["executions"]
        reqs = [["g0", 999001, pkt.b64e(b"x1")], ["g0", 999002, pkt.b64e(b"x2")]]
        p = {"type": pkt.APP_REQUEST_BATCH, "bid": 424242, "reqs": reqs,
             "client_addr": [client.addr[0], client.addr[1]]}
        got2, cb2, ev2 = _collect(2)
        with client._lock:
            for r in [999001, 999002]:
                client._callbacks[r] = cb2
                client._cb_deadline[r] = time.monotonic() + 30
        target = client.request_actives("g0")[0]
        client.m.send(target, dict(p))
        assert ev2.wait(20)
        execs_mid = cluster.manager.stats["executions"]
        assert execs_mid > execs_before
        # duplicate: same bid — server must answer from cache, no new commits
        got3, cb3, ev3 = _collect(2)
        with client._lock:
            for r in [999001, 999002]:
                client._callbacks[r] = cb3
                client._cb_deadline[r] = time.monotonic() + 30
        client.m.send(target, dict(p))
        assert ev3.wait(20)
        time.sleep(1.0)
        assert cluster.manager.stats["executions"] == execs_mid
    finally:
        client.close()
        cluster.close()

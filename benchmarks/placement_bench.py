"""Placement-plane benchmark: Zipf-skewed load on an 8-shard virtual mesh,
rebalancer ON vs OFF.

What it models
--------------
Names are created in popularity order, so the row allocator packs the hot
names into the first shards' row ranges — the pathological-but-natural
placement the demand-driven rebalancer exists to fix.  Offered load is
Zipf-distributed over the names; each mesh shard models one machine of the
deployment with a bounded per-tick intake frame (``--edge-budget``, the
analog of a node's transport frame/NIC): requests for a name are admitted
through the frame of the shard the name currently lives in, and queue when
that frame is full.  Under skew the hot shard's frame saturates while the
cold shards' frames idle; after migration the same offered load spreads
over more frames and aggregate admitted (= decided) throughput rises.

That per-shard edge budget is a DRIVER-SIDE model: the single-process
virtual mesh has no real per-node NIC, so without it shard imbalance is
invisible to throughput (the dense device tick processes all rows every
tick regardless).  The shard-load ratio, by contrast, is measured from the
real placement counters (EWMA demand folded on device through the compact
dispatch).

Usage: python benchmarks/placement_bench.py [--rebalance] [--ticks N] ...
Prints one JSON line; commit into benchmarks/results_placement_pr2.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=256)
    ap.add_argument("--names", type=int, default=96)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--ticks", type=int, default=160)
    ap.add_argument("--warmup", type=int, default=8)
    ap.add_argument("--window", type=int, default=8)
    ap.add_argument("--edge-budget", type=int, default=48,
                    help="per-shard per-tick admission frame (see docstring)")
    ap.add_argument("--offered", type=int, default=300,
                    help="offered requests per tick across all names")
    ap.add_argument("--zipf", type=float, default=1.05)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--rebalance", action="store_true")
    ap.add_argument("--rebalance-every", type=int, default=8)
    args = ap.parse_args()

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from gigapaxos_tpu.config import GigapaxosTpuConfig
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.paxos.manager import PaxosManager
    from gigapaxos_tpu.placement import GroupMigrator, ShardRebalancer
    from gigapaxos_tpu.reconfiguration.coordinator import (
        PaxosReplicaCoordinator,
    )

    R = args.replicas
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = args.groups
    cfg.paxos.window = args.window
    cfg.paxos.compact_outbox = True
    cfg.paxos.pipeline_ticks = True
    cfg.paxos.deactivation_ticks = 0
    cfg.paxos.mesh_devices = 8
    cfg.paxos.mesh_replica_shards = 1
    cfg.placement.enabled = True
    cfg.placement.sample_every_ticks = args.rebalance_every
    cfg.placement.min_interval_ticks = 2 * args.rebalance_every

    m = PaxosManager(cfg, R, [KVApp() for _ in range(R)])
    nodes = [f"AR{i}" for i in range(R)]
    coord = PaxosReplicaCoordinator(m, nodes)
    names = [f"svc{i:03d}" for i in range(args.names)]
    for n in names:  # popularity order -> hot names pack the first shards
        assert coord.create_replica_group(n, 0, b"", nodes)

    gs, per = m.shard_geometry()
    mig = GroupMigrator(coord, counters=m._placement)
    reb = ShardRebalancer(
        m.G, gs, skew_threshold=1.5, hysteresis=1.1,
        min_interval_ticks=cfg.placement.min_interval_ticks,
        max_moves_per_plan=4,
    )

    rng = np.random.default_rng(args.seed)
    w = 1.0 / np.arange(1, args.names + 1) ** args.zipf
    probs = w / w.sum()

    queues = [0] * args.names  # pending offered requests per name

    def shard_of(i):
        n = names[i]
        return m.rows.row(f"{n}#{coord.current_epoch(n)}") // per

    def admit_tick():
        """Offered load arrives; each shard's frame admits up to budget."""
        for i, k in enumerate(rng.multinomial(args.offered, probs)):
            queues[i] += int(k)
        frame = [args.edge_budget] * gs
        admitted = 0
        # round-robin over names within each shard's frame
        by_shard = [[] for _ in range(gs)]
        for i in range(args.names):
            if queues[i]:
                by_shard[shard_of(i)].append(i)
        for k in range(gs):
            idx = by_shard[k]
            while frame[k] > 0 and idx:
                nxt = []
                for i in idx:
                    if frame[k] == 0:
                        break
                    take = min(queues[i], max(frame[k] // len(idx), 1),
                               frame[k])
                    for _ in range(take):
                        coord.coordinate_request(
                            names[i], coord.current_epoch(names[i]),
                            b"PUT x 1")
                    queues[i] -= take
                    frame[k] -= take
                    admitted += take
                    if queues[i]:
                        nxt.append(i)
                idx = nxt
        return admitted

    for _ in range(args.warmup):
        admit_tick()
        m.tick()
    m.drain_pipeline()
    base_decided = int(m.stats["decisions"])
    base_ticks = m.tick_num

    t0 = time.perf_counter()
    moved_total, plans = 0, 0
    for t in range(args.ticks):
        admit_tick()
        m.tick()
        if args.rebalance and t % args.rebalance_every == 0:
            demand = m.demand_snapshot()
            plan = reb.propose(m.tick_num, demand,
                               free_rows_in_shard=m.free_rows_in_shard)
            if plan:
                plans += 1
                n = mig.execute_plan(plan, pump=m.tick)
                reb.record_executed(n)
                moved_total += n
    m.drain_pipeline()
    dt = time.perf_counter() - t0

    decided = int(m.stats["decisions"]) - base_decided
    ticks_run = m.tick_num - base_ticks
    # measured from the real device-folded EWMA counters
    m.demand_snapshot()
    loads = m._placement.shard_loads()
    ratio = float(loads.max()) / max(float(loads.min()), 1.0)
    out = {
        "metric": (
            f"placement_stack_{args.groups}_groups_{args.names}_names_"
            f"mesh8x1r_zipf{args.zipf}_cpu"
        ),
        "rebalance": bool(args.rebalance),
        "groups": args.groups, "names": args.names, "replicas": R,
        "ticks": ticks_run, "edge_budget": args.edge_budget,
        "offered_per_tick": args.offered,
        "decisions": decided,
        "decisions_per_s": round(decided / dt, 1),
        "decisions_per_tick": round(decided / max(ticks_run, 1), 2),
        "ms_per_tick": round(1e3 * dt / max(ticks_run, 1), 3),
        "backlog_end": int(sum(queues)),
        "shard_loads_ewma": [round(float(x), 1) for x in loads],
        "shard_load_max_min_ratio": round(ratio, 2),
        "groups_moved": moved_total, "plans": plans,
        "migration_stats": mig.stats.snapshot(),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""The fused consensus tick: one jitted step of multi-decree Paxos over every
group at once.

This replaces the entire per-packet dispatch pyramid of the reference
(``PaxosInstanceStateMachine.handlePaxosMessage``,
PaxosInstanceStateMachine.java:423-583, and the handlers it fans out to) with
a single branch-free dataflow over dense arrays:

  phase 0  coordinator-candidacy check   (checkRunForCoordinator, :2070-2130)
  phase 1  prepare/promise + carryover   (handlePrepare, PaxosAcceptor.java:239-273;
                                          combinePValuesOntoProposals,
                                          PaxosCoordinatorState.java:393)
  phase 2  intake + slot assignment      (RequestBatcher + PaxosCoordinatorState.propose :233)
           accept                         (acceptAndUpdateBallot, PaxosAcceptor.java:302-322)
           vote tally + quorum            (handleAcceptReplyMyBallot,
                                          PaxosCoordinatorState.java:597-640;
                                          WaitforUtility majority -> popcount over replica axis)
  phase 3  decision sync                  (syncLongDecisionGaps analog, :1550)
  phase 4  in-order execution extraction  (putAndRemoveNextExecutable,
                                          PaxosAcceptor.java:325-366)

Message passing is implicit: every cross-replica read is a reduction or
broadcast over the leading replica axis.  Run single-device, that axis is a
plain array dimension; sharded over a mesh axis ``replica``, XLA turns the
same reductions into ICI collectives (psum/all-gather) — the TPU-native
equivalent of the reference's NIO ACCEPT fan-out / ACCEPT_REPLY fan-in
(``nio/NIOTransport.java:65-114``).

Layout: all ring windows are ``[R, W, G]`` (G = lane axis; see state.py), and
ring gathers are one-hot selects over the W planes (``window.gather_planes``)
so the lane axis never participates in a hardware gather.

Failure model: ``inbox.alive`` is the host failure detector's liveness view
(``FailureDetection.isNodeUp``, FailureDetection.java:252-258).  A dead
replica contributes nothing and its state freezes; flipping it back alive
models crash-recovery with intact local state.  The tick is deterministic
given (state, inbox), which is what makes the WAL an inbox command log with
replay recovery (see ``wal/logger.py``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..types import GroupStatus, NO_REQUEST
from .ballot import bal_ge, bal_gt
from .window import gather_planes, match_planes

I32 = jnp.int32
# numpy scalar, NOT jnp: a module-level jnp value would initialize the
# default backend at import time (and hang the importer for the whole
# backend-init timeout when the TPU tunnel is down)
NEG_INF = np.int32(-(2**31))


class TickInbox(NamedTuple):
    """Per-tick inputs assembled by the host batcher.

    req:   int32 [R, P, G] — new client request ids that arrived at entry
           replica r for group g this tick (0 = empty slot).
    stop:  bool  [R, P, G] — request is a paxos stop (end-of-epoch).
    alive: bool  [R]       — failure-detector liveness per replica slot.
    """

    req: jnp.ndarray
    stop: jnp.ndarray
    alive: jnp.ndarray


class TickOutbox(NamedTuple):
    """Per-tick outputs consumed by the host (app execution, callbacks, WAL).

    exec_req:   int32 [R, W, G] — request ids executed this tick, plane j
                holds slot exec_base+j (0 = noop/empty).
    exec_stop:  bool  [R, W, G]
    exec_base:  int32 [R, G]    — first slot executed this tick.
    exec_count: int32 [R, G]    — number of slots executed this tick.
    intake_taken: bool [R, P, G] — which inbox requests got slots (host
                re-enqueues the rest, mirroring RequestBatcher backpressure).
    coord_id:   int32 [G]       — current effective coordinator (-1 if none).
    decided_now: int32 [G]      — decisions reaching quorum this tick (metric).
    lag:        int32 [R, G]    — how many slots this replica trails the live
                maximum.  lag >= W means ring sync cannot catch it up and the
                host must do a checkpoint transfer (StatePacket analog,
                PaxosInstanceStateMachine.handleCheckpoint :1852).
    donor:      int32 [R, G]    — control summary for that transfer: the best
                live member to copy from (argmax post-tick exec watermark over
                live members other than r, ties to the lowest replica id — the
                same choice manager.sync_laggard's host scan makes), or -1
                when no live member is strictly ahead.  Emitted for every
                (r, g) but only meaningful where lag >= W.
    donor_exec: int32 [R, G]    — the donor's post-tick exec watermark (the
                value a checkpoint transfer adopts; 0 where donor == -1).
    donor_status: int32 [R, G]  — the donor's post-tick group status.
    """

    exec_req: jnp.ndarray
    exec_stop: jnp.ndarray
    exec_base: jnp.ndarray
    exec_count: jnp.ndarray
    intake_taken: jnp.ndarray
    coord_id: jnp.ndarray
    decided_now: jnp.ndarray
    lag: jnp.ndarray
    donor: jnp.ndarray
    donor_exec: jnp.ndarray
    donor_status: jnp.ndarray


def _lexmax(n, c, axis):
    """Lexicographic (n, c) max along `axis` -> (n*, c*), masked entries must
    already be NEG_INF in `n`."""
    nmax = jnp.max(n, axis=axis, keepdims=True)
    cmax = jnp.max(jnp.where(n == nmax, c, NEG_INF), axis=axis)
    return jnp.squeeze(nmax, axis=axis), cmax


class LeaseState(NamedTuple):
    """Leader-lease columns (ISSUE 17): dense ``[G]`` lease state folded
    inside the fused tick, so grant/renew/expiry piggyback on the
    accept/heartbeat traffic the tick already emits — no per-group host
    work, vmapped across every group like everything else.

    Time is the lease clock itself: one tick = one unit, advanced inside
    the fold, so lease decisions are a pure function of (state, inbox)
    and WAL replay reproduces them bit for bit.

    clock:  int32 []   — lease clock; +1 per tick.
    holder: int32 [G]  — replica id holding the read lease (-1 = none).
    epoch:  int32 [G]  — grant counter; bumps whenever the holder changes.
    until:  int32 [G]  — expiry tick; reads are valid while clock < until.
    margin: int32 [G]  — skew allowance: a DIFFERENT coordinator may not
            admit new writes until ``clock >= until + margin``, so a
            holder whose clock runs up to ``margin`` ticks slow still
            stops serving reads before any conflicting write can be
            acked (the write-side fence of the classic lease argument).
    """

    clock: jnp.ndarray
    holder: jnp.ndarray
    epoch: jnp.ndarray
    until: jnp.ndarray
    margin: jnp.ndarray


#: lease_pack row indices (the [5, G] per-plane host summary emitted by the
#: lease tick variants — ONE device->host pull per plane per tick)
LP_HOLDER, LP_EPOCH, LP_UNTIL, LP_ASN, LP_WAIT = range(5)
LP_ROWS = 5


def init_lease(n_groups: int, margin_ticks: int = 0) -> LeaseState:
    return LeaseState(
        clock=jnp.zeros((), I32),
        holder=jnp.full((n_groups,), -1, I32),
        epoch=jnp.zeros((n_groups,), I32),
        until=jnp.zeros((n_groups,), I32),
        margin=jnp.full((n_groups,), margin_ticks, I32),
    )


def _lease_clear_rows_impl(lease: LeaseState, rows):
    """Drop leases on the given rows (row lifecycle: create/remove/pause,
    placement migration).  Out-of-range rows (padding) are dropped."""
    return lease._replace(
        holder=lease.holder.at[rows].set(-1, mode="drop"),
        epoch=lease.epoch.at[rows].set(0, mode="drop"),
        until=lease.until.at[rows].set(0, mode="drop"),
    )


#: O(rows) scatter; the manager pads rows to power-of-two buckets so row
#: lifecycle events reuse a handful of compiles.
lease_clear_rows = jax.jit(_lease_clear_rows_impl, donate_argnums=(0,))


class HealthState(NamedTuple):
    """Group-health columns (ISSUE 18): dense ``[G]`` per-group health
    facts folded inside the fused tick, so "which of a million groups is
    sick" is answered by an on-device reduction instead of an O(G) host
    pull.  Observation-only: nothing here ever feeds back into the
    consensus dataflow, so the journal bytes of a health-on run are
    identical to a health-off run.

    Time is the tick clock (one tick = one unit, the LeaseState
    convention), so every column is a pure function of (state, inbox)
    and WAL replay reproduces it bit for bit.

    clock:       int32 []  — health clock; +1 per tick.
    last_active: int32 [G] — last tick the group made commit/exec progress
                 OR had no device-visible backlog (an idle group is
                 healthy); ``clock - last_active`` is the stall age.
    last_coord:  int32 [G] — last effective coordinator observed (-1 until
                 a first election); the churn detector's memory.
    churn:       int32 [G] — decaying coordinator-handoff score, Q4 fixed
                 point (one handoff adds 16; each tick decays by
                 ``1/2**decay_shift`` of the current value).
    heat:        int32 [G] — decaying offered-intake EWMA, Q4 fixed point
                 (the "hottest rows" ranking key).
    """

    clock: jnp.ndarray
    last_active: jnp.ndarray
    last_coord: jnp.ndarray
    churn: jnp.ndarray
    heat: jnp.ndarray


def init_health(n_groups: int) -> HealthState:
    return HealthState(
        clock=jnp.zeros((), I32),
        last_active=jnp.zeros((n_groups,), I32),
        last_coord=jnp.full((n_groups,), -1, I32),
        churn=jnp.zeros((n_groups,), I32),
        heat=jnp.zeros((n_groups,), I32),
    )


def _health_clear_rows_impl(health: HealthState, rows):
    """Reset health columns for freed/migrated rows: a recycled row must
    not inherit the previous occupant's stall age or churn score.
    Out-of-range rows (padding) are dropped."""
    return health._replace(
        last_active=health.last_active.at[rows].set(health.clock,
                                                    mode="drop"),
        last_coord=health.last_coord.at[rows].set(-1, mode="drop"),
        churn=health.churn.at[rows].set(0, mode="drop"),
        heat=health.heat.at[rows].set(0, mode="drop"),
    )


health_clear_rows = jax.jit(_health_clear_rows_impl, donate_argnums=(0,))


#: health_pack gauge indices (see :class:`HealthLayout`)
(HG_ALLOC, HG_BACKLOG, HG_WEDGED, HG_MAX_STALL, HG_MAX_CHURN,
 HG_LEASE_WAIT) = range(6)
HG_N = 6
#: log2 histogram buckets in the health pack — bucket i holds values with
#: ``int(v).bit_length() == i`` (the obs/metrics.py convention), bucket 31
#: is the overflow tail
HB = 32


def _log2_hist(v, mask):
    """[G] int32 values -> [HB] bucket counts over ``mask`` rows, bucketed
    by bit_length (matches obs/metrics.py Histogram).

    Computed as 31 vectorized ``>= 2^i`` count-sums and an adjacent diff
    rather than a scatter-add: bucket ``i+1`` (values in ``[2^i, 2^(i+1))``)
    is ``ge[i] - ge[i+1]`` and bucket 0 is the masked zero count.  Exact
    same counts, ~3x cheaper on CPU where 1-element scatter-adds over a
    million rows serialize."""
    vv = jnp.where(mask, jnp.maximum(v, 0), -1)  # masked negatives: bucket 0
    ge = jnp.stack([jnp.sum(vv >= (1 << i), dtype=I32)
                    for i in range(HB - 1)])
    n0 = jnp.sum(vv == 0, dtype=I32)
    counts = ge - jnp.concatenate([ge[1:], jnp.zeros(1, I32)])
    return jnp.concatenate([n0[None], counts])


def _health_pack_impl(stall, churn, heat, backlog, allocated, wait_n,
                      wedge_ticks: int, topk: int):
    """Reduce the [G] health columns into the flat host summary described
    by :class:`HealthLayout`: scalar gauges, two log2 histograms, and the
    top-K (value, row) columns per anomaly criterion."""
    wedged = allocated & (stall >= wedge_ticks)
    gauges = jnp.stack([
        jnp.sum(allocated.astype(I32)),
        jnp.sum(backlog.astype(I32)),
        jnp.sum(wedged.astype(I32)),
        jnp.max(jnp.where(allocated, stall, 0)),
        jnp.max(jnp.where(allocated, churn, 0)),
        wait_n,
    ]).astype(I32)
    parts = [gauges, _log2_hist(stall, allocated),
             _log2_hist(churn >> 4, allocated)]
    for v in (stall, churn, heat):
        # rank in f32: XLA CPU's TopK has a vectorized f32 path but falls
        # back to a ~100x slower generic sort for int32.  Values clamp at
        # 2^24 (exact in f32) — ranking saturates there, far beyond any
        # plausible stall age or Q4 churn/heat score
        vf = jnp.where(allocated, jnp.minimum(v, 1 << 24), -1).astype(
            jnp.float32)
        tv, ti = jax.lax.top_k(vf, topk)
        parts += [tv.astype(I32), ti.astype(I32)]
    return jnp.concatenate(parts)


def paxos_tick_impl(state, inbox: TickInbox, own_row: int = -1,
                    exec_budget: int = 0, group_axis: str | None = None,
                    fast_elect: bool = False, lease: LeaseState | None = None,
                    lease_horizon: int = 0,
                    health: HealthState | None = None,
                    wedge_ticks: int = 32, health_decay_shift: int = 6,
                    health_topk: int = 8):
    """Un-jitted tick body (jit/shard it yourself; `paxos_tick` below is the
    ready-made single-program jit with state donation).

    fast_elect: static flag enabling consecutive-ballot fast re-election
    (arxiv 2006.01885).  When False (default) the compiled graph is the
    legacy election path, bit for bit.  When True, three coupled rules
    activate:

    * **fast takeover** (phase 0): the candidate skips the prepare round
      and goes straight to ``coord_active`` when its own promised ballot
      already equals the group max over member rows — the new ballot is
      then the predecessor's immediate successor, so every accept the
      predecessor could have pushed is visible in the candidate's mirrors
      and the prepare snapshot would be redundant.  Such a reign is marked
      ``coord_fast`` (the bit rides the frame flags word).
    * **conflict refusal** (phase 2b): because a fast ballot never
      collected promises, an acceptor refuses a fast push that would
      overwrite a *different* accepted value (same value / empty slot
      accepts normally, and the refusal still raises the promise).  Any
      chosen value therefore stays held by a blocking set — a conflicting
      fast value can never reach a majority (quorum intersection), which
      is the safety argument for skipping prepare.
    * **adoption + consecutive bump** (between intake and 2b): a fast
      coordinator that can see a higher-ballot accepted value differing
      from its own proposal adopts that value and bumps its ballot by one
      (proposals carry no per-slot ballot, so re-pushing a different value
      under the SAME ballot would corrupt the per-ballot vote tally).  The
      bump keeps the ballot consecutive, so the reign stays fast.

    Liveness escape: a refused fast push can stall behind a refuser plus a
    dead node (the classical path would overwrite after fresh promises).
    When the coordinator can *prove* a refusal from its mirrors — a member
    promised at/above the pushed ballot while a conflicting lower-ballot
    value stays accepted — it demotes itself to an ordinary full prepare
    at the next ballot, which is always safe.

    Known residual window (why the flag defaults to False): with majority
    quorums, a recovery prepare cannot always distinguish "old prepared
    value chosen, fast value partially accepted" from the mirror-image
    world — the promise sets can be identical (the Fast Paxos quorum
    lower bound: safe uncoordinated rounds need ~3n/4 quorums or a
    Raft-style up-to-dateness vote).  Concretely, a value the dead
    coordinator pushed in its final frame RTT can be invisible to the
    taker's mirrors, and if that value was chosen AND its decision also
    never surfaced, a later classical recovery ranks the fast pvalue
    above it by ballot.  Exploiting the window needs a chosen-but-
    unlearned value younger than one frame RTT at takeover plus a second
    coordinator death before the demote resolves; the chaos soaks assert
    the per-slot ledger across every scheduled run, but the flag stays
    opt-in until the fast-quorum variant closes the window.

    group_axis: name of a mesh axis the group dimension G is sharded over
    when this body is traced inside a shard_map (``parallel/shard_tick``).
    Every per-group computation is oblivious to it; only the exec_budget
    ranking below crosses groups, and with ``group_axis`` set it exchanges
    per-(j, r) block counts over that axis so the global rank — and hence
    the kept execution set — is bit-identical to the unsharded program.

    exec_budget: 0 = unlimited.  > 0 caps the TOTAL executions extracted
    this tick across all (replica, group) pairs, cutting each group's
    in-order run at a prefix (flat enumeration order is (r, j, g), so the
    per-group prefix property is preserved).  Decisions beyond the budget
    stay in the decision ring — ``exec_slot`` does not advance past them,
    the window-arithmetic dwrite guard keeps them from being overwritten,
    and a full window throttles intake — so the cap is lossless
    backpressure, not drop.  This is what makes a *bounded* compacted
    outbox transfer safe (see :func:`paxos_tick_compact_impl`): the host
    never needs more than ``exec_budget`` execution records per tick.

    own_row: -1 for Mode A (all rows authoritative: the whole replica set is
    one device program, so same-tick cross-row writes ARE the messages).
    In Mode B (independent per-process nodes, ``modeb/``) peer rows are
    frame-derived mirrors, and every state *transition* must be confined to
    ``own_row``: a same-tick simulated peer promise/accept/candidacy/win is
    not a fact — counting it toward an election or quorum lets an isolated
    minority fabricate majorities (split brain), and a locally-"won" peer
    candidacy would push that peer's stale mirror proposals under a fresh
    ballot (conflicting values under one ballot).  With ``own_row >= 0`` the
    masks below restrict start_prep / promise-upgrade / prepare-win / intake
    / accept to the own row, so winning a prepare or deciding a slot
    requires real promises/votes carried by received frames — mirroring the
    reference, where a minority partition can never decide
    (PaxosCoordinatorState majority tally, WaitforUtility)."""
    R, G = state.exec_slot.shape
    W = state.acc_req.shape[1]
    P = inbox.req.shape[1]
    RP = R * P
    Wm = jnp.int32(W - 1)

    alive = inbox.alive
    r_idx = jnp.arange(R, dtype=I32)[:, None]  # [R, 1] broadcasts over G
    # Mode-B authority mask: transitions allowed only on the own row.
    own2 = (r_idx == own_row) if own_row >= 0 else jnp.ones((R, 1), jnp.bool_)
    member = state.member  # [R, G] bool
    is_active = state.status == int(GroupStatus.ACTIVE)  # [R, G]
    acc_ok = member & alive[:, None] & is_active  # live active member [R, G]
    # serve_ok: may serve decisions from its ring even after STOPPED, so a
    # laggard that missed the stop decision can still learn it (otherwise the
    # group wedges with one eternally-ACTIVE stuck replica).
    serve_ok = member & alive[:, None] & (state.status != int(GroupStatus.FREE))
    maj = state.n_members // 2 + 1  # [G]

    def alive_at(ids):
        """Liveness lookup by global node id ([..] int32; -1 -> False)."""
        out = jnp.zeros(ids.shape, jnp.bool_)
        for r in range(R):
            out = jnp.where(ids == r, alive[r], out)
        return out

    # Common window base: max exec slot among live members (all caught-up live
    # replicas share it; laggards resync in phase 3).
    exec_rel = jnp.where(acc_ok, state.exec_slot, NEG_INF)
    any_live = jnp.any(acc_ok, axis=0)  # [G]
    base = jnp.where(any_live, jnp.max(exec_rel, axis=0), 0).astype(I32)  # [G]
    # lag reference includes stopped-but-serving peers so a laggard behind a
    # finished group still reports its true gap to the host.
    base_serve = jnp.where(
        jnp.any(serve_ok, axis=0),
        jnp.max(jnp.where(serve_ok, state.exec_slot, NEG_INF), axis=0),
        0,
    ).astype(I32)
    jw = jnp.arange(W, dtype=I32)[:, None]  # [W, 1]
    s_j = base[None, :] + jw  # [W, G] absolute slots, window order
    i_j = jnp.bitwise_and(s_j, Wm)  # [W, G] ring indices (replica-agnostic)

    # ---------------- phase 0: candidacy ----------------
    coord_dead = ~alive_at(state.bal_coord)  # [R, G]
    caught_up = (state.exec_slot - base[None, :]) >= 0
    # candidate = first live *caught-up* member: a stuck laggard must not
    # hold the coordinatorship hostage (at least one live member is always
    # caught up, by definition of base).
    cand_ok = acc_ok & caught_up
    first_live = jnp.argmax(cand_ok, axis=0).astype(I32)  # [G]
    im_cand = (r_idx == first_live[None, :]) & cand_ok
    have_auth = (state.coord_active | state.coord_preparing) & bal_ge(
        state.coord_bnum, r_idx, state.bal_num, state.bal_coord
    )
    start_any = im_cand & coord_dead & ~have_auth & own2
    if fast_elect:
        # consecutive-ballot fast takeover: my promise is already the group
        # max among member rows (mirror facts included — they only ever
        # under-report), so max(bal_num, coord_bnum)+1 below is the
        # predecessor's immediate successor and prepare is skippable.
        gmax_bn = jnp.max(jnp.where(member, state.bal_num, NEG_INF), axis=0)
        consec = (state.bal_num == gmax_bn[None, :]) & (
            state.bal_num >= state.coord_bnum
        )
        fast_start = start_any & consec
        start_prep = start_any & ~consec
    else:
        start_prep = start_any
    coord_bnum = jnp.where(
        start_any,
        jnp.maximum(state.bal_num, state.coord_bnum) + 1,
        state.coord_bnum,
    )
    coord_preparing = state.coord_preparing | start_prep
    coord_active = state.coord_active
    coord_fast = state.coord_fast

    # ---------------- phase 1: prepare / promise / carryover ----------------
    prep_mask = coord_preparing & acc_ok  # [R, G] candidates broadcasting
    pn = jnp.where(prep_mask, coord_bnum, NEG_INF)
    best_pn, best_pc = _lexmax(pn, jnp.broadcast_to(r_idx, (R, G)), axis=0)  # [G]
    upgrade = (
        acc_ok
        & own2
        & (best_pn[None, :] != NEG_INF)
        & bal_gt(best_pn[None, :], best_pc[None, :], state.bal_num, state.bal_coord)
    )
    bal_num = jnp.where(upgrade, best_pn[None, :], state.bal_num)
    bal_coord = jnp.where(upgrade, best_pc[None, :], state.bal_coord)
    if fast_elect:
        # a fast winner promises its own new ballot at once (the analog of
        # the promise a full winner collects from itself via prep_mask)
        bal_num = jnp.where(fast_start, coord_bnum, bal_num)
        bal_coord = jnp.where(
            fast_start, jnp.broadcast_to(r_idx, (R, G)), bal_coord
        )

    # promise match[r1, r2, g]: acceptor r2's promised ballot == candidate r1's
    match = (
        prep_mask[:, None, :]
        & acc_ok[None, :, :]
        & (bal_num[None, :, :] == coord_bnum[:, None, :])
        & (bal_coord[None, :, :] == r_idx[:, None])
    )
    promises = jnp.sum(match, axis=1).astype(I32)  # [R, G]
    won = prep_mask & (promises >= maj[None, :]) & own2  # ≤1 winner per g

    # Gather every replica's accepted window at the common base ring indices:
    # A_x[r, j, g] = acc_x[r, i_j[j, g], g].
    a_bnum = gather_planes(state.acc_bnum, i_j)
    a_bcoord = gather_planes(state.acc_bcoord, i_j)
    a_req = gather_planes(state.acc_req, i_j)
    a_slot = gather_planes(state.acc_slot, i_j)
    a_stop = gather_planes(state.acc_stop, i_j)
    acc_here = (a_slot == s_j[None, :, :]) & (a_bnum >= 0)  # [R, W, G]

    # carryover: among the winner's promisers, max-ballot accepted pvalue/slot
    promiser = jnp.einsum("rg,rsg->sg", won, match).astype(jnp.bool_)  # [R, G]
    if fast_elect:
        # a fast winner has no promisers; its carryover source is every
        # member row of its own mirrors (monotone facts — a stale mirror
        # under-reports, which only makes the seeded prefix shorter)
        fast_any = jnp.any(fast_start, axis=0)  # [G]
        sel_rows = jnp.where(fast_any[None, :], member, promiser)
        eff = sel_rows[:, None, :] & acc_here
    else:
        eff = promiser[:, None, :] & acc_here
    c_n, c_c = _lexmax(jnp.where(eff, a_bnum, NEG_INF), a_bcoord, axis=0)  # [W, G]
    c_exists = jnp.any(eff, axis=0)
    sel = eff & (a_bnum == c_n[None]) & (a_bcoord == c_c[None])
    c_req = jnp.max(jnp.where(sel, a_req, 0), axis=0)
    c_stop = jnp.any(sel & a_stop, axis=0)
    # noop-fill gaps below the highest carried slot so later slots can commit
    hi = jnp.max(jnp.where(c_exists, jw, -1), axis=0)  # [G], -1 if none
    if fast_elect:
        # a fast winner also covers the predecessor's visible assignment
        # frontier (max member next_slot): slots the predecessor assigned
        # whose accepts this candidate hasn't seen get noop proposals
        # instead of gaps (the refusal rule keeps any real value safe; the
        # adoption rule converges them).  Capped at base+W (ring capacity).
        next_mem = jnp.max(jnp.where(member, state.next_slot, NEG_INF), axis=0)
        fast_next = jnp.minimum(
            jnp.maximum(base + hi + 1, next_mem), base + W
        )  # [G]
        hi_eff = jnp.where(fast_any, fast_next - base - 1, hi)
        ns_win = jnp.where(fast_any, fast_next, base + hi + 1)
        c_valid = jw <= hi_eff[None, :]  # [W, G] window order
    else:
        ns_win = base + hi + 1
        c_valid = jw <= hi[None, :]  # [W, G] window order
    # window-order -> ring-order: ring plane i holds window offset (i-base)%W
    j_of_i = jnp.bitwise_and(jw - base[None, :], Wm)  # [W, G]

    def to_ring(v):  # [W, G] window-order -> ring-order
        return gather_planes(v, j_of_i)

    co_req, co_stop, co_valid, co_slot = (
        to_ring(c_req),
        to_ring(c_stop),
        to_ring(c_valid),
        to_ring(s_j),
    )
    won_any = (won | fast_start) if fast_elect else won
    won3 = won_any[:, None, :]
    prop_req = jnp.where(won3, co_req[None], state.prop_req)
    prop_slot = jnp.where(won3, co_slot[None], state.prop_slot)
    prop_valid = jnp.where(won3, co_valid[None], state.prop_valid)
    prop_stop = jnp.where(won3, co_stop[None], state.prop_stop)
    next_slot = jnp.where(won_any, ns_win[None, :], state.next_slot)

    coord_active = coord_active | won_any
    coord_preparing = coord_preparing & ~won
    if fast_elect:
        coord_fast = (coord_fast | fast_start) & ~won
    # retirement: somebody holds a promise above my ballot (preemption,
    # handleAcceptReplyHigherBallot analog, PaxosCoordinatorState.java:661)
    pm_n, pm_c = _lexmax(
        jnp.where(acc_ok, bal_num, NEG_INF), jnp.where(acc_ok, bal_coord, NEG_INF), axis=0
    )  # [G]
    retire = bal_gt(pm_n[None, :], pm_c[None, :], coord_bnum, r_idx)
    coord_active = coord_active & ~retire
    coord_preparing = coord_preparing & ~retire
    if fast_elect:
        coord_fast = coord_fast & ~retire
    prop_valid = prop_valid & ~retire[:, None, :]

    # ---------------- phase 2a: intake + slot assignment ----------------
    an = jnp.where(coord_active & acc_ok, coord_bnum, NEG_INF)
    w_n, w_c = _lexmax(an, jnp.broadcast_to(r_idx, (R, G)), axis=0)  # [G]
    has_coord = w_n != NEG_INF
    is_win = (r_idx == w_c[None, :]) & has_coord[None, :] & own2  # [R, G]

    req_flat = inbox.req.reshape(RP, G)
    stop_flat = inbox.stop.reshape(RP, G)
    src_alive = jnp.broadcast_to(
        alive[:, None, None], (R, P, G)
    ).reshape(RP, G)
    group_open = has_coord & jnp.any(is_win & is_active, axis=0)
    if lease is not None:
        # ---- lease write fence (ISSUE 17) ----
        # A coordinator that is NOT the lease holder may not admit new
        # writes until the prior lease has expired past its skew margin:
        # blocking intake here blocks slot assignment, so no write the
        # holder has not itself assigned (and thus counted into its
        # accepted frontier) can ever be acked while local reads are
        # still legal at the holder.  Already-assigned proposals keep
        # pushing — they are covered by the holder's frontier.
        lclock = lease.clock + 1
        lease_expired = lclock >= lease.until + lease.margin
        fence_ok = (lease.holder < 0) | (lease.holder == w_c) | lease_expired
        lease_wait = group_open & ~fence_ok
        group_open = group_open & fence_ok
    valid_in = (req_flat != NO_REQUEST) & src_alive & group_open[None, :]
    # FIFO admission without a sort (argsort over the request axis was ~2/3
    # of the whole tick on TPU): rank each valid entry by prefix count —
    # stable valid-first order over the index axis is exactly index order
    # restricted to valid entries, so prefix sums replace the permutation.
    vi = valid_in.astype(I32)
    p_rank = jnp.cumsum(vi, axis=0) - vi  # [RP, G] rank among valid
    k_total = jnp.sum(valid_in, axis=0).astype(I32)  # [G]
    w_next = jnp.sum(jnp.where(is_win, next_slot, 0), axis=0).astype(I32)  # [G]
    w_exec = jnp.sum(jnp.where(is_win, state.exec_slot, 0), axis=0).astype(I32)
    space = jnp.maximum(jnp.int32(W) - (w_next - w_exec), 0)
    k = jnp.minimum(k_total, space)  # [G]
    # stop-request fencing: nothing may be proposed after a stop; if a stop
    # is among the first k, truncate intake right after it.  The prefix of
    # taken stops in index order equals the sorted-order prefix (above).
    taken_pre = valid_in & (p_rank < k[None, :])
    stop_taken = stop_flat & taken_pre
    stop_before = (jnp.cumsum(stop_taken.astype(I32), axis=0)
                   - stop_taken.astype(I32))
    taken_flat = taken_pre & (stop_before == 0)  # [RP, G] in index order
    k = jnp.sum(taken_flat, axis=0).astype(I32)
    # rank among TAKEN entries == p_rank (taken is a rank prefix of valid);
    # mask non-taken entries out of the match domain
    q_key = jnp.where(taken_flat, p_rank, jnp.int32(-1))

    ji = jnp.bitwise_and(jw - w_next[None, :], Wm)  # [W, G]
    new_at_i = ji < k[None, :]  # [W, G] ring planes receiving new proposals
    nreq_i = match_planes(req_flat, q_key, ji)
    nstop_i = match_planes(stop_flat, q_key, ji)
    nslot_i = w_next[None, :] + ji
    wmask = is_win[:, None, :] & new_at_i[None, :, :]
    prop_req = jnp.where(wmask, nreq_i[None], prop_req)
    prop_stop = jnp.where(wmask, nstop_i[None], prop_stop)
    prop_slot = jnp.where(wmask, nslot_i[None], prop_slot)
    prop_valid = prop_valid | wmask
    next_slot = jnp.where(is_win, w_next[None, :] + k[None, :], next_slot)

    intake_taken = taken_flat.reshape(R, P, G)

    if fast_elect:
        # ---- fast-coordinator adoption + consecutive bump ----
        # A fast reign skipped the prepare snapshot, so a proposal seeded
        # from stale mirrors may conflict with a higher-ballot accepted
        # value that IS visible now.  Adopt the max-ballot accepted value
        # strictly below my own ballot wherever it differs from my
        # proposal, and bump my ballot by one per affected group: the
        # re-push must be a fresh ballot (vote tallies key on ballot —
        # two values under one ballot would corrupt them), and +1 keeps
        # the reign consecutive, hence still fast.
        vis = member[:, None, :] & acc_here  # [R, W, G] pre-tick facts
        m_n, m_c = _lexmax(jnp.where(vis, a_bnum, NEG_INF), a_bcoord, axis=0)
        m_sel = vis & (a_bnum == m_n[None]) & (a_bcoord == m_c[None])
        m_req = jnp.max(jnp.where(m_sel, a_req, 0), axis=0)  # [W, G]
        m_stop = jnp.any(m_sel & a_stop, axis=0)
        ad_req, ad_stop, ad_n, ad_c, ad_slot = (
            to_ring(m_req), to_ring(m_stop), to_ring(m_n), to_ring(m_c),
            to_ring(s_j),
        )
        fastc = coord_fast & coord_active & own2  # [R, G]
        below = bal_gt(
            coord_bnum[:, None, :], r_idx[:, None, :], ad_n[None], ad_c[None]
        )  # accepted ballot strictly under my own (my ballot's values are mine)
        adoptp = (
            fastc[:, None, :]
            & prop_valid
            & (ad_n[None] != NEG_INF)
            & (prop_slot == ad_slot[None])
            & below
            & (prop_req != ad_req[None])
        )
        prop_req = jnp.where(adoptp, ad_req[None], prop_req)
        prop_stop = jnp.where(adoptp, ad_stop[None], prop_stop)
        any_adopt = jnp.any(adoptp, axis=1)  # [R, G]
        coord_bnum = jnp.where(any_adopt, coord_bnum + 1, coord_bnum)

    # ---------------- phase 2b: accept ----------------
    pushing = (coord_active & acc_ok)[:, None, :] & prop_valid  # [R, W, G]
    cand_n = jnp.where(pushing, coord_bnum[:, None, :], NEG_INF)
    cand_c = jnp.broadcast_to(r_idx[:, None, :], (R, W, G))
    b_n, b_c = _lexmax(cand_n, cand_c, axis=0)  # [W, G] best pushed ballot
    psel = pushing & (cand_n == b_n[None]) & (cand_c == b_c[None])
    p_req = jnp.max(jnp.where(psel, prop_req, 0), axis=0)  # [W, G]
    p_slot = jnp.max(jnp.where(psel, prop_slot, NEG_INF), axis=0)
    p_stop = jnp.any(psel & prop_stop, axis=0)
    exists = b_n != NEG_INF

    d = p_slot[None, :, :] - state.exec_slot[:, None, :]  # [R, W, G]
    in_win = (d >= 0) & (d < W)
    acceptable = (
        exists[None]
        & in_win
        & bal_ge(b_n[None], b_c[None], bal_num[:, None, :], bal_coord[:, None, :])
        & acc_ok[:, None, :]
        & own2[:, None, :]
    )
    if fast_elect:
        # conflict refusal: a push under a fast ballot must not overwrite a
        # DIFFERENT accepted value — the fast reign never collected
        # promises, so the classical "prepare saw everything" overwrite
        # license does not apply.  Same-value pushes still accept (ballot
        # raise), and the refusal still promises (pr_mask below), so the
        # coordinator can later prove the refusal from its mirrors.
        src_fast = jnp.any(psel & coord_fast[:, None, :], axis=0)  # [W, G]
        conflict = (
            (state.acc_slot == p_slot[None])
            & (state.acc_bnum >= 0)
            & (state.acc_req != p_req[None])
            & src_fast[None]
        )  # [R, W, G]
        refused = acceptable & conflict
        acceptable = acceptable & ~conflict
        pr_mask = acceptable | refused
    else:
        pr_mask = acceptable
    # ring plane for pvalue at slot p_slot is its own plane position already
    # (coordinators store proposals ring-indexed by slot), so accept in place.
    acc_bnum = jnp.where(acceptable, b_n[None], state.acc_bnum)
    acc_bcoord = jnp.where(acceptable, b_c[None], state.acc_bcoord)
    acc_req = jnp.where(acceptable, p_req[None], state.acc_req)
    acc_slot = jnp.where(acceptable, p_slot[None], state.acc_slot)
    acc_stop = jnp.where(acceptable, p_stop[None], state.acc_stop)
    # promise-on-accept (acceptAndUpdateBallot raises the promised ballot)
    ab_n, ab_c = _lexmax(
        jnp.where(pr_mask, b_n[None], NEG_INF),
        jnp.where(pr_mask, b_c[None], NEG_INF),
        axis=1,
    )  # [R, G]
    raise_p = (ab_n != NEG_INF) & bal_gt(ab_n, ab_c, bal_num, bal_coord)
    bal_num = jnp.where(raise_p, ab_n, bal_num)
    bal_coord = jnp.where(raise_p, ab_c, bal_coord)
    if fast_elect:
        # liveness escape: a refuser plus a dead member can block a fast
        # quorum forever (classical prepare would overwrite).  A refusal is
        # PROVEN in my mirrors when a member's promise is at/above my
        # pushed ballot while a conflicting lower-ballot value stays
        # accepted; demote to an ordinary full prepare at the next ballot
        # (always safe).  A fresh adoption bump this tick can't false-
        # positive here: no mirror can already hold a promise at the
        # just-created ballot.
        seen_refusal = (
            conflict
            & member[:, None, :]
            & bal_ge(
                bal_num[:, None, :], bal_coord[:, None, :],
                b_n[None], b_c[None],
            )
        )
        ref_plane = jnp.any(seen_refusal, axis=0)  # [W, G]
        mine = b_c[None] == r_idx[:, None, :]  # [R, W, G] my push planes
        demote = (
            coord_fast & coord_active & own2
            & jnp.any(ref_plane[None] & mine, axis=1)
        )
        coord_active = coord_active & ~demote
        coord_fast = coord_fast & ~demote
        coord_preparing = coord_preparing | demote
        coord_bnum = jnp.where(demote, coord_bnum + 1, coord_bnum)

    # ---------------- phase 2c: tally + quorum ----------------
    A_bnum = gather_planes(acc_bnum, i_j)
    A_bcoord = gather_planes(acc_bcoord, i_j)
    A_req = gather_planes(acc_req, i_j)
    A_slot = gather_planes(acc_slot, i_j)
    A_stop = gather_planes(acc_stop, i_j)
    voteable = (A_slot == s_j[None]) & (A_bnum >= 0) & acc_ok[:, None, :]
    B_n, B_c = _lexmax(jnp.where(voteable, A_bnum, NEG_INF), A_bcoord, axis=0)
    votes = voteable & (A_bnum == B_n[None]) & (A_bcoord == B_c[None])
    cnt = jnp.sum(votes, axis=0).astype(I32)  # [W, G]
    decided = (cnt >= maj[None, :]) & (B_n != NEG_INF)  # [W, G] window order
    v_req = jnp.max(jnp.where(votes, A_req, 0), axis=0)
    v_stop = jnp.any(votes & A_stop, axis=0)
    D_slot = gather_planes(state.dec_slot, i_j)
    D_valid = gather_planes(state.dec_valid, i_j)
    already = jnp.any((D_slot == s_j[None]) & D_valid, axis=0)  # [W, G]
    decided_now = jnp.sum(decided & ~already, axis=0).astype(I32)  # [G]

    de_req, de_stop, de_valid, de_slot = (
        to_ring(v_req),
        to_ring(v_stop),
        to_ring(decided),
        to_ring(s_j),
    )
    # write decisions, but never clobber a laggard's still-undelivered ring
    rel_w = de_slot[None] - state.exec_slot[:, None, :]
    dwrite = de_valid[None] & (rel_w >= 0) & (rel_w < W) & acc_ok[:, None, :]
    dec_req = jnp.where(dwrite, de_req[None], state.dec_req)
    dec_slot = jnp.where(dwrite, de_slot[None], state.dec_slot)
    dec_stop = jnp.where(dwrite, de_stop[None], state.dec_stop)
    dec_valid = jnp.where(dwrite, True, state.dec_valid)

    # ---------------- phase 3: decision sync (laggard catch-up) ----------------
    # latest decision per ring plane among live serving members, then each
    # replica adopts entries that fall inside its own forward window.
    rel = jnp.where(
        dec_valid & serve_ok[:, None, :], dec_slot - base[None, None, :], NEG_INF
    )  # [R, W, G] relative slots are small; max = latest
    rel_best = jnp.max(rel, axis=0)  # [W, G]
    sel_l = rel == rel_best[None]
    l_req = jnp.max(jnp.where(sel_l, dec_req, 0), axis=0)
    l_stop = jnp.any(sel_l & dec_stop, axis=0)
    l_slot = rel_best + base[None, :]  # [W, G] absolute
    have = dec_valid & (dec_slot == l_slot[None])
    d2 = l_slot[None] - state.exec_slot[:, None, :]
    adopt = (
        (rel_best[None] != NEG_INF)
        & (d2 >= 0)
        & (d2 < W)
        & ~have
        & acc_ok[:, None, :]
    )
    dec_req = jnp.where(adopt, l_req[None], dec_req)
    dec_slot = jnp.where(adopt, l_slot[None], dec_slot)
    dec_stop = jnp.where(adopt, l_stop[None], dec_stop)
    dec_valid = jnp.where(adopt, True, dec_valid)

    # ---------------- phase 4: in-order execution ----------------
    s_own = state.exec_slot[:, None, :] + jw[None]  # [R, W, G]
    i_own = jnp.bitwise_and(s_own, Wm)
    Dreq = gather_planes(dec_req, i_own)
    Dslot = gather_planes(dec_slot, i_own)
    Dstop = gather_planes(dec_stop, i_own)
    Dval = gather_planes(dec_valid, i_own)
    ready = Dval & (Dslot == s_own) & acc_ok[:, None, :]
    run = jnp.cumprod(ready.astype(I32), axis=1).astype(jnp.bool_)
    stop_hit = run & Dstop
    stop_before2 = jnp.cumsum(stop_hit.astype(I32), axis=1) - stop_hit.astype(I32)
    exec_mask = run & (stop_before2 == 0)
    if exec_budget > 0:
        # global budget cap: rank would-be executions in (j, r, g) order —
        # every replica's FIRST pending slot outranks anyone's second — and
        # keep the first `exec_budget`.  For fixed (r, g) the rank grows
        # with j, so the kept set is a per-group run prefix (in-order
        # execution preserved; the rest defers).  Fairness across the
        # replica axis matters: ranking (r, j, g)-first starves the highest
        # replica slots under sustained pressure until they fall > W behind
        # and their missed slots rotate out of every decision ring.
        em_t = exec_mask.transpose(1, 0, 2)  # [W, R, G]
        fi = em_t.reshape(-1).astype(I32)
        rank = (jnp.cumsum(fi) - fi).reshape(em_t.shape)
        if group_axis is not None:
            # G is a shard-local block of a mesh-sharded group axis, but the
            # flat (j, r, g) enumeration above must rank GLOBALLY (g is the
            # fastest-varying axis, so shard k's (j, r) row sits after the
            # same row on shards < k).  Exchange tiny [W, R] per-row counts
            # and rebase:  global rank = (count before this (j, r) row)
            # + (this row's count on earlier shards) + (local within-row
            # rank).  Exact, so budget decisions match the unsharded tick
            # bit for bit.
            blk = jnp.sum(em_t, axis=2).astype(I32)  # [W, R] local row counts
            allblk = jax.lax.all_gather(blk, group_axis)  # [S, W, R]
            nsh = allblk.shape[0]
            shard = jax.lax.axis_index(group_axis)
            total = jnp.sum(allblk, axis=0)  # [W, R] global row counts
            tf = total.reshape(-1)
            before_row = (jnp.cumsum(tf) - tf).reshape(total.shape)
            earlier = jnp.sum(
                jnp.where(
                    jnp.arange(nsh, dtype=I32)[:, None, None] < shard,
                    allblk, 0,
                ),
                axis=0,
            )  # [W, R] same row, shards before this one
            lf = blk.reshape(-1)
            row_start = (jnp.cumsum(lf) - lf).reshape(blk.shape)
            rank = (rank - row_start[:, :, None]
                    + (before_row + earlier)[:, :, None])
        exec_mask = exec_mask & (
            rank.transpose(1, 0, 2) < exec_budget
        )
    n_exec = jnp.sum(exec_mask, axis=1).astype(I32)  # [R, G]
    exec_req_out = jnp.where(exec_mask, Dreq, NO_REQUEST)
    exec_stop_out = exec_mask & Dstop
    exec_base = state.exec_slot
    exec_slot = state.exec_slot + n_exec
    stopped_now = jnp.any(exec_mask & Dstop, axis=1)
    status = jnp.where(stopped_now, jnp.int32(int(GroupStatus.STOPPED)), state.status)

    # coordinator GC: stop pushing proposals already executed locally
    prop_valid = prop_valid & (prop_slot - exec_slot[:, None, :] >= 0)

    # ---------------- freeze dead replica slots ----------------
    al3 = alive[:, None, None]
    al2 = alive[:, None]

    def fr2(new, old):
        return jnp.where(al2, new, old)

    def fr3(new, old):
        return jnp.where(al3, new, old)

    new_state = state._replace(
        exec_slot=fr2(exec_slot, state.exec_slot),
        bal_num=fr2(bal_num, state.bal_num),
        bal_coord=fr2(bal_coord, state.bal_coord),
        status=fr2(status, state.status),
        acc_bnum=fr3(acc_bnum, state.acc_bnum),
        acc_bcoord=fr3(acc_bcoord, state.acc_bcoord),
        acc_req=fr3(acc_req, state.acc_req),
        acc_slot=fr3(acc_slot, state.acc_slot),
        acc_stop=fr3(acc_stop, state.acc_stop),
        dec_req=fr3(dec_req, state.dec_req),
        dec_slot=fr3(dec_slot, state.dec_slot),
        dec_valid=fr3(dec_valid, state.dec_valid),
        dec_stop=fr3(dec_stop, state.dec_stop),
        coord_active=fr2(coord_active, state.coord_active),
        coord_preparing=fr2(coord_preparing, state.coord_preparing),
        coord_fast=fr2(coord_fast, state.coord_fast),
        coord_bnum=fr2(coord_bnum, state.coord_bnum),
        next_slot=fr2(next_slot, state.next_slot),
        prop_req=fr3(prop_req, state.prop_req),
        prop_slot=fr3(prop_slot, state.prop_slot),
        prop_valid=fr3(prop_valid, state.prop_valid),
        prop_stop=fr3(prop_stop, state.prop_stop),
    )
    # ------------- laggard repair control summary (donor selection) --------
    # The host repair path used to re-derive the donor from a full [R, G]
    # exec pull (manager.sync_laggard); emit it from the tick instead so the
    # host never touches [R, G] state for repair.  Donor for laggard r =
    # argmax post-tick exec over live members m != r, ties to the lowest m
    # (Python ``max`` over ascending member ids picks the first maximum —
    # match it exactly so journaled OP_SYNC records are bit-identical to the
    # host scan).  Computed as top-2 over the replica axis: r's donor is the
    # global best unless r IS the best, then the runner-up.
    post_exec = new_state.exec_slot
    ridx = jnp.broadcast_to(
        jnp.arange(post_exec.shape[0], dtype=I32)[:, None], post_exec.shape
    )
    d_cand = jnp.where(member & alive[:, None], post_exec, NEG_INF)
    t1_exec, t1_nid = _lexmax(d_cand, -ridx, axis=0)  # [G]
    t2_exec, t2_nid = _lexmax(
        jnp.where(ridx == -t1_nid[None, :], NEG_INF, d_cand), -ridx, axis=0
    )
    self_best = ridx == -t1_nid[None, :]
    d_exec = jnp.where(self_best, t2_exec[None, :], t1_exec[None, :])
    d_id = jnp.where(self_best, -t2_nid[None, :], -t1_nid[None, :])
    # a transfer only helps when the donor is STRICTLY ahead (sync_laggard
    # refuses otherwise); NEG_INF (no eligible donor) fails this too since
    # exec watermarks are never negative
    d_ok = d_exec > post_exec
    d_status = jnp.take_along_axis(
        new_state.status, jnp.clip(d_id, 0, post_exec.shape[0] - 1), axis=0
    )
    outbox = TickOutbox(
        exec_req=jnp.where(al3, exec_req_out, NO_REQUEST),
        exec_stop=jnp.where(al3, exec_stop_out, False),
        exec_base=exec_base,
        exec_count=jnp.where(al2, n_exec, 0),
        intake_taken=intake_taken,
        coord_id=jnp.where(has_coord, w_c, -1),
        decided_now=decided_now,
        lag=jnp.where(
            member & (state.status != int(GroupStatus.FREE)),
            jnp.maximum(base_serve[None, :] - exec_slot, 0),
            0,
        ),
        donor=jnp.where(d_ok, d_id, -1),
        donor_exec=jnp.where(d_ok, d_exec, 0),
        donor_status=jnp.where(d_ok, d_status, 0),
    )
    if lease is not None:
        # ---- lease grant/renew fold (ISSUE 17) ----
        # Renewal piggybacks on the accept traffic this same tick pushed:
        # the effective winner keeps its lease alive just by staying the
        # winner.  A grant needs the previous lease gone (never held, or
        # expired past margin) — a dead holder's lease simply runs out.
        renew = has_coord & (lease.holder == w_c)
        grant = has_coord & ~renew & ((lease.holder < 0) | lease_expired)
        l_holder = jnp.where(grant, w_c, lease.holder)
        l_epoch = jnp.where(grant, lease.epoch + 1, lease.epoch)
        l_until = jnp.where(renew | grant,
                            lclock + jnp.int32(lease_horizon), lease.until)
        new_lease = LeaseState(lclock, l_holder, l_epoch, l_until,
                               lease.margin)
        # accepted frontier: max assigned slot over MEMBER rows (dead
        # included — a dead ex-coordinator's assignments are still
        # accepted facts).  The host's local-read validity check compares
        # the holder's executed watermark against this, both as-of the
        # same tick, so a read is served locally only when the holder has
        # executed every write any coordinator ever assigned (quiescent).
        asn = jnp.max(jnp.where(member, new_state.next_slot, 0), axis=0)
        lease_pack = jnp.stack([
            l_holder, l_epoch, l_until, asn, lease_wait.astype(I32),
        ])
    if health is not None:
        # ---- group health fold (ISSUE 18) ----
        # Read-only w.r.t. consensus: every input below is a fact the tick
        # already computed.  Device-visible backlog = offered intake (the
        # host re-places rejected requests every tick, so a wedged group
        # keeps offering), an assignment frontier ahead of the exec
        # frontier, or an election that has not resolved — which covers
        # the quorum-lost case where intake is never admitted at all.
        hclock = health.clock + 1
        allocated = jnp.any(member, axis=0)  # [G]
        offered = jnp.any(req_flat != NO_REQUEST, axis=0)  # [G]
        asn_h = jnp.max(jnp.where(member, new_state.next_slot, 0), axis=0)
        done_h = jnp.max(jnp.where(member, new_state.exec_slot, 0), axis=0)
        electing = jnp.any(
            member & alive[:, None] & new_state.coord_preparing, axis=0
        )
        backlog = (offered | (asn_h > done_h) | electing) & allocated
        progress = (decided_now > 0) | (jnp.max(n_exec, axis=0) > 0)
        h_last_active = jnp.where(progress | ~backlog, hclock,
                                  health.last_active)
        # coordinator churn: count real handoffs only — a first election
        # is not churn, and a coordinatorless gap collapses into the one
        # handoff its resolution is
        w_eff = jnp.where(has_coord, w_c, -1)
        handoff = has_coord & (health.last_coord >= 0) & (
            w_eff != health.last_coord
        )
        h_last_coord = jnp.where(has_coord, w_eff, health.last_coord)
        sh = jnp.int32(health_decay_shift)
        h_churn = (health.churn - (health.churn >> sh)
                   + (handoff.astype(I32) << 4))
        offered_n = jnp.sum((req_flat != NO_REQUEST).astype(I32), axis=0)
        h_heat = health.heat - (health.heat >> sh) + (offered_n << 4)
        new_health = HealthState(hclock, h_last_active, h_last_coord,
                                 h_churn, h_heat)
        stall = jnp.where(allocated & backlog, hclock - h_last_active, 0)
        wait_n = (jnp.sum(lease_wait.astype(I32)) if lease is not None
                  else jnp.zeros((), I32))
        health_pack = _health_pack_impl(
            stall, h_churn, h_heat, backlog, allocated, wait_n,
            wedge_ticks, health_topk,
        )
    if lease is not None and health is not None:
        return (new_state, outbox, new_lease, lease_pack, new_health,
                health_pack)
    if lease is not None:
        return new_state, outbox, new_lease, lease_pack
    if health is not None:
        return new_state, outbox, new_health, health_pack
    return new_state, outbox


paxos_tick = jax.jit(paxos_tick_impl, donate_argnums=(0,),
                     static_argnums=(2, 3, 4, 5))


class HostOutbox(NamedTuple):
    """Numpy mirror of :class:`TickOutbox` — what the host control loop
    actually consumes.  Produced by :func:`unpack_outbox` from ONE device
    transfer; the per-field ``np.array(out.x)`` pattern costs a fixed
    ~100-200us dispatch+sync per field and dominated the round-2 host
    profile (the pipeline analog of PaxosPacketBatcher: ship one buffer,
    not 26)."""

    exec_req: "np.ndarray"
    exec_stop: "np.ndarray"
    exec_base: "np.ndarray"
    exec_count: "np.ndarray"
    intake_taken: "np.ndarray"
    coord_id: "np.ndarray"
    decided_now: "np.ndarray"
    lag: "np.ndarray"
    donor: "np.ndarray"
    donor_exec: "np.ndarray"
    donor_status: "np.ndarray"


def pack_outbox_impl(out: TickOutbox) -> jnp.ndarray:
    """Flatten every outbox field into one i32 vector (single transfer)."""
    return jnp.concatenate([
        out.exec_req.ravel(),
        out.exec_stop.astype(I32).ravel(),
        out.exec_base.ravel(),
        out.exec_count.ravel(),
        out.intake_taken.astype(I32).ravel(),
        out.coord_id.ravel(),
        out.decided_now.ravel(),
        out.lag.ravel(),
        out.donor.ravel(),
        out.donor_exec.ravel(),
        out.donor_status.ravel(),
    ])


def unpack_outbox(flat, R: int, P: int, W: int, G: int) -> HostOutbox:
    """Host-side inverse of :func:`pack_outbox_impl` (zero-copy views)."""
    flat = np.asarray(flat)
    sizes = [R * W * G, R * W * G, R * G, R * G, R * P * G, G, G, R * G,
             R * G, R * G, R * G]
    offs = np.cumsum([0] + sizes)
    cut = [flat[offs[i]:offs[i + 1]] for i in range(len(sizes))]
    return HostOutbox(
        exec_req=cut[0].reshape(R, W, G),
        exec_stop=cut[1].reshape(R, W, G).astype(bool),
        exec_base=cut[2].reshape(R, G),
        exec_count=cut[3].reshape(R, G),
        intake_taken=cut[4].reshape(R, P, G).astype(bool),
        coord_id=cut[5],
        decided_now=cut[6],
        lag=cut[7].reshape(R, G),
        donor=cut[8].reshape(R, G),
        donor_exec=cut[9].reshape(R, G),
        donor_status=cut[10].reshape(R, G),
    )


def _paxos_tick_packed_impl(state, inbox: TickInbox, own_row: int = -1,
                            exec_budget: int = 0, fast_elect: bool = False):
    state, out = paxos_tick_impl(state, inbox, own_row, exec_budget,
                                 fast_elect=fast_elect)
    return state, pack_outbox_impl(out)


#: fused tick + outbox pack: one dispatch, one device->host buffer.
#: exec_budget matters even on this full-outbox path: WAL replay of a run
#: that ticked with a budget must evolve state identically.
paxos_tick_packed = jax.jit(
    _paxos_tick_packed_impl, donate_argnums=(0,), static_argnums=(2, 3, 4)
)


def _paxos_tick_packed_lease_impl(state, lease: LeaseState, inbox: TickInbox,
                                  own_row: int = -1, exec_budget: int = 0,
                                  lease_horizon: int = 0,
                                  fast_elect: bool = False):
    state, out, lease, lp = paxos_tick_impl(
        state, inbox, own_row, exec_budget, fast_elect=fast_elect,
        lease=lease, lease_horizon=lease_horizon)
    return state, lease, pack_outbox_impl(out), lp


#: lease twin of paxos_tick_packed: same tick + the lease fold, returning
#: the new LeaseState and the [5, G] lease_pack host summary.  A build with
#: read_leases off never calls this — the lease-off program is the literal
#: pre-lease function above, bit for bit.
paxos_tick_packed_lease = jax.jit(
    _paxos_tick_packed_lease_impl, donate_argnums=(0, 1),
    static_argnums=(3, 4, 5, 6),
)


# --------------------------------------------------------------------------
# Compacted outbox: the bounded-transfer tick for the at-scale host path.
#
# The full outbox is O(R*W*G) — ~220 MB/tick at the 1M-group design point,
# which would drown the host link no matter how fast the host loop is.  At
# steady state the host only needs (a) the executed decision stream, whose
# length the exec budget bounds, (b) which placed intake was taken (P bits
# per (r, g)), (c) the rare laggards needing checkpoint transfer, and (d)
# the decision counter.  The device compacts exactly that with an on-device
# prefix-sum scatter (the TPU-native analog of the reference shipping
# individual DECISION packets instead of whole acceptor state,
# PaxosInstanceStateMachine.java:1755-1842), so the device->host transfer is
# O(decisions), not O(state).
# --------------------------------------------------------------------------


class CompactHostOutbox(NamedTuple):
    """Host view of the compacted tick (all numpy, one transfer).

    Executed entries appear in flat (r, j, g) order — per (replica, group)
    they are slot-ordered, which is the only order execution needs.
    ``n_exec == budget`` means the budget may have bitten; deferred work
    arrives on later ticks (see exec_budget in :func:`paxos_tick_impl`).
    """

    n_exec: int
    decided_total: int
    lag_n: int            # total laggards (may exceed the recorded list)
    taken_bits: "np.ndarray"  # i32 [R, G], bit p = inbox slot p was taken
    e_rid: "np.ndarray"   # i32 [n_exec]
    e_rep: "np.ndarray"   # i32 [n_exec]
    e_row: "np.ndarray"   # i32 [n_exec]
    e_slot: "np.ndarray"  # i32 [n_exec]
    e_stop: "np.ndarray"  # bool [n_exec]
    l_rep: "np.ndarray"   # i32 [min(lag_n, lag_budget)]
    l_row: "np.ndarray"   # i32 [min(lag_n, lag_budget)]
    # control summary per flagged laggard: everything a checkpoint transfer
    # needs, so repair never re-derives from [R, G] state (see TickOutbox)
    l_donor: "np.ndarray"  # i32 — device-selected donor replica (-1 = none)
    l_dexec: "np.ndarray"  # i32 — donor's post-tick exec watermark
    l_dstat: "np.ndarray"  # i32 — donor's post-tick group status
    l_lexec: "np.ndarray"  # i32 — the laggard's own post-tick exec watermark


def _compact_outbox_impl(out: TickOutbox, exec_budget: int,
                         lag_budget: int) -> jnp.ndarray:
    R, W, G = out.exec_req.shape
    P = out.intake_taken.shape[1]
    E, Lb = exec_budget, lag_budget
    ji = jnp.arange(W, dtype=I32)[None, :, None]
    mask = ji < out.exec_count[:, None, :]  # [R, W, G] (post-cap)
    mf = mask.reshape(-1)
    mi = mf.astype(I32)
    rank = jnp.cumsum(mi) - mi
    idx = jnp.where(mf, rank, E)  # E -> dropped by mode="drop"

    def scat(vals):
        return jnp.zeros((E,), I32).at[idx].set(
            vals.reshape(-1).astype(I32), mode="drop"
        )

    slot = out.exec_base[:, None, :] + ji
    rep = jnp.broadcast_to(jnp.arange(R, dtype=I32)[:, None, None], (R, W, G))
    row = jnp.broadcast_to(jnp.arange(G, dtype=I32)[None, None, :], (R, W, G))
    meta = rep | (out.exec_stop.astype(I32) << 8)
    n_exec = jnp.sum(mi)
    # intake: P bits per (r, g) — placed-and-taken; host knows what it placed
    pb = jnp.arange(P, dtype=I32)[None, :, None]
    taken_bits = jnp.sum(out.intake_taken.astype(I32) << pb, axis=1)  # [R,G]
    # laggards needing checkpoint transfer (lag >= W): compacted pair list
    lmask = (out.lag >= W).reshape(-1)
    li = lmask.astype(I32)
    lrank = jnp.cumsum(li) - li
    lidx = jnp.where(lmask, lrank, Lb)
    rep2 = jnp.broadcast_to(jnp.arange(R, dtype=I32)[:, None], (R, G))
    row2 = jnp.broadcast_to(jnp.arange(G, dtype=I32)[None, :], (R, G))

    def lscat(vals):
        return jnp.zeros((Lb,), I32).at[lidx].set(
            vals.reshape(-1), mode="drop"
        )

    header = jnp.stack([
        n_exec,
        jnp.sum(out.decided_now),
        jnp.sum(li),
    ]).astype(I32)
    return jnp.concatenate([
        header,
        taken_bits.reshape(-1),
        scat(out.exec_req),
        scat(meta),
        scat(slot),
        scat(row),
        lscat(rep2),
        lscat(row2),
        lscat(out.donor),
        lscat(out.donor_exec),
        lscat(out.donor_status),
        lscat(out.exec_base + out.exec_count),  # laggard's post-tick exec
    ])


def _paxos_tick_compact_impl(state, inbox: TickInbox, own_row: int,
                             exec_budget: int, lag_budget: int,
                             fast_elect: bool = False):
    state, out = paxos_tick_impl(state, inbox, own_row, exec_budget,
                                 fast_elect=fast_elect)
    return state, _compact_outbox_impl(out, exec_budget, lag_budget)


#: fused tick + budgeted on-device compaction: one dispatch, one
#: O(budget) device->host buffer
paxos_tick_compact = jax.jit(
    _paxos_tick_compact_impl, donate_argnums=(0,), static_argnums=(2, 3, 4, 5)
)


def _paxos_tick_compact_lease_impl(state, lease: LeaseState,
                                   inbox: TickInbox, own_row: int,
                                   exec_budget: int, lag_budget: int,
                                   lease_horizon: int,
                                   fast_elect: bool = False):
    state, out, lease, lp = paxos_tick_impl(
        state, inbox, own_row, exec_budget, fast_elect=fast_elect,
        lease=lease, lease_horizon=lease_horizon)
    return state, lease, _compact_outbox_impl(out, exec_budget, lag_budget), lp


#: lease twin of paxos_tick_compact (the at-scale path): the O(budget)
#: compact buffer plus the O(G) lease_pack — still one dispatch, two pulls.
paxos_tick_compact_lease = jax.jit(
    _paxos_tick_compact_lease_impl, donate_argnums=(0, 1),
    static_argnums=(3, 4, 5, 6, 7),
)


class CompactLayout:
    """THE single source of truth for the compacted-outbox flat buffer:
    every offset any consumer needs, computed in one place.

    Producers (:func:`_compact_outbox_impl` and the device-app
    ``fused_compact``, which appends its per-execution extras) emit
    sections in exactly this order; consumers (:func:`unpack_compact`,
    ``PaxosManager._complete_tick``, WAL device-app replay) slice through
    this object only — one field added to the packed buffer is one edit
    here, not silent corruption in a hand-computed twin offset.

    Section order: header[3] | taken_bits[R*G] | e_rid[E] | e_meta[E] |
    e_slot[E] | e_row[E] | l_rep[Lb] | l_row[Lb] | l_donor[Lb] |
    l_dexec[Lb] | l_dstat[Lb] | l_lexec[Lb] | app extras
    (device-app: e_resp[E] | e_miss[E])."""

    HEADER = 3  # n_exec, decided_total, lag_n

    LAG_COLS = 6  # rep, row, donor, donor exec, donor status, laggard exec

    def __init__(self, R: int, G: int, exec_budget: int, lag_budget: int):
        self.R, self.G = R, G
        self.E, self.Lb = exec_budget, lag_budget
        self.o_taken = self.HEADER
        self.o_exec = self.o_taken + R * G      # 4 E-sized exec columns
        self.o_lag = self.o_exec + 4 * self.E   # LAG_COLS Lb-sized columns
        self.base = self.o_lag + self.LAG_COLS * self.Lb  # app extras
        self.o_resp = self.base                 # device-app: KV responses
        self.o_miss = self.base + self.E        # device-app: descriptor miss
        self.total_plain = self.base
        self.total_device = self.base + 2 * self.E

    def kv_extras(self, flat):
        """Device-app extras aligned with the exec stream: (e_resp, e_miss)."""
        return (flat[self.o_resp:self.o_resp + self.E],
                flat[self.o_miss:self.o_miss + self.E])


def unpack_compact(flat, R: int, G: int, exec_budget: int,
                   lag_budget: int) -> CompactHostOutbox:
    """Host-side inverse of :func:`_compact_outbox_impl` (zero-copy views
    into the one transferred buffer)."""
    flat = np.asarray(flat)
    L = CompactLayout(R, G, exec_budget, lag_budget)
    E, Lb = L.E, L.Lb
    n_exec, decided_total, lag_n = (int(flat[0]), int(flat[1]), int(flat[2]))
    o = L.o_exec
    e_rid = flat[o:o + n_exec]; o += E
    e_meta = flat[o:o + n_exec]; o += E
    e_slot = flat[o:o + n_exec]; o += E
    e_row = flat[o:o + n_exec]; o += E
    assert o == L.o_lag
    ln = min(lag_n, Lb)
    l_rep = flat[o:o + ln]; o += Lb
    l_row = flat[o:o + ln]; o += Lb
    l_donor = flat[o:o + ln]; o += Lb
    l_dexec = flat[o:o + ln]; o += Lb
    l_dstat = flat[o:o + ln]; o += Lb
    l_lexec = flat[o:o + ln]
    return CompactHostOutbox(
        n_exec=n_exec,
        decided_total=decided_total,
        lag_n=lag_n,
        taken_bits=flat[L.o_taken:L.o_taken + R * G].reshape(R, G),
        e_rid=e_rid,
        e_rep=e_meta & 0xFF,
        e_row=e_row,
        e_slot=e_slot,
        e_stop=(e_meta >> 8).astype(bool),
        l_rep=l_rep,
        l_row=l_row,
        l_donor=l_donor,
        l_dexec=l_dexec,
        l_dstat=l_dstat,
        l_lexec=l_lexec,
    )


# --------------------------------------------------------------------------
# Control summaries beyond the compact buffer: payload-sweep frontier and the
# single-device demand fold.  Both keep the flat compact program byte-
# identical — they are SEPARATE dispatches (frontier) or fuse into the
# single-device program where no GSPMD partitioner is involved (demand).
# --------------------------------------------------------------------------


def sweep_frontier_impl(exec_slot, member, alive):
    """Per-group payload-sweep frontier, the device twin of the host
    reductions ``_sweep_outstanding`` used to run over full ``[R, G]``
    numpy arrays:

    * ``amin``: min exec watermark over MEMBERS (dead included — a slot
      inside a dead member's ring-reach gap must keep its payload for ring
      replay on revival); int32 max where a group has no members.
    * ``base``: max exec watermark over members (the ring-rotation bound);
      int32 min where a group has no members.
    * ``live``: any member currently alive.

    Returns ``(amin[G], base[G], live[G])`` — device arrays.  The manager
    immediately gathers the rows with live outstanding records
    (:func:`frontier_rows`, enqueued in the same dispatch window, before
    the next tick program) and stashes only the [rows] results, so the
    host never transfers or reduces ``[R, G]`` and never queues a device
    program at tick completion."""
    amin = jnp.min(jnp.where(member, exec_slot, jnp.int32(2**31 - 1)), axis=0)
    base = jnp.max(jnp.where(member, exec_slot, NEG_INF), axis=0)
    live = jnp.any(member & alive[:, None], axis=0)
    return amin, base, live


#: Own dispatch on purpose: under the mesh the inputs are
#: P(replica, groups)-sharded and the replica-axis reductions become
#: collectives — correct in an ordinary global-view program, but fusing them
#: into the shard_map tick's jit would trip the documented check_rep
#: miscompile (see parallel/shard_tick module docstring).
sweep_frontier = jax.jit(sweep_frontier_impl)


def _frontier_rows_impl(amin, base, live, rows):
    return (jnp.take(amin, rows, mode="clip"),
            jnp.take(base, rows, mode="clip"),
            jnp.take(live, rows, mode="clip"))


#: O(rows) gather + device->host transfer of a stashed frontier.  One
#: compile per padded row-count bucket; the manager pads to powers of two.
frontier_rows = jax.jit(_frontier_rows_impl)


def _paxos_tick_compact_demand_impl(state, inbox: TickInbox, demand,
                                    own_row: int, exec_budget: int,
                                    lag_budget: int, decay: float,
                                    fast_elect: bool = False):
    """Single-device twin of shard_tick's demand-folding compact tick:
    tick + compaction + placement demand EWMA in ONE program.

    The fold consumes per-row INTAKE (sum of ``intake_taken`` over entry
    and p slots — exactly the ``taken_bits`` popcount the host fold used to
    compute in an O(G*P) numpy loop per tick), so the host-visible demand
    samples are bit-identical to the old host fold.  Fusing is safe here
    precisely because there is no mesh: the GSPMD same-jit miscompile that
    forces the mesh path's fold into a separate dispatch does not exist in
    a single-device program, and the flat compact buffer stays
    byte-identical."""
    state, out = paxos_tick_impl(state, inbox, own_row, exec_budget,
                                 fast_elect=fast_elect)
    per_row = jnp.sum(out.intake_taken.astype(demand.dtype), axis=(0, 1))
    new_demand = decay * demand + per_row
    return state, _compact_outbox_impl(out, exec_budget, lag_budget), new_demand


paxos_tick_compact_demand = jax.jit(
    _paxos_tick_compact_demand_impl, donate_argnums=(0, 2),
    static_argnums=(3, 4, 5, 6, 7),
)


def make_inbox(n_replicas: int, n_groups: int, per_tick: int) -> TickInbox:
    """An empty inbox template (host fills rows it has traffic for)."""
    return TickInbox(
        req=jnp.zeros((n_replicas, per_tick, n_groups), I32),
        stop=jnp.zeros((n_replicas, per_tick, n_groups), jnp.bool_),
        alive=jnp.ones((n_replicas,), jnp.bool_),
    )


# --------------------------------------------------------------------------
# Mixed log/register planes (register mode, RMWPaxos arxiv 2001.03362).
#
# Register groups run the SAME tick kernel on a second dense state plane
# built with W=1: the ring degenerates to a single in-place consensus cell
# (space caps at one outstanding, prepare carryover IS carry-forward, and
# exec_slot counts versions instead of log length).  The composite row
# space the manager exposes is [0, G_log) log rows followed by
# [G_log, G_log + G_reg) register rows — the row index is the mode bit, so
# one fused program splits the inbox at the static plane boundary, runs
# paxos_tick_impl per plane, and the host merges the two outboxes back
# into the composite row space.  No mode mask inside the kernel: the
# W-generic ring math already IS the register semantics at W=1.
# --------------------------------------------------------------------------


def _split_inbox(inbox: TickInbox, g_log: int):
    return (
        TickInbox(inbox.req[:, :, :g_log], inbox.stop[:, :, :g_log],
                  inbox.alive),
        TickInbox(inbox.req[:, :, g_log:], inbox.stop[:, :, g_log:],
                  inbox.alive),
    )


def _paxos_tick_mixed_packed_impl(state, rstate, inbox: TickInbox,
                                  own_row: int = -1, exec_budget: int = 0):
    """Fused mixed tick, full (packed) outbox per plane."""
    g_log = state.exec_slot.shape[1]
    ib_l, ib_r = _split_inbox(inbox, g_log)
    state, out_l = paxos_tick_impl(state, ib_l, own_row, exec_budget)
    rstate, out_r = paxos_tick_impl(rstate, ib_r, own_row, exec_budget)
    return state, rstate, pack_outbox_impl(out_l), pack_outbox_impl(out_r)


paxos_tick_mixed_packed = jax.jit(
    _paxos_tick_mixed_packed_impl, donate_argnums=(0, 1),
    static_argnums=(3, 4),
)


def _paxos_tick_mixed_packed_lease_impl(state, rstate, lease, rlease,
                                        inbox: TickInbox, own_row: int = -1,
                                        exec_budget: int = 0,
                                        lease_horizon: int = 0):
    """Lease twin of the mixed packed tick: each plane folds its own
    LeaseState (register groups are first-class lease targets — their W=1
    quiescence test is exactly the same frontier comparison)."""
    g_log = state.exec_slot.shape[1]
    ib_l, ib_r = _split_inbox(inbox, g_log)
    state, out_l, lease, lp_l = paxos_tick_impl(
        state, ib_l, own_row, exec_budget, lease=lease,
        lease_horizon=lease_horizon)
    rstate, out_r, rlease, lp_r = paxos_tick_impl(
        rstate, ib_r, own_row, exec_budget, lease=rlease,
        lease_horizon=lease_horizon)
    return (state, rstate, lease, rlease,
            pack_outbox_impl(out_l), pack_outbox_impl(out_r), lp_l, lp_r)


paxos_tick_mixed_packed_lease = jax.jit(
    _paxos_tick_mixed_packed_lease_impl, donate_argnums=(0, 1, 2, 3),
    static_argnums=(5, 6, 7),
)


def _paxos_tick_mixed_compact_impl(state, rstate, inbox: TickInbox,
                                   own_row: int, exec_budget: int,
                                   lag_budget: int):
    """Fused mixed tick, budgeted compact outbox per plane.  The register
    plane's compaction flags laggards at lag >= 1 for free: the lag
    threshold inside _compact_outbox_impl is the plane's own W."""
    g_log = state.exec_slot.shape[1]
    ib_l, ib_r = _split_inbox(inbox, g_log)
    state, out_l = paxos_tick_impl(state, ib_l, own_row, exec_budget)
    rstate, out_r = paxos_tick_impl(rstate, ib_r, own_row, exec_budget)
    return (state, rstate,
            _compact_outbox_impl(out_l, exec_budget, lag_budget),
            _compact_outbox_impl(out_r, exec_budget, lag_budget))


paxos_tick_mixed_compact = jax.jit(
    _paxos_tick_mixed_compact_impl, donate_argnums=(0, 1),
    static_argnums=(3, 4, 5),
)


def _paxos_tick_mixed_compact_lease_impl(state, rstate, lease, rlease,
                                         inbox: TickInbox, own_row: int,
                                         exec_budget: int, lag_budget: int,
                                         lease_horizon: int):
    g_log = state.exec_slot.shape[1]
    ib_l, ib_r = _split_inbox(inbox, g_log)
    state, out_l, lease, lp_l = paxos_tick_impl(
        state, ib_l, own_row, exec_budget, lease=lease,
        lease_horizon=lease_horizon)
    rstate, out_r, rlease, lp_r = paxos_tick_impl(
        rstate, ib_r, own_row, exec_budget, lease=rlease,
        lease_horizon=lease_horizon)
    return (state, rstate, lease, rlease,
            _compact_outbox_impl(out_l, exec_budget, lag_budget),
            _compact_outbox_impl(out_r, exec_budget, lag_budget), lp_l, lp_r)


paxos_tick_mixed_compact_lease = jax.jit(
    _paxos_tick_mixed_compact_lease_impl, donate_argnums=(0, 1, 2, 3),
    static_argnums=(5, 6, 7, 8),
)


def merge_outbox(out_l: HostOutbox, out_r: HostOutbox) -> HostOutbox:
    """Concatenate the two planes' full outboxes into the composite row
    space (register rows offset by G_log positionally — every field is
    indexed by row, so plain concatenation along the group axis is the
    whole merge).  The register plane's W=1 exec ring is zero-padded to
    the log plane's W; safe because consumers read only j < exec_count
    entries and a register row executes at most one slot per tick."""
    R, W, _ = out_l.exec_req.shape
    Rr, Wr, Gr = out_r.exec_req.shape

    def wide(a):
        if Wr == W:
            return a
        pad = np.zeros((Rr, W - Wr, Gr), a.dtype)
        return np.concatenate([a, pad], axis=1)

    cat = np.concatenate
    return HostOutbox(
        exec_req=cat([out_l.exec_req, wide(out_r.exec_req)], axis=2),
        exec_stop=cat([out_l.exec_stop, wide(out_r.exec_stop)], axis=2),
        exec_base=cat([out_l.exec_base, out_r.exec_base], axis=1),
        exec_count=cat([out_l.exec_count, out_r.exec_count], axis=1),
        intake_taken=cat([out_l.intake_taken, out_r.intake_taken], axis=2),
        coord_id=cat([out_l.coord_id, out_r.coord_id]),
        decided_now=cat([out_l.decided_now, out_r.decided_now]),
        lag=cat([out_l.lag, out_r.lag], axis=1),
        donor=cat([out_l.donor, out_r.donor], axis=1),
        donor_exec=cat([out_l.donor_exec, out_r.donor_exec], axis=1),
        donor_status=cat([out_l.donor_status, out_r.donor_status], axis=1),
    )


def merge_compact_outbox(co_l: CompactHostOutbox, co_r: CompactHostOutbox,
                         g_log: int) -> CompactHostOutbox:
    """Merge two planes' compact outboxes into composite rows: counts sum,
    taken_bits stack along G, and the e_*/l_* columns (already trimmed to
    valid length by unpack_compact — no padding reaches the host) simply
    concatenate with the register plane's row ids offset by g_log."""
    cat = np.concatenate
    return CompactHostOutbox(
        n_exec=co_l.n_exec + co_r.n_exec,
        decided_total=co_l.decided_total + co_r.decided_total,
        lag_n=co_l.lag_n + co_r.lag_n,
        taken_bits=np.hstack([co_l.taken_bits, co_r.taken_bits]),
        e_rid=cat([co_l.e_rid, co_r.e_rid]),
        e_rep=cat([co_l.e_rep, co_r.e_rep]),
        e_row=cat([co_l.e_row, co_r.e_row + g_log]),
        e_slot=cat([co_l.e_slot, co_r.e_slot]),
        e_stop=cat([co_l.e_stop, co_r.e_stop]),
        l_rep=cat([co_l.l_rep, co_r.l_rep]),
        l_row=cat([co_l.l_row, co_r.l_row + g_log]),
        l_donor=cat([co_l.l_donor, co_r.l_donor]),
        l_dexec=cat([co_l.l_dexec, co_r.l_dexec]),
        l_dstat=cat([co_l.l_dstat, co_r.l_dstat]),
        l_lexec=cat([co_l.l_lexec, co_r.l_lexec]),
    )


# --------------------------------------------------------------------------
# Batched WAL replay (ISSUE 19): lax.scan over the tick axis.
#
# Journal replay re-runs the SAME fused tick body as the live run, but the
# record-at-a-time loop paid one host→device inbox upload, one device
# dispatch and one device→host outbox pull PER journaled tick.  Here a
# window of K tick inboxes arrives as padded COO columns (entry, lane,
# row, rid, stop — see wal/columnar.py), the scan body scatters each
# tick's dense inbox on device and runs the tick, and each tick emits the
# budgeted compact outbox — so a window costs ONE dispatch and one
# [K, total] pull, and the host processes the per-tick exec streams
# through the vectorized compact fold.
#
# The scan programs deliberately do NOT donate their inputs: the host
# keeps the pre-window state so a budget overflow (a tick whose true
# n_exec exceeds the scatter budget — detectable from the compact header)
# can discard the window's outputs and re-run it through the
# record-at-a-time reference arm without any loss.
# --------------------------------------------------------------------------


def _coo_inbox(x, R: int, P: int, g_total: int) -> TickInbox:
    """Scatter one tick's COO columns into the dense [R, P, G] inbox.
    Padding lanes target row == g_total, one past the composite row
    space, and fall out via mode="drop" — bit-identical to the host-side
    dense buffers the reference arm builds."""
    e, p, g = x["e"], x["p"], x["g"]
    req = jnp.zeros((R, P, g_total), I32).at[e, p, g].set(
        x["rid"], mode="drop")
    stop = jnp.zeros((R, P, g_total), jnp.bool_).at[e, p, g].set(
        x["stop"], mode="drop")
    return TickInbox(req, stop, x["alive"])


def _replay_scan_impl(state, xs, P: int, exec_budget: int,
                      scat_budget: int, lag_budget: int):
    R, G = state.exec_slot.shape

    def body(st, x):
        st, out = paxos_tick_impl(st, _coo_inbox(x, R, P, G), -1,
                                  exec_budget)
        return st, _compact_outbox_impl(out, scat_budget, lag_budget)

    return jax.lax.scan(body, state, xs)


#: K journaled ticks in one device program; returns (state, packs[K, total])
replay_scan_ticks = jax.jit(_replay_scan_impl, static_argnums=(2, 3, 4, 5))


def _replay_scan_lease_impl(state, lease, xs, P: int, exec_budget: int,
                            scat_budget: int, lag_budget: int,
                            lease_horizon: int):
    R, G = state.exec_slot.shape
    lp0 = jnp.zeros((5, G), I32)

    def body(carry, x):
        st, ls, _ = carry
        st, out, ls, lp = paxos_tick_impl(
            st, _coo_inbox(x, R, P, G), -1, exec_budget, lease=ls,
            lease_horizon=lease_horizon)
        packed = _compact_outbox_impl(out, scat_budget, lag_budget)
        return (st, ls, lp), (packed, jnp.sum(lp[LP_WAIT]).astype(I32))

    (state, lease, lp_last), (packs, waits) = jax.lax.scan(
        body, (state, lease, lp0), xs)
    return state, lease, packs, lp_last, waits


#: lease twin: also returns the FINAL tick's lease pack (the host mirror
#: only ever holds the latest pack) and per-tick wait sums for metrics
replay_scan_ticks_lease = jax.jit(
    _replay_scan_lease_impl, static_argnums=(3, 4, 5, 6, 7))


def _replay_scan_mixed_impl(state, rstate, xs, P: int, exec_budget: int,
                            scat_budget: int, lag_budget: int):
    R, g_log = state.exec_slot.shape
    g_total = g_log + rstate.exec_slot.shape[1]

    def body(carry, x):
        st, rst = carry
        ib_l, ib_r = _split_inbox(_coo_inbox(x, R, P, g_total), g_log)
        st, out_l = paxos_tick_impl(st, ib_l, -1, exec_budget)
        rst, out_r = paxos_tick_impl(rst, ib_r, -1, exec_budget)
        return (st, rst), jnp.concatenate([
            _compact_outbox_impl(out_l, scat_budget, lag_budget),
            _compact_outbox_impl(out_r, scat_budget, lag_budget),
        ])

    (state, rstate), packs = jax.lax.scan(body, (state, rstate), xs)
    return state, rstate, packs


#: mixed-plane twin: per tick the two planes' compact buffers ride one
#: [total_l + total_r] row (host slices via CompactLayout per plane)
replay_scan_ticks_mixed = jax.jit(
    _replay_scan_mixed_impl, static_argnums=(3, 4, 5, 6))


def _replay_scan_mixed_lease_impl(state, rstate, lease, rlease, xs, P: int,
                                  exec_budget: int, scat_budget: int,
                                  lag_budget: int, lease_horizon: int):
    R, g_log = state.exec_slot.shape
    g_reg = rstate.exec_slot.shape[1]
    g_total = g_log + g_reg
    lp0 = (jnp.zeros((5, g_log), I32), jnp.zeros((5, g_reg), I32))

    def body(carry, x):
        st, rst, ls, rls, _ = carry
        ib_l, ib_r = _split_inbox(_coo_inbox(x, R, P, g_total), g_log)
        st, out_l, ls, lp_l = paxos_tick_impl(
            st, ib_l, -1, exec_budget, lease=ls,
            lease_horizon=lease_horizon)
        rst, out_r, rls, lp_r = paxos_tick_impl(
            rst, ib_r, -1, exec_budget, lease=rls,
            lease_horizon=lease_horizon)
        packed = jnp.concatenate([
            _compact_outbox_impl(out_l, scat_budget, lag_budget),
            _compact_outbox_impl(out_r, scat_budget, lag_budget),
        ])
        waits = (jnp.sum(lp_l[LP_WAIT]) + jnp.sum(lp_r[LP_WAIT])).astype(I32)
        return (st, rst, ls, rls, (lp_l, lp_r)), (packed, waits)

    (state, rstate, lease, rlease, lp_last), (packs, waits) = jax.lax.scan(
        body, (state, rstate, lease, rlease, lp0), xs)
    return state, rstate, lease, rlease, packs, lp_last, waits


replay_scan_ticks_mixed_lease = jax.jit(
    _replay_scan_mixed_lease_impl, static_argnums=(5, 6, 7, 8, 9))


# --------------------------------------------------------------------------
# Sparse window replay (ISSUE 19): the tick fold is a pure per-group map —
# a row whose inbox is empty does not change AT ALL across a tick (no tick
# counter enters the fold, cross-replica reductions are row-local), so a
# replay window only needs the rows its journaled inboxes actually touch.
# The dispatcher gathers those rows into a narrow [R, .., A] plane (G is
# the minor axis of every state field), runs the SAME scan programs above
# at width A instead of G, and scatters the evolved columns back — per
# journaled tick the device fold costs O(active), not O(G).  This is what
# makes batched replay win at 1M groups: the dense scan still pays the
# full-plane tick body per journaled tick, which at G=1M dwarfs the
# dispatch overhead it saves.  The lease fold (per-tick countdown on every
# row) and the health fold (per-tick heat decay) violate the idle-row
# no-op and keep the dense scan path (wal/logger gates them out).
# --------------------------------------------------------------------------


def _gather_rows_impl(state, rows):
    return jax.tree.map(lambda a: jnp.take(a, rows, axis=a.ndim - 1), state)


#: columns `rows` of the G (minor) axis of every field, as a narrow state
replay_gather_rows = jax.jit(_gather_rows_impl)


def _scatter_rows_impl(full, compact, rows):
    return jax.tree.map(
        lambda f, c: f.at[..., rows].set(c), full, compact)


#: inverse of :func:`replay_gather_rows`; `rows` must be duplicate-free
replay_scatter_rows = jax.jit(_scatter_rows_impl)


# --------------------------------------------------------------------------
# Group-health plane (ISSUE 18): the host side of the health fold above —
# the flat health_pack layout, its unpack, the composite-plane merge, and
# the single generic health tick entry point that covers every dispatch
# combination (compact/packed x lease/plain x mixed/single) without a
# twin-per-combination explosion.  Health-off builds never import any of
# this into their dispatch: the off program is the literal pre-health
# function, bit for bit.
# --------------------------------------------------------------------------


class HealthLayout:
    """Single source of truth for the flat health_pack buffer (the
    :class:`CompactLayout` discipline): ``gauges[HG_N] | hist_stall[HB] |
    hist_churn[HB] | (val[K], row[K]) x (stuck, churny, hot)``."""

    def __init__(self, topk: int):
        self.K = topk
        self.o_hist_stall = HG_N
        self.o_hist_churn = self.o_hist_stall + HB
        self.o_top = self.o_hist_churn + HB
        self.total = self.o_top + 6 * topk


class HealthView(NamedTuple):
    """Host (numpy) view of one tick's health pack: the needle-finding
    summary the manager mirrors each tick at O(K) transfer cost."""

    alloc: int          # allocated groups
    backlog: int        # groups with device-visible backlog this tick
    wedged: int         # backlogged groups stalled >= wedge_ticks
    max_stall: int      # worst stall age (ticks)
    max_churn: int      # worst churn score (Q4 fixed point)
    lease_wait: int     # coordinators write-fenced behind a prior lease
    hist_stall: "np.ndarray"  # [HB] log2 buckets of stall age
    hist_churn: "np.ndarray"  # [HB] log2 buckets of handoff score (whole)
    stuck_val: "np.ndarray"   # [K] desc; -1 entries = fewer than K rows
    stuck_row: "np.ndarray"
    churn_val: "np.ndarray"
    churn_row: "np.ndarray"
    heat_val: "np.ndarray"
    heat_row: "np.ndarray"


def unpack_health(flat, topk: int) -> HealthView:
    """Host-side inverse of :func:`_health_pack_impl` (zero-copy views)."""
    flat = np.asarray(flat)
    L = HealthLayout(topk)
    o = L.o_top
    cols = []
    for _ in range(6):
        cols.append(flat[o:o + topk])
        o += topk
    return HealthView(
        alloc=int(flat[HG_ALLOC]),
        backlog=int(flat[HG_BACKLOG]),
        wedged=int(flat[HG_WEDGED]),
        max_stall=int(flat[HG_MAX_STALL]),
        max_churn=int(flat[HG_MAX_CHURN]),
        lease_wait=int(flat[HG_LEASE_WAIT]),
        hist_stall=flat[L.o_hist_stall:L.o_hist_stall + HB],
        hist_churn=flat[L.o_hist_churn:L.o_hist_churn + HB],
        stuck_val=cols[0], stuck_row=cols[1],
        churn_val=cols[2], churn_row=cols[3],
        heat_val=cols[4], heat_row=cols[5],
    )


def _merge_top(val_l, row_l, val_r, row_r, g_log: int, topk: int):
    """Merge two planes' top-K columns into composite-row top-K: register
    rows re-offset by g_log, then one partial sort over 2K entries."""
    vals = np.concatenate([val_l, val_r])
    rows = np.concatenate([row_l, row_r + g_log])
    order = np.argsort(-vals, kind="stable")[:topk]
    return vals[order], rows[order]


def merge_health(hv_l: HealthView, hv_r: HealthView, g_log: int,
                 topk: int) -> HealthView:
    """Compose the two planes' health views into the composite row space
    (counts sum, maxima max, histograms add, top-K re-ranks)."""
    sv, sr = _merge_top(hv_l.stuck_val, hv_l.stuck_row,
                        hv_r.stuck_val, hv_r.stuck_row, g_log, topk)
    cv, cr = _merge_top(hv_l.churn_val, hv_l.churn_row,
                        hv_r.churn_val, hv_r.churn_row, g_log, topk)
    hv, hr = _merge_top(hv_l.heat_val, hv_l.heat_row,
                        hv_r.heat_val, hv_r.heat_row, g_log, topk)
    return HealthView(
        alloc=hv_l.alloc + hv_r.alloc,
        backlog=hv_l.backlog + hv_r.backlog,
        wedged=hv_l.wedged + hv_r.wedged,
        max_stall=max(hv_l.max_stall, hv_r.max_stall),
        max_churn=max(hv_l.max_churn, hv_r.max_churn),
        lease_wait=hv_l.lease_wait + hv_r.lease_wait,
        hist_stall=hv_l.hist_stall + hv_r.hist_stall,
        hist_churn=hv_l.hist_churn + hv_r.hist_churn,
        stuck_val=sv, stuck_row=sr,
        churn_val=cv, churn_row=cr,
        heat_val=hv, heat_row=hr,
    )


def _paxos_tick_health_impl(state, rstate, lease, rlease, health, rhealth,
                            inbox: TickInbox, own_row: int, exec_budget: int,
                            lag_budget: int, lease_horizon: int,
                            compact: bool, wedge_ticks: int,
                            decay_shift: int, topk: int):
    """The one health-build tick program: ticks the log plane (and the
    register plane when ``rstate`` is present), folds lease columns when
    present, folds health columns per plane, and packs the outbox compact
    or full per the static ``compact`` flag.  Absent planes/folds pass
    None and collapse out of the traced program (the empty-pytree
    property), so one jit covers the whole dispatch tree the non-health
    manager spells out explicitly.

    Returns a fixed 12-tuple
    ``(state, rstate, lease, rlease, health, rhealth,
       out_l, out_r, lp_l, lp_r, hp_l, hp_r)``
    with None in every absent position."""

    def _plane(st, ib, le, he, k):
        res = paxos_tick_impl(
            st, ib, own_row, exec_budget, lease=le,
            lease_horizon=lease_horizon, health=he, wedge_ticks=wedge_ticks,
            health_decay_shift=decay_shift, health_topk=k,
        )
        st2, out = res[0], res[1]
        i = 2
        le2 = lp = he2 = hp = None
        if le is not None:
            le2, lp = res[i], res[i + 1]
            i += 2
        if he is not None:
            he2, hp = res[i], res[i + 1]
        pk = (_compact_outbox_impl(out, exec_budget, lag_budget)
              if compact else pack_outbox_impl(out))
        return st2, le2, he2, pk, lp, hp

    g_log = state.exec_slot.shape[1]
    if rstate is not None:
        ib_l, ib_r = _split_inbox(inbox, g_log)
        k_r = min(topk, rstate.exec_slot.shape[1])
    else:
        ib_l, ib_r = inbox, None
        k_r = 0
    k_l = min(topk, g_log)
    state, lease, health, pk_l, lp_l, hp_l = _plane(
        state, ib_l, lease, health, k_l)
    pk_r = lp_r = hp_r = None
    if rstate is not None:
        rstate, rlease, rhealth, pk_r, lp_r, hp_r = _plane(
            rstate, ib_r, rlease, rhealth, k_r)
    return (state, rstate, lease, rlease, health, rhealth,
            pk_l, pk_r, lp_l, lp_r, hp_l, hp_r)


#: health twin covering every single-device dispatch combination.  Note
#: the per-plane top-K is ``min(topk, G_plane)`` — the host unpacks with
#: the same clamp (see ``PaxosManager._adopt_health_pack``).
paxos_tick_health = jax.jit(
    _paxos_tick_health_impl, donate_argnums=(0, 1, 2, 3, 4, 5),
    static_argnums=(7, 8, 9, 10, 11, 12, 13, 14),
)

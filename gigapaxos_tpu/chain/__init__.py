from .coordinator import ChainReplicaCoordinator
from .manager import ChainManager
from .modeb import ChainModeBNode

__all__ = ["ChainManager", "ChainModeBNode", "ChainReplicaCoordinator"]

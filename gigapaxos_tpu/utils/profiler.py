"""EWMA delay/rate/counter instrumentation.

Analog of the reference's ``utils/DelayProfiler.java`` (``updateDelay
:61-131``, ``updateMovAvg :156``, ``getStats``): named exponentially-weighted
moving averages for latencies, rates and counters, printed as a one-line
summary.  Used the same way — sampled (1-in-N) instrumentation on hot paths
(``PaxosInstanceStateMachine.java:135-158``), full instrumentation on control
paths.

Host-side only; device-side timing comes from the JAX profiler.
"""

from __future__ import annotations

import threading
import time
from typing import Dict


class DelayProfiler:
    """Thread-safe registry of EWMA stats.

    * ``update_delay(key, t0)`` — EWMA of (now - t0) in milliseconds;
    * ``update_mov_avg(key, value)`` — EWMA of an arbitrary sample;
    * ``update_rate(key, n)`` — EWMA events/sec measured between calls;
    * ``update_count(key, n)`` — plain counter.
    """

    def __init__(self, alpha: float = 1.0 / 32) -> None:
        self.alpha = alpha
        self._lock = threading.Lock()
        self._avg: Dict[str, float] = {}
        self._unit: Dict[str, str] = {}  # "ms" for delays, "" for raw EWMAs
        self._n: Dict[str, int] = {}
        self._count: Dict[str, int] = {}
        self._rate: Dict[str, float] = {}
        self._rate_last: Dict[str, float] = {}

    def _ewma(self, table: Dict[str, float], key: str, sample: float) -> None:
        old = table.get(key)
        table[key] = (
            sample if old is None else (1 - self.alpha) * old + self.alpha * sample
        )

    def update_delay(self, key: str, t0: float, n: int = 1) -> None:
        """Fold in the delay since ``t0`` (``time.monotonic()``), averaged
        over ``n`` operations (the reference's batched variant,
        DelayProfiler.java:102-110)."""
        sample_ms = (time.monotonic() - t0) * 1000.0 / max(n, 1)
        with self._lock:
            self._ewma(self._avg, key, sample_ms)
            self._unit[key] = "ms"
            self._n[key] = self._n.get(key, 0) + n

    def update_mov_avg(self, key: str, value: float) -> None:
        with self._lock:
            self._ewma(self._avg, key, float(value))
            self._unit.setdefault(key, "")
            self._n[key] = self._n.get(key, 0) + 1

    def update_rate(self, key: str, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            last = self._rate_last.get(key)
            self._rate_last[key] = now
            if last is not None and now > last:
                self._ewma(self._rate, key, n / (now - last))

    def update_count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._count[key] = self._count.get(key, 0) + n

    def get(self, key: str) -> float | None:
        with self._lock:
            if key in self._avg:
                return self._avg[key]
            if key in self._rate:
                return self._rate[key]
            if key in self._count:
                return float(self._count[key])
            return None

    def get_stats(self) -> str:
        """One-line summary, the ``DelayProfiler.getStats()`` idiom."""
        with self._lock:
            parts = [
                f"{k}:{v:.2f}{self._unit.get(k, '')}[{self._n.get(k, 0)}]"
                for k, v in sorted(self._avg.items())
            ]
            parts += [f"{k}:{v:.1f}/s" for k, v in sorted(self._rate.items())]
            parts += [f"{k}:{v}" for k, v in sorted(self._count.items())]
        return " ".join(parts)

    def clear(self) -> None:
        with self._lock:
            self._avg.clear()
            self._unit.clear()
            self._n.clear()
            self._count.clear()
            self._rate.clear()
            self._rate_last.clear()


# Module-level default instance (the reference's DelayProfiler is static).
profiler = DelayProfiler()


class Sampler:
    """The 1-in-N instrumentation gate (``instrument(n)``,
    PaxosInstanceStateMachine.java:135-158): ``if sampler(): profiler...``."""

    def __init__(self, n: int = 100):
        self.n = n
        self._i = 0

    def __call__(self) -> bool:
        self._i += 1
        if self._i >= self.n:
            self._i = 0
            return True
        return False

"""ctypes binding for the C++ journal backend (``native/journal.cc``).

Builds the shared library on first use if the toolchain is available (no
pybind11 in the target image — plain C ABI + ctypes).  On-disk format is
byte-identical to :mod:`gigapaxos_tpu.wal.journal`, so readers are shared.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_LOAD_ERROR: Exception | None = None
_LOCK = threading.Lock()
_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")


class NativeUnavailable(RuntimeError):
    pass


def _load():
    global _LIB, _LOAD_ERROR
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_ERROR is not None:
            # cache the failure: re-running the build subprocess on every
            # journal roll would put a fork+compile on the durability path
            raise NativeUnavailable(str(_LOAD_ERROR)) from _LOAD_ERROR
        so = os.path.abspath(os.path.join(_NATIVE_DIR, "libgpjournal.so"))
        src = os.path.abspath(os.path.join(_NATIVE_DIR, "journal.cc"))
        try:
            if not os.path.exists(so) or (
                os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(so)
            ):
                if not os.path.exists(src):
                    raise NativeUnavailable("journal.cc not found")
                subprocess.run(
                    ["make", "-C", os.path.dirname(src), "libgpjournal.so"],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(so)
        except Exception as e:
            _LOAD_ERROR = e
            raise NativeUnavailable(f"native journal unavailable: {e}") from e
        lib.gpj_open.restype = ctypes.c_void_p
        lib.gpj_open.argtypes = [ctypes.c_char_p]
        lib.gpj_append.restype = ctypes.c_int
        lib.gpj_append.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.gpj_sync.restype = ctypes.c_int
        lib.gpj_sync.argtypes = [ctypes.c_void_p]
        lib.gpj_close.restype = None
        lib.gpj_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib


class NativeJournal:
    def __init__(self, path: str):
        lib = _load()
        self._lib = lib
        if os.path.exists(path) and os.path.getsize(path) > 0:
            # scribble classification is the Python scanner's job: gpj_open
            # truncates at the first bad frame, which on a mid-log scribble
            # would silently destroy the intact (possibly acked) suffix
            from .journal import JournalCorruptError, scan_journal

            scan = scan_journal(path)
            if scan.kind == "scribble":
                raise JournalCorruptError(path, scan)
        self._h = lib.gpj_open(path.encode())
        if not self._h:
            raise OSError(f"gpj_open failed for {path}")
        self.path = path
        self.failed = False

    def append(self, record: bytes) -> None:
        if self.failed:
            raise OSError("journal has failed; refusing further appends")
        if self._lib.gpj_append(self._h, record, len(record)) != 0:
            self.failed = True
            raise OSError("journal append failed")

    def sync(self) -> None:
        if self.failed:
            raise OSError("journal has failed; refusing further syncs")
        if self._lib.gpj_sync(self._h) != 0:
            self.failed = True
            raise OSError("journal sync failed")

    def close(self) -> None:
        if self._h:
            self._lib.gpj_close(self._h)
            self._h = None

"""Open-loop load harness: simulated client populations with think times.

The capacity probe (``testing/capacity.py``) is effectively closed-loop at
saturation: a slowing system stretches its own arrival schedule, so offered
load sags exactly when the question is "what happens past the knee?".  This
module drives OPEN-loop load — the overload plane's gate methodology
(ISSUE 14): a population of ``n_clients`` simulated clients, each issuing a
request and then thinking ``think_s`` seconds, yields offered rate
``n_clients / think_s``; arrivals are clock-scheduled (Poisson via
exponential gaps) and NEVER wait for completions.  Hundreds of thousands of
clients cost one generator thread, not one thread each.

Per rung it separates the overload plane's four outcomes — admitted
completions (goodput), ``busy`` NACKs (classed admission shed), ``expired``
refusals (deadline cutoffs), and losses — plus p50/p99 of ADMITTED work,
the number the plane promises stays bounded past the knee ("finish or
refuse fast, never silently drop or do dead work").

``make_overload_cluster`` is the ``make_loopback_cluster`` fixture with the
overload knobs (intake watermark, wire deadline) surfaced, so a bench can
pull the knee down to where a 2-second rung ladder can walk through it.
"""

from __future__ import annotations

import collections
import dataclasses
import random
import time
from dataclasses import field
from typing import Dict, List, Optional

from ..client import ReconfigurableAppClient
from ..config import GigapaxosTpuConfig
from ..models.replicable import NoopApp
from ..node import InProcessCluster

#: a rung "passes" (is at or below the knee) while goodput holds 90% of
#: offered — the same threshold the closed-loop probe uses, applied to
#: clock-scheduled arrivals
KNEE_THRESHOLD = 0.9


@dataclasses.dataclass
class RungResult:
    """One rung of offered load and what came back."""

    offered_rps: float
    n_clients: int
    think_s: float
    duration_s: float
    sent: int = 0
    admitted: int = 0       # ok responses inside the run window
    admitted_late: int = 0  # ok responses that straggled into the drain
    shed_busy: int = 0      # retriable admission NACKs (the refuse-fast arm)
    expired: int = 0        # deadline refusals surfaced to the client
    errors: int = 0         # everything else (not_active, stopped, ...)
    lost: int = 0           # never answered within the drain window
    latencies_s: List[float] = field(default_factory=list)

    @property
    def goodput_rps(self) -> float:
        return self.admitted / self.duration_s if self.duration_s else 0.0

    @property
    def refused(self) -> int:
        return self.shed_busy + self.expired

    def p50_s(self) -> float:
        return self._pct(0.50)

    def p99_s(self) -> float:
        return self._pct(0.99)

    def _pct(self, q: float) -> float:
        if not self.latencies_s:
            return 0.0
        xs = sorted(self.latencies_s)
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    def passed(self) -> bool:
        return self.goodput_rps >= KNEE_THRESHOLD * self.offered_rps

    def to_dict(self) -> dict:
        return {
            "offered_rps": round(self.offered_rps, 1),
            "n_clients": self.n_clients,
            "think_s": self.think_s,
            "goodput_rps": round(self.goodput_rps, 1),
            "sent": self.sent,
            "admitted": self.admitted,
            "admitted_late": self.admitted_late,
            "shed_busy": self.shed_busy,
            "expired": self.expired,
            "errors": self.errors,
            "lost": self.lost,
            "p50_ms": round(self.p50_s() * 1e3, 2),
            "p99_ms": round(self.p99_s() * 1e3, 2),
            "passed": self.passed(),
        }


class OpenLoopGenerator:
    """Clock-driven load from a simulated client population.

    Arrivals come from one thread replaying a seeded Poisson process at
    rate ``n_clients / think_s``; completions are accounted on the client's
    receive threads via lock-free deque appends.  The generator deliberately
    uses the ASYNC ``send_request`` path (fire the request, classify the
    response in the callback) — the sync ``request()`` retry loop would
    close the loop and mask the very overload this measures.
    """

    #: latency is sampled 1-in-N admitted responses so the harness itself
    #: does not tax the measured system at six-figure client counts
    LAT_SAMPLE = 4

    def __init__(self, client: ReconfigurableAppClient, names: List[str],
                 payload: bytes = b"noop", deadline_s: float = 2.0,
                 seed: int = 0):
        self.client = client
        self.names = names
        self.payload = payload
        self.seed = seed
        # every async send stamps this as the wire deadline — per-rung dead
        # work past it is refused at whatever stage first sees it expired
        self.client.default_deadline_s = deadline_s
        for n in names:  # pre-resolve so rungs exclude actives lookups
            self.client.request_actives(n)

    def run_rung(self, n_clients: int, think_s: float,
                 duration_s: float, drain_s: float = 2.0) -> RungResult:
        offered = n_clients / think_s
        res = RungResult(offered_rps=offered, n_clients=n_clients,
                         think_s=think_s, duration_s=duration_s)
        rng = random.Random(self.seed * 1_000_003 + n_clients)
        ok_in = collections.deque()
        ok_late = collections.deque()
        busy = collections.deque()
        expired = collections.deque()
        errs = collections.deque()
        lats = collections.deque()
        t_end = time.monotonic() + duration_s
        next_t = time.monotonic()
        i = 0

        def cb_fast(p):
            if p.get("ok"):
                (ok_in if time.monotonic() <= t_end else ok_late).append(1)
            elif p.get("error") == "busy":
                busy.append(1)
            elif p.get("error") == "expired":
                expired.append(1)
            else:
                errs.append(1)

        while True:
            now = time.monotonic()
            if now >= t_end:
                break
            if now < next_t:
                time.sleep(min(next_t - now, 0.002))
                continue
            # Poisson arrivals: the superposition of n_clients independent
            # think-time renewal processes, one exponential gap at a time
            next_t += rng.expovariate(offered)
            name = self.names[i % len(self.names)]
            i += 1
            if i % self.LAT_SAMPLE == 0:
                t0 = time.monotonic()

                def cb(p, t0=t0):
                    if p.get("ok"):
                        now2 = time.monotonic()
                        (ok_in if now2 <= t_end else ok_late).append(1)
                        lats.append(now2 - t0)
                    elif p.get("error") == "busy":
                        busy.append(1)
                    elif p.get("error") == "expired":
                        expired.append(1)
                    else:
                        errs.append(1)
            else:
                cb = cb_fast
            try:
                self.client.send_request(name, self.payload, cb)
                res.sent += 1
            except Exception:
                errs.append(1)
        deadline = time.monotonic() + drain_s
        while time.monotonic() < deadline:
            got = (len(ok_in) + len(ok_late) + len(busy) + len(expired)
                   + len(errs))
            if got >= res.sent:
                break
            time.sleep(0.01)
        res.admitted = len(ok_in)
        res.admitted_late = len(ok_late)
        res.shed_busy = len(busy)
        res.expired = len(expired)
        res.errors = len(errs)
        res.lost = max(0, res.sent - res.admitted - res.admitted_late
                       - res.shed_busy - res.expired - res.errors)
        res.latencies_s = list(lats)
        return res

    def ramp(self, populations: List[int], think_s: float,
             duration_s: float) -> List[RungResult]:
        """Walk the rung ladder in population order (offered load ramps
        with it); unlike the closed-loop probe this does NOT stop at the
        first failing rung — past-the-knee rungs are the point."""
        return [self.run_rung(n, think_s, duration_s)
                for n in populations]


def find_knee(rungs: List[RungResult]) -> Optional[RungResult]:
    """Highest offered load whose rung still passed (goodput >= 90% of
    offered) — the capacity knee of the ladder."""
    passing = [r for r in rungs if r.passed()]
    return max(passing, key=lambda r: r.offered_rps) if passing else None


def make_overload_cluster(
    n_groups: int = 4,
    n_actives: int = 3,
    intake_hi: int = 4096,
    intake_lo: int = 0,
    app_factory=NoopApp,
    max_groups: Optional[int] = None,
):
    """``make_loopback_cluster`` with the overload plane's knobs surfaced:
    a low ``intake_hi`` pulls the admission watermark under the raw socket
    capacity so a short ladder reaches real shedding."""
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = max_groups or max(64, n_groups)
    cfg.paxos.pipeline_ticks = True
    cfg.paxos.compact_outbox = True
    cfg.paxos.min_tick_interval_s = 0.004
    cfg.overload.enabled = True
    cfg.overload.intake_hi = intake_hi
    cfg.overload.intake_lo = intake_lo
    for i in range(n_actives):
        cfg.nodes.actives[f"AR{i}"] = ("127.0.0.1", 0)
    cfg.nodes.reconfigurators["RC0"] = ("127.0.0.1", 0)
    from ..reconfiguration.demand import DemandProfile

    cluster = InProcessCluster(
        cfg, app_factory,
        demand_profile_factory=lambda name: DemandProfile(
            name, min_requests_before_report=64),
    )
    client = ReconfigurableAppClient(cfg.nodes)
    for g in range(n_groups):
        resp = client.create(f"g{g}")
        if not resp.get("ok"):
            raise RuntimeError(f"create g{g} failed: {resp}")
    return cluster, client


def shed_totals() -> Dict[str, int]:
    """Sum the overload plane's shed counters by class from the process
    registry — the bench's starvation check (client-class sheds active,
    control-class sheds zero) reads straight off the PR-9 metrics."""
    from ..obs.metrics import registry

    out: Dict[str, int] = {"control": 0, "client": 0}
    for m in registry().find("overload_admission_shed_total"):
        cls = dict(m.labels).get("cls", "?")
        out[cls] = out.get(cls, 0) + int(m.value)
    return out


def expired_totals() -> Dict[str, int]:
    """Per-stage expired-drop counters from the process registry."""
    from ..obs.metrics import registry

    out: Dict[str, int] = {}
    for m in registry().find("overload_expired_drops_total"):
        stage = dict(m.labels).get("stage", "?")
        out[stage] = out.get(stage, 0) + int(m.value)
    return out

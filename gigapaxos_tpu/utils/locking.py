"""Shared locking helper for the host managers.

Both data-plane managers (paxos, chain) serialize their public API against
the tick driver on a reentrant ``self.lock`` (the reference synchronizes on
the instance map the same way, PaxosManager.java:2284-2412); this decorator
is that convention in one place.
"""

from __future__ import annotations

import functools
import threading


class ContendedLock:
    """Reentrant lock that flags when an acquirer found it taken.

    CPython locks are unfair: a spinning tick driver re-acquires before any
    waiting control-plane thread (propose, create, stop) gets scheduled,
    starving them indefinitely.  The round-2 fix was an unconditional 0.5 ms
    sleep per tick — a hard ~2k ticks/s ceiling.  Instead, waiters set
    ``contended`` and the driver yields a window only when someone actually
    waited (see paxos/driver.py)."""

    __slots__ = ("_lock", "contended")

    def __init__(self):
        self._lock = threading.RLock()
        self.contended = threading.Event()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._lock.acquire(blocking=False):
            return True
        if not blocking:
            return False
        self.contended.set()
        return self._lock.acquire(timeout=timeout)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._lock.release()


def locked(fn):
    """Serialize a method on ``self.lock`` (reentrant: callbacks that
    re-enter the manager from the tick thread are fine)."""

    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        with self.lock:
            return fn(self, *a, **kw)

    return wrapper

"""Device-app deployment mode: the manager owns a DeviceKVState, request
descriptors upload inside the fused tick, decisions execute ON DEVICE.

This is the deployment wiring of models/device_kv.py (the round-3 version
was bench-only): propose_bulk_kv end-to-end, per-request responses,
WAL crash/recovery reproducing device state, crash/heal via row-granular
checkpoint transfer, and a reconfiguration e2e (create -> commit ->
migrate -> continue) with the device app behind the client edge — the
TESTPaxosApp-on-device analog (gigapaxos/testing/TESTPaxosApp.java:60).
"""

import struct

import numpy as np
import pytest

from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.device_kv import OP_DEL, OP_GET, OP_PUT, pack_desc
from gigapaxos_tpu.paxos.manager import PaxosManager


def mk(G=32, R=3, budget=0):
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = G
    cfg.paxos.compact_outbox = True
    cfg.paxos.device_app = True
    cfg.paxos.bulk_capacity = 1 << 16
    if budget:
        cfg.paxos.exec_budget = budget
    return PaxosManager(cfg, R, [None] * R), cfg


def drain(m, ticks=30):
    for _ in range(ticks):
        m.tick()
    m.drain_pipeline()


def kv_row(m, r, row):
    return (np.asarray(m.kv.key[r, row]), np.asarray(m.kv.val[r, row]))


def test_device_put_get_roundtrip():
    m, _ = mk()
    for i in range(8):
        assert m.create_paxos_instance(f"d{i}", [0, 1, 2])
    rows = np.array([m.rows.row(f"d{i}") for i in range(8)])
    got = {}

    def cb_for(tag):
        return lambda rid, resp: got.setdefault(tag, resp)

    m.propose_bulk_kv(rows, [OP_PUT] * 8, [7] * 8,
                      [100 + i for i in range(8)],
                      callbacks=[cb_for(f"p{i}") for i in range(8)])
    drain(m)
    assert m.bulk_stats()["done"] == 8
    # PUT echoes the value
    for i in range(8):
        assert got[f"p{i}"] == struct.pack("<i", 100 + i)
    # all replicas hold identical device state
    for i, row in enumerate(rows):
        for r in (1, 2):
            k0, v0 = kv_row(m, 0, row)
            kr, vr = kv_row(m, r, row)
            assert (k0 == kr).all() and (v0 == vr).all()
        assert 100 + i in kv_row(m, 0, row)[1]
    # GET returns current value; DEL removes
    m.propose_bulk_kv(rows[:1], [OP_GET], [7], [0],
                      callbacks=[cb_for("g")])
    m.propose_bulk_kv(rows[:1], [OP_DEL], [7], [0],
                      callbacks=[cb_for("dl")])
    drain(m)
    assert got["g"] == struct.pack("<i", 100)
    m.propose_bulk_kv(rows[:1], [OP_GET], [7], [0],
                      callbacks=[cb_for("g2")])
    drain(m)
    assert got["g2"] == struct.pack("<i", 0)
    assert m.stats["kv_misses"] == 0


def test_device_scalar_propose_miss_path():
    """Control-plane scalar proposes carry descriptors with no device
    upload: every replica misses identically and the host fallback applies
    the op consistently."""
    m, _ = mk()
    assert m.create_paxos_instance("d0", [0, 1, 2])
    row = m.rows.row("d0")
    got = []
    m.propose("d0", pack_desc(OP_PUT, 5, 42),
              callback=lambda rid, resp: got.append(resp))
    drain(m)
    assert got and got[0] == struct.pack("<i", 42)
    for r in range(3):
        keys, vals = kv_row(m, r, row)
        assert 42 in vals


def test_device_wal_recovery(tmp_path):
    from gigapaxos_tpu.wal.logger import PaxosLogger, recover

    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 16
    cfg.paxos.compact_outbox = True
    cfg.paxos.device_app = True
    cfg.paxos.bulk_capacity = 1 << 16
    wal = PaxosLogger(str(tmp_path), sync_every_ticks=1,
                      checkpoint_every_ticks=5, native=False)
    m = PaxosManager(cfg, 3, [None] * 3, wal=wal)
    for i in range(4):
        assert m.create_paxos_instance(f"d{i}", [0, 1, 2])
    rows = np.array([m.rows.row(f"d{i}") for i in range(4)])
    for wave in range(6):
        m.propose_bulk_kv(rows, [OP_PUT] * 4, [wave % 3 + 1] * 4,
                          [1000 * wave + i for i in range(4)])
        drain(m, ticks=4)
    assert m.bulk_stats()["done"] == 24
    live_keys = np.asarray(m.kv.key)
    live_vals = np.asarray(m.kv.val)
    wal.close()

    m2 = recover(cfg, 3, [None] * 3, str(tmp_path), native=False)
    assert (np.asarray(m2.kv.key) == live_keys).all()
    assert (np.asarray(m2.kv.val) == live_vals).all()
    # recovered manager continues on the device path
    got = []
    m2.propose_bulk_kv(rows[:1], [OP_GET], [2], [0],
                       callbacks=[lambda rid, resp: got.append(resp)])
    drain(m2, ticks=10)
    assert len(got) == 1 and len(got[0]) == 4


def test_device_crash_heal_checkpoint_transfer():
    m, _ = mk(G=64)
    for i in range(8):
        assert m.create_paxos_instance(f"d{i}", [0, 1, 2])
    rows = np.array([m.rows.row(f"d{i}") for i in range(8)])
    m.propose_bulk_kv(rows, [OP_PUT] * 8, [1] * 8, [11] * 8)
    drain(m, ticks=8)
    m.set_alive(2, False)
    for wave in range(12):
        m.propose_bulk_kv(rows, [OP_PUT] * 8, [2] * 8, [20 + wave] * 8)
        drain(m, ticks=3)
    m.set_alive(2, True)
    drain(m, ticks=40)
    assert m.stats["checkpoint_transfers"] > 0
    for row in rows:
        k0, v0 = kv_row(m, 0, row)
        k2, v2 = kv_row(m, 2, row)
        assert (k0 == k2).all() and (v0 == v2).all()


@pytest.mark.slow
def test_device_cluster_reconfiguration_e2e():
    """create -> batched device traffic -> migrate -> more traffic, all
    over real sockets with the binary client edge."""
    import threading

    from gigapaxos_tpu.testing.capacity import make_loopback_cluster

    cluster, client = make_loopback_cluster(
        n_groups=0, n_actives=3, max_groups=64,
    )
    # rebuild with device mode is intrusive; instead flip a fresh cluster
    client.close()
    cluster.close()

    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 64
    cfg.paxos.compact_outbox = True
    cfg.paxos.device_app = True
    cfg.paxos.pipeline_ticks = True
    cfg.paxos.bulk_capacity = 1 << 16
    for i in range(3):
        cfg.nodes.actives[f"AR{i}"] = ("127.0.0.1", 0)
    cfg.nodes.reconfigurators["RC0"] = ("127.0.0.1", 0)

    from gigapaxos_tpu.client import ReconfigurableAppClient
    from gigapaxos_tpu.node import InProcessCluster

    cluster = InProcessCluster(cfg, lambda: None)
    client = ReconfigurableAppClient(cfg.nodes)
    try:
        assert client.create("svc").get("ok")
        sender = client.batching(max_batch=32, flush_interval_s=0.005)
        ok, done = [], threading.Event()

        def submit(i, tries=20):
            def cb(p):
                if p.get("ok"):
                    ok.append(p)
                    if len(ok) >= 20:
                        done.set()
                elif tries > 0:
                    # a create response races the ARs' StartEpoch; clients
                    # retry not_active exactly like the scalar request()
                    time.sleep(0.1)
                    submit(i, tries - 1)

            sender.submit("svc", pack_desc(OP_PUT, i % 4 + 1, 500 + i), cb)

        import time

        for i in range(20):
            submit(i)
        assert done.wait(40), len(ok)
        # migrate the name, then keep going
        assert client.reconfigure("svc", ["AR0", "AR1", "AR2"]).get("ok")
        got = client.request("svc", pack_desc(OP_GET, 3, 0))
        assert len(got) == 4
        val = struct.unpack("<i", got)[0]
        assert val != 0, "migrated epoch lost device state"
        sender.close()
    finally:
        client.close()
        cluster.close()


def test_descriptor_miss_fails_request_explicitly():
    """A committed rid whose descriptor is unrecoverable (device-table
    eviction under a violated sizing invariant) must FAIL the request
    (cb(None), failed_requests counted) — never an empty success that
    silently loses the update (ADVICE r4)."""
    m, _ = mk(G=8)
    assert m.create_paxos_instance("d0", [0, 1, 2])
    row = m.rows.row("d0")
    store = m._ensure_bulk()
    rid = 424242
    pay = np.empty(1, object)
    pay[:] = [b""]  # device-app store requests carry no host payload
    store.admit_at(np.array([rid], np.int64), np.array([row], np.int32),
                   np.array([0], np.int32), np.array([False]), pay)
    got = {}
    m._bulk_cbs[rid] = lambda r_, resp: got.setdefault("resp", resp)
    sidx = rid & store.mask
    before = m.stats["failed_requests"]
    for r in range(3):
        m._store_exec_one(r, row, rid, 5 + r, sidx)
    # entry replica 0 saw the lost descriptor: explicit failure, not b""
    assert m.stats["failed_requests"] == before + 1
    for cb, rid_, resp in list(m._held_callbacks):
        cb(rid_, resp)
    assert got.get("resp", b"MISSING") is None


def test_compact_layout_single_source_of_truth():
    """Pack (device fused program) and unpack (host) agree through the one
    CompactLayout descriptor: buffer sizes match the descriptor exactly and
    a real commit's response surfaces through kv_extras at the documented
    offsets (VERDICT r4 weak #7)."""
    import jax.numpy as jnp

    from gigapaxos_tpu.models.device_kv import (OP_PUT, fused_compact,
                                                init_kv, register_requests)
    from gigapaxos_tpu.ops.tick import (CompactLayout, TickInbox,
                                        paxos_tick_compact, unpack_compact)
    from gigapaxos_tpu.paxos import state as st

    R, G, W, E, Lb = 3, 8, 8, 64, 64
    L = CompactLayout(R, G, E, Lb)
    assert L.o_taken == 3
    assert L.o_exec == 3 + R * G
    assert L.o_lag == L.o_exec + 4 * E
    assert L.o_resp == L.o_lag + L.LAG_COLS * Lb
    assert L.LAG_COLS == 6  # rep, row, donor, dexec, dstat, lexec
    assert L.o_miss == L.o_resp + E

    s = st.create_groups(st.init_state(R, G, W),
                         np.arange(G, dtype=np.int32), np.ones((G, R), bool))
    # plain compact buffer: exactly total_plain
    req = np.zeros((R, 2, G), np.int32)
    req[0, 0, 0] = 77
    inbox = TickInbox(jnp.asarray(req), jnp.zeros((R, 2, G), bool),
                      jnp.ones(R, bool))
    s2, packed = paxos_tick_compact(s, inbox, -1, E, Lb)
    assert np.asarray(packed).shape[0] == L.total_plain

    # device-app buffer: total_device, and the response round-trips
    kv = init_kv(R, G, slots=8, table=1 << 16)
    kv = register_requests(kv, jnp.asarray([77], jnp.int32),
                           jnp.asarray([OP_PUT], jnp.int32),
                           jnp.asarray([3], jnp.int32),
                           jnp.asarray([1234], jnp.int32))
    state = st.create_groups(st.init_state(R, G, W),
                             np.arange(G, dtype=np.int32),
                             np.ones((G, R), bool))
    zeros = np.zeros(4, np.int32)
    flat = None
    for _ in range(4):  # propose -> accept -> decide -> execute
        state, kv, packed = fused_compact(
            state, kv, inbox, zeros, zeros, zeros, zeros, -1, E, Lb)
        inbox = TickInbox(jnp.zeros((R, 2, G), jnp.int32),
                          jnp.zeros((R, 2, G), bool), jnp.ones(R, bool))
        flat = np.asarray(packed)
        co = unpack_compact(flat, R, G, E, Lb)
        if co.n_exec:
            break
    assert flat.shape[0] == L.total_device
    co = unpack_compact(flat, R, G, E, Lb)
    assert co.n_exec >= 1
    e_resp, e_miss = L.kv_extras(flat)
    execd = co.e_rid[:co.n_exec] == 77
    assert execd.any()
    # PUT echoes the stored value through the layout's response column
    assert (e_resp[:co.n_exec][execd] == 1234).all()
    assert (e_miss[:co.n_exec][execd] == 0).all()


def test_device_row_lifecycle_no_leak_and_pause_preserves():
    """Mode A twin of the Mode B lifecycle test: removed rows scrub their
    device KV data; paused groups carry it in the spilled record."""
    m, _ = mk(G=4)
    assert m.create_paxos_instance("old", [0, 1, 2])
    got = {}
    m.propose_bulk_kv(np.array([m.rows.row("old")]), [OP_PUT], [5], [77],
                      callbacks=[lambda rid, r: got.setdefault("p", r)])
    drain(m)
    assert got["p"] == struct.pack("<i", 77)
    assert m.remove_paxos_instance("old")
    assert m.create_paxos_instance("fresh", [0, 1, 2])
    m.propose_bulk_kv(np.array([m.rows.row("fresh")]), [OP_GET], [5], [0],
                      callbacks=[lambda rid, r: got.setdefault("g", r)])
    drain(m)
    assert got["g"] == struct.pack("<i", 0)  # no leak from "old"

    m.propose_bulk_kv(np.array([m.rows.row("fresh")]), [OP_PUT], [2], [42],
                      callbacks=[lambda rid, r: got.setdefault("p2", r)])
    drain(m)
    paused = m._pause_eligible(limit=4, ignore_idle=True)
    assert "fresh" in paused
    # transparent unpause on propose; state preserved through the spill
    m.propose_bulk_kv(np.array([m._resident_row("fresh")]), [OP_GET], [2],
                      [0], callbacks=[lambda rid, r: got.setdefault("g2", r)])
    drain(m)
    assert got["g2"] == struct.pack("<i", 42)

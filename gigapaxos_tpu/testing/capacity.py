"""Capacity-probe harness: the TESTPaxos analog.

Reproduces the reference's benchmark methodology end-to-end over real
sockets (``testing/TESTPaxosMain.java:43`` spawns in-JVM nodes,
``TESTPaxosClient.java:59`` drives load, probe parameters
``TESTPaxosConfig.java:190-229``): start at an initial load, multiply by
``PROBE_LOAD_INCREASE_FACTOR`` (1.1) each run, and stop when the response
rate drops below ``0.9 x load`` or average latency exceeds 1 s; the last
passing load is the capacity.

The in-process cluster mirrors ``tests/loopback_1_group`` /
``loopback_10_groups`` (3 actives on loopback, NoopApp workload); this
module is also the host-path complement of ``bench.py``, which measures the
raw device engine without the socket edge.

CLI: ``python -m gigapaxos_tpu.testing.capacity [--groups N] [--load L]``.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..client import ReconfigurableAppClient
from ..config import GigapaxosTpuConfig
from ..models.replicable import NoopApp
from ..node import InProcessCluster

#: probe parameters (TESTPaxosConfig.java:190-229)
PROBE_LOAD_INCREASE_FACTOR = 1.1
PROBE_RESPONSE_THRESHOLD = 0.9
PROBE_MAX_LATENCY_S = 1.0
PROBE_MAX_RUNS = 50


@dataclass
class ProbeResult:
    load: float  # offered req/s
    sent: int
    responded: int  # total, including post-window stragglers
    errors: int
    duration_s: float
    responded_in_window: int = 0
    latencies_s: List[float] = field(default_factory=list)

    @property
    def response_rate(self) -> float:
        """Sustained rate: only responses that arrived WITHIN the run
        window count — a saturated system drains its backlog afterwards,
        and counting that would overstate capacity by up to 2x."""
        return (self.responded_in_window / self.duration_s
                if self.duration_s else 0.0)

    @property
    def avg_latency_s(self) -> float:
        return (
            sum(self.latencies_s) / len(self.latencies_s)
            if self.latencies_s else 0.0
        )

    def p50_latency_s(self) -> float:
        if not self.latencies_s:
            return 0.0
        xs = sorted(self.latencies_s)
        return xs[len(xs) // 2]

    def passed(self, load: float) -> bool:
        return (
            self.response_rate >= PROBE_RESPONSE_THRESHOLD * load
            and self.avg_latency_s <= PROBE_MAX_LATENCY_S
        )


def make_loopback_cluster(
    n_groups: int = 1,
    n_actives: int = 3,
    n_rc: int = 1,
    app_factory=NoopApp,
    max_groups: Optional[int] = None,
):
    """The ``tests/loopback_*`` fixture: one process, real sockets,
    ``n_groups`` pre-created names g0..g{n-1} on 3 replicas."""
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = max_groups or max(64, n_groups)
    cfg.paxos.pipeline_ticks = True  # stage-overlap on the probe clusters
    cfg.paxos.compact_outbox = True  # vectorized host loop (batch edge)
    cfg.paxos.min_tick_interval_s = 0.004  # coalesce: amortize tick cost
    for i in range(n_actives):
        cfg.nodes.actives[f"AR{i}"] = ("127.0.0.1", 0)
    for i in range(n_rc):
        cfg.nodes.reconfigurators[f"RC{i}"] = ("127.0.0.1", 0)
    from ..reconfiguration.demand import DemandProfile

    cluster = InProcessCluster(
        cfg, app_factory,
        # sparse demand reports: at probe rates the reference's
        # report-per-request cadence floods the RC plane (3 frames/req)
        demand_profile_factory=lambda name: DemandProfile(
            name, min_requests_before_report=64
        ),
    )
    client = ReconfigurableAppClient(cfg.nodes)
    for g in range(n_groups):
        resp = client.create(f"g{g}")
        if not resp.get("ok"):
            raise RuntimeError(f"create g{g} failed: {resp}")
    return cluster, client


class CapacityProbe:
    """Drives open-loop load through the async client and walks the probe
    ladder (TESTPaxosClient's runTestWorkload + capacity loop)."""

    def __init__(self, client: ReconfigurableAppClient, names: List[str],
                 payload: bytes = b"noop", batch: bool = False):
        self.client = client
        self.names = names
        self.payload = payload
        # client-edge coalescing (RequestBatcher analog): many requests per
        # frame instead of one — the round-3 capacity knee was frame cost
        self.sender = client.batching() if batch else None
        # pre-resolve every name so measurement excludes actives lookups
        for n in names:
            self.client.request_actives(n)

    #: latency is sampled 1-in-N so the probe harness itself doesn't tax
    #: the measured system (the shared-core analog of the reference's
    #: sampled response timing, TESTPaxosClient.java:59)
    LAT_SAMPLE = 8

    def run_once(self, load: float, duration_s: float) -> ProbeResult:
        res = ProbeResult(load=load, sent=0, responded=0, errors=0,
                          duration_s=duration_s)
        # deque.append is atomic under the GIL: response accounting needs
        # no lock on the hot path
        ok_in = collections.deque()
        ok_late = collections.deque()
        errs = collections.deque()
        lats = collections.deque()
        t_end = time.monotonic() + duration_s
        interval = 1.0 / load
        i = 0
        next_t = time.monotonic()

        def cb_fast(p):
            if p.get("ok"):
                (ok_in if time.monotonic() <= t_end else ok_late).append(1)
            else:
                errs.append(1)

        while time.monotonic() < t_end:
            now = time.monotonic()
            if now < next_t:
                time.sleep(min(next_t - now, 0.002))
                continue
            next_t += interval
            name = self.names[i % len(self.names)]
            i += 1
            if i % self.LAT_SAMPLE == 0:
                t0 = time.monotonic()

                def cb(p, t0=t0):
                    if p.get("ok"):
                        now2 = time.monotonic()
                        (ok_in if now2 <= t_end else ok_late).append(1)
                        lats.append(now2 - t0)
                    else:
                        errs.append(1)
            else:
                cb = cb_fast
            try:
                if self.sender is not None:
                    self.sender.submit(name, self.payload, cb)
                else:
                    self.client.send_request(name, self.payload, cb)
                res.sent += 1
            except Exception:
                res.errors += 1
        # drain window: late responses still count against offered load
        deadline = time.monotonic() + min(2.0, PROBE_MAX_LATENCY_S * 2)
        while time.monotonic() < deadline:
            if len(ok_in) + len(ok_late) + len(errs) + res.errors >= res.sent:
                break
            time.sleep(0.01)
        res.responded_in_window = len(ok_in)
        res.responded = len(ok_in) + len(ok_late)
        res.errors += len(errs)
        res.latencies_s = list(lats)
        return res

    def probe(self, init_load: float, duration_s: float = 2.0,
              max_runs: int = PROBE_MAX_RUNS) -> List[ProbeResult]:
        """The capacity ladder; returns all runs (last passing = capacity)."""
        runs: List[ProbeResult] = []
        load = init_load
        for _ in range(max_runs):
            r = self.run_once(load, duration_s)
            runs.append(r)
            if not r.passed(load):
                break
            load *= PROBE_LOAD_INCREASE_FACTOR
        return runs

    @staticmethod
    def capacity(runs: List[ProbeResult]) -> float:
        passing = [r.load for r in runs if r.passed(r.load)]
        return max(passing) if passing else 0.0


def main() -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=10)
    ap.add_argument("--load", type=float, default=1000.0)
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--runs", type=int, default=10)
    ap.add_argument("--batch", action="store_true",
                    help="coalesce requests into batched frames")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu — the ambient "
                         "axon backend hangs the whole probe when the TPU "
                         "tunnel is down)")
    args = ap.parse_args()

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    cluster, client = make_loopback_cluster(n_groups=args.groups)
    try:
        probe = CapacityProbe(client, [f"g{i}" for i in range(args.groups)],
                              batch=args.batch)
        runs = probe.probe(args.load, args.duration, args.runs)
        for r in runs:
            print(json.dumps({
                "load": round(r.load, 1),
                "response_rate": round(r.response_rate, 1),
                "avg_latency_ms": round(r.avg_latency_s * 1e3, 2),
                "p50_latency_ms": round(r.p50_latency_s() * 1e3, 2),
                "passed": r.passed(r.load),
            }))
        print(json.dumps({
            "metric": f"loopback_capacity_req_per_s_{args.groups}_groups"
                      + ("_batched" if args.batch else ""),
            "value": round(CapacityProbe.capacity(runs), 1),
            "unit": "req/s",
        }))
    finally:
        client.close()
        cluster.close()


if __name__ == "__main__":
    main()

"""Transport security: CLEAR / SERVER_AUTH / MUTUAL_AUTH.

Analog of the reference's SSL stack (``nio/SSLDataProcessingWorker.java:59``
``SSL_MODES {CLEAR, SERVER_AUTH, MUTUAL_AUTH}``, selected per node role at
``reconfiguration/ReconfigurableNode.java:298``): the same three modes wrap
the framed TCP transport (``net/transport.py``) with stdlib ``ssl``.

* CLEAR        — plaintext (intra-datacenter ICI-adjacent links);
* SERVER_AUTH  — servers present certificates, clients verify against the
  deployment CA; client edge privacy without client certs;
* MUTUAL_AUTH  — additionally, clients must present certificates the CA
  signed (the reference requires this for admin/create operations and
  server-to-server links).

Certificates are deployment artifacts (the reference ships keystore files
configured via ``javax.net.ssl.*`` properties); tests generate a throwaway
CA with :mod:`gigapaxos_tpu.testing.certs`.  Node ids are not hostnames, so
hostname checking is off — peer identity is the CA-signed certificate plus
the node-id hello, exactly the reference's keystore trust model.
"""

from __future__ import annotations

import enum
import ssl
from dataclasses import dataclass
from typing import Optional


class SSLMode(enum.Enum):
    CLEAR = "clear"
    SERVER_AUTH = "server_auth"
    MUTUAL_AUTH = "mutual_auth"


@dataclass
class TransportSecurity:
    """Everything one endpoint needs to speak TLS in a deployment.

    ``certfile``/``keyfile`` identify THIS endpoint (server role always;
    client role under MUTUAL_AUTH); ``cafile`` is the deployment trust
    root every certificate must chain to.
    """

    mode: SSLMode = SSLMode.CLEAR
    certfile: Optional[str] = None
    keyfile: Optional[str] = None
    cafile: Optional[str] = None

    @classmethod
    def from_config(cls, ssl_cfg) -> Optional["TransportSecurity"]:
        """Build from the config registry's ``ssl`` section (None = CLEAR,
        no wrapping at all)."""
        if ssl_cfg is None:
            return None
        mode = SSLMode(ssl_cfg.mode)
        if mode is SSLMode.CLEAR:
            return None
        return cls(
            mode=mode,
            certfile=ssl_cfg.certfile or None,
            keyfile=ssl_cfg.keyfile or None,
            cafile=ssl_cfg.cafile or None,
        )

    # ------------------------------------------------------------- contexts
    def server_context(self) -> Optional[ssl.SSLContext]:
        """Context for accepted connections (both modes present a cert;
        MUTUAL_AUTH additionally demands and verifies the client's).

        An endpoint with no certificate of its own is client-only: it can
        dial TLS peers but cannot accept TLS connections (peers dialing it
        back fail their handshake and drop) — the shape of a certless
        client under MUTUAL_AUTH, which can reach nobody anyway."""
        if self.mode is SSLMode.CLEAR or not self.certfile:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.certfile, self.keyfile)
        if self.mode is SSLMode.MUTUAL_AUTH:
            if not self.cafile:
                raise ValueError("mutual_auth requires cafile")
            ctx.verify_mode = ssl.CERT_REQUIRED
            ctx.load_verify_locations(self.cafile)
        return ctx

    def client_context(self) -> Optional[ssl.SSLContext]:
        """Context for outbound connections: always verifies the server
        against the CA; presents our certificate when we have one (required
        by MUTUAL_AUTH servers)."""
        if self.mode is SSLMode.CLEAR:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if not self.cafile:
            raise ValueError(f"{self.mode.value} requires cafile")
        ctx.check_hostname = False  # node ids, not hostnames (see module doc)
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(self.cafile)
        if self.certfile:
            ctx.load_cert_chain(self.certfile, self.keyfile)
        return ctx

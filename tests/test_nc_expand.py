"""NC-driven Mode B universe expansion: client.add_active -> committed NC
record -> broadcast -> every active's data plane grows in lockstep -> the
new server boots with the committed slot order -> names migrate onto it.

The newcomer's id ("AR1") deliberately sorts BETWEEN the incumbents
("AR0", "AR2", "AR4"): sorted-topology boot order would give it the wrong
slot index, so this exercises the committed-universe-order mechanism
(NC record ``universe`` field -> add_active response -> ``nodes.universe``
boot key).
"""

import time

import pytest

from gigapaxos_tpu.client import ReconfigurableAppClient
from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.server import ModeBServer

ACTIVES = ["AR0", "AR2", "AR4"]
RCS = ["RC0", "RC1", "RC2"]


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_cfg():
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 32
    cfg.fd.ping_interval_s = 0.05
    cfg.fd.timeout_s = 1.5
    for nid in ACTIVES:
        cfg.nodes.actives[nid] = ("127.0.0.1", _free_port())
    for nid in RCS:
        cfg.nodes.reconfigurators[nid] = ("127.0.0.1", _free_port())
    return cfg


def test_nc_add_active_expands_universes_and_migrates():
    cfg = make_cfg()
    srv = {}
    client = None
    newcomer = None
    try:
        for nid in ACTIVES + RCS:
            srv[nid] = ModeBServer(nid, cfg, start_fd=True)
        for s in srv.values():
            assert s.wait_ready(300)
        client = ReconfigurableAppClient(cfg.nodes)

        assert client.create("svc", timeout=90)["ok"]
        assert client.request("svc", b"PUT city paris", timeout=60) == b"OK"

        # ---- add AR1 (sorts between AR0 and AR2) ----
        new_port = _free_port()
        resp = client.add_active("AR1", "127.0.0.1", new_port, timeout=60)
        assert resp["ok"], resp
        universe = resp.get("universe")
        assert universe == ACTIVES + ["AR1"], universe

        # every incumbent's data plane grows to R=4 with AR1 LAST
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(srv[a].node.R == 4 and srv[a].node.members[-1] == "AR1"
                   for a in ACTIVES):
                break
            time.sleep(0.2)
        for a in ACTIVES:
            assert srv[a].node.members == universe, (a, srv[a].node.members)

        # ---- boot the newcomer with the COMMITTED slot order ----
        import copy

        cfg2 = copy.deepcopy(cfg)
        cfg2.nodes.actives["AR1"] = ("127.0.0.1", new_port)
        cfg2.nodes.universe = list(universe)
        newcomer = ModeBServer("AR1", cfg2, start_fd=True)
        assert newcomer.wait_ready(300)
        assert newcomer.node.members == universe

        # ---- migrate the name onto the newcomer and use it ----
        new_set = ["AR1", "AR2", "AR4"]
        r = client.reconfigure("svc", new_set, timeout=90)
        assert r["ok"], r
        deadline = time.monotonic() + 120
        got = set()
        while time.monotonic() < deadline:
            got = set(client.request_actives("svc", force=True))
            if got == set(new_set):
                break
            time.sleep(0.3)
        assert got == set(new_set)
        assert client.request("svc", b"GET city", timeout=60) == b"paris"
        assert client.request("svc", b"PUT n 1", timeout=60) == b"OK"
        # the newcomer's own app copy converges (it is a real member)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            db = getattr(newcomer.app, "db", {})
            ok = any(t.get("city") == "paris" for t in db.values())
            if ok:
                break
            time.sleep(0.2)
        assert any(t.get("city") == "paris"
                   for t in getattr(newcomer.app, "db", {}).values())

        # ---- remove an incumbent: the pool shrinks, names drain off it,
        # but its replica SLOT is retained (universe is append-only) ----
        rm = client.remove_active("AR0", timeout=60)
        assert rm["ok"], rm
        deadline = time.monotonic() + 120
        got = set()
        while time.monotonic() < deadline:
            got = set(client.request_actives("svc", force=True))
            if "AR0" not in got and len(got) == 3:
                break
            time.sleep(0.3)
        assert "AR0" not in got and len(got) == 3, got
        assert client.request("svc", b"GET city", timeout=60) == b"paris"
        # slot order unchanged everywhere: removal never recycles slots
        for a in ("AR2", "AR4"):
            assert srv[a].node.members == universe, srv[a].node.members
    finally:
        if client is not None:
            client.close()
        if newcomer is not None:
            newcomer.close()
        for s in srv.values():
            s.close()

"""Chunked bulk transfer (LargeCheckpointer analog) tests."""

import os
import threading
import time

from gigapaxos_tpu.net.bulk import BulkTransfer
from gigapaxos_tpu.net.messenger import Messenger, NodeMap


def make_pair():
    nm = NodeMap()
    a = Messenger("A", ("127.0.0.1", 0), nm)
    b = Messenger("B", ("127.0.0.1", 0), nm)
    nm.add("A", "127.0.0.1", a.port)
    nm.add("B", "127.0.0.1", b.port)
    return a, b


def test_roundtrip_large_blob():
    a, b = make_pair()
    try:
        got = {}
        ev = threading.Event()
        BulkTransfer(b, on_complete=lambda s, k, d: (got.update({k: (s, d)}), ev.set()))
        ta = BulkTransfer(a)
        data = os.urandom(5 * 1024 * 1024 + 137)  # not chunk-aligned
        n = ta.send("B", "efs:3:alice", data)
        assert n == 6
        assert ev.wait(30)
        sender, rx = got["efs:3:alice"]
        assert sender == "A" and rx == data
    finally:
        a.close()
        b.close()


def test_interleaved_keys_and_prefix_routing():
    a, b = make_pair()
    try:
        got = {}
        lock = threading.Lock()
        done = threading.Event()
        rx = BulkTransfer(b)

        def h(sender, key, d):
            with lock:
                got[key] = d
                if len(got) == 2:
                    done.set()

        rx.register_prefix("efs:", h)
        ta = BulkTransfer(a, chunk_size=64 * 1024)
        d1, d2 = os.urandom(300_000), os.urandom(200_000)
        # interleave chunks of two transfers by sending alternately
        ta.send("B", "efs:1:x", d1)
        ta.send("B", "efs:2:y", d2)
        assert done.wait(30)
        assert got["efs:1:x"] == d1 and got["efs:2:y"] == d2
        assert rx.pending() == 0
    finally:
        a.close()
        b.close()


def test_big_final_state_over_bulk():
    """An epoch-final checkpoint above the inline limit must travel the
    bulk channel and still complete the WaitEpochFinalState task."""
    from gigapaxos_tpu.config import GigapaxosTpuConfig
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.client import ReconfigurableAppClient
    from gigapaxos_tpu.node import InProcessCluster

    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 64
    for i in range(5):
        cfg.nodes.actives[f"AR{i}"] = ("127.0.0.1", 0)
    cfg.nodes.reconfigurators["RC0"] = ("127.0.0.1", 0)
    cl = InProcessCluster(cfg, KVApp)
    # force the remote-fetch path: tiny inline limit so ANY state is "big"
    for ar in cl.actives.values():
        ar.inline_state_limit = 64
    c = ReconfigurableAppClient(cfg.nodes)
    try:
        assert c.create("fat")["ok"]
        big = "x" * 500_000
        assert c.request("fat", f"PUT blob {big}".encode()) == b"OK"
        old = set(c.request_actives("fat"))
        # stop epoch 0 so its final state becomes fetchable
        stopped = threading.Event()
        cl.coordinator.stop_replica_group("fat", 0, lambda ok: stopped.set())
        assert stopped.wait(30)
        # drive the AR-to-AR fetch protocol explicitly: AR_x handles a
        # StartEpoch whose previous actives answer over the bulk channel
        # (the shared coordinator's local fast path is disabled by stubbing
        # get_final_state for the fetching side only)
        fetcher = cl.actives[sorted(set(cfg.nodes.active_ids()) - old)[0]]
        real_gfs = fetcher.coord.get_final_state
        calls = {"n": 0}

        def gfs_once_none(name, epoch):
            calls["n"] += 1
            return None if calls["n"] == 1 else real_gfs(name, epoch)

        fetcher.coord = type(fetcher.coord).__new__(type(fetcher.coord))
        fetcher.coord.__dict__.update(cl.coordinator.__dict__)
        fetcher.coord.get_final_state = gfs_once_none
        start = {
            "type": "start_epoch", "name": "fat", "epoch": 1,
            "actives": sorted(old), "initiator": "RC0",
            "prev_epoch": 0, "prev_actives": sorted(old),
            "initial_state": None,
        }
        fetcher._on_start_epoch("RC0", start)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if cl.coordinator.current_epoch("fat") == 1:
                break
            time.sleep(0.1)
        assert cl.coordinator.current_epoch("fat") == 1
        assert calls["n"] >= 1  # the remote path actually ran
        # epoch 1 carries the big state fetched over bulk
        assert c.request("fat", b"GET blob") == big.encode()
    finally:
        c.close()
        cl.close()

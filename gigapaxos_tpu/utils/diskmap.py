"""Demand-paged map: a dict whose cold entries live on disk.

Analog of ``utils/DiskMap.java:97`` (used by the reference's logger for the
message-log index and by pause state): a memory map with a bounded hot set;
entries evicted from RAM are written to disk and transparently paged back
on access.  The dense framework uses it for the pause/spill store — a node
can hold orders of magnitude more *paused* groups than device rows or host
RAM would allow (``PaxosManager.java:2284-2365`` pause analog).

Layout: one record file per key under ``dir_path`` (typed binary codec,
wal/records.py — nothing executable on any replay path; keys hash to
filenames; collisions resolved by storing the key alongside the value).
Thread-safe via one lock — callers are host control-plane paths, not the
device hot loop.
"""

from __future__ import annotations

import collections
import hashlib
import os

import threading
from typing import Any, Iterator, Optional

from ..wal import records


class DiskMap:
    """dict-like with an LRU RAM cache of ``cache_cap`` entries; the rest
    pages to ``dir_path``.  ``None`` dir keeps everything in RAM (the map
    degrades to a plain bounded-cache-less dict)."""

    def __init__(self, dir_path: Optional[str] = None, cache_cap: int = 1024):
        self.dir = dir_path
        self.cache_cap = max(cache_cap, 1)
        if dir_path is not None:
            os.makedirs(dir_path, exist_ok=True)
        self._hot: "collections.OrderedDict[str, Any]" = collections.OrderedDict()
        #: keys currently resident on disk (superset check avoids stat calls)
        self._cold: set = set()
        self._lock = threading.Lock()
        if dir_path is not None:
            for fn in os.listdir(dir_path):
                if fn.endswith(".rec"):
                    try:
                        with open(os.path.join(dir_path, fn), "rb") as f:
                            key, _ = records.loads(f.read())
                        self._cold.add(key)
                    except Exception:
                        continue  # torn file: treated as absent

    # ------------------------------------------------------------- disk I/O
    def _path(self, key: str) -> str:
        h = hashlib.blake2b(key.encode(), digest_size=12).hexdigest()
        return os.path.join(self.dir, f"{h}.rec")

    def _page_out(self, key: str, value: Any) -> None:
        path = self._path(key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(records.dumps((key, value)))
        os.replace(tmp, path)
        self._cold.add(key)

    def _page_in(self, key: str) -> Any:
        with open(self._path(key), "rb") as f:
            stored_key, value = records.loads(f.read())
        if stored_key != key:
            raise KeyError(key)  # hash collision with a different key
        return value

    def _evict_if_needed(self) -> None:
        while len(self._hot) > self.cache_cap and self.dir is not None:
            old_key, old_val = self._hot.popitem(last=False)
            self._page_out(old_key, old_val)

    # ----------------------------------------------------------- dict-alike
    def __setitem__(self, key: str, value: Any) -> None:
        with self._lock:
            self._hot[key] = value
            self._hot.move_to_end(key)
            if self.dir is not None and key in self._cold:
                # stale disk copy must not resurrect on a later page-in
                try:
                    os.unlink(self._path(key))
                except OSError:
                    pass
                self._cold.discard(key)
            self._evict_if_needed()

    def __getitem__(self, key: str) -> Any:
        with self._lock:
            if key in self._hot:
                self._hot.move_to_end(key)
                return self._hot[key]
            if key in self._cold:
                value = self._page_in(key)
                self._cold.discard(key)
                try:
                    os.unlink(self._path(key))
                except OSError:
                    pass
                self._hot[key] = value
                self._evict_if_needed()
                return value
        raise KeyError(key)

    def get(self, key: str, default: Any = None) -> Any:
        try:
            return self[key]
        except KeyError:
            return default

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._hot or key in self._cold

    def __delitem__(self, key: str) -> None:
        with self._lock:
            found = False
            if key in self._hot:
                del self._hot[key]
                found = True
            if key in self._cold:
                try:
                    os.unlink(self._path(key))
                except OSError:
                    pass
                self._cold.discard(key)
                found = True
            if not found:
                raise KeyError(key)

    def pop(self, key: str, *default: Any) -> Any:
        try:
            value = self[key]
        except KeyError:
            if default:
                return default[0]
            raise
        del self[key]
        return value

    def __len__(self) -> int:
        with self._lock:
            return len(self._hot) + len(self._cold)

    def __iter__(self) -> Iterator[str]:
        with self._lock:
            return iter(list(self._hot) + list(self._cold))

    def keys(self):
        return iter(self)

    def peek(self, key: str) -> Any:
        """Non-destructive read: a cold entry stays on disk (no unlink, no
        LRU churn) — the snapshot path iterates the whole map and must not
        rewrite the entire cold tier doing so."""
        with self._lock:
            if key in self._hot:
                return self._hot[key]
            if key in self._cold:
                return self._page_in(key)
        raise KeyError(key)

    def clear(self) -> None:
        """Drop everything, disk copies included (recovery loads the
        snapshot's paused set as the sole authority)."""
        with self._lock:
            self._hot.clear()
            if self.dir is not None:
                for key in list(self._cold):
                    try:
                        os.unlink(self._path(key))
                    except OSError:
                        pass
            self._cold.clear()

    def update(self, other) -> None:
        for k in other:
            self[k] = other[k]

    def hot_count(self) -> int:
        with self._lock:
            return len(self._hot)

    def cold_count(self) -> int:
        with self._lock:
            return len(self._cold)

"""Group-health plane overhead gate: fold on vs off (ISSUE 18).

The health fold claims the needle-in-a-million detector is (near) free:
per-group stall/churn/heat columns update inside the already-fused tick,
the reductions (log2 histograms, scalar gauges, ``lax.top_k``) are O(G)
device work on arrays the tick already touched, and the host adopts a
``6 + 64 + 6K`` float column per tick.  This bench prices exactly that
delta through the REAL stack (``stack_bench.py``: admission -> device
tick -> WAL fsync -> compacted outbox -> execution -> completion).

Three interleaved arms per leg, fresh subprocess each (the metrics
registry switch is read at import):

* **off**  — ``group_health=false`` (the baseline every prior PR priced);
* **on**   — the full fold + top-K + gauge adoption;
* **on_nometrics** — fold on with ``GPTPU_METRICS=0``: isolates the
  device fold from the host-side gauge plumbing.

Legs: decisions/s at the capacity knee with the WAL on, and wall ms/tick
at ``--groups-big`` (default 1M — the paper's headline scale, where a
per-tick device cost is most visible).  Gate: on-vs-off overhead < 2 %.

Writes ``benchmarks/results_health_pr18.json`` and prints one JSON line
(``run_artifacts.py`` consumes the line).

Usage: python benchmarks/health_bench.py [--groups-knee 131072]
       [--groups-big 1048576] [--repeat 2] [--platform cpu] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

ARMS = ("off", "on", "on_nometrics")


def run_stack(groups: int, ticks: int, warmup: int, wal: bool, arm: str,
              platform: str) -> dict:
    env = dict(os.environ)
    env["GPTPU_METRICS"] = "0" if arm == "on_nometrics" else "1"
    cmd = [sys.executable, os.path.join(HERE, "stack_bench.py"),
           "--groups", str(groups), "--ticks", str(ticks),
           "--warmup", str(warmup), "--platform", platform,
           "--lat-samples", "0"]
    if arm != "off":
        cmd.append("--health")
    if wal:
        cmd.append("--wal")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=ROOT,
                         env=env, timeout=3600)
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    raise RuntimeError(
        f"stack_bench produced no JSON (arm={arm}); "
        f"stderr tail: {out.stderr.strip()[-400:]!r}")


def ab_leg(groups: int, ticks: int, warmup: int, wal: bool, repeat: int,
           platform: str) -> dict:
    """Interleaved three-arm runs; best-of-N per arm (shared-box
    interference only ever slows a run down, so max estimates the
    uncontended number for every arm identically)."""
    runs = {arm: [] for arm in ARMS}
    for _ in range(repeat):
        for arm in ARMS:
            r = run_stack(groups, ticks, warmup, wal, arm, platform)
            runs[arm].append({
                "decisions_per_s": r["value"],
                "tick_ms": round(1000.0 / r["detail"]["ticks_per_s"], 2),
            })
    best = {arm: max(rs, key=lambda x: x["decisions_per_s"])
            for arm, rs in runs.items()}
    off = best["off"]["decisions_per_s"]

    def pct(arm: str) -> float:
        on = best[arm]["decisions_per_s"]
        return (off - on) / off * 100.0 if off else 0.0

    raw = pct("on")
    return {
        "groups": groups,
        "wal": wal,
        "ticks": ticks,
        **best,
        # negative raw delta = health arm measured FASTER (pure noise);
        # the gate compares the clamped value, raw recorded for honesty
        "overhead_pct_raw": round(raw, 3),
        "overhead_pct": round(max(raw, 0.0), 3),
        "overhead_pct_nometrics_raw": round(pct("on_nometrics"), 3),
        "all_runs": runs,
    }


def tpu_attempt() -> dict:
    """Record whether a TPU was reachable for this artifact (the standing
    tunnel protocol): every refresh appends one honest line to
    ``benchmarks/tpu_attempts.jsonl``."""
    rec = {"unix": int(time.time()), "bench": "health_bench",
           "requested": "tpu", "outcome": None}
    try:
        import jax

        devs = jax.devices()
        kinds = sorted({d.platform for d in devs})
        if any(k == "tpu" for k in kinds):
            rec["outcome"] = f"tpu available: {len(devs)} devices"
        else:
            rec["outcome"] = (f"no tpu in jax.devices() "
                              f"(platforms: {kinds}); ran on cpu")
    except Exception as e:  # pragma: no cover - depends on local runtime
        rec["outcome"] = f"jax device probe failed: {type(e).__name__}: {e}"
    with open(os.path.join(HERE, "tpu_attempts.jsonl"), "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups-knee", type=int, default=1 << 17)
    ap.add_argument("--groups-big", type=int, default=1 << 20)
    ap.add_argument("--ticks", type=int, default=12)
    ap.add_argument("--warmup", type=int, default=4)
    ap.add_argument("--big-ticks", type=int, default=5)
    ap.add_argument("--big-warmup", type=int, default=2)
    ap.add_argument("--repeat", type=int, default=4)
    ap.add_argument("--big-repeat", type=int, default=2,
                    help="best-of-N for the large-G leg (single-run legs "
                         "are hostage to co-tenant noise at 20s/tick)")
    ap.add_argument("--gate-pct", type=float, default=2.0)
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--skip-big", action="store_true",
                    help="knee leg only (quick refresh)")
    ap.add_argument("--out", default=os.path.join(
        HERE, "results_health_pr18.json"))
    args = ap.parse_args()

    attempt = tpu_attempt()

    legs = {}
    legs["capacity_knee_wal"] = ab_leg(
        args.groups_knee, args.ticks, args.warmup, wal=True,
        repeat=args.repeat, platform=args.platform)
    if not args.skip_big:
        legs["large_g_tick"] = ab_leg(
            args.groups_big, args.big_ticks, args.big_warmup, wal=False,
            repeat=args.big_repeat, platform=args.platform)

    ok = all(l["overhead_pct"] < args.gate_pct for l in legs.values())
    doc = {
        "generated_unix": int(time.time()),
        "gate_pct": args.gate_pct,
        "pass": ok,
        "method": "interleaved group_health off/on/on+GPTPU_METRICS=0 "
                  "stack_bench subprocesses, best-of-N per arm",
        "environment": {"cpu_count": os.cpu_count(),
                        "python": sys.version.split()[0],
                        "platform": args.platform,
                        "tpu_attempt": attempt["outcome"]},
        "legs": legs,
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    knee = legs["capacity_knee_wal"]
    print(json.dumps({
        "metric": "group_health_overhead_pct_at_capacity_knee",
        "value": knee["overhead_pct"],
        "unit": "% decisions/s lost vs group_health=false (clamped at 0)",
        "pass_lt_pct": args.gate_pct,
        "pass": ok,
        "knee_decisions_per_s": {a: knee[a]["decisions_per_s"]
                                 for a in ARMS},
        "large_g_tick_ms": ({a: legs["large_g_tick"][a]["tick_ms"]
                             for a in ARMS}
                            if "large_g_tick" in legs else None),
        "written": args.out,
    }))


if __name__ == "__main__":
    main()

"""ChainManager: host control loop for the chain data plane.

Peer of :class:`~gigapaxos_tpu.paxos.manager.PaxosManager` for chains
(``chainreplication/ChainManager.java:71-99``), deliberately exposing the
same public surface (``propose``/``propose_stop``/``create_paxos_instance``/
``remove_paxos_instance``/``group_members``/``is_stopped``/``tick``/
``pending_count``/``apps``/``alive``/``rows``/``lock``) so the
replica-coordination SPI binding and the TickDriver work unchanged — the
reference swaps coordinators the same way via ``REPLICA_COORDINATOR_CLASS``
(``ReconfigurableNode.java:203-218``).

Differences from the paxos manager, mirroring protocol semantics:

* requests are ordered once by the head — no re-proposal, no duplicate
  commits, so there is no execution-side dedup machinery;
* the client response fires when the *tail* applies the request (commit
  point; reads at the tail), not the entry replica;
* every member executes every request in the same order as it applies.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..config import GigapaxosTpuConfig
from ..models.replicable import Replicable
from ..types import NO_REQUEST
from .. import overload as _overload
from ..utils.intmap import RowAllocator
from ..obs.phase import phase_clock as _phase_clock
from ..utils.locking import ContendedLock, locked as _locked
from . import state as st
from .tick import (ChainInbox, HostChainOutbox, chain_tick_packed,
                   unpack_chain_outbox)


@dataclass
class ChainRequest:
    rid: int
    name: str
    row: int
    payload: bytes
    stop: bool
    callback: Optional[Callable[[int, bytes], None]]
    responded: bool = False
    executed_by: set = field(default_factory=set)


class ChainManager:
    def __init__(
        self,
        cfg: GigapaxosTpuConfig,
        n_replicas: int,
        apps: List[Replicable],
        wal=None,
    ):
        assert len(apps) == n_replicas
        self.cfg = cfg
        self.R = n_replicas
        self.G = cfg.paxos.max_groups
        self.W = cfg.paxos.window
        self.P = cfg.paxos.proposals_per_tick
        self.state = st.init_state(self.R, self.G, self.W)
        self.rows = RowAllocator(self.G)
        self.apps = apps
        self.wal = wal
        self.alive = np.ones(self.R, bool)
        self.tick_num = 0
        self.outstanding: Dict[int, ChainRequest] = {}
        self._next_rid = 1
        self._queues: Dict[int, collections.deque] = collections.defaultdict(
            collections.deque
        )
        self._held_callbacks: list = []
        self.stats = collections.Counter()
        self._stopped_rows: set[int] = set()
        # intake governor: watermark shed of client-class proposes (ISSUE 14)
        self.overload = (
            _overload.IntakeGovernor(cfg.overload.intake_hi,
                                     cfg.overload.intake_lo, node="chain")
            if cfg.overload.enabled else None)
        # host mirrors of config state (see paxos/manager.py rationale)
        self._member_np = np.zeros((self.R, self.G), bool)
        self._n_members_np = np.zeros(self.G, np.int32)
        self._in_req = np.zeros((self.P, self.G), np.int32)
        self._in_stp = np.zeros((self.P, self.G), bool)
        self._placed: list = []
        self.lock = ContendedLock()
        self._pc = _phase_clock("chain")
        if self.wal is not None:
            self.wal.attach(self)

    # ------------------------------------------------------------------ admin
    @_locked
    def create_paxos_instance(
        self, name: str, members: List[int], epoch: int = 0
    ) -> bool:
        """Name kept for SPI compatibility; creates a replicated *chain*."""
        if name in self.rows:
            return False
        row = self.rows.alloc(name)
        mask = np.zeros((1, self.R), bool)
        for m in members:
            mask[0, m] = True
        self.state = st.create_groups(
            self.state, np.array([row], np.int32), mask,
            np.array([epoch], np.int32),
        )
        self._member_np[:, row] = mask[0]
        self._n_members_np[row] = mask[0].sum()
        self._stopped_rows.discard(row)
        if self.wal is not None:
            self.wal.log_create(name, members, epoch)
        return True

    @_locked
    def remove_paxos_instance(self, name: str) -> bool:
        row = self.rows.row(name)
        if row is None:
            return False
        self.state = st.free_groups(self.state, np.array([row], np.int32))
        self._member_np[:, row] = False
        self._n_members_np[row] = 0
        self.rows.free(name)
        self._fail_queued(row)
        self._stopped_rows.discard(row)
        if self.wal is not None:
            self.wal.log_remove(name)
        return True

    @_locked
    def group_members(self, name: str) -> Optional[List[int]]:
        row = self.rows.row(name)
        if row is None:
            return None
        return [int(r) for r in np.where(self._member_np[:, row])[0]]

    @_locked
    def is_stopped(self, name: str) -> bool:
        row = self.rows.row(name)
        return row is not None and row in self._stopped_rows

    @_locked
    def exec_watermarks(self, name: str):
        """Per-replica applied watermark [R] (donor selection for
        checkpoint transfer — see PaxosManager.exec_watermarks)."""
        row = self.rows.row(name)
        if row is None:
            return None
        return np.array(self.state.applied[:, row])

    # ---------------------------------------------------------------- propose
    @_locked
    def propose(
        self,
        name: str,
        payload: bytes,
        callback: Optional[Callable[[int, bytes], None]] = None,
        stop: bool = False,
        entry: Optional[int] = None,
        deadline: Optional[int] = None,
        cls: int = _overload.CLS_CONTROL,
    ) -> Optional[int]:
        """Order one write through the chain's head (``propose :434``).
        ``entry`` is accepted for SPI compatibility and ignored — the head
        is always the entry."""
        if _overload.expired(deadline):
            if callback is not None:
                self._held_callbacks.append(
                    (callback, _overload.RID_EXPIRED, None))
            self.stats["expired_drops"] += 1
            _overload.count_expired("intake", "chain")
            return None
        if (cls == _overload.CLS_CLIENT and self.overload is not None
                and not self.overload.admit(cls)):
            if callback is not None:
                self._held_callbacks.append(
                    (callback, _overload.RID_BUSY, None))
            self.stats["shed_requests"] += 1
            _overload.count_shed(cls, "intake", "chain")
            return None
        row = self.rows.row(name)
        if row is None:
            return None
        if row in self._stopped_rows:
            if callback is not None:
                self._held_callbacks.append((callback, -1, None))
            self.stats["failed_requests"] += 1
            return None
        rid = self._next_rid
        self._next_rid += 1
        self.outstanding[rid] = ChainRequest(rid, name, row, payload, stop, callback)
        self._queues[row].append(rid)
        return rid

    def propose_stop(self, name: str, payload: bytes = b"", callback=None):
        return self.propose(name, payload, callback, stop=True)

    def _fail_queued(self, row: int) -> None:
        q = self._queues.pop(row, None)
        if not q:
            return
        for rid in q:
            rec = self.outstanding.pop(rid, None)
            if rec is not None and rec.callback is not None and not rec.responded:
                self._held_callbacks.append((rec.callback, rid, None))
            self.stats["failed_requests"] += 1

    # ------------------------------------------------------------------- tick
    def _build_inbox(self) -> ChainInbox:
        req, stp = self._in_req, self._in_stp
        for _row, take in self._placed:
            for _rid, _e, p in take:
                req[p, _row] = 0
                stp[p, _row] = False
        placed = []
        for row, q in self._queues.items():
            take = []
            while q and len(take) < self.P:
                rid = q.popleft()
                if rid not in self.outstanding:
                    continue
                p = len(take)
                req[p, row] = rid
                stp[p, row] = self.outstanding[rid].stop
                take.append((rid, 0, p))
            if take:
                placed.append((row, take))
        self._placed = placed
        # fresh copies: the staging buffers are mutated next build, and the
        # WAL reads inbox.alive without a device round-trip
        return ChainInbox(req.copy(), stp.copy(), self.alive.copy())

    @_locked
    def tick(self) -> HostChainOutbox:
        pc = self._pc
        pc.begin()
        if self.overload is not None:
            self.overload.update(
                sum(len(q) for q in self._queues.values())
                + sum(1 for rec in self.outstanding.values()
                      if not rec.responded))
        inbox = self._build_inbox()
        pc.mark("intake")
        # dispatch first, journal second: the WAL fsync overlaps the async
        # device step (see paxos/manager.py tick)
        self.state, packed = chain_tick_packed(self.state, inbox)
        pc.mark("dispatch")
        if self.wal is not None:
            self.wal.log_inbox(self.tick_num, inbox)
        pc.mark("wal_fsync")
        out = unpack_chain_outbox(packed, self.R, self.P, self.W, self.G)
        pc.mark("tally")
        self._process_outbox(out)
        self.tick_num += 1
        if self.wal is not None:
            self.wal.maybe_checkpoint()
        self._flush_callbacks()
        if self.tick_num % 64 == 0:
            self._sweep_outstanding()
        pc.mark("execute")
        pc.end()
        return out

    def _flush_callbacks(self) -> None:
        """Release client responses only once the WAL covering their tick
        is durable (log-before-respond, as in the paxos manager)."""
        if not self._held_callbacks:
            return
        if self.wal is not None and not self.wal.is_synced():
            return
        held, self._held_callbacks = self._held_callbacks, []
        for cb, rid, resp in held:
            cb(rid, resp)

    def _process_outbox(self, out: HostChainOutbox) -> None:
        taken = out.intake_taken
        for row, take in self._placed:
            for rid, _entry, p in reversed(take):
                if not taken[p, row] and rid in self.outstanding:
                    self._queues[row].appendleft(rid)
        er, es, ec = out.exec_req, out.exec_stop, out.exec_count
        tail = out.tail_id
        active = np.where(ec.sum(axis=0) > 0)[0] if ec.any() else []
        for row in active:
            name = self.rows.name(int(row))
            if name is None:
                continue
            for r in range(self.R):
                n = int(ec[r, row])
                for j in range(n):
                    rid = int(er[r, j, row])
                    is_stop = bool(es[r, j, row])
                    self._execute_one(
                        r, int(row), name, rid, is_stop, r == int(tail[row])
                    )
        self.stats["decisions"] += int(out.committed_now.sum())

    def _execute_one(self, r: int, row: int, name: str, rid: int,
                     is_stop: bool, at_tail: bool) -> None:
        if is_stop and at_tail and row not in self._stopped_rows:
            self._stopped_rows.add(row)
            self._fail_queued(row)
        if rid == NO_REQUEST:
            return
        rec = self.outstanding.get(rid)
        if rec is None:
            self.stats["orphan_execs"] += 1
            return
        response = self.apps[r].execute(name, rec.payload, rid)
        rec.executed_by.add(r)
        self.stats["executions"] += 1
        if at_tail and not rec.responded:
            # commit point: the tail applied it (every upstream member has
            # therefore applied it too)
            rec.responded = True
            if rec.callback is not None:
                self._held_callbacks.append((rec.callback, rid, response))
        members = int(self._n_members_np[row])
        if rec.responded and len(rec.executed_by) >= members:
            del self.outstanding[rid]

    def _sweep_outstanding(self) -> None:
        """Drop responded records every *live* member has executed — with a
        dead member, executed_by can never cover the full membership, and
        without this sweep every request payload is retained forever (the
        paxos manager sweeps identically; dead members catch up from the
        ring or by checkpoint transfer, not from the host payload store)."""
        if not self.outstanding:
            return
        member = self._member_np
        dead = []
        for rid, rec in self.outstanding.items():
            if not rec.responded:
                continue
            ms = np.where(member[:, rec.row])[0]
            live = [m for m in ms if self.alive[m]]
            if live and all(m in rec.executed_by for m in live):
                dead.append(rid)
        for rid in dead:
            del self.outstanding[rid]
            self.stats["swept"] += 1

    # --------------------------------------------------------------- liveness
    def set_alive(self, r: int, up: bool) -> None:
        self.alive[r] = up

    # ------------------------------------------------------------ conveniences
    def run_ticks(self, n: int) -> None:
        for _ in range(n):
            self.tick()

    @_locked
    def pending_count(self) -> int:
        return sum(len(q) for q in self._queues.values())

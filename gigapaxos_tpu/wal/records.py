"""Typed binary record codec for everything the WALs persist.

Replaces pickle in journals, snapshots, and the disk spill tier.  Pickle's
replay path executes arbitrary constructors from disk bytes; a torn or
tampered journal could thus run code at recovery.  This codec is pure data
— a fixed tag set, length-delimited, no imports, no callables — the moral
equivalent of the reference's typed SQL tables (SQLPaxosLogger.java:
3973-4018), shaped for the records this framework writes: admin tuples,
per-tick intake (ints, bytes, nested lists), HotRestoreInfo dicts with
numpy arrays, checkpoint metadata with sets and bytes blobs.

Wire format: 1 tag byte + payload.  Integers are i64 little-endian (a 'I'
bigint escape covers the rest); containers are u32-counted; ndarrays carry
dtype-str + shape + raw bytes.  Dict keys are full values (tuples of ints
are common keys here).
"""

from __future__ import annotations

import struct

import numpy as np

_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

I64_MIN, I64_MAX = -(1 << 63), (1 << 63) - 1


def _enc(o, out: bytearray) -> None:
    if o is None:
        out.append(0x4E)  # N
    elif o is True:
        out.append(0x54)  # T
    elif o is False:
        out.append(0x46)  # F
    elif isinstance(o, (np.integer,)):
        _enc(int(o), out)
    elif isinstance(o, (np.bool_,)):
        _enc(bool(o), out)
    elif isinstance(o, int):
        if I64_MIN <= o <= I64_MAX:
            out.append(0x69)  # i
            out += _I64.pack(o)
        else:
            b = o.to_bytes((o.bit_length() + 8) // 8, "little", signed=True)
            out.append(0x49)  # I
            out += _U32.pack(len(b))
            out += b
    elif isinstance(o, (float, np.floating)):
        out.append(0x66)  # f
        out += _F64.pack(float(o))
    elif isinstance(o, str):
        b = o.encode()
        out.append(0x73)  # s
        out += _U32.pack(len(b))
        out += b
    elif isinstance(o, (bytes, bytearray, memoryview)):
        b = bytes(o)
        out.append(0x62)  # b
        out += _U32.pack(len(b))
        out += b
    elif isinstance(o, np.ndarray):
        d = o.dtype.str.encode()
        out.append(0x61)  # a
        out.append(len(d))
        out += d
        out.append(o.ndim)
        for s in o.shape:
            out += _U32.pack(s)
        raw = np.ascontiguousarray(o).tobytes()
        out += _U32.pack(len(raw))
        out += raw
    elif isinstance(o, tuple):
        out.append(0x74)  # t
        out += _U32.pack(len(o))
        for x in o:
            _enc(x, out)
    elif isinstance(o, list):
        out.append(0x6C)  # l
        out += _U32.pack(len(o))
        for x in o:
            _enc(x, out)
    elif isinstance(o, (set, frozenset)):
        out.append(0x65)  # e
        out += _U32.pack(len(o))
        for x in o:
            _enc(x, out)
    elif isinstance(o, dict):
        out.append(0x64)  # d
        out += _U32.pack(len(o))
        for k, v in o.items():
            _enc(k, out)
            _enc(v, out)
    else:
        raise TypeError(f"records codec: unsupported type {type(o)!r}")


def dumps(o) -> bytes:
    out = bytearray()
    _enc(o, out)
    return bytes(out)


class _Reader:
    __slots__ = ("b", "o")

    def __init__(self, b: bytes):
        self.b = b
        self.o = 0

    def take(self, n: int) -> bytes:
        v = self.b[self.o:self.o + n]
        if len(v) != n:
            raise ValueError("records codec: truncated record")
        self.o += n
        return v

    def u32(self) -> int:
        return _U32.unpack(self.take(4))[0]


def _dec(r: _Reader):
    tag = r.take(1)[0]
    if tag == 0x4E:
        return None
    if tag == 0x54:
        return True
    if tag == 0x46:
        return False
    if tag == 0x69:
        return _I64.unpack(r.take(8))[0]
    if tag == 0x49:
        return int.from_bytes(r.take(r.u32()), "little", signed=True)
    if tag == 0x66:
        return _F64.unpack(r.take(8))[0]
    if tag == 0x73:
        return r.take(r.u32()).decode()
    if tag == 0x62:
        return bytes(r.take(r.u32()))
    if tag == 0x61:
        dtype = np.dtype(r.take(r.take(1)[0]).decode())
        ndim = r.take(1)[0]
        shape = tuple(r.u32() for _ in range(ndim))
        raw = r.take(r.u32())
        return np.frombuffer(raw, dtype).reshape(shape).copy()
    if tag == 0x74:
        return tuple(_dec(r) for _ in range(r.u32()))
    if tag == 0x6C:
        return [_dec(r) for _ in range(r.u32())]
    if tag == 0x65:
        return {_dec(r) for _ in range(r.u32())}
    if tag == 0x64:
        return {_dec(r): _dec(r) for _ in range(r.u32())}
    raise ValueError(f"records codec: unknown tag {tag:#x}")


def loads(b: bytes):
    r = _Reader(b)
    v = _dec(r)
    if r.o != len(b):
        raise ValueError("records codec: trailing garbage")
    return v


class SchemaError(ValueError):
    """A CRC-valid record decoded to something no logger ever writes."""


def validate_op_record(rec, schema) -> int:
    """Fail-closed whitelist check for op records decoded from disk.

    CRC catches torn/flipped bytes, but a corrupt-but-CRC-valid record (or
    a record from a foreign/garbage file resynced into the stream) must
    not reach the replay dispatchers, which index into it and execute it.
    ``schema`` maps op byte -> (min_arity, max_arity); anything outside
    the whitelist raises :class:`SchemaError` before any field is used.
    Returns the validated op byte.
    """
    if not isinstance(rec, tuple) or not rec:
        raise SchemaError(
            f"op record is {type(rec).__name__}, expected non-empty tuple")
    op = rec[0]
    if isinstance(op, bool) or not isinstance(op, int):
        raise SchemaError(f"op byte is {type(op).__name__}, expected int")
    arity = schema.get(op)
    if arity is None:
        raise SchemaError(f"unknown op {op}")
    lo, hi = arity
    if not lo <= len(rec) <= hi:
        raise SchemaError(
            f"op {op} arity {len(rec)} outside whitelist [{lo}, {hi}]")
    return op

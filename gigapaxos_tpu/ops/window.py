"""Ring-buffer window ops over ``[..., G, W]`` arrays.

The reference keeps per-group sparse maps ``acceptedProposals`` and
``committedRequests`` keyed by slot (``PaxosAcceptor.java:108-115``) whose
size is bounded in practice by the out-of-order arrival window.  Here each
group owns a fixed ring of W slots: slot ``s`` lives at ring index
``s & (W-1)`` and an entry is valid only for slots in
``[exec_slot, exec_slot + W)``.  In-order extraction
(``PaxosAcceptor.putAndRemoveNextExecutable``, PaxosAcceptor.java:325-366)
becomes a leading-run count over the reordered window — branch-free, vmap- and
MXU-friendly.
"""

from __future__ import annotations

import jax.numpy as jnp


def ring_index(slots, window: int):
    """Ring index for (possibly wrapped) int32 slot numbers. W power of two."""
    return jnp.bitwise_and(slots.astype(jnp.int32), jnp.int32(window - 1))


def window_slots(exec_slot, window: int):
    """``[..., W]`` array of the absolute slots covered by each group's window,
    position j = exec_slot + j."""
    ar = jnp.arange(window, dtype=jnp.int32)
    return exec_slot[..., None] + ar


def in_window(slots, exec_slot, window: int):
    """True where ``slots`` fall inside [exec_slot, exec_slot+W) (wraparound-
    aware)."""
    d = (slots - exec_slot).astype(jnp.int32)
    return (d >= 0) & (d < window)


def gather_by_slot(arr, exec_slot, window: int):
    """Reorder ring storage ``[..., G, W]`` so position j holds the entry for
    slot exec_slot+j.  ``exec_slot`` has shape ``[..., G]``."""
    idx = ring_index(window_slots(exec_slot, window), window)
    return jnp.take_along_axis(arr, idx, axis=-1)


def leading_run(valid):
    """Number of leading True along the last axis (per group): how many
    consecutive in-order entries are ready.  ``valid``: bool ``[..., W]``."""
    return jnp.sum(jnp.cumprod(valid.astype(jnp.int32), axis=-1), axis=-1)


def clear_below(arr, slot_of_entry, watermark, fill):
    """Invalidate ring entries whose slot is below ``watermark``.

    ``arr``: payload ``[..., G, W]``; ``slot_of_entry``: the absolute slot each
    ring entry claims to hold ``[..., G, W]``; ``watermark``: ``[..., G]``.
    Entries with slot < watermark are replaced by ``fill``.
    """
    stale = (slot_of_entry - watermark[..., None]).astype(jnp.int32) < 0
    return jnp.where(stale, fill, arr)

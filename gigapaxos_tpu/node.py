"""Node bootstrap: wire transport, data plane and control plane together.

Analog of ``reconfiguration/ReconfigurableNode.java:63`` (entry point that
builds a messenger, then an ActiveReplica and/or Reconfigurator per role)
plus ``TESTReconfigurationMain.startLocalServers``
(reconfiguration/testing/TESTReconfigurationMain.java:86), whose strategy —
instantiate every node of a cluster *in one process* on loopback ports with
real sockets — is exactly how our tests run (SURVEY §4).

TPU shape (Mode A): all active replicas of one deployment share a single
dense-device data plane — node ids are replica slots of one mesh program —
so the cluster owns

* one active-side :class:`PaxosManager` (R = #actives) + TickDriver,
* one RC-side :class:`PaxosManager` (R = #reconfigurators) + TickDriver,
  whose apps are the :class:`ReconfiguratorDB` replicas,
* per active node id: a Messenger + :class:`ActiveReplica`,
* per RC node id: a Messenger + :class:`Reconfigurator`,
* failure detectors on every node feeding a shared liveness view.

In a multi-host deployment the same wiring runs once per host with the
replica axis sharded over the mesh (parallel/mesh.py); the control-plane
objects are unchanged — only the manager's mesh placement differs.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Dict, List, Optional

from .config import GigapaxosTpuConfig
from .models.replicable import Replicable
from .net.failure_detection import FailureDetection
from .net.messenger import Messenger, NodeMap
from .paxos.driver import TickDriver
from .paxos.manager import PaxosManager
from .placement import GroupMigrator, MigrationStats, ShardRebalancer
from .reconfiguration.active_replica import ActiveReplica
from .reconfiguration.coordinator import PaxosReplicaCoordinator
from .reconfiguration.demand import AbstractDemandProfile, DemandProfile
from .reconfiguration.rc_db import (
    ReconfiguratorDB,
    RepliconfigurableReconfiguratorDB,
)
from .reconfiguration.reconfigurator import Reconfigurator


class RebalancerDaemon:
    """Periodic placement loop: ``ShardRebalancer.propose`` over the live
    demand snapshot, ``GroupMigrator.execute_plan`` through the epoch
    machinery (ROADMAP placement follow-up — callers no longer drive the
    loop by hand).  OFF by default: started only by an explicit
    :meth:`InProcessCluster.start_rebalancer`; its lifecycle is tied to the
    node (``close()`` stops it)."""

    def __init__(self, cluster: "InProcessCluster", interval_s: float = 1.0,
                 *, table=None, stats: Optional[MigrationStats] = None,
                 migrator: Optional[GroupMigrator] = None,
                 rebalancer: Optional[ShardRebalancer] = None,
                 **rebalancer_kw):
        m = cluster.manager
        if getattr(m, "_placement", None) is None:
            raise RuntimeError(
                "rebalancer daemon needs cfg.placement.enabled demand "
                "counters on the data-plane manager"
            )
        gs, _per = m.shard_geometry()
        self.m = m
        self.driver = cluster.driver
        self.interval_s = float(interval_s)
        self.stats = stats or MigrationStats()
        self.migrator = migrator or GroupMigrator(
            cluster.coordinator, table=table, counters=m._placement,
            stats=self.stats,
        )
        self.rebalancer = rebalancer or ShardRebalancer(
            m.G, gs, **rebalancer_kw
        )
        self.moves_total = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="rebalancer", daemon=True
        )
        self._thread.start()

    def _pump(self) -> None:
        # the TickDriver owns the tick loop; the migrator just needs the
        # plane to advance while it waits for the stop/checkpoint to land
        self.driver.kick()
        time.sleep(0.002)

    def run_once(self) -> int:
        """One propose/execute round; returns groups moved."""
        demand = self.m.demand_snapshot()
        if demand is None:
            return 0
        plan = self.rebalancer.propose(
            self.m.tick_num, demand,
            free_rows_in_shard=self.m.free_rows_in_shard,
            blob_bytes=self.m.blob_bytes_of_row,
        )
        if not plan:
            return 0
        moved = self.migrator.execute_plan(plan, pump=self._pump)
        if moved:
            self.rebalancer.record_executed(moved)
        else:
            self.rebalancer.record_aborted()
        self.moves_total += moved
        return moved

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception:
                # a transient failure (shutdown race, full destination)
                # must not kill the daemon; the next round re-plans
                pass

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)


class InProcessCluster:
    """A whole deployment in one process on loopback ports.

    ``cfg.nodes`` lists actives and reconfigurators with their bind
    addresses (the ``active.*``/``reconfigurator.*`` topology of
    ``gigapaxos.properties``); ``app_factory()`` builds one Replicable per
    active replica slot.
    """

    def __init__(
        self,
        cfg: GigapaxosTpuConfig,
        app_factory: Callable[[], Replicable],
        demand_profile_factory: Callable[[str], AbstractDemandProfile] = DemandProfile,
        replicas_per_name: int = 3,
        rc_group_size: int = 3,
        wal=None,
        rc_wal=None,
        start_fd: bool = False,
        coordinator: str = "paxos",
        spare_replica_slots: int = 0,
        spare_rc_slots: int = 0,
        wal_dir: Optional[str] = None,
        rc_wal_dir: Optional[str] = None,
    ):
        self.cfg = cfg
        active_ids = cfg.nodes.active_ids()
        rc_ids = cfg.nodes.reconfigurator_ids()
        if not active_ids or not rc_ids:
            raise ValueError("topology needs >=1 active and >=1 reconfigurator")

        # ---------------- data plane (shared dense device state, Mode A)
        # the coordination protocol is pluggable exactly like the reference's
        # REPLICA_COORDINATOR_CLASS (ReconfigurableNode.java:203-218)
        # spare slots = provisioned-but-unbound replica capacity for runtime
        # active-node adds (elasticity binds node ids to spare slots)
        self._demand_profile_factory = demand_profile_factory
        self._rc_group_size = rc_group_size
        n_slots = len(active_ids) + spare_replica_slots
        apps = [app_factory() for _ in range(n_slots)]
        if coordinator == "chain":
            from .chain import ChainManager, ChainReplicaCoordinator

            self.manager = ChainManager(cfg, n_slots, apps, wal=wal)
            self.coordinator = ChainReplicaCoordinator(self.manager, active_ids)
        elif coordinator == "paxos":
            if wal_dir is not None:
                if wal is not None:
                    raise ValueError("pass wal= or wal_dir=, not both")
                self.manager = self._open_plane(cfg, n_slots, apps,
                                                wal_dir, "ar")
            else:
                self.manager = PaxosManager(cfg, n_slots, apps, wal=wal,
                                            spill_ns="ar")
            self.coordinator = PaxosReplicaCoordinator(self.manager, active_ids)
            # a WAL-replayed manager has its groups back but the fresh
            # coordinator's epoch map is empty — re-adopt name#epoch rows so
            # recovered groups answer instead of "not_active"
            self.coordinator.adopt_live_epochs()
        else:
            raise ValueError(f"unknown coordinator {coordinator!r}")
        self.driver = TickDriver(self.manager).start()

        # ---------------- RC plane (the DB replicated on its own data plane)
        # spare RC slots = provisioned capacity for runtime RC-node adds
        # (Reconfigurator.handleReconfigureRCNodeConfig:1044)
        rc_apps = [ReconfiguratorDB(r) for r in rc_ids] + [
            ReconfiguratorDB(f"_spare{i}") for i in range(spare_rc_slots)
        ]
        # the RC DB is a host state machine: a device-app data plane must
        # not leak its mode into the control plane's manager
        rc_cfg = cfg
        if cfg.paxos.device_app:
            import copy as _copy
            import dataclasses as _dc

            rc_cfg = _copy.copy(cfg)
            rc_cfg.paxos = _dc.replace(cfg.paxos, device_app=False)
        if rc_wal_dir is not None:
            if rc_wal is not None:
                raise ValueError("pass rc_wal= or rc_wal_dir=, not both")
            self.rc_manager = self._open_plane(rc_cfg, len(rc_apps), rc_apps,
                                               rc_wal_dir, "rc")
        else:
            self.rc_manager = PaxosManager(rc_cfg, len(rc_apps), rc_apps,
                                           wal=rc_wal, spill_ns="rc")
        self.rdb = RepliconfigurableReconfiguratorDB(
            self.rc_manager, rc_ids, k=rc_group_size
        )
        self.rc_driver = TickDriver(self.rc_manager).start()

        # ---------------- per-node control plane endpoints
        from .net.security import TransportSecurity

        security = TransportSecurity.from_config(cfg.ssl)
        self.nodemap = NodeMap(cfg.nodes)
        self.actives: Dict[str, ActiveReplica] = {}
        self.reconfigurators: Dict[str, Reconfigurator] = {}
        self.fds: Dict[str, FailureDetection] = {}
        self.rebalancer: Optional[RebalancerDaemon] = None
        self._liveness: Dict[str, bool] = {n: True for n in rc_ids + active_ids}

        for a in active_ids:
            m = Messenger(a, cfg.nodes.actives[a], self.nodemap,
                          security=security)
            # port 0 binds ephemerally: publish the real port, both in this
            # cluster's nodemap and back into cfg.nodes so clients built
            # from the same config resolve correctly
            self.nodemap.add(a, cfg.nodes.actives[a][0], m.port)
            cfg.nodes.actives[a] = (cfg.nodes.actives[a][0], m.port)
            self.actives[a] = ActiveReplica(
                a, m, self.coordinator, rc_ids,
                demand_profile_factory=demand_profile_factory,
                rc_group_size=rc_group_size,
            )
        for r in rc_ids:
            m = Messenger(r, cfg.nodes.reconfigurators[r], self.nodemap,
                          security=security)
            self.nodemap.add(r, cfg.nodes.reconfigurators[r][0], m.port)
            cfg.nodes.reconfigurators[r] = (cfg.nodes.reconfigurators[r][0], m.port)
            self.reconfigurators[r] = Reconfigurator(
                r, m, self.rdb, active_ids,
                replicas_per_name=replicas_per_name,
                demand_profile_factory=demand_profile_factory,
                is_node_up=lambda n: self._liveness.get(n, True),
            )
        # block until both planes' jitted ticks are compiled — otherwise the
        # first client RPC races a multi-second XLA compile and times out
        self.driver.wait_ready()
        self.rc_driver.wait_ready()
        if start_fd:
            for r in rc_ids:
                self.fds[r] = FailureDetection(
                    self.reconfigurators[r].m, monitored=rc_ids,
                    ping_interval_s=cfg.fd.ping_interval_s,
                    timeout_s=cfg.fd.timeout_s,
                    adaptive=cfg.fd.adaptive,
                    adaptive_beta=cfg.fd.adaptive_beta,
                    adaptive_gain=cfg.fd.adaptive_gain,
                    on_change=self._fd_change,
                )

    @staticmethod
    def _open_plane(cfg, n_slots: int, apps, wal_dir: str, ns: str):
        """Build one plane's manager against an on-disk WAL directory:
        recover (snapshot + journal replay) when the directory already holds
        a journal, else start fresh with a new logger — the cell worker's
        crash-restart path (cells/worker.py) in one switch."""
        from .wal import logger as wal_logger

        os.makedirs(wal_dir, exist_ok=True)
        if any(fn.startswith(("journal.", "snapshot."))
               for fn in os.listdir(wal_dir)):
            return wal_logger.recover(cfg, n_slots, apps, wal_dir,
                                      native=cfg.native_journal, spill_ns=ns)
        wal = wal_logger.PaxosLogger(
            wal_dir, sync_every_ticks=cfg.paxos.sync_every_ticks,
            native=cfg.native_journal,
            payload_dedup=getattr(cfg.paxos, "wal_payload_dedup", True),
        )
        return PaxosManager(cfg, n_slots, apps, wal=wal, spill_ns=ns)

    def _fd_change(self, node: str, up: bool) -> None:
        self._liveness[node] = up

    # ------------------------------------------------------------- elasticity
    def add_active_endpoint(self, node_id: str,
                            bind=("127.0.0.1", 0)) -> ActiveReplica:
        """Local wiring for a runtime active-node add: bind a spare replica
        slot and start the node's control-plane endpoint.  Pair with an
        admin ``add_active`` request to a reconfigurator so the RC pool
        learns the node (the committed NC change carries the address)."""
        slot = self.coordinator.bind_node(node_id)
        if slot is None:
            raise RuntimeError("no spare replica slots provisioned")
        self.manager.set_alive(slot, True)  # slot may be recycled from a remove
        m = Messenger(node_id, bind, self.nodemap)
        self.nodemap.add(node_id, bind[0], m.port)
        self.cfg.nodes.actives[node_id] = (bind[0], m.port)
        ar = ActiveReplica(
            node_id, m, self.coordinator, self.cfg.nodes.reconfigurator_ids(),
            demand_profile_factory=self._demand_profile_factory,
            rc_group_size=self._rc_group_size,
        )
        self.actives[node_id] = ar
        self._liveness[node_id] = True
        return ar

    def remove_active_endpoint(self, node_id: str) -> None:
        """Tear down a removed node's endpoint (after the admin
        ``remove_active`` request migrated its names away)."""
        ar = self.actives.pop(node_id, None)
        if ar is not None:
            ar.close()
        slot = self.coordinator.unbind_node(node_id)
        if slot is not None:
            self.manager.set_alive(slot, False)  # dead until rebound
        self.cfg.nodes.actives.pop(node_id, None)
        self._liveness[node_id] = False

    def add_rc_endpoint(self, node_id: str,
                        bind=("127.0.0.1", 0)) -> Reconfigurator:
        """Local wiring for a runtime RC-node add: bind a spare RC-plane
        slot and start the node's control endpoint.  Pair with an admin
        ``add_reconfigurator`` request so the committed NC-RC change splices
        the ring everywhere (Reconfigurator.java:1044)."""
        slot = self.rdb.bind_rc(node_id)
        if slot is None:
            raise RuntimeError("no spare RC slots provisioned")
        self.rc_manager.set_alive(slot, True)
        from .net.security import TransportSecurity

        m = Messenger(node_id, bind, self.nodemap,
                      security=TransportSecurity.from_config(self.cfg.ssl))
        self.nodemap.add(node_id, bind[0], m.port)
        self.cfg.nodes.reconfigurators[node_id] = (bind[0], m.port)
        k = (next(iter(self.reconfigurators.values())).k
             if self.reconfigurators else 3)
        rc = Reconfigurator(
            node_id, m, self.rdb, self.cfg.nodes.active_ids(),
            replicas_per_name=k,
            demand_profile_factory=self._demand_profile_factory,
            is_node_up=lambda n: self._liveness.get(n, True),
        )
        self.reconfigurators[node_id] = rc
        self._liveness[node_id] = True
        return rc

    def remove_rc_endpoint(self, node_id: str) -> None:
        """Tear down a removed reconfigurator's endpoint (after the admin
        ``remove_reconfigurator`` request re-homed its records)."""
        rc = self.reconfigurators.pop(node_id, None)
        if rc is not None:
            rc.close()
        slot = self.rdb.unbind_rc(node_id)
        if slot is not None:
            self.rc_manager.set_alive(slot, False)
        self.cfg.nodes.reconfigurators.pop(node_id, None)
        self._liveness[node_id] = False

    # ------------------------------------------------------------- placement
    def start_rebalancer(self, interval_s: float = 1.0,
                         **kw) -> RebalancerDaemon:
        """Start the periodic rebalancer (off by default).  ``kw`` passes
        through to :class:`RebalancerDaemon` — ``table=`` to keep a
        placement-override table in step with moves, plus any
        :class:`ShardRebalancer` tuning (``skew_threshold``, ...)."""
        if self.rebalancer is not None:
            raise RuntimeError("rebalancer already running")
        self.rebalancer = RebalancerDaemon(self, interval_s, **kw)
        return self.rebalancer

    def stop_rebalancer(self) -> None:
        if self.rebalancer is not None:
            self.rebalancer.stop()
            self.rebalancer = None

    # ----------------------------------------------------------------- admin
    def kick(self) -> None:
        self.driver.kick()
        self.rc_driver.kick()

    def set_node_up(self, node: str, up: bool) -> None:
        """Test hook: mark a node's liveness (crash emulation, the analog of
        TESTPaxosConfig.crash, testing/TESTPaxosConfig.java:563-578)."""
        self._liveness[node] = up

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Quiesce both planes: kick the drivers until no proposal is
        outstanding and every journaled tick is fsync-covered (a response
        the client saw must never be lost by the shutdown that follows).
        Returns False if the deadline passed with work still in flight."""
        deadline = time.monotonic() + timeout_s
        planes = [(self.driver, self.manager), (self.rc_driver, self.rc_manager)]
        while True:
            busy = False
            for drv, m in planes:
                wal = getattr(m, "wal", None)
                if m.pending_count() > 0 or (wal is not None
                                             and not wal.is_synced()):
                    busy = True
                    drv.kick()
            if not busy:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.005)

    def close(self) -> None:
        self.stop_rebalancer()
        for fd in self.fds.values():
            fd.close()
        # drivers stop BEFORE the messengers close: a tick flushing frames
        # after its transport died would fail sends mid-commit (the old
        # order); stop() also drains the execution pipeline
        self.driver.stop()
        self.rc_driver.stop()
        # final fsync + journal close: an acked commit must be disk-covered
        # before the process exits
        for m in (self.manager, self.rc_manager):
            wal = getattr(m, "wal", None)
            if wal is not None:
                try:
                    if wal.journal is not None:
                        wal._sync()
                    wal.close()
                except Exception:
                    pass
        for ar in self.actives.values():
            ar.close()
        for rc in self.reconfigurators.values():
            rc.close()

    def shutdown(self, drain_timeout_s: float = 10.0) -> bool:
        """Graceful stop: drain in-flight work, then close.  Returns the
        drain verdict (close happens either way)."""
        ok = self.drain(drain_timeout_s)
        self.close()
        return ok

    def install_sigterm(self, drain_timeout_s: float = 10.0,
                        on_exit: Optional[Callable[[], None]] = None) -> None:
        """SIGTERM = graceful cell shutdown (cells/worker.py, systemd stop):
        drain the in-flight tick, flush + close the WAL, close transports,
        then exit 0.  Main-thread only (signal module constraint)."""
        def _handler(signum, frame):
            try:
                self.shutdown(drain_timeout_s)
                if on_exit is not None:
                    on_exit()
            finally:
                os._exit(0)

        signal.signal(signal.SIGTERM, _handler)


def build_node(
    node_id: str,
    cfg: GigapaxosTpuConfig,
    app_factory: Callable[[], Replicable],
    **kw,
) -> InProcessCluster:
    """CLI-style single-entry bootstrap (ReconfigurableNode.main analog).

    Today every deployment is driven by one process per replica-mesh (Mode
    A), so this simply builds the cluster object; per-host Mode B spawning
    lands with the multi-host transport binding.
    """
    return InProcessCluster(cfg, app_factory, **kw)

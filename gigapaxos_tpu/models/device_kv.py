"""Device-resident KV application: execution fused behind the consensus tick.

The reference's workload app (``gigapaxos/testing/TESTPaxosApp.java:60``)
executes inside the JVM next to the acceptor; every decision still crosses
the per-request handler stack.  Host apps here have the same shape — the
decision stream leaves the device and ``Replicable.execute`` runs
interpreted Python per request (``paxos/manager.py``), which caps e2e
throughput orders of magnitude below the raw kernel.

:class:`DeviceKV` moves the app itself into device arrays so the decision
stream NEVER leaves the device:

* app state — a direct-mapped KV cache per (replica, group):
  ``key[R, G, S]`` / ``val[R, G, S]`` int32 (0 = empty slot; key k lives at
  slot ``k & (S-1)``, last-writer-wins on collision, deterministic on every
  replica by construction);
* request descriptors — clients register ``rid -> (op, key, val)`` in a
  hashed device table ``[T]`` (op PUT=1/GET=2/DEL=3); the tick's executed
  rids gather their descriptors and a vectorized apply updates the KV
  arrays for every group at once;
* misses (descriptor evicted/never uploaded) surface in a ``miss`` mask so
  the host can repair via its slow path — mirroring the dense design's
  general fast-path/slow-path split (SURVEY §7 hard part f).

``fused_step`` runs ``paxos_tick`` and the KV apply in ONE jitted program —
XLA fuses the gather/scatter chain with the tick's phase-4 extraction, so
"execute" costs one more fused elementwise pass over ``[R, W, G]``, not a
host round-trip per decision.
"""

from __future__ import annotations

import json
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.tick import TickInbox, paxos_tick_impl
from ..types import NO_REQUEST

OP_NONE = 0
OP_PUT = 1
OP_GET = 2
OP_DEL = 3

I32 = jnp.int32


class DeviceKVState(NamedTuple):
    """Dense app state + request-descriptor table (all device arrays)."""

    key: jnp.ndarray   # i32 [R, G, S]   stored key per slot (0 = empty)
    val: jnp.ndarray   # i32 [R, G, S]
    t_rid: jnp.ndarray  # i32 [T] descriptor table: registered rid (0 = none)
    t_op: jnp.ndarray   # i32 [T]
    t_key: jnp.ndarray  # i32 [T]
    t_val: jnp.ndarray  # i32 [T]

    @property
    def slots(self) -> int:
        return self.key.shape[2]

    @property
    def table(self) -> int:
        return self.t_rid.shape[0]


def init_kv(n_replicas: int, n_groups: int, slots: int = 16,
            table: int = 1 << 16) -> DeviceKVState:
    assert slots & (slots - 1) == 0 and table & (table - 1) == 0
    R, G = n_replicas, n_groups
    return DeviceKVState(
        key=jnp.zeros((R, G, slots), I32),
        val=jnp.zeros((R, G, slots), I32),
        t_rid=jnp.zeros((table,), I32),
        t_op=jnp.zeros((table,), I32),
        t_key=jnp.zeros((table,), I32),
        t_val=jnp.zeros((table,), I32),
    )


def register_requests(kv: DeviceKVState, rids, ops, keys, vals) -> DeviceKVState:
    """Upload request descriptors (host batch -> one scatter).  Clients call
    this before proposing the rids; collisions evict (the evicted request
    will execute as a miss and fall back to the host slow path)."""
    rids = jnp.asarray(rids, I32)
    idx = jnp.bitwise_and(rids, kv.table - 1)
    return kv._replace(
        t_rid=kv.t_rid.at[idx].set(rids),
        t_op=kv.t_op.at[idx].set(jnp.asarray(ops, I32)),
        t_key=kv.t_key.at[idx].set(jnp.asarray(keys, I32)),
        t_val=kv.t_val.at[idx].set(jnp.asarray(vals, I32)),
    )


def kv_apply(kv: DeviceKVState, exec_req: jnp.ndarray,
             exec_count: jnp.ndarray) -> Tuple[DeviceKVState, jnp.ndarray,
                                               jnp.ndarray]:
    """Vectorized execution of one tick's decision stream.

    exec_req: i32 [R, W, G] executed rids in window order (0 = none);
    exec_count: i32 [R, G].
    Returns (kv', responses i32 [R, W, G] — PUT echoes the value, GET/DEL
    return the pre-op value (0 = absent) — and miss bool [R, W, G]).

    Window plane j executes slot base+j, so planes apply in order: a
    ``lax.scan`` over the W axis (W is small and static) threads the store
    through the planes — each step is fully vectorized over [R, G], and XLA
    unrolls/fuses the short scan into the surrounding program.  This is the
    TPU idiom for the reference's in-order ``execute`` loop
    (PaxosInstanceStateMachine.java:1755-1842) with read-your-writes inside
    one tick's batch.
    """
    from jax import lax

    R, W, G = exec_req.shape
    S = kv.slots
    ji = jnp.arange(W, dtype=I32)
    valid = (exec_req != NO_REQUEST) & (ji[None, :, None] < exec_count[:, None, :])

    tix = jnp.bitwise_and(exec_req, kv.table - 1)  # [R, W, G]
    hit = valid & (kv.t_rid[tix] == exec_req)
    op = jnp.where(hit, kv.t_op[tix], OP_NONE)
    k = kv.t_key[tix]
    v = kv.t_val[tix]
    slot = jnp.bitwise_and(k, S - 1)  # [R, W, G]

    rr = jnp.arange(R, dtype=I32)[:, None]
    gg = jnp.arange(G, dtype=I32)[None, :]

    def plane(carry, xs):
        key_s, val_s = carry  # [R, G, S]
        op_j, k_j, v_j, slot_j = xs  # [R, G]
        cur_key = key_s[rr, gg, slot_j]
        cur_val = val_s[rr, gg, slot_j]
        present = cur_key == k_j
        resp = jnp.where(
            op_j == OP_PUT, v_j, jnp.where(present, cur_val, 0)
        )
        wr = (op_j == OP_PUT) | (op_j == OP_DEL)
        wslot = jnp.where(wr, slot_j, S)  # S -> drop
        nk = jnp.where(op_j == OP_DEL, 0, k_j)
        nv = jnp.where(op_j == OP_DEL, 0, v_j)
        key_s = key_s.at[rr, gg, wslot].set(nk, mode="drop")
        val_s = val_s.at[rr, gg, wslot].set(nv, mode="drop")
        return (key_s, val_s), resp

    xs = (op.transpose(1, 0, 2), k.transpose(1, 0, 2),
          v.transpose(1, 0, 2), slot.transpose(1, 0, 2))
    (key_s, val_s), resps = lax.scan(plane, (kv.key, kv.val), xs)
    responses = jnp.where(hit, resps.transpose(1, 0, 2), 0)
    kv2 = kv._replace(key=key_s, val=val_s)
    miss = valid & ~hit
    return kv2, responses, miss


def fused_step(state, kv: DeviceKVState, inbox: TickInbox, own_row: int = -1):
    """One consensus tick + device app execution in a single program."""
    new_state, out = paxos_tick_impl(state, inbox, own_row)
    kv2, responses, miss = kv_apply(kv, out.exec_req, out.exec_count)
    return new_state, kv2, out, responses, miss


fused_step_jit = jax.jit(fused_step, donate_argnums=(0, 1),
                         static_argnums=(3,))


class DeviceKVApp:
    """Replicable-shaped wrapper so the control plane can checkpoint /
    restore device KV groups (row-granular pulls; the hot path never calls
    ``execute`` — that is the whole point).

    ``row_of(name)`` maps service names to group rows (wire it to the
    manager's RowAllocator).
    """

    def __init__(self, kv: DeviceKVState, replica: int,
                 row_of=None):
        self.kv = kv
        self.replica = replica
        self.row_of = row_of or (lambda name: None)

    def execute(self, name: str, request: bytes, request_id: int) -> bytes:
        raise NotImplementedError(
            "device app decisions execute on-device via fused_step; the "
            "host slow path is only for descriptor misses"
        )

    def checkpoint(self, name: str) -> bytes:
        row = self.row_of(name)
        if row is None:
            return b""
        keys = np.asarray(self.kv.key[self.replica, row])
        vals = np.asarray(self.kv.val[self.replica, row])
        live = keys != 0
        return json.dumps({
            "k": keys[live].tolist(), "v": vals[live].tolist(),
        }).encode()

    def restore(self, name: str, state: bytes) -> None:
        row = self.row_of(name)
        if row is None:
            return
        S = self.kv.slots
        keys = np.zeros(S, np.int32)
        vals = np.zeros(S, np.int32)
        if state:
            d = json.loads(state.decode())
            for k, v in zip(d["k"], d["v"]):
                keys[k & (S - 1)] = k
                vals[k & (S - 1)] = v
        self.kv = self.kv._replace(
            key=self.kv.key.at[self.replica, row].set(jnp.asarray(keys)),
            val=self.kv.val.at[self.replica, row].set(jnp.asarray(vals)),
        )

"""Placement plane: demand-driven live migration of Paxos groups.

PR-1 sharded the data plane (parallel/shard_tick.py) but left nothing
balancing it: a hot groups-axis shard caps the whole mesh while cold shards
idle ("The Performance of Paxos in the Cloud" shape of collapse).  This
package closes the control loop:

* :mod:`counters`   — per-group demand as EWMA request-rate counters, folded
  on device inside the compaction dispatch (mesh path) or from host intake
  bookkeeping (everywhere else), reduced per shard;
* :mod:`rebalancer` — host-side hot/cold shard detection + greedy bin-pack
  migration plans, with hysteresis and min-interval guards mirroring the
  demand SPI's rate limits (reconfiguration/demand.py);
* :mod:`migrator`   — live row migration between shard ranges through the
  stop/start epoch protocol (reconfiguration/coordinator.py), journaled for
  deterministic WAL replay;
* :mod:`table`      — an explicit placement-override table layered over the
  consistent-hash ring, consulted by edge routing and serializable through
  the replicated reconfigurator DB (rc_db.py).

The decision plane runs host-side off dense device counters (the HT-Paxos
separation of load shedding from the consensus hot path); the data plane
never waits on it.
"""

from .counters import PlacementCounters
from .migrator import GroupMigrator, MigrationStats
from .rebalancer import MigrationPlan, ShardRebalancer
from .table import PLACEMENT_RECORD, PlacementTable

__all__ = [
    "PlacementCounters",
    "GroupMigrator",
    "MigrationStats",
    "MigrationPlan",
    "ShardRebalancer",
    "PlacementTable",
    "PLACEMENT_RECORD",
]

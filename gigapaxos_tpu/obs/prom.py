"""Prometheus text exposition (version 0.0.4) for :mod:`.metrics`.

Two renderers:

* :func:`render_registry` — one process's registry as scrape text, with
  optional ``extra_labels`` injected into every sample (a cell worker
  renders itself with ``cell="3"`` so the supervisor can concatenate).
* :func:`merge_scrapes` — concatenates already-rendered per-cell bodies
  under one host-level scrape, deduplicating ``# HELP`` / ``# TYPE``
  header lines (Prometheus rejects duplicate metadata).

Histograms emit the classic ``_bucket{le=}`` / ``_sum`` / ``_count``
families plus precomputed ``<name>_p50 / _p90 / _p99`` gauges so operators
get percentiles without server-side ``histogram_quantile``.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from .metrics import Counter, Gauge, Histogram, Registry


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labelstr(labels: Iterable[Tuple[str, str]],
              extra: Optional[Dict[str, str]] = None) -> str:
    items = list(labels)
    if extra:
        have = {k for k, _ in items}
        items += [(k, v) for k, v in extra.items() if k not in have]
    if not items:
        return ""
    return "{" + ",".join(
        f'{k}="{_esc(v)}"' for k, v in sorted(items)) + "}"


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_registry(reg: Registry,
                    extra_labels: Optional[Dict[str, str]] = None) -> str:
    """Registry -> Prometheus text; stable order (name, then labels)."""
    by_name: Dict[str, List[object]] = {}
    for m in reg.metrics():
        by_name.setdefault(m.name, []).append(m)

    out: List[str] = []
    for name in sorted(by_name):
        family = sorted(by_name[name], key=lambda m: m.labels)
        first = family[0]
        help_ = reg.help_text(name)
        if isinstance(first, Histogram):
            if help_:
                out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} histogram")
            for m in family:
                cum = 0
                # only emit buckets up to the highest occupied one (+inf
                # covers the rest); keeps 64-bucket families readable
                top = max((i for i, c in enumerate(m.buckets) if c),
                          default=-1)
                for i in range(top + 1):
                    cum += m.buckets[i]
                    le = _labelstr(m.labels, dict(extra_labels or {},
                                                  le=_fmt(m.bucket_upper(i))))
                    out.append(f"{name}_bucket{le} {cum}")
                inf = _labelstr(m.labels, dict(extra_labels or {}, le="+Inf"))
                out.append(f"{name}_bucket{inf} {m.count}")
                ls = _labelstr(m.labels, extra_labels)
                out.append(f"{name}_sum{ls} {repr(float(m.total))}")
                out.append(f"{name}_count{ls} {m.count}")
            for q, tag in ((0.50, "p50"), (0.90, "p90"), (0.99, "p99")):
                out.append(f"# TYPE {name}_{tag} gauge")
                for m in family:
                    ls = _labelstr(m.labels, extra_labels)
                    out.append(f"{name}_{tag}{ls} {repr(m.percentile(q))}")
        else:
            kind = "counter" if isinstance(first, Counter) else "gauge"
            if help_:
                out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {kind}")
            for m in family:
                ls = _labelstr(m.labels, extra_labels)
                out.append(f"{name}{ls} {_fmt(m.value)}")
    return "\n".join(out) + ("\n" if out else "")


def merge_scrapes(bodies: Iterable[str]) -> str:
    """Concatenate rendered scrape bodies, deduping # HELP/# TYPE lines."""
    seen_meta = set()
    out: List[str] = []
    for body in bodies:
        for line in body.splitlines():
            if line.startswith("# "):
                if line in seen_meta:
                    continue
                seen_meta.add(line)
            out.append(line)
    return "\n".join(out) + ("\n" if out else "")

"""WAL + recovery tests: crash/restart state parity via deterministic replay
(the analog of the reference's kill-and-recover testing around
``PaxosManager.initiateRecovery``, PaxosManager.java:1852-2055)."""

import os

import numpy as np

from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.paxos.manager import PaxosManager
from gigapaxos_tpu.wal.journal import PyJournal, read_journal
from gigapaxos_tpu.wal.logger import PaxosLogger, recover


def mk(tmp_path, ckpt_every=1024):
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 32
    apps = [KVApp() for _ in range(3)]
    wal = PaxosLogger(str(tmp_path), checkpoint_every_ticks=ckpt_every,
                      native=False)
    return cfg, apps, PaxosManager(cfg, 3, apps, wal=wal)


def drive(m, n_names=3, n_reqs=8):
    for g in range(n_names):
        m.create_paxos_instance(f"kv{g}", [0, 1, 2])
    for g in range(n_names):
        for i in range(n_reqs):
            m.propose(f"kv{g}", f"PUT k{i} {g}.{i}".encode())
    m.run_ticks(8)


def test_journal_roundtrip_and_torn_tail(tmp_path):
    p = str(tmp_path / "j.log")
    j = PyJournal(p)
    for i in range(5):
        j.append(f"rec{i}".encode())
    j.close()
    assert read_journal(p) == [f"rec{i}".encode() for i in range(5)]
    # simulate a crash mid-write: append garbage half-record
    with open(p, "ab") as f:
        f.write(b"\x63\x00\x00\x00\xde\xad")
    assert read_journal(p) == [f"rec{i}".encode() for i in range(5)]
    # reopening repairs the tear so new appends stay readable
    j2 = PyJournal(p)
    j2.append(b"after")
    j2.close()
    assert read_journal(p)[-1] == b"after"


def test_recovery_state_parity(tmp_path):
    cfg, apps, m = mk(tmp_path)
    drive(m)
    exec_before = np.array(m.state.exec_slot).copy()
    db_before = [dict(a.db) for a in apps]
    m.wal.close()  # crash

    apps2 = [KVApp() for _ in range(3)]
    m2 = recover(cfg, 3, apps2, str(tmp_path), native=False)
    assert np.array_equal(np.array(m2.state.exec_slot), exec_before)
    assert np.array_equal(np.array(m2.state.bal_num), np.array(m.state.bal_num))
    for r in range(3):
        assert apps2[r].db == db_before[r]
    # recovered manager keeps working and rid space does not collide
    done = []
    rid = m2.propose("kv0", b"PUT post 1", lambda _r, resp: done.append(resp))
    assert rid is not None and rid >= m._next_rid
    m2.run_ticks(3)
    assert done == [b"OK"]
    m2.wal.close()


def test_recovery_with_checkpoint_rollover(tmp_path):
    cfg, apps, m = mk(tmp_path, ckpt_every=4)  # checkpoint every 4 ticks
    drive(m, n_names=2, n_reqs=12)
    snaps = [f for f in os.listdir(tmp_path) if f.startswith("snapshot")]
    assert snaps, "expected at least one checkpoint"
    db_before = dict(apps[0].db)
    m.wal.close()

    apps2 = [KVApp() for _ in range(3)]
    m2 = recover(cfg, 3, apps2, str(tmp_path), native=False)
    assert apps2[0].db == db_before
    assert m2.tick_num == m.tick_num
    m2.wal.close()


def test_recovery_preserves_stop_state(tmp_path):
    cfg, apps, m = mk(tmp_path)
    m.create_paxos_instance("svc", [0, 1, 2])
    m.propose("svc", b"PUT a 1")
    m.propose_stop("svc")
    m.run_ticks(4)
    assert m.is_stopped("svc")
    m.wal.close()

    apps2 = [KVApp() for _ in range(3)]
    m2 = recover(cfg, 3, apps2, str(tmp_path), native=False)
    assert m2.is_stopped("svc")
    # stopped groups reject new work after recovery too (fail-fast None)
    got = []
    assert m2.propose("svc", b"PUT b 2", lambda r, resp: got.append(resp)) is None
    m2.run_ticks(3)
    assert got == [None]
    m2.wal.close()


def test_recovery_idempotent_double_crash(tmp_path):
    cfg, apps, m = mk(tmp_path)
    drive(m, n_names=1, n_reqs=5)
    m.wal.close()
    apps2 = [KVApp() for _ in range(3)]
    m2 = recover(cfg, 3, apps2, str(tmp_path), native=False)
    m2.propose("kv0", b"PUT x y")
    m2.run_ticks(2)
    db = dict(apps2[0].db)
    tick = m2.tick_num
    m2.wal.close()  # crash again
    apps3 = [KVApp() for _ in range(3)]
    m3 = recover(cfg, 3, apps3, str(tmp_path), native=False)
    assert apps3[0].db == db
    assert m3.tick_num == tick
    m3.wal.close()


def test_recovery_preserves_free_list_order(tmp_path):
    """Pause churn reorders the row free-list (LIFO); a checkpoint taken then
    must restore it verbatim, or journaled OP_UNPAUSE replay re-allocates
    different rows than the live run and row-addressed OP_TICK placements
    land on the wrong groups (silently losing committed writes)."""
    cfg, apps, m = mk(tmp_path)
    drive(m, n_names=3, n_reqs=2)  # kv0,kv1,kv2 on rows 0,1,2; quiescent
    m._sweep_outstanding()
    # free rows 0 then 1 -> free list tail is [..., 0, 1], next alloc pops 1
    m._do_pause(["kv0", "kv1"])
    m.wal.log_pause(["kv0", "kv1"])
    m.wal.checkpoint()
    # transparently unpauses kv0 -- live run places it on row 1
    done = []
    m.propose("kv0", b"PUT pk pv", lambda _r, resp: done.append(resp))
    m.run_ticks(3)
    assert done == [b"OK"]
    assert m.rows.row("kv0") == 1
    db_before = [dict(a.db) for a in apps]
    m.wal.close()  # crash after the PUT committed + was acked

    apps2 = [KVApp() for _ in range(3)]
    m2 = recover(cfg, 3, apps2, str(tmp_path), native=False)
    assert m2.rows.row("kv0") == 1  # same row as the live run
    for r in range(3):
        assert apps2[r].db == db_before[r]
    got = []
    m2.propose("kv0", b"GET pk", lambda _r, resp: got.append(resp))
    m2.run_ticks(3)
    assert got == [b"pv"]  # the committed PUT survived recovery
    m2.wal.close()


def test_native_journal_parity(tmp_path):
    """C++ journal writes the byte-identical format (shared reader), repairs
    torn tails, and interoperates with the Python writer."""
    import pytest

    try:
        from gigapaxos_tpu.wal.native_journal import NativeJournal
    except Exception:
        pytest.skip("native toolchain unavailable")
    p = str(tmp_path / "n.log")
    j = NativeJournal(p)
    recs = [b"a", b"bb" * 1000, b"", b"\x00\xff" * 7]
    for r in recs:
        j.append(r)
    j.sync()
    j.close()
    assert read_journal(p) == recs
    # tear + native reopen repairs
    with open(p, "ab") as f:
        f.write(b"\x10\x00\x00\x00bad")
    j2 = NativeJournal(p)
    j2.append(b"post-tear")
    j2.close()
    assert read_journal(p) == recs + [b"post-tear"]
    # python writer can continue the same file
    j3 = PyJournal(p)
    j3.append(b"py")
    j3.close()
    assert read_journal(p)[-1] == b"py"


def test_recovery_with_native_backend(tmp_path):
    import pytest

    try:
        from gigapaxos_tpu.wal.native_journal import NativeJournal  # noqa: F401
    except Exception:
        pytest.skip("native toolchain unavailable")
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 16
    apps = [KVApp() for _ in range(3)]
    wal = PaxosLogger(str(tmp_path), native=True)
    m = PaxosManager(cfg, 3, apps, wal=wal)
    m.create_paxos_instance("svc", [0, 1, 2])
    m.propose("svc", b"PUT k v")
    m.run_ticks(3)
    m.wal.close()
    apps2 = [KVApp() for _ in range(3)]
    m2 = recover(cfg, 3, apps2, str(tmp_path), native=True)
    assert apps2[0].db == apps[0].db
    m2.wal.close()

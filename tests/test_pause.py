"""Pause/spill (deactivation) tests.

Mirrors the reference's memory-scaling machinery (§3.5 of the survey:
``Deactivator`` PaxosManager.java:2951, ``pause`` :2284-2365, ``unpause``
:2370-2412, ``HotRestoreInfo`` paxosutil/HotRestoreInfo.java:31-69): cold
groups spill ~9 scalars per replica to host RAM, their device rows are
recycled, and any touch transparently restores them — which is what lets a
node hold far more groups than device rows.
"""

import numpy as np
import pytest

from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.paxos.manager import PaxosManager


def mk(G=8, deactivation=0):
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = G
    cfg.paxos.deactivation_ticks = deactivation
    return PaxosManager(cfg, 3, [KVApp() for _ in range(3)])


def run_until(mgr, pred, max_ticks=200):
    for _ in range(max_ticks):
        mgr.tick()
        if pred():
            return True
    return pred()


def test_pause_and_transparent_unpause():
    mgr = mk()
    mgr.create_paxos_instance("a", [0, 1, 2])
    got = {}
    mgr.propose("a", b"PUT k v", lambda r, v: got.update({"r": v}))
    assert run_until(mgr, lambda: "r" in got)
    before = mgr.exec_watermarks("a").copy()
    assert mgr.pause_idle(limit=8) == 1
    assert mgr.paused_count() == 1 and mgr.rows.row("a") is None
    # reads work while paused (served from the spill)
    assert mgr.group_members("a") == [0, 1, 2]
    np.testing.assert_array_equal(mgr.exec_watermarks("a"), before)
    # touching the name unpauses it and consensus continues where it left off
    got2 = {}
    mgr.propose("a", b"GET k", lambda r, v: got2.update({"r": v}))
    assert run_until(mgr, lambda: "r" in got2)
    assert got2["r"] == b"v"
    assert mgr.paused_count() == 0
    np.testing.assert_array_equal(mgr.exec_watermarks("a"), before + 1)


def test_busy_group_not_pausable():
    mgr = mk()
    mgr.create_paxos_instance("busy", [0, 1, 2])
    mgr.propose("busy", b"PUT a 1", None)  # queued, not yet committed
    assert mgr.pause_idle(limit=8) == 0


def test_stopped_flag_survives_pause():
    mgr = mk()
    mgr.create_paxos_instance("s", [0, 1, 2])
    done = {}
    mgr.propose_stop("s", callback=lambda r, v: done.update({"r": v}))
    assert run_until(mgr, lambda: "r" in done)
    assert mgr.is_stopped("s")
    assert mgr.pause_idle(limit=8) == 1
    assert mgr.is_stopped("s")  # visible while spilled
    assert mgr.propose("s", b"PUT x 1", None) is None  # still fenced


def test_more_groups_than_rows():
    """The point of the machinery: G=8 device rows hosting 24 groups, with
    eviction keeping the working set resident."""
    mgr = mk(G=8)
    N = 24
    got = {}
    for i in range(N):
        assert mgr.create_paxos_instance(f"g{i}", [0, 1, 2])
        mgr.propose(f"g{i}", f"PUT k {i}".encode(),
                    lambda r, v, i=i: got.update({i: v}))
        assert run_until(mgr, lambda i=i: i in got, max_ticks=60)
    assert len(got) == N and all(v == b"OK" for v in got.values())
    assert mgr.paused_count() == N - len(mgr.rows)
    assert mgr.paused_count() >= N - 8
    # every group still readable: unpause on demand, state intact
    got2 = {}
    for i in range(N):
        mgr.propose(f"g{i}", b"GET k", lambda r, v, i=i: got2.update({i: v}))
        assert run_until(mgr, lambda i=i: i in got2, max_ticks=60)
        assert got2[i] == str(i).encode(), f"g{i}"


def test_periodic_deactivator_in_tick():
    mgr = mk(deactivation=10)
    mgr.create_paxos_instance("cold", [0, 1, 2])
    got = {}
    mgr.propose("cold", b"PUT k v", lambda r, v: got.update({"r": v}))
    assert run_until(mgr, lambda: "r" in got)
    # run past the idle threshold and the 256-tick deactivator period
    mgr.run_ticks(300)
    assert mgr.paused_count() == 1


def test_pause_wal_replay(tmp_path):
    """Row allocation must stay in lockstep across recovery when pause and
    unpause reshuffled rows mid-journal."""
    from gigapaxos_tpu.wal import PaxosLogger, recover

    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 4
    d = str(tmp_path / "pwal")
    mgr = PaxosManager(cfg, 3, [KVApp() for _ in range(3)],
                       wal=PaxosLogger(d))
    got = {}
    for i in range(6):  # 6 groups > 4 rows: forces eviction mid-journal
        mgr.create_paxos_instance(f"g{i}", [0, 1, 2])
        mgr.propose(f"g{i}", f"PUT k {i}".encode(),
                    lambda r, v, i=i: got.update({i: v}))
        assert run_until(mgr, lambda i=i: i in got, max_ticks=60)
    mgr.wal.close()

    m2 = recover(cfg, 3, [KVApp() for _ in range(3)], d)
    for i in range(6):
        got2 = {}
        m2.propose(f"g{i}", b"GET k", lambda r, v: got2.update({"r": v}))
        assert run_until(m2, lambda: "r" in got2, max_ticks=60)
        assert got2["r"] == str(i).encode(), f"g{i}"
    m2.wal.close()


def test_snapshot_while_paused_recovers(tmp_path):
    """A checkpoint taken while groups are spilled must carry the spill
    store and their app state (losing them once the journal is GC'd would
    be unrecoverable)."""
    from gigapaxos_tpu.wal import PaxosLogger, recover

    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 8
    cfg.paxos.deactivation_ticks = 0
    d = str(tmp_path / "psnap")
    mgr = PaxosManager(cfg, 3, [KVApp() for _ in range(3)],
                       wal=PaxosLogger(d))
    got = {}
    mgr.create_paxos_instance("cold", [0, 1, 2])
    mgr.propose("cold", b"PUT k frozen", lambda r, v: got.update({"r": v}))
    assert run_until(mgr, lambda: "r" in got)
    assert mgr.pause_idle(limit=8) == 1
    mgr.wal.checkpoint()  # snapshot with the group spilled; journal rolled+GC'd
    mgr.wal.close()

    m2 = recover(cfg, 3, [KVApp() for _ in range(3)], d)
    assert m2.paused_count() == 1
    got2 = {}
    m2.propose("cold", b"GET k", lambda r, v: got2.update({"r": v}))
    assert run_until(m2, lambda: "r" in got2)
    assert got2["r"] == b"frozen"
    m2.wal.close()


def test_remove_with_inflight_frees_row_counter():
    """Removing a group with placed-but-unexecuted requests must not wedge
    the recycled row's outstanding counter (which would make it forever
    unpausable)."""
    mgr = mk(G=4)
    mgr.create_paxos_instance("x", [0, 1, 2])
    fails = {}
    mgr.propose("x", b"PUT a 1", lambda r, v: fails.update({"cb": (r, v)}))
    mgr.tick()  # place it so it leaves the queue
    row = mgr.rows.row("x")
    mgr.remove_paxos_instance("x")
    mgr.tick()
    assert mgr._row_outstanding[row] == 0
    assert not mgr.outstanding
    # the recycled row is pausable again
    mgr.create_paxos_instance("y", [0, 1, 2])
    got = {}
    mgr.propose("y", b"PUT b 2", lambda r, v: got.update({"r": v}))
    assert run_until(mgr, lambda: "r" in got)
    assert mgr.pause_idle(limit=8) == 1

"""The reconfigurator database — itself replicated on the data plane.

Reference analogs:

* ``AbstractReconfiguratorDB.java:77`` — application semantics over
  per-name :class:`ReconfigurationRecord`s, driven by deterministic
  RCRecordRequest commands;
* ``RepliconfigurableReconfiguratorDB.java:54`` — wraps that DB in a
  ``PaxosReplicaCoordinator`` so reconfigurator state is itself
  paxos-replicated ("the control plane runs *on* the data plane",
  SURVEY §3.4);
* ``SQLReconfiguratorDB.java:93`` — durability, which here falls out of the
  data plane's own WAL (commands are replayed into the DB app on recovery).

Design: :class:`ReconfiguratorDB` is a :class:`Replicable` whose requests
are JSON commands (create / delete_intent / delete_complete /
reconfigure_intent / reconfigure_complete).  One DB replica lives on each
reconfigurator node; commands commit through the RC nodes' own
:class:`PaxosManager`, one paxos group per consistent-hash RC group —
exactly the reference's RC group scheme (``ConsistentHashing.java:40-64``),
so a name's record is replicated on the k reconfigurators that own it.

Each DB replica invokes ``listener(command, record_dict)`` after applying a
command, which is how a Reconfigurator learns about commits it did not
propose (the basis of primary-failover, WaitPrimaryExecution.java:60).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Dict, List, Optional

from ..models.replicable import Replicable
from ..paxos.manager import PaxosManager
from .consistent_hashing import ConsistentHashRing
from .records import RCState, ReconfigurationRecord

#: paxos-group-name prefix for RC-group instances
RC_GROUP_PREFIX = "_RC:"
#: the special node-config record/group replicated on ALL reconfigurators
#: (the reference's AbstractReconfiguratorDB.RecordNames.AR_NODES)
NC_RECORD = "_NC"
#: the reconfigurator-pool record (RecordNames.RC_NODES — RC-node
#: add/remove at runtime, Reconfigurator.handleReconfigureRCNodeConfig:1044)
NC_RC_RECORD = "_NC_RC"


class ReconfiguratorDB(Replicable):
    """One reconfigurator node's replica of the record database.

    ``execute`` is deterministic over (records, command) — every replica of
    an RC group derives identical records from the committed command stream.
    Non-state inputs (wall time for delete aging) ride inside the command.
    """

    def __init__(self, node_id: str = "?"):
        self.node_id = node_id
        self.records: Dict[str, ReconfigurationRecord] = {}
        #: deleted names -> their final epoch (reincarnation tombstones).
        #: A recreate continues at tombstone+1 so the OLD incarnation's
        #: still-in-flight DropEpoch (async AR-side GC) can never address —
        #: and destroy — the new incarnation's data-plane group.  The
        #: reference retains deleted records for MAX_FINAL_STATE the same
        #: way.  Applied inside the replicated command stream, so every RC
        #: replica derives identical epochs.  Never evicted: any
        #: size-triggered eviction order would depend on how THIS node's
        #: several RC groups' command streams interleaved locally —
        #: non-deterministic across replicas — and a tombstone is ~50
        #: bytes per deleted name ever (recreates reclaim theirs).
        self.tombstones: Dict[str, int] = {}
        self._lock = threading.RLock()
        #: called (command_dict, record_dict_or_none) after each apply
        self.listener: Optional[Callable[[dict, Optional[dict]], None]] = None
        #: scope(service_name, paxos_group_name) -> bool; installed by
        #: RepliconfigurableReconfiguratorDB so checkpoint/restore of one RC
        #: paxos group only touches the records that group owns (a node in
        #: several RC groups must not clobber one group's records with a
        #: checkpoint of another's)
        self.scope: Optional[Callable[[str, str], bool]] = None

    # ----------------------------------------------------------- inspection
    def get(self, name: str) -> Optional[ReconfigurationRecord]:
        with self._lock:
            return self.records.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(n for n in self.records if n != NC_RECORD)

    # ------------------------------------------------------------ Replicable
    def execute(self, name: str, request: bytes, request_id: int) -> bytes:
        cmd = json.loads(request.decode())
        with self._lock:
            result = self._apply(cmd)
        if self.listener is not None:
            rec = self.get(cmd.get("name", ""))
            try:
                self.listener(cmd, rec.to_dict() if rec is not None else None)
            except Exception:
                # a listener bug must not poison the deterministic apply
                # (execute runs on the data-plane tick thread) — but it must
                # be visible: it can silently disable failover watchdogs
                logging.getLogger("gigapaxos_tpu.rc_db").exception(
                    "DB listener failed on %s", cmd.get("op")
                )
        return json.dumps(result).encode()

    def _apply(self, cmd: dict) -> dict:
        op = cmd["op"]
        name = cmd["name"]
        rec = self.records.get(name)
        if op in ("add_rc", "remove_rc"):
            if name != NC_RC_RECORD:
                return {"ok": False, "error": "nc_rc_only"}
            if rec is None:
                rec = self.records[name] = ReconfigurationRecord(
                    name=name, actives=sorted(cmd.get("seed_pool", []))
                )
            node = cmd["node"]
            pool = set(rec.actives)  # the RC pool rides the actives field
            if op == "add_rc":
                pool.add(node)
            else:
                pool.discard(node)
                min_pool = int(cmd.get("min_pool", 1))
                if len(pool) < min_pool:
                    return {"ok": False, "error": "pool_too_small",
                            "pool": rec.actives}
            rec.actives = sorted(pool)
            rec.epoch += 1
            return {"ok": True, "pool": rec.actives, "epoch": rec.epoch}
        if op == "tombstone_install":
            # idempotent tombstone carry-over into a re-homed RC group (the
            # record_install twin for deleted names)
            if rec is not None:
                # the name was recreated meanwhile: its live record already
                # supersedes the tombstone
                return {"ok": True, "installed": False}
            ep = int(cmd["epoch"])
            if self.tombstones.get(name, -1) < ep:
                self.tombstones[name] = ep
            return {"ok": True, "installed": True}
        if op == "record_install":
            # idempotent record carry-over into a re-homed RC group after a
            # ring splice (the reference re-hashes record ownership the same
            # way when RC nodes change, Reconfigurator.java:1044)
            incoming = ReconfigurationRecord.from_dict(cmd["record"])
            if rec is not None and rec.epoch >= incoming.epoch:
                return {"ok": True, "installed": False, "epoch": rec.epoch}
            self.records[name] = incoming
            return {"ok": True, "installed": True, "epoch": incoming.epoch}
        if op in ("add_active", "remove_active"):
            if name != NC_RECORD:
                # node-config ops are only valid on the NC record; applied to
                # a service record they would desync its epoch from the live
                # paxos group and brick the name
                return {"ok": False, "error": "nc_only"}
            # node-config change on the NC record: rec.actives is the active
            # POOL (ReconfigureActiveNodeConfig analog); per-name membership
            # changes flow as ordinary reconfigurations afterwards
            if rec is None:
                # first NC change: seed the pool with the boot topology
                # (carried in the committed command so every replica derives
                # the identical record)
                rec = self.records[name] = ReconfigurationRecord(
                    name=name, actives=sorted(cmd.get("seed_pool", []))
                )
            node = cmd["node"]
            pool = set(rec.actives)
            if not rec.universe:
                # seed the ordered slot universe from the boot topology
                # (sorted — every node derives the same boot order)
                rec.universe = sorted(cmd.get("seed_pool", rec.actives))
            if op == "add_active":
                if (node not in rec.universe
                        and len(rec.universe) >= (1 << 6)):
                    # the rid encoding carries the replica slot in 6 bits
                    # (modeb/common.py RID_SHIFT): reject HERE, inside the
                    # totally ordered apply, or the commit would succeed
                    # while every data plane refuses to expand
                    return {"ok": False, "error": "universe_full",
                            "pool": rec.actives}
                pool.add(node)
                if node not in rec.universe:
                    # replica-slot order is append-only and totally ordered
                    # by this commit stream: Mode B universes derive their
                    # slot indices from it (expand_universe appends)
                    rec.universe.append(node)
            else:
                pool.discard(node)
                # the shrink invariant must hold HERE, inside the totally
                # ordered apply — the RC-side pre-check is only advisory
                # (two concurrent removals can each pass it)
                min_pool = int(cmd.get("min_pool", 0))
                if len(pool) < min_pool:
                    return {"ok": False, "error": "pool_too_small",
                            "pool": rec.actives}
                # the node leaves the placement POOL but its slot is never
                # recycled (a re-add reuses the same slot index)
            rec.actives = sorted(pool)
            rec.epoch += 1  # NC epoch counts config versions
            return {"ok": True, "pool": rec.actives, "epoch": rec.epoch,
                    "universe": list(rec.universe)}
        if op in ("placement_set", "placement_clear",
                  "placement_set_cell", "placement_clear_cell"):
            # placement-override table (placement/table.py): overrides ride
            # the special _PLACEMENT record's rc_epochs map, so they are
            # replicated/checkpointed like every other record.  Import is
            # deferred: reconfiguration.__init__ imports this module, and
            # placement.table imports consistent_hashing back from it.
            from ..placement.table import (PLACEMENT_RECORD,
                                           apply_placement_command)

            if name != PLACEMENT_RECORD:
                return {"ok": False, "error": "placement_record_only"}
            return apply_placement_command(
                self.records, cmd,
                lambda n: ReconfigurationRecord(name=n),
            )
        if op == "create":
            if rec is not None:
                return {"ok": False, "error": "exists", "epoch": rec.epoch}
            rec = ReconfigurationRecord(
                name=name,
                epoch=max(int(cmd.get("epoch", 0)),
                          self.tombstones.pop(name, -1) + 1),
                actives=sorted(cmd["actives"]),
            )
            self.records[name] = rec
            # side-channel for the commit LISTENER (same decoded dict): the
            # backup creation drivers must fire ONLY for names this command
            # actually created — an "exists" name's record belongs to a
            # live (possibly reconfigured) incarnation that a stale-state
            # creation StartEpoch would clobber
            cmd["_created"] = {name: rec.epoch}
            return {"ok": True, "epoch": rec.epoch}
        if op == "create_batch":
            # one committed command creates every record of the batch
            # (BatchedCreateServiceName.java applied atomically per RC group)
            results = {}
            created = {}
            for c in cmd.get("creates", []):
                n = c["name"]
                if n in self.records:
                    results[n] = {"ok": False, "error": "exists",
                                  "epoch": self.records[n].epoch}
                else:
                    ep = self.tombstones.pop(n, -1) + 1
                    self.records[n] = ReconfigurationRecord(
                        name=n, epoch=ep, actives=sorted(c["actives"]),
                    )
                    results[n] = {"ok": True, "epoch": ep}
                    created[n] = ep
            cmd["_created"] = created  # see the "create" op's note
            return {"ok": True, "results": results}
        if rec is None:
            return {"ok": False, "error": "unknown"}
        if op == "reconfigure_intent":
            # READY -> WAIT_ACK_STOP (RCRecordRequest RECONFIGURATION_INTENT)
            ok = rec.set_intent(cmd["new_actives"])
            return {"ok": ok, "epoch": rec.epoch,
                    "state": rec.state.value}
        if op == "reconfigure_complete":
            # WAIT_ACK_STOP -> READY @ epoch+1 (RECONFIGURATION_COMPLETE);
            # guarded so duplicate completes (failover re-runs) are no-ops
            if rec.state != RCState.WAIT_ACK_STOP or (
                rec.epoch != int(cmd["epoch"])
            ):
                return {"ok": False, "error": "wrong_state",
                        "state": rec.state.value, "epoch": rec.epoch}
            ok = rec.set_complete()
            return {"ok": ok, "epoch": rec.epoch}
        if op == "delete_intent":
            ok = rec.set_delete_intent(now=cmd.get("now"))
            return {"ok": ok, "state": rec.state.value, "epoch": rec.epoch}
        if op == "delete_complete":
            if rec.state != RCState.WAIT_DELETE:
                return {"ok": False, "error": "wrong_state",
                        "state": rec.state.value}
            self.tombstones[name] = rec.epoch
            del self.records[name]
            return {"ok": True}
        return {"ok": False, "error": f"bad op {op}"}

    def _in_scope(self, service_name: str, group_name: str) -> bool:
        return self.scope is None or self.scope(service_name, group_name)

    def checkpoint(self, name: str) -> bytes:
        with self._lock:
            return json.dumps({
                "__rcdb__": 2,
                "recs": {
                    n: r.to_dict() for n, r in self.records.items()
                    if self._in_scope(n, name)
                },
                "tombs": {
                    n: e for n, e in self.tombstones.items()
                    if self._in_scope(n, name)
                },
            }).encode()

    def restore(self, name: str, state: bytes) -> None:
        with self._lock:
            kept = {
                n: r for n, r in self.records.items()
                if not self._in_scope(n, name)
            }
            kept_t = {
                n: e for n, e in self.tombstones.items()
                if not self._in_scope(n, name)
            }
            if state:
                d = json.loads(state.decode())
                if isinstance(d, dict) and d.get("__rcdb__") == 2:
                    recs, tombs = d["recs"], d.get("tombs", {})
                else:  # pre-tombstone checkpoint: flat record map
                    recs, tombs = d, {}
                kept.update({
                    n: ReconfigurationRecord.from_dict(rd)
                    for n, rd in recs.items()
                })
                kept_t.update({n: int(e) for n, e in tombs.items()})
            self.records = kept
            self.tombstones = kept_t


class RepliconfigurableReconfiguratorDB:
    """The commit path: one shared RC-side PaxosManager whose replica slots
    are the reconfigurator nodes and whose apps are their DB replicas.

    RC paxos groups are created lazily per consistent-hash group (the
    reference creates them eagerly at boot from the ring,
    RepliconfigurableReconfiguratorDB.java:54); group ``_RC:A:B:C`` has
    members {A,B,C}.  ``commit`` proposes a command to the group owning the
    name and fires ``callback(result_dict)`` when it executes on the
    proposer's DB replica.
    """

    def __init__(
        self,
        manager: PaxosManager,
        rc_ids: List[str],
        k: int = 3,
    ):
        self.manager = manager
        self.rc_ids = sorted(rc_ids)
        self._slot = {n: i for i, n in enumerate(self.rc_ids)}
        self.ring = ConsistentHashRing(self.rc_ids)
        self.k = min(k, len(self.rc_ids))
        for app in manager.apps:
            if isinstance(app, ReconfiguratorDB):
                app.scope = (
                    lambda sname, gname: self._pax_group(self.rc_group_of(sname))
                    == gname
                )

    # ---------------------------------------------------------------- groups
    def rc_group_of(self, name: str) -> List[str]:
        """The k reconfigurators owning ``name`` (its RC group).  The
        node-config records are replicated on ALL reconfigurators (the
        reference's RC_NODES/AR_NODES groups span every RC,
        ReconfigurableNode.java:180-188)."""
        if name in (NC_RECORD, NC_RC_RECORD):
            return list(self.rc_ids)
        return self.ring.replicated_servers(name, self.k)

    def primary_of(self, name: str) -> str:
        return self.rc_group_of(name)[0]

    def _pax_group(self, rcs: List[str]) -> str:
        return RC_GROUP_PREFIX + ":".join(sorted(rcs))

    def _ensure_group(self, rcs: List[str]) -> str:
        gname = self._pax_group(rcs)
        slots = [self._slot[r] for r in rcs]
        self.manager.create_paxos_instance(gname, slots)  # idempotent (False if exists)
        return gname

    # ---------------------------------------------------------------- commit
    def commit(
        self,
        name: str,
        cmd: dict,
        callback: Optional[Callable[[dict], None]] = None,
        proposer: Optional[str] = None,
    ) -> Optional[int]:
        """Paxos-commit one record command for ``name``; the callback gets
        the decoded result dict (or ``{"ok": False, "error": "failed"}``)."""
        gname = self._ensure_group(self.rc_group_of(name))
        entry = self._slot.get(proposer) if proposer else None

        def cb(rid: int, resp: Optional[bytes]) -> None:
            if callback is None:
                return
            if resp is None:
                callback({"ok": False, "error": "failed"})
            else:
                callback(json.loads(resp.decode()))

        return self.manager.propose(
            gname, json.dumps(cmd).encode(),
            cb if callback is not None else None, entry=entry,
        )

    def db_of(self, rc_id: str) -> ReconfiguratorDB:
        return self.manager.apps[self._slot[rc_id]]

    # ------------------------------------------------- RC-node elasticity
    def bind_rc(self, node_id: str) -> Optional[int]:
        """Bind a new reconfigurator id to a spare RC-plane replica slot
        (the manager must have been provisioned with spare slots)."""
        if node_id in self._slot:
            return self._slot[node_id]
        used = set(self._slot.values())
        for s in range(self.manager.R):
            if s not in used:
                self._slot[node_id] = s
                app = self.manager.apps[s]
                if isinstance(app, ReconfiguratorDB):
                    app.node_id = node_id
                    app.scope = (
                        lambda sname, gname:
                        self._pax_group(self.rc_group_of(sname)) == gname
                    )
                return s
        return None

    def unbind_rc(self, node_id: str) -> Optional[int]:
        return self._slot.pop(node_id, None)

    def update_pool(self, pool: List[str]) -> None:
        """Splice the consistent-hash ring to a committed RC pool.  Slots
        for departed nodes stay bound until ``unbind_rc`` so in-flight
        commits through old groups still resolve."""
        self.rc_ids = sorted(pool)
        self.ring = ConsistentHashRing(self.rc_ids)

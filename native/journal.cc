// Append-only CRC32-framed journal — native backend.
//
// The performance-critical half of the WAL (the analog of the reference's
// Journaler append path, SQLPaxosLogger.java:965-1076, which it keeps fast by
// batching and fsyncing off the critical thread).  Format matches
// gigapaxos_tpu/wal/journal.py exactly:
//   file  := MAGIC ("GPTPUJ01") record*
//   record:= u32 len | u32 crc32(payload) | payload        (little-endian)
// A torn tail is truncated on open so appends after a crash stay readable.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).  Appends are
// buffered in user space; gpj_sync() flushes + fdatasyncs (group commit).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <fcntl.h>
#include <unistd.h>
#include <zlib.h>

namespace {

constexpr char kMagic[8] = {'G', 'P', 'T', 'P', 'U', 'J', '0', '1'};
constexpr size_t kBufCap = 1 << 20;  // 1 MiB append buffer

struct Journal {
  int fd = -1;
  uint8_t* buf = nullptr;
  size_t buf_len = 0;
};

bool write_all(int fd, const uint8_t* p, size_t n) {
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool flush_buf(Journal* j) {
  if (j->buf_len == 0) return true;
  if (!write_all(j->fd, j->buf, j->buf_len)) return false;
  j->buf_len = 0;
  return true;
}

// Scan an existing journal; return the byte length of the intact prefix.
off_t valid_length(int fd) {
  char magic[sizeof(kMagic)];
  if (::pread(fd, magic, sizeof(magic), 0) != (ssize_t)sizeof(magic) ||
      memcmp(magic, kMagic, sizeof(kMagic)) != 0) {
    return 0;
  }
  off_t pos = sizeof(kMagic);
  off_t end = ::lseek(fd, 0, SEEK_END);
  uint8_t hdr[8];
  uint8_t* payload = static_cast<uint8_t*>(malloc(kBufCap));
  size_t payload_cap = kBufCap;
  while (pos + 8 <= end) {
    if (::pread(fd, hdr, 8, pos) != 8) break;
    uint32_t len, crc;
    memcpy(&len, hdr, 4);
    memcpy(&crc, hdr + 4, 4);
    if (pos + 8 + (off_t)len > end) break;
    if (len > payload_cap) {
      uint8_t* grown = static_cast<uint8_t*>(realloc(payload, len));
      if (grown == nullptr) break;  // treat as tear; recovery must not crash
      payload = grown;
      payload_cap = len;
    }
    if (::pread(fd, payload, len, pos + 8) != (ssize_t)len) break;
    if (crc32(0, payload, len) != crc) break;
    pos += 8 + (off_t)len;
  }
  free(payload);
  return pos;
}

}  // namespace

extern "C" {

void* gpj_open(const char* path) {
  int fd = ::open(path, O_RDWR | O_CREAT, 0644);
  if (fd < 0) return nullptr;
  off_t size = ::lseek(fd, 0, SEEK_END);
  if (size > 0) {
    off_t good = valid_length(fd);
    if (good == 0) {
      // not our file / empty-magic: rewrite from scratch
      if (::ftruncate(fd, 0) != 0) { ::close(fd); return nullptr; }
      size = 0;
    } else if (good < size) {
      if (::ftruncate(fd, good) != 0) { ::close(fd); return nullptr; }
    }
    ::lseek(fd, 0, SEEK_END);
  }
  if (size == 0) {
    if (!write_all(fd, reinterpret_cast<const uint8_t*>(kMagic),
                   sizeof(kMagic))) {
      ::close(fd);
      return nullptr;
    }
  }
  Journal* j = new Journal();
  j->fd = fd;
  j->buf = static_cast<uint8_t*>(malloc(kBufCap));
  return j;
}

int gpj_append(void* h, const uint8_t* data, uint32_t len) {
  Journal* j = static_cast<Journal*>(h);
  uint32_t crc = crc32(0, data, len);
  uint8_t hdr[8];
  memcpy(hdr, &len, 4);
  memcpy(hdr + 4, &crc, 4);
  if (8 + (size_t)len > kBufCap - j->buf_len) {
    if (!flush_buf(j)) return -1;
  }
  if (8 + (size_t)len > kBufCap) {  // oversized record: write through
    if (!write_all(j->fd, hdr, 8) || !write_all(j->fd, data, len)) return -1;
    return 0;
  }
  memcpy(j->buf + j->buf_len, hdr, 8);
  memcpy(j->buf + j->buf_len + 8, data, len);
  j->buf_len += 8 + len;
  return 0;
}

int gpj_sync(void* h) {
  Journal* j = static_cast<Journal*>(h);
  if (!flush_buf(j)) return -1;
  return ::fdatasync(j->fd);
}

void gpj_close(void* h) {
  Journal* j = static_cast<Journal*>(h);
  if (j == nullptr) return;
  flush_buf(j);
  ::fdatasync(j->fd);
  ::close(j->fd);
  free(j->buf);
  delete j;
}

}  // extern "C"

"""The scrape endpoint: a tiny threaded HTTP server (stdlib only).

Routes (every route served here MUST be listed in this docstring —
tests/test_obs_coverage.py enforces it):

* ``GET /metrics``       -> Prometheus text (the ``scrape`` callback)
* ``GET /trace/<tid>``   -> JSON timeline for one trace id (``trace`` cb)
* ``GET /trace``         -> JSON list of recent trace ids
* ``GET /flight``        -> JSON flight-recorder ring (``flight`` cb)
* ``GET /healthz``       -> readiness probe: 200 while ticking, 503 when
  the WAL is stickily failed or the node is draining (``healthz`` cb)
* ``GET /health``        -> JSON group-health summary: gauges, log2
  histograms, top-K stuck/churny/hot groups (``health`` cb)
* ``GET /group/<name>``  -> JSON single-group drill-down (``group`` cb;
  404 when the group is not resident)
* ``GET /timeline``      -> JSON scenario timeline: metric series vs wall
  clock with event annotations (``timeline`` cb)

Every route also answers ``HEAD`` (same status/headers, no body).

Bound to ``127.0.0.1`` by default — operators front it with their own
ingress; port 0 picks an ephemeral port (tests), ``.port`` reports it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


class MetricsServer:
    def __init__(self, scrape: Callable[[], str],
                 trace: Optional[Callable[[Optional[str]], object]] = None,
                 flight: Optional[Callable[[], object]] = None,
                 healthz: Optional[Callable[[], dict]] = None,
                 health: Optional[Callable[[], object]] = None,
                 group: Optional[Callable[[str], object]] = None,
                 timeline: Optional[Callable[[], object]] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        self._scrape = scrape
        self._trace = trace
        self._flight = flight
        self._healthz = healthz
        self._health = health
        self._group = group
        self._timeline = timeline
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no stderr chatter
                pass

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(data)

            def _json(self, obj, code: int = 200) -> None:
                self._send(code, json.dumps(obj), "application/json")

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0].rstrip("/") or "/"
                    if path == "/metrics":
                        self._send(200, outer._scrape(),
                                   "text/plain; version=0.0.4")
                    elif path == "/trace" and outer._trace is not None:
                        self._json(outer._trace(None))
                    elif (path.startswith("/trace/")
                          and outer._trace is not None):
                        tid = path[len("/trace/"):]
                        self._json(outer._trace(tid))
                    elif path == "/flight" and outer._flight is not None:
                        self._json(outer._flight())
                    elif path == "/healthz" and outer._healthz is not None:
                        # readiness contract: 200 iff the node can make
                        # progress — a stickily failed WAL or a draining
                        # node answers 503 so balancers/supervisors stop
                        # routing to it while it still serves diagnostics
                        doc = outer._healthz()
                        self._json(doc, 200 if doc.get("ok") else 503)
                    elif path == "/health" and outer._health is not None:
                        doc = outer._health()
                        if doc is None:
                            self._json({"error": "health fold off"}, 404)
                        else:
                            self._json(doc)
                    elif (path.startswith("/group/")
                          and outer._group is not None):
                        name = path[len("/group/"):]
                        doc = outer._group(name)
                        if doc is None:
                            self._json({"error": "no such group"}, 404)
                        else:
                            self._json(doc)
                    elif path == "/timeline" and outer._timeline is not None:
                        self._json(outer._timeline())
                    else:
                        self._send(404, "not found\n", "text/plain")
                except BrokenPipeError:
                    pass
                except Exception as e:  # a broken source must not kill serve
                    try:
                        self._send(500, f"{type(e).__name__}: {e}\n",
                                   "text/plain")
                    except Exception:
                        pass

            # HEAD mirrors GET byte-for-byte in status and headers; _send
            # suppresses the body when self.command == "HEAD"
            do_HEAD = do_GET

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name=f"metrics-http:{self.port}", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=2)

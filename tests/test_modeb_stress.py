"""Mode B safety valves under stress: link latency, mass-laggard rejoin,
anti-entropy cost at scale.

Round-2 verdict items: failover tests all ran at loopback RTT (the
reference emulates WAN delays, ``nio/JSONDelayEmulator.java:39-77``); the
mass-laggard path (a fresh node joining a busy cluster with many groups)
was untested; anti-entropy traffic was unmeasured.  All three run here over
the deterministic ``SimNet``.
"""

import sys

import numpy as np
import pytest

from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.modeb import ModeBNode
from gigapaxos_tpu.testing.simnet import SimNet

IDS = ["N0", "N1", "N2"]


def make_cfg(groups, window=8):
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = groups
    cfg.paxos.window = window
    return cfg


class SimCluster:
    def __init__(self, groups=16, anti_entropy_every=16, delay=0):
        self.net = SimNet()
        self.net.default_delay = delay
        cfg = make_cfg(groups)
        self.cfg = cfg
        self.apps = {nid: KVApp() for nid in IDS}
        self.nodes = {
            nid: ModeBNode(cfg, IDS, nid, self.apps[nid],
                           self.net.messenger(nid),
                           anti_entropy_every=anti_entropy_every)
            for nid in IDS
        }

    def create(self, name, only=None):
        for nid, nd in self.nodes.items():
            if only is None or nid in only:
                nd.create_group(name, [0, 1, 2])

    def spin(self, k, only=None):
        for _ in range(k):
            for nid, nd in self.nodes.items():
                if only is None or nid in only:
                    nd.tick()
            self.net.pump()

    def commit(self, at, name, payload, max_ticks=300, only=None):
        done = []
        rid = self.nodes[at].propose(name, payload,
                                     lambda _r, x: done.append(x))
        assert rid is not None
        for _ in range(max_ticks):
            self.spin(1, only=only)
            if done:
                return done[0]
        raise AssertionError(f"no commit of {payload!r} at {at}")


def test_commit_and_failover_under_link_delay():
    """Every link carries 3 pump-rounds of latency (the JSONDelayEmulator
    scenario): commits still land, and killing the coordinator still fails
    over — correctness must not depend on loopback RTT."""
    cl = SimCluster(delay=3)
    cl.create("svc")
    assert cl.commit("N1", "svc", b"PUT a 1") == b"OK"
    # kill the coordinator (N0): endpoints close, survivors mark it dead
    cl.nodes["N0"].close()
    del cl.nodes["N0"]
    for nd in cl.nodes.values():
        nd.set_alive(0, False)
    assert cl.commit("N1", "svc", b"PUT b 2",
                     only=("N1", "N2"), max_ticks=400) == b"OK"
    for _ in range(200):  # delayed links: give N2 time to learn the decision
        if all(cl.apps[nid].db.get("svc", {}).get("b") == "2"
               for nid in ("N1", "N2")):
            break
        cl.spin(1, only=("N1", "N2"))
    for nid in ("N1", "N2"):
        assert cl.apps[nid].db["svc"]["b"] == "2", nid


@pytest.mark.slow
def test_mass_laggard_fresh_node_converges():
    """A FRESH node (empty state, no WAL) joins a busy cluster with many
    groups: whois resolves the gids, anti-entropy full frames rebuild the
    mirrors, and checkpoint transfers repair groups whose decisions are
    long gone — until its app state matches the cluster's."""
    G = 64
    cl = SimCluster(groups=G + 8, anti_entropy_every=16)
    names = [f"g{i}" for i in range(G)]
    # only N0/N1 know the groups; N2 stays dark (the fresh joiner later)
    for n in names:
        cl.create(n, only=("N0", "N1"))
    cl.nodes["N2"].close()
    del cl.nodes["N2"]
    for nd in cl.nodes.values():
        nd.set_alive(2, False)
    # busy cluster: several committed writes per group (more than W in some)
    for i, n in enumerate(names):
        assert cl.commit("N0", n, f"PUT k {i}".encode(),
                         only=("N0", "N1")) == b"OK"
    for n in names[:4]:  # push a few groups past the ring window
        for j in range(10):
            assert cl.commit("N0", n, f"PUT deep {j}".encode(),
                             only=("N0", "N1")) == b"OK"
    # fresh N2: brand-new state, no journal — joins and asks for sync
    cl.apps["N2"] = KVApp()
    cl.nodes["N2"] = ModeBNode(cl.cfg, IDS, "N2", cl.apps["N2"],
                               cl.net.messenger("N2"),
                               anti_entropy_every=16)
    for nd in cl.nodes.values():
        nd.set_alive(2, True)
    cl.nodes["N2"].request_sync()
    want_rows = len(names)
    for round_ in range(4000):
        cl.spin(1)
        n2 = cl.nodes["N2"]
        if (len(list(n2.rows.items())) >= want_rows
                and all(cl.apps["N2"].db.get(n, {}).get("k") is not None
                        for n in names)
                and cl.apps["N2"].db.get("g0", {}).get("deep") == "9"):
            break
    else:
        known = len(list(cl.nodes["N2"].rows.items()))
        missing = [n for n in names
                   if cl.apps["N2"].db.get(n, {}).get("k") is None]
        raise AssertionError(
            f"fresh node never converged: rows={known}/{want_rows}, "
            f"missing={missing[:8]} stats={dict(cl.nodes['N2'].stats)}"
        )
    # and it serves traffic afterwards
    assert cl.commit("N2", "g1", b"PUT post 1") == b"OK"


def test_anti_entropy_cost_measured():
    """Anti-entropy full frames re-ship every row periodically: measure the
    actual frame bytes per tick at a few hundred groups so the cost is a
    recorded number, not folklore (printed for the bench artifact)."""
    G = 256
    cl = SimCluster(groups=G, anti_entropy_every=32)
    for i in range(G - 8):
        cl.create(f"g{i}")
    # one committed write in a slice of groups so rows are live
    for i in range(0, G - 8, 32):
        assert cl.commit("N0", f"g{i}", b"PUT a 1") == b"OK"
    sent0 = cl.net.stats["sent"]
    n0 = cl.nodes["N0"]
    bytes0 = n0.stats.get("frame_bytes", 0)
    t0 = n0.tick_num
    cl.spin(96)  # 3 anti-entropy cycles, no load
    dticks = n0.tick_num - t0
    dbytes = n0.stats.get("frame_bytes", 0) - bytes0
    per_tick = dbytes / max(dticks, 1)
    print(f"\nanti-entropy: {per_tick:.0f} frame B/tick/node at "
          f"{G - 8} groups (idle), {cl.net.stats['sent'] - sent0} msgs",
          file=sys.stderr)
    # sanity bound: idle anti-entropy must stay << full-state-per-tick
    # (full frame every 32 ticks amortizes to ~rows/32 per tick)
    assert dbytes > 0
    full_frame_estimate = (G - 8) * 150  # ~150B/row on the wire
    assert per_tick < full_frame_estimate, (
        "anti-entropy is shipping ~full state EVERY tick"
    )

"""Chain replication ACROSS hosts: independent per-process chain nodes over
real loopback sockets (round-2 verdict: "chain replication never crosses a
host"; reference chains ride NIO, chainreplication/ChainManager.java:71-99,
FORWARD/ACK packets chainpackets/ChainPacket.java:119-133).

Covers: head-ordered writes entering at head AND non-head nodes (forward),
responses at the commit point (tail application), mid-chain death re-link,
tail death moving the commit point, and a fresh node catching up by
checkpoint transfer.
"""

import time

import pytest

from gigapaxos_tpu.chain.modeb import ChainModeBNode
from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.net.messenger import Messenger, NodeMap

IDS = ["C0", "C1", "C2"]


def make_cfg(groups=16, window=8):
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = groups
    cfg.paxos.window = window
    return cfg


class Cluster:
    def __init__(self, cfg, wal_root=None):
        from gigapaxos_tpu.chain.modeb_logger import ChainBLogger

        self.cfg = cfg
        self.wal_root = wal_root
        self.nodemap = NodeMap()
        self.msgs = {}
        self.apps = {}
        self.nodes = {}
        for nid in IDS:
            m = Messenger(nid, ("127.0.0.1", 0), self.nodemap)
            self.nodemap.add(nid, "127.0.0.1", m.port)
            self.msgs[nid] = m
        for nid in IDS:
            wal = None
            if wal_root is not None:
                wal = ChainBLogger(str(wal_root / nid), native=False)
            self.apps[nid] = KVApp()
            self.nodes[nid] = ChainModeBNode(
                cfg, IDS, nid, self.apps[nid], self.msgs[nid], wal=wal,
                anti_entropy_every=16,
            )

    def create(self, name, members=(0, 1, 2), only=None):
        for nid, n in self.nodes.items():
            if only is None or nid in only:
                n.create_group(name, list(members))

    def ticks(self, k, only=None, sleep=0.004):
        for _ in range(k):
            for nid, n in self.nodes.items():
                if only is None or nid in only:
                    n.tick()
            if sleep:
                time.sleep(sleep)

    def commit(self, at, name, payload, timeout_ticks=300, only=None):
        done = []
        rid = self.nodes[at].propose(
            name, payload, lambda _r, resp: done.append(resp)
        )
        assert rid is not None
        for _ in range(timeout_ticks):
            self.ticks(1, only=only)
            if done:
                return done[0]
        raise AssertionError(f"no chain commit of {payload!r} at {at}")

    def kill(self, nid):
        self.nodes[nid].close()
        dead = IDS.index(nid)
        del self.nodes[nid]
        for n in self.nodes.values():
            n.set_alive(dead, False)

    def drop_backlog(self, nid):
        """reset_peer also strands a writer-held in-flight frame — one
        delivered to the restarted incarnation can mask the mechanism
        under test (see tests/test_modeb.py drop_backlog)."""
        for other in self.nodes.values():
            other.m.transport.reset_peer(nid)

    def restart(self, nid):
        from gigapaxos_tpu.chain.modeb_logger import recover_chain_modeb

        assert self.wal_root is not None
        self.apps[nid] = KVApp()
        node = recover_chain_modeb(self.cfg, IDS, nid, self.apps[nid],
                                   str(self.wal_root / nid), native=False)
        m = Messenger(nid, ("127.0.0.1", 0), self.nodemap)
        self.nodemap.add(nid, "127.0.0.1", m.port)
        self.msgs[nid] = m
        node.attach_messenger(m)
        node.request_sync()
        self.nodes[nid] = node
        for n in self.nodes.values():
            n.set_alive(IDS.index(nid), True)
        return node

    def close(self):
        for n in self.nodes.values():
            n.close()


@pytest.fixture()
def cluster():
    cl = Cluster(make_cfg())
    yield cl
    cl.close()


def test_chain_commit_head_and_forward(cluster):
    cluster.create("svc")
    # at the head (C0): ordered directly
    assert cluster.commit("C0", "svc", b"PUT a 1") == b"OK"
    # at a non-head: forwarded to the head process over TCP
    assert cluster.commit("C2", "svc", b"PUT b 2") == b"OK"
    cluster.ticks(30)
    for nid in IDS:
        assert cluster.apps[nid].db["svc"] == {"a": "1", "b": "2"}, nid


def test_chain_midchain_death_relinks(cluster):
    cluster.create("svc")
    assert cluster.commit("C0", "svc", b"PUT pre 0") == b"OK"
    cluster.kill("C1")  # middle of the chain
    # live members re-link: head forwards straight to the (old) tail
    assert cluster.commit("C0", "svc", b"PUT post 1",
                          only=("C0", "C2")) == b"OK"
    cluster.ticks(20, only=("C0", "C2"))
    for nid in ("C0", "C2"):
        assert cluster.apps[nid].db["svc"]["post"] == "1", nid


def test_chain_tail_death_moves_commit_point(cluster):
    cluster.create("svc")
    assert cluster.commit("C0", "svc", b"PUT pre 0") == b"OK"
    cluster.kill("C2")  # the tail
    # the live tail is now C1: commits must still complete (ACK path moved)
    assert cluster.commit("C0", "svc", b"PUT post 1",
                          only=("C0", "C1")) == b"OK"
    assert cluster.commit("C1", "svc", b"PUT more 2",
                          only=("C0", "C1")) == b"OK"
    cluster.ticks(20, only=("C0", "C1"))
    for nid in ("C0", "C1"):
        assert cluster.apps[nid].db["svc"]["more"] == "2", nid


def test_chain_missed_create_node_catches_up(cluster):
    """A member that missed the group's creation learns it by whois from
    the first frame carrying the unknown gid and catches up.  The chain
    window is deliberately bounded by the slowest MEMBER (a dead member
    freezes intake after W more slots — chain/tick.py module doc), so a
    member can never trail by more than W; gaps beyond that are an epoch
    change's job, not a transfer's."""
    cluster.create("deep", only=("C0", "C1"))
    # C2 marked down: the live chain re-links to C0 -> C1 and commits up
    # to W slots (the window bound with a frozen member)
    for nid in ("C0", "C1"):
        cluster.nodes[nid].set_alive(2, False)
    for i in range(cluster.cfg.paxos.window):
        assert cluster.commit("C0", "deep", f"PUT k{i} {i}".encode(),
                              only=("C0", "C1")) == b"OK"
    # C2 revives: whois -> create -> ring copy (and/or checkpoint transfer)
    for nid in ("C0", "C1"):
        cluster.nodes[nid].set_alive(2, True)
    last = f"k{cluster.cfg.paxos.window - 1}"
    for _ in range(400):
        cluster.ticks(1)
        if cluster.apps["C2"].db.get("deep", {}).get(last) is not None:
            break
    assert cluster.apps["C2"].db["deep"] == cluster.apps["C0"].db["deep"]
    # and the healed chain accepts new writes through every member again
    assert cluster.commit("C1", "deep", b"PUT post 9") == b"OK"


@pytest.mark.slow
def test_chain_modeb_control_plane():
    """Full deployment with chain-coordinated Mode B actives: the same
    ActiveReplica/Reconfigurator control plane binds ChainModeBNode via the
    shared coordinator SPI (REPLICA_COORDINATOR_CLASS swap,
    ReconfigurableNode.java:203-218) — create/request/respond/delete over
    independent per-process chain planes."""
    import socket

    from gigapaxos_tpu.client import ReconfigurableAppClient
    from gigapaxos_tpu.server import ModeBServer

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    cfg = make_cfg()
    cfg.fd.ping_interval_s = 0.1
    cfg.fd.timeout_s = 1.5
    for i in range(3):
        cfg.nodes.actives[f"CA{i}"] = ("127.0.0.1", free_port())
    cfg.nodes.reconfigurators["CR0"] = ("127.0.0.1", free_port())
    srv = {
        nid: ModeBServer(nid, cfg, coordinator="chain")
        for nid in list(cfg.nodes.actives) + ["CR0"]
    }
    client = None
    try:
        for s in srv.values():
            assert s.wait_ready(300)
        client = ReconfigurableAppClient(cfg.nodes)
        assert client.create("csvc", timeout=60)["ok"]
        assert client.request("csvc", b"PUT k chained", timeout=30) == b"OK"
        assert client.request("csvc", b"GET k", timeout=30) == b"chained"
        assert client.delete("csvc")["ok"]
    finally:
        if client is not None:
            client.close()
        for s in srv.values():
            s.close()


def test_chain_kill_restart_from_own_journal(tmp_path):
    """SIGKILL-equivalent: a chain node dies, restarts from ITS OWN journal
    (nothing shared but TCP), recovers pre-crash state locally and catches
    up on what it missed (the chain flavor of the Mode B recovery story)."""
    from gigapaxos_tpu.chain.modeb_logger import ChainBLogger, recover_chain_modeb

    cfg = make_cfg()
    nodemap = NodeMap()
    msgs = {}
    for nid in IDS:
        m = Messenger(nid, ("127.0.0.1", 0), nodemap)
        nodemap.add(nid, "127.0.0.1", m.port)
        msgs[nid] = m
    apps = {nid: KVApp() for nid in IDS}
    nodes = {
        nid: ChainModeBNode(
            cfg, IDS, nid, apps[nid], msgs[nid],
            wal=ChainBLogger(str(tmp_path / nid), native=False),
            anti_entropy_every=16,
        )
        for nid in IDS
    }

    def ticks(k, only=None):
        for _ in range(k):
            for nid, n in nodes.items():
                if only is None or nid in only:
                    n.tick()
            time.sleep(0.004)

    def commit(at, payload, only=None):
        done = []
        assert nodes[at].propose("svc", payload,
                                 lambda _r, x: done.append(x)) is not None
        for _ in range(300):
            ticks(1, only=only)
            if done:
                return done[0]
        raise AssertionError(f"no commit {payload!r}")

    try:
        for n in nodes.values():
            n.create_group("svc", [0, 1, 2])
        assert commit("C1", b"PUT k1 v1") == b"OK"
        ticks(10)
        db_c1 = dict(apps["C1"].db)
        # kill the middle node (C1): survivors re-link and keep committing
        nodes["C1"].close()
        del nodes["C1"]
        for n in nodes.values():
            n.set_alive(1, False)
        assert commit("C0", b"PUT k2 v2", only=("C0", "C2")) == b"OK"
        # restart C1 from ITS OWN journal: pre-crash state must be back
        apps["C1"] = KVApp()
        n1 = recover_chain_modeb(cfg, IDS, "C1", apps["C1"],
                                 str(tmp_path / "C1"), native=False)
        assert apps["C1"].db == db_c1  # recovered locally, not copied
        m = Messenger("C1", ("127.0.0.1", 0), nodemap)
        nodemap.add("C1", "127.0.0.1", m.port)
        n1.attach_messenger(m)
        n1.request_sync()
        nodes["C1"] = n1
        for n in nodes.values():
            n.set_alive(1, True)
        for _ in range(300):
            ticks(1)
            if apps["C1"].db.get("svc", {}).get("k2") == "v2":
                break
        assert apps["C1"].db["svc"] == {"k1": "v1", "k2": "v2"}
        # the rejoined node serves new traffic
        assert commit("C1", b"PUT k3 v3") == b"OK"
    finally:
        for n in nodes.values():
            n.close()


def test_chain_stop_fences(cluster):
    cluster.create("svc")
    assert cluster.commit("C0", "svc", b"PUT a 1") == b"OK"
    done = []
    cluster.nodes["C0"].propose_stop("svc", callback=lambda r, x: done.append(x))
    cluster.ticks(60)
    assert done, "stop never committed"
    for nid in IDS:
        assert cluster.nodes[nid].is_stopped("svc"), nid
    got = []
    assert cluster.nodes["C1"].propose(
        "svc", b"PUT b 2", lambda r, x: got.append(x)
    ) is None
    cluster.ticks(5)
    assert got == [None]


def test_chain_expand_universe_and_commit_through_new_tail():
    """Runtime chain-universe expansion: every member appends the new
    node's slot, the newcomer joins, and a chain spanning old + NEW slots
    commits with the newcomer as its tail (chain flavor of
    ModeBNode.expand_universe; tests/test_modeb_expand.py covers paxos)."""
    cl = Cluster(make_cfg(groups=16))
    try:
        cl.create("old")
        assert cl.commit("C0", "old", b"PUT a 1") == b"OK"

        # expand every live member, then boot the newcomer last
        m3 = Messenger("C3", ("127.0.0.1", 0), cl.nodemap)
        cl.nodemap.add("C3", "127.0.0.1", m3.port)
        for n in cl.nodes.values():
            assert n.expand_universe(["C3"])
        cl.apps["C3"] = KVApp()
        cl.nodes["C3"] = ChainModeBNode(
            cl.cfg, IDS + ["C3"], "C3", cl.apps["C3"], m3,
            anti_entropy_every=16,
        )
        for nid in IDS:
            cl.nodes[nid].set_alive(3, True)  # FD stand-in (see modeb tests)

        # chain 1 -> 2 -> 3: the NEWCOMER is the tail (the commit point),
        # so the write only acks once C3 really applied it
        for n in cl.nodes.values():
            n.create_group("mix", [1, 2, 3])
        assert cl.commit("C1", "mix", b"PUT k v") == b"OK"
        assert cl.apps["C3"].db.get("mix", {}).get("k") == "v"
        # the old chain still works after expansion
        assert cl.commit("C2", "old", b"PUT b 2") == b"OK"
    finally:
        cl.close()


def test_chain_node_epoch_gc_duck_typing(cluster):
    """ModeBReplicaCoordinator duck-types over ChainModeBNode (server.py
    coordinator == 'chain'), which has no pause tier: the epoch-GC donor
    paths (drop_final_state retransmits for an already-dropped epoch,
    final_state_gone probes) must not assume `_paused` exists."""
    from gigapaxos_tpu.modeb.coordinator import ModeBReplicaCoordinator

    node = cluster.nodes["C0"]
    coord = ModeBReplicaCoordinator(node)
    assert coord.create_replica_group("csvc", 0, b"", list(IDS))
    # routine WaitAckDropEpoch retransmit for an epoch never hosted here
    assert coord.drop_final_state("csvc", -1)
    assert coord.get_final_state("csvc", -1) is None
    assert coord.final_state_gone("csvc", -1)
    # and for one that exists: drop removes the row before freeing state
    assert coord.drop_final_state("csvc", 0)
    assert coord.get_final_state("csvc", 0) is None


@pytest.mark.parametrize("seed", [3, 14])
def test_chain_random_kill_restart_released_writes_converge(tmp_path, seed):
    """Randomized chain durability: random commits under random single-node
    kills (head, mid, or tail) + journal restarts with backlog resets —
    every write whose response was RELEASED to a client (including late
    releases after the submitter stopped waiting) converges onto every
    node's app (the chain twin of the Mode B paxos property in
    tests/test_modeb.py)."""
    import numpy as _np

    rng = _np.random.default_rng(seed)
    cl = Cluster(make_cfg(), wal_root=tmp_path)
    pending = {}  # key -> (value, done-list); folded into released at end
    dead = None
    try:
        cl.create("svc")
        cnt = 0
        for step in range(24):
            if dead is None and rng.random() < 0.3:
                dead = IDS[int(rng.integers(0, 3))]
                cl.kill(dead)
            elif dead is not None and rng.random() < 0.45:
                cl.drop_backlog(dead)
                cl.restart(dead)
                dead = None
            at = str(rng.choice([i for i in IDS if i != dead]))
            cnt += 1
            k, v = f"h{cnt}", str(step)
            done = []
            if cl.nodes[at].propose("svc", f"PUT {k} {v}".encode(),
                                    lambda _r, x: done.append(x)) is None:
                continue
            pending[k] = (v, done)
            for _ in range(300):
                cl.ticks(1)
                if done:
                    break
        if dead is not None:
            cl.drop_backlog(dead)
            cl.restart(dead)

        def released():
            # late releases count: a response that fired after its
            # submitter stopped waiting is still a client-visible promise
            return {k: v for k, (v, d) in pending.items() if b"OK" in d}

        deadline = time.monotonic() + 150
        while time.monotonic() < deadline:
            cl.ticks(1)
            rel = released()
            if rel and all(cl.apps[nid].db.get("svc", {}).get(k) == v
                           for nid in IDS for k, v in rel.items()):
                break
        rel = released()
        for nid in IDS:
            db = cl.apps[nid].db.get("svc", {})
            missing = {k: v for k, v in rel.items() if db.get(k) != v}
            assert not missing, (nid, len(missing))
        assert rel
    finally:
        cl.close()

"""Per-process deployment unit: the ``gpServer.sh`` / ``ReconfigurableNode``
analog.

The reference's unit of deployment is one process running the whole stack —
transport, ActiveReplica and/or Reconfigurator, coordinator, logger — built
by ``ReconfigurableNode.main``
(``reconfiguration/ReconfigurableNode.java:63,259-336,434``, launched by
``bin/gpServer.sh``).  :class:`ModeBServer` is that unit for the TPU
framework: each process owns

* a Messenger per role (actives and reconfigurators are distinct ids in the
  topology, like ``active.*`` / ``reconfigurator.*`` lines);
* an independent Mode B consensus node per plane, with its own WAL and
  device state (``modeb/``), replica traffic as SoA frames over TCP;
* the control-plane face for the role: :class:`ActiveReplica` over a
  :class:`ModeBReplicaCoordinator`, and/or :class:`Reconfigurator` over a
  :class:`ModeBRepliconfigurableDB`;
* a keep-alive failure detector feeding the node's liveness mask every
  tick (``FailureDetection.java:209-258`` → candidacy phase 0) — killing a
  coordinator process needs no manual liveness control anywhere;
* a TickDriver pumping each plane.

Run from the CLI::

    python -m gigapaxos_tpu.server --node AR0 --properties gigapaxos.properties \
        --log-dir /var/lib/gptpu

or embed (tests boot several in one process on loopback, the
``TESTReconfigurationMain.startLocalServers`` strategy).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading
from typing import Callable, Optional

from .config import GigapaxosTpuConfig, load_properties
from .models.replicable import KVApp, Replicable
from .modeb import ModeBLogger, ModeBNode, recover_modeb
from .modeb.coordinator import ModeBReplicaCoordinator, ModeBRepliconfigurableDB
from .net.failure_detection import FailureDetection
from .net.messenger import Messenger, NodeMap
from .net.security import TransportSecurity
from .paxos.driver import TickDriver
from .reconfiguration.active_replica import ActiveReplica
from .reconfiguration.demand import AbstractDemandProfile, DemandProfile
from .reconfiguration.rc_db import ReconfiguratorDB
from .reconfiguration.reconfigurator import Reconfigurator

log = logging.getLogger(__name__)


class ModeBServer:
    """One OS process of a Mode B deployment (active and/or reconfigurator
    role, depending on which topology section names ``node_id``)."""

    def __init__(
        self,
        node_id: str,
        cfg: GigapaxosTpuConfig,
        app_factory: Callable[[], Replicable] = KVApp,
        log_dir: Optional[str] = None,
        start_fd: bool = True,
        replicas_per_name: int = 3,
        rc_group_size: int = 3,
        demand_profile_factory: Callable[[str], AbstractDemandProfile] = DemandProfile,
        coordinator: str = "paxos",
    ):
        """``coordinator``: "paxos" (ModeBNode data plane) or "chain"
        (ChainModeBNode — cross-host chain replication); both WAL-backed
        when ``log_dir`` is set, recovering from their own journals.
        Mirrors REPLICA_COORDINATOR_CLASS (ReconfigurableNode.java:203-218)."""
        self.node_id = node_id
        self.cfg = cfg
        self.nodemap = NodeMap(cfg.nodes)
        # Two distinct node lists: the replica-slot UNIVERSE (append-only,
        # committed NC order — data-plane member axis) and the live
        # placement POOL (current actives — what reconfigurators place new
        # names on).  The universe may retain removed nodes whose slots are
        # never recycled; the pool must not.
        universe_ids = cfg.nodes.universe_order()
        active_ids = cfg.nodes.active_ids()
        rc_ids = cfg.nodes.reconfigurator_ids()
        self.is_active = node_id in cfg.nodes.actives
        self.is_rc = node_id in cfg.nodes.reconfigurators
        if not (self.is_active or self.is_rc):
            raise ValueError(f"{node_id!r} is in neither topology section")
        log_dir = log_dir or cfg.log_dir
        security = TransportSecurity.from_config(cfg.ssl)

        self.fds: list = []
        self.drivers: list = []
        self.reporter = None
        if cfg.stats_interval_s > 0:
            from .utils.observability import StatsReporter

            self.reporter = StatsReporter(node_id, cfg.stats_interval_s)
        self.node: Optional[ModeBNode] = None
        self.rc_node: Optional[ModeBNode] = None
        self.timeline_rec = None
        self._closing = False
        self.active_replica: Optional[ActiveReplica] = None
        self.reconfigurator: Optional[Reconfigurator] = None
        self.app: Optional[Replicable] = None

        if self.is_active:
            bind = cfg.nodes.actives[node_id]
            m = Messenger(node_id, bind, self.nodemap, security=security)
            self.nodemap.add(node_id, bind[0], m.port)
            cfg.nodes.actives[node_id] = (bind[0], m.port)
            self.app = app_factory()
            if coordinator == "chain":
                from .chain.modeb import ChainModeBNode
                from .chain.modeb_logger import ChainBLogger, recover_chain_modeb

                wal_dir = (os.path.join(log_dir, f"{node_id}-chain")
                           if log_dir else None)
                if wal_dir and os.path.isdir(wal_dir) and os.listdir(wal_dir):
                    node = recover_chain_modeb(
                        cfg, universe_ids, node_id, self.app, wal_dir,
                        native=cfg.native_journal,
                    )
                    recovered = True
                else:
                    wal = (ChainBLogger(wal_dir, native=cfg.native_journal)
                           if wal_dir else None)
                    node = ChainModeBNode(cfg, universe_ids, node_id,
                                          self.app, wal=wal)
                    recovered = False
            elif coordinator == "paxos":
                node, recovered = self._make_node(
                    universe_ids, self.app,
                    os.path.join(log_dir, f"{node_id}-ar") if log_dir else None,
                    spill_ns=f"{node_id}-ar",
                )
                if cfg.paxos.device_app:
                    # device mode: the node built its own DeviceKVApp face
                    # over the device arrays; the control plane (epoch
                    # final-state, demand, tests) must see THAT app
                    self.app = node.app
            else:
                raise ValueError(f"unknown coordinator {coordinator!r}")
            self.coordinator = ModeBReplicaCoordinator(node)
            # ActiveReplica first: its BulkTransfer claims the raw-bytes
            # handler, and the node's frame handler must chain OVER it
            self.active_replica = ActiveReplica(
                node_id, m, self.coordinator, rc_ids,
                demand_profile_factory=demand_profile_factory,
                rc_group_size=rc_group_size,
            )
            node.attach_messenger(m)
            m.register("nc_universe_apply", self._on_nc_universe)
            if recovered:
                node.request_sync()
            if start_fd:
                fd = FailureDetection(
                    m, monitored=universe_ids,
                    ping_interval_s=cfg.fd.ping_interval_s,
                    timeout_s=cfg.fd.timeout_s,
                    adaptive=cfg.fd.adaptive,
                    adaptive_beta=cfg.fd.adaptive_beta,
                    adaptive_gain=cfg.fd.adaptive_gain,
                )
                node.attach_failure_detector(fd)
                self.fds.append(fd)
            self.node = node
            self.drivers.append(self._start_driver(node))
            if self.reporter is not None:
                from .utils.observability import (node_stats_source,
                                                  transport_stats_source)

                self.reporter.add_source("ar", node_stats_source(node))
                self.reporter.add_source(
                    "ar_net", transport_stats_source(m.transport)
                )

        if self.is_rc:
            bind = cfg.nodes.reconfigurators[node_id]
            m = Messenger(node_id, bind, self.nodemap, security=security)
            self.nodemap.add(node_id, bind[0], m.port)
            cfg.nodes.reconfigurators[node_id] = (bind[0], m.port)
            db = ReconfiguratorDB(node_id)
            rc_node, recovered = self._make_node(
                rc_ids, db,
                os.path.join(log_dir, f"{node_id}-rc") if log_dir else None,
                spill_ns=f"{node_id}-rc", rc_plane=True,
            )
            self.rdb = ModeBRepliconfigurableDB(rc_node, rc_ids, k=rc_group_size)
            fd = None
            if start_fd:
                fd = FailureDetection(
                    m, monitored=rc_ids,
                    ping_interval_s=cfg.fd.ping_interval_s,
                    timeout_s=cfg.fd.timeout_s,
                    adaptive=cfg.fd.adaptive,
                    adaptive_beta=cfg.fd.adaptive_beta,
                    adaptive_gain=cfg.fd.adaptive_gain,
                )
                self.fds.append(fd)
            self.reconfigurator = Reconfigurator(
                node_id, m, self.rdb, active_ids,
                replicas_per_name=replicas_per_name,
                demand_profile_factory=demand_profile_factory,
                is_node_up=fd.is_node_up if fd is not None else None,
            )
            rc_node.attach_messenger(m)
            if recovered:
                rc_node.request_sync()
            if fd is not None:
                rc_node.attach_failure_detector(fd)
            self.rc_node = rc_node
            self.drivers.append(self._start_driver(rc_node))
            if self.reporter is not None:
                from .utils.observability import (node_stats_source,
                                                  transport_stats_source)

                self.reporter.add_source("rc", node_stats_source(rc_node))
                self.reporter.add_source(
                    "rc_net", transport_stats_source(m.transport)
                )

        # ---------------------------------------------------- flight deck
        # per-node scrape endpoint + crash flight recorder (cfg.obs); the
        # serving-cell plane wires the same pieces per worker process
        self.metrics_server = None
        self.flight = None
        obs = getattr(cfg, "obs", None)
        if obs is not None and obs.flight_dir:
            from .obs.flight import FlightRecorder

            self.flight = FlightRecorder(
                os.path.join(obs.flight_dir, f"{node_id}-flight.json"),
                cap=obs.flight_cap, node=node_id)
            self.flight.install_excepthook()
            self.flight.record("boot", node=node_id, pid=os.getpid())
            if self.reporter is not None:
                self.reporter.sink = self.flight.snapshot_sink
            if self.node is not None:
                # health fold records wedge/recover transitions here
                self.node.flight = self.flight
        if obs is not None and obs.http_port >= 0:
            from .obs import registry as _obs_registry
            from .obs.http import MetricsServer
            from .obs.prom import render_registry
            from .utils import reqtrace as _reqtrace

            def _scrape() -> str:
                return render_registry(_obs_registry(),
                                       extra_labels={"node": node_id})

            def _trace(tid):
                d = _reqtrace.dump_ns()
                return (d if tid is None
                        else {k: v for k, v in d.items() if k == str(tid)})

            flight_cb = None
            if self.flight is not None:
                fr = self.flight
                flight_cb = lambda: fr.read(fr.persist())  # noqa: E731

            # health plane (ISSUE 18): readiness + group drill-down served
            # off the data-plane node; the RC plane is control traffic and
            # reports only through /healthz's wal check
            def _wal_failed() -> bool:
                for n in (self.node, getattr(self, "rc_node", None)):
                    if n is not None and getattr(n, "wal", None) is not None:
                        if getattr(n.wal, "failed", False):
                            return True
                return False

            def _healthz() -> dict:
                return {"ok": not _wal_failed() and not self._closing,
                        "node": node_id, "draining": self._closing,
                        "wal_failed": _wal_failed()}

            health_cb = group_cb = None
            if self.node is not None:
                health_cb = self.node.health_snapshot
                group_cb = self.node.group_info
            from .obs.timeline import TimelineRecorder, registry_sampler
            self.timeline_rec = TimelineRecorder(
                registry_sampler(
                    "health_backlogged_groups", "health_wedged_groups",
                    "overload_admission_shed_total", "tick_seconds"),
                interval_s=obs.timeline_interval_s,
                node=node_id).start()
            self.timeline_rec.annotate("boot", node=node_id)
            self.metrics_server = MetricsServer(
                _scrape, trace=_trace, flight=flight_cb,
                healthz=_healthz, health=health_cb, group=group_cb,
                timeline=self.timeline_rec.snapshot,
                port=obs.http_port)

        if self.reporter is not None:
            self.reporter.start()

    def _on_nc_universe(self, sender: str, p: dict) -> None:
        """A reconfigurator committed a node addition: adopt the new
        node's address and grow this plane's replica universe to match the
        committed slot order (idempotent; lost broadcasts are repaired by
        the next one, which carries the complete order)."""
        for nid, addr in (p.get("addrs") or {}).items():
            self.nodemap.add(nid, addr[0], int(addr[1]))
        uni = list(p.get("universe") or [])
        node = self.node
        if node is None or not hasattr(node, "expand_universe"):
            return
        with node.lock:
            known = list(node.members)
        if uni[: len(known)] != known:
            if uni == known[: len(uni)]:
                # stale broadcast (an earlier add, delivered late over a
                # different RC's connection): already applied, nothing to do
                return
            # a conflicting order would desync slot indices across nodes —
            # never apply it (this node's own WAL/boot order is authoritative
            # for the prefix it already has)
            log.warning("%s: nc universe %s conflicts with members %s",
                        self.node_id, uni, known)
            return
        fresh = uni[len(known):]
        if fresh:
            try:
                node.expand_universe(fresh)
            except ValueError:
                # cap enforcement lives in the NC apply; this guard keeps a
                # malformed broadcast from killing the handler thread
                log.exception("%s: universe expansion rejected", self.node_id)

    @staticmethod
    def _start_driver(node: ModeBNode) -> TickDriver:
        """Event-driven pumping: long idle sleep (several planes may share
        few cores — an idle plane must not burn them), with work arrival
        (propose / forwarded proposal / inbound frame) kicking the driver
        awake immediately."""
        driver = TickDriver(node, idle_sleep_s=0.05)
        node.on_work = driver.kick
        return driver.start()

    def _make_node(self, member_ids, app, wal_dir, spill_ns=None,
                   rc_plane=False):
        """Build (or WAL-recover) one plane's ModeBNode, messenger-less —
        the caller attaches the messenger after the control-plane endpoint
        claims its handlers (3-pass recovery before live traffic,
        PaxosManager.initiateRecovery, PaxosManager.java:1852)."""
        cfg = self.cfg
        if rc_plane and cfg.paxos.device_app:
            # the RC DB is a host state machine: a device-app data plane
            # must not leak its mode into the control plane (node.py does
            # the same for Mode A)
            import copy as _copy
            import dataclasses as _dc

            cfg = _copy.copy(cfg)
            cfg.paxos = _dc.replace(cfg.paxos, device_app=False)
        if wal_dir and os.path.isdir(wal_dir) and os.listdir(wal_dir):
            node = recover_modeb(
                cfg, member_ids, self.node_id, app, wal_dir,
                native=cfg.native_journal, spill_ns=spill_ns,
            )
            return node, True
        wal = None
        if wal_dir:
            wal = ModeBLogger(wal_dir, native=cfg.native_journal)
        node = ModeBNode(
            cfg, member_ids, self.node_id, app, messenger=None,
            wal=wal, spill_ns=spill_ns,
        )
        return node, False

    # ------------------------------------------------------------------ admin
    def wait_ready(self, timeout_s: float = 180.0) -> bool:
        """Block until every plane's jitted tick compiled."""
        return all(d.wait_ready(timeout_s) for d in self.drivers)

    def close(self) -> None:
        self._closing = True
        if self.timeline_rec is not None:
            self.timeline_rec.stop()
        if self.metrics_server is not None:
            self.metrics_server.close()
        if self.reporter is not None:
            self.reporter.stop()
        if self.flight is not None:
            self.flight.dump("close")
        for fd in self.fds:
            fd.close()
        # drivers first: a tick sending frames after the messenger closed
        # would die with SendFailure on the driver thread
        for d in self.drivers:
            d.stop()
        if self.active_replica is not None:
            self.active_replica.close()
        if self.reconfigurator is not None:
            self.reconfigurator.close()
        for n in (self.node, self.rc_node):
            if n is not None:
                n.close()


def _run_cells(cfg: GigapaxosTpuConfig, log_dir: Optional[str]) -> None:
    """``--cells`` bootstrap: one supervised multi-core host plane instead
    of one ModeBServer process — N crash-isolated Mode A cells (cells/),
    sized and tuned by the ``cells.*`` properties section."""
    from .cells.supervisor import build_supervisor

    base_dir = log_dir or cfg.log_dir or os.path.join(
        os.getcwd(), "gptpu-cells")
    os.makedirs(base_dir, exist_ok=True)
    sup = build_supervisor(cfg, base_dir, edge=cfg.cells.edge_port > 0)
    sup.start()
    edge = (f" edge={sup.edge_addr[0]}:{sup.edge_addr[1]}"
            if sup.edge_addr else "")
    print(f"gigapaxos_tpu cells host ready: {sup.n_cells} cells{edge}",
          flush=True)

    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    stop.wait()
    sup.stop()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="gigapaxos_tpu per-process server (gpServer.sh analog)"
    )
    ap.add_argument("--node", default=None, help="node id from the topology")
    ap.add_argument("--properties", required=True,
                    help="gigapaxos.properties-style topology/config file")
    ap.add_argument("--log-dir", default=None, help="WAL root directory")
    ap.add_argument("--no-fd", action="store_true",
                    help="disable the failure detector (tests only)")
    ap.add_argument("--cells", action="store_true",
                    help="boot the multi-core serving-cell plane (cells/) "
                         "for this host instead of a single-node server; "
                         "sized by the cells.* properties section")
    args = ap.parse_args(argv)

    cfg = load_properties(args.properties)
    if args.cells or cfg.cells.enabled:
        _run_cells(cfg, args.log_dir)
        return
    if not args.node:
        ap.error("--node is required unless --cells is set")
    server = ModeBServer(
        args.node, cfg, log_dir=args.log_dir, start_fd=not args.no_fd
    )
    server.wait_ready()
    print(f"gigapaxos_tpu server {args.node} ready", flush=True)

    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    stop.wait()
    server.close()


if __name__ == "__main__":
    main()

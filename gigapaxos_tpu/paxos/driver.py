"""TickDriver: the thread that pumps the device data plane.

The reference's data plane is driven by packet arrival (NIO threads call
``PaxosManager.handleIncomingPacket``); the dense design instead advances
*all* groups in one fused device step, so something must call
``manager.tick()`` repeatedly.  This driver is that something: it ticks
eagerly while work is pending (queued proposals, undelivered windows) and
backs off to a low idle rate otherwise — the RequestBatcher's adaptive-sleep
idea (``gigapaxos/RequestBatcher.java:25-60``) applied to the whole plane.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ..wal.logger import WalError
from .manager import PaxosManager

log = logging.getLogger(__name__)

#: process-wide hook for unrecoverable storage failures surfacing in a tick
#: loop (fsyncgate semantics: the kernel may have dropped dirty pages, so
#: retrying the write would ack data that never reached disk).  The cells
#: worker installs a handler that dumps the flight recorder and exits the
#: process nonzero so the supervisor restarts the cell onto intact storage;
#: in-process embeddings (tests, notebooks) leave it None and observe
#: ``driver.fatal`` instead — the driver thread stops ticking either way,
#: which is exactly "the node stops acking".
FATAL_HANDLER: Optional[Callable[[BaseException], None]] = None


class TickDriver:
    def __init__(
        self,
        manager: PaxosManager,
        idle_sleep_s: float = 0.002,
        drain_ticks: int = 4,
    ):
        """``drain_ticks``: extra ticks after the queues empty so in-flight
        device state (accepted-but-undecided slots, ring-buffer deliveries)
        reaches quiescence before the driver goes idle."""
        self.manager = manager
        self.idle_sleep_s = idle_sleep_s
        self.drain_ticks = drain_ticks
        #: the WalError that fail-stopped this driver, if any
        self.fatal: Optional[BaseException] = None
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._first_tick = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="tick-driver", daemon=True
        )

    def start(self) -> "TickDriver":
        self._thread.start()
        return self

    def kick(self) -> None:
        """Wake the driver immediately (call after enqueuing proposals)."""
        self._kick.set()

    def wait_ready(self, timeout_s: float | None = None) -> bool:
        """Block until the first tick completed — i.e. the jitted step is
        compiled and the plane answers at interactive latency.

        Default timeout is 120s, tripled for mesh managers: the shard_map
        tick compiles one SPMD program per mesh plus the separate
        pack/compact dispatch, which takes several times longer than the
        single-device program (worst on the 8-way virtual CPU mesh the
        tests use)."""
        if timeout_s is None:
            timeout_s = 360.0 if getattr(self.manager, "mesh", None) \
                is not None else 120.0
        return self._first_tick.wait(timeout=timeout_s)

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        self._thread.join(timeout=10)
        # a pipelined manager may hold one final unprocessed outbox whose
        # callbacks clients are still waiting on
        drain = getattr(self.manager, "drain_pipeline", None)
        if drain is not None:
            drain()

    def _run(self) -> None:
        drain = self.drain_ticks
        lock = getattr(self.manager, "lock", None)
        counted = hasattr(lock, "waiters")
        min_ivl = getattr(
            getattr(self.manager.cfg, "paxos", None),
            "min_tick_interval_s", 0.0,
        ) or 0.0
        last = 0.0
        while not self._stop.is_set():
            if min_ivl > 0:
                gap = min_ivl - (time.monotonic() - last)
                if gap > 0:
                    time.sleep(gap)  # coalesce: let requests accumulate
                last = time.monotonic()
            try:
                self.manager.tick()
            except WalError as e:
                # fail-stop: storage lost (or refused) a write the plane
                # was about to ack.  Stop ticking — no further decision is
                # acked from this node — and surface the failure instead of
                # dying as a silent daemon thread.
                self.fatal = e
                log.critical("tick driver fail-stop (WAL): %s", e)
                self._first_tick.set()  # unblock wait_ready() callers
                handler = FATAL_HANDLER
                if handler is not None:
                    handler(e)
                return
            self._first_tick.set()
            # CPython locks are unfair: without a yield window the driver
            # re-acquires manager.lock before any waiting control-plane
            # thread (propose, create, stop) gets scheduled, starving them
            # indefinitely.  Blocked acquirers register in lock.waiters
            # (utils/locking.py), so the window is paid per tick for as long
            # as someone is STILL waiting — not just once per flag edge.
            if not counted:
                time.sleep(0.0005)
            elif lock.waiters > 0:
                time.sleep(0.0005)
            else:
                # clients stage proposals without touching the lock now, so
                # lock contention no longer signals their presence: yield
                # the GIL so messenger/client threads run on few-core hosts
                time.sleep(0)
            busy = self.manager.pending_count() > 0
            if not busy:
                # decided_now needs a device sync; only check when draining
                drain -= 1
                if drain <= 0:
                    self._kick.wait(timeout=self.idle_sleep_s)
                    self._kick.clear()
                    drain = 1  # idle wake: one probe tick, drain more if busy
            else:
                drain = self.drain_ticks

"""Sharded execution tests on the virtual 8-device CPU mesh: the tick under
GSPMD must produce bit-identical results to the single-device run, with the
replica axis sharded (quorum reductions -> collectives) and/or the group axis
sharded (pure data parallel)."""

import jax
import numpy as np
import jax.numpy as jnp

from gigapaxos_tpu.ops.tick import TickInbox, make_inbox, paxos_tick_impl
from gigapaxos_tpu.parallel import mesh as pmesh
from gigapaxos_tpu.paxos import state as st


def build(R=4, G=64, W=8):
    s = st.init_state(R, G, W)
    return st.create_groups(
        s, np.arange(G, dtype=np.int32), np.ones((G, R), bool)
    )


def load_inbox(R=4, G=64, P=2, seed=0, alive=None):
    rng = np.random.default_rng(seed)
    req = np.zeros((R, P, G), np.int32)
    for g in range(G):
        n = rng.integers(0, P + 1)
        for p in range(n):
            req[rng.integers(0, R), p, g] = int(rng.integers(1, 1 << 20))
    al = np.ones(R, bool) if alive is None else np.asarray(alive, bool)
    return TickInbox(
        jnp.asarray(req), jnp.zeros((R, P, G), jnp.bool_), jnp.asarray(al)
    )


def run_ticks(tick_fn, s, n_ticks, put=lambda x: x):
    outs = []
    s = put(s)
    for t in range(n_ticks):
        ib = put(load_inbox(seed=t, alive=[True, True, True, t % 2 == 0]))
        s, out = tick_fn(s, ib)
        outs.append(jax.tree.map(np.asarray, out))
    return jax.tree.map(np.asarray, s), outs


def assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_replica_and_group_sharding_bit_identical():
    assert len(jax.devices()) == 8
    ref_state, ref_outs = run_ticks(jax.jit(paxos_tick_impl), build(), 5)

    mesh = pmesh.make_mesh(replica_shards=2)  # (2 replica, 4 groups) shards
    tick = pmesh.sharded_tick(mesh)
    sh_state, sh_outs = run_ticks(
        tick, build(), 5, put=lambda x: (
            pmesh.shard_state(x, mesh)
            if isinstance(x, st.PaxosState)
            else pmesh.shard_inbox(x, mesh)
        )
    )
    assert_trees_equal(ref_state, sh_state)
    for a, b in zip(ref_outs, sh_outs):
        assert_trees_equal(a, b)


def test_pure_group_sharding_bit_identical():
    ref_state, ref_outs = run_ticks(jax.jit(paxos_tick_impl), build(), 3)
    mesh = pmesh.make_mesh(replica_shards=1)  # (1, 8)
    tick = pmesh.sharded_tick(mesh)
    sh_state, sh_outs = run_ticks(
        tick, build(), 3, put=lambda x: (
            pmesh.shard_state(x, mesh)
            if isinstance(x, st.PaxosState)
            else pmesh.shard_inbox(x, mesh)
        )
    )
    assert_trees_equal(ref_state, sh_state)
    for a, b in zip(ref_outs, sh_outs):
        assert_trees_equal(a, b)


def test_collectives_present_when_replica_sharded():
    """The compiled module for a replica-sharded mesh must contain
    cross-replica collectives (the ICI 'transport')."""
    mesh = pmesh.make_mesh(replica_shards=2)
    s = pmesh.shard_state(build(), mesh)
    ib = pmesh.shard_inbox(load_inbox(), mesh)
    lowered = jax.jit(paxos_tick_impl).lower(s, ib)
    hlo = lowered.compile().as_text()
    assert "all-reduce" in hlo or "all-gather" in hlo or "collective" in hlo

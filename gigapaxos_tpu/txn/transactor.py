"""Distributed transactions over multiple service names.

Analog of ``src/edu/umass/cs/txn`` (SURVEY §2.5, ~2k LoC, experimental in
the reference and tested only by a Noop app — same status here):

* ``DistTransactor`` (txn/DistTransactor.java:36) — extends the replica
  coordination SPI with multi-name transactions;
* lock/unlock (``TXLockerMap``, ``txpackets/LockRequest.java``) — here the
  lock table is *replicated state*: lock and unlock are coordinated
  requests executed by every replica of the participant group, so a lock
  survives replica failover exactly like app state (the reference inserts
  LockRequests through the same coordination path);
* 2PC shape (``CommitRequest``/``AbortRequest``): lock acquisition is the
  prepare phase, execution + unlock is the commit, releasing held locks on
  a failed acquire is the abort.  Deadlock freedom comes from acquiring in
  global (sorted-name) order, so no wait-for cycle can form.

Wire format: a transactional payload is ``TX_MAGIC + json + [0x00 + inner]``
understood by :class:`TxApp`, a :class:`Replicable` wrapper that owns the
per-name lock entry and passes everything else through to the real app.
"""

from __future__ import annotations

import json
import threading
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from ..models.replicable import Replicable

TX_MAGIC = b"\x01TX\x01"

#: lock denial marker returned by TxApp for ops on a locked name
TX_LOCKED = b"\x01TX_LOCKED"


def tx_payload(op: str, txid: str, inner: Optional[bytes] = None,
               now: Optional[int] = None,
               deadline: Optional[int] = None) -> bytes:
    """``now``/``deadline`` are PR-14 wire deadlines (unix ms,
    overload.deadline_at): ``deadline`` on a lock op bounds how long the
    acquired lock may be held; ``now`` is the sender's clock stamp that
    lets participants expire stale locks deterministically (the stamp is
    part of the ordered payload bytes, so every replica sees the same
    value at the same slot — no local clock reads in the decision)."""
    meta: Dict[str, object] = {"op": op, "txid": txid}
    if now is not None:
        meta["now"] = int(now)
    if deadline is not None:
        meta["deadline"] = int(deadline)
    head = TX_MAGIC + json.dumps(meta).encode()
    return head + b"\x00" + inner if inner is not None else head


class TxApp(Replicable):
    """Replicable wrapper adding a transactional lock entry per name.

    Deterministic by construction: the lock table is derived purely from
    the totally-ordered request stream, so every replica agrees on it.

    Semantics (TXLockerMap analog):
    * ``lock``   — acquire for txid; idempotent re-acquire by the same txid
      succeeds; denial returns ``TX_LOCKED`` (the transactor aborts/retries);
    * ``unlock`` — release if held by txid (idempotent);
    * ``exec``   — run the inner request iff the lock is held by txid;
    * any non-transactional request on a locked name is refused with
      ``TX_LOCKED`` — the client retries after the transaction commits.

    Stale-lock expiry (ISSUE 17): a coordinator crashing between lock and
    commit would leave the lock held forever.  Lock ops may carry a
    ``deadline`` (unix ms) bounding the hold; any later *conflicting* tx
    op stamped with the sender's ``now`` auto-releases a lock whose
    deadline has passed before the normal logic runs.  Both stamps ride
    the ordered payload, so expiry is a pure function of the request
    stream — identical on every replica and under WAL replay.
    """

    def __init__(self, app: Replicable):
        self.app = app
        self.locks: Dict[str, str] = {}  # name -> holder txid
        self.lock_deadlines: Dict[str, int] = {}  # name -> unix-ms bound

    def execute(self, name: str, request: bytes, request_id: int) -> bytes:
        if not request.startswith(TX_MAGIC):
            if name in self.locks:
                return TX_LOCKED
            return self.app.execute(name, request, request_id)
        body = request[len(TX_MAGIC):]
        sep = body.find(b"\x00")
        meta = json.loads((body if sep < 0 else body[:sep]).decode())
        inner = None if sep < 0 else body[sep + 1:]
        op, txid = meta["op"], meta["txid"]
        holder = self.locks.get(name)
        # deterministic stale-lock expiry: a conflicting op whose ordered
        # now-stamp exceeds the holder's deadline releases the lock (the
        # holder's own ops never expire it — idempotent re-acquire and a
        # late commit by a live-but-slow coordinator both stay legal; its
        # exec after a rival expired the lock gets TX_LOCKED and aborts)
        if holder is not None and holder != txid:
            dl = self.lock_deadlines.get(name, 0)
            if 0 < dl < int(meta.get("now") or 0):
                del self.locks[name]
                self.lock_deadlines.pop(name, None)
                holder = None
        if op == "lock":
            if holder is None or holder == txid:
                self.locks[name] = txid
                dl = int(meta.get("deadline") or 0)
                if dl > 0:
                    self.lock_deadlines[name] = dl
                else:
                    self.lock_deadlines.pop(name, None)
                return b"TX_OK"
            return TX_LOCKED
        if op == "unlock":
            if holder == txid:
                del self.locks[name]
                self.lock_deadlines.pop(name, None)
            return b"TX_OK"
        if op == "exec":
            if holder != txid:
                return TX_LOCKED
            return self.app.execute(name, inner or b"", request_id)
        return b"TX_BADOP"

    def checkpoint(self, name: str) -> bytes:
        # ALWAYS envelope — an unwrapped inner blob that happened to begin
        # with TX_MAGIC would be misparsed as a lock header on restore
        inner = self.app.checkpoint(name)
        holder = self.locks.get(name)
        meta = {"holder": holder}
        dl = self.lock_deadlines.get(name)
        if holder is not None and dl:
            meta["deadline"] = dl
        return TX_MAGIC + json.dumps(meta).encode() + b"\x00" + inner

    def restore(self, name: str, state: bytes) -> None:
        if state.startswith(TX_MAGIC):
            body = state[len(TX_MAGIC):]
            sep = body.find(b"\x00")
            try:
                meta = json.loads(body[:sep].decode())
            except (ValueError, UnicodeDecodeError):
                meta = None  # raw client state that collides with the magic
            if meta is not None:
                if meta.get("holder") is None:
                    self.locks.pop(name, None)
                    self.lock_deadlines.pop(name, None)
                else:
                    self.locks[name] = meta["holder"]
                    dl = int(meta.get("deadline") or 0)
                    if dl > 0:
                        self.lock_deadlines[name] = dl
                    else:
                        self.lock_deadlines.pop(name, None)
                self.app.restore(name, body[sep + 1:])
                return
        # plain state (client-provided initial state / legacy checkpoint)
        self.locks.pop(name, None)
        self.lock_deadlines.pop(name, None)
        self.app.restore(name, state)


class TxResult:
    def __init__(self, txid: str):
        self.txid = txid
        self.committed = False
        self.aborted = False
        #: True when wait() gave up before the transaction finished — the
        #: background worker may STILL commit later; callers must not treat
        #: a timed-out result as a clean abort (retrying would double-apply)
        self.timed_out = False
        self.error: Optional[str] = None
        #: per-op results, aligned with the ops list (a name may appear in
        #: several ops; keying by name would drop all but the last)
        self.results: List[Optional[bytes]] = []
        self._ev = threading.Event()

    def result_for(self, name: str, ops=None) -> Optional[bytes]:
        """Convenience: the last result for ``name`` (ops optional when the
        transactor recorded them)."""
        ops = ops if ops is not None else self._ops
        for i in range(len(self.results) - 1, -1, -1):
            if ops[i][0] == name:
                return self.results[i]
        return None

    def wait(self, timeout: float = 30.0) -> "TxResult":
        self.timed_out = not self._ev.wait(timeout)
        return self

    def _finish(self) -> None:
        self._ev.set()


class DistTransactor:
    """Drives multi-name transactions through any coordinator SPI.

    ``coordinate(name, payload, callback)`` is the single dependency — bind
    it to ``AbstractReplicaCoordinator.coordinate_request`` (server side) or
    to an async client's ``send_request`` (client side).
    """

    def __init__(
        self,
        coordinate: Callable[[str, bytes, Callable[[Optional[bytes]], None]], object],
        max_lock_retries: int = 20,
        retry_delay_s: float = 0.05,
        lock_ttl_s: Optional[float] = None,
    ):
        """``lock_ttl_s``: bound every acquired lock's hold time (PR-14
        wire-deadline unit under the hood).  A transactor that crashes
        between lock and commit then no longer wedges the participants —
        the next conflicting transaction's stamped op expires the stale
        lock.  None (default) keeps the original hold-forever semantics."""
        self.coordinate = coordinate
        self.max_lock_retries = max_lock_retries
        self.retry_delay_s = retry_delay_s
        self.lock_ttl_s = lock_ttl_s

    # ------------------------------------------------------------------ public
    def transact(
        self,
        ops: List[Tuple[str, bytes]],
        callback: Optional[Callable[[TxResult], None]] = None,
    ) -> TxResult:
        """Atomically execute ``ops`` = [(name, request), ...] across names.

        Runs asynchronously; returns a :class:`TxResult` whose ``wait()``
        blocks for completion.  All-or-nothing: either every op executes
        under locks (committed) or none do (aborted)."""
        txid = uuid.uuid4().hex[:16]
        res = TxResult(txid)
        res._ops = list(ops)
        t = threading.Thread(
            target=self._run, args=(ops, res, callback),
            name=f"tx-{txid}", daemon=True,
        )
        t.start()
        return res

    # ----------------------------------------------------------------- phases
    def _call(self, name: str, payload: bytes,
              timeout: float = 15.0) -> Optional[bytes]:
        ev = threading.Event()
        box: List[Optional[bytes]] = [None]

        def cb(*args) -> None:
            # server SPI callbacks are (rid, resp); client ones may be (resp)
            r = args[-1]
            if isinstance(r, dict):
                # client binding (send_request) delivers the raw response
                # packet {ok, response(b64), error}; unwrap to the app payload
                # so the TX_OK/TX_LOCKED comparisons below see real bytes
                from ..reconfiguration import packets as pkt

                r = (pkt.b64d(r.get("response")) or b"") if r.get("ok") else None
            box[0] = r
            ev.set()

        r = self.coordinate(name, payload, cb)
        if r is None:
            return None
        if not ev.wait(timeout):
            return None
        return box[0]

    def _run(self, ops, res: TxResult, callback) -> None:
        import time

        def now_ms() -> Optional[int]:
            # stamp ops only when expiry is enabled: unstamped payloads
            # keep the original bytes, so existing journals/tests are
            # byte-identical when lock_ttl_s is None
            return int(time.time() * 1000) if self.lock_ttl_s else None

        names = sorted({n for n, _ in ops})  # global order = deadlock freedom
        held: List[str] = []
        try:
            # ---- phase 1 (prepare): lock every participant, in order
            for n in names:
                # mark as possibly-held BEFORE the first attempt: a lock
                # proposal whose reply times out can still commit later, and
                # the abort path must unlock it or the name wedges forever
                # (unlock of a never-acquired lock is an idempotent no-op)
                held.append(n)
                acquired = False
                for attempt in range(self.max_lock_retries):
                    dl = (None if self.lock_ttl_s is None
                          else int(time.time() * 1000
                                   + self.lock_ttl_s * 1000))
                    r = self._call(n, tx_payload("lock", res.txid,
                                                 now=now_ms(), deadline=dl))
                    if r == b"TX_OK":
                        acquired = True
                        break
                    if r is None:
                        break  # unknown name / stopped epoch: abort
                    time.sleep(self.retry_delay_s * (attempt + 1))
                if not acquired:
                    res.aborted = True
                    res.error = f"lock failed on {n}"
                    return
            # ---- phase 2 (commit): execute under locks
            for n, payload in ops:
                r = self._call(n, tx_payload("exec", res.txid, payload,
                                             now=now_ms()))
                if r is None or r == TX_LOCKED:
                    # lock lost (epoch change mid-tx or our lease expired
                    # under a rival's stamp): abort — executed ops on
                    # other names are NOT rolled back, matching the
                    # experimental reference's semantics; see module doc
                    res.aborted = True
                    res.error = f"exec failed on {n}"
                    return
                res.results.append(r)
            res.committed = True
        finally:
            # release on abort covers the expired-txid case too: unlock is
            # holder-checked, so releasing a lock a rival already expired
            # and re-acquired is a no-op rather than a theft
            for n in held:
                self._call(n, tx_payload("unlock", res.txid, now=now_ms()))
            res._finish()
            if callback is not None:
                callback(res)

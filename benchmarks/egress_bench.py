"""Coordinator egress economics of the ordering/dissemination split
(ISSUE 12; HT-Paxos / HT-Ring Paxos, arxiv 1407.1237 / 1507.04086).

Before the split, every decision's payload fanned out from the
coordinator to R-1 peers, so coordinator bytes/decision grew linearly
with replica count — the tax the 3R -> 5R drop in
``results_stack_pr5.json`` measures.  With digest ordering the frames
carry rids only and payload bytes ride the dissemination ring (one
downstream send per node per tick), so the ingress node's egress per
decision is ~constant in R.

This bench drives KB-payload writes through a SimNet Mode B cluster at
R in {3, 5, 7} — ALL traffic entering at N0, the payload origin whose
egress the split is about — and reads that node's egress straight off
the node stats the `egress_bytes_per_decision` gauge is built from
(frame_bytes_sent + relay_bytes_sent).  Every write is exactly one
Paxos decision and every arm commits all of them (asserted), so the
per-decision denominator is the committed write count.  Two arms:

* ``ring on``  — digest ordering + ring dissemination (the new default
  shape at scale): bytes/decision must stay ~flat (exit criterion:
  7R <= 1.2x the 3R value);
* ``ring off`` — digest ordering with the pre-split entry broadcast:
  bytes/decision must grow ~linearly in R (each payload still leaves the
  entry node R-1 times).

Usage:  python benchmarks/egress_bench.py [--payload 16384] [--writes 24]
        [--json out.json]
Prints one JSON line per (R, arm) and, with --json, writes the artifact
consumed by run_artifacts.py (results_egress_pr12.json).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPLICAS = (3, 5, 7)


def run_arm(R: int, ring: bool, payload_bytes: int, writes: int) -> dict:
    from gigapaxos_tpu.config import GigapaxosTpuConfig
    from gigapaxos_tpu.modeb import ModeBNode
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.testing.simnet import SimNet

    ids = [f"N{i}" for i in range(R)]
    net = SimNet(seed=7)
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 8
    cfg.paxos.window = 8
    cfg.paxos.digest_accepts = True
    cfg.paxos.ring_dissemination = ring
    apps = {n: KVApp() for n in ids}
    nodes = {n: ModeBNode(cfg, ids, n, apps[n], net.messenger(n),
                          anti_entropy_every=8) for n in ids}
    for nd in nodes.values():
        nd.create_group("svc", list(range(R)))

    def pump(k: int) -> None:
        for _ in range(k):
            for nd in nodes.values():
                nd.tick()
            net.pump()

    # settle coordinatorship on N0 (slot 0) before measuring
    warm = []
    nodes["N0"].propose("svc", b"PUT warm 1",
                        lambda _r, resp: warm.append(resp))
    pump(20)
    assert warm == [b"OK"], warm
    n0 = nodes["N0"]
    for k in ("frame_bytes_sent", "relay_bytes_sent"):
        n0.stats[k] = 0

    body = "x" * payload_bytes
    done = []
    t0 = time.perf_counter()
    for i in range(writes):
        nodes["N0"].propose("svc", f"PUT k{i} {body}".encode(),
                            lambda _r, resp: done.append(resp))
        pump(3)
    pump(30)
    dt = time.perf_counter() - t0

    ok = sum(1 for r in done if r == b"OK")
    assert ok == writes, (ok, writes)
    egress = n0.stats["frame_bytes_sent"] + n0.stats["relay_bytes_sent"]
    # every node converged on every write
    dbs = [apps[n].db.get("svc", {}) for n in ids]
    assert all(d == dbs[0] for d in dbs)
    return {
        "replicas": R,
        "ring": ring,
        "payload_bytes": payload_bytes,
        "writes": writes,
        "decisions": int(ok),
        "egress_bytes": int(egress),
        "egress_bytes_per_decision": round(egress / ok, 1),
        "relay_bytes_sent": int(n0.stats["relay_bytes_sent"]),
        "commits_per_s": round(ok / dt, 1),
        "undigest_fills": int(sum(nd.stats["undigest_fills"]
                                  for nd in nodes.values())),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--payload", type=int, default=16384)
    ap.add_argument("--writes", type=int, default=24)
    ap.add_argument("--json", default=None, help="artifact output path")
    args = ap.parse_args()

    runs = []
    for ring in (True, False):
        for R in REPLICAS:
            r = run_arm(R, ring, args.payload, args.writes)
            print(json.dumps(r))
            runs.append(r)

    def bpd(R: int, ring: bool) -> float:
        return next(r["egress_bytes_per_decision"] for r in runs
                    if r["replicas"] == R and r["ring"] is ring)

    ratio_on = bpd(7, True) / bpd(3, True)
    ratio_off = bpd(7, False) / bpd(3, False)
    gate_pass = ratio_on <= 1.2 and ratio_off > 1.5
    result = {
        "bench": "egress",
        "payload_bytes": args.payload,
        "writes_per_arm": args.writes,
        "ring_on_7R_over_3R": round(ratio_on, 3),
        "ring_off_7R_over_3R": round(ratio_off, 3),
        "gate": "ring-on bytes/decision at 7R <= 1.2x 3R; "
                "ring-off grows > 1.5x",
        "gate_pass": gate_pass,
        "runs": runs,
    }
    print(json.dumps({k: v for k, v in result.items() if k != "runs"}))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        print(f"wrote {args.json}")
    return 0 if gate_pass else 1


if __name__ == "__main__":
    sys.exit(main())

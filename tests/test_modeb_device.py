"""Device app in the per-process (Mode B) deployment: each node owns a
1-replica-axis DeviceKVState; its OWN row's decisions execute on device
inside the fused node tick (descriptor upload + consensus + KV apply in one
program — the per-machine deployment shape of PaxosManager.java:108-111
with the TESTPaxosApp workload moved into device arrays).
"""

import struct

import numpy as np
from test_modeb import IDS, Cluster, make_cfg

from gigapaxos_tpu.models.device_kv import OP_DEL, OP_GET, OP_PUT, pack_desc


def _device_cfg(groups=16):
    cfg = make_cfg(groups=groups)
    cfg.paxos.device_app = True
    return cfg


def kv_of(node, row):
    return (np.asarray(node.kv.key[0, row]), np.asarray(node.kv.val[0, row]))


def test_device_commit_roundtrip_and_convergence():
    cl = Cluster(_device_cfg())
    try:
        cl.create("svc")
        # PUTs entering at different nodes; responses echo the value
        for i, nid in enumerate(IDS * 2):
            resp = cl.commit(nid, "svc", pack_desc(OP_PUT, i + 1, 100 + i))
            assert resp == struct.pack("<i", 100 + i), (nid, resp)
        # GET returns the stored value
        resp = cl.commit("N1", "svc", pack_desc(OP_GET, 3, 0))
        assert resp == struct.pack("<i", 102)
        # DEL removes; subsequent GET sees absent
        assert cl.commit("N2", "svc", pack_desc(OP_DEL, 3, 0)) == \
            struct.pack("<i", 102)
        assert cl.commit("N0", "svc", pack_desc(OP_GET, 3, 0)) == \
            struct.pack("<i", 0)
        cl.ticks(20)
        # every node's device row converged (state machine replication
        # across INDEPENDENT device states)
        row = {nid: cl.nodes[nid].rows.row("svc") for nid in IDS}
        k0, v0 = kv_of(cl.nodes["N0"], row["N0"])
        for nid in ("N1", "N2"):
            k, v = kv_of(cl.nodes[nid], row[nid])
            assert (k == k0).all() and (v == v0).all(), nid
        # the device fast path actually ran (not everything via scalar)
        execs = sum(cl.nodes[nid].stats["executions"] for nid in IDS)
        assert execs >= 6 * 3
    finally:
        cl.close()


def test_device_miss_routes_scalar_and_state_converges():
    """A row whose descriptor misses on device (payload arrived but upload
    raced the commit, emulated by clearing the pending upload) is
    suppressed on device and re-applied host-side in order."""
    cl = Cluster(_device_cfg())
    try:
        cl.create("svc")
        cl.ticks(5)
        # force a miss at the coordinator N0: sabotage its upload staging
        # for one proposal so the commit exec precedes the descriptor
        n0 = cl.nodes["N0"]
        done = []
        rid = n0.propose("svc", pack_desc(OP_PUT, 7, 777),
                         lambda _r, resp: done.append(resp))
        assert rid is not None
        # drop the staged descriptor (it is re-staged by nothing — the
        # scalar path must recover from the payload in outstanding)
        n0._kv_pending.clear()
        for _ in range(120):
            cl.ticks(1)
            if done:
                break
        assert done and done[0] == struct.pack("<i", 777)
        cl.ticks(10)
        row = n0.rows.row("svc")
        k, v = kv_of(n0, row)
        assert 777 in v
        # peers converge too (their descriptors arrived via frames)
        for nid in ("N1", "N2"):
            r = cl.nodes[nid].rows.row("svc")
            kk, vv = kv_of(cl.nodes[nid], r)
            assert 777 in vv, nid
    finally:
        cl.close()


def test_device_crash_recovery_from_own_journal(tmp_path):
    """SIGKILL-equivalent: node dies, survivors commit on, the node
    restarts from ITS OWN journal with identical device arrays and rejoins."""
    cl = Cluster(_device_cfg(), wal_root=tmp_path)
    try:
        cl.create("svc")
        for i in range(6):
            assert cl.commit(IDS[i % 3], "svc",
                             pack_desc(OP_PUT, i + 1, 10 + i)) == \
                struct.pack("<i", 10 + i)
        cl.ticks(10)
        row1 = cl.nodes["N1"].rows.row("svc")
        pre_k, pre_v = kv_of(cl.nodes["N1"], row1)
        cl.kill("N1")
        cl.drop_backlog("N1")
        assert cl.commit("N0", "svc", pack_desc(OP_PUT, 2, 999),
                         only=("N0", "N2")) == struct.pack("<i", 999)
        node = cl.restart("N1")
        row1 = node.rows.row("svc")
        rk, rv = kv_of(node, row1)
        assert (rk == pre_k).all() and (rv == pre_v).all()
        # catches up with the commit it missed (checkpoint/laggard repair)
        import time

        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            cl.ticks(2)
            rk, rv = kv_of(node, node.rows.row("svc"))
            if 999 in rv:
                break
        assert 999 in rv
        # and serves new device-mode commits
        assert cl.commit("N1", "svc", pack_desc(OP_GET, 2, 0)) == \
            struct.pack("<i", 999)
    finally:
        cl.close()


def test_device_row_lifecycle_no_leak_and_pause_preserves():
    """A removed group's recycled row must not leak its keys to the next
    occupant, and pause/unpause must carry the device row's state."""
    cfg = _device_cfg(groups=4)
    cfg.paxos.deactivation_ticks = 1
    cl = Cluster(cfg)
    try:
        cl.create("old")
        assert cl.commit("N0", "old", pack_desc(OP_PUT, 5, 77)) == \
            struct.pack("<i", 77)
        cl.ticks(5)
        for n in cl.nodes.values():
            n.remove_group("old")
        cl.ticks(3)
        cl.create("fresh")  # recycles the freed row on every node
        # the previous occupant's key must be gone
        assert cl.commit("N1", "fresh", pack_desc(OP_GET, 5, 0)) == \
            struct.pack("<i", 0)

        # pause/unpause: spill the group, then traffic demand-pages it back
        assert cl.commit("N0", "fresh", pack_desc(OP_PUT, 2, 42)) == \
            struct.pack("<i", 42)
        cl.ticks(5)
        for n in cl.nodes.values():
            with n.lock:
                n.pause_idle(limit=4, ignore_idle=True)
        assert all("fresh" in n._paused for n in cl.nodes.values())
        assert cl.commit("N2", "fresh", pack_desc(OP_GET, 2, 0)) == \
            struct.pack("<i", 42)
    finally:
        cl.close()

"""Metrics core: counters, gauges, fixed log-bucket histograms.

Design constraints (this sits inside the tick hot path):

* **Allocation-free observation.**  ``Histogram.observe`` converts the
  sample to integer microseconds and indexes a preallocated bucket list by
  ``int.bit_length()`` — no float math beyond one multiply, no dict lookups,
  no allocation.
* **Lock-light.**  Single increments ride CPython's atomic int ops (the
  same contract ``Transport.stats`` already relies on); the registry lock is
  taken only at metric *creation* and at render/snapshot time.
* **Compile-out switch.**  ``GPTPU_METRICS=0`` makes :func:`registry` hand
  back a null registry whose metrics are shared no-op singletons, so every
  instrumentation site degenerates to one attribute lookup + empty call.
  The switch is read once at import (hot paths bind metric objects at
  construction, not per-observation), which is what makes the
  ``benchmarks/obs_overhead.py`` A/B honest: both arms run identical site
  code, only the bound objects differ.

Buckets are powers of two in the sample's base unit (microseconds for
``unit="s"`` histograms, raw integers otherwise), so bucket ``i`` holds
samples with ``int(v).bit_length() == i`` — upper bound ``2**i - 1``.
64 buckets cover < 1 us .. > 2 centuries; percentile error is bounded by
the 2x bucket width, which is the right trade for an always-on plane.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, List, Tuple


def _env_metrics_enabled() -> bool:
    val = os.environ.get("GPTPU_METRICS", "")
    return val.strip().lower() not in ("0", "false", "off", "no")


#: Read once at import; hot paths bind metric objects at construction time,
#: so flipping this mid-process would not (and must not) take effect.
METRICS_ENABLED = _env_metrics_enabled()

N_BUCKETS = 64


def metrics_enabled() -> bool:
    """True unless the process was started with ``GPTPU_METRICS=0``."""
    return METRICS_ENABLED


def _freeze(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter.  ``inc`` is a single int add (GIL-atomic)."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.labels = labels
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1) -> None:
        self.value += n


class Histogram:
    """Fixed log-bucket histogram.

    ``unit="s"`` histograms take float seconds and bucket by integer
    microseconds; ``unit=""`` histograms take raw non-negative numbers
    (batch sizes, frame counts).  ``observe`` never allocates.
    """

    __slots__ = ("name", "labels", "unit", "buckets", "count", "total",
                 "_scale")

    def __init__(self, name: str,
                 labels: Tuple[Tuple[str, str], ...] = (),
                 unit: str = "s"):
        self.name = name
        self.labels = labels
        self.unit = unit
        self._scale = 1e6 if unit == "s" else 1.0
        self.buckets: List[int] = [0] * N_BUCKETS
        self.count = 0
        self.total = 0.0

    def observe(self, v: float) -> None:
        raw = int(v * self._scale)
        if raw < 0:
            raw = 0
        i = raw.bit_length()
        if i >= N_BUCKETS:
            i = N_BUCKETS - 1
        self.buckets[i] += 1
        self.count += 1
        self.total += v

    # -------------------------------------------------------------- queries
    def bucket_upper(self, i: int) -> float:
        """Inclusive upper bound of bucket ``i`` in the observe() unit."""
        return ((1 << i) - 1) / self._scale

    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile sample.

        Error is bounded by the bucket width (a factor of 2), which is the
        always-on trade; exact latencies come from reqtrace / bench runs.
        """
        n = self.count
        if n == 0:
            return 0.0
        # rank of the q-quantile sample, 1-based, clamped into [1, n]
        rank = min(max(int(q * n) + (0 if q * n == int(q * n) else 1), 1), n)
        cum = 0
        for i, c in enumerate(self.buckets):
            cum += c
            if cum >= rank:
                return self.bucket_upper(i)
        return self.bucket_upper(N_BUCKETS - 1)


class _NullMetric:
    """Shared no-op twin: every mutator is an empty method."""

    __slots__ = ()
    name = "null"
    labels: Tuple[Tuple[str, str], ...] = ()
    unit = ""
    value = 0
    count = 0
    total = 0.0
    buckets: List[int] = []

    def inc(self, n=1) -> None:
        pass

    def set(self, v) -> None:
        pass

    def observe(self, v) -> None:
        pass

    def percentile(self, q) -> float:
        return 0.0

    def bucket_upper(self, i) -> float:
        return 0.0


_NULL_METRIC = _NullMetric()


class Registry:
    """Get-or-create store keyed by (name, frozen labels).

    One process-wide default instance backs :func:`registry`; tests create
    private ones.  The lock guards only creation and iteration — observation
    goes straight at the returned metric object.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}
        self._help: Dict[str, str] = {}

    def _get(self, cls, name: str, labels: Dict[str, str], help_: str,
             **kw):
        key = (name, _freeze(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, key[1], **kw)
                    self._metrics[key] = m
                    if help_ and name not in self._help:
                        self._help[name] = help_
        return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get(Counter, name, labels, help)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get(Gauge, name, labels, help)

    def histogram(self, name: str, help: str = "", unit: str = "s",
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, help, unit=unit)

    # ------------------------------------------------------------ inspection
    def metrics(self) -> Iterable[object]:
        with self._lock:
            return list(self._metrics.values())

    def help_text(self, name: str) -> str:
        return self._help.get(name, "")

    def find(self, name: str) -> List[object]:
        with self._lock:
            return [m for (n, _), m in self._metrics.items() if n == name]

    def snapshot(self) -> dict:
        """Flat JSON-able dump (flight-recorder / StatsReporter payload)."""
        out = {}
        for m in self.metrics():
            key = m.name
            if m.labels:
                key += "{" + ",".join(f"{k}={v}" for k, v in m.labels) + "}"
            if isinstance(m, Histogram):
                out[key] = {
                    "count": m.count,
                    "sum": round(m.total, 6),
                    "p50": m.percentile(0.50),
                    "p90": m.percentile(0.90),
                    "p99": m.percentile(0.99),
                }
            else:
                out[key] = m.value
        return out


class NullRegistry(Registry):
    """Hands out the shared no-op metric: the GPTPU_METRICS=0 arm."""

    def _get(self, cls, name, labels, help_, **kw):
        return _NULL_METRIC

    def metrics(self):
        return []

    def snapshot(self) -> dict:
        return {}


_DEFAULT = Registry()
_NULL = NullRegistry()


def registry() -> Registry:
    """The process default registry (null twin under ``GPTPU_METRICS=0``)."""
    return _DEFAULT if METRICS_ENABLED else _NULL

"""Ring-buffer window primitives for ``[..., W, G]`` arrays (G = lane axis).

The reference keeps per-group sparse maps ``acceptedProposals`` and
``committedRequests`` keyed by slot (``PaxosAcceptor.java:108-115``) whose
size is bounded in practice by the out-of-order arrival window.  Here each
group owns a fixed ring of W slots: slot ``s`` lives at ring plane
``s & (W-1)`` (the second-to-last axis) and an entry is valid only for slots
in ``[exec_slot, exec_slot + W)``.  In-order extraction
(``PaxosAcceptor.putAndRemoveNextExecutable``, PaxosAcceptor.java:325-366)
becomes a leading-run count over the reordered window — branch-free and
lane-parallel.  W stays off the lane axis on purpose: a minor dimension of 8
pads to 128 on TPU (16x HBM blowup); see state.py's layout note.
"""

from __future__ import annotations

import jax.numpy as jnp


def ring_index(slots, window: int):
    """Ring index for (possibly wrapped) int32 slot numbers. W power of two."""
    return jnp.bitwise_and(slots.astype(jnp.int32), jnp.int32(window - 1))


def window_slots(exec_slot, window: int):
    """Absolute slots covered by each group's window, in window order.

    ``exec_slot``: ``[..., G]`` -> ``[..., W, G]`` with plane j holding
    exec_slot + j (plane axis = second-to-last, per the module layout)."""
    ar = jnp.arange(window, dtype=jnp.int32)
    return exec_slot[..., None, :] + ar[:, None]


def in_window(slots, exec_slot, window: int):
    """True where ``slots`` (``[..., W, G]``) fall inside
    [exec_slot, exec_slot+W) for their group (wraparound-aware);
    ``exec_slot``: ``[..., G]``."""
    d = (slots - exec_slot[..., None, :]).astype(jnp.int32)
    return (d >= 0) & (d < window)


def leading_run(valid):
    """Number of leading True along the plane (second-to-last) axis per
    group: how many consecutive in-order entries are ready.
    ``valid``: bool ``[..., W, G]`` -> int32 ``[..., G]``."""
    return jnp.sum(jnp.cumprod(valid.astype(jnp.int32), axis=-2), axis=-2)


def gather_planes(arr, idx):
    """Gather along the plane (second-to-last) axis via one-hot selects.

    ``arr``: ``[..., Wp, G]``; ``idx``: ``[..., J, G]`` int32 in [0, Wp).
    Returns ``out[..., j, g] = arr[..., idx[..., j, g], g]``.

    PRECONDITION: every idx value must be in [0, Wp) — callers pass mod-W /
    clamped ring indices.  Out-of-range indices are UNDEFINED and the two
    implementations genuinely diverge there (the pallas kernel yields 0,
    this one-hot fallback yields plane 0's value); never rely on either.

    This is the TPU-friendly form of ``take_along_axis`` for ring windows:
    the G (lane) axis stays minor and fully parallel, and the Wp-way select
    unrolls into Wp fused ``where`` ops instead of a hardware gather along a
    non-lane axis.  Wp is the ring depth (small, e.g. 8).

    On TPU backends the select chain is executed by a pallas kernel that
    keeps the Wp-way work in VMEM (ops/pallas_gather.py) — the XLA
    formulation materializes the broadcast temporaries in HBM and was
    measured at >99% of the fused tick's time at W=8, G=1M.  This one-hot
    path remains the portable fallback and semantic reference.
    """
    from .pallas_gather import use_pallas_gather

    if (
        use_pallas_gather()
        and arr.ndim >= 2
        and arr.shape[-1] % 128 == 0
        and (idx.ndim == 2 or idx.shape == arr.shape[:-2] + idx.shape[-2:])
    ):
        from .pallas_gather import gather_planes_pallas

        return gather_planes_pallas(arr, idx)
    wp = arr.shape[-2]
    res = None
    for w in range(wp):
        plane = arr[..., w : w + 1, :]  # [..., 1, G]
        # every idx value lies in [0, wp), so each position is overwritten
        # by its matching plane exactly once
        res = plane if res is None else jnp.where(idx == w, plane, res)
    target = jnp.broadcast_shapes(res.shape, idx.shape)
    return jnp.broadcast_to(res, target) if res.shape != target else res


def match_planes(vals, keys, idx):
    """Per-lane key-match select: ``out[..., j, g] = vals[..., e, g]`` for
    the entry ``e`` with ``keys[..., e, g] == idx[..., j, g]`` (0 when no
    entry matches; keys must be unique per lane among entries that can
    match).

    The generalization of :func:`gather_planes` from plane-number indices to
    arbitrary per-lane keys — used by the intake stage to place the
    rank-q taken request onto its ring plane without a sort (argsort over
    the request axis was measured at ~2/3 of the whole fused tick on TPU;
    sort lowers catastrophically there, and this E-way select keeps the
    lane axis fully parallel).
    """
    from .pallas_gather import use_pallas_gather

    if (
        use_pallas_gather()
        and vals.ndim == 2
        and keys.shape == vals.shape
        and idx.ndim == 2
        and vals.shape[-1] % 128 == 0
    ):
        from .pallas_gather import match_planes_pallas

        return match_planes_pallas(vals, keys, idx)
    e_planes = vals.shape[-2]
    res = jnp.zeros(vals.shape[:-2] + idx.shape[-2:], vals.dtype)
    for e in range(e_planes):
        res = jnp.where(
            keys[..., e : e + 1, :] == idx, vals[..., e : e + 1, :], res
        )
    return res


def clear_below(arr, slot_of_entry, watermark, fill):
    """Invalidate ring entries whose slot is below ``watermark``.

    ``arr``: payload ``[..., W, G]``; ``slot_of_entry``: the absolute slot each
    ring entry claims to hold ``[..., W, G]``; ``watermark``: ``[..., G]``.
    Entries with slot < watermark are replaced by ``fill``.
    """
    stale = (slot_of_entry - watermark[..., None, :]).astype(jnp.int32) < 0
    return jnp.where(stale, fill, arr)

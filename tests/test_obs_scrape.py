"""Flight-deck end-to-end: per-node scrape endpoint, crash flight
recorder, the host-level supervisor scrape over 2 serving cells, trace
propagation across a cell-forwarded request, and the SIGKILL postmortem
(ISSUE 9 tentpole acceptance + satellite 3)."""

import json
import os
import threading
import time
import urllib.request

import pytest

from gigapaxos_tpu.config import CellsConfig
from gigapaxos_tpu.obs.flight import FlightRecorder
from gigapaxos_tpu.obs.http import MetricsServer
from gigapaxos_tpu.obs.metrics import Registry
from gigapaxos_tpu.obs.prom import render_registry


def _get(url: str, timeout: float = 10.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8")


# ---------------------------------------------------------- node endpoint
def test_metrics_server_serves_scrape_trace_and_flight(tmp_path):
    reg = Registry()
    reg.counter("up_total", help="x", node="n0").inc(2)
    reg.histogram("lat_seconds").observe(0.003)
    fr = FlightRecorder(str(tmp_path / "f.json"), node="n0")
    fr.record("boot", pid=os.getpid())
    srv = MetricsServer(
        lambda: render_registry(reg, extra_labels={"node": "n0"}),
        trace=lambda tid: {"tid": tid, "events": []},
        flight=lambda: FlightRecorder.read(fr.persist()),
        port=0)
    try:
        body = _get(srv.url + "/metrics")
        assert 'up_total{node="n0"} 2' in body
        assert "lat_seconds_bucket" in body and "lat_seconds_p99" in body
        t = json.loads(_get(srv.url + "/trace/123"))
        assert t["tid"] == "123"
        t_all = json.loads(_get(srv.url + "/trace"))
        assert t_all["tid"] is None
        fl = json.loads(_get(srv.url + "/flight"))
        assert fl["node"] == "n0"
        assert any(ev["kind"] == "boot" for ev in fl["events"])
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url + "/nope")
        assert ei.value.code == 404
    finally:
        srv.close()
    # closed server: port must actually be released for quick restart
    with pytest.raises(Exception):
        _get(srv.url + "/metrics", timeout=1.0)


def test_flight_recorder_ring_persist_and_sigusr2_style_dump(tmp_path):
    path = str(tmp_path / "sub" / "flight.json")
    fr = FlightRecorder(path, cap=8, node="c0", persist_every_s=0.0)
    for i in range(20):
        fr.record("ev", i=i)
    fr.snapshot_sink({"node": "c0", "ticks": 7})
    out = fr.dump(reason="test")
    assert out == path
    doc = FlightRecorder.read(path)
    assert doc["node"] == "c0" and doc["pid"] == os.getpid()
    kinds = [e["kind"] for e in doc["events"]]
    # bounded ring: only the newest cap events survive, newest last
    assert len(doc["events"]) == 8
    assert kinds[-1] == "dump" and doc["events"][-1]["reason"] == "test"
    assert any(k == "stats" for k in kinds)
    assert doc["dumps"] == 1
    # continuous persistence: a plain record() past the debounce rewrites
    # the artifact without any dump() — that is what survives SIGKILL
    fr.record("after", x=1)
    assert any(e["kind"] == "after"
               for e in FlightRecorder.read(path)["events"])


# ----------------------------------------------------- 2-cell supervisor e2e
def _mk_supervisor(base_dir, n_cells=2, **kw):
    from gigapaxos_tpu.cells.supervisor import CellSupervisor

    cc = CellsConfig(enabled=True, n_cells=n_cells, n_actives=3,
                     n_reconfigurators=1, pin_cores=False,
                     restart_backoff_s=0.2)
    kw.setdefault("paxos_overrides", {"max_groups": 16})
    return CellSupervisor(str(base_dir), cells=cc, **kw)


@pytest.mark.slow
def test_supervisor_host_scrape_two_cells(tmp_path):
    """THE acceptance check: curl the supervisor endpoint on a live 2-cell
    deployment -> one Prometheus body with per-cell tick-phase histograms,
    commit-latency percentiles and supervisor gauges."""
    sup = _mk_supervisor(tmp_path / "cells", http_port=0).start()
    try:
        c = sup.make_client()
        names = [f"s{i}" for i in range(4)]
        for n in names:
            assert c.create(n).get("ok"), n
        for i, n in enumerate(names):
            assert c.request(n, f"PUT k{i} v{i}".encode()) == b"OK"
        assert sup.metrics_server is not None
        body = _get(sup.metrics_server.url + "/metrics", timeout=60)
        lines = body.splitlines()

        # every cell exported its own series, cell-labelled
        for cell in ("0", "1"):
            assert any(f'cell="{cell}"' in l
                       and l.startswith("tick_phase_seconds_bucket")
                       for l in lines), f"cell {cell} phase histograms"
        # always-on phase timing covers the Mode A tick breakdown
        for phase in ("intake", "dispatch", "wal_fsync", "execute"):
            assert any(f'phase="{phase}"' in l for l in lines), phase
        # commit-latency SLO percentiles at the ActiveReplica
        assert any(l.startswith("commit_latency_seconds_p50") for l in lines)
        assert any(l.startswith("commit_latency_seconds_p99") for l in lines)
        # WAL + transport planes surfaced too
        assert any(l.startswith("wal_fsync_seconds_count") for l in lines)
        assert any(l.startswith("transport_sent_total") for l in lines)
        # supervisor's own gauges ride the same scrape
        assert 'cell_up{cell="0",node="SUP"} 1' in lines
        assert 'cell_up{cell="1",node="SUP"} 1' in lines
        assert any(l.startswith('cell_restarts_total{cell="0"')
                   for l in lines)
        assert any(l.startswith("supervisor_restart_backoff_seconds")
                   for l in lines)
        # merged metadata is deduplicated (Prometheus rejects dup HELP)
        meta = [l for l in lines if l.startswith("# TYPE tick_phase_seconds ")]
        assert len(meta) == 1
        c.close()
    finally:
        sup.stop()


@pytest.mark.slow
def test_trace_propagates_across_cell_forwarding(tmp_path):
    """Cross-process tracing: a client-minted trace id stamped on the wire
    survives the edge hop into the owner cell — the merged supervisor
    timeline shows client_sent -> (edge_forward ->) ar_recv ->
    ar_responded -> client_responded, with per-process origins."""
    from gigapaxos_tpu.reconfiguration import packets as pkt

    sup = _mk_supervisor(tmp_path / "cells", edge=True).start()
    try:
        c = sup.make_client()
        # one name per cell, picked by hash owner: whichever cell the edge
        # connection lands on, at least one request must be forwarded
        picks = {}
        for i in range(64):
            n = f"t{i}"
            k = sup.router.cell(n)
            if k not in picks:
                picks[k] = n
            if len(picks) == 2:
                break
        assert len(picks) == 2, picks
        picks = sorted(picks.values())
        for n in picks:
            assert c.create(n).get("ok"), n

        ec = sup.make_client()
        ec.trace.enabled = True  # the one switch: stamps ids on the wire
        ec.nodemap.add("EDGE", sup.edge_addr[0], int(sup.edge_addr[1]))
        for n in picks:
            assert c.request(n, f"PUT x.{n} 7".encode()) == b"OK"
            done = threading.Event()
            box = {}

            def cb(p, box=box, done=done):
                box.update(p)
                done.set()

            ec.send_request(n, f"GET x.{n}".encode(), cb, active="EDGE")
            assert done.wait(60), f"edge request for {n} timed out"
            assert box.get("ok"), box
            assert pkt.b64d(box["response"]) == b"7"

        merged = sup.trace()
        assert merged, "no cross-process timelines recorded"
        stages_by_tid = {
            tid: [(ev[0], ev[2]) for ev in evs]  # (origin, stage)
            for tid, evs in merged.items()
        }
        # the client-side bracket is recorded in the supervisor/test
        # process; the AR-side hops in a worker process, merged over the
        # control socket
        flat = [(o, s) for evs in stages_by_tid.values() for o, s in evs]
        assert ("SUP", "client_sent") in flat
        assert ("SUP", "client_responded") in flat
        assert any(o.startswith("c") and s == "ar_recv" for o, s in flat)
        assert any(o.startswith("c") and s == "ar_responded"
                   for o, s in flat)
        # the cross-cell hop itself: recorded by the NON-owner cell
        assert any(s == "edge_forward" for _o, s in flat), flat
        # single-timeline fetch matches the merged view
        tid = next(iter(merged))
        one = sup.trace(tid)
        assert list(one) == [tid]
        ec.close()
        c.close()
    finally:
        sup.stop()


@pytest.mark.slow
def test_flight_recorder_survives_sigkill_via_chaos_runner(tmp_path):
    """A SIGKILL'd cell gets no last words — its continuously-persisted
    flight artifact is the postmortem, and ProcChaosRunner threads the
    path into the chaos log."""
    from gigapaxos_tpu.testing.chaos import (ChaosEvent, ChaosSchedule,
                                             ProcChaosRunner)

    sup = _mk_supervisor(tmp_path / "cells")
    for spec in sup.specs.values():
        spec.stats_interval_s = 0.5  # fast snapshots into the ring
    sup.start()
    try:
        c = sup.make_client()
        assert c.create("g0").get("ok")
        assert c.request("g0", b"PUT a 1") == b"OK"
        victim = sup.router.cell("g0")
        h = sup.cells[victim]
        fpath = h.flight_path
        assert fpath and fpath == sup.specs[victim].flight
        # let at least one periodic stats snapshot land on disk
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if os.path.exists(fpath):
                doc = FlightRecorder.read(fpath)
                if any(e["kind"] == "stats" for e in doc["events"]):
                    break
            time.sleep(0.1)

        sched = ChaosSchedule("obs-kill", [
            ChaosEvent(at_tick=0, action="crash",
                       args={"node": f"c{victim}"}),
        ])
        log = ProcChaosRunner({f"c{victim}": h}, sched, tick_s=0.01).run()
        assert not h.alive()

        # the chaos log carries the postmortem path...
        recs = [r for r in log.records if r["action"] == "crash"]
        assert recs and recs[0]["info"]["flight"] == fpath
        # ...and the artifact survived the SIGKILL with real content
        doc = FlightRecorder.read(fpath)
        assert doc["node"] == f"c{victim}"
        kinds = [e["kind"] for e in doc["events"]]
        assert "boot" in kinds
        assert "stats" in kinds, kinds
        stats_evs = [e for e in doc["events"] if e["kind"] == "stats"]
        assert any(e.get("ar", {}).get("ticks", 0) >= 0 for e in stats_evs)
        c.close()
    finally:
        sup.stop()

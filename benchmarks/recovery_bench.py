"""Fast-restart artifact (ISSUE 19): time-to-full-service after a crash.

The tentpole claim: columnar WAL decode + batched (sparse) replay
dispatch turn recovery from O(ticks) host↔device round trips over a
full-width plane into a handful of narrow scan programs, so a node with
a huge group plane restarts in seconds, not minutes.  This bench
measures it end to end at G ∈ {64k, 256k, 1M} and writes
``benchmarks/results_recovery_pr19.json``:

* ``t_ref_replay_s`` / ``t_batched_replay_s`` — wall time of journal
  replay through the record-at-a-time reference arm vs the columnar
  batched arm (sparse window dispatch engaged), same journal, fresh
  process-equivalent manager each (gate: batched >= 5x at 1M);
* ``bit_identical`` — the two recovered managers compare equal field by
  field (state plane + apps + host bookkeeping);
* ``t_first_served_s`` — crash-to-first-ack: batched replay plus live
  ticks until a probe PUT on one group is executed and fsynced;
* ``t_full_service_s`` — crash-to-all-served: until a probe on EVERY
  journaled group has been acked;
* ``peer_stream`` — parallel peer snapshot streaming: Mode B recovery
  fetching checkpoint blobs from two donors over a synthetic 10 ms RTT,
  serial (window=1) vs windowed (window=4) wall time.

Run: ``python benchmarks/recovery_bench.py [--json PATH] [--quick]``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if os.environ.get("GPTPU_BENCH_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["GPTPU_BENCH_PLATFORM"])

import numpy as np  # noqa: E402

R = 3
GROUPS = 8          # journaled services riding the huge plane
GATE_SPEEDUP = 5.0


def _mk_cfg(g: int):
    from gigapaxos_tpu.config import GigapaxosTpuConfig

    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = g
    cfg.paxos.window = 4
    cfg.paxos.compact_outbox = True
    cfg.paxos.exec_budget = 8192
    return cfg


def _drive(m, ticks: int) -> None:
    """Traffic on GROUPS services for `ticks` journaled ticks (2-3 placed
    proposals per service per tick — the busy-few / idle-many shape a
    real restart replays)."""
    for t in range(ticks):
        for s in range(GROUPS):
            for j in range(2 + (t + s) % 2):
                m.propose(f"svc{s}", f"PUT k{t}.{j} v{s}.{t}.{j}".encode())
        m.run_ticks(1)


def _recover(cfg, workdir: str, mode: str):
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.wal.logger import recover

    t0 = time.monotonic()
    m = recover(cfg, R, [KVApp() for _ in range(R)], workdir,
                native=False, replay_mode=mode)
    return m, time.monotonic() - t0


def _serve_probe(m, services) -> float:
    """Ticks until a probe PUT on every listed service is acked (executed
    + fsynced — the ack rides the post-sync callback flush)."""
    t0 = time.monotonic()
    pending = set(services)

    def mk_cb(s):
        def cb(rid, resp):
            pending.discard(s)
        return cb

    for s in services:
        m.propose(s, b"PUT probe 1", callback=mk_cb(s))
    for _ in range(64):
        m.run_ticks(1)
        if not pending:
            break
    assert not pending, f"probe never served: {pending}"
    return time.monotonic() - t0


def bench_recovery(g: int, ticks: int) -> dict:
    """One plane size: journal a workload, crash, recover through both
    arms, then measure service-restoration latency on the batched arm."""
    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.paxos.manager import PaxosManager
    from gigapaxos_tpu.wal.logger import PaxosLogger

    cfg = _mk_cfg(g)
    root = tempfile.mkdtemp(prefix="recovery_bench_")
    try:
        live = os.path.join(root, "live")
        wal = PaxosLogger(live, native=False)
        m = PaxosManager(cfg, R, [KVApp() for _ in range(R)], wal=wal)
        for s in range(GROUPS):
            m.create_paxos_instance(f"svc{s}", [0, 1, 2])
        t0 = time.monotonic()
        _drive(m, ticks)
        t_live = time.monotonic() - t0
        m.wal.close()  # crash: no checkpoint, the journal is the state
        jbytes = sum(os.path.getsize(p) for p in
                     glob.glob(os.path.join(live, "journal.*.log")))
        del m, wal

        copy = os.path.join(root, "copy")
        shutil.copytree(live, copy)
        m_ref, t_ref = _recover(cfg, live, "reference")
        ref_state = m_ref.state
        ref_meta = (m_ref.tick_num, m_ref._next_rid,
                    m_ref._host_exec.copy(),
                    [dict(a.db) for a in m_ref.apps])
        m_ref.wal.close()
        del m_ref

        crash_t0 = time.monotonic()
        m_bat, t_bat = _recover(cfg, copy, "batched")
        identical = all(
            np.array_equal(np.asarray(getattr(ref_state, f)),
                           np.asarray(getattr(m_bat.state, f)))
            for f in ref_state._fields)
        identical = (identical
                     and ref_meta[0] == m_bat.tick_num
                     and ref_meta[1] == m_bat._next_rid
                     and np.array_equal(ref_meta[2], m_bat._host_exec)
                     and all(ref_meta[3][r] == m_bat.apps[r].db
                             for r in range(R)))
        del ref_state, ref_meta
        t_first = t_bat + _serve_probe(m_bat, ["svc0"])
        _serve_probe(m_bat, [f"svc{s}" for s in range(GROUPS)])
        t_full = time.monotonic() - crash_t0
        out = {
            "groups": g,
            "ticks": ticks,
            "journal_bytes": jbytes,
            "t_live_s": round(t_live, 2),
            "t_ref_replay_s": round(t_ref, 2),
            "t_batched_replay_s": round(t_bat, 2),
            "speedup": round(t_ref / t_bat, 2),
            "bit_identical": bool(identical),
            "replay_windows": m_bat._replay_windows,
            "sparse_windows": m_bat._replay_sparse_windows,
            "t_first_served_s": round(t_first, 2),
            "t_full_service_s": round(t_full, 2),
        }
        m_bat.wal.close()
        del m_bat
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_peer_stream(rtt_s: float = 0.01) -> dict:
    """Parallel peer snapshot streaming: Mode B recovery pulling fresh
    checkpoint blobs from two donors whose fetch path carries a
    synthetic RTT — windowed streaming overlaps the waits."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests"))
    from test_modeb import IDS, Cluster, make_cfg

    from gigapaxos_tpu.models.replicable import KVApp
    from gigapaxos_tpu.modeb import PeerCheckpointStreamer, recover_modeb

    cfg = make_cfg()
    out = {"rtt_ms": rtt_s * 1e3}
    for label, window in (("serial", 1), ("window4", 4)):
        root = tempfile.mkdtemp(prefix="recovery_bench_ps_")
        try:
            cl = Cluster(cfg, wal_root=__import__("pathlib").Path(root))
            try:
                for s in range(GROUPS):
                    cl.create(f"svc{s}")
                for i in range(4):
                    for s in range(GROUPS):
                        cl.commit(IDS[0], f"svc{s}",
                                  f"PUT k{i} v{i}".encode())
                victim = IDS[2]
                cl.kill(victim)
                cl.drop_backlog(victim)
                for s in range(GROUPS):
                    cl.commit(IDS[0], f"svc{s}", b"PUT gap 1",
                              only=set(IDS[:2]))

                def slow(fn):
                    def wrapped(*a, **kw):
                        time.sleep(rtt_s)
                        return fn(*a, **kw)
                    return wrapped

                ps = PeerCheckpointStreamer(
                    {nid: slow(cl.nodes[nid].donate_ckpt)
                     for nid in IDS[:2]}, window=window)
                cl.apps[victim] = KVApp()
                t0 = time.monotonic()
                node = recover_modeb(
                    cfg, IDS, victim, cl.apps[victim],
                    os.path.join(root, victim), native=False,
                    peer_stream=ps)
                out[f"t_{label}_s"] = round(time.monotonic() - t0, 3)
                out[f"fetched_{label}"] = ps.stats["fetched"]
                assert ps.stats["failed"] == 0
                cl.nodes[victim] = node
            finally:
                cl.close()
        finally:
            shutil.rmtree(root, ignore_errors=True)
    out["speedup"] = round(out["t_serial_s"] / out["t_window4_s"], 2)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "results_recovery_pr19.json"))
    ap.add_argument("--quick", action="store_true",
                    help="64k/256k only, fewer ticks")
    ap.add_argument("--ticks", type=int, default=16)
    args = ap.parse_args()

    sizes = [65536, 262144] if args.quick else [65536, 262144, 1048576]
    ticks = min(args.ticks, 8) if args.quick else args.ticks
    res = {"bench": "recovery_pr19", "platform": jax.default_backend(),
           "sizes": []}
    for g in sizes:
        r = bench_recovery(g, ticks)
        res["sizes"].append(r)
        print(json.dumps(r), flush=True)
    res["peer_stream"] = bench_peer_stream()
    print(json.dumps(res["peer_stream"]), flush=True)

    top = res["sizes"][-1]
    res["gate"] = {
        "target_speedup": GATE_SPEEDUP,
        "at_groups": top["groups"],
        "speedup": top["speedup"],
        "bit_identical_all": all(s["bit_identical"]
                                 for s in res["sizes"]),
        "pass": bool(top["speedup"] >= GATE_SPEEDUP
                     and all(s["bit_identical"] for s in res["sizes"])),
    }
    with open(args.json, "w") as f:
        json.dump(res, f, indent=2)
    print(json.dumps({"bench": "recovery_pr19", "gate": res["gate"]}),
          flush=True)


if __name__ == "__main__":
    main()

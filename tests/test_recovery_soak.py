"""6-seed SIGKILL crash/recover soak (ISSUE 19 satellite): a randomized
workload, a process-death kill of a random node, majority-only traffic
while it is down, then recovery with parallel peer snapshot streaming
active — asserting the per-slot S1 ledger cluster-wide (no (group, slot)
ever executes two rids, across the crash) and zero lost acked decisions
(every write acked before or during the outage is present on every node,
including the restarted one, after catch-up)."""

import numpy as np
import pytest

from gigapaxos_tpu.models.replicable import KVApp
from gigapaxos_tpu.modeb import PeerCheckpointStreamer, recover_modeb
from gigapaxos_tpu.net.messenger import Messenger
from gigapaxos_tpu.testing.chaos import SafetyLedger
from test_modeb import IDS, Cluster, make_cfg

SERVICES = ["svcA", "svcB", "svcC"]


@pytest.mark.parametrize("seed", [
    pytest.param(s, marks=pytest.mark.slow) if s >= 3 else s
    for s in range(6)
])
def test_sigkill_crash_recover_soak(tmp_path, seed):
    rng = np.random.default_rng(seed)
    cfg = make_cfg()
    cl = Cluster(cfg, wal_root=tmp_path)
    led = SafetyLedger()
    for nid in IDS:
        led.attach(nid, cl.nodes[nid])
    acked = {s: {} for s in SERVICES}  # service -> key -> value (acked only)
    try:
        for s in SERVICES:
            cl.create(s)

        def put(i, only=None):
            at = rng.choice(sorted(only) if only else IDS)
            s = SERVICES[int(rng.integers(len(SERVICES)))]
            k, v = f"k{i}", f"v{seed}.{i}"
            assert cl.commit(str(at), s, f"PUT {k} {v}".encode(),
                             only=only) == b"OK"
            acked[s][k] = v

        for i in range(int(rng.integers(6, 10))):
            put(i)
        cl.ticks(int(rng.integers(2, 6)))

        victim = IDS[int(rng.integers(len(IDS)))]
        survivors = {n for n in IDS if n != victim}
        cl.kill(victim)
        cl.drop_backlog(victim)
        for i in range(int(rng.integers(3, 6))):
            put(100 + i, only=survivors)

        # recover with parallel peer snapshot streaming from both survivors
        donors = sorted(survivors)
        rng.shuffle(donors)
        ps = PeerCheckpointStreamer(
            {nid: cl.nodes[nid].donate_ckpt for nid in donors}, window=2)
        cl.apps[victim] = KVApp()
        node = recover_modeb(cfg, IDS, victim, cl.apps[victim],
                             str(tmp_path / victim), native=False,
                             peer_stream=ps)
        # rows that missed writes during the outage were streamed (the
        # quiesced-watermark case legitimately yields only stale blobs)
        assert ps.stats["fetched"] >= len(SERVICES)
        assert ps.stats["failed"] == 0
        led.attach(victim, node)
        m = Messenger(victim, ("127.0.0.1", 0), cl.nodemap)
        cl.nodemap.add(victim, "127.0.0.1", m.port)
        cl.msgs[victim] = m
        node.attach_messenger(m)
        node.request_sync()
        cl.nodes[victim] = node
        back_r = IDS.index(victim)
        for n in cl.nodes.values():
            n.set_alive(back_r, True)
        # zero lost acked decisions: every acked write on every node.  A
        # donor that acked a write it had not yet executed streams a blob
        # one slot short — anti-entropy owes the tail, so catch-up is
        # bounded-eventual, not instant
        def missing():
            return [(nid, s, k)
                    for nid in IDS
                    for s in SERVICES
                    for k, v in acked[s].items()
                    if cl.apps[nid].db.get(s, {}).get(k) != v]

        for _ in range(10):
            cl.ticks(20)
            if not missing():
                break
        assert not missing(), f"seed {seed}: lost acked writes {missing()}"
        # S1 across the crash: replayed + streamed + live executions agree
        assert led.observations > 0
        led.assert_safe()
    finally:
        cl.close()

"""The reconfiguration e2e suite over Mode B deployment units.

Round-2 verdict: "Mode B is an island" — the control plane, client and
epoch machinery only ran on the shared Mode A plane.  This suite boots one
:class:`ModeBServer` per node id (the ``ReconfigurableNode`` per-process
unit) in one test process on loopback — the reference's own test strategy
(``TESTReconfigurationMain.startLocalServers``,
reconfiguration/testing/TESTReconfigurationMain.java:86) — and drives
create → request → migrate (state carried across epochs between
*independent* per-node data planes) → delete with the real client, plus a
coordinator death detected by the failure detector alone (no ``set_alive``
anywhere in this file).
"""

import time

import numpy as np
import pytest

from gigapaxos_tpu.client import ClientError, ReconfigurableAppClient
from gigapaxos_tpu.config import GigapaxosTpuConfig
from gigapaxos_tpu.server import ModeBServer

N_ACTIVE = 4
N_RC = 3


def _request_via(client, name, payload, active, timeout=30.0):
    """Send one app request through a SPECIFIC active replica.

    Retries on not_active within the budget: creates/epoch-changes ack at a
    MAJORITY of StartEpochs, so the remaining member may still be birthing
    the group when targeted directly."""
    import threading

    from gigapaxos_tpu.reconfiguration import packets as pkt

    deadline = time.monotonic() + timeout
    box = {}
    while time.monotonic() < deadline:
        done = threading.Event()
        box = {}

        # bind per-attempt objects by value: a LATE callback from a timed-out
        # earlier attempt must not write into this attempt's box/event
        def cb(resp, box=box, done=done):
            box.update(resp)
            done.set()

        client.request_actives(name)
        client.send_request(name, payload, cb, active=active)
        if not done.wait(min(10.0, max(deadline - time.monotonic(), 0.5))):
            continue  # timed out this attempt; retry
        if box.get("ok"):
            return pkt.b64d(box.get("response")) or b""
        if box.get("error") not in ("not_active", "stopped"):
            break
        time.sleep(0.5)
    raise AssertionError(f"request via {active} failed: {box}")


def _dump_cp_state(srv, name, got, want) -> str:
    """Post-mortem for convergence stalls: every RC's record view + RC/AR
    plane health, so a CI failure names the wedged component instead of
    'actives never converged'."""
    lines = [f"actives for {name!r}: got {sorted(got)} want {sorted(want)}"]
    for nid, s in srv.items():
        try:
            if s.reconfigurator is not None:
                rec = s.reconfigurator.db.get(name)
                rc = s.rc_node
                lines.append(
                    f"  {nid}: rec={{state: {getattr(rec, 'state', None)}, "
                    f"epoch: {getattr(rec, 'epoch', None)}, "
                    f"actives: {getattr(rec, 'actives', None)}, "
                    f"new: {getattr(rec, 'new_actives', None)}}} "
                    f"rc_plane={{ticks: {rc.tick_num}, "
                    f"alive: {list(map(bool, rc.alive))}, "
                    f"queued: {sum(map(len, rc._queues.values()))}, "
                    f"outstanding: {len(rc.outstanding)}, "
                    f"stalled: {len(rc._stalled)}, "
                    f"tainted: {len(rc._tainted_rows)}, "
                    f"decisions: {rc.stats['decisions']}, "
                    f"rerouted: {rc.stats['rerouted']}, "
                    f"coord_view: {sorted(set(int(x) for x in rc._coord_view[:8]))}}}"
                )
            if s.node is not None:
                n = s.node
                lines.append(
                    f"  {nid}(ar): ticks={n.tick_num} "
                    f"alive={list(map(bool, n.alive))} "
                    f"epochs={dict(s.coordinator._epoch)} "
                    f"rows={dict(n.rows.items())} "
                    f"stopped={sorted(n._stopped_rows)} "
                    f"tainted={sorted(n._tainted_rows)} "
                    f"decisions={n.stats['decisions']} "
                    f"ckpt_req={n.stats['ckpt_requests']} "
                    f"ckpt_xfer={n.stats['ckpt_transfers']} "
                    f"exec={np.asarray(n.state.exec_slot[n.r])[:6].tolist()} "
                    f"db={dict(getattr(s.app, 'db', {}))}"
                )
        except Exception as e:  # the dump must never mask the real failure
            lines.append(f"  {nid}: dump failed: {type(e).__name__}: {e}")
    return "\n".join(lines)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_cfg():
    """Concrete pre-assigned ports, as a real properties file would have:
    every process resolves every peer from the static topology."""
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = 32
    cfg.paxos.window = 8
    cfg.fd.ping_interval_s = 0.05
    cfg.fd.timeout_s = 1.0
    for i in range(N_ACTIVE):
        cfg.nodes.actives[f"AR{i}"] = ("127.0.0.1", _free_port())
    for i in range(N_RC):
        cfg.nodes.reconfigurators[f"RC{i}"] = ("127.0.0.1", _free_port())
    return cfg


@pytest.fixture(scope="module")
def servers():
    cfg = make_cfg()
    srv = {}
    for nid in list(cfg.nodes.actives) + list(cfg.nodes.reconfigurators):
        srv[nid] = ModeBServer(nid, cfg, start_fd=True)
    for s in srv.values():
        assert s.wait_ready(300)
    yield cfg, srv
    for s in srv.values():
        s.close()


@pytest.fixture(scope="module")
def client(servers):
    cfg, _ = servers
    c = ReconfigurableAppClient(cfg.nodes)
    yield c
    c.close()


def test_create_and_request(servers, client):
    resp = client.create("svc0", timeout=60)
    assert resp["ok"], resp
    actives = client.request_actives("svc0")
    assert len(actives) == 3
    assert client.request("svc0", b"PUT k v1", timeout=30) == b"OK"
    assert client.request("svc0", b"GET k", timeout=30) == b"v1"


def test_request_from_every_member(servers, client):
    cfg, srv = servers
    assert client.create("multi", timeout=60)["ok"]
    # hit every member AR directly: cross-process forwarding to whichever
    # process currently coordinates the group
    for i, a in enumerate(sorted(client.request_actives("multi"))):
        assert _request_via(client, "multi", f"PUT k{i} {i}".encode(), a) == b"OK"
    assert client.request("multi", b"GET k0", timeout=30) == b"0"
    assert client.request("multi", b"GET k2", timeout=30) == b"2"


def test_migrate_preserves_state_across_processes(servers, client):
    cfg, srv = servers
    assert client.create("mig", timeout=60)["ok"]
    assert client.request("mig", b"PUT city amherst", timeout=30) == b"OK"
    old = set(client.request_actives("mig"))
    pool = set(cfg.nodes.active_ids())
    # move to a set containing a node that was NOT in the old epoch, so the
    # final state must cross process boundaries (WaitEpochFinalState fetch)
    newcomer = sorted(pool - old)
    assert newcomer, "need a spare active for the migration test"
    new = sorted(sorted(old)[:2] + newcomer[:1])
    resp = client.reconfigure("mig", new)
    assert resp["ok"], resp
    # resolution may briefly hit an RC replica that has not yet executed
    # the complete — poll until the committed record is visible (generous:
    # the migration is several cross-process paxos commits, and the CI box
    # runs every plane on one core)
    deadline = time.monotonic() + 300
    got = set()
    while time.monotonic() < deadline:
        got = set(client.request_actives("mig", force=True))
        if got == set(new):
            break
        time.sleep(0.3)
    assert got == set(new), _dump_cp_state(srv, "mig", got, new)
    try:
        assert client.request("mig", b"GET city", timeout=60) == b"amherst"
        assert client.request("mig", b"PUT t 2", timeout=60) == b"OK"
    except (TimeoutError, ClientError, AssertionError) as e:
        raise AssertionError(
            f"post-migration request failed: {e}\n"
            + _dump_cp_state(srv, "mig", got, new)
        ) from e
    # the newcomer's own app copy converges (its independent plane learned
    # by state transfer, not shared memory)
    nc = newcomer[0]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        db = getattr(srv[nc].app, "db", {})
        if db.get("mig#1", {}).get("city") == "amherst":
            break
        time.sleep(0.1)
    assert srv[nc].app.db.get("mig#1", {}).get("city") == "amherst", \
        _dump_cp_state(srv, "mig", got, new)


def test_delete_and_recreate(servers, client):
    assert client.create("gone", timeout=60)["ok"]
    assert client.request("gone", b"PUT x 1", timeout=30) == b"OK"
    resp = client.delete("gone")
    assert resp["ok"], resp
    with pytest.raises(ClientError):
        client.request_actives("gone", force=True)
    assert client.create("gone", timeout=60)["ok"]
    assert client.request("gone", b"GET x", timeout=30) == b"NF"


def test_coordinator_process_death_fd_failover(servers, client):
    """Kill the group's coordinator (close its server: transport gone,
    ticking stops).  NO manual liveness calls: the survivors' failure
    detectors must mark it dead and the next-in-line must take over —
    the round-2 verdict's missing wiring."""
    cfg, srv = servers
    assert client.create("failover", timeout=60)["ok"]
    assert client.request("failover", b"PUT pre 1", timeout=30) == b"OK"
    members = sorted(client.request_actives("failover"))
    # the coordinator is the first live caught-up member slot: the member
    # with the smallest universe slot index
    universe = cfg.nodes.active_ids()
    coord = min(members, key=universe.index)
    srv[coord].close()
    survivors = [a for a in members if a != coord]
    # commits must resume once FD timeout (1s) expires; retry via survivors
    # (generous budget: this runs last in the module, with all prior tests'
    # groups ticking on a box that may have a single core)
    deadline = time.monotonic() + 300
    committed = False
    i = 0
    while time.monotonic() < deadline and not committed:
        try:
            r = _request_via(client, "failover", f"PUT post {i}".encode(),
                             survivors[i % len(survivors)], timeout=5)
            committed = r == b"OK"
        except (AssertionError, ClientError, TimeoutError):
            pass
        i += 1
    assert committed, "no commit after coordinator process death"
    assert client.request("failover", b"GET post", timeout=30) is not None

"""Consistent hashing of service names onto reconfigurator groups.

Analog of ``reconfigurationutils/ConsistentHashing.java:40-64``: an MD5 ring
over node ids; a name hashes to a point on the ring and its replica group is
the next ``k`` distinct nodes clockwise.  This is how the control plane
shards itself (SURVEY §2.2 parallelism axis 4): each name's RC group is a
deterministic function of the RC node set, so any node can route control
traffic without a directory.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, List, Sequence


def _h(s: str) -> int:
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")


class ConsistentHashRing:
    def __init__(self, nodes: Sequence[str], replicas_per_node: int = 50):
        """``replicas_per_node`` = virtual points per node for load balance
        (the reference hashes each node id once; virtual nodes strictly
        improve balance with the same interface)."""
        self.nodes = sorted(set(nodes))
        self.vpoints = replicas_per_node
        self._ring: List[int] = []
        self._owner: Dict[int, str] = {}
        for n in self.nodes:
            for v in range(replicas_per_node):
                p = _h(f"{n}#{v}")
                # deterministic collision tiebreak: lowest node id wins
                if p not in self._owner or n < self._owner[p]:
                    self._owner[p] = n
        self._ring = sorted(self._owner)

    def replicated_servers(self, name: str, k: int = 3) -> List[str]:
        """The ``k`` distinct nodes clockwise from the name's ring point
        (``getReplicatedServers`` analog).  k is capped at the node count."""
        if not self.nodes:
            return []
        k = min(k, len(self.nodes))
        start = bisect.bisect_left(self._ring, _h(name)) % len(self._ring)
        out: List[str] = []
        i = start
        while len(out) < k:
            n = self._owner[self._ring[i % len(self._ring)]]
            if n not in out:
                out.append(n)
            i += 1
        return out

    def primary(self, name: str) -> str:
        return self.replicated_servers(name, 1)[0]

"""WAL + recovery for the chain data plane.

The journal format is shared with the paxos WAL (OP_CREATE / OP_REMOVE /
OP_TICK records, snapshot + deterministic replay — ``logger.py``); only the
manager-specific snapshot metadata and the tick-replay inbox shape differ.
This mirrors the reference, where chains persist through the same logger
infrastructure as paxos groups (``ChainManager`` reuses
``AbstractPaxosLogger``, chainreplication/ChainManager.java:100-120).
"""

from __future__ import annotations

import collections
import io

import numpy as np

from .logger import PaxosLogger, load_latest_snapshot, replay_journals


class ChainLogger(PaxosLogger):
    def _meta(self, m) -> dict:
        return {
            "tick_num": m.tick_num,
            "next_rid": m._next_rid,
            "rows": dict(m.rows.items()),
            # verbatim LIFO free-list — see PaxosLogger._meta for why order
            # must survive recovery
            "free_rows": list(m.rows._free),
            "stopped_rows": set(m._stopped_rows),
            "outstanding": [
                (r.rid, r.name, r.row, r.payload, r.stop,
                 sorted(r.executed_by), r.responded)
                for r in m.outstanding.values()
            ],
            "queues": {row: list(q) for row, q in m._queues.items() if q},
            "apps": [
                {name: m.apps[i].checkpoint(name) for name in m.rows.names()}
                for i in range(m.R)
            ],
        }


def recover_chain(cfg, n_replicas: int, apps, log_dir: str, native: bool = True):
    """Rebuild a ChainManager from disk: snapshot + deterministic replay of
    journaled ticks (3-pass recovery analog, PaxosManager.java:1852-2055)."""
    import jax.numpy as jnp

    from ..chain.manager import ChainManager, ChainRequest
    from ..chain.state import ChainState
    from ..chain.tick import ChainInbox, chain_tick_packed, unpack_chain_outbox

    logger = ChainLogger(log_dir, native=native)
    m = ChainManager(cfg, n_replicas, apps)
    snap = load_latest_snapshot(log_dir)
    start_seq = 0
    if snap is not None:
        snap_seq, (meta, npz_blob) = snap
        arrs = np.load(io.BytesIO(npz_blob))
        m.state = ChainState(
            **{f: jnp.asarray(arrs[f]) for f in ChainState._fields}
        )
        m._member_np = np.asarray(m.state.member).copy()
        m._n_members_np = np.asarray(m.state.n_members).copy()
        m.tick_num = meta["tick_num"]
        m._next_rid = meta["next_rid"]
        m.rows.restore(meta["rows"], meta.get("free_rows"))
        m._stopped_rows = set(meta["stopped_rows"])
        for rid, name, row, payload, stop, eby, responded in meta["outstanding"]:
            # executed_by was an int count in snapshots written before it
            # became a replica-index set; those carry no index information,
            # so restore conservatively as empty (the record is merely
            # retained longer until the sweep re-covers it)
            eby_set = set(eby) if isinstance(eby, (list, tuple, set)) else set()
            m.outstanding[rid] = ChainRequest(
                rid, name, row, payload, stop, None, responded, eby_set
            )
        for row, rids in meta["queues"].items():
            m._queues[int(row)] = collections.deque(rids)
        for i in range(m.R):
            for name, blob in meta["apps"][i].items():
                m.apps[i].restore(name, blob)
        start_seq = snap_seq

    def make_record(m, rid, row, payload, stop, entry):
        return ChainRequest(rid, m.rows.name(row) or "?", row, payload, stop,
                            None)

    def new_buffers(m):
        return (np.zeros((m.P, m.G), np.int32), np.zeros((m.P, m.G), bool))

    def place(bufs, entry, p, row, rid, stop):
        bufs[0][p, row] = rid
        bufs[1][p, row] = stop

    def build_inbox(bufs, alive):
        return ChainInbox(jnp.asarray(bufs[0]), jnp.asarray(bufs[1]),
                          jnp.asarray(alive))

    def tick_host(state, inbox):
        state, packed = chain_tick_packed(state, inbox)
        return state, unpack_chain_outbox(packed, m.R, m.P, m.W, m.G)

    replay_journals(m, log_dir, start_seq, make_record, new_buffers, place,
                    build_inbox, tick_host)
    logger.attach(m)
    m.wal = logger
    return m

"""Per-node consensus step for Mode B (independent processes per replica).

Mode A runs the whole replica set as one device program (``ops/tick.py``);
Mode B gives every node its own process, disk and device state — the
reference's actual deployment shape (one ``PaxosManager`` per machine,
gigapaxos/PaxosManager.java:104-119, replica traffic over NIO,
nio/NIOTransport.java:65-114).

Design: each node holds the full ``[R, ...]`` state arrays but is
**authoritative only for its own row r**.  Peer rows are *mirrors*, updated
exclusively by replica frames received over the transport (``wire.py``).
The node step reuses the verified fused dataflow (``paxos_tick_impl``) and
then keeps only row r of the result — peer rows stay whatever the last
frames said.

Why this is safe with stale mirrors: the tick runs with ``own_row=r``
(``ops/tick.py``), which confines every state *transition* — candidacy,
promise upgrade, prepare win, intake, accept — to row r.  Peer rows are
pure frame-derived snapshots, and every cross-replica read then consumes
only *monotone facts*:

* a promise in a mirror row means that acceptor really promised that ballot
  at its frame snapshot (promises only rise), so counting a prepare
  majority from mirrors counts real promises, and the carryover window
  rides the same frame snapshot (= "accepteds as of the promise", the
  classic prepare-reply content, PaxosInstanceStateMachine.java:1017);
* a vote (accepted pvalue) in a mirror is a historical fact: once a
  majority ever accepted (slot, ballot, value), that value is chosen —
  tallying stale votes can only *under*-count, never fabricate a quorum;
* a pushing peer coordinator (mirror coord_active + prop ring, shipped
  together in one frame) is a real ACCEPT in flight — the value and ballot
  are the peer's own consistent facts, never locally recomputed;
* decisions are facts by construction.

Without the own-row confinement the fused tick SIMULATES peer promises and
accepts in the same step (that is Mode A's whole point: one device program
IS the replica set), and counting those toward quorums would let an
isolated minority self-elect and commit — split brain.  Regression:
``tests/test_modeb_partition.py`` (isolated node must never commit; two
live coordinators across a partition must not diverge).

Staleness therefore costs latency (a decision needs a frame round-trip to
gather votes), never agreement.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..ops.pallas_gather import shard_local_trace
from ..ops.tick import TickInbox, paxos_tick_impl

#: own-row state fields shipped in replica frames ([R, G] / [R, W, G])
FRAME_FIELDS_2D = ("exec_slot", "bal_num", "bal_coord", "status",
                   "coord_active", "coord_preparing", "coord_fast",
                   "coord_bnum", "next_slot")
FRAME_FIELDS_3D = ("acc_bnum", "acc_bcoord", "acc_req", "acc_slot",
                   "acc_stop", "dec_req", "dec_slot", "dec_valid",
                   "dec_stop", "prop_req", "prop_slot", "prop_valid",
                   "prop_stop")


def node_tick_impl(state, inbox: TickInbox, r: int, fast: bool = False):
    """One Mode-B node step: fused dataflow, own-row commit, change mask.

    Returns (state', outbox, changed[G]) where ``changed`` marks groups
    whose own-row frame fields differ from before (the delta-frame mask —
    the batching analog of PaxosPacketBatcher coalescing per-peer traffic,
    gigapaxos/PaxosPacketBatcher.java:28-35).

    ``fast`` enables consecutive-ballot fast re-election (see
    ``paxos_tick_impl``); the ``coord_fast`` bit it maintains travels in
    the frame flags word, so peers' acceptors apply the conflict-refusal
    rule to this node's fast pushes.
    """
    # a node program is single-device by construction (each Mode-B process
    # owns one chip) — never GSPMD-partitioned — so the Pallas gathers are
    # safe here even when the host exposes multiple devices, where the
    # backend-wide heuristic in use_pallas_gather() would refuse them
    with shard_local_trace():
        new, out = paxos_tick_impl(state, inbox, own_row=r, fast_elect=fast)
    R = state.exec_slot.shape[0]
    row2 = (jnp.arange(R) == r)[:, None]        # [R, 1]
    row3 = row2[:, None, :]                      # [R, 1, 1]

    merged = {}
    changed = jnp.zeros(state.exec_slot.shape[1], jnp.bool_)
    for f in FRAME_FIELDS_2D:
        old_a, new_a = getattr(state, f), getattr(new, f)
        merged[f] = jnp.where(row2, new_a, old_a)
        changed = changed | (new_a[r] != old_a[r])
    for f in FRAME_FIELDS_3D:
        old_a, new_a = getattr(state, f), getattr(new, f)
        merged[f] = jnp.where(row3, new_a, old_a)
        changed = changed | jnp.any(new_a[r] != old_a[r], axis=0)
    # member/n_members/epoch are config state managed by create/free ops,
    # identical on every node — the tick never writes them
    return state._replace(**merged), out, changed


@functools.lru_cache(maxsize=None)
def node_tick(r: int, fast: bool = False):
    """Jitted per-node step (r, fast static; state donated)."""
    return jax.jit(functools.partial(node_tick_impl, r=r, fast=fast),
                   donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def node_tick_packed(r: int, fast: bool = False):
    """Jitted per-node step returning (state', flat_i32) where the flat
    buffer is pack_outbox(outbox) ++ changed — ONE device->host transfer
    per tick instead of one per consumed field (see ops/tick.HostOutbox)."""
    from ..ops.tick import pack_outbox_impl

    def impl(state, inbox):
        new, out, changed = node_tick_impl(state, inbox, r, fast)
        flat = jnp.concatenate(
            [pack_outbox_impl(out), changed.astype(jnp.int32)]
        )
        return new, flat

    return jax.jit(impl, donate_argnums=(0,))


def unpack_node_tick(flat, R: int, P: int, W: int, G: int):
    """Host inverse of :func:`node_tick_packed`'s flat buffer."""
    import numpy as np

    from ..ops.tick import unpack_outbox

    flat = np.asarray(flat)
    out = unpack_outbox(flat[:-G], R, P, W, G)
    return out, flat[-G:].astype(bool)


@functools.lru_cache(maxsize=None)
def node_tick_device(r: int, K: int, fast: bool = False):
    """Jitted per-node step with the device KV app fused behind it (the
    Mode-B twin of models/device_kv.fused_compact): descriptor upload +
    consensus tick + own-row on-device execution in ONE program.

    The node's kv has replica-axis 1 (it executes only its own row).  Rows
    with ANY descriptor miss this tick — or held by the host (``hold``:
    rows whose execution stream is stalled on an unarrived payload) — are
    SUPPRESSED on device: no kv write at all, because applying slot j+1
    while slot j is missing/stalled would break RSM order.  The host
    re-applies a suppressed row's batch in order through the scalar
    fallback (reusing the digest stall machinery).  reg_*: up to K new
    descriptors (rid 0 = empty).

    Returns (state', kv', flat) with flat = pack_outbox ++ changed[G] ++
    resp[W*G] ++ row_skip[G] (own row, window-major).
    """
    from ..models.device_kv import kv_apply, register_requests
    from ..ops.tick import pack_outbox_impl

    def impl(state, kv, inbox, reg_rids, reg_ops, reg_keys, reg_vals, hold):
        kv = register_requests(kv, reg_rids, reg_ops, reg_keys, reg_vals,
                               mix=True)
        new, out, changed = node_tick_impl(state, inbox, r, fast)
        er = out.exec_req[r:r + 1]      # [1, W, G]
        ec = out.exec_count[r:r + 1]
        kv2, resp, miss = kv_apply(kv, er, ec, mix=True)
        row_skip = jnp.any(miss[0], axis=0) | hold  # [G]
        # suppress every kv effect of a skipped row (host replays in order)
        keep = ~row_skip[None, :, None]
        kv2 = kv2._replace(
            key=jnp.where(keep, kv2.key, kv.key),
            val=jnp.where(keep, kv2.val, kv.val),
        )
        flat = jnp.concatenate([
            pack_outbox_impl(out), changed.astype(jnp.int32),
            resp[0].reshape(-1), row_skip.astype(jnp.int32),
        ])
        return new, kv2, flat

    return jax.jit(impl, donate_argnums=(0, 1))


def unpack_node_tick_device(flat, R: int, P: int, W: int, G: int):
    """Host inverse of :func:`node_tick_device`: -> (outbox, changed[G],
    resp[W, G], row_skip[G])."""
    import numpy as np

    from ..ops.tick import unpack_outbox

    flat = np.asarray(flat)
    tail = G + W * G + G
    out = unpack_outbox(flat[:-tail], R, P, W, G)
    changed = flat[-tail:-tail + G].astype(bool)
    resp = flat[-tail + G:-G].reshape(W, G)
    row_skip = flat[-G:].astype(bool)
    return out, changed, resp, row_skip


@functools.lru_cache(maxsize=None)
def frame_extract(r: int, K: int):
    """Jitted own-row gather for frame building: selects ``K`` rows of every
    frame field in one device program and returns one flat i32 buffer
    (layout: scalars [S,K] ++ flags [K] ++ rings [NR,K,W] ++ bits [NB,K,W]).
    The round-2 path sliced ~21 fields individually (one dispatch+transfer
    each) per frame per tick; K is pow2-padded so the jit cache stays
    bounded."""
    from .wire import FLAG_COORD_ACTIVE, FLAG_COORD_FAST, \
        FLAG_COORD_PREPARING, RING_BITS, RINGS, SCALARS

    def impl(state, rows):
        parts = []
        for f in SCALARS:
            parts.append(getattr(state, f)[r, rows])                 # [K]
        flags = (state.coord_active[r, rows].astype(jnp.int32)
                 * FLAG_COORD_ACTIVE
                 + state.coord_preparing[r, rows].astype(jnp.int32)
                 * FLAG_COORD_PREPARING
                 + state.coord_fast[r, rows].astype(jnp.int32)
                 * FLAG_COORD_FAST)
        parts.append(flags)
        for f in RINGS + RING_BITS:
            parts.append(getattr(state, f)[r][:, rows].T)            # [K, W]
        return jnp.concatenate(
            [p.astype(jnp.int32).ravel() for p in parts]
        )

    return jax.jit(impl)


def unpack_frame_extract(flat, n: int, K: int, W: int):
    """Host inverse of :func:`frame_extract`: -> (scalars dict, flags,
    rings dict, ring_bits dict) truncated to the first ``n`` rows."""
    import numpy as np

    from .wire import RING_BITS, RINGS, SCALARS

    flat = np.asarray(flat)
    off = 0
    scalars = {}
    for f in SCALARS:
        scalars[f] = flat[off:off + K][:n]
        off += K
    flags = flat[off:off + K][:n]
    off += K
    rings = {}
    for f in RINGS:
        rings[f] = flat[off:off + K * W].reshape(K, W)[:n]
        off += K * W
    bits = {}
    for f in RING_BITS:
        bits[f] = flat[off:off + K * W].reshape(K, W)[:n].astype(bool)
        off += K * W
    return scalars, flags, rings, bits


def mirror_apply_impl(state, sr, rows, scalars, flags, rings, bits):
    """Apply one decoded replica frame to sender ``sr``'s mirror rows in a
    single fused device step.

    The naive path (one ``.at[].set`` dispatch per field per frame — ~20
    dispatches) dominates host time at high frame rates; fusing them into
    one jitted program is the ingest analog of PaxosPacketBatcher
    coalescing per-peer traffic (gigapaxos/PaxosPacketBatcher.java:28-35).

    rows: i32 [K], padded with G (out-of-bounds -> mode='drop' discards);
    scalars: i32 [S, K] in wire.SCALARS order; flags: i32 [K];
    rings: i32 [NR, K, W] in wire.RINGS order; bits: bool [NB, K, W] in
    wire.RING_BITS order.
    """
    from .wire import (FLAG_COORD_ACTIVE, FLAG_COORD_FAST,
                       FLAG_COORD_PREPARING, RING_BITS, RINGS, SCALARS)

    upd = {}
    for i, f in enumerate(SCALARS):
        upd[f] = getattr(state, f).at[sr, rows].set(scalars[i], mode="drop")
    upd["coord_active"] = state.coord_active.at[sr, rows].set(
        (flags & FLAG_COORD_ACTIVE) > 0, mode="drop"
    )
    upd["coord_preparing"] = state.coord_preparing.at[sr, rows].set(
        (flags & FLAG_COORD_PREPARING) > 0, mode="drop"
    )
    upd["coord_fast"] = state.coord_fast.at[sr, rows].set(
        (flags & FLAG_COORD_FAST) > 0, mode="drop"
    )
    for i, f in enumerate(RINGS):
        upd[f] = getattr(state, f).at[sr, :, rows].set(rings[i], mode="drop")
    for i, f in enumerate(RING_BITS):
        upd[f] = getattr(state, f).at[sr, :, rows].set(bits[i], mode="drop")
    return state._replace(**upd)


mirror_apply = jax.jit(mirror_apply_impl, donate_argnums=(0,))


def ring_downstream(alive, r: int) -> int:
    """Next alive replica clockwise from ``r``: the dissemination-ring hop
    target (HT-Ring Paxos, arxiv 1507.04086).  The tick above orders rids
    only (digest accepts); the payload bytes those rids reference travel
    along the ring this routing defines — one downstream send per node per
    tick regardless of R.  Returns -1 when no OTHER replica is alive (a
    singleton keeps its payloads staged until someone rejoins).  Host-side
    like the unpack inverses: ``alive`` is the manager's numpy liveness
    mirror, never device state."""
    R = len(alive)
    for k in range(1, R):
        i = (r + k) % R
        if alive[i]:
            return i
    return -1

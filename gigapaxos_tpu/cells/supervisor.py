"""Cell supervisor: spawn, pin, monitor and restart per-core Paxos cells.

One host runs N serving cells (``cells/worker.py`` processes); the
supervisor owns their lifecycle:

* **spawn** — one worker per cell with pre-allocated FIXED ports (a
  restarted cell rebinds the same endpoints, so peer nodemaps and clients
  never need re-wiring) and its own WAL directories;
* **pinning** — cell k is ``sched_setaffinity``-pinned to core k (workers
  pin themselves; ``CellsConfig.pin_cores`` gates it);
* **health** — the EWMA heartbeat detector (net/failure_detection.py) over
  a local control messenger pings every cell's AR0; process death is
  additionally caught directly by ``poll()`` in the supervision loop —
  the heartbeat covers live-but-wedged cells, the poll covers SIGKILL;
* **restart** — a dead cell is relaunched against the same WAL dirs after
  ``restart_backoff_s`` (capped at ``max_restarts``); WAL replay rebuilds
  its groups, client routing is untouched because the ports are stable;
* **drain** — ``stop()`` SIGTERMs every worker (the in-process handler
  drains the in-flight tick and flushes the WAL before exit), escalating
  to SIGKILL only past ``drain_timeout_s``.

The supervisor also carries the host's routing directory
(:class:`~gigapaxos_tpu.cells.routing.CellRouter`) and builds clients wired
to it (``make_client``), so group->cell resolution needs zero RC hops.
"""

from __future__ import annotations

import json
import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..config import CellsConfig, GigapaxosTpuConfig, NodeConfig
from ..net.failure_detection import FailureDetection
from ..net.messenger import Messenger, NodeMap
from ..obs.http import MetricsServer
from ..obs.metrics import NullRegistry, Registry, metrics_enabled
from ..obs.prom import merge_scrapes, render_registry
from ..utils import reqtrace
from .routing import CellRouter

SUP_ID = "SUP"


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@dataclass
class CellSpec:
    """Everything needed to (re)spawn one cell — ports and WAL dirs are
    allocated once, so a restart is exactly a respawn of the same spec."""

    cell: int
    n_cells: int
    actives: Dict[str, list]
    reconfigurators: Dict[str, list]
    peers: Dict[str, list]
    wal_dir: str
    rc_wal_dir: str
    core: Optional[int] = None
    edge: Optional[list] = None
    paxos: Dict[str, object] = field(default_factory=dict)
    cfg: Dict[str, object] = field(default_factory=dict)
    ledger: bool = False
    overrides: Dict[str, int] = field(default_factory=dict)
    drain_timeout_s: float = 10.0
    flight: Optional[str] = None
    stats_interval_s: float = 2.0

    def to_json(self) -> str:
        return json.dumps({
            "cell": self.cell, "n_cells": self.n_cells,
            "actives": self.actives,
            "reconfigurators": self.reconfigurators,
            "peers": self.peers,
            "wal_dir": self.wal_dir, "rc_wal_dir": self.rc_wal_dir,
            "core": self.core, "edge": self.edge,
            "paxos": self.paxos, "cfg": self.cfg,
            "ledger": self.ledger, "overrides": self.overrides,
            "drain_timeout_s": self.drain_timeout_s,
            "flight": self.flight,
            "stats_interval_s": self.stats_interval_s,
        })


class CellHandle:
    """One live worker process: line-protocol plumbing plus the ``proc`` /
    ``sigkill()`` surface ``testing.chaos.ProcChaosRunner`` drives."""

    def __init__(self, spec: CellSpec, python: Optional[str] = None):
        self.spec = spec
        env = dict(os.environ)
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        env.pop("JAX_PLATFORMS", None)  # the worker forces cpu itself
        self.proc = subprocess.Popen(
            [python or sys.executable, "-m", "gigapaxos_tpu.cells.worker",
             spec.to_json()],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env,
        )
        self.lines: "queue.Queue[str]" = queue.Queue()
        threading.Thread(target=self._read, daemon=True,
                         name=f"cell{spec.cell}-out").start()

    def _read(self) -> None:
        for line in self.proc.stdout:
            self.lines.put(line.strip())

    def send(self, cmd: str) -> None:
        self.proc.stdin.write(cmd + "\n")
        self.proc.stdin.flush()

    def expect(self, prefix: str, timeout: float = 60.0) -> str:
        deadline = time.monotonic() + timeout
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise TimeoutError(
                    f"cell {self.spec.cell}: no '{prefix}' line")
            try:
                line = self.lines.get(timeout=left)
            except queue.Empty:
                continue
            if line.startswith(prefix):
                return line
            if line.startswith("startup_failed"):
                raise RuntimeError(f"cell {self.spec.cell}: {line}")

    def rpc(self, cmd: str, prefix: str, timeout: float = 60.0) -> str:
        self.send(cmd)
        return self.expect(prefix, timeout)

    def db(self, r: int = 0, timeout: float = 30.0) -> dict:
        return json.loads(self.rpc(f"db {r}", "db ", timeout)[3:])

    def ledger(self, timeout: float = 30.0) -> list:
        return json.loads(self.rpc("ledger", "ledger ", timeout)[7:])

    def stats(self, timeout: float = 30.0) -> dict:
        return json.loads(self.rpc("stats", "stats ", timeout)[6:])

    def healthz(self, timeout: float = 30.0) -> dict:
        return json.loads(self.rpc("healthz", "healthz ", timeout)[8:])

    def health(self, timeout: float = 30.0):
        return json.loads(self.rpc("health", "health ", timeout)[7:])

    def group(self, name: str, timeout: float = 30.0):
        return json.loads(self.rpc(f"group {name}", "group ", timeout)[6:])

    def timeline(self, timeout: float = 30.0) -> dict:
        return json.loads(self.rpc("timeline", "timeline ", timeout)[9:])

    def metrics(self, timeout: float = 30.0) -> str:
        """This cell's Prometheus text body (every series cell-labelled)."""
        return json.loads(self.rpc("metrics", "metrics ", timeout)[8:])

    def trace(self, tid: Optional[str] = None, timeout: float = 30.0) -> dict:
        cmd = "trace" if tid is None else f"trace {tid}"
        return json.loads(self.rpc(cmd, "trace ", timeout)[6:])

    @property
    def flight_path(self) -> Optional[str]:
        """On-disk flight-recorder artifact (postmortem after a SIGKILL)."""
        return self.spec.flight

    def sigkill(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10)

    def alive(self) -> bool:
        return self.proc.poll() is None

    def terminate(self, timeout: float = 15.0) -> None:
        """Graceful stop: SIGTERM (the worker drains + flushes), SIGKILL
        only past the deadline."""
        if not self.alive():
            return
        try:
            self.proc.send_signal(signal.SIGTERM)
            self.proc.wait(timeout=timeout)
        except (OSError, subprocess.TimeoutExpired):
            self.proc.kill()


class CellSupervisor:
    """Spawn and babysit ``n_cells`` serving cells on this host."""

    def __init__(
        self,
        base_dir: str,
        cells: Optional[CellsConfig] = None,
        n_actives: Optional[int] = None,
        n_reconfigurators: Optional[int] = None,
        paxos_overrides: Optional[dict] = None,
        cfg_overrides: Optional[dict] = None,
        ledger: bool = False,
        edge: bool = False,
        python: Optional[str] = None,
        ready_timeout_s: float = 600.0,
        http_port: Optional[int] = None,
        trace_wire: Optional[bool] = None,
    ):
        self.cc = cells or CellsConfig(enabled=True)
        self.n_cells = self.cc.n_cells or max(1, (os.cpu_count() or 2) - 1)
        self.base_dir = base_dir
        self.python = python
        self.ready_timeout_s = ready_timeout_s
        n_ar = n_actives or self.cc.n_actives
        n_rc = n_reconfigurators or self.cc.n_reconfigurators
        self.restarts: Dict[int, int] = {k: 0 for k in range(self.n_cells)}
        self.fd_events: List[tuple] = []
        self._stopping = False

        # ---- fixed endpoint plan: every node of every cell, up front
        actives_by_cell: Dict[int, List[str]] = {}
        rcs_by_cell: Dict[int, List[str]] = {}
        addr: Dict[str, list] = {}
        for k in range(self.n_cells):
            actives_by_cell[k] = [f"c{k}.AR{i}" for i in range(n_ar)]
            rcs_by_cell[k] = [f"c{k}.RC{i}" for i in range(n_rc)]
            for nid in actives_by_cell[k] + rcs_by_cell[k]:
                addr[nid] = ["127.0.0.1", free_port()]
        self.addr = addr
        self.router = CellRouter(
            [actives_by_cell[k] for k in range(self.n_cells)],
            [rcs_by_cell[k] for k in range(self.n_cells)],
        )
        edge_port = (self.cc.edge_port or free_port()) if edge else None
        self.edge_addr = (["127.0.0.1", edge_port]
                          if edge_port is not None else None)

        # ---- control endpoint + heartbeats over it
        self._nodemap = NodeMap()
        for nid, (h, p) in addr.items():
            self._nodemap.add(nid, h, int(p))
        self.m = Messenger(SUP_ID, ("127.0.0.1", 0), self._nodemap)
        self.fd = FailureDetection(
            self.m, monitored=(),
            ping_interval_s=self.cc.heartbeat_interval_s,
            timeout_s=self.cc.heartbeat_timeout_s,
            on_change=self._on_fd_change,
        )

        # ---- per-cell specs
        self.specs: Dict[int, CellSpec] = {}
        for k in range(self.n_cells):
            own = set(actives_by_cell[k] + rcs_by_cell[k])
            peers = {n: a for n, a in addr.items() if n not in own}
            peers[SUP_ID] = ["127.0.0.1", self.m.port]
            self.specs[k] = CellSpec(
                cell=k, n_cells=self.n_cells,
                actives={n: addr[n] for n in actives_by_cell[k]},
                reconfigurators={n: addr[n] for n in rcs_by_cell[k]},
                peers=peers,
                wal_dir=os.path.join(base_dir, f"c{k}", "ar"),
                rc_wal_dir=os.path.join(base_dir, f"c{k}", "rc"),
                core=(k % (os.cpu_count() or 1)
                      if self.cc.pin_cores else None),
                edge=self.edge_addr,
                paxos=dict(paxos_overrides or {}),
                cfg=dict(cfg_overrides or {}),
                ledger=ledger,
                drain_timeout_s=self.cc.drain_timeout_s,
                flight=os.path.join(base_dir, f"c{k}", "flight.json"),
            )
        self.cells: Dict[int, CellHandle] = {}
        self._thread: Optional[threading.Thread] = None

        # ---- supervisor-side flight-deck gauges: a private registry (the
        # supervisor may share a process with tests/clients — its series
        # must not leak into theirs), same compile-out switch as everything
        self._reg: Registry = (Registry() if metrics_enabled()
                               else NullRegistry())
        self._g_up = {k: self._reg.gauge(
            "cell_up", help="1 if the cell's current incarnation is alive",
            cell=str(k)) for k in range(self.n_cells)}
        self._g_restarts = {k: self._reg.gauge(
            "cell_restarts_total", help="supervisor-initiated respawns",
            cell=str(k)) for k in range(self.n_cells)}
        self._g_core = {k: self._reg.gauge(
            "cell_core_pin", help="pinned CPU core (-1 when unpinned)",
            cell=str(k)) for k in range(self.n_cells)}
        for k in range(self.n_cells):
            core = self.specs[k].core
            self._g_core[k].set(-1 if core is None else int(core))
        self._reg.gauge(
            "supervisor_restart_backoff_seconds",
            help="respawn backoff between death and relaunch",
        ).set(float(self.cc.restart_backoff_s))
        self._reg.gauge(
            "supervisor_heartbeat_timeout_seconds",
            help="EWMA failure-detector timeout over the control messenger",
        ).set(float(self.cc.heartbeat_timeout_s))
        self._g_fd_down = self._reg.gauge(
            "supervisor_fd_down_events_total",
            help="heartbeat down-verdicts observed (fd timeouts)")
        self.metrics_server: Optional[MetricsServer] = None
        self._http_port = http_port
        self._trace_wire = trace_wire
        # supervisor-side timeline: events only (cell deaths, respawns, fd
        # verdicts) — unstarted sampler thread; the per-cell series come
        # from each worker's own recorder and merge in timeline()
        from ..obs.timeline import TimelineRecorder

        self.timeline_rec = TimelineRecorder(
            lambda: {}, node=SUP_ID)

    # ---------------------------------------------------------------- spawn
    def start(self) -> "CellSupervisor":
        for k in range(self.n_cells):
            self.cells[k] = CellHandle(self.specs[k], python=self.python)
        for k, h in self.cells.items():
            h.expect("ready", timeout=self.ready_timeout_s)
            self.fd.monitor(sorted(self.specs[k].actives)[0])
        self._thread = threading.Thread(
            target=self._supervise, name="cell-supervisor", daemon=True)
        self._thread.start()
        if self._http_port is not None and self._http_port >= 0:
            self.metrics_server = MetricsServer(
                self.scrape, trace=self._trace_route,
                healthz=self.healthz, health=self.health,
                group=self.group_info, timeline=self.timeline,
                port=self._http_port)
        return self

    def _on_fd_change(self, node: str, up: bool) -> None:
        # heartbeat verdicts are advisory alongside the poll() watchdog: a
        # live-but-wedged cell surfaces here for operators/tests; actual
        # respawn keys off process death (deterministic under chaos)
        self.fd_events.append((time.monotonic(), node, up))
        self.timeline_rec.annotate("fd_change", target=node, up=up)
        if not up:
            self._g_fd_down.inc()

    def _supervise(self) -> None:
        backoff = max(self.cc.restart_backoff_s, 0.05)
        while not self._stopping:
            time.sleep(backoff / 2)
            for k, h in list(self.cells.items()):
                if self._stopping or h.alive():
                    continue
                if self.restarts[k] >= self.cc.max_restarts:
                    continue  # crash-looping cell: leave it down
                self.restarts[k] += 1
                self._g_restarts[k].set(self.restarts[k])
                self.timeline_rec.annotate("cell_death", cell=k,
                                           restarts=self.restarts[k])
                time.sleep(backoff)
                if self._stopping:
                    return
                try:
                    nh = CellHandle(self.specs[k], python=self.python)
                    nh.expect("ready", timeout=self.ready_timeout_s)
                    self.cells[k] = nh
                    self.timeline_rec.annotate("cell_restart", cell=k)
                except Exception:
                    continue  # next sweep retries, counted above

    def wait_cell_alive(self, k: int, timeout: float = 600.0) -> CellHandle:
        """Block until cell k's CURRENT incarnation is live (post-crash:
        until the supervision loop finished the respawn)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            h = self.cells.get(k)
            if h is not None and h.alive():
                return h
            time.sleep(0.05)
        raise TimeoutError(f"cell {k} not restarted in {timeout}s")

    # -------------------------------------------------------------- routing
    def merged_nodes(self) -> NodeConfig:
        """One NodeConfig spanning every cell's endpoints (clients resolve
        any cell's nodes by id)."""
        nc = NodeConfig()
        for k in range(self.n_cells):
            for n in self.router.actives_by_cell[k]:
                nc.actives[n] = tuple(self.addr[n])
            for n in self.router.rcs_by_cell[k]:
                nc.reconfigurators[n] = tuple(self.addr[n])
        return nc

    def make_client(self, **kw):
        from .. import client as client_mod

        if self._trace_wire is not None:
            kw.setdefault("trace_wire", self._trace_wire)
        return client_mod.ReconfigurableAppClient(
            self.merged_nodes(), placement_table=self.router, **kw)

    def broadcast_override(self, name: str, cell: int) -> None:
        """Install a migrated name's new owner everywhere: the router (for
        clients built from it) and every live worker's edge directory."""
        self.router.set_override(name, cell)
        for h in self.cells.values():
            if h.alive():
                try:
                    h.rpc(f"override {name} {cell}", "override_ok", 10)
                except Exception:
                    pass  # a dead cell re-learns via its restart spec

    # ------------------------------------------------------------ flight deck
    def scrape(self) -> str:
        """One host-level Prometheus body: supervisor gauges plus every
        live cell's export (each worker renders its own registry with a
        ``cell="k"`` label over the control socket), merged with HELP/TYPE
        metadata deduplicated.  Dead/backing-off cells are simply absent —
        their ``cell_up`` gauge says why."""
        bodies = []
        for k, h in sorted(self.cells.items()):
            up = h.alive()
            self._g_up[k].set(1 if up else 0)
            if not up:
                continue
            try:
                bodies.append(h.metrics(timeout=15))
            except Exception:
                self._g_up[k].set(0)  # died mid-scrape
        sup = render_registry(self._reg, extra_labels={"node": SUP_ID})
        return merge_scrapes([sup] + bodies)

    def trace(self, tid: Optional[str] = None) -> dict:
        """Cross-process timeline merge: this process's shared-namespace
        store (the client side usually lives here) plus every live cell's
        dump.  Hop clocks are per-process monotonic — entries keep their
        origin so consumers don't compare timestamps across processes."""
        merged: Dict[str, list] = {}

        def fold(origin: str, dump: dict) -> None:
            for rid, evs in dump.items():
                if tid is not None and rid != str(tid):
                    continue
                merged.setdefault(rid, []).extend(
                    [[origin] + list(ev) for ev in evs])

        fold(SUP_ID, reqtrace.dump_ns())
        for k, h in sorted(self.cells.items()):
            if not h.alive():
                continue
            try:
                fold(f"c{k}", h.trace(tid, timeout=15))
            except Exception:
                pass  # a cell dying mid-dump only narrows the timeline
        return merged

    def _trace_route(self, tid: Optional[str]) -> dict:
        # /trace -> recent ids; /trace/<tid> -> one merged timeline
        return self.trace(tid)

    # -------------------------------------------------- health plane (ISSUE 18)
    def _replay_sidecar(self, k: int) -> Optional[dict]:
        """A cell mid-WAL-replay is single-threaded inside recovery and
        cannot answer the healthz RPC — but the replay publishes a
        ``replay_progress.json`` sidecar next to its journals (ISSUE 19).
        A fresh, unfinished sidecar distinguishes "long replay" from
        "hung cell"."""
        spec = self.specs.get(k)
        if spec is None:
            return None
        best = None
        for d in (spec.wal_dir, spec.rc_wal_dir):
            try:
                with open(os.path.join(d, "replay_progress.json")) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if doc.get("phase") == "done":
                continue
            if time.time() - float(doc.get("ts", 0)) > 15.0:
                continue  # stale: a replay that died mid-flight
            if best is None or doc.get("ts", 0) > best.get("ts", 0):
                best = doc
        return best

    def healthz(self) -> dict:
        """Host-level readiness: 200 only when every cell's current
        incarnation is up AND answers ok (not draining, WAL healthy) —
        the body names the cell that isn't.  A cell that is alive but
        still replaying its WAL reports ``recovering`` with progress
        read from the replay sidecar rather than a bare ``up: False``."""
        cells = {}
        ok = not self._stopping
        for k, h in sorted(self.cells.items()):
            doc = {"up": h.alive()}
            if doc["up"]:
                try:
                    doc.update(h.healthz(timeout=10))
                except Exception:
                    rep = self._replay_sidecar(k)
                    if rep is not None:
                        doc["recovering"] = True
                        tot = max(1, int(rep.get("records_total", 0)))
                        doc["wal_replay_progress"] = (
                            int(rep.get("records_done", 0)) / tot)
                        doc["replay"] = rep
                    else:
                        doc["up"] = False
            cells[str(k)] = doc
            if not (doc["up"] and doc.get("ok", False)):
                ok = False
        return {"ok": ok, "cells": cells}

    def health(self) -> Optional[dict]:
        """Merged group-health summary across cells (the `/health` body):
        counts sum, maxima max, top-K lists re-rank with a cell tag.
        None (404) when no cell runs the health fold."""
        docs = []
        for k, h in sorted(self.cells.items()):
            if not h.alive():
                continue
            try:
                d = h.health(timeout=15)
            except Exception:
                continue
            if d:
                d["cell"] = k
                docs.append(d)
        if not docs:
            return None
        merged = {
            "cells": {str(d["cell"]): d.get("clock", 0) for d in docs},
            "allocated": sum(d.get("allocated", 0) for d in docs),
            "backlogged": sum(d.get("backlogged", 0) for d in docs),
            "wedged": sum(d.get("wedged", 0) for d in docs),
            "max_stall_ticks": max(d.get("max_stall_ticks", 0)
                                   for d in docs),
            "max_churn": max(d.get("max_churn", 0) for d in docs),
            "wedge_ticks": max(d.get("wedge_ticks", 0) for d in docs),
        }
        for key in ("top_stuck", "top_churny", "top_hot"):
            # per-cell lists are already K-bounded; re-rank the union so
            # the host view is the top n_cells*K with cell provenance
            rows = [dict(e, cell=d["cell"])
                    for d in docs for e in d.get(key, [])]
            rows.sort(key=lambda e: -e["value"])
            merged[key] = rows
        hists = [d for d in docs if "hist_stall" in d]
        if hists:
            merged["hist_stall"] = [
                sum(h["hist_stall"][i] for h in hists)
                for i in range(len(hists[0]["hist_stall"]))]
        return merged

    def group_info(self, name: str) -> Optional[dict]:
        """Resolve ``name`` to its owner cell (override map first, static
        hash second — the same directory the edge uses) and drill down
        there; the answer is tagged with the owning cell."""
        k = self.router.cell(name)
        h = self.cells.get(k)
        if h is None or not h.alive():
            return {"name": name, "cell": k, "error": "cell down"}
        try:
            doc = h.group(name, timeout=15)
        except Exception as e:
            return {"name": name, "cell": k,
                    "error": f"{type(e).__name__}: {e}"}
        if doc is None:
            return None
        doc["cell"] = k
        return doc

    def timeline(self) -> dict:
        """Merged scenario timeline (the `/timeline` body): every live
        cell's sampled series plus this supervisor's lifecycle events
        (cell deaths, respawns, fd verdicts) on one wall clock."""
        from ..obs.timeline import merge_timelines

        snaps = [self.timeline_rec.snapshot()]
        for k, h in sorted(self.cells.items()):
            if not h.alive():
                continue
            try:
                snaps.append(h.timeline(timeout=15))
            except Exception:
                pass  # a cell dying mid-dump only narrows the timeline
        return merge_timelines(snaps)

    # ----------------------------------------------------------------- stop
    def stop(self) -> None:
        self._stopping = True
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None
        if self._thread is not None:
            self._thread.join(timeout=10)
        for h in self.cells.values():
            h.terminate(timeout=self.cc.drain_timeout_s + 5)
        self.fd.close()
        self.m.close()

    def __enter__(self) -> "CellSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def build_supervisor(cfg: GigapaxosTpuConfig, base_dir: str,
                     **kw) -> CellSupervisor:
    """Config-driven constructor (server.py ``--cells`` bootstrap): the
    ``cfg.cells`` section sizes and tunes the plane; ``cfg.obs`` wires the
    host-level scrape endpoint."""
    obs = getattr(cfg, "obs", None)
    if obs is not None and obs.sup_http_port >= 0:
        kw.setdefault("http_port", obs.sup_http_port)
    if obs is not None and obs.trace_wire:
        kw.setdefault("trace_wire", True)
    return CellSupervisor(base_dir, cells=cfg.cells, **kw)

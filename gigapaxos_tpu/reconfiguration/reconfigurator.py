"""Reconfigurator: the control-plane brain.

Analog of ``reconfiguration/Reconfigurator.java:128``.  Handles client name
management (``handleCreateServiceName :505``, ``handleDeleteServiceName
:768``, ``handleRequestActiveReplicas :910``), demand-driven migration
(``handleDemandReport :332``), and drives the epoch-change workflow through
protocol tasks — the direct analogs of
``reconfigurationprotocoltasks/``:

* :class:`WaitAckStopEpoch` (WaitAckStopEpoch.java:38) — stop the old epoch
  at a majority of its actives;
* :class:`WaitAckStartEpoch` (WaitAckStartEpoch.java:50) — start the new
  epoch at a majority of the new actives;
* :class:`WaitAckDropEpoch` (WaitAckDropEpoch.java:45) — lazily GC the old
  epoch's final state (bounded retries);
* :class:`WaitPrimaryExecution` (WaitPrimaryExecution.java:60) — non-primary
  members of a name's RC group watchdog an in-flight reconfiguration and
  take over if the primary dies mid-workflow.

Every step is gated on a paxos-committed record mutation through the
replicated :mod:`rc_db` (RCRecordRequest intents/completes committed by
``CommitWorker``, CommitWorker.java:46 — here the commit liveness comes from
the data plane's own retry loop plus task restarts), so any RC replica can
resume the workflow from the record state alone.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..net.messenger import Messenger
from ..protocoltask.executor import ProtocolExecutor, ProtocolTask
from . import packets as pkt
from .consistent_hashing import ConsistentHashRing
from .demand import AbstractDemandProfile, DemandProfile
from .rc_db import NC_RC_RECORD, NC_RECORD, RepliconfigurableReconfiguratorDB
from .records import RCState


def _majority(n: int) -> int:
    return n // 2 + 1


class _WaitAcks(ProtocolTask):
    """Shared shape of the epoch ack-threshold tasks: multicast a packet to a
    node set, complete at a threshold of distinct acks (ThresholdProtocolTask
    analog, protocoltask/ThresholdProtocolTask.java)."""

    period_s = 0.5
    #: backup/watchdog instances set this so the initial schedule() sends
    #: nothing — only the first periodic restart does, giving the primary's
    #: own task a head start before duplicate packets go out
    first_delayed = False

    def __init__(self, targets: List[str], threshold: int,
                 on_done: Optional[Callable[[], None]] = None):
        self.targets = list(targets)
        self.threshold = threshold
        self.acked: set = set()
        self._on_done = on_done
        self._held_first = False

    def packet(self) -> dict:
        raise NotImplementedError

    def start(self):
        if self.first_delayed and not self._held_first:
            self._held_first = True
            return []
        p = self.packet()
        return [(t, dict(p)) for t in self.targets if t not in self.acked]

    def handle(self, event: dict):
        sender = event.get("sender")
        if sender in self.targets:
            self.acked.add(sender)
        return [], len(self.acked) >= self.threshold

    def on_done(self):
        if self._on_done is not None:
            self._on_done()


class WaitAckStopEpoch(_WaitAcks):
    def __init__(self, rc: "Reconfigurator", name: str, epoch: int,
                 actives: List[str], on_done):
        super().__init__(actives, _majority(len(actives)), on_done)
        self.rc, self.name, self.epoch = rc, name, epoch

    @property
    def key(self) -> str:
        return f"WaitAckStopEpoch:{self.name}:{self.epoch}"

    def packet(self) -> dict:
        return pkt.stop_epoch(self.name, self.epoch, self.rc.node_id)


class WaitAckStartEpoch(_WaitAcks):
    def __init__(self, rc: "Reconfigurator", name: str, epoch: int,
                 actives: List[str], prev_epoch: int, prev_actives: List[str],
                 initial_state: Optional[bytes], on_done):
        super().__init__(actives, _majority(len(actives)), on_done)
        self.rc, self.name, self.epoch = rc, name, epoch
        self.prev_epoch, self.prev_actives = prev_epoch, prev_actives
        self.initial_state = initial_state

    @property
    def key(self) -> str:
        return f"WaitAckStartEpoch:{self.name}:{self.epoch}"

    def packet(self) -> dict:
        return pkt.start_epoch(
            self.name, self.epoch, self.targets, self.rc.node_id,
            self.prev_epoch, self.prev_actives, self.initial_state,
        )


class WaitAckDropEpoch(_WaitAcks):
    """GC task: wants *all* acks but gives up after max_restarts (the
    reference's WaitAckDropEpoch similarly bounds retries)."""

    period_s = 1.0
    max_restarts = 8

    def __init__(self, rc: "Reconfigurator", name: str, epoch: int,
                 actives: List[str], on_done=None):
        super().__init__(actives, len(actives), on_done)
        self.rc, self.name, self.epoch = rc, name, epoch

    @property
    def key(self) -> str:
        return f"WaitAckDropEpoch:{self.name}:{self.epoch}"

    def packet(self) -> dict:
        return pkt.drop_epoch(self.name, self.epoch, self.rc.node_id)


class WaitPrimaryExecution(ProtocolTask):
    """Failover watchdog: a non-primary RC sees an intent commit and waits;
    if the record is still mid-reconfiguration after a grace period and the
    primary looks dead (or enough restarts pass), this RC re-drives the
    workflow — safe because every step is idempotent and record-gated."""

    period_s = 2.0
    max_restarts = 30

    def __init__(self, rc: "Reconfigurator", name: str, epoch: int,
                 takeover_after: int = 2):
        self.rc, self.name, self.epoch = rc, name, epoch
        self.takeover_after = takeover_after
        self._fires = 0

    @property
    def key(self) -> str:
        return f"WaitPrimaryExecution:{self.name}:{self.epoch}"

    def start(self):
        return []

    def restart(self):
        rec = self.rc.db.get(self.name)
        if rec is None or (rec.state == RCState.READY and rec.epoch > self.epoch):
            self.rc.executor.cancel(self.key)  # workflow finished
            return []
        self._fires += 1
        primary_dead = not self.rc.is_node_up(self.rc.rdb.primary_of(self.name))
        if primary_dead or self._fires >= self.takeover_after:
            self.rc.executor.cancel(self.key)
            self.rc._resume_workflow(self.name)
        return []

    def handle(self, event):
        return [], True  # explicit completion event (unused today)


class RCMigrateTask(ProtocolTask):
    """After an RC ring splice, sweep local records into their re-homed RC
    groups with idempotent ``record_install`` commits until every record
    this node should drive is installed (Reconfigurator.java:1044's record
    re-hash, made crash-tolerant by retrying sweeps)."""

    period_s = 1.0
    max_restarts = 30

    def __init__(self, rc: "Reconfigurator", change_epoch: int):
        self.rc = rc
        self.change_epoch = change_epoch

    @property
    def key(self) -> str:
        return f"RCMigrate:{self.change_epoch}"

    def start(self):
        self.rc._rc_migrate_once()
        return []

    def restart(self):
        if self.rc._rc_migrate_once() == 0:
            self.rc.executor.cancel(self.key)
        return []

    def handle(self, event):
        return [], True


class UniverseGossipTask(ProtocolTask):
    """Low-rate re-broadcast of the committed replica universe: closes the
    window where an active partitioned across an add_active only converges
    at the NEXT add (nc_universe_apply is idempotent, so over-delivery is
    free)."""

    period_s = 5.0
    max_restarts = 1 << 30

    def __init__(self, rc):
        self.rc = rc

    @property
    def key(self) -> str:
        return f"UniverseGossip:{self.rc.node_id}"

    def start(self):
        self.rc._broadcast_universe()
        return []

    def handle(self, event):
        return [], False


class NodeDrainTask(ProtocolTask):
    """Retrying drain of a removed active: sweeps until no record this RC
    can see still lists the node (names that were mid-reconfiguration at
    NC-commit time get migrated on a later sweep)."""

    period_s = 1.5
    max_restarts = 60

    def __init__(self, rc: "Reconfigurator", node: str):
        self.rc, self.node = rc, node

    @property
    def key(self) -> str:
        return f"NodeDrain:{self.node}"

    def start(self):
        self.rc._drain_node_once(self.node)
        return []

    def restart(self):
        if self.rc._drain_node_once(self.node) == 0:
            self.rc.executor.cancel(self.key)
        return []

    def handle(self, event):
        return [], True


class Reconfigurator:
    def __init__(
        self,
        node_id: str,
        messenger: Messenger,
        rdb: RepliconfigurableReconfiguratorDB,
        active_ids: List[str],
        replicas_per_name: int = 3,
        demand_profile_factory: Callable[[str], AbstractDemandProfile] = DemandProfile,
        is_node_up: Optional[Callable[[str], bool]] = None,
    ):
        self.node_id = node_id
        self.m = messenger
        self.rdb = rdb
        self.db = rdb.db_of(node_id)
        self.db.listener = self._on_db_commit
        self.actives_pool: List[str] = sorted(active_ids)
        self.actives_ring = ConsistentHashRing(self.actives_pool)
        self.k = replicas_per_name
        self.profile_factory = demand_profile_factory
        self._profiles: Dict[str, AbstractDemandProfile] = {}
        self._lock = threading.RLock()
        self.is_node_up = is_node_up or (lambda n: True)
        #: in-flight client replies: name -> (client_id, reply_packet_base)
        self._pending_reply: Dict[str, tuple] = {}
        #: records already re-homed after an RC ring splice (convergence
        #: marker for RCMigrateTask sweeps)
        self._rc_migrated: set = set()
        #: delegated create_batch sub-requests: sub-rid -> aggregation hook
        self._sub_batches: Dict[int, list] = {}
        self._sub_done: Dict[int, Callable[[dict], None]] = {}
        self._sub_next = 1 << 41  # disjoint from client and anycast rids
        #: optional placement-override table (placement/table.py): when the
        #: placement plane is live, overrides take precedence over the ring
        #: for name placement (set by the deployment wiring, not here)
        self.placement_table = None
        self.executor = ProtocolExecutor(self.m.send, name=f"rc-{node_id}")
        for ptype, h in [
            (pkt.CREATE_SERVICE_NAME, self._on_create),
            (pkt.CREATE_BATCH, self._on_create_batch),
            (pkt.CREATE_BATCH_RESPONSE, self._on_create_batch_response),
            (pkt.DELETE_SERVICE_NAME, self._on_delete),
            (pkt.REQUEST_ACTIVE_REPLICAS, self._on_request_actives),
            (pkt.CLIENT_RECONFIGURE, self._on_client_reconfigure),
            (pkt.DEMAND_REPORT, self._on_demand_report),
            (pkt.ACK_STOP_EPOCH, self._route_ack("WaitAckStopEpoch")),
            (pkt.ACK_START_EPOCH, self._route_ack("WaitAckStartEpoch")),
            (pkt.ACK_DROP_EPOCH, self._route_ack("WaitAckDropEpoch")),
            (pkt.ADD_ACTIVE, self._on_node_config),
            (pkt.REMOVE_ACTIVE, self._on_node_config),
            (pkt.ADD_RC, self._on_rc_node_config),
            (pkt.REMOVE_RC, self._on_rc_node_config),
        ]:
            self.m.register(ptype, h)

    def close(self) -> None:
        self.executor.stop()
        self.m.close()

    # ------------------------------------------------------------- placement
    def initial_actives(self, name: str) -> List[str]:
        """Default placement: consistent-hash the name onto the active pool
        (ReconfigurationConfig's default placement policy).  With a
        placement-override table attached, an overridden name's servers
        come from the table instead (lookup falls through to the same ring
        when no override exists)."""
        k = min(self.k, len(self.actives_pool))
        if self.placement_table is not None:
            return self.placement_table.lookup(name, k)
        return self.actives_ring.replicated_servers(name, k)

    def _ensure_owner(self, name: str, sender: str, p: dict) -> bool:
        """With more reconfigurators than k, a client packet may land on an
        RC outside the name's group — forward it to the primary with client
        reply routing preserved (the reference forwards RCRecordRequests to
        the responsible group the same way).  Returns True when this RC
        should handle the packet locally."""
        if self.node_id in self.rdb.rc_group_of(name):
            return True
        if p.get("rc_fwd"):
            return True  # one hop max: handle (and possibly fail) here
        p2 = dict(p)
        p2["reply_to"] = p.get("reply_to") or sender
        p2["rc_fwd"] = 1
        self.m.send(self.rdb.primary_of(name), p2)
        return False

    def _route_ack(self, task: str):
        def h(sender: str, p: dict) -> None:
            self.executor.handle_event(f"{task}:{p['name']}:{p['epoch']}", p)
        return h

    # ------------------------------------------------------------ name create
    def _on_create(self, sender: str, p: dict) -> None:
        pkt.register_client(self.m.nodemap, p)
        sender = p.get("reply_to") or sender
        name, rid = p["name"], p["rid"]
        if not self._ensure_owner(name, sender, p):
            return
        state = pkt.b64d(p["initial_state"]) or b""
        actives = self.initial_actives(name)

        def committed(result: dict) -> None:
            if not result.get("ok"):
                self.m.send(sender, {
                    "type": pkt.CREATE_RESPONSE, "rid": rid, "name": name,
                    "ok": False, "error": result.get("error", "failed"),
                })
                return

            def started() -> None:
                self.m.send(sender, {
                    "type": pkt.CREATE_RESPONSE, "rid": rid, "name": name,
                    "ok": True, "actives": actives,
                })

            # a recreated name continues at tombstone+1 (rc_db): the old
            # incarnation's still-in-flight DropEpoch must never be able
            # to address the new incarnation's data-plane group
            ep = int(result.get("epoch", 0))
            # a stale backup task from a previous incarnation of this name
            # would block this key and orphan the client response
            self.executor.cancel(f"WaitAckStartEpoch:{name}:{ep}")
            self.executor.schedule(WaitAckStartEpoch(
                self, name, ep, actives, -1, [], state, started
            ))

        # origin + initial_state ride inside the replicated command so any
        # RC-group member can re-send the creation StartEpoch if this RC
        # dies between the commit and the delivery (see _on_db_commit)
        self.rdb.commit(
            name,
            {"op": "create", "name": name, "actives": actives,
             "origin": self.node_id, "initial_state": p["initial_state"]},
            committed, proposer=self.node_id,
        )

    # ------------------------------------------------------------ batch create
    def _on_create_batch(self, sender: str, p: dict) -> None:
        """handleCreateServiceName's batched flavor: ONE paxos commit per RC
        group creates every record of the batch, then per-name StartEpochs
        run concurrently (BatchedCreateServiceName.java; issued by the
        client library which packs creates,
        ReconfigurableAppClientAsync.java:35)."""
        pkt.register_client(self.m.nodemap, p)
        sender = p.get("reply_to") or sender
        rid = p["rid"]
        # dedup by name: results are keyed by name, so duplicates would make
        # the completion count unreachable and strand the response
        creates = list({c["name"]: c for c in p.get("creates", [])}.values())
        if not creates:
            self.m.send(sender, {"type": pkt.CREATE_BATCH_RESPONSE,
                                 "rid": rid, "ok": False,
                                 "error": "empty_batch"})
            return
        results: Dict[str, dict] = {}
        total = len(creates)
        lock = threading.Lock()

        def name_done(n: str, entry: dict) -> None:
            with lock:
                if n in results:
                    return
                results[n] = entry
                finished = len(results) == total
            if finished:
                self.m.send(sender, {
                    "type": pkt.CREATE_BATCH_RESPONSE, "rid": rid,
                    "ok": all(r.get("ok") for r in results.values()),
                    "results": results,
                })

        # partition server-side by RC group: every partition is one commit
        parts: Dict[tuple, list] = {}
        for c in creates:
            key = tuple(self.rdb.rc_group_of(c["name"]))
            parts.setdefault(key, []).append({
                "name": c["name"],
                "actives": self.initial_actives(c["name"]),
                "initial_state": c.get("initial_state"),
            })

        # partitions whose group excludes this RC cannot commit here (a
        # non-member's proposal never fires its callback in Mode B):
        # delegate the sub-batch to that group's primary and fold its
        # response back into ours (_ensure_owner's batched analog)
        foreign = {g: e for g, e in parts.items()
                   if self.node_id not in g and not p.get("rc_fwd")}
        for group, entries in foreign.items():
            del parts[group]
            sub_rid = self._sub_rid()
            with self._lock:
                self._sub_batches[sub_rid] = [e["name"] for e in entries]

                def sub_done(results_by_name: dict, entries=entries) -> None:
                    for e in entries:
                        n = e["name"]
                        name_done(n, results_by_name.get(
                            n, {"ok": False, "error": "forward_failed"}
                        ))

                self._sub_done[sub_rid] = sub_done
            self.m.send(group[0], {
                "type": pkt.CREATE_BATCH, "rid": sub_rid, "rc_fwd": 1,
                "reply_to": self.node_id,
                "creates": [
                    {"name": e["name"],
                     "initial_state": e["initial_state"]}
                    for e in entries
                ],
            })

        for entries in parts.values():
            def committed(result: dict, entries=entries) -> None:
                if not result.get("ok"):
                    for e in entries:
                        name_done(e["name"], {
                            "ok": False,
                            "error": result.get("error", "failed"),
                        })
                    return
                per = result.get("results", {})
                for e in entries:
                    n = e["name"]
                    r = per.get(n, {"ok": False, "error": "failed"})
                    if not r.get("ok"):
                        name_done(n, dict(r))
                        continue
                    ep = int(r.get("epoch", 0))
                    self.executor.cancel(f"WaitAckStartEpoch:{n}:{ep}")
                    self.executor.schedule(WaitAckStartEpoch(
                        self, n, ep, e["actives"], -1, [],
                        pkt.b64d(e["initial_state"]) or b"",
                        lambda n=n, e=e: name_done(
                            n, {"ok": True, "actives": e["actives"]}
                        ),
                    ))

            self.rdb.commit(
                entries[0]["name"],
                {"op": "create_batch", "name": entries[0]["name"],
                 "creates": entries, "origin": self.node_id},
                committed, proposer=self.node_id,
            )

    def _sub_rid(self) -> int:
        with self._lock:
            self._sub_next += 1
            return self._sub_next

    def _on_create_batch_response(self, sender: str, p: dict) -> None:
        """Fold a delegated sub-batch's response into the original batch."""
        with self._lock:
            self._sub_batches.pop(p.get("rid"), None)
            hook = self._sub_done.pop(p.get("rid"), None)
        if hook is None:
            return
        hook(p.get("results") or {})

    # ------------------------------------------------------------ name delete
    def _on_delete(self, sender: str, p: dict) -> None:
        pkt.register_client(self.m.nodemap, p)
        sender = p.get("reply_to") or sender
        name, rid = p["name"], p["rid"]
        if not self._ensure_owner(name, sender, p):
            return

        def committed(result: dict) -> None:
            if not result.get("ok"):
                self.m.send(sender, {
                    "type": pkt.DELETE_RESPONSE, "rid": rid, "name": name,
                    "ok": False, "error": result.get("error", "failed"),
                })
                return
            rec = self.db.get(name)
            epoch = rec.epoch if rec is not None else int(result.get("epoch", 0))
            actives = list(rec.actives) if rec is not None else []

            def stopped() -> None:
                def deleted(res: dict) -> None:
                    self.m.send(sender, {
                        "type": pkt.DELETE_RESPONSE, "rid": rid, "name": name,
                        "ok": bool(res.get("ok")),
                    })

                def dropped() -> None:
                    # the record stays WAIT_DELETE until the old epoch's
                    # state is GC'd everywhere (or the drop task ages out —
                    # the MAX_FINAL_STATE_AGE grace), so a recreate at epoch
                    # 0 can never race an in-flight drop of the old instance
                    self.rdb.commit(
                        name, {"op": "delete_complete", "name": name},
                        deleted, proposer=self.node_id,
                    )

                if actives:
                    self.executor.schedule(
                        WaitAckDropEpoch(self, name, epoch, actives, dropped)
                    )
                else:
                    dropped()

            if actives:
                self.executor.schedule(
                    WaitAckStopEpoch(self, name, epoch, actives, stopped)
                )
            else:
                stopped()

        self.rdb.commit(
            name, {"op": "delete_intent", "name": name, "now": time.time()},
            committed, proposer=self.node_id,
        )

    # -------------------------------------------------------- actives lookup
    def _on_request_actives(self, sender: str, p: dict) -> None:
        pkt.register_client(self.m.nodemap, p)
        sender = p.get("reply_to") or sender
        name, rid = p["name"], p["rid"]
        if name != pkt.ALL_ACTIVES and not self._ensure_owner(name, sender, p):
            return
        if name == pkt.ALL_ACTIVES:
            # anycast pool resolution: the whole active set, no record
            # (ReconfigurableAppClientAsync.ALL_ACTIVES)
            addrs = {}
            for a in self.actives_pool:
                addr = self.m.nodemap(a)
                if addr is not None:
                    addrs[a] = [addr[0], addr[1]]
            self.m.send(sender, {
                "type": pkt.ACTIVES_RESPONSE, "rid": rid, "name": name,
                "ok": True, "epoch": -1, "actives": list(self.actives_pool),
                "addrs": addrs,
            })
            return
        rec = self.db.get(name)
        if rec is None or rec.state == RCState.WAIT_DELETE:
            self.m.send(sender, {
                "type": pkt.ACTIVES_RESPONSE, "rid": rid, "name": name,
                "ok": False, "error": "unknown_name",
            })
            return
        addrs = {}
        for a in rec.actives:
            addr = self.m.nodemap(a)
            if addr is not None:
                addrs[a] = [addr[0], addr[1]]
        self.m.send(sender, {
            "type": pkt.ACTIVES_RESPONSE, "rid": rid, "name": name, "ok": True,
            "epoch": rec.epoch, "actives": list(rec.actives), "addrs": addrs,
        })

    # -------------------------------------------------------- reconfiguration
    def _on_demand_report(self, sender: str, p: dict) -> None:
        """handleDemandReport (Reconfigurator.java:332): aggregate, ask the
        policy, and (primary only) kick off a migration."""
        name = p["name"]
        with self._lock:
            prof = self._profiles.get(name)
            if prof is None:
                prof = self._profiles[name] = self.profile_factory(name)
            prof.combine(p["stats"])
        if self.rdb.primary_of(name) != self.node_id:
            return
        rec = self.db.get(name)
        if rec is None or not rec.can_reconfigure():
            return
        new_actives = prof.reconfigure(list(rec.actives), self.actives_pool)
        if new_actives:
            new_actives = [a for a in new_actives if a in self.actives_pool]
        if new_actives and sorted(new_actives) != sorted(rec.actives):
            self._reconfigure(name, sorted(new_actives), on_done=prof.just_reconfigured)

    def _on_client_reconfigure(self, sender: str, p: dict) -> None:
        pkt.register_client(self.m.nodemap, p)
        sender = p.get("reply_to") or sender
        name, rid = p["name"], p["rid"]
        if not self._ensure_owner(name, sender, p):
            return
        requested = p.get("new_actives") or []
        bad = [a for a in requested if a not in self.actives_pool]
        if not requested or bad:
            # committing an unknown/empty active set would brick the name:
            # the old epoch gets stopped but no reachable new epoch starts
            self.m.send(sender, {
                "type": pkt.RECONFIGURE_RESPONSE, "rid": rid, "name": name,
                "ok": False, "error": f"bad_actives:{','.join(bad) or 'empty'}",
            })
            return
        rec = self.db.get(name)
        if rec is None or not rec.can_reconfigure():
            self.m.send(sender, {
                "type": pkt.RECONFIGURE_RESPONSE, "rid": rid, "name": name,
                "ok": False,
                "error": "unknown_name" if rec is None else "busy",
            })
            return

        def done() -> None:
            self.m.send(sender, {
                "type": pkt.RECONFIGURE_RESPONSE, "rid": rid, "name": name,
                "ok": True, "actives": sorted(p["new_actives"]),
            })

        ok = self._reconfigure(name, sorted(p["new_actives"]), on_done=done)
        if not ok:
            self.m.send(sender, {
                "type": pkt.RECONFIGURE_RESPONSE, "rid": rid, "name": name,
                "ok": False, "error": "busy",
            })

    def _reconfigure(self, name: str, new_actives: List[str],
                     on_done: Optional[Callable[[], None]] = None) -> bool:
        """Drive READY -> intent -> stop old -> complete -> start new
        (§3.4's full chain).  Returns False if the intent can't be placed."""
        rec = self.db.get(name)
        if rec is None or not rec.can_reconfigure():
            return False

        def intent_committed(result: dict) -> None:
            if not result.get("ok"):
                return  # raced with another workflow; watchdogs cover it
            self._drive_stop_then_start(name, on_done)

        self.rdb.commit(
            name,
            {"op": "reconfigure_intent", "name": name,
             "new_actives": new_actives},
            intent_committed, proposer=self.node_id,
        )
        return True

    def _drive_stop_then_start(
        self, name: str, on_done: Optional[Callable[[], None]] = None
    ) -> None:
        """From a committed WAIT_ACK_STOP record, run the rest of the epoch
        change.  Used by both the primary path and failover resume.

        Ordering: stop old -> start new -> commit reconfigure_complete ->
        GC old.  The complete is committed only after a majority of the new
        epoch acked StartEpoch, so the record stays WAIT_ACK_STOP for the
        whole in-flight window — which is exactly what lets
        WaitPrimaryExecution on any RC re-drive the workflow from the record
        alone if the driving RC crashes at any point (every step below is
        idempotent)."""
        rec = self.db.get(name)
        if rec is None or rec.state != RCState.WAIT_ACK_STOP:
            return
        old_epoch, old_actives = rec.epoch, list(rec.actives)
        new_actives = list(rec.new_actives)

        def stopped() -> None:
            def started() -> None:
                def completed(result: dict) -> None:
                    # ok=False means another RC completed it first — the
                    # epoch changed either way, so GC and finish
                    self.executor.schedule(
                        WaitAckDropEpoch(self, name, old_epoch, old_actives)
                    )
                    if on_done is not None:
                        on_done()

                self.rdb.commit(
                    name,
                    {"op": "reconfigure_complete", "name": name,
                     "epoch": old_epoch},
                    completed, proposer=self.node_id,
                )

            self.executor.schedule(WaitAckStartEpoch(
                self, name, old_epoch + 1, new_actives,
                old_epoch, old_actives, None, started,
            ))

        self.executor.schedule(
            WaitAckStopEpoch(self, name, old_epoch, old_actives, stopped)
        )

    def _resume_workflow(self, name: str) -> None:
        """Failover entry (WaitPrimaryExecution takeover): re-drive whatever
        the record state says is unfinished."""
        rec = self.db.get(name)
        if rec is None:
            return
        if rec.state == RCState.WAIT_ACK_STOP:
            self._drive_stop_then_start(name)
        elif rec.state == RCState.WAIT_DELETE:
            def stopped() -> None:
                def dropped() -> None:
                    # same drop-before-delete_complete gating as the primary
                    # delete path: a recreate at epoch 0 must never race an
                    # in-flight drop of the old instance
                    self.rdb.commit(
                        name, {"op": "delete_complete", "name": name},
                        proposer=self.node_id,
                    )
                self.executor.schedule(WaitAckDropEpoch(
                    self, name, rec.epoch, list(rec.actives), dropped
                ))
            self.executor.schedule(WaitAckStopEpoch(
                self, name, rec.epoch, list(rec.actives), stopped
            ))

    # ------------------------------------------------------- node elasticity
    def _on_node_config(self, sender: str, p: dict) -> None:
        """handleReconfigureRCNodeConfig analog (Reconfigurator.java:1044):
        add/remove an active node at runtime.  The change commits through
        the all-RC node-config record, so every reconfigurator updates its
        pool/ring deterministically from the commit stream; names placed on
        a removed node are migrated away as ordinary reconfigurations."""
        pkt.register_client(self.m.nodemap, p)
        node, rid = p.get("node"), p.get("rid")

        def reject(error: str) -> None:
            self.m.send(sender, {
                "type": pkt.NODE_CONFIG_RESPONSE, "rid": rid, "ok": False,
                "error": error,
            })

        if not node:
            reject("need node")
            return
        removing = p["type"] == pkt.REMOVE_ACTIVE
        if removing:
            if node not in self.actives_pool:
                reject("unknown_node")
                return
            if len(self.actives_pool) - 1 < self.k:
                # shrinking below replicas_per_name would silently
                # under-replicate every migrated name
                reject("pool_too_small")
                return
        cmd = {"op": "remove_active" if removing else "add_active",
               "name": NC_RECORD, "node": node, "addr": p.get("addr"),
               "seed_pool": sorted(self.actives_pool), "min_pool": self.k}

        def committed(result: dict) -> None:
            self.m.send(sender, {
                "type": pkt.NODE_CONFIG_RESPONSE, "rid": rid,
                "ok": bool(result.get("ok")), "node": node,
                "pool": result.get("pool"),
                # the committed replica-slot order: the operator puts this
                # in the new node's properties (``universe=...``) so its
                # boot slot indices match the incumbents'
                "universe": result.get("universe"),
            })

        self.rdb.commit(NC_RECORD, cmd, committed, proposer=self.node_id)

    def _apply_node_config(self, cmd: dict, record: Optional[dict]) -> None:
        node = cmd["node"]
        pool = sorted(record["actives"]) if record else self.actives_pool
        with self._lock:
            self.actives_pool = pool
            self.actives_ring = ConsistentHashRing(pool)
        if cmd["op"] == "add_active":
            addr = cmd.get("addr")
            if addr:
                # overwrite unconditionally: a node removed and re-added at
                # a new address must not keep its stale routing entry
                self.m.nodemap.add(node, addr[0], int(addr[1]))
            # push the committed slot order to every active so Mode B data
            # planes grow their replica universe in lockstep (idempotent:
            # each broadcast carries the complete order AND every address
            # this RC can resolve, so a server that missed an earlier add
            # catches up on both the slots and the routing from the next)
            universe = (record or {}).get("universe") or pool
            self._universe_committed = list(universe)
            self._broadcast_universe()
            # keep re-broadcasting at a low rate: an active partitioned
            # across THIS add would otherwise only converge when a future
            # add triggers the next broadcast (advisor, round 3)
            self.executor.schedule(UniverseGossipTask(self))
            return
        # removal: drain the node with a retrying task, not a one-shot pass —
        # names mid-reconfiguration (or whose primary is down) at commit time
        # must still be migrated once they quiesce
        self.executor.schedule(NodeDrainTask(self, node))

    def _broadcast_universe(self) -> None:
        """Send the committed replica-slot order + resolvable addresses to
        every pool member (idempotent; see _apply_node_config)."""
        universe = getattr(self, "_universe_committed", None)
        if not universe:
            return
        addrs = {}
        for nid in universe:
            a_ = self.m.nodemap(nid)
            if a_ is not None:
                addrs[nid] = list(a_)
        for a in self.actives_pool:
            try:
                self.m.send(a, {
                    "type": "nc_universe_apply",
                    "universe": list(universe), "addrs": addrs,
                })
            except Exception:  # a down active learns from its WAL/boot
                pass

    def _drain_node_once(self, node: str) -> int:
        """One drain sweep: migrate off ``node`` every name this RC should
        drive.  Returns how many names still reference the node."""
        remaining = 0
        pool = self.actives_pool
        for name in self.db.names():
            rec = self.db.get(name)
            if rec is None or node not in rec.actives:
                continue
            remaining += 1
            primary = self.rdb.primary_of(name)
            drive = primary == self.node_id or not self.is_node_up(primary)
            if not drive or not rec.can_reconfigure():
                continue
            keep = [a for a in rec.actives if a != node]
            spare = [a for a in pool if a not in keep]
            new = sorted(keep + spare[: max(0, len(rec.actives) - len(keep))])
            if new and sorted(new) != sorted(rec.actives):
                self._reconfigure(name, new)
        return remaining

    # -------------------------------------------------- RC-node elasticity
    def _on_rc_node_config(self, sender: str, p: dict) -> None:
        """handleReconfigureRCNodeConfig (Reconfigurator.java:1044), RC
        side: splice a reconfigurator in/out of the pool.  The change
        commits on the all-RC ``_NC_RC`` record; every RC then updates its
        ring deterministically from the commit stream and re-homes records
        whose consistent-hash group changed (``RCMigrateTask``)."""
        pkt.register_client(self.m.nodemap, p)
        sender = p.get("reply_to") or sender
        node, rid = p.get("node"), p.get("rid")

        def reject(error: str) -> None:
            self.m.send(sender, {
                "type": pkt.NODE_CONFIG_RESPONSE, "rid": rid, "ok": False,
                "error": error,
            })

        if not node:
            reject("need node")
            return
        removing = p["type"] == pkt.REMOVE_RC
        pool = set(self.rdb.rc_ids)
        if removing and node not in pool:
            reject("unknown_node")
            return
        if removing and len(pool) - 1 < self.rdb.k:
            reject("pool_too_small")
            return
        if not removing and p.get("addr"):
            # learn the newcomer's address before the commit fans out
            self.m.nodemap.add(node, p["addr"][0], int(p["addr"][1]))
        cmd = {"op": "remove_rc" if removing else "add_rc",
               "name": NC_RC_RECORD, "node": node, "addr": p.get("addr"),
               "seed_pool": sorted(pool), "min_pool": self.rdb.k}

        def committed(result: dict) -> None:
            self.m.send(sender, {
                "type": pkt.NODE_CONFIG_RESPONSE, "rid": rid,
                "ok": bool(result.get("ok")), "node": node,
                "pool": result.get("pool"),
            })

        self.rdb.commit(NC_RC_RECORD, cmd, committed, proposer=self.node_id)

    def _apply_rc_node_config(self, cmd: dict, record: Optional[dict]) -> None:
        node = cmd["node"]
        pool = sorted(record["actives"]) if record else self.rdb.rc_ids
        if cmd["op"] == "add_rc":
            addr = cmd.get("addr")
            if addr:
                self.m.nodemap.add(node, addr[0], int(addr[1]))
            self.rdb.bind_rc(node)
        # splice the shared ring once (several Reconfigurator listeners may
        # share one rdb in-process; update_pool is idempotent)
        if sorted(pool) != sorted(self.rdb.rc_ids):
            self.rdb.update_pool(pool)
        epoch = record["epoch"] if record else 0
        self.executor.cancel(f"RCMigrate:{epoch}")
        self.executor.schedule(RCMigrateTask(self, epoch))

    def _rc_migrate_once(self) -> int:
        """One re-home sweep: install every local record whose new RC group
        this node primaries (or whose primary is down) into that group.
        Returns how many installs were issued (0 = converged)."""
        issued = 0
        pool_key = tuple(self.rdb.rc_ids)
        for name in self.db.names() + [NC_RECORD]:
            rec = self.db.get(name)
            if rec is None:
                continue
            # EVERY holder installs (no primary gate): after a splice the
            # re-homed group's primary may be the fresh node, which holds
            # nothing — only the old holders can carry the record over.
            # Duplicates are cheap no-op commits, deduped per holder below.
            key = (pool_key, name, rec.epoch)
            if key in self._rc_migrated:
                continue

            # confirm-on-success only: a lost proposal (no callback) keeps
            # the key unmarked, so the next sweep re-issues the idempotent
            # install instead of silently abandoning the record
            def installed(result: dict, key=key) -> None:
                if result.get("ok"):
                    self._rc_migrated.add(key)

            # re-commit into the (possibly new) group; the install is a
            # no-op wherever an equal-or-newer record already exists
            self.rdb.commit(
                name,
                {"op": "record_install", "name": name,
                 "record": rec.to_dict()},
                installed, proposer=self.node_id,
            )
            issued += 1
        # reincarnation tombstones re-home too: without them a recreate in
        # the new group would restart at epoch 0 and the old incarnation's
        # late DropEpoch could destroy it (see rc_db tombstones)
        for name, ep in list(self.db.tombstones.items()):
            key = (pool_key, name, "tomb", ep)
            if key in self._rc_migrated:
                continue

            def t_installed(result: dict, key=key) -> None:
                if result.get("ok"):
                    self._rc_migrated.add(key)

            self.rdb.commit(
                name,
                {"op": "tombstone_install", "name": name, "epoch": ep},
                t_installed, proposer=self.node_id,
            )
            issued += 1
        return issued

    # --------------------------------------------------------- commit events
    def _on_db_commit(self, cmd: dict, record: Optional[dict]) -> None:
        """Listener on this node's DB replica: non-primary RC-group members
        arm the failover watchdog when they see an intent commit."""
        name = cmd.get("name")
        if name is None:
            return
        if name == NC_RECORD:
            if cmd.get("op") in ("add_active", "remove_active"):
                self._apply_node_config(cmd, record)
            return
        if name == NC_RC_RECORD:
            if cmd.get("op") in ("add_rc", "remove_rc"):
                self._apply_rc_node_config(cmd, record)
            return
        op = cmd.get("op")
        if op == "delete_complete":
            with self._lock:
                self._profiles.pop(name, None)
            # kill any lingering start/drop tasks for the dead name so a
            # later recreate at epoch 0 neither collides on task keys nor
            # gets zombie-resurrected by a stale backup StartEpoch
            for key in self.executor.pending():
                if key.split(":")[0] in (
                    "WaitAckStartEpoch", "WaitPrimaryExecution"
                ) and key.split(":")[1:-1] == name.split(":"):
                    self.executor.cancel(key)
            return
        in_group = self.node_id in self.rdb.rc_group_of(name)
        if op in ("reconfigure_intent", "delete_intent"):
            if in_group and self.rdb.primary_of(name) != self.node_id:
                epoch = record["epoch"] if record else 0
                self.executor.schedule(WaitPrimaryExecution(self, name, epoch))
        elif op == "create_batch":
            if cmd.get("origin") == self.node_id:
                return
            created = cmd.get("_created") or {}
            for c in cmd.get("creates", []):
                n = c["name"]
                if n not in created:
                    # "exists" outcome: the record belongs to a live
                    # incarnation — a creation StartEpoch with the batch's
                    # stale initial_state would clobber it
                    continue
                if self.node_id not in self.rdb.rc_group_of(n):
                    continue
                t = WaitAckStartEpoch(
                    self, n, created[n], c["actives"], -1, [],
                    pkt.b64d(c.get("initial_state")) or b"", None,
                )
                t.first_delayed = True
                t.period_s = 2.0
                self.executor.cancel(t.key)
                self.executor.schedule(t)
        elif op == "create" and record is not None:
            if (in_group and cmd.get("origin") != self.node_id
                    and name in (cmd.get("_created") or {})):
                # backup creation driver: if the origin RC dies before its
                # StartEpochs go out, this (delayed, idempotent) task still
                # births the created group.  Gated on _created: an "exists"
                # outcome's record belongs to a live incarnation that this
                # command's stale initial_state must never touch
                t = WaitAckStartEpoch(
                    self, name, record["epoch"], record["actives"], -1, [],
                    pkt.b64d(cmd.get("initial_state")) or b"", None,
                )
                t.first_delayed = True
                t.period_s = 2.0
                # evict a stale same-key backup from a deleted incarnation
                # (it would otherwise block this one and push stale state)
                self.executor.cancel(t.key)
                self.executor.schedule(t)

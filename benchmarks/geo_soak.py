"""Geo soak: region-loss latency SLO over the full Mode B stack on SimNet.

Runs a 3-region WAN topology (``testing.simnet.GEO_TOPOLOGIES``) with one
Mode B node per region, drives a steady closed-loop workload through three
phases — before a region loss, during it, after healing — and reports
p50/p99 commit latency per phase plus time-to-new-coordinator, A/B'd
between classical full-prepare re-election and consecutive-ballot fast
re-election (``paxos.fast_reelection``).

All latencies are SIMULATED WAN milliseconds: one SimNet pump round is
``--ms-per-round`` ms and link delays come from the topology's RTT matrix
(see PARITY.md — these are not loopback wall-clock numbers and loopback
RTT is not citable as geo latency).  Every run executes under the chaos
harness with the per-slot S1 safety ledger asserted.

Usage:
    python benchmarks/geo_soak.py [--topo us3] [--ticks-per-phase 160]
        [--every 4] [--ms-per-round 10] [--seed 0] [--out PATH]

Prints one JSON line (the artifact body) on stdout; writes
``benchmarks/results_geo_soak_pr6.json`` unless ``--out -``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from gigapaxos_tpu.config import GigapaxosTpuConfig  # noqa: E402
from gigapaxos_tpu.models.replicable import KVApp  # noqa: E402
from gigapaxos_tpu.modeb import ModeBNode  # noqa: E402
from gigapaxos_tpu.testing.chaos import (ChaosEvent, ChaosSchedule,  # noqa: E402
                                         SimChaosRunner)
from gigapaxos_tpu.testing.simnet import GEO_TOPOLOGIES, SimNet  # noqa: E402

IDS = ["N0", "N1", "N2"]


def build_cluster(topo: str, seed: int, fast: bool, ms_per_round: float,
                  groups: int = 8, window: int = 8):
    """One node per region (first three regions of the topology)."""
    net = SimNet(seed=seed)
    cfg = GigapaxosTpuConfig()
    cfg.paxos.max_groups = groups
    cfg.paxos.window = window
    cfg.paxos.fast_reelection = fast
    apps = {n: KVApp() for n in IDS}
    nodes = {n: ModeBNode(cfg, IDS, n, apps[n], net.messenger(n),
                          anti_entropy_every=8) for n in IDS}
    regions = list(GEO_TOPOLOGIES[topo]["regions"])[:3]
    placement = {nid: regions[i] for i, nid in enumerate(IDS)}
    net.apply_geo(topo, placement, ms_per_round=ms_per_round)
    for nd in nodes.values():
        nd.create_group("svc", [0, 1, 2])
    return net, nodes, apps, placement


def percentile(xs, p):
    return float(np.percentile(np.asarray(xs, dtype=float), p)) if xs else None


def soak(topo: str, fast: bool, seed: int, ticks_per_phase: int,
         every: int, ms_per_round: float, detect_after: int = 8) -> dict:
    """One full before/during/after run.  Returns phase SLO numbers,
    time-to-new-coordinator, and the safety ledger summary."""
    net, nodes, apps, placement = build_cluster(topo, seed, fast,
                                                ms_per_round)
    # warm up OUTSIDE the measured window: first election + jit compile +
    # anti-entropy settling would otherwise pollute the "before" p99
    warm = []
    nodes["N0"].propose("svc", b"PUT warm 0", lambda _r, x: warm.append(x))
    for _ in range(200):
        for nd in nodes.values():
            nd.tick()
        net.pump()
        if warm:
            break
    assert warm == [b"OK"], "warmup commit failed"
    cut_at = ticks_per_phase
    heal_at = 2 * ticks_per_phase
    total = 3 * ticks_per_phase
    # the workload enters at N1 (a region that stays up); N0's region is
    # the one lost, and N0 starts as coordinator of every group
    lost_region = placement["N0"]
    events = [
        ChaosEvent(cut_at, "cut_region", {"region": lost_region}),
        ChaosEvent(cut_at + detect_after, "mark_down", {"node": "N0"}),
        ChaosEvent(heal_at, "heal_region", {"region": lost_region}),
        ChaosEvent(heal_at, "mark_up", {"node": "N0"}),
    ]
    events += [ChaosEvent(t, "propose",
                          {"node": "N1", "group": "svc",
                           "payload": f"PUT k{t} v{t}"})
               for t in range(2, total, every)]
    sched = ChaosSchedule(f"geo_soak_{topo}", events, seed=seed)
    runner = SimChaosRunner(net, nodes, sched)

    row = nodes["N1"].rows.row("svc")
    takeover = {"tick": None}

    def on_tick(t):
        if (takeover["tick"] is None and t >= cut_at
                and int(nodes["N1"]._coord_view[row]) not in (-1, 0)):
            takeover["tick"] = t

    runner.run(total, on_tick=on_tick)
    # drain: no new proposals, let in-flight commits land and the healed
    # region catch up before the convergence check
    runner.run(ticks_per_phase)
    runner.ledger.assert_safe()

    phases = {"before": (0, cut_at), "during": (cut_at, heal_at),
              "after": (heal_at, total)}
    slo = {}
    for ph, (lo, hi) in phases.items():
        lats = [(p["resp_tick"] - p["tick"]) * ms_per_round
                for p in runner.proposals
                if p["resp"] == "OK" and lo <= p["tick"] < hi]
        lost = sum(1 for p in runner.proposals
                   if p["resp"] is None and lo <= p["tick"] < hi)
        slo[ph] = {
            "n": len(lats), "unanswered": lost,
            "p50_ms": round(percentile(lats, 50), 1) if lats else None,
            "p90_ms": round(percentile(lats, 90), 1) if lats else None,
            # tail includes requests in flight when the region died — a
            # cut-straddling proposal is retried after re-election and
            # honestly lands in its SEND phase's bucket
            "p99_ms": round(percentile(lats, 99), 1) if lats else None,
        }
    ttc = (None if takeover["tick"] is None
           else takeover["tick"] - cut_at)
    return {
        "fast_reelection": fast,
        "topology": topo,
        "lost_region": lost_region,
        "placement": placement,
        "ms_per_round": ms_per_round,
        "detect_after_ticks": detect_after,
        "slo": slo,
        "ticks_to_new_coordinator": ttc,
        "time_to_new_coordinator_ms": (None if ttc is None
                                       else round(ttc * ms_per_round, 1)),
        "safety": {"observations": runner.ledger.observations,
                   "violations": len(runner.ledger.violations)},
        "dbs_converged": len({json.dumps(a.db, sort_keys=True)
                              for a in apps.values()}) == 1,
    }


def failover_ab(topo: str, seed: int, ms_per_round: float,
                detect_after: int = 8) -> dict:
    """Tight A/B of re-election cost alone: cut the coordinator's region,
    count ticks until a survivor IS coordinator and until its first
    post-cut commit — classical prepare vs fast takeover."""
    out = {}
    for fast in (False, True):
        net, nodes, apps, placement = build_cluster(topo, seed, fast,
                                                    ms_per_round)

        def spin(k, only=None):
            for _ in range(k):
                for nid, nd in nodes.items():
                    if only is None or nid in only:
                        nd.tick()
                net.pump()

        done = []
        nodes["N0"].propose("svc", b"PUT a 1", lambda _r, x: done.append(x))
        spin(120)
        assert done == [b"OK"], "warmup commit failed"
        row = nodes["N1"].rows.row("svc")
        net.cut_region(placement["N0"])
        spin(detect_after, only=("N1", "N2"))
        for nid in ("N1", "N2"):
            nodes[nid].set_alive(0, False)
        done2 = []
        nodes["N1"].propose("svc", b"PUT b 2",
                            lambda _r, x: done2.append(x))
        t_coord = t_commit = None
        for t in range(1, 400):
            spin(1, only=("N1", "N2"))
            if t_coord is None and int(nodes["N1"]._coord_view[row]) == 1:
                t_coord = t
            if done2:
                t_commit = t
                break
        key = "fast" if fast else "full_prepare"
        out[key] = {
            "ticks_to_coordinator": t_coord,
            "ticks_to_first_commit": t_commit,
            "sim_ms_to_coordinator": (None if t_coord is None
                                      else round(t_coord * ms_per_round, 1)),
            "sim_ms_to_first_commit": (None if t_commit is None
                                       else round(t_commit * ms_per_round, 1)),
        }
    f, c = out["fast"], out["full_prepare"]
    if f["ticks_to_coordinator"] and c["ticks_to_coordinator"]:
        out["coordinator_speedup"] = round(
            c["ticks_to_coordinator"] / f["ticks_to_coordinator"], 2)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--topo", default="us3", choices=sorted(GEO_TOPOLOGIES))
    ap.add_argument("--ticks-per-phase", type=int, default=160)
    ap.add_argument("--every", type=int, default=4)
    ap.add_argument("--ms-per-round", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    t0 = time.monotonic()
    result = {
        "generated_unix": int(time.time()),
        "environment": {"cpu_count": os.cpu_count(),
                        "python": sys.version.split()[0],
                        "note": ("latencies are SIMULATED WAN ms "
                                 "(SimNet geo profiles), not wall clock")},
        "soak_full_prepare": soak(args.topo, False, args.seed,
                                  args.ticks_per_phase, args.every,
                                  args.ms_per_round),
        "soak_fast_reelection": soak(args.topo, True, args.seed,
                                     args.ticks_per_phase, args.every,
                                     args.ms_per_round),
        "reelection_ab": failover_ab(args.topo, args.seed,
                                     args.ms_per_round),
    }
    result["wall_clock_s"] = round(time.monotonic() - t0, 1)
    for k in ("soak_full_prepare", "soak_fast_reelection"):
        assert result[k]["safety"]["violations"] == 0
        assert result[k]["dbs_converged"]

    out = args.out
    if out != "-":
        out = out or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "results_geo_soak_pr6.json")
        with open(out, "w") as f:
            json.dump(result, f, indent=1)
        result["written"] = out
    print(json.dumps(result))


if __name__ == "__main__":
    main()
